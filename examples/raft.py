#!/usr/bin/env python
"""Raft leader election example CLI (new; BASELINE config — the reference has
no Raft example). 5 servers, lossy network, term-bounded."""

import sys

from _cli import (
    network_names,
    opt_int,
    opt_network,
    opt_str,
    parse_args,
    report,
    thread_count,
)

from stateright_tpu.models.raft import RaftModelCfg


def _cfg(server_count, max_term, network):
    kwargs = dict(server_count=server_count, max_term=max_term, lossy=True)
    if network is not None:
        kwargs["network"] = network
    return RaftModelCfg(**kwargs)


def main(argv=sys.argv):
    cmd, free = parse_args(argv)
    if cmd in ("check", "check-sym", "check-live"):
        server_count = opt_int(free, 0, 5)
        max_term = opt_int(free, 1, 2)
        network = opt_network(free, 2)
        mode = {
            "check-sym": " with symmetry reduction",
            "check-live": " with cycle-complete liveness",
        }.get(cmd, "")
        print(
            f"Model checking Raft leader election with {server_count} servers"
            f" (max term {max_term}){mode}."
        )
        builder = (
            _cfg(server_count, max_term, network)
            .into_model()
            .checker()
            .threads(thread_count())
        )
        if cmd == "check-sym":
            builder = builder.symmetry()
        if cmd == "check-live":
            # Opt-in lasso search: catches repeated-election loops the
            # reference's eventually semantics miss (see checker/liveness.py).
            builder = builder.complete_liveness()
        report(builder.spawn_dfs())
    elif cmd == "explore":
        server_count = opt_int(free, 0, 3)
        address = opt_str(free, 1, "localhost:3000")
        network = opt_network(free, 2)
        print(
            f"Exploring state space for Raft with {server_count} servers "
            f"on {address}."
        )
        _cfg(server_count, 1, network).into_model().checker().threads(
            thread_count()
        ).serve(address)
    else:
        print("USAGE:")
        print("  ./raft.py check [SERVER_COUNT] [MAX_TERM] [NETWORK]")
        print("  ./raft.py check-sym [SERVER_COUNT] [MAX_TERM] [NETWORK]")
        print("  ./raft.py check-live [SERVER_COUNT] [MAX_TERM] [NETWORK]")
        print("  ./raft.py explore [SERVER_COUNT] [ADDRESS] [NETWORK]")
        print(f"NETWORK: {network_names()}")


if __name__ == "__main__":
    main()
