"""Shared CLI plumbing for example binaries (subcommand parsing, reporter).

Mirrors the reference examples' pico_args conventions: each example exposes
``check [N] [NETWORK]``, some ``check-sym``, ``explore [N] [ADDR]``, actor
examples ``spawn``.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stateright_tpu import WriteReporter  # noqa: E402
from stateright_tpu.actor import Network  # noqa: E402


def thread_count() -> int:
    return os.cpu_count() or 1


def parse_args(argv):
    """Returns (subcommand, free_args)."""
    args = argv[1:]
    if not args:
        return None, []
    return args[0], args[1:]


def opt_int(free, index, default):
    try:
        return int(free[index])
    except (IndexError, ValueError):
        return default


def opt_str(free, index, default):
    try:
        return free[index]
    except IndexError:
        return default


def opt_network(free, index, default_name="unordered_nonduplicating"):
    name = opt_str(free, index, default_name)
    return Network.from_name(name)


def report(checker):
    checker.report(WriteReporter(sys.stdout))
    return checker


def network_names() -> str:
    return " | ".join(Network.names())
