#!/usr/bin/env python
"""Single Decree Paxos example CLI (reference: examples/paxos.rs)."""

import sys

from _cli import (
    network_names,
    opt_int,
    opt_network,
    opt_str,
    parse_args,
    report,
    thread_count,
)

from stateright_tpu.models.paxos import PaxosActor, PaxosModelCfg


def main(argv=sys.argv):
    cmd, free = parse_args(argv)
    if cmd == "check":
        client_count = opt_int(free, 0, 2)
        network = opt_network(free, 1)
        print(f"Model checking Single Decree Paxos with {client_count} clients.")
        report(
            PaxosModelCfg(
                client_count=client_count, server_count=3, network=network
            )
            .into_model()
            .checker()
            .threads(thread_count())
            .spawn_dfs()
        )
    elif cmd == "explore":
        client_count = opt_int(free, 0, 2)
        address = opt_str(free, 1, "localhost:3000")
        network = opt_network(free, 2)
        print(
            f"Exploring state space for Single Decree Paxos with "
            f"{client_count} clients on {address}."
        )
        PaxosModelCfg(
            client_count=client_count, server_count=3, network=network
        ).into_model().checker().threads(thread_count()).serve(address)
    elif cmd == "spawn":
        import json

        from stateright_tpu.actor import Id
        from stateright_tpu.actor.spawn import spawn
        from stateright_tpu.actor.wire import register_msg_from_wire, register_msg_to_wire

        port = 3000
        print("  A set of servers that implement Single Decree Paxos.")
        print("  You can interact using netcat, e.g.:")
        print(f"$ nc -u localhost {port}")
        print(json.dumps({"Put": [1, "X"]}))
        print(json.dumps({"Get": [2]}))
        print()
        ids = [Id.from_socket_addr("127.0.0.1", port + i) for i in range(3)]
        spawn(
            register_msg_to_wire,
            register_msg_from_wire,
            [
                (ids[i], PaxosActor([ids[j] for j in range(3) if j != i]))
                for i in range(3)
            ],
        )
    else:
        print("USAGE:")
        print("  ./paxos.py check [CLIENT_COUNT] [NETWORK]")
        print("  ./paxos.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  ./paxos.py spawn")
        print(f"NETWORK: {network_names()}")


if __name__ == "__main__":
    main()
