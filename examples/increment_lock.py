#!/usr/bin/env python
"""IncrementLock example CLI (reference: examples/increment_lock.rs)."""

import sys

from _cli import opt_int, opt_str, parse_args, report, thread_count

from stateright_tpu.models.increment import IncrementLock


def main(argv=sys.argv):
    cmd, free = parse_args(argv)
    if cmd == "check":
        n = opt_int(free, 0, 3)
        print(f"Model checking increment_lock with {n} threads.")
        report(IncrementLock(n).checker().threads(thread_count()).spawn_dfs())
    elif cmd == "check-sym":
        n = opt_int(free, 0, 3)
        print(f"Model checking increment_lock with {n} threads using symmetry reduction.")
        report(
            IncrementLock(n)
            .checker()
            .threads(thread_count())
            .symmetry()
            .spawn_dfs()
        )
    elif cmd == "check-tpu":
        n = opt_int(free, 0, 3)
        print(f"Model checking increment_lock with {n} threads on TPU.")
        report(IncrementLock(n).checker().spawn_tpu_bfs())
    elif cmd == "explore":
        n = opt_int(free, 0, 3)
        address = opt_str(free, 1, "localhost:3000")
        print(f"Exploring the state space of increment_lock with {n} threads on {address}.")
        IncrementLock(n).checker().threads(thread_count()).serve(address)
    else:
        print("USAGE:")
        print("  ./increment_lock.py check [THREAD_COUNT]")
        print("  ./increment_lock.py check-sym [THREAD_COUNT]")
        print("  ./increment_lock.py check-tpu [THREAD_COUNT]")
        print("  ./increment_lock.py explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
