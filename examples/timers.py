#!/usr/bin/env python
"""Timer-driven pinger example CLI (reference: examples/timers.rs)."""

import sys

from _cli import network_names, opt_network, opt_str, parse_args, report, thread_count

from stateright_tpu.models.timers import PingerModelCfg


def main(argv=sys.argv):
    cmd, free = parse_args(argv)
    if cmd == "check":
        network = opt_network(free, 0)
        print("Model checking Pingers")
        report(
            PingerModelCfg(server_count=3, network=network)
            .into_model()
            .checker()
            .threads(thread_count())
            .target_max_depth(6)
            .spawn_dfs()
        )
    elif cmd == "explore":
        address = opt_str(free, 0, "localhost:3000")
        network = opt_network(free, 1)
        print(f"Exploring state space for Pingers on {address}.")
        PingerModelCfg(server_count=3, network=network).into_model().checker().threads(
            thread_count()
        ).serve(address)
    else:
        print("USAGE:")
        print("  ./timers.py check [NETWORK]")
        print("  ./timers.py explore [ADDRESS] [NETWORK]")
        print(f"NETWORK: {network_names()}")


if __name__ == "__main__":
    main()
