#!/usr/bin/env python
"""Single-copy register example CLI
(reference: examples/single-copy-register.rs)."""

import sys

from _cli import (
    network_names,
    opt_int,
    opt_network,
    opt_str,
    parse_args,
    report,
    thread_count,
)

from stateright_tpu.models.single_copy_register import SingleCopyModelCfg


def main(argv=sys.argv):
    cmd, free = parse_args(argv)
    if cmd == "check":
        client_count = opt_int(free, 0, 2)
        network = opt_network(free, 1)
        print(f"Model checking a single-copy register with {client_count} clients.")
        report(
            SingleCopyModelCfg(
                client_count=client_count, server_count=1, network=network
            )
            .into_model()
            .checker()
            .threads(thread_count())
            .spawn_dfs()
        )
    elif cmd == "explore":
        client_count = opt_int(free, 0, 2)
        address = opt_str(free, 1, "localhost:3000")
        network = opt_network(free, 2)
        print(
            f"Exploring state space for a single-copy register with "
            f"{client_count} clients on {address}."
        )
        SingleCopyModelCfg(
            client_count=client_count, server_count=1, network=network
        ).into_model().checker().threads(thread_count()).serve(address)
    else:
        print("USAGE:")
        print("  ./single_copy_register.py check [CLIENT_COUNT] [NETWORK]")
        print("  ./single_copy_register.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print(f"NETWORK: {network_names()}")


if __name__ == "__main__":
    main()
