#!/usr/bin/env python
"""Two-phase commit example CLI (reference: examples/2pc.rs)."""

import sys

from _cli import opt_int, opt_str, parse_args, report, thread_count

from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def main(argv=sys.argv):
    cmd, free = parse_args(argv)
    if cmd == "check":
        rm_count = opt_int(free, 0, 2)
        print(f"Checking two phase commit with {rm_count} resource managers.")
        report(
            TwoPhaseSys(rm_count)
            .checker()
            .threads(thread_count())
            .spawn_dfs()
        )
    elif cmd == "check-sym":
        rm_count = opt_int(free, 0, 2)
        print(
            f"Checking two phase commit with {rm_count} resource managers "
            "using symmetry reduction."
        )
        report(
            TwoPhaseSys(rm_count)
            .checker()
            .threads(thread_count())
            .symmetry()
            .spawn_dfs()
        )
    elif cmd == "check-tpu":
        rm_count = opt_int(free, 0, 2)
        print(f"Checking two phase commit with {rm_count} resource managers on TPU.")
        report(TwoPhaseSys(rm_count).checker().spawn_tpu_bfs())
    elif cmd == "explore":
        rm_count = opt_int(free, 0, 2)
        address = opt_str(free, 1, "localhost:3000")
        print(
            f"Exploring state space for two phase commit with {rm_count} "
            f"resource managers on {address}."
        )
        TwoPhaseSys(rm_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  ./two_phase_commit.py check [RESOURCE_MANAGER_COUNT]")
        print("  ./two_phase_commit.py check-sym [RESOURCE_MANAGER_COUNT]")
        print("  ./two_phase_commit.py check-tpu [RESOURCE_MANAGER_COUNT]")
        print("  ./two_phase_commit.py explore [RESOURCE_MANAGER_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
