#!/usr/bin/env python
"""Tiered-storage summary from a telemetry trace JSONL.

    python scripts/storage_report.py TRACE.jsonl

Reads the JSONL sink an out-of-core checker run produced (``--trace-out``
on bench.py, or ``get_tracer().add_sink(path)`` on any run) and
summarizes the storage tier activity: eviction/merge/spill counts and
costs, probe batches with per-tier hit counts and latency percentiles,
Bloom-filter effectiveness, and the final tier occupancy trajectory taken
from the wave spans' ``storage_fps`` argument.

Stdlib-only (json + argparse), same contract as ``trace_summary.py``:
trace files outlive the runs that wrote them and must stay inspectable on
boxes without jax.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_events(path):
    """Events from a JSONL trace; unparseable lines (a killed run's
    partial tail write) are skipped, never fatal."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return events


def _pct(values, q):
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(len(vals) - 1, int(q * (len(vals) - 1) + 0.5))
    return vals[idx]


def _span_kind(name):
    """The storage-span kind, or None. Matches any backend prefix
    (``tpu_bfs.storage.evict``, ``sharded_bfs.storage.probe``, ...)."""
    if ".storage." not in name:
        return None
    kind = name.rsplit(".", 1)[1]
    return kind if kind in ("evict", "merge", "spill", "probe") else None


def summarize(events):
    spans = {
        "evict": [], "merge": [], "merge_l2": [], "spill": [], "probe": [],
    }
    wave_storage = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        kind = _span_kind(ev.get("name", ""))
        if kind is not None:
            # L2 compactions share the ".merge" span name but record
            # tier="l2"; split them so disk-compaction cost is never
            # attributed to L1.
            if kind == "merge" and (ev.get("args") or {}).get("tier") == "l2":
                kind = "merge_l2"
            spans[kind].append(ev)
        args = ev.get("args") or {}
        if "storage_fps" in args:
            wave_storage.append(args)

    out = {}
    ms = lambda ev: ev.get("dur", 0.0) / 1000.0  # noqa: E731
    for kind in ("evict", "merge", "merge_l2", "spill"):
        evs = spans[kind]
        out[kind] = {
            "count": len(evs),
            "fps": sum((e.get("args") or {}).get("fps", 0) for e in evs),
            "total_ms": sum(ms(e) for e in evs),
        }
    probes = spans["probe"]
    probe_ms = [ms(e) for e in probes]
    pargs = [e.get("args") or {} for e in probes]
    out["probe"] = {
        "batches": len(probes),
        "keys": sum(a.get("keys", 0) for a in pargs),
        "hits_l1": sum(a.get("hits_l1", 0) for a in pargs),
        "hits_l2": sum(a.get("hits_l2", 0) for a in pargs),
        "blocks_decoded": sum(a.get("blocks_decoded", 0) for a in pargs),
        "bloom_rejects": sum(a.get("bloom_rejects", 0) for a in pargs),
        "total_ms": sum(probe_ms),
        "p50_ms": _pct(probe_ms, 0.50),
        "p99_ms": _pct(probe_ms, 0.99),
    }
    if wave_storage:
        out["tier_fps_final"] = wave_storage[-1].get("storage_fps", 0)
        out["tier_fps_peak"] = max(
            a.get("storage_fps", 0) for a in wave_storage
        )
        out["stale_dropped"] = sum(
            a.get("storage_stale", 0) for a in wave_storage
        )
    return out


def print_report(s, out=sys.stdout):
    w = out.write
    w("tiered-storage summary\n")
    w("----------------------\n")
    for kind, label in (
        ("evict", "L0 evictions"),
        ("merge", "L1 merges"),
        ("merge_l2", "L2 compactions"),
        ("spill", "L2 spills"),
    ):
        r = s[kind]
        w(
            f"{label:<14} {r['count']:>6}   "
            f"{r['fps']:>12} fps   {r['total_ms']:>9.1f} ms\n"
        )
    p = s["probe"]
    w(
        f"{'probes':<14} {p['batches']:>6}   {p['keys']:>12} keys   "
        f"{p['total_ms']:>9.1f} ms  "
        f"(p50 {p['p50_ms']:.2f} / p99 {p['p99_ms']:.2f} ms)\n"
    )
    w(
        f"{'':14} hits: l1={p['hits_l1']} l2={p['hits_l2']}  "
        f"bloom_rejects={p['bloom_rejects']}  "
        f"blocks_decoded={p['blocks_decoded']}\n"
    )
    if "tier_fps_final" in s:
        w(
            f"{'tier fps':<14} final={s['tier_fps_final']}  "
            f"peak={s['tier_fps_peak']}  "
            f"stale_dropped={s['stale_dropped']}\n"
        )


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Tiered-storage summary from a telemetry trace JSONL."
    )
    parser.add_argument("trace", help="JSONL trace file (telemetry sink)")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the summary as one JSON object instead of the table",
    )
    args = parser.parse_args(argv)

    events = load_events(args.trace)
    if not events:
        print(f"no events in {args.trace}", file=sys.stderr)
        return 1
    s = summarize(events)
    if args.json:
        # One JSON object on stdout — the shared machine-readable
        # convention (gap_report.py --json, coverage_report.py --json,
        # bench_compare's single-line leg files): dashboards consume it
        # without scraping the table.
        json.dump(s, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
        return 0
    if not any(
        s[k]["count"] for k in ("evict", "merge", "merge_l2", "spill")
    ) and not s["probe"]["batches"]:
        print(
            f"{len(events)} events, no storage-tier spans "
            "(run was not out-of-core: no hbm_budget_mib?)",
        )
        return 0
    print_report(s)
    return 0


if __name__ == "__main__":
    sys.exit(main())
