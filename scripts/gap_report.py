#!/usr/bin/env python
"""Phase ledger + overlap headroom from an attribution-mode trace JSONL.

    python scripts/gap_report.py TRACE.jsonl [--json]

Reads the JSONL sink an attribution-mode run produced (``bench.py
--attribution --trace-out ...``, or any checker spawned with
``attribution=True`` plus ``get_tracer().add_sink(path)``) and renders,
per checker prefix, the wave-timeline phase ledger the
``<prefix>.pipeline`` spans carry: total wall, per-phase milliseconds and
shares (device compute, host Bloom+run probe, evict/merge/spill,
table growth, checkpoint, compile, residual dispatch gap), and the
**overlap headroom** — the wall-clock a perfect async overlap of the host
phases under device compute would reclaim, the go/no-go number for the
pipelined wave engine (ROADMAP item 2):

    headroom  = min(host_probe + evict + checkpoint, device)
    predicted = wall - headroom

When the trace came from an ``async_pipeline=True`` run, the worker's
``<prefix>.pipeline.overlapped`` spans carry the host time actually
shadowed under device compute; the report then renders the ACHIEVED
overlap next to the prediction — realized utilization (device/wall)
against the utilization a perfect overlap of this ledger's own host
phases would produce — closing the loop the PR-7 headroom estimate
opened. Compare an async-off and an async-on leg of the same model with
``scripts/bench_compare.py --ab-async`` for the per-leg
predicted-vs-realized delta.

``--json`` emits the ledgers as one JSON object instead of the tables
(machine-readable; the tests consume it). The event loader, the
``.pipeline``-span aggregation, and the phase lists are shared with
``trace_summary.py`` (same directory) — stdlib-only, like every trace
reader here: trace files outlive the runs that wrote them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from trace_summary import (  # noqa: E402
    DEVICE_PHASES,
    HOST_OVERLAPPABLE,
    PHASE_ORDER,
    attribution_rows,
    load_events,
)


def collect_ledgers(events):
    """Per-prefix ledgers from the shared ``.pipeline`` aggregation:
    ``{prefix: {"waves": N, "wall_ms": W, "phases_ms": {...}}}`` where
    ``gap`` rides phases_ms like any other phase."""
    ledgers = {}
    for name, g in attribution_rows(events).items():
        prefix = name[: -len(".pipeline")]
        ledgers[prefix] = {
            "waves": g["waves"],
            "wall_ms": g["wall_ms"],
            "phases_ms": dict(g["phases"]),
        }
    return ledgers


def collect_overlapped(events):
    """Per-prefix ACHIEVED-overlap sums from the async worker's
    ``<prefix>.pipeline.overlapped`` spans: ``{prefix: {phase: ms}}``.
    Empty for synchronous (async-off) traces."""
    out = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        if not name.endswith(".pipeline.overlapped"):
            continue
        prefix = name[: -len(".pipeline.overlapped")]
        args = ev.get("args") or {}
        phase = args.get("phase") or "overlapped"
        d = out.setdefault(prefix, {})
        d[phase] = d.get(phase, 0.0) + float(ev.get("dur", 0.0)) / 1e3
    return out


# Cross-shard exchange args the sharded wave spans carry (sieve-and-
# compact routing, PR-17): summed into the per-prefix comms ledger.
COMMS_KEYS = (
    "comms_probes",
    "comms_killed",
    "comms_bloom_probes",
    "comms_bloom_hits",
    "comms_bloom_fps",
    "comms_lanes",
    "comms_bytes",
)


def collect_comms(events):
    """Per-prefix exchange-ledger sums from the wave/drain spans that
    carry ``comms_*`` args: ``{prefix: {key: total}}``. Empty for
    single-device traces and for sharded runs whose exchange shipped
    nothing (a zero-lane trace has no ledger to render)."""
    out = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        if "comms_lanes" not in args:
            continue
        prefix = ev.get("name", "").rsplit(".", 1)[0]
        led = out.setdefault(prefix, dict.fromkeys(COMMS_KEYS, 0))
        for key in COMMS_KEYS:
            led[key] += int(args.get(key, 0) or 0)
    return out


def comms_block(c):
    """The derived-rate view of one comms ledger (the ``--json`` shape):
    raw sums plus sieve kill rate and the OBSERVED Bloom FP rate — the
    audit number to hold against the filter's design bound."""
    probes, killed = c["comms_probes"], c["comms_killed"]
    bloom_probes, bloom_fps = c["comms_bloom_probes"], c["comms_bloom_fps"]
    return {
        **c,
        "sieve_kill_rate": (killed / probes) if probes else None,
        "bloom_fp_rate_observed": (
            bloom_fps / bloom_probes if bloom_probes else None
        ),
    }


def print_comms(prefix, c, out=sys.stdout):
    out.write(
        f"comms ledger: {prefix} — {c['comms_lanes']:,} lanes / "
        f"{c['comms_bytes']:,} bytes shipped cross-shard\n"
    )
    probes, killed = c["comms_probes"], c["comms_killed"]
    if probes:
        out.write(
            f"  sieve: {killed:,}/{probes:,} candidate lanes killed "
            f"pre-exchange ({100.0 * killed / probes:.1f}%)\n"
        )
    bloom_probes, bloom_fps = c["comms_bloom_probes"], c["comms_bloom_fps"]
    if bloom_probes:
        out.write(
            f"  bloom audit: {bloom_fps:,}/{bloom_probes:,} observed "
            f"false positives ({100.0 * bloom_fps / bloom_probes:.3f}%)\n"
        )
    out.write("\n")


# Per-shard fleet columns the sharded wave/drain spans carry
# (telemetry/fleet.py FLEET_COLS — kept in sync by the tier-1 fleet
# report test). Stdlib fold: trace files outlive the runs (and the
# numpy installs) that wrote them.
FLEET_KEYS = (
    "live_lanes",
    "generated",
    "fresh",
    "insert_load",
    "overflow",
    "routed",
    "sieve_hits",
    "probe_ms",
    "evict_ms",
    "evict_bytes",
)


def collect_fleet(events):
    """Per-prefix per-shard sums + slowest-wave tallies from the spans
    carrying ``fleet_*`` columns: ``{prefix: {"shards": n, "hosts": h,
    "waves": W, "cost_waves": C, "totals": {col: [per-shard]},
    "slowest": [per-shard]}}``. Empty for non-fleet traces."""
    out = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        n = args.get("fleet_shards")
        if not n:
            continue
        n = int(n)
        prefix = ev.get("name", "").rsplit(".", 1)[0]
        f = out.setdefault(prefix, {
            "shards": n,
            "hosts": int(args.get("fleet_hosts") or 1),
            "waves": 0,
            "cost_waves": 0,
            "totals": {k: [0.0] * n for k in FLEET_KEYS},
            "slowest": [0] * n,
        })
        try:
            f["waves"] += max(1, int(args.get("waves") or 1))
        except (TypeError, ValueError):
            f["waves"] += 1
        rows = {}
        for key in FLEET_KEYS:
            col = args.get(f"fleet_{key}")
            if isinstance(col, list) and len(col) == n:
                rows[key] = [float(x) for x in col]
                tot = f["totals"][key]
                for d, x in enumerate(rows[key]):
                    tot[d] += float(x)
        # The wave's cost vector: host tier wall when any shard paid one
        # (time dominates), owner-side insert load otherwise — the same
        # straggler definition as the live fold.
        host = [
            rows.get("probe_ms", [0.0] * n)[d]
            + rows.get("evict_ms", [0.0] * n)[d]
            for d in range(n)
        ]
        cost = host if sum(host) > 0 else rows.get(
            "insert_load", rows.get("live_lanes", [0.0] * n)
        )
        if sum(cost) > 0:
            f["cost_waves"] += 1
            f["slowest"][cost.index(max(cost))] += 1
    return out


def _skew(values):
    mean = sum(values) / len(values) if values else 0.0
    if mean <= 0.0:
        return None
    var = sum((v - mean) ** 2 for v in values) / len(values)
    return {
        "max_over_mean": max(values) / mean,
        "cv": var ** 0.5 / mean,
    }


def fleet_block(f):
    """The derived skew/straggler view of one fleet fold (the
    ``--json`` shape): per-shard totals, run-total skew per column, and
    the slowest shards ranked by summed cost."""
    n = f["shards"]
    per_host = max(1, n // max(1, f["hosts"]))
    host_ms = [
        f["totals"]["probe_ms"][d] + f["totals"]["evict_ms"][d]
        for d in range(n)
    ]
    cost = host_ms if sum(host_ms) > 0 else f["totals"]["insert_load"]
    total_cost = sum(cost) or 1.0
    order = sorted(range(n), key=lambda d: -cost[d])
    stragglers = [
        {
            "shard": d,
            "host": d // per_host,
            "share": cost[d] / total_cost,
            "score": n * cost[d] / total_cost,
            "slowest_waves": f["slowest"][d],
            "persistence": (
                f["slowest"][d] / f["cost_waves"]
                if f["cost_waves"]
                else 0.0
            ),
        }
        for d in order[:2]
    ]
    return {
        "shards": n,
        "hosts": f["hosts"],
        "waves": f["waves"],
        "per_shard": [
            {
                "shard": d,
                "host": d // per_host,
                **{k: f["totals"][k][d] for k in FLEET_KEYS},
            }
            for d in range(n)
        ],
        "skew": {
            k: s
            for k in ("live_lanes", "fresh", "insert_load", "probe_ms")
            if (s := _skew(f["totals"][k])) is not None
        },
        "stragglers": stragglers,
    }


def print_fleet(prefix, f, out=sys.stdout):
    b = fleet_block(f)
    out.write(
        f"fleet skew: {prefix} — {b['shards']} shards / "
        f"{b['hosts']} host(s), {b['waves']} waves\n"
    )
    cols = ("live_lanes", "fresh", "insert_load", "probe_ms", "evict_ms")
    header = "  " + f"{'shard':>5}" + "".join(
        f"{c:>13}" for c in cols
    )
    out.write(header + "\n")
    out.write("  " + "-" * (len(header) - 2) + "\n")
    for row in b["per_shard"]:
        out.write(
            f"  {row['shard']:>5}"
            + "".join(f"{row[c]:>13,.1f}" for c in cols)
            + "\n"
        )
    for col, s in b["skew"].items():
        out.write(
            f"  skew[{col}]: max/mean {s['max_over_mean']:.2f}, "
            f"cv {s['cv']:.2f}\n"
        )
    for i, st in enumerate(b["stragglers"]):
        out.write(
            f"  {'straggler' if i == 0 else 'runner-up'}: shard "
            f"{st['shard']} (host {st['host']}) — {100 * st['share']:.1f}% "
            f"of cost, slowest in {st['slowest_waves']}/"
            f"{f['cost_waves']} waves\n"
        )
    out.write("\n")


def overlap_headroom(led):
    """The headroom block for one ledger: always non-null (zero host
    phases => zero headroom, predicted == measured)."""
    phases = led["phases_ms"]
    wall = led["wall_ms"]
    device = sum(phases.get(p, 0.0) for p in DEVICE_PHASES)
    host = sum(phases.get(p, 0.0) for p in HOST_OVERLAPPABLE)
    headroom = min(host, device)
    return {
        "host_overlappable_ms": host,
        "device_ms": device,
        "headroom_ms": headroom,
        "headroom_pct": (100.0 * headroom / wall) if wall else 0.0,
        "predicted_wall_ms": wall - headroom,
    }


def utilization_block(led, overlapped_ms=None):
    """Predicted vs realized utilization for one ledger. ``realized`` is
    what the run measured (device share of wall); ``predicted`` is the
    utilization a perfect overlap of this ledger's own host phases
    would produce. On an async-on trace, ``achieved_overlap_ms`` is the
    host time executed on the pipeline worker — an upper bound on the
    wall actually saved (fractions spent while the checker was blocked
    at an epoch barrier ran against an idle device)."""
    wall = led["wall_ms"]
    oh = overlap_headroom(led)
    predicted_wall = oh["predicted_wall_ms"]
    block = {
        "realized": (oh["device_ms"] / wall) if wall else None,
        "predicted_under_full_overlap": (
            oh["device_ms"] / predicted_wall if predicted_wall else None
        ),
    }
    if overlapped_ms:
        block["achieved_overlap_ms"] = dict(sorted(overlapped_ms.items()))
        block["achieved_overlap_total_ms"] = sum(overlapped_ms.values())
    return block


def _phase_rows(phases_ms):
    known = [p for p in PHASE_ORDER if p in phases_ms]
    extra = sorted(p for p in phases_ms if p not in PHASE_ORDER)
    return known + extra


def print_ledger(prefix, led, overlapped_ms=None, out=sys.stdout):
    wall = led["wall_ms"]
    out.write(
        f"phase ledger: {prefix} ({led['waves']} waves, "
        f"{wall:.1f} ms wall)\n"
    )
    header = f"  {'phase':<12} {'ms':>10} {'share':>7}"
    out.write(header + "\n")
    out.write("  " + "-" * (len(header) - 2) + "\n")
    for phase in _phase_rows(led["phases_ms"]):
        ms = led["phases_ms"][phase]
        share = 100.0 * ms / wall if wall else 0.0
        mark = " *" if phase in HOST_OVERLAPPABLE else ""
        out.write(f"  {phase:<12} {ms:>10.2f} {share:>6.1f}%{mark}\n")
    oh = overlap_headroom(led)
    out.write(
        "  (* host phases an async pipelined engine could overlap)\n"
        f"overlap headroom: {oh['headroom_ms']:.1f} ms "
        f"({oh['headroom_pct']:.1f}% of wall) — predicted wall under "
        f"perfect host/device overlap: {oh['predicted_wall_ms']:.1f} ms\n"
    )
    util = utilization_block(led, overlapped_ms)
    realized = util["realized"]
    predicted = util["predicted_under_full_overlap"]
    out.write(
        f"utilization: realized {100.0 * (realized or 0.0):.1f}% vs "
        f"{100.0 * (predicted or 0.0):.1f}% predicted under full "
        "overlap"
    )
    if overlapped_ms:
        per_phase = " ".join(
            f"{p}={ms:.1f}ms"
            for p, ms in sorted(overlapped_ms.items())
        )
        out.write(
            f"\nachieved overlap (async pipeline): "
            f"{util['achieved_overlap_total_ms']:.1f} ms host work run "
            f"on the pipeline worker ({per_phase}) — an upper bound on "
            "wall saved (barrier-stalled fractions included); the "
            "realized saving is the utilization/wall delta"
        )
    out.write("\n\n")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Phase ledger + overlap headroom from an "
        "attribution-mode trace JSONL."
    )
    parser.add_argument("trace", help="JSONL trace file (telemetry sink)")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the ledgers as JSON instead of tables",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="also render the per-shard fleet skew / straggler view "
        "(sharded runs with fleet=True)",
    )
    args = parser.parse_args(argv)

    events = load_events(args.trace)
    ledgers = collect_ledgers(events)
    overlapped = collect_overlapped(events)
    comms = collect_comms(events)
    fleet = collect_fleet(events) if args.fleet else {}
    if not ledgers and not fleet:
        hint = (
            " or fleet columns" if args.fleet else ""
        )
        print(
            f"no .pipeline attribution spans{hint} in {args.trace} — was "
            "the run spawned with attribution=True?",
            file=sys.stderr,
        )
        return 1
    if args.json:
        out = {
            prefix: {
                **led,
                "overlap_headroom": overlap_headroom(led),
                "utilization": utilization_block(
                    led, overlapped.get(prefix)
                ),
                **(
                    {"overlapped_ms": overlapped[prefix]}
                    if prefix in overlapped
                    else {}
                ),
                **(
                    {"comms": comms_block(comms[prefix])}
                    if prefix in comms
                    else {}
                ),
            }
            for prefix, led in sorted(ledgers.items())
        }
        for prefix, f in sorted(fleet.items()):
            out.setdefault(prefix, {})["fleet"] = fleet_block(f)
        json.dump(out, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0
    for prefix, led in sorted(ledgers.items()):
        print_ledger(prefix, led, overlapped.get(prefix))
        if prefix in comms:
            print_comms(prefix, comms[prefix])
    for prefix, f in sorted(fleet.items()):
        print_fleet(prefix, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
