#!/usr/bin/env python
"""Phase ledger + overlap headroom from an attribution-mode trace JSONL.

    python scripts/gap_report.py TRACE.jsonl [--json]

Reads the JSONL sink an attribution-mode run produced (``bench.py
--attribution --trace-out ...``, or any checker spawned with
``attribution=True`` plus ``get_tracer().add_sink(path)``) and renders,
per checker prefix, the wave-timeline phase ledger the
``<prefix>.pipeline`` spans carry: total wall, per-phase milliseconds and
shares (device compute, host Bloom+run probe, evict/merge/spill,
table growth, checkpoint, compile, residual dispatch gap), and the
**overlap headroom** — the wall-clock a perfect async overlap of the host
phases under device compute would reclaim, the go/no-go number for the
pipelined wave engine (ROADMAP item 2):

    headroom  = min(host_probe + evict + checkpoint, device)
    predicted = wall - headroom

``--json`` emits the ledgers as one JSON object instead of the tables
(machine-readable; the tests consume it). The event loader, the
``.pipeline``-span aggregation, and the phase lists are shared with
``trace_summary.py`` (same directory) — stdlib-only, like every trace
reader here: trace files outlive the runs that wrote them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from trace_summary import (  # noqa: E402
    HOST_OVERLAPPABLE,
    PHASE_ORDER,
    attribution_rows,
    load_events,
)


def collect_ledgers(events):
    """Per-prefix ledgers from the shared ``.pipeline`` aggregation:
    ``{prefix: {"waves": N, "wall_ms": W, "phases_ms": {...}}}`` where
    ``gap`` rides phases_ms like any other phase."""
    ledgers = {}
    for name, g in attribution_rows(events).items():
        prefix = name[: -len(".pipeline")]
        ledgers[prefix] = {
            "waves": g["waves"],
            "wall_ms": g["wall_ms"],
            "phases_ms": dict(g["phases"]),
        }
    return ledgers


def overlap_headroom(led):
    """The headroom block for one ledger: always non-null (zero host
    phases => zero headroom, predicted == measured)."""
    phases = led["phases_ms"]
    wall = led["wall_ms"]
    device = phases.get("device", 0.0)
    host = sum(phases.get(p, 0.0) for p in HOST_OVERLAPPABLE)
    headroom = min(host, device)
    return {
        "host_overlappable_ms": host,
        "device_ms": device,
        "headroom_ms": headroom,
        "headroom_pct": (100.0 * headroom / wall) if wall else 0.0,
        "predicted_wall_ms": wall - headroom,
    }


def _phase_rows(phases_ms):
    known = [p for p in PHASE_ORDER if p in phases_ms]
    extra = sorted(p for p in phases_ms if p not in PHASE_ORDER)
    return known + extra


def print_ledger(prefix, led, out=sys.stdout):
    wall = led["wall_ms"]
    out.write(
        f"phase ledger: {prefix} ({led['waves']} waves, "
        f"{wall:.1f} ms wall)\n"
    )
    header = f"  {'phase':<12} {'ms':>10} {'share':>7}"
    out.write(header + "\n")
    out.write("  " + "-" * (len(header) - 2) + "\n")
    for phase in _phase_rows(led["phases_ms"]):
        ms = led["phases_ms"][phase]
        share = 100.0 * ms / wall if wall else 0.0
        mark = " *" if phase in HOST_OVERLAPPABLE else ""
        out.write(f"  {phase:<12} {ms:>10.2f} {share:>6.1f}%{mark}\n")
    oh = overlap_headroom(led)
    out.write(
        "  (* host phases an async pipelined engine could overlap)\n"
        f"overlap headroom: {oh['headroom_ms']:.1f} ms "
        f"({oh['headroom_pct']:.1f}% of wall) — predicted wall under "
        f"perfect host/device overlap: {oh['predicted_wall_ms']:.1f} ms\n\n"
    )


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Phase ledger + overlap headroom from an "
        "attribution-mode trace JSONL."
    )
    parser.add_argument("trace", help="JSONL trace file (telemetry sink)")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the ledgers as JSON instead of tables",
    )
    args = parser.parse_args(argv)

    events = load_events(args.trace)
    ledgers = collect_ledgers(events)
    if not ledgers:
        print(
            f"no .pipeline attribution spans in {args.trace} — was the "
            "run spawned with attribution=True?",
            file=sys.stderr,
        )
        return 1
    if args.json:
        out = {
            prefix: {**led, "overlap_headroom": overlap_headroom(led)}
            for prefix, led in sorted(ledgers.items())
        }
        json.dump(out, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0
    for prefix, led in sorted(ledgers.items()):
        print_ledger(prefix, led)
    return 0


if __name__ == "__main__":
    sys.exit(main())
