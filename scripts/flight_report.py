#!/usr/bin/env python
"""Render a flight-recorder crash dump (``flight-<run_id>.json``).

    python scripts/flight_report.py flight-20260803-1234.json [--waves N]

The flight recorder (``stateright_tpu/telemetry/server.py``) dumps on
uncaught exception or SIGTERM/SIGINT: run identity + reason, the
exception traceback when there was one, the checker's state digest
(depth, counts, table capacity, storage tier stats, checkpoint path),
a full metrics snapshot, and the tracer ring buffer (the final waves
before death). This renders it: header, digest, scalar metrics, and the
last ``--waves`` wave-level spans as the usual per-wave table.

Stdlib-only: flight files are read on whatever box the run died on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from trace_summary import print_table, wave_rows  # noqa: E402


def render(record: dict, waves: int = 20, out=sys.stdout) -> None:
    out.write("flight recorder dump\n")
    out.write("====================\n")
    for key in ("run_id", "reason", "wall_time", "pid"):
        out.write(f"{key:<12} {record.get(key)}\n")

    exc = record.get("exception")
    if exc:
        out.write(f"\nexception: {exc.get('type')}: {exc.get('message')}\n")
        tb = exc.get("traceback")
        if tb:
            out.write(tb if tb.endswith("\n") else tb + "\n")
    else:
        out.write("\nexception: none (signal or manual dump)\n")

    digest = record.get("digest")
    out.write("\ncheckpoint of record (state digest)\n")
    out.write("-----------------------------------\n")
    if isinstance(digest, dict):
        for key, value in digest.items():
            if key == "storage" and isinstance(value, dict):
                out.write("storage:\n")
                for sk, sv in value.items():
                    out.write(f"  {sk:<22} {sv}\n")
            else:
                out.write(f"{key:<24} {value}\n")
    else:
        out.write(f"(none: {digest})\n")

    metrics = record.get("metrics") or {}
    scalars = {
        k: v for k, v in sorted(metrics.items())
        if not isinstance(v, dict) and v is not None
    }
    if scalars:
        out.write("\nmetrics at death (scalars)\n")
        out.write("--------------------------\n")
        for key, value in scalars.items():
            out.write(f"{key:<40} {value}\n")

    ring = record.get("ring") or []
    rows = wave_rows(ring)
    out.write(
        f"\nring buffer: {len(ring)} events, {len(rows)} wave-level "
        f"spans (showing last {min(max(waves, 0), len(rows))})\n\n"
    )
    if rows and waves > 0:  # rows[-0:] would be ALL of them
        print_table(rows[-waves:], out=out)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Render a flight-<run_id>.json crash dump."
    )
    parser.add_argument("flight", help="flight-*.json file")
    parser.add_argument(
        "--waves", type=int, default=20,
        help="wave-level ring spans to show (default 20)",
    )
    args = parser.parse_args(argv)

    try:
        with open(args.flight) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read {args.flight}: {e}", file=sys.stderr)
        return 2
    if record.get("flight_recorder") != 1:
        print(
            f"error: {args.flight} is not a flight recorder dump",
            file=sys.stderr,
        )
        return 2
    render(record, waves=args.waves)
    return 0


if __name__ == "__main__":
    sys.exit(main())
