#!/usr/bin/env python
"""State-space cartography from a coverage-mode trace JSONL, with a CI
vacuity gate.

    python scripts/coverage_report.py TRACE.jsonl [--json] [--no-gate]

Reads the JSONL sink a coverage-recording run produced (``bench.py
--coverage --trace-out ...``, any device checker spawned with
``coverage=True``, or any host engine — they are always-on — plus
``get_tracer().add_sink(path)``) and renders, per checker prefix, the
full coverage report the ``<prefix>.coverage.summary`` instant carries:
the per-action fired/fresh table (dead actions flagged), the
per-property exercise table (antecedent vacuity, ``sometimes``
witnesses + near-miss depth, ``eventually`` met population), and the
state-space shape (new-unique-per-depth histogram, successors-per-state
log2 histogram, terminal states, revisit rate, orbit compression).

Exit codes (the CI contract):

- ``0`` — coverage data found, no vacuity findings;
- ``1`` — vacuity findings: dead actions, an ``always`` whose declared
  antecedent never fired, or an undiscovered ``sometimes`` (suppress
  with ``--no-gate`` to render only);
- ``2`` — no coverage summaries in the trace (was the run spawned with
  ``coverage=True``? host-engine runs emit them always).

``--json`` emits the reports as one JSON object keyed by prefix
(machine-readable; the tests consume it), same convention as
``gap_report.py --json`` / ``storage_report.py --json``.

Stdlib-only, like every trace reader here: trace files outlive the runs
that wrote them and must stay inspectable on boxes without jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from trace_summary import load_events  # noqa: E402


def collect_reports(events):
    """The LAST ``<prefix>.coverage.summary`` instant per prefix (host
    engines emit one per worker shutdown; the final one carries the
    complete totals)."""
    reports = {}
    for ev in events:
        name = ev.get("name", "")
        if not name.endswith(".coverage.summary"):
            continue
        report = (ev.get("args") or {}).get("report")
        if isinstance(report, dict):
            reports[name[: -len(".coverage.summary")]] = report
    return reports


def collect_liveness(events):
    """The LAST ``<prefix>.liveness.summary`` instant per prefix — the
    device-liveness ledger (edge-store occupancy + per-property
    verdicts) rendered next to the coverage met-bit population."""
    out = {}
    for ev in events:
        name = ev.get("name", "")
        if not name.endswith(".liveness.summary"):
            continue
        args = ev.get("args") or {}
        if isinstance(args.get("store"), dict):
            out[name[: -len(".liveness.summary")]] = args
    return out


def print_liveness(prefix, rep, live, out=sys.stdout):
    """The liveness block for one prefix: per-``eventually``-property
    met-bit population (from the PR 8 coverage ledger) beside the
    device verdict, plus edge-store occupancy."""
    w = out.write
    props = (rep or {}).get("properties") or {}
    eventually = {
        name: p
        for name, p in props.items()
        if p.get("expectation") == "eventually"
    }
    outcomes = (live or {}).get("outcomes") or {}
    if not eventually and not live:
        return
    w(f"\n  liveness ({prefix})\n")
    if eventually:
        w(
            f"  {'property':<32} {'met-bit pop':>11} "
            f"{'device verdict':>16}\n"
        )
        w("  " + "-" * 62 + "\n")
        for name, p in eventually.items():
            o = outcomes.get(name) or {}
            verdict = o.get("verdict", "-")
            w(
                f"  {name:<32} {p.get('exercised', 0):>11} "
                f"{verdict:>16}\n"
            )
    store = (live or {}).get("store") or {}
    if store:
        w(
            f"  edge store: {store.get('edges_logged', 0):,} edges "
            f"logged, {store.get('evictions', 0)} evictions, "
            f"{store.get('host_bytes', 0):,} host bytes"
            + (
                f", {store['spilled_chunks']} spilled chunks"
                if store.get("spilled_chunks")
                else ""
            )
            + f", analysis {live.get('analysis_s', 0):.2f}s\n"
        )


def _bar(n, peak, width=24):
    if not peak:
        return ""
    return "#" * max(1 if n else 0, round(width * n / peak))


def print_report(prefix, rep, out=sys.stdout):
    w = out.write
    w(
        f"coverage: {prefix} — {rep.get('evaluated', 0)} evaluated, "
        f"{rep.get('generated', 0)} generated, {rep.get('unique', 0)} "
        f"unique, {rep.get('terminal_states', 0)} terminal, "
        f"{100.0 * rep.get('revisit_rate', 0.0):.1f}% revisit\n"
    )
    actions = rep.get("actions") or {}
    table = actions.get("table") or {}
    if table:
        peak = max((v.get("fired", 0) for v in table.values()), default=0)
        w(f"\n  {'action':<24} {'fired':>10} {'fresh':>10}  coverage\n")
        w("  " + "-" * 60 + "\n")
        for label, v in table.items():
            fired, fresh = v.get("fired", 0), v.get("fresh", 0)
            flag = (
                " DEAD" if fired == 0
                else " never-new" if fresh == 0
                else ""
            )
            w(
                f"  {label:<24} {fired:>10} {fresh:>10}  "
                f"{_bar(fired, peak)}{flag}\n"
            )
    props = rep.get("properties") or {}
    if props:
        w(f"\n  {'property':<32} {'kind':<10} {'exercised':>9}  verdict\n")
        w("  " + "-" * 66 + "\n")
        for name, p in props.items():
            kind = p.get("expectation", "?")
            ex = p.get("exercised", 0)
            if kind == "sometimes":
                verdict = (
                    "witnessed"
                    if p.get("discovered") or ex
                    else "NOT DISCOVERED"
                    + (
                        f" (near-miss depth {p['near_miss_depth']})"
                        if p.get("near_miss_depth") is not None
                        else ""
                    )
                )
            elif kind == "always":
                verdict = (
                    "VACUOUS (antecedent never fired)"
                    if p.get("has_antecedent") and ex == 0
                    else "violated" if p.get("discovered") else "exercised"
                )
            else:  # eventually
                verdict = (
                    "violated" if p.get("discovered")
                    else "held" if ex else "condition never met"
                )
            w(f"  {name:<32} {kind:<10} {ex:>9}  {verdict}\n")
    shape = rep.get("shape") or {}
    depth_hist = shape.get("depth_hist") or []
    if depth_hist:
        peak = max(depth_hist)
        w("\n  new unique per depth:\n")
        for d, n in enumerate(depth_hist):
            if n:
                w(f"    d={d:<4} {n:>9}  {_bar(n, peak)}\n")
        if shape.get("depth_saturated"):
            w("    (last bin saturates: deeper states folded in)\n")
    succ = shape.get("succ_hist_log2") or []
    if succ:
        peak = max(succ)
        w("  successors per state (log2 bins):\n")
        for b, n in enumerate(succ):
            if n:
                label = "<=1" if b == 0 else f"<={1 << b}"
                w(f"    {label:<6} {n:>9}  {_bar(n, peak)}\n")
    sym = rep.get("symmetry")
    if sym and sym.get("orbit_compression"):
        w(
            f"  orbit compression: {sym['orbit_compression']:.2f}x "
            f"({sym['wave_distinct_fps']} wave-distinct fps over "
            f"{sym['wave_distinct_orbits']} orbits)\n"
        )
    vac = rep.get("vacuity") or {}
    findings = [
        f"{kind.replace('_', ' ')}: {', '.join(items)}"
        for kind, items in vac.items()
        if items
    ]
    if findings:
        w("\n  VACUITY FINDINGS:\n")
        for f in findings:
            w(f"    - {f}\n")
    else:
        w("\n  no vacuity findings\n")
    w("\n")
    return bool(findings)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="State-space coverage/vacuity report from a "
        "coverage-mode trace JSONL."
    )
    parser.add_argument("trace", help="JSONL trace file (telemetry sink)")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the reports as one JSON object instead of the tables",
    )
    parser.add_argument(
        "--no-gate", action="store_true",
        help="always exit 0 on rendered reports, even with vacuity "
        "findings (report-only mode)",
    )
    args = parser.parse_args(argv)

    events = load_events(args.trace)
    reports = collect_reports(events)
    liveness = collect_liveness(events)
    if not reports and not liveness:
        print(
            f"no .coverage.summary or .liveness.summary instants in "
            f"{args.trace} — was the run spawned with coverage=True or "
            "liveness='device'? (host engines always emit coverage)",
            file=sys.stderr,
        )
        return 2
    vacuous = False
    if args.json:
        payload = dict(sorted(reports.items()))
        for prefix, live in liveness.items():
            payload[f"{prefix}.liveness"] = live
        json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
        vacuous = any(r.get("vacuous") for r in reports.values())
    else:
        for prefix, rep in sorted(reports.items()):
            if print_report(prefix, rep):
                vacuous = True
            print_liveness(prefix, rep, liveness.get(prefix))
        for prefix in sorted(set(liveness) - set(reports)):
            # Liveness-mode runs without coverage=True still render
            # their edge-store ledger.
            print_liveness(prefix, None, liveness[prefix])
    if vacuous and not args.no_gate:
        print(
            "vacuity findings present (dead actions / unexercised "
            "always / undiscovered sometimes) — failing the gate; use "
            "--no-gate to render only",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
