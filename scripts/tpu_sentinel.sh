#!/bin/bash
# Standing TPU-tunnel sentinel (VERDICT r03 #1a).
#
# Probes the device tunnel on a schedule, appending every attempt to
# PROBE_LOG.jsonl (bench.py summarizes that log into the bench JSON, so
# even an all-CPU round carries proof of continuous attempts). The
# moment a probe succeeds AND some bench leg still lacks a device
# datapoint in DEVICE_RUNS.jsonl, it fires scripts/device_bench_run.sh
# for the missing legs in priority order.
#
# Usage: setsid nohup bash scripts/tpu_sentinel.sh & disown
REPO=/root/repo
PROBES="$REPO/PROBE_LOG.jsonl"
RUNS="$REPO/DEVICE_RUNS.jsonl"
INTERVAL=${SENTINEL_INTERVAL_S:-120}
# smoke leads (VERDICT r04 #1a): 8,832 states, warm in seconds — banks a
# device-labeled datapoint before any long leg can ride a short tunnel
# window into a wedge.
LEGS="smoke 2pc paxos3 abd3o paxos ilock raft5 scr4"

cd "$REPO"

probe() {
    timeout -k 10 60 python -c \
        "import jax; d = jax.devices(); print('probe-ok', d[0].platform)" \
        2>/dev/null | grep -q probe-ok
}

have_tpu_result() {
    grep "\"leg\": \"$1\"" "$RUNS" 2>/dev/null | grep -q '"device": "tpu"'
}

missing_legs() {
    local out=""
    for leg in $LEGS; do
        have_tpu_result "$leg" || out="$out $leg"
    done
    echo "$out"
}

bench_main_running() {
    # The full bench advertises itself; the chip is single-tenant, so a
    # sentinel firing mid-bench would wedge both claimants. Guard
    # against pid reuse after a crashed bench: the live process must
    # actually BE bench.py.
    local pidfile="$REPO/.runtime/stateright_bench_main.pid" pid
    [ -f "$pidfile" ] || return 1
    pid=$(cat "$pidfile" 2>/dev/null)
    [ -n "$pid" ] && kill -0 "$pid" 2>/dev/null \
        && grep -aq "bench.py" "/proc/$pid/cmdline" 2>/dev/null
}

while true; do
    if bench_main_running; then
        # Log the stand-down: a silent gap in the probe log would be
        # indistinguishable from a dead sentinel.
        echo "{\"ts\": \"$(date -u +%FT%TZ)\", \"ok\": false, \"standdown\": true}" >> "$PROBES"
        sleep "$INTERVAL"
        continue
    fi
    if probe; then
        echo "{\"ts\": \"$(date -u +%FT%TZ)\", \"ok\": true}" >> "$PROBES"
        miss=$(missing_legs)
        if [ -n "${miss// /}" ]; then
            echo "sentinel: tunnel up, firing legs:$miss" >&2
            # device_bench_run.sh skips legs that already have a tpu
            # result, so re-firing it is idempotent.
            bash "$REPO/scripts/device_bench_run.sh" "$RUNS"
        fi
    else
        echo "{\"ts\": \"$(date -u +%FT%TZ)\", \"ok\": false}" >> "$PROBES"
    fi
    sleep "$INTERVAL"
done
