#!/usr/bin/env python
"""Diff bench trajectory files into a per-leg delta table with a
regression gate.

    python scripts/bench_compare.py BENCH_r05.json BENCH_r06.json
    python scripts/bench_compare.py BENCH_r*.json            # trajectory
    python scripts/bench_compare.py BENCH_r05.json /tmp/leg.json \
        --as-leg smoke --threshold 0.25

With exactly two inputs, prints old-vs-new per-leg rates and exits
nonzero iff any shared leg's rate regresses past ``--threshold``
(fraction, default 0.10) — the CI-checkable gate the bench trajectory
never had. With more inputs, prints the whole trajectory (legs x files;
no gate). Legs the bench marks advisory (``<leg>_advisory``: sub-second
steady windows, not rate claims) are shown but never gate.

Accepted input shapes, sniffed per file:

- a ``BENCH_r*.json`` wrapper (``{"parsed": {...}, "tail": "..."}``) —
  uses ``parsed`` when present, else regex-salvages rates out of the
  ``tail`` (which may be truncated mid-line: killed benches tear it);
- the raw ``bench.py`` output line itself (``{"metric": ..., "value":
  ..., "<leg>_rate": ...}``);
- a single leg child's JSON line (``{"rate": ..., "unique": ...}``) —
  named via ``--as-leg`` (default: the file stem).

Stdlib-only: trajectory files outlive the runs that wrote them and must
stay comparable on boxes without jax.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import re
import sys

# The primary 2pc leg rides the headline "value" field; every other leg
# is "<leg>_rate". Salvage both shapes straight out of (possibly torn)
# text so a truncated tail still yields every complete key it carries.
# The delimiter lookahead is load-bearing: a tail torn mid-number
# ('"value": 123' from '"value": 123456.7') must be DROPPED, not
# salvaged as a rate that is wrong by orders of magnitude.
_LEG_RATE_RE = re.compile(
    r'"([A-Za-z0-9_]+)_rate"\s*:\s*([0-9.eE+-]+)(?=[,}\s])'
)
_VALUE_RE = re.compile(r'"value"\s*:\s*([0-9.eE+-]+)(?=[,}\s])')
_ADVISORY_RE = re.compile(r'"([A-Za-z0-9_]+)_advisory"\s*:\s*true')
_METRIC_LEG_RE = re.compile(r'"metric"\s*:\s*"([A-Za-z0-9_]+)')

PRIMARY_LEG = "2pc"


def _primary_leg_of(metric) -> str:
    """The leg the headline "value" belongs to: the metric string's
    leading word ("2pc-7 exhaustive ..." -> "2pc", "service aggregate
    ..." -> "service"). Attributing a service-bench aggregate to the
    2pc leg would poison the trajectory gate with an apples-to-oranges
    regression."""
    if not metric:
        return PRIMARY_LEG
    head = re.match(r"[A-Za-z0-9_]+", str(metric))
    return head.group(0) if head else PRIMARY_LEG


def _rates_from_text(text):
    rates, advisory = {}, set()
    m = _VALUE_RE.search(text)
    if m:
        metric = _METRIC_LEG_RE.search(text)
        try:
            rates[
                _primary_leg_of(metric.group(1) if metric else None)
            ] = float(m.group(1))
        except ValueError:
            pass  # interleaved-write garbage ('1.23.4'): DROP, don't die
    for leg, value in _LEG_RATE_RE.findall(text):
        if leg == "host":  # host_rate is the baseline, not a leg
            continue
        try:
            rates[leg] = float(value)
        except ValueError:
            pass
    for (leg,) in (m.groups() for m in _ADVISORY_RE.finditer(text)):
        advisory.add(leg)
    return rates, advisory


def _rates_from_line(line: dict):
    rates, advisory = {}, set()
    if "value" in line:
        try:
            rates[_primary_leg_of(line.get("metric"))] = float(
                line["value"]
            )
        except (TypeError, ValueError):
            pass  # null/garbage from a torn or hand-edited file: DROP
    for key, value in line.items():
        if key.endswith("_rate") and key != "host_rate":
            try:
                rates[key[: -len("_rate")]] = float(value)
            except (TypeError, ValueError):
                pass
        if key.endswith("_advisory") and value:
            advisory.add(key[: -len("_advisory")])
    return rates, advisory


def load_rates(path, as_leg=None):
    """``(rates {leg: states/s}, advisory legs, note)`` for one file."""
    with open(path) as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        rates, advisory = _rates_from_text(text)
        return rates, advisory, "unparseable JSON; regex salvage"
    if isinstance(obj, dict) and ("tail" in obj or "parsed" in obj):
        parsed = obj.get("parsed")
        if isinstance(parsed, dict):
            rates, advisory = _rates_from_line(parsed)
            return rates, advisory, None
        rates, advisory = _rates_from_text(obj.get("tail") or "")
        return rates, advisory, "no parsed line; salvaged from tail"
    if isinstance(obj, dict) and ("value" in obj or "metric" in obj):
        rates, advisory = _rates_from_line(obj)
        return rates, advisory, None
    if isinstance(obj, dict) and "rate" in obj:
        leg = as_leg or os.path.splitext(os.path.basename(path))[0]
        advisory = {leg} if obj.get("advisory") else set()
        try:
            return {leg: float(obj["rate"])}, advisory, None
        except (TypeError, ValueError):
            return {}, set(), "null/garbage rate; dropped"
    return {}, set(), "unrecognized shape"


def compare(old, new, threshold, out=sys.stdout):
    """Old-vs-new delta table; returns the legs breaching the gate.

    A non-advisory leg present in old but MISSING from new gates too —
    a leg that crashed entirely is worse than one that merely slowed —
    but only when the files share at least one leg (zero overlap means
    the inputs aren't comparable trajectories, e.g. a bench line vs a
    single fresh leg: table only, caller warned), and only when the new
    side was fully parsed: in a torn-tail salvage a missing key is
    indistinguishable from truncation, so absence there cannot convict."""
    old_rates, old_adv, _ = old
    new_rates, new_adv, new_note = new
    legs = sorted(set(old_rates) | set(new_rates))
    comparable = bool(set(old_rates) & set(new_rates))
    new_complete = new_note is None
    breaches = []
    header = (
        f"{'leg':<10} {'old /s':>12} {'new /s':>12} {'delta':>8}  flag"
    )
    out.write(header + "\n" + "-" * len(header) + "\n")
    for leg in legs:
        a, b = old_rates.get(leg), new_rates.get(leg)
        if a is None or b is None:
            dropped = b is None
            gates = (
                dropped and comparable and new_complete
                and leg not in old_adv
            )
            if gates:
                breaches.append(leg)
            flag = (
                "DROPPED (gate)" if gates
                else "(dropped?)" if dropped and not new_complete
                else "(dropped)" if dropped
                else "(new leg)"
            )
            out.write(
                f"{leg:<10} {_fmt(a):>12} {_fmt(b):>12} {'':>8}  {flag}\n"
            )
            continue
        delta = (b - a) / a if a else 0.0
        advisory = leg in old_adv or leg in new_adv
        breached = delta < -threshold and not advisory
        if breached:
            breaches.append(leg)
        flag = (
            "REGRESSION" if breached
            else "advisory" if advisory and delta < -threshold
            else ""
        )
        out.write(
            f"{leg:<10} {a:>12,.1f} {b:>12,.1f} {delta:>+7.1%}  {flag}\n"
        )
    if not comparable:
        print(
            "warning: no shared legs between the two inputs; "
            "nothing gated",
            file=sys.stderr,
        )
    return breaches


def trajectory(loaded, out=sys.stdout):
    """Legs x files rate table over the whole trajectory (no gate)."""
    names = [os.path.basename(p) for p, _ in loaded]
    legs = sorted({leg for _, (rates, _, _) in loaded for leg in rates})
    width = max(12, max((len(n) for n in names), default=12) + 1)
    out.write(f"{'leg':<10}" + "".join(f"{n:>{width}}" for n in names) + "\n")
    for leg in legs:
        row = f"{leg:<10}"
        for _, (rates, _, _) in loaded:
            value = rates.get(leg)
            row += f"{_fmt(value):>{width}}"
        out.write(row + "\n")


def _fmt(value):
    return f"{value:,.1f}" if value is not None else "-"


def _leg_utilization(leg):
    """(realized, predicted-under-full-overlap) from one A/B leg's
    attribution record; (None, None) when the ledger is absent."""
    att = leg.get("attribution") or {}
    realized = att.get("utilization")
    oh = att.get("overlap_headroom") or {}
    device = (att.get("phases_s") or {}).get("device")
    predicted_wall = oh.get("predicted_wall_s")
    predicted = (
        device / predicted_wall
        if device is not None and predicted_wall
        else None
    )
    return realized, predicted


def service_trajectory(paths, out=sys.stdout):
    """Concurrent-throughput trajectory across service bench records
    (r10 time-sliced -> r12 tenant-packed): aggregate states/s, its
    ratio to the single-job rate (the "concurrency tax"), ttfv
    latencies, preempt counts, and lane fill where the record carries
    pack accounting. Renders every file that holds a ``per_job``
    record; exits nonzero when fewer than two do (nothing to compare)."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)))
    )
    from service_report import load_record

    rows = []
    for path in paths:
        rec = load_record(path)
        if rec is None:
            print(f"note: {path}: no service record", file=sys.stderr)
            continue
        agg = rec.get("aggregate_states_per_s")
        steady = rec.get("aggregate_steady_states_per_s", agg)
        single = rec.get("single_job_rate")
        pack = rec.get("pack") or {}
        rows.append(
            {
                "name": os.path.basename(path),
                "mode": "packed" if rec.get("packed") else "sliced",
                "jobs": rec.get("jobs"),
                "aggregate": agg,
                "steady": steady,
                "ratio": (
                    steady / single if steady and single else None
                ),
                "p50": rec.get("p50_ttfv_s"),
                "p99": rec.get("p99_ttfv_s"),
                "preempts": rec.get("preempts_total"),
                "lane_fill": pack.get("lane_fill"),
            }
        )
    if len(rows) < 2:
        print(
            "error: need >= 2 files with service records "
            "(bench.py --service / --service-packed output)",
            file=sys.stderr,
        )
        return 2
    header = (
        f"{'record':<16} {'mode':>7} {'jobs':>5} {'agg/s':>10} "
        f"{'steady/s':>10} {'vs-1job':>8} {'p50ttfv':>8} {'p99ttfv':>8} "
        f"{'preempts':>8} {'lanefill':>8}\n"
    )
    out.write(header)
    out.write("-" * (len(header) - 1) + "\n")

    def cell(v, spec="{:,.1f}"):
        return "-" if v is None else spec.format(v)

    for r in rows:
        out.write(
            f"{r['name']:<16} {r['mode']:>7} {str(r['jobs']):>5} "
            f"{cell(r['aggregate']):>10} {cell(r['steady']):>10} "
            f"{cell(r['ratio'], '{:.2f}x'):>8} "
            f"{cell(r['p50'], '{:.2f}s'):>8} "
            f"{cell(r['p99'], '{:.2f}s'):>8} "
            f"{str(r['preempts']):>8} "
            f"{cell(r['lane_fill'], '{:.2f}'):>8}\n"
        )
    first, last = rows[0], rows[-1]
    if first["aggregate"] and last["aggregate"]:
        out.write(
            f"\nconcurrent aggregate {first['name']} -> {last['name']}: "
            f"{first['aggregate']:,.1f} -> {last['aggregate']:,.1f} "
            f"states/s ({last['aggregate'] / first['aggregate']:.2f}x)\n"
        )
    return 0


def ab_async_report(path, out=sys.stdout):
    """The async-pipeline A/B table from one ``bench.py --async-ab``
    record (BENCH_r11+): rate and pipeline-utilization deltas between
    the async-off and async-on legs, with the async-off ledger's
    PREDICTED utilization (the PR-7 headroom estimate) next to the
    async-on leg's REALIZED one — the instrument closing its own loop.
    Always advisory (exit 0 when both legs parsed): CPU boxes make
    rate claims noise; the bit-identical assert lives in the bench
    child itself."""
    with open(path) as f:
        obj = json.load(f)
    rec = obj.get("parsed") if isinstance(obj, dict) and "parsed" in obj \
        else obj
    if not isinstance(rec, dict):
        print(f"error: {path}: no parsed A/B record", file=sys.stderr)
        return 2
    off, on = rec.get("async_off"), rec.get("async_on")
    if not off or not on:
        print(
            f"error: {path}: record carries no async_off/async_on legs "
            "(produce one with bench.py --async-ab)",
            file=sys.stderr,
        )
        return 2
    u_off, predicted = _leg_utilization(off)
    u_on, _ = _leg_utilization(on)
    header = (
        f"{'':<14} {'async off':>12} {'async on':>12} {'delta':>8}"
    )
    out.write(header + "\n" + "-" * len(header) + "\n")
    r_off, r_on = off.get("rate"), on.get("rate")
    rate_delta = (
        f"{(r_on - r_off) / r_off:+.1%}" if r_off and r_on else ""
    )
    out.write(
        f"{'states/s':<14} {_fmt(r_off):>12} {_fmt(r_on):>12} "
        f"{rate_delta:>8}\n"
    )
    def pct(v):
        return f"{100.0 * v:.1f}%" if v is not None else "-"
    util_delta = (
        f"{100.0 * (u_on - u_off):+.1f}pp"
        if u_on is not None and u_off is not None
        else ""
    )
    out.write(
        f"{'utilization':<14} {pct(u_off):>12} {pct(u_on):>12} "
        f"{util_delta:>8}\n"
    )
    out.write(
        f"{'predicted':<14} {pct(predicted):>12} {'(realized ^)':>12}\n"
    )
    overlapped = on.get("overlapped_total_s")
    if overlapped is not None:
        out.write(
            f"achieved overlap: {overlapped:.2f}s host work run on the "
            "pipeline worker (upper bound on wall saved; the realized "
            "saving is the rate/utilization delta above)\n"
        )
    if rec.get("bit_identical") is not None:
        out.write(f"bit-identical: {rec['bit_identical']}\n")
    return 0


def megakernel_report(path, out=sys.stdout):
    """The fused-wave megakernel A/B table from one ``bench.py
    --megakernel`` record (BENCH_r16): per-model staged-vs-fused
    utilization, gap share, dispatch windows per wave (the staged
    chain's ``device`` windows vs the fused path's single
    ``wave_kernel`` dispatch), and rate. Always advisory (exit 0 when
    the record parsed): on CPU the fused kernel runs under the Pallas
    interpreter, so wall/utilization are the interpreter's cost — the
    bit-identical assert lives in the bench child itself."""
    with open(path) as f:
        obj = json.load(f)
    rec = obj.get("parsed") if isinstance(obj, dict) and "parsed" in obj \
        else obj
    if not isinstance(rec, dict) or "models" not in rec:
        print(
            f"error: {path}: no megakernel A/B record (produce one with "
            "bench.py --megakernel)",
            file=sys.stderr,
        )
        return 2
    out.write(
        f"fused wave megakernel A/B ({rec.get('device')}"
        + (", advisory" if rec.get("advisory") else "")
        + ")\n"
    )

    def pct(v):
        return f"{100.0 * v:.1f}%" if v is not None else "-"

    def windows(leg):
        w = leg.get("phase_windows") or {}
        n = w.get("wave_kernel", w.get("device"))
        waves = (leg.get("attribution") or {}).get("waves")
        if n is None or not waves:
            return "-"
        return f"{n}/{waves}w"

    for mname, m in rec["models"].items():
        staged, fused = m.get("staged") or {}, m.get("fused") or {}
        out.write(f"\n{mname}\n")
        header = (
            f"{'':<14} {'staged':>12} {'fused':>12} {'delta':>9}"
        )
        out.write(header + "\n" + "-" * len(header) + "\n")
        u_s, u_f = staged.get("utilization"), fused.get("utilization")
        u_delta = (
            f"{100.0 * (u_f - u_s):+.1f}pp"
            if u_s is not None and u_f is not None
            else ""
        )
        out.write(
            f"{'utilization':<14} {pct(u_s):>12} {pct(u_f):>12} "
            f"{u_delta:>9}\n"
        )
        g_s, g_f = staged.get("gap_share"), fused.get("gap_share")
        g_delta = (
            f"{100.0 * (g_f - g_s):+.1f}pp"
            if g_s is not None and g_f is not None
            else ""
        )
        out.write(
            f"{'gap share':<14} {pct(g_s):>12} {pct(g_f):>12} "
            f"{g_delta:>9}\n"
        )
        out.write(
            f"{'dispatches':<14} {windows(staged):>12} "
            f"{windows(fused):>12}\n"
        )
        r_s, r_f = staged.get("rate"), fused.get("rate")
        rate_delta = (
            f"{(r_f - r_s) / r_s:+.1%}" if r_s and r_f else ""
        )
        out.write(
            f"{'states/s':<14} {_fmt(r_s):>12} {_fmt(r_f):>12} "
            f"{rate_delta:>9}\n"
        )
        if m.get("bit_identical") is not None:
            out.write(f"bit-identical: {m['bit_identical']}\n")
    return 0


def swarm_report(path, out=sys.stdout):
    """The swarm-verification table from one ``bench.py --swarm``
    record (BENCH_r15): per-leg time-to-first-violation (swarm vs
    exhaustive where exhaustive exists), walk throughput, and the
    honest unique-coverage sample. Always advisory (exit 0 when the
    record parsed): wall-clock claims are noise on shared CPU boxes;
    the determinism asserts live in the bench child and the tier-1
    suite."""
    with open(path) as f:
        rec = None
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "swarm" in obj:
                rec = obj
    if rec is None:
        print(
            f"error: {path}: no swarm record found (produce one with "
            "bench.py --swarm)",
            file=sys.stderr,
        )
        return 2
    sw = rec["swarm"]
    out.write(
        f"swarm verification ({rec.get('device')}"
        + (", advisory" if rec.get("advisory") else "")
        + ")\n\n"
    )
    header = (
        f"{'leg':<24} {'swarm ttfv':>11} {'exhaustive':>11} "
        f"{'speedup':>8} {'sample uniq':>12}"
    )
    out.write(header + "\n" + "-" * len(header) + "\n")

    def sample_cell(leg):
        s = leg.get("swarm_sample") or leg.get("sample") or {}
        u = s.get("unique_sample")
        if u is None:
            return "-"
        return ("≥" if s.get("saturated") else "") + f"{u:,}"

    raft = sw.get("raft3_check_live") or {}
    out.write(
        f"{'raft-3 check-live':<24} "
        f"{_fmt(raft.get('swarm_ttfv_s')) + 's':>11} "
        f"{_fmt(raft.get('exhaustive_ttfv_s')) + 's':>11} "
        f"{_fmt(raft.get('speedup')) + 'x':>8} "
        f"{sample_cell(raft):>12}\n"
    )
    two = sw.get("two_phase") or sw.get("two_phase_5") or {}
    two_label = f"{two.get('model', '2pc')} witnesses"
    out.write(
        f"{two_label:<24} "
        f"{_fmt(two.get('swarm_wall_s')) + 's':>11} "
        f"{_fmt(two.get('exhaustive_wall_s')) + 's':>11} "
        f"{'':>8} {sample_cell(two):>12}\n"
    )
    kv = sw.get("sharded_kv") or {}
    if kv.get("exhaustive_found"):
        ex_cell = _fmt(kv.get("exhaustive_ttfv_s")) + "s"
        sp_cell = _fmt(kv.get("speedup_lower_bound")) + "x"
    else:
        budget = kv.get("exhaustive_budget_s")
        bound = kv.get("speedup_lower_bound")
        ex_cell = f">{budget:.0f}s" if budget is not None else "-"
        sp_cell = f">={bound:.0f}x" if bound is not None else "-"
    out.write(
        f"{'sharded_kv 4x8 (~1e14)':<24} "
        f"{_fmt(kv.get('ttfv_s')) + 's':>11} "
        f"{ex_cell:>11} {sp_cell:>8} {sample_cell(kv):>12}\n"
    )
    if not kv.get("exhaustive_found"):
        out.write(
            f"  (exhaustive explored "
            f"{kv.get('exhaustive_states_explored', 0):,} states to "
            f"depth {kv.get('exhaustive_max_depth')} inside its wall "
            "budget without reaching the violation)\n"
        )
    if kv.get("walk_steps_per_s") is not None:
        out.write(
            f"\nwalk throughput: {kv['walk_steps_per_s']:,.0f} "
            f"walk-steps/s over {kv.get('walk_steps', 0):,} steps "
            f"(violation: {kv.get('violation')!r} at depth "
            f"{kv.get('violation_len')})\n"
        )
    return 0


def conformance_report(path, out=sys.stdout):
    """The conformance-plane throughput table from one ``bench.py
    --conformance`` record (BENCH_r20): replay traces/sec and audit
    histories/sec vs batch size (the batching-amortization story), and
    the divergence-rate sweep (flat = the replay kernel stayed
    branchless). Always advisory (exit 0 when the record parsed):
    wall-clock claims are noise on shared CPU boxes; the bit-identity
    asserts live in the parity suite."""
    with open(path) as f:
        rec = None
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "conformance" in obj:
                rec = obj
    if rec is None:
        print(
            f"error: {path}: no conformance record found (produce one "
            "with bench.py --conformance)",
            file=sys.stderr,
        )
        return 2
    conf = rec["conformance"]
    out.write(
        f"conformance plane ({conf.get('device')}, "
        f"{conf.get('model')} traces, T={conf.get('trace_steps')})\n\n"
    )
    header = (
        f"{'batch':>6} {'replay traces/s':>16} {'warm':>9} "
        f"{'cold':>7} {'audit hist/s':>13} {'warm':>9}"
    )
    out.write(header + "\n" + "-" * len(header) + "\n")
    batches = sorted(
        set(conf.get("replay") or {}) | set(conf.get("audit") or {}),
        key=int,
    )
    for b in batches:
        rp = (conf.get("replay") or {}).get(b) or {}
        au = (conf.get("audit") or {}).get(b) or {}

        def ms(v):
            return "-" if v is None else f"{v * 1e3:,.1f}ms"

        out.write(
            f"{b:>6} {_fmt(rp.get('traces_per_s')):>16} "
            f"{ms(rp.get('warm_s')):>9} "
            f"{_fmt(rp.get('cold_s')) + 's':>7} "
            f"{_fmt(au.get('histories_per_s')):>13} "
            f"{ms(au.get('warm_s')):>9}\n"
        )
    amort = conf.get("replay_batch_amortization")
    if amort is not None:
        out.write(
            f"\nbatch amortization: {amort:,.0f}x traces/s at the "
            "widest batch vs batch=1\n"
        )
    sweep = conf.get("divergence_sweep") or {}
    if sweep:
        out.write("\ndivergence-rate sweep (widest batch)\n")
        for label, v in sweep.items():
            out.write(
                f"  {label:>6}: {_fmt(v.get('traces_per_s')):>12} "
                f"traces/s ({v.get('divergent_lanes', 0):,} divergent "
                "lanes)\n"
            )
        flat = conf.get("divergence_flatness")
        if flat is not None:
            out.write(
                f"  flatness (min/max): {flat:.2f} "
                "(~1.0 = branchless, rate-independent)\n"
            )
    return 0


def multichip_trajectory(paths, out=sys.stdout):
    """The pod-scale sharding trajectory across ``MULTICHIP_r*.json``
    records (r01 dryruns -> r06 sieve A/B scaling curve): one summary
    row per file keyed on the legacy dryrun fields (``n_devices`` /
    ``rc`` / ``ok`` / ``skipped`` / ``tail``), then the newest record's
    shard-count curve when it carries one. A file absent from the
    series renders as a ``(missing)`` row instead of aborting — the
    early points of a trajectory outlive the boxes that wrote them, and
    one lost file must not hide the rest. Exits nonzero only when no
    input loads at all."""
    rows = []
    for path in paths:
        name = os.path.basename(path)
        try:
            with open(path) as f:
                rec = json.load(f)
        except OSError:
            rows.append((name, None, "(missing)"))
            continue
        except json.JSONDecodeError:
            rows.append((name, None, "(unparseable)"))
            continue
        if not isinstance(rec, dict) or "n_devices" not in rec:
            rows.append((name, None, "(no multichip record)"))
            continue
        rows.append((name, rec, None))
    if not any(rec is not None for _, rec, _ in rows):
        print(
            "error: no readable MULTICHIP record among inputs",
            file=sys.stderr,
        )
        return 2
    header = (
        f"{'record':<20} {'devices':>8} {'verdict':>8} {'states/s':>10}"
        "  note"
    )
    out.write(header + "\n" + "-" * len(header) + "\n")
    newest_curve = None
    for name, rec, note in rows:
        if rec is None:
            out.write(f"{name:<20} {'-':>8} {'-':>8} {'-':>10}  {note}\n")
            continue
        verdict = (
            "skipped" if rec.get("skipped")
            else "ok" if rec.get("ok")
            else f"rc={rec.get('rc')}"
        )
        value = rec.get("value")
        rate = value if isinstance(value, (int, float)) and value else None
        tail = (rec.get("tail") or "").strip()
        tail_note = "" if rec.get("ok") else tail.splitlines()[-1][:44] \
            if tail else ""
        out.write(
            f"{name:<20} {str(rec.get('n_devices', '-')):>8} "
            f"{verdict:>8} {_fmt(rate):>10}  {tail_note}\n"
        )
        if isinstance(rec.get("curve"), list) and rec["curve"]:
            newest_curve = (name, rec["curve"])
    if newest_curve is None:
        out.write(
            "\n(no record carries a scaling curve yet — produce one "
            "with bench.py --multichip)\n"
        )
        return 0
    name, curve = newest_curve
    out.write(f"\nscaling curve ({name}): sieve off vs on per width\n")
    header = (
        f"{'shards':>6} {'off /s':>10} {'on /s':>10} {'bit-id':>7} "
        f"{'lanes/wave':>16} {'reduction':>10} {'kill':>6} {'fp':>9}"
    )
    out.write(header + "\n" + "-" * len(header) + "\n")
    for point in curve:
        off = point.get("sieve_off") or {}
        on = point.get("sieve_on") or {}
        coff, con = off.get("comms") or {}, on.get("comms") or {}
        ident = point.get("bit_identical")
        lanes = (
            f"{coff['lanes_per_wave']:,.0f}->{con['lanes_per_wave']:,.0f}"
            if "lanes_per_wave" in coff and "lanes_per_wave" in con
            else "-"
        )
        reduction = point.get("lane_reduction_x")
        kill = con.get("sieve_kill_rate")
        probes, fps = con.get("bloom_probe_total"), con.get("bloom_fp_total")
        fp_cell = f"{fps}/{probes}" if probes else "-"
        out.write(
            f"{str(point.get('n_shards', '-')):>6} "
            f"{_fmt(off.get('rate')):>10} {_fmt(on.get('rate')):>10} "
            f"{'yes' if ident else '-' if ident is None else 'NO':>7} "
            f"{lanes:>16} "
            f"{(str(reduction) + 'x') if reduction is not None else '-':>10} "
            f"{f'{kill:.0%}' if kill is not None else '-':>6} "
            f"{fp_cell:>9}\n"
        )
    diverged = [
        str(p.get("n_shards"))
        for p in curve
        if p.get("bit_identical") is False
    ]
    if diverged:
        out.write(
            f"\nBIT-IDENTITY BROKEN at shard widths: {', '.join(diverged)}"
            " — the sieve changed results; gate before trusting rates\n"
        )
        return 1
    return 0


def slo_trajectory(paths, out=sys.stdout):
    """The serving-latency trajectory across service bench records
    (r10 time-sliced -> r12 tenant-packed -> r18 SLO ledger): one row
    per file with its best-available ttfv evidence — the full
    queue/compile/explore decomposition where the record carries an
    ``slo`` block (BENCH_r18+), the bare p50/p99 ttfv where it only has
    the legacy service keys (r10/r12), the swarm ttfv where only a
    swarm record exists (r15). A file absent from the series renders as
    a ``(missing)`` row instead of aborting — matching ``--multichip``:
    early trajectory points outlive the boxes that wrote them, and one
    lost file must not hide the rest. Exits nonzero only when no input
    loads at all. After the table, the newest ``slo`` block renders
    per-mode."""
    rows = []
    newest_slo = None
    for path in paths:
        name = os.path.basename(path)
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            rows.append((name, None, "(missing)"))
            continue
        rec = None
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                rec = obj
        if rec is None:
            rows.append((name, None, "(unparseable)"))
            continue
        slo = rec.get("slo")
        if isinstance(slo, dict) and slo.get("modes"):
            modes = {
                m: v
                for m, v in slo["modes"].items()
                if (v.get("jobs") or 0) > 0
            }
            view = modes.get("packed") or (
                next(iter(modes.values())) if modes else None
            )
            if view is None:
                rows.append((name, None, "(empty slo ledger)"))
                continue
            d = view.get("decomposition") or {}
            comp = view.get("compile") or {}
            rows.append((name, {
                "source": "slo ledger",
                "jobs": sum(v.get("jobs", 0) for v in modes.values()),
                "p50": view["ttfv"].get("p50_s"),
                "p99": view["ttfv"].get("p99_s"),
                "queue": (d.get("queue_s") or {}).get("p50_s"),
                "compile": (d.get("compile_s") or {}).get("p50_s"),
                "explore": (d.get("explore_s") or {}).get("p50_s"),
                # Compile-share columns (r19 warm-start records): the
                # per-job compile-seconds percentiles and the fraction
                # of served jobs that never compiled; None on r18.
                "comp_p50": comp.get("p50_s"),
                "comp_p99": comp.get("p99_s"),
                "comp_free": comp.get("free_fraction"),
            }, None))
            newest_slo = (name, slo)
        elif "p50_ttfv_s" in rec:
            rows.append((name, {
                "source": "packed" if rec.get("packed") else "sliced",
                "jobs": rec.get("jobs"),
                "p50": rec.get("p50_ttfv_s"),
                "p99": rec.get("p99_ttfv_s"),
                "queue": None, "compile": None, "explore": None,
            }, None))
        elif isinstance(rec.get("swarm"), dict):
            raft = rec["swarm"].get("raft3_check_live") or {}
            rows.append((name, {
                "source": "swarm",
                "jobs": None,
                "p50": raft.get("swarm_ttfv_s"),
                "p99": None,
                "queue": None, "compile": None, "explore": None,
            }, None))
        else:
            rows.append((name, None, "(no ttfv data)"))
    if not any(r is not None for _, r, _ in rows):
        print(
            "error: no readable ttfv/SLO record among inputs",
            file=sys.stderr,
        )
        return 2

    def cell(v, spec="{:.3f}"):
        return "-" if v is None else spec.format(v)

    header = (
        f"{'record':<18} {'source':>11} {'jobs':>5} {'ttfv p50':>9} "
        f"{'ttfv p99':>9} {'queue':>8} {'compile':>8} {'explore':>8}"
        "  note"
    )
    out.write(header + "\n" + "-" * len(header) + "\n")
    for name, r, note in rows:
        if r is None:
            out.write(
                f"{name:<18} {'-':>11} {'-':>5} {'-':>9} {'-':>9} "
                f"{'-':>8} {'-':>8} {'-':>8}  {note}\n"
            )
            continue
        out.write(
            f"{name:<18} {r['source']:>11} {str(r['jobs'] or '-'):>5} "
            f"{cell(r['p50']):>9} {cell(r['p99']):>9} "
            f"{cell(r['queue']):>8} {cell(r['compile']):>8} "
            f"{cell(r['explore']):>8}\n"
        )
    if newest_slo is None:
        out.write(
            "\n(no record carries an SLO ledger yet — produce one with "
            "bench.py --slo)\n"
        )
        return 0
    name, slo = newest_slo
    targets = slo.get("targets") or {}
    tgt = (
        ", ".join(f"{k} <= {v}s" for k, v in sorted(targets.items()))
        if targets
        else "none"
    )
    out.write(
        f"\nper-mode ledger ({name}; targets: {tgt})\n"
    )
    header = (
        f"{'mode':<12} {'jobs':>5} {'ttfv p50':>9} {'ttfv p99':>9} "
        f"{'queue':>8} {'compile':>8} {'explore':>8} {'burn':>12}"
    )
    out.write(header + "\n" + "-" * len(header) + "\n")
    for mode, view in slo["modes"].items():
        if not (view.get("jobs") or 0):
            continue
        d = view.get("decomposition") or {}
        burn = view.get("burn_rate") or {}
        burn_cell = (
            ", ".join(f"{k} {v:.1f}x" for k, v in sorted(burn.items()))
            if burn
            else "-"
        )
        out.write(
            f"{mode:<12} {view.get('jobs', 0):>5} "
            f"{cell(view['ttfv'].get('p50_s')):>9} "
            f"{cell(view['ttfv'].get('p99_s')):>9} "
            f"{cell((d.get('queue_s') or {}).get('p50_s')):>8} "
            f"{cell((d.get('compile_s') or {}).get('p50_s')):>8} "
            f"{cell((d.get('explore_s') or {}).get('p50_s')):>8} "
            f"{burn_cell:>12}\n"
        )
    # Compile-share delta between the two newest SLO-ledger records
    # (r18 -> r19): what the warm-start plane bought in per-job compile
    # seconds and compile-free-job fraction.
    ledger_rows = [
        (n, r) for n, r, _ in rows
        if r is not None and r.get("source") == "slo ledger"
    ]
    if len(ledger_rows) >= 2:
        (old_name, old), (new_name, new) = ledger_rows[-2], ledger_rows[-1]
        out.write(f"\ncompile share ({old_name} -> {new_name})\n")

        def pct_cell(v):
            return "-" if v is None else f"{v:.0%}"

        for label, key, fmt in (
            ("compile p50 (s)", "comp_p50", cell),
            ("compile p99 (s)", "comp_p99", cell),
            ("compile-free jobs", "comp_free", pct_cell),
        ):
            ov, nv = old.get(key), new.get(key)
            delta = ""
            if ov is not None and nv is not None and key != "comp_free":
                delta = f"  ({nv - ov:+.3f}s)"
            elif ov is not None and nv is not None:
                delta = f"  ({(nv - ov) * 100:+.0f}pp)"
            out.write(
                f"  {label:<18} {fmt(ov):>9} -> {fmt(nv):>9}{delta}\n"
            )
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Per-leg rate deltas between bench trajectory files, "
        "with a regression threshold gate."
    )
    parser.add_argument("files", nargs="+", help="BENCH_r*.json (or raw "
                        "bench/leg JSON lines); 2 = gated diff, 3+ = "
                        "trajectory table")
    parser.add_argument(
        "--threshold", type=float, default=None,
        help="max tolerated fractional rate drop per leg (default 0.10); "
        "exceeded => exit 1. With 3+ files the trajectory table prints "
        "and an explicit --threshold gates the newest step",
    )
    parser.add_argument(
        "--legs", help="comma-separated leg filter (default: all)"
    )
    parser.add_argument(
        "--as-leg",
        help="leg name for bare single-leg result files (bench.py --leg "
        "output); default: the file stem",
    )
    parser.add_argument(
        "--ab-async", action="store_true",
        help="render the async-pipeline A/B table (rate + predicted vs "
        "realized utilization) from one bench.py --async-ab record",
    )
    parser.add_argument(
        "--megakernel", action="store_true",
        help="render the fused-wave megakernel A/B table (per-model "
        "staged vs fused utilization, gap share, dispatch windows) from "
        "one bench.py --megakernel record",
    )
    parser.add_argument(
        "--swarm", action="store_true",
        help="render the swarm-verification table (ttfv vs exhaustive, "
        "walk throughput, coverage sample) from one bench.py --swarm "
        "record",
    )
    parser.add_argument(
        "--conformance", action="store_true",
        help="render the conformance-plane throughput table (replay "
        "traces/s and audit histories/s vs batch size, divergence-rate "
        "sweep) from one bench.py --conformance record",
    )
    parser.add_argument(
        "--multichip", action="store_true",
        help="render the pod-scale sharding trajectory across "
        "MULTICHIP_r*.json records (per-file verdicts, then the newest "
        "sieve A/B scaling curve); missing files render as rows, not "
        "errors",
    )
    parser.add_argument(
        "--slo", action="store_true",
        help="render the serving-latency trajectory across service "
        "bench records (r10/r12 ttfv -> r18 SLO ledger with "
        "queue/compile/explore decomposition); missing files render as "
        "rows, not errors",
    )
    parser.add_argument(
        "--service-trajectory", action="store_true",
        help="render the concurrent-throughput trajectory across "
        "service bench records (time-sliced r10 vs tenant-packed r12+: "
        "aggregate, ratio to single-job rate, ttfv, preempts, lane "
        "fill)",
    )
    args = parser.parse_args(argv)

    if args.multichip:
        return multichip_trajectory(args.files)

    if args.slo:
        return slo_trajectory(args.files)

    if args.service_trajectory:
        return service_trajectory(args.files)

    if args.megakernel:
        if len(args.files) != 1:
            print(
                "error: --megakernel takes exactly one bench record",
                file=sys.stderr,
            )
            return 2
        try:
            return megakernel_report(args.files[0])
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {args.files[0]}: {e}", file=sys.stderr)
            return 2

    if args.conformance:
        if len(args.files) != 1:
            print(
                "error: --conformance takes exactly one bench record",
                file=sys.stderr,
            )
            return 2
        try:
            return conformance_report(args.files[0])
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {args.files[0]}: {e}", file=sys.stderr)
            return 2

    if args.swarm:
        if len(args.files) != 1:
            print(
                "error: --swarm takes exactly one bench record",
                file=sys.stderr,
            )
            return 2
        try:
            return swarm_report(args.files[0])
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {args.files[0]}: {e}", file=sys.stderr)
            return 2

    if args.ab_async:
        if len(args.files) != 1:
            print(
                "error: --ab-async takes exactly one bench record",
                file=sys.stderr,
            )
            return 2
        try:
            return ab_async_report(args.files[0])
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: {args.files[0]}: {e}", file=sys.stderr)
            return 2

    loaded = []
    for path in args.files:
        try:
            rates, advisory, note = load_rates(path, as_leg=args.as_leg)
        except OSError as e:
            print(f"error: {path}: {e}", file=sys.stderr)
            return 2
        if note:
            print(f"note: {path}: {note}", file=sys.stderr)
        if not rates:
            print(f"error: {path}: no leg rates found", file=sys.stderr)
            return 2
        if args.legs:
            keep = set(args.legs.split(","))
            rates = {k: v for k, v in rates.items() if k in keep}
            if not rates:
                # A typo'd filter must not turn the gate vacuously green.
                print(
                    f"error: {path}: --legs {args.legs!r} matches no leg",
                    file=sys.stderr,
                )
                return 2
        loaded.append((path, (rates, advisory, note)))

    threshold = 0.10 if args.threshold is None else args.threshold

    def gate(base, cand, base_path, out=sys.stdout):
        breaches = compare(base, cand, threshold=threshold, out=out)
        if breaches:
            print(
                f"REGRESSION: {', '.join(breaches)} regressed past "
                f"{threshold:.0%} (or vanished) vs {base_path}",
                file=sys.stderr,
            )
            return 1
        return 0

    if len(loaded) == 2:
        return gate(loaded[0][1], loaded[1][1], loaded[0][0])
    trajectory(loaded)
    if args.threshold is not None:
        # An explicit threshold must never be a silent no-op: gate the
        # newest step of the trajectory (table already printed above).
        if len(loaded) < 2:
            print(
                "error: --threshold needs at least two files to gate "
                "(usage error, not a regression)",
                file=sys.stderr,
            )
            return 2
        return gate(
            loaded[-2][1], loaded[-1][1], loaded[-2][0], out=io.StringIO()
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
