#!/usr/bin/env python
"""Per-mode SLO latency report from a bench/service record.

    python scripts/slo_report.py BENCH_r18.json [--json]

Reads the JSON line ``bench.py --slo`` prints (saved as
``BENCH_r18.json``), or any record carrying an ``"slo"`` block — the
``GET /slo`` snapshot shape (``service/slo.py``) — and renders the
end-to-end latency attribution per verification mode: rolling p50/p99
ttfv and verdict latency, the queue/compile/explore ttfv decomposition
(clamped to partition ttfv exactly), and burn rates against the
record's targets when they were set.

``--json`` emits the summary as one JSON object instead of the tables
(machine-readable; the tests consume it) — the convention shared by
``gap_report.py`` / ``service_report.py`` / ``storage_report.py``.
Stdlib-only, like every report reader here: bench records outlive the
runs that wrote them.
"""

from __future__ import annotations

import argparse
import json
import sys

MODES = ("exhaustive", "swarm", "packed")


def load_record(path):
    """The SLO record from a bench JSON file: the last parseable JSON
    line carrying an ``slo`` block (files may hold stderr noise or a
    wrapper line ahead of the record). A bare ``GET /slo`` snapshot
    (top-level ``modes``) is accepted too."""
    record = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(obj, dict):
                continue
            if isinstance(obj.get("slo"), dict):
                record = obj
            elif "modes" in obj and "objective" in obj:
                record = {"slo": obj}
    return record


def summarize(rec):
    slo = rec.get("slo") or {}
    modes = slo.get("modes") or {}
    return {
        "model": rec.get("model"),
        "device": rec.get("device"),
        "jobs_per_mode": rec.get("jobs_per_mode"),
        "targets": slo.get("targets") or {},
        "objective": slo.get("objective"),
        "window": slo.get("window"),
        "decomposition_partitions": rec.get("decomposition_partitions"),
        "modes": {
            m: modes[m]
            for m in MODES
            if m in modes and (modes[m].get("jobs") or 0) > 0
        },
    }


def _fmt(v, spec="{:.3f}", none="-"):
    if v is None:
        return none
    return spec.format(v)


def render(summary, out=sys.stdout):
    w = out.write
    targets = summary["targets"]
    tgt = (
        ", ".join(f"{k} <= {v}s" for k, v in sorted(targets.items()))
        if targets
        else "none (observational)"
    )
    w(
        f"slo ledger: {summary['model'] or '?'} on "
        f"{summary['device'] or '?'} — targets: {tgt}"
        + (
            f" @ {summary['objective']:.0%} objective"
            if targets and summary.get("objective") is not None
            else ""
        )
        + "\n\n"
    )
    if not summary["modes"]:
        w("  (no served jobs in any mode)\n")
        return
    header = (
        f"  {'mode':<12} {'jobs':>5} {'ttfv p50':>9} {'ttfv p99':>9} "
        f"{'queue p50':>10} {'compile p50':>12} {'explore p50':>12} "
        f"{'verdict p50':>12} {'verdict p99':>12}\n"
    )
    w(header)
    w("  " + "-" * (len(header) - 3) + "\n")
    for mode, view in summary["modes"].items():
        d = view.get("decomposition") or {}
        w(
            f"  {mode:<12} {view.get('jobs', 0):>5} "
            f"{_fmt(view['ttfv'].get('p50_s')):>9} "
            f"{_fmt(view['ttfv'].get('p99_s')):>9} "
            f"{_fmt((d.get('queue_s') or {}).get('p50_s')):>10} "
            f"{_fmt((d.get('compile_s') or {}).get('p50_s')):>12} "
            f"{_fmt((d.get('explore_s') or {}).get('p50_s')):>12} "
            f"{_fmt(view['verdict'].get('p50_s')):>12} "
            f"{_fmt(view['verdict'].get('p99_s')):>12}\n"
        )
    w("\n")
    # Compile share (warm-start plane): per-job compile seconds and the
    # fraction of served jobs that never compiled at all.
    any_compile = any(
        (view.get("compile") or {}).get("count")
        for view in summary["modes"].values()
    )
    if any_compile:
        header = (
            f"  {'mode':<12} {'compile p50':>12} {'compile p99':>12} "
            f"{'compile-free':>13} {'warm-start':>11}\n"
        )
        w(header)
        w("  " + "-" * (len(header) - 3) + "\n")
        for mode, view in summary["modes"].items():
            comp = view.get("compile") or {}
            if not comp.get("count"):
                continue
            w(
                f"  {mode:<12} "
                f"{_fmt(comp.get('p50_s')):>12} "
                f"{_fmt(comp.get('p99_s')):>12} "
                f"{_fmt(comp.get('free_fraction'), '{:.0%}'):>13} "
                f"{comp.get('warm_start_jobs', 0):>11}\n"
            )
        w("\n")
    any_burn = False
    for mode, view in summary["modes"].items():
        burn = view.get("burn_rate")
        if burn:
            any_burn = True
            rendered = ", ".join(
                f"{k} {v:.2f}x" for k, v in sorted(burn.items())
            )
            w(f"  burn rate [{mode}]: {rendered} (1.0 = at budget)\n")
    if not any_burn and targets:
        w("  burn rate: no observations against targets yet\n")
    parts = summary.get("decomposition_partitions")
    if parts:
        bad = sorted(m for m, ok in parts.items() if not ok)
        w(
            "  decomposition: queue + compile + explore partitions ttfv "
            + (
                "in every mode\n"
                if not bad
                else f"EXCEPT {', '.join(bad)}\n"
            )
        )
    w("\n")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Render a bench.py --slo record (per-mode ttfv/"
        "verdict percentiles + decomposition + burn rates)."
    )
    parser.add_argument("record", help="BENCH_r18.json / /slo snapshot JSON")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the summary as one JSON object (machine-readable)",
    )
    args = parser.parse_args(argv)
    rec = load_record(args.record)
    if rec is None:
        print(
            f"{args.record}: no SLO record found (need a JSON line with "
            "an 'slo' block — run `python bench.py --slo`)",
            file=sys.stderr,
        )
        return 2
    summary = summarize(rec)
    if args.json:
        json.dump(summary, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        render(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
