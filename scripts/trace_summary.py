#!/usr/bin/env python
"""Per-wave table from a telemetry trace JSONL (stateright_tpu.telemetry).

    python scripts/trace_summary.py TRACE.jsonl [--chrome-out OUT.json]

Reads the JSONL sink a checker run produced (``--trace-out`` on bench.py,
or ``get_tracer().add_sink(path)`` on any run), prints one row per
wave/drain span — wall ms, frontier width, generated, new-unique, dedup
hit-rate, hash-set occupancy, and (out-of-core runs) the ``storage``
column as ``stale-dropped/tier-resident-fps`` — and totals. On
attribution-mode traces (``attribution=True`` runs emit ``.pipeline``
spans) an ``attribution`` table follows: one row per span group with the
per-phase ms share of wave wall (device/host_probe/evict/checkpoint/
compile/gap). On coverage-recording traces (``coverage=True`` device
runs; host engines always-on) a ``coverage`` table follows: cumulative
evaluated/terminal counts, action coverage with the dead-action tally,
revisit rate, and sometimes-witness counts per backend. Use
``scripts/storage_report.py`` for the tier-level view (evictions,
merges, spills, per-tier probe latency), ``scripts/gap_report.py`` for
the full phase ledger + overlap-headroom estimate, and
``scripts/coverage_report.py`` for the full cartography + the CI
vacuity gate. ``--chrome-out`` additionally writes the Chrome
trace-event export (load it in https://ui.perfetto.dev or
chrome://tracing).

Stdlib-only on the read path (json + argparse): trace files outlive the
runs that wrote them and must stay inspectable on boxes without jax.
"""

from __future__ import annotations

import argparse
import json
import sys

# Canonical phase order + async-overlappable host set for the script-side
# renderers (this file and gap_report.py, which imports them). Keep in
# sync with stateright_tpu.telemetry.attribution PHASES /
# HOST_OVERLAPPABLE_PHASES — the scripts cannot import the package
# because traces must stay inspectable on boxes without jax.
PHASE_ORDER = (
    "device", "wave_kernel", "host_probe", "evict", "table_grow",
    "checkpoint", "compile", "gap",
)
HOST_OVERLAPPABLE = ("host_probe", "evict", "checkpoint")
# Device-compute phase class: the staged wave chain ("device") and the
# fused Pallas megakernel's single dispatch ("wave_kernel").
DEVICE_PHASES = ("device", "wave_kernel")


def load_events(path):
    """Events from a JSONL trace; unparseable lines (a killed run tears
    the tail line; disk-full runs can tear any) are skipped with a
    stderr count, never fatal."""
    events = []
    skipped = 0
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(event, dict):
                skipped += 1
                continue
            events.append(event)
    if skipped:
        print(
            f"warning: skipped {skipped} unparseable line(s) in {path} "
            "(torn write from a killed run?)",
            file=sys.stderr,
        )
    return events


def wave_rows(events):
    """The per-wave/per-drain span rows, oldest first. Any complete span
    whose args carry a ``new_unique`` count qualifies — the shape every
    backend's wave-level span shares."""
    rows = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        if "new_unique" not in args:
            continue
        rows.append(
            {
                "name": ev.get("name", "?"),
                "ms": ev.get("dur", 0.0) / 1000.0,
                "frontier": args.get("frontier"),
                "generated": args.get("generated", 0),
                "new_unique": args.get("new_unique", 0),
                "dedup_pct": 100.0 * args.get("dedup_hit_rate", 0.0),
                "occupancy_pct": 100.0 * args.get("occupancy", 0.0),
                "waves": args.get("waves", 1),
                "bucket": args.get("bucket", ""),
                # Out-of-core runs: stale lanes the host tier probe
                # dropped this wave / fingerprints resident in L1+L2.
                "storage": (
                    f"{args['storage_stale']}/{args.get('storage_fps', 0)}"
                    if "storage_stale" in args
                    else ""
                ),
                "phase": args.get("phase", ""),
            }
        )
    return rows


def print_table(rows, out=sys.stdout):
    header = (
        f"{'#':>4} {'span':<18} {'ms':>9} {'waves':>5} {'frontier':>8} "
        f"{'bucket':>7} {'generated':>10} {'new':>9} {'dedup%':>7} "
        f"{'occ%':>6} {'storage':>13} phase"
    )
    out.write(header + "\n")
    out.write("-" * len(header) + "\n")
    for i, r in enumerate(rows, 1):
        out.write(
            f"{i:>4} {r['name']:<18} {r['ms']:>9.2f} {r['waves']:>5} "
            f"{str(r['frontier']):>8} {str(r['bucket']):>7} "
            f"{r['generated']:>10} "
            f"{r['new_unique']:>9} {r['dedup_pct']:>7.1f} "
            f"{r['occupancy_pct']:>6.1f} {r.get('storage', ''):>13} "
            f"{r['phase']}\n"
        )
    total_gen = sum(r["generated"] for r in rows)
    total_new = sum(r["new_unique"] for r in rows)
    total_ms = sum(r["ms"] for r in rows)
    dedup = 100.0 * (total_gen - total_new) / total_gen if total_gen else 0.0
    out.write(
        f"\ntotal: {len(rows)} spans, {total_ms:.1f} ms, "
        f"{total_gen} generated, {total_new} new unique "
        f"({dedup:.1f}% dedup)\n"
    )


def attribution_rows(events):
    """Per-span-group attribution aggregates from ``.pipeline`` spans
    (attribution-mode runs): waves, total wall ms, and per-phase ms."""
    groups = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        if not name.endswith(".pipeline"):
            continue
        args = ev.get("args") or {}
        if "wall_ms" not in args:
            continue
        g = groups.setdefault(
            name, {"waves": 0, "wall_ms": 0.0, "phases": {}}
        )
        g["waves"] += 1
        g["wall_ms"] += float(args["wall_ms"] or 0.0)
        for k, v in args.items():
            if k.endswith("_ms") and k != "wall_ms":
                phase = k[: -len("_ms")]
                g["phases"][phase] = g["phases"].get(phase, 0.0) + float(
                    v or 0.0
                )
    return groups


def print_attribution(groups, out=sys.stdout):
    """The attribution column per span group: each phase as
    ``ms (share%)`` of the group's summed wave wall."""
    out.write("\nattribution (per-phase ms share of wave wall):\n")
    header = (
        f"{'span group':<22} {'waves':>5} {'wall ms':>10}  attribution"
    )
    out.write(header + "\n")
    out.write("-" * len(header) + "\n")
    for name in sorted(groups):
        g = groups[name]
        wall = g["wall_ms"]
        phases = g["phases"]
        keys = [p for p in PHASE_ORDER if p in phases] + sorted(
            p for p in phases if p not in PHASE_ORDER
        )
        cells = " ".join(
            f"{p}={phases[p]:.1f}ms"
            f"({100.0 * phases[p] / wall:.0f}%)" if wall else f"{p}=0"
            for p in keys
        )
        out.write(
            f"{name:<22} {g['waves']:>5} {wall:>10.1f}  {cells}\n"
        )


def coverage_rows(events):
    """Per-prefix coverage aggregates from the cumulative ``.coverage``
    spans (coverage-mode device runs / always-on host engines): the LAST
    span per name wins — every span carries run-so-far totals."""
    rows = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        if not name.endswith(".coverage"):
            continue
        args = ev.get("args") or {}
        if "actions_fired" not in args:
            continue
        rows[name[: -len(".coverage")]] = dict(args)
    return rows


def print_coverage(rows, out=sys.stdout):
    """The coverage table: per prefix, evaluated/terminal counts, action
    coverage (dead actions flagged), revisit rate, and the
    sometimes-witness tally — the vacuity quick-look
    (``scripts/coverage_report.py`` renders the full cartography)."""
    out.write("\ncoverage (cumulative, per backend):\n")
    header = (
        f"{'prefix':<14} {'evaluated':>10} {'terminals':>9} "
        f"{'actions':>9} {'dead':>5} {'revisit%':>9} {'sometimes':>10}"
    )
    out.write(header + "\n")
    out.write("-" * len(header) + "\n")
    for prefix in sorted(rows):
        a = rows[prefix]
        total = a.get("actions_total")
        actions = (
            f"{a.get('actions_fired', 0)}/{total}"
            if total is not None
            else str(a.get("actions_fired", 0))
        )
        sometimes = (
            f"{a.get('sometimes_witnessed', 0)}/{a.get('sometimes_total', 0)}"
        )
        out.write(
            f"{prefix:<14} {a.get('evaluated', 0):>10} "
            f"{a.get('terminals', 0):>9} {actions:>9} "
            f"{str(a.get('dead_actions', '')):>5} "
            f"{100.0 * a.get('revisit_rate', 0.0):>9.1f} "
            f"{sometimes:>10}\n"
        )


def top_spans(events, n):
    """The n slowest complete spans, any name — where the wall time went
    (wave, drain, table_grow, storage evict/merge/probe alike)."""
    spans = [
        ev for ev in events
        if ev.get("ph") == "X" and isinstance(ev.get("dur"), (int, float))
    ]
    return sorted(spans, key=lambda ev: -ev["dur"])[:n]


def print_top(spans, out=sys.stdout):
    header = f"{'#':>4} {'span':<26} {'ms':>10}  args"
    out.write(header + "\n")
    out.write("-" * len(header) + "\n")
    for i, ev in enumerate(spans, 1):
        args = ev.get("args") or {}
        brief = " ".join(
            f"{k}={args[k]}" for k in list(args)[:4]
        )
        out.write(
            f"{i:>4} {ev.get('name', '?'):<26} "
            f"{ev['dur'] / 1000.0:>10.2f}  {brief}\n"
        )


def _positive_int(value):
    n = int(value)
    if n <= 0:
        raise argparse.ArgumentTypeError(f"expected N > 0, got {value}")
    return n


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Per-wave table from a telemetry trace JSONL."
    )
    parser.add_argument("trace", help="JSONL trace file (telemetry sink)")
    parser.add_argument(
        "--chrome-out",
        help="also write Chrome trace-event JSON (Perfetto-loadable)",
    )
    parser.add_argument(
        "--top", type=_positive_int, metavar="N",
        help="also print the N slowest spans of any kind",
    )
    args = parser.parse_args(argv)

    events = load_events(args.trace)
    if not events:
        print(f"no events in {args.trace}", file=sys.stderr)
        return 1
    rows = wave_rows(events)
    if rows:
        print_table(rows)
    else:
        print(
            f"{len(events)} events, none with per-wave args "
            "(host block/trace spans only)",
        )
    attribution = attribution_rows(events)
    if attribution:
        print_attribution(attribution)
    coverage = coverage_rows(events)
    if coverage:
        print_coverage(coverage)
    if args.top:
        print()
        print_top(top_spans(events, args.top))
    if args.chrome_out:
        with open(args.chrome_out, "w") as f:
            json.dump(
                {"traceEvents": events, "displayTimeUnit": "ms"}, f
            )
        print(f"chrome trace written to {args.chrome_out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
