"""Import FIRST in any ad-hoc script meant to run on the CPU backend.

This image's sitecustomize registers a tunneled ``axon`` TPU backend and
forces ``jax_platforms=axon,cpu`` through ``jax.config`` — which OVERRIDES
the ``JAX_PLATFORMS`` env var, so ``JAX_PLATFORMS=cpu python script.py``
still dispatches (and hangs) through a wedged tunnel. Re-pinning must go
through the config, after importing jax::

    import scripts.cpu_pin  # noqa: F401  (must be the first import)

Mirrors tests/conftest.py and bench.py's ``--cpu`` leg pinning.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stateright_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_cache,
)

enable_persistent_cache()
