#!/bin/bash
# Priority-ordered device bench: fired the moment a tunnel probe succeeds
# (by scripts/tpu_sentinel.sh, or by hand). Runs each leg as its own
# process (a wedged tunnel costs one leg, not the run), in VERDICT-r03
# priority order: 2pc headline first, then the never-measured north-star
# paxos3, then the rest. Legs that already produced a device result in
# the output file are skipped, so re-firing is idempotent. A lockfile
# serializes concurrent firings — the chip is single-tenant and a second
# claimant wedges both.
OUT=${1:-/root/repo/DEVICE_RUNS.jsonl}
RUNTIME=/root/repo/.runtime
mkdir -p -m 700 "$RUNTIME"
LOCK="$RUNTIME/device_bench_run.lock"
cd /root/repo

if ! mkdir "$LOCK" 2>/dev/null; then
  echo "device_bench_run: another run holds $LOCK; exiting" >&2
  exit 0
fi
trap 'rmdir "$LOCK"' EXIT

# smoke FIRST (VERDICT r04 #1a): 2pc-5, 8,832 states, completes in
# seconds warm — banks a `"device": "tpu"` line before the ~25-minute
# headline leg gets a chance to ride a short window into a wedge.
for spec in "smoke:180:--no-host-baseline" "2pc:1500:--no-host-baseline" \
            "paxos3:1500:" "abd3o:900:" \
            "paxos:900:" "ilock:600:" "raft5:900:" "scr4:3600:"; do
  leg=${spec%%:*}; rest=${spec#*:}; t=${rest%%:*}; extra=${rest#*:}
  if grep "\"leg\": \"$leg\"" "$OUT" 2>/dev/null | grep -q '"device": "tpu"'; then
    echo "=== leg $leg already has a tpu result; skipping ===" >&2
    continue
  fi
  echo "=== leg $leg (timeout ${t}s) $(date -u +%FT%TZ) ===" >&2
  line=$(timeout "$t" python bench.py --leg "$leg" $extra 2>>"${OUT%.jsonl}.err" | tail -1)
  if [ -n "$line" ]; then
    echo "{\"leg\": \"$leg\", \"ts\": \"$(date -u +%FT%TZ)\", \"result\": $line}" >> "$OUT"
  else
    # Wedged mid-leg: salvage the progress sidecar (bench.py writes it
    # every 2s) so the round records a partial rate, not `result: null`
    # (VERDICT r04 #1c). Keyed "partial_leg", NOT "leg": the skip check
    # above (and the sentinel's have_tpu_result) grep for `"leg": X` +
    # `"device": "tpu"` on one line, and a salvaged partial must never
    # masquerade as a completed device result and disable retries. The
    # sidecar is consumed (rm) so it can't be re-salvaged by a later leg.
    partial=$(cat "$RUNTIME/leg_$leg.progress.json" 2>/dev/null)
    rm -f "$RUNTIME/leg_$leg.progress.json"
    if [ -n "$partial" ]; then
      echo "{\"partial_leg\": \"$leg\", \"ts\": \"$(date -u +%FT%TZ)\", \"result\": null, \"progress\": $partial}" >> "$OUT"
    else
      echo "{\"leg\": \"$leg\", \"ts\": \"$(date -u +%FT%TZ)\", \"result\": null}" >> "$OUT"
    fi
  fi
done
# Device-side stage attribution for the headline + predicate-heavy legs
# (bench.py --breakdown): compiled stage jits on the real chip.
for leg in 2pc abd3o paxos3; do
  if grep "\"breakdown\": \"$leg\"" "$OUT" 2>/dev/null | grep -q '"device": "tpu"'; then
    continue
  fi
  echo "=== breakdown $leg $(date -u +%FT%TZ) ===" >&2
  line=$(timeout 600 python bench.py --breakdown "$leg" 2>>"${OUT%.jsonl}.err" | tail -1)
  [ -n "$line" ] && echo "{\"breakdown\": \"$leg\", \"ts\": \"$(date -u +%FT%TZ)\", \"result\": $line}" >> "$OUT"
done
# Dedup-mode A/B on the chip: the scatter insert beats the sorted path
# 2.3x on CPU; whether TPU HBM prefers the sort's sequential probes is
# an open measurement — recorded as its own entry.
if grep '"leg": "2pc"' "$OUT" 2>/dev/null | grep -q '"device": "tpu"'; then
  if ! grep '"ab": "2pc-scatter"' "$OUT" 2>/dev/null | grep -q '"device": "tpu"'; then
    echo "=== 2pc scatter-dedup A/B $(date -u +%FT%TZ) ===" >&2
    line=$(timeout 900 python bench.py --leg 2pc --no-host-baseline --dedup scatter \
           2>>"${OUT%.jsonl}.err" | tail -1)
    [ -n "$line" ] && echo "{\"ab\": \"2pc-scatter\", \"ts\": \"$(date -u +%FT%TZ)\", \"result\": $line}" >> "$OUT"
  fi
fi
# Pallas-vs-XLA insert flip-test, COMPILED on the chip (VERDICT r03 #4):
# decides the checkers' hashset_impl default per backend.
if ! grep '"flip_test"' "$OUT" 2>/dev/null | grep -q '"device": "tpu"'; then
  echo "=== hashset flip-test $(date -u +%FT%TZ) ===" >&2
  line=$(timeout 600 python -m stateright_tpu.ops.bench_hashset 20 32768 --json \
         2>>"${OUT%.jsonl}.err" | tail -1)
  [ -n "$line" ] && echo "{\"flip_test\": true, \"ts\": \"$(date -u +%FT%TZ)\", \"result\": $line}" >> "$OUT"
fi
echo "=== device bench run complete $(date -u +%FT%TZ) ===" >&2
