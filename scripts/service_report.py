#!/usr/bin/env python
"""Per-job latency/throughput/preemption report from a service bench
record.

    python scripts/service_report.py BENCH_r10.json [--json]

Reads the JSON line ``bench.py --service`` prints (saved as
``BENCH_r*.json``, or the raw ``--service-leg`` record) and renders the
checking-as-a-service numbers: batch-vs-service throughput, the
concurrent-load aggregate, the p50/p99 time-to-first-violation
latencies, and the per-job table (ttfv, wall, queued, preempts, slices,
compile seconds — zero compile == the job rode the shared AOT cache).

``--json`` emits the summary as one JSON object instead of the tables
(machine-readable; the tests consume it) — the convention shared by
``gap_report.py`` / ``storage_report.py`` / ``coverage_report.py``.
Stdlib-only, like every report reader here: bench records outlive the
runs that wrote them.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_record(path):
    """The service record from a bench JSON file: the last parseable
    JSON line containing ``per_job`` (files may carry stderr noise or a
    wrapper line ahead of the record)."""
    record = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "per_job" in obj:
                record = obj
            elif isinstance(obj, dict) and "per_job" in (
                obj.get("service") or {}
            ):
                record = obj["service"]
    return record


def summarize(rec):
    per_job = rec.get("per_job") or []
    return {
        "model": rec.get("model"),
        "device": rec.get("device"),
        "jobs": rec.get("jobs", len(per_job)),
        "quantum_s": rec.get("quantum_s"),
        # Tenant-packed records (BENCH_r12+): scheduling mode + the
        # lane-occupancy evidence; absent/False on time-sliced records.
        "packed": rec.get("packed", False),
        "pack": rec.get("pack"),
        "aggregate_vs_single_pct": rec.get("aggregate_vs_single_pct"),
        "batch_rate": rec.get("batch_rate"),
        "single_job_rate": rec.get("single_job_rate"),
        "service_overhead_pct": rec.get("service_overhead_pct"),
        "aggregate_states_per_s": rec.get("aggregate_states_per_s"),
        "concurrent_wall_s": rec.get("concurrent_wall_s"),
        "p50_ttfv_s": rec.get("p50_ttfv_s"),
        "p99_ttfv_s": rec.get("p99_ttfv_s"),
        "preempts_total": rec.get("preempts_total"),
        # Fault-tolerance columns (PR 13): zero on healthy runs, the
        # recovery evidence on chaos legs.
        "retries_total": rec.get(
            "retries_total",
            sum(j.get("retries", 0) for j in per_job),
        ),
        "faults_total": rec.get(
            "faults_total",
            sum(j.get("faults", 0) for j in per_job),
        ),
        "jobs_quarantined": sum(
            1 for j in per_job if j.get("quarantined")
        ),
        "jobs_zero_compile": rec.get("jobs_zero_compile"),
        # Liveness honesty (device-liveness PR): how each job's
        # `eventually` verdicts were produced, and downgrades.
        "liveness_modes": {
            mode: sum(
                1 for j in per_job if j.get("liveness_mode") == mode
            )
            for mode in ("device", "host_pass", "default")
            if any(j.get("liveness_mode") == mode for j in per_job)
        },
        "liveness_downgraded": sum(
            1 for j in per_job if j.get("liveness_reason")
        ),
        # Verification modes (swarm PR; conformance PR): exhaustive BFS,
        # randomized-walk, and trace-replay/audit jobs sharing the one
        # device.
        "modes": {
            mode: sum(
                1
                for j in per_job
                if j.get("mode", "exhaustive") == mode
            )
            for mode in ("exhaustive", "swarm", "conformance")
            if any(
                j.get("mode", "exhaustive") == mode for j in per_job
            )
        },
        # Warm-start plane (BENCH_r19+): seeded jobs, disk-AOT hit
        # evidence, and the warm/cold ttfv sub-leg; absent on older
        # records.
        "jobs_warm_started": sum(
            1 for j in per_job if j.get("warm_start")
        ),
        "aot_disk_hits": sum(
            (j.get("aot") or {}).get("aot_cache.disk_hit", 0)
            for j in per_job
        ),
        "aot_disk_misses": sum(
            (j.get("aot") or {}).get("aot_cache.disk_miss", 0)
            for j in per_job
        ),
        "aot_refused_stale": sum(
            (j.get("aot") or {}).get("aot_cache.refused_stale", 0)
            + (j.get("aot") or {}).get("aot_cache.refused_corrupt", 0)
            for j in per_job
        ),
        "warmstart": rec.get("warmstart"),
        "per_job": per_job,
        # SLO ledger (BENCH_r18+ / any record carrying a GET /slo
        # snapshot): rendered as its own table; absent on older records.
        "slo": rec.get("slo"),
    }


def _fmt(v, spec="{:,.1f}", none="-"):
    if v is None:
        return none
    return spec.format(v)


def render(summary, out=sys.stdout):
    w = out.write
    mode = "tenant-packed" if summary.get("packed") else "time-sliced"
    w(
        f"service bench: {summary['jobs']} concurrent "
        f"{summary['model']} jobs on {summary['device']} "
        f"({mode}, quantum {summary['quantum_s']}s)\n\n"
    )
    w("  throughput (unique states/s)\n")
    w(f"    batch path        {_fmt(summary['batch_rate'])}\n")
    w(
        f"    service, 1 job    {_fmt(summary['single_job_rate'])}"
        f"  ({_fmt(summary['service_overhead_pct'], '{:+.1f}')}% overhead)\n"
    )
    vs_single = ""
    if summary.get("aggregate_vs_single_pct") is not None:
        vs_single = (
            f"  ({_fmt(summary['aggregate_vs_single_pct'], '{:+.1f}')}% "
            "vs single job)"
        )
    w(
        f"    service, {summary['jobs']} jobs   "
        f"{_fmt(summary['aggregate_states_per_s'])}  aggregate over "
        f"{_fmt(summary['concurrent_wall_s'], '{:.1f}')}s{vs_single}\n\n"
    )
    pack = summary.get("pack")
    if pack:
        w(
            f"  packing: {pack.get('packed_jobs', '?')}/{summary['jobs']} "
            f"jobs co-scheduled over {pack.get('waves', '?')} shared "
            f"waves, lane fill "
            f"{_fmt(pack.get('lane_fill'), '{:.2f}')} "
            f"({pack.get('lanes_live', 0):,} live / "
            f"{pack.get('lanes_dispatched', 0):,} dispatched)\n\n"
        )
    w("  latency (submit -> first violation/witness)\n")
    w(f"    p50  {_fmt(summary['p50_ttfv_s'], '{:.3f}')}s\n")
    w(f"    p99  {_fmt(summary['p99_ttfv_s'], '{:.3f}')}s\n\n")
    w(
        f"  scheduling: {summary['preempts_total']} preemptions; "
        f"{summary['jobs_zero_compile']}/{summary['jobs']} jobs "
        "compile-free (shared AOT cache)\n"
    )
    w(
        f"  fault tolerance: {summary['faults_total'] or 0} faults, "
        f"{summary['retries_total'] or 0} retries, "
        f"{summary['jobs_quarantined']} quarantined\n"
    )
    if (
        summary.get("jobs_warm_started")
        or summary.get("aot_disk_hits")
        or summary.get("aot_disk_misses")
    ):
        refused = summary.get("aot_refused_stale") or 0
        w(
            f"  warm start: {summary.get('jobs_warm_started', 0)}/"
            f"{summary['jobs']} jobs seeded; disk AOT "
            f"{summary.get('aot_disk_hits', 0)} hits / "
            f"{summary.get('aot_disk_misses', 0)} misses"
            + (f", {refused} refused (stale/corrupt)" if refused else "")
            + "\n"
        )
    ws = summary.get("warmstart")
    if ws:
        w(
            f"  warm vs cold process: warm ttfv "
            f"{_fmt(ws.get('warm_ttfv_s'), '{:.3f}')}s, cold ttfv "
            f"{_fmt(ws.get('cold_ttfv_s'), '{:.3f}')}s "
            f"({_fmt(ws.get('cold_over_warm_pct'), '{:+.1f}')}% cold "
            "over warm)\n"
        )
    vmodes = summary.get("modes") or {}
    if len(vmodes) > 1 or "swarm" in vmodes or "conformance" in vmodes:
        w(
            "  modes: "
            + ", ".join(f"{n} {m}" for m, n in sorted(vmodes.items()))
            + "\n"
        )
    modes = summary.get("liveness_modes") or {}
    if modes:
        rendered = ", ".join(f"{n} {m}" for m, n in modes.items())
        downgraded = summary.get("liveness_downgraded") or 0
        w(
            f"  liveness: {rendered}"
            + (f"; {downgraded} downgraded" if downgraded else "")
            + "\n"
        )
    w("\n")
    header = (
        f"  {'job':<10} {'tenant':<10} {'ttfv_s':>8} {'wall_s':>8} "
        f"{'queued_s':>9} {'rate':>10} {'preempts':>8} {'slices':>6} "
        f"{'packed':>6} {'faults':>6} {'retries':>7} {'compile_s':>9} "
        f"{'warm':>5}\n"
    )
    w(header)
    w("  " + "-" * (len(header) - 3) + "\n")
    for j in summary["per_job"]:
        aot = j.get("aot") or {}
        warm = (
            "seed"
            if j.get("warm_start")
            else ("disk" if aot.get("aot_cache.disk_hit") else "-")
        )
        w(
            f"  {j.get('job_id', '?'):<10} {str(j.get('tenant', '')):<10} "
            f"{_fmt(j.get('ttfv_s'), '{:.3f}'):>8} "
            f"{_fmt(j.get('wall_s'), '{:.2f}'):>8} "
            f"{_fmt(j.get('queued_s'), '{:.3f}'):>9} "
            f"{_fmt(j.get('rate')):>10} "
            f"{j.get('preempts', 0):>8} {j.get('slices', 0):>6} "
            f"{str(bool(j.get('packed', False))):>6} "
            f"{j.get('faults', 0):>6} {j.get('retries', 0):>7} "
            f"{_fmt(j.get('compile_s'), '{:.2f}'):>9} "
            f"{warm:>5}\n"
        )


def print_slo(slo, out=sys.stdout):
    """The per-mode SLO table (records carrying a ``GET /slo``
    snapshot — ``service/slo.py``); a compact sibling of
    ``slo_report.py``'s full rendering."""
    w = out.write
    modes = {
        m: v
        for m, v in (slo.get("modes") or {}).items()
        if (v.get("jobs") or 0) > 0
    }
    if not modes:
        return
    targets = slo.get("targets") or {}
    tgt = (
        " (targets: "
        + ", ".join(f"{k} <= {v}s" for k, v in sorted(targets.items()))
        + ")"
        if targets
        else ""
    )
    w(f"\n  slo ledger{tgt}\n")
    header = (
        f"  {'mode':<12} {'jobs':>5} {'ttfv p50':>9} {'ttfv p99':>9} "
        f"{'queue p50':>10} {'compile p50':>12} {'compile p99':>12} "
        f"{'explore p50':>12} {'compile-free':>13}\n"
    )
    w(header)
    w("  " + "-" * (len(header) - 3) + "\n")
    for mode, view in modes.items():
        d = view.get("decomposition") or {}
        comp = view.get("compile") or {}
        w(
            f"  {mode:<12} {view.get('jobs', 0):>5} "
            f"{_fmt(view['ttfv'].get('p50_s'), '{:.3f}'):>9} "
            f"{_fmt(view['ttfv'].get('p99_s'), '{:.3f}'):>9} "
            f"{_fmt((d.get('queue_s') or {}).get('p50_s'), '{:.3f}'):>10} "
            f"{_fmt(comp.get('p50_s'), '{:.3f}'):>12} "
            f"{_fmt(comp.get('p99_s'), '{:.3f}'):>12} "
            f"{_fmt((d.get('explore_s') or {}).get('p50_s'), '{:.3f}'):>12} "
            f"{_fmt(comp.get('free_fraction'), '{:.0%}'):>13}\n"
        )
        burn = view.get("burn_rate")
        if burn:
            rendered = ", ".join(
                f"{k} {v:.2f}x" for k, v in sorted(burn.items())
            )
            w(f"    burn rate: {rendered} (1.0 = at budget)\n")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Render a bench.py --service record "
        "(latency/throughput/preemption per job)."
    )
    parser.add_argument("record", help="BENCH_r*.json / service leg JSON")
    parser.add_argument(
        "--json", action="store_true",
        help="emit the summary as one JSON object (machine-readable)",
    )
    args = parser.parse_args(argv)
    rec = load_record(args.record)
    if rec is None:
        print(
            f"{args.record}: no service record found (need a JSON line "
            "with 'per_job' — run `python bench.py --service`)",
            file=sys.stderr,
        )
        return 2
    summary = summarize(rec)
    if args.json:
        # One JSON object on stdout — the shared machine-readable
        # convention (gap_report.py --json, storage_report.py --json).
        json.dump(summary, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        render(summary)
        if summary.get("slo"):
            print_slo(summary["slo"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
