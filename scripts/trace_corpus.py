#!/usr/bin/env python
"""Generate a labeled conformance corpus as wire-format JSONL.

    python scripts/trace_corpus.py --seed 7 --out corpus.jsonl
    python scripts/trace_corpus.py --store ./svc/corpus --name nightly

Drives ``conformance/corpus.py``: seeded random-walk traces (clean by
construction) plus mutated divergent twins per zoo model, and
clean/random/invalid histories per (spec, semantics, threads, ops)
shape. Every record carries its ground-truth label in ``meta`` —
``expect`` and, for divergent traces, the exact ``divergence_index`` /
``offending_action`` — which the parity suite and the tier-1 smoke read
back against the device verdicts.

``--store/--name`` saves into a service's named ``CorpusStore``
(``service_dir/corpus``) so a running server can audit it by name over
HTTP: ``POST /jobs {"mode": "conformance", "corpus": "nightly"}``.
Deterministic: same seed + options -> byte-identical corpus.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(
    0, __file__.rsplit("/", 2)[0]
)  # repo root, when run as a script

from stateright_tpu.conformance import encode_record, generate_corpus
from stateright_tpu.service.zoo import default_zoo

DEFAULT_MODELS = ("increment_lock", "2pc")

DEFAULT_HISTORY_SHAPES = (
    ("register", "linearizability", 2, 2),
    ("register", "sequential", 2, 2),
    ("register", "linearizability", 3, 2),
    ("vec", "linearizability", 2, 2),
)


def build_lines(args) -> list:
    zoo = default_zoo()
    specs = []
    for name in args.models:
        if name not in zoo:
            raise SystemExit(
                f"unknown model {name!r}; zoo: {sorted(zoo)}"
            )
        specs.append((name, {}, zoo[name]()))
    records = generate_corpus(
        args.seed,
        model_specs=specs,
        traces_per_model=args.traces_per_model,
        mutated_per_model=args.mutated_per_model,
        trace_steps=args.trace_steps,
        histories=args.histories,
        history_shapes=DEFAULT_HISTORY_SHAPES,
    )
    return [encode_record(r) for r in records]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Generate a labeled conformance corpus "
        "(wire-format JSONL)."
    )
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument(
        "--models", nargs="+", default=list(DEFAULT_MODELS),
        help="zoo models to draw traces from",
    )
    parser.add_argument("--traces-per-model", type=int, default=4)
    parser.add_argument("--mutated-per-model", type=int, default=2)
    parser.add_argument("--trace-steps", type=int, default=12)
    parser.add_argument("--histories", type=int, default=24)
    parser.add_argument(
        "--out", default=None,
        help="write JSONL here ('-' = stdout; default stdout)",
    )
    parser.add_argument(
        "--store", default=None,
        help="save into a CorpusStore root (a service_dir/corpus)",
    )
    parser.add_argument(
        "--name", default=None,
        help="corpus name inside --store (a name, never a path)",
    )
    args = parser.parse_args(argv)
    if (args.store is None) != (args.name is None):
        parser.error("--store and --name go together")
    lines = build_lines(args)
    if args.store is not None:
        from stateright_tpu.storage.corpus import CorpusStore

        path = CorpusStore(args.store).save(args.name, lines)
        print(
            f"saved {len(lines)} records as corpus {args.name!r} "
            f"({path})",
            file=sys.stderr,
        )
    if args.out is not None and args.out != "-":
        with open(args.out, "w", encoding="utf-8") as f:
            for line in lines:
                f.write(line + "\n")
        print(
            f"wrote {len(lines)} records to {args.out}", file=sys.stderr
        )
    elif args.store is None or args.out == "-":
        for line in lines:
            print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
