"""The checking service: scheduling, preemptive multiplexing, the shared
AOT cache, per-run telemetry scoping, and the HTTP front-end."""

import io
import json
import re
import time
import urllib.error
import urllib.request

import pytest

from stateright_tpu import WriteReporter
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.service import CheckService, ServiceServer
from stateright_tpu.telemetry import metrics_registry

# Shapes shared with the rest of the suite, so the persistent compile
# cache keeps these tests cheap.
SPAWN_2PC = {
    "frontier_capacity": 16,
    "table_capacity": 1 << 12,
    "max_drain_waves": 2,
    # One shared AOT namespace for the module (the signature separates
    # the 2pc-3 and 2pc-4 configurations): incarnations never re-trace.
    "aot_cache": "t-svc",
}
UNIQUE_2PC3 = 288
UNIQUE_2PC4 = 1568


def _golden(checker_or_text):
    if isinstance(checker_or_text, str):
        text = checker_or_text
    else:
        out = io.StringIO()
        checker_or_text.report(WriteReporter(out))
        text = out.getvalue()
    return re.sub(r"sec=\d+", "sec=_", text)


@pytest.fixture
def service():
    # The quantum must exceed the resume overhead (respawn + restore,
    # ~1s cold on this CPU backend) or slices make no progress and the
    # tests churn; the service default (1.0s) reflects the same rule.
    svc = CheckService(quantum_s=0.75, default_spawn=dict(SPAWN_2PC))
    yield svc
    svc.close()


def test_single_job_full_verdict(service):
    handle = service.submit(model_name="2pc", model_args={"rm_count": 3})
    result = handle.result(timeout=180)
    assert result["unique"] == UNIQUE_2PC3
    assert result["properties_hold"] is True
    assert "Done." in result["report"]
    assert set(result["discoveries"]) == {
        "abort agreement", "commit agreement",
    }
    status = handle.status()
    assert status["state"] == "done"
    lat = status["latency"]
    # The latency fields the bench and the HTTP API surface.
    assert lat["wall_s"] is not None and lat["wall_s"] > 0
    assert lat["queued_s"] is not None
    assert lat["ttfv_s"] is not None  # 2pc's sometimes props discover


def test_concurrent_jobs_preempt_and_stay_exact():
    """The TIME-SLICE path (packing disabled — PR 12's packer would
    co-schedule these): two equal-priority contending jobs round-robin
    the device at wave granularity; both verdicts match the batch path
    exactly and their golden reports agree with each other (identical
    workload). Packed co-scheduling of the same pair is covered by
    tests/test_packed_tenancy.py."""
    svc = CheckService(
        quantum_s=0.75, default_spawn=dict(SPAWN_2PC), packing=False
    )
    try:
        h1 = svc.submit(model_name="2pc", model_args={"rm_count": 4})
        h2 = svc.submit(model_name="2pc", model_args={"rm_count": 4})
        r1 = h1.result(timeout=300)
        r2 = h2.result(timeout=300)
        assert r1["unique"] == UNIQUE_2PC4
        assert r2["unique"] == UNIQUE_2PC4
        assert _golden(r1["report"]) == _golden(r2["report"])
        # Contention existed, so at least one job was preempted mid-run —
        # and its result is still exact (the bit-identical guarantee under
        # real scheduling, not just the direct-API test).
        assert h1.status()["preempts"] + h2.status()["preempts"] >= 1
    finally:
        svc.close()


def test_high_priority_job_overtakes_running_low():
    """A high-priority arrival preempts the running low-priority job at
    its next quantum and completes first. Dedicated short-quantum
    service: with a warm AOT cache a 2pc-4 job can finish inside the
    fixture's 0.75s quantum, and a job that completes its first slice
    is (correctly) never preempted."""
    svc = CheckService(quantum_s=0.15, default_spawn=dict(SPAWN_2PC))
    try:
        low = svc.submit(model_name="2pc", model_args={"rm_count": 4})
        deadline = time.monotonic() + 60
        while (
            svc.job(low.job_id).state == "queued"
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        high = svc.submit(
            model_name="2pc", model_args={"rm_count": 3}, priority=5
        )
        assert high.result(timeout=180)["unique"] == UNIQUE_2PC3
        assert svc.job(low.job_id).finished_t is None or (
            svc.job(high.job_id).finished_t
            <= svc.job(low.job_id).finished_t
        )
        assert low.result(timeout=300)["unique"] == UNIQUE_2PC4
    finally:
        svc.close()


def test_second_job_shares_aot_cache_zero_compiles(service):
    """The acceptance criterion: two jobs of the same wave shape share
    the AOT rung cache — the later job's attribution ledger records
    ZERO compile phases (in-wave and outside-wave both)."""
    from stateright_tpu.checker.tpu import clear_shared_aot_caches

    # The cache is process-global (that's the point — it outlives
    # service instances); clear it so THIS test's first job provably
    # pays the compiles its second job then skips.
    clear_shared_aot_caches()
    h1 = service.submit(
        model_name="2pc", model_args={"rm_count": 3},
        spawn={"attribution": True},
    )
    h1.result(timeout=180)
    h2 = service.submit(
        model_name="2pc", model_args={"rm_count": 3},
        spawn={"attribution": True},
    )
    r2 = h2.result(timeout=180)
    attr = r2["attribution"]
    assert attr["phases_s"].get("compile", 0.0) == 0.0
    assert (attr.get("outside_wave_s") or {}).get("compile", 0.0) == 0.0
    # compile_s_total spans every incarnation via the run registry — the
    # honest cross-preemption evidence bench.py counts.
    assert r2["compile_s_total"] == 0.0
    # The first job did compile (it built the cache the second one rode).
    r1 = h1.status()["result"]
    a1 = r1["attribution"]
    compiled = a1["phases_s"].get("compile", 0.0) + (
        a1.get("outside_wave_s") or {}
    ).get("compile", 0.0)
    assert compiled > 0.0
    assert r1["compile_s_total"] > 0.0
    assert r1["unique"] == r2["unique"] == UNIQUE_2PC3


def test_zoo_aliases_share_one_aot_namespace(service):
    """"2pc" and "two_phase_commit" are the same factory; their jobs
    must land in one AOT namespace (aliases never recompile)."""
    ns = []
    for name in ("2pc", "two_phase_commit"):
        h = service.submit(model_name=name, model_args={"rm_count": 3})
        ns.append(service.job(h.job_id).aot_namespace)
        h.cancel()
    assert ns[0] == ns[1]


def test_cancel_running_job(service):
    victim = service.submit(model_name="2pc", model_args={"rm_count": 4})
    # Let it actually start, then cancel mid-run.
    deadline = time.monotonic() + 60
    while (
        service.job(victim.job_id).state == "queued"
        and time.monotonic() < deadline
    ):
        time.sleep(0.005)
    assert victim.cancel() is True
    with pytest.raises(RuntimeError, match="cancelled"):
        victim.result(timeout=120)
    assert victim.status()["state"] == "cancelled"
    # The device frees up for the next tenant.
    after = service.submit(model_name="2pc", model_args={"rm_count": 3})
    assert after.result(timeout=180)["unique"] == UNIQUE_2PC3


def test_per_tenant_hbm_budget(service):
    """A tenant's hbm_budget_mib flows to the tiered store: the job
    completes exactly despite forced L0 evictions, and its own (run-
    scoped) registry records them."""
    import math

    actions = TwoPhaseSys(4).packed_action_count()
    rows = 1 << math.ceil(math.log2(16 * actions / 0.55 + 1))
    budget = ((rows + 128) * 8) / (1 << 20)
    handle = service.submit(
        model_name="2pc", model_args={"rm_count": 4},
        hbm_budget_mib=budget, tenant="small-tenant",
    )
    result = handle.result(timeout=300)
    assert result["unique"] == UNIQUE_2PC4
    job = service.job(handle.job_id)
    snap = metrics_registry(job.run_id).snapshot()
    assert snap.get("tpu_bfs.storage.evictions", 0) >= 1


def test_submit_validation(service):
    with pytest.raises(ValueError, match="unknown model"):
        service.submit(model_name="nope")
    with pytest.raises(ValueError, match="model_name"):
        service.submit()
    with pytest.raises(ValueError, match="unknown options"):
        service.submit(model_name="2pc", options={"bogus": 1})
    # Scheduling inputs are coerced at submit time: a string deadline
    # from an HTTP body must be rejected HERE, not TypeError the
    # scheduler thread mid-sort (which would hang every job).
    with pytest.raises(ValueError, match="deadline_s"):
        service.submit(model_name="2pc", deadline_s="soon")
    with pytest.raises(ValueError, match="hbm_budget_mib"):
        service.submit(model_name="2pc", hbm_budget_mib="lots")


def test_quantum_preempts_only_when_peer_would_be_picked():
    """The quantum-expiry guard compares real reschedule order: a
    finite-deadline job keeps the device over a deadline-less peer (it
    would be re-picked anyway — preempting is pure churn), while an
    earlier-deadline or higher-priority peer does preempt."""
    from stateright_tpu.service.jobs import CheckJob

    svc = CheckService(quantum_s=0.1)
    try:
        def add(jid, seq, **kw):
            job = CheckJob(jid, lambda: None, seq=seq, **kw)
            svc._jobs[jid] = job
            return job

        edf = add("edf", 0, deadline_s=60.0)
        edf.state = "running"
        plain = add("plain", 1)
        # plain would NOT be picked over edf's re-entry -> no preempt.
        assert svc._should_preempt_for_peer(edf) is False
        # An earlier-deadline peer would be picked -> preempt.
        add("sooner", 2, deadline_s=1.0)
        assert svc._should_preempt_for_peer(edf) is True
        # A higher-priority peer preempts a deadline-less runner; a
        # lower-priority one never does.
        plain.state = "running"
        del svc._jobs["edf"], svc._jobs["sooner"]
        add("low", 3, priority=-1)
        assert svc._should_preempt_for_peer(plain) is False
        add("high", 4, priority=1)
        assert svc._should_preempt_for_peer(plain) is True
    finally:
        svc.close()


def test_finished_job_retention(service):
    """Terminal jobs (and their run registries) beyond the cap are
    evicted oldest-first; live handles keep answering."""
    from stateright_tpu.telemetry.metrics import run_registries

    service.max_finished_jobs = 1
    h1 = service.submit(model_name="2pc", model_args={"rm_count": 3})
    r1 = h1.result(timeout=180)
    h2 = service.submit(model_name="2pc", model_args={"rm_count": 3})
    h2.result(timeout=180)
    deadline = time.monotonic() + 10
    while service.job(h1.job_id) is not None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert service.job(h1.job_id) is None, "oldest finished job evicted"
    assert service.job(h2.job_id) is not None
    assert h1.job_id not in run_registries(), "registry discarded"
    # The handle still works — it holds the job, not the index entry.
    assert h1.status()["state"] == "done"
    assert r1["unique"] == UNIQUE_2PC3


# -- per-run telemetry scoping (the namespacing satellite) -------------------


def test_run_scoped_registries_do_not_collide():
    """Two checkers in one process with distinct run_ids each count
    their own waves/uniques; the default registry sees neither."""
    base = metrics_registry().snapshot().get("tpu_bfs.states_unique", 0)
    a = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(run_id="iso-a", **SPAWN_2PC)
        .join()
    )
    b = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(run_id="iso-b", **SPAWN_2PC)
        .join()
    )
    snap_a = metrics_registry("iso-a").snapshot()
    snap_b = metrics_registry("iso-b").snapshot()
    assert snap_a["tpu_bfs.states_unique"] == UNIQUE_2PC3
    assert snap_b["tpu_bfs.states_unique"] == UNIQUE_2PC4
    assert a.metrics() is metrics_registry("iso-a")
    assert b.metrics() is metrics_registry("iso-b")
    after = metrics_registry().snapshot().get("tpu_bfs.states_unique", 0)
    assert after == base, "run-scoped checkers must not touch the default"


def test_monitor_core_run_filter():
    """MonitorCore(run_filter=...) selects one run's wave stream; the
    unfiltered core aggregates every run."""
    from stateright_tpu.telemetry.server import MonitorCore

    selected = MonitorCore(run_filter="run-a", registry=metrics_registry("mcrf"))
    aggregate = MonitorCore(registry=metrics_registry("mcrf2"))
    try:
        for run, n_new in (("run-a", 5), ("run-b", 7)):
            event = {
                "ph": "X", "name": "tpu_bfs.wave", "dur": 1000.0,
                "args": {"new_unique": n_new, "generated": n_new,
                         "run_id": run},
            }
            selected.write_event(event)
            aggregate.write_event(event)
        assert selected.estimator.unique_total == 5
        assert aggregate.estimator.unique_total == 12
    finally:
        selected.close()
        aggregate.close()


def test_run_scoped_tracer_stamps_spans():
    from stateright_tpu.telemetry import get_tracer

    tracer = get_tracer("stamp-test")
    with tracer.span("x.wave", foo=1):
        pass
    ev = [e for e in tracer.events() if e["name"] == "x.wave"][-1]
    assert ev["args"]["run_id"] == "stamp-test"
    assert ev["args"]["foo"] == 1


# -- HTTP front-end ----------------------------------------------------------


def _http_json(url, data=None):
    req = urllib.request.Request(
        url,
        data=data,
        headers={"Content-Type": "application/json"} if data else {},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.load(resp)


def test_http_front_end():
    with ServiceServer(
        quantum_s=0.75, default_spawn=dict(SPAWN_2PC)
    ) as server:
        # Submit two concurrent jobs over HTTP (the CI smoke shape).
        ids = []
        for _ in range(2):
            resp = _http_json(
                server.url + "/jobs",
                json.dumps(
                    {"model": "2pc", "model_args": {"rm_count": 3}}
                ).encode(),
            )
            assert resp["state"] in ("queued", "running")
            ids.append(resp["job_id"])
        deadline = time.monotonic() + 240
        done = {}
        while len(done) < 2 and time.monotonic() < deadline:
            for jid in ids:
                st = _http_json(f"{server.url}/jobs/{jid}")
                if st["state"] in ("done", "failed", "cancelled"):
                    done[jid] = st
            time.sleep(0.1)
        assert len(done) == 2, "jobs did not finish in time"
        for st in done.values():
            assert st["state"] == "done"
            assert st["result"]["unique"] == UNIQUE_2PC3
            assert st["result"]["properties_hold"] is True
            lat = st["latency"]
            assert lat["wall_s"] is not None
            assert lat["ttfv_s"] is not None

        # Job list (the UI panel feed).
        listing = _http_json(server.url + "/jobs")
        assert {j["job_id"] for j in listing["jobs"]} >= set(ids)

        # Per-job metrics: that run's registry, labeled with its run_id.
        text = (
            urllib.request.urlopen(
                f"{server.url}/jobs/{ids[0]}/metrics", timeout=30
            )
            .read()
            .decode()
        )
        assert f'run_id="{ids[0]}"' in text
        # Packed jobs carry their per-tenant lane accounting; a job that
        # fell back to time-slicing carries the solo wave family. Either
        # way the per-run registry is populated and labeled.
        assert (
            "stateright_pack_tenant_states_unique_total" in text
            or "stateright_tpu_bfs_states_unique_total" in text
        )

        # Aggregate /metrics exports every run under its label, with at
        # most ONE TYPE line per metric family (spec-valid exposition —
        # strict parsers reject duplicates).
        agg = (
            urllib.request.urlopen(server.url + "/metrics", timeout=30)
            .read()
            .decode()
        )
        for jid in ids:
            assert f'run_id="{jid}"' in agg
        type_lines = [
            line for line in agg.splitlines() if line.startswith("# TYPE ")
        ]
        assert len(type_lines) == len(set(type_lines))

        # The /jobs listing is the summary view: scalar verdicts only,
        # no report text / ledgers (the UI polls it every ~2s).
        listed = _http_json(server.url + "/jobs")["jobs"]
        for j in listed:
            if isinstance(j.get("result"), dict):
                assert "report" not in j["result"]
                assert "attribution" not in j["result"]

        # Unknown model / unknown job surface as HTTP errors.
        with pytest.raises(urllib.error.HTTPError) as err:
            _http_json(
                server.url + "/jobs", json.dumps({"model": "nope"}).encode()
            )
        assert err.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as err:
            _http_json(server.url + "/jobs/absent")
        assert err.value.code == 404
        # Bare "/jobs/" (trailing slash) is a clean 404, not a dropped
        # connection from an unhandled IndexError.
        with pytest.raises(urllib.error.HTTPError) as err:
            _http_json(server.url + "/jobs/")
        assert err.value.code == 404

        # Dangerous spawn kwargs are refused over HTTP: resume_from
        # would pickle.load a server-side path of the client's choosing.
        for bad_body in (
            {"model": "2pc", "spawn": {"resume_from": "/tmp/evil.pkl"}},
            {"model": "2pc", "spawn": {"checkpoint_path": "/tmp/x"}},
            {"model": "2pc", "spawn": 5},
            {"model": "2pc", "model_args": 5},
            {"model": "2pc", "priority": [1]},
        ):
            with pytest.raises(urllib.error.HTTPError) as err:
                _http_json(
                    server.url + "/jobs", json.dumps(bad_body).encode()
                )
            assert err.value.code == 400

        # The UI page (with the jobs panel markup) serves from /.
        page = (
            urllib.request.urlopen(server.url + "/", timeout=30)
            .read()
            .decode()
        )
        assert "jobs-panel" in page

        # Cancel over HTTP: submit a bigger job and kill it.
        resp = _http_json(
            server.url + "/jobs",
            json.dumps(
                {"model": "2pc", "model_args": {"rm_count": 4}}
            ).encode(),
        )
        jid = resp["job_id"]
        out = _http_json(f"{server.url}/jobs/{jid}/cancel", b"")
        assert out["cancelled"] is True
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            st = _http_json(f"{server.url}/jobs/{jid}")
            if st["state"] in ("done", "failed", "cancelled"):
                break
            time.sleep(0.05)
        assert st["state"] == "cancelled"


@pytest.mark.parametrize(
    "body,code,needle",
    [
        # Unknown zoo name: structured 400 naming the zoo.
        ({"model": "not-a-model"}, 400, "unknown model"),
        # Inadmissible budget: rejected at admission, not mid-run.
        (
            {"model": "2pc", "hbm_budget_mib": 0.0001},
            400,
            "rejected at admission",
        ),
        # Non-numeric deadline: coerced at submit, 400 with the reason.
        ({"model": "2pc", "deadline_s": "soon"}, 400, "deadline_s"),
        # Bad retry policy shape.
        ({"model": "2pc", "retry": "always"}, 400, "retry"),
        # Full admission queue: 429 + Retry-After (graceful
        # degradation, not a client error).
        ({"model": "2pc", "model_args": {"rm_count": 4}}, 429, "full"),
    ],
)
def test_http_admission_errors_are_structured(body, code, needle):
    """Every admission failure over HTTP is a structured JSON error
    with the right status — including 429 for a full queue."""
    with ServiceServer(
        quantum_s=0.5,
        default_spawn=dict(SPAWN_2PC),
        max_queued_jobs=1,
    ) as server:
        filler = None
        if code == 429:
            # Occupy the single queue slot first.
            filler = _http_json(
                server.url + "/jobs",
                json.dumps(
                    {"model": "2pc", "model_args": {"rm_count": 4}}
                ).encode(),
            )
        with pytest.raises(urllib.error.HTTPError) as err:
            _http_json(server.url + "/jobs", json.dumps(body).encode())
        assert err.value.code == code
        payload = json.loads(err.value.read().decode())
        assert needle in payload["error"]
        if code == 429:
            assert err.value.headers.get("Retry-After") is not None
            assert payload["retry_after_s"] > 0
        if filler is not None:
            _http_json(
                f"{server.url}/jobs/{filler['job_id']}/cancel", b""
            )
