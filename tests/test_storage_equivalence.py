"""Tiny-budget out-of-core equivalence: a checker under ``hbm_budget_mib``
pressure (multiple L0 evictions, host-tier probes on every wave) must be
BIT-IDENTICAL to the unbounded single-tier run — state counts, unique
counts, depths, discovery fingerprints, and the golden WriteReporter
strings. The argument: the tier union is exactly the visited set, each
key's first global appearance is the only one surviving the two-phase
filter, and the survivor gather preserves lane order, so the frontier
sequence never diverges (storage/__init__.py).

Fast lane: 2pc-4 (materializing pipeline, deep-drain→wave handoff),
2pc-4 under symmetry (orbit-key probe path), a mid-eviction checkpoint
resume, plus the async-pipeline twins (``async_pipeline=True``: probe/
evict/checkpoint on the host worker, survivors one wave late — must
stay bit-identical, including a checkpoint taken mid-pipeline then
resumed). Slow lane: the 2pc-5 acceptance run (async off AND on), ABD
with ``expand_fps`` on/off × async off/on, and the sharded checker
with disk spill (L2), async off/on.
"""

import io
import math
import pickle
import re

import pytest

from stateright_tpu import WriteReporter
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry import metrics_registry


def _golden(checker):
    out = io.StringIO()
    checker.report(WriteReporter(out))
    # The wall-clock field is the only permitted difference.
    return re.sub(r"sec=\d+", "sec=_", out.getvalue())


def budget_for_table(rows: int) -> float:
    return ((rows + 128) * 8) / (1 << 20)


def tiny_budget(model, frontier: int) -> float:
    """The smallest admissible ``hbm_budget_mib`` for this model at this
    frontier width — the maximum eviction pressure the checker accepts
    (the shared library definition, so a load-factor change cannot
    silently stop these budgets from binding)."""
    from stateright_tpu.checker.tpu import min_admissible_hbm_budget_mib

    return min_admissible_hbm_budget_mib(model, frontier)


@pytest.fixture(scope="module")
def unbounded_2pc4():
    """One unbounded 2pc-4 reference run shared by the fast-lane tests
    (same spawn config everywhere it is compared against)."""
    checker = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=16, table_capacity=1 << 12)
        .join()
    )
    assert checker.worker_error() is None
    return checker


def _assert_identical(budgeted, unbounded, min_evictions, prefix="tpu_bfs"):
    assert budgeted.worker_error() is None
    assert unbounded.worker_error() is None
    assert budgeted.unique_state_count() == unbounded.unique_state_count()
    assert budgeted.state_count() == unbounded.state_count()
    assert budgeted.max_depth() == unbounded.max_depth()
    assert budgeted._discoveries_fp == unbounded._discoveries_fp
    assert _golden(budgeted) == _golden(unbounded)
    snap = metrics_registry().snapshot()
    evictions = snap.get(f"{prefix}.storage.evictions", 0)
    assert evictions >= min_evictions, (
        f"budget never bound: {evictions} evictions "
        f"(needed >= {min_evictions})"
    )


def test_budget_identical_2pc4(unbounded_2pc4):
    """Materializing pipeline under eviction pressure, including the
    deep-drain → wave-mode handoff at the first eviction."""
    metrics_registry().reset()
    budgeted = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16,
            table_capacity=1 << 12,
            hbm_budget_mib=tiny_budget(TwoPhaseSys(4), 16),
        )
        .join()
    )
    _assert_identical(budgeted, unbounded_2pc4, min_evictions=2)
    assert budgeted.unique_state_count() == 1568
    budgeted.assert_properties()


def test_async_pipeline_identical_2pc4(unbounded_2pc4):
    """Async pipelined wave engine under eviction pressure: the host
    worker applies every probe/evict verdict one wave late, yet counts,
    depths, discoveries, and the golden reporter must match the
    unbounded synchronous run exactly (README "Async pipeline")."""
    metrics_registry().reset()
    budgeted = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16,
            table_capacity=1 << 12,
            hbm_budget_mib=tiny_budget(TwoPhaseSys(4), 16),
            async_pipeline=True,
        )
        .join()
    )
    _assert_identical(budgeted, unbounded_2pc4, min_evictions=2)
    assert budgeted.unique_state_count() == 1568
    budgeted.assert_properties()


def test_async_checkpoint_mid_pipeline_resume(tmp_path, unbounded_2pc4):
    """A checkpoint taken mid-pipeline (epoch barrier drains in-flight
    verdicts, payload snapshots AFTER the barrier, pickle rides the
    worker) must restore into a run that finishes bit-identical — the
    survivors that landed during the barrier's drain must be in the
    payload's chunk list, not just its counters."""
    ckpt = tmp_path / "2pc4-async.ckpt"
    budget = tiny_budget(TwoPhaseSys(4), 16)
    metrics_registry().reset()
    first = (
        TwoPhaseSys(4)
        .checker()
        .target_state_count(2500)  # stop early, mid-space
        .spawn_tpu_bfs(
            frontier_capacity=16,
            table_capacity=1 << 12,
            hbm_budget_mib=budget,
            checkpoint_path=str(ckpt),
            checkpoint_every_chunks=4,
            async_pipeline=True,
        )
        .join()
    )
    assert first.worker_error() is None
    assert first.unique_state_count() < 1568
    with open(ckpt, "rb") as f:
        payload = pickle.load(f)
    assert payload["version"] == 2
    resumed = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16,
            table_capacity=1 << 12,
            hbm_budget_mib=budget,
            resume_from=str(ckpt),
            async_pipeline=True,
        )
        .join()
    )
    _assert_identical(resumed, unbounded_2pc4, min_evictions=1)
    assert resumed.unique_state_count() == 1568
    resumed.assert_properties()


@pytest.mark.parametrize("async_on", [False, True])
def test_packed_budget_identical_2pc4(unbounded_2pc4, async_on):
    """Tenant-packed out-of-core (PR 12): two tenants share one
    budget-capped table whose evictions drain into PER-TENANT host
    partitions; each tenant's two-phase probe runs against its own run
    set (on the pack's pipeline worker when async) and both stay
    bit-identical to the unbounded solo run."""
    from stateright_tpu.checker.packed_tenancy import TenantPackedEngine

    engine = TenantPackedEngine(
        TwoPhaseSys(4),
        frontier_capacity=16,
        table_capacity=1 << 12,
        max_tenants=2,
        hbm_budget_mib=2 * tiny_budget(TwoPhaseSys(4), 16),
        async_pipeline=async_on,
        aot_cache="t-se-pack",
    )
    a = engine.admit("se-a", "se-pk-a")
    b = engine.admit("se-b", "se-pk-b")
    steps = 0
    while engine.live_count():
        engine.step()
        steps += 1
        assert steps < 50_000
    engine.close()
    for view in (a, b):
        assert view.unique_state_count() == (
            unbounded_2pc4.unique_state_count()
        )
        assert view.state_count() == unbounded_2pc4.state_count()
        assert view.max_depth() == unbounded_2pc4.max_depth()
        assert _golden(view) == _golden(unbounded_2pc4)
    # The budget actually bound: stale keys were answered by the
    # per-tenant partitions, not the device table.
    snap = metrics_registry("se-pk-a").snapshot()
    assert snap.get("pack.tenant.storage_stale", 0) > 0


def test_budget_identical_2pc4_symmetry():
    """Orbit-key probe path: under symmetry the visited keys are
    canonical-form fingerprints; the host tier must store and probe THAT
    key space (and the filtered key log keeps checkpoints coherent)."""
    metrics_registry().reset()
    budgeted = (
        TwoPhaseSys(4)
        .checker()
        .symmetry()
        .spawn_tpu_bfs(
            frontier_capacity=8,
            table_capacity=1 << 12,
            hbm_budget_mib=tiny_budget(TwoPhaseSys(4), 8),
        )
        .join()
    )
    unbounded = (
        TwoPhaseSys(4)
        .checker()
        .symmetry()
        .spawn_tpu_bfs(frontier_capacity=8, table_capacity=1 << 12)
        .join()
    )
    _assert_identical(budgeted, unbounded, min_evictions=1)


def test_checkpoint_mid_eviction_resume(tmp_path, unbounded_2pc4):
    """A checkpoint written AFTER evictions (runs + Bloom filters in the
    payload, format v2) restores — runs CRC-validated, L0 rebuilt as
    keys-not-in-runs — and finishes bit-identical to the unbounded run."""
    ckpt = tmp_path / "2pc4-oob.ckpt"
    budget = tiny_budget(TwoPhaseSys(4), 16)
    metrics_registry().reset()
    first = (
        TwoPhaseSys(4)
        .checker()
        .target_state_count(2500)  # stop early, mid-space
        .spawn_tpu_bfs(
            frontier_capacity=16,
            table_capacity=1 << 12,
            hbm_budget_mib=budget,
            checkpoint_path=str(ckpt),
            checkpoint_every_chunks=4,
        )
        .join()
    )
    assert first.worker_error() is None
    assert first.unique_state_count() < 1568
    snap = metrics_registry().snapshot()
    assert snap["tpu_bfs.storage.evictions"] >= 1
    with open(ckpt, "rb") as f:
        payload = pickle.load(f)
    assert payload["version"] == 2
    assert payload["storage"]["l1"] or payload["storage"]["l2"], (
        "checkpoint written mid-eviction must carry the tier runs"
    )

    resumed = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16,
            table_capacity=1 << 12,
            hbm_budget_mib=budget,
            resume_from=str(ckpt),
        )
        .join()
    )
    _assert_identical(resumed, unbounded_2pc4, min_evictions=1)
    assert resumed.unique_state_count() == 1568
    resumed.assert_properties()


@pytest.mark.slow
@pytest.mark.parametrize("async_on", [False, True])
def test_budget_identical_2pc5_acceptance(async_on):
    """The acceptance run: 2pc-5 with the budget forcing >= 2 evictions,
    bit-identical counts/discoveries/golden output to unbounded — on
    the synchronous path and the async pipelined one."""
    metrics_registry().reset()
    budgeted = (
        TwoPhaseSys(5)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16,
            table_capacity=1 << 14,
            hbm_budget_mib=tiny_budget(TwoPhaseSys(5), 16),
            async_pipeline=async_on,
        )
        .join()
    )
    unbounded = (
        TwoPhaseSys(5)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=16, table_capacity=1 << 14)
        .join()
    )
    _assert_identical(budgeted, unbounded, min_evictions=2)
    assert budgeted.unique_state_count() == 8832
    budgeted.assert_properties()


@pytest.mark.slow
@pytest.mark.parametrize("fps", [True, False])
@pytest.mark.parametrize("async_on", [False, True])
def test_budget_identical_abd_expand_fps(fps, async_on):
    """ABD register, fingerprint-only expansion on/off × async pipeline
    off/on: the fps wave's survivor path materializes only probed-fresh
    children (in async mode that materialization runs on the pipeline
    worker); every combination must stay bit-identical to its unbounded
    synchronous twin."""
    from stateright_tpu.models.linearizable_register import AbdModelCfg

    def spawn(**kw):
        return (
            AbdModelCfg(2, 2)
            .into_model()
            .checker()
            .spawn_tpu_bfs(
                frontier_capacity=8,
                table_capacity=1 << 12,
                expand_fps=fps,
                **kw,
            )
            .join()
        )

    metrics_registry().reset()
    model = AbdModelCfg(2, 2).into_model()
    budgeted = spawn(
        hbm_budget_mib=tiny_budget(model, 8), async_pipeline=async_on
    )
    unbounded = spawn()
    _assert_identical(budgeted, unbounded, min_evictions=2)
    assert budgeted.unique_state_count() == 544


@pytest.mark.slow
@pytest.mark.parametrize("async_on", [False, True])
def test_sharded_budget_identical_with_spill(tmp_path, async_on):
    """Sharded checker: per-shard tiers, disk spill (L2) under a host
    budget, and bit-identical results — synchronous and async-pipelined
    (harvest verdicts on the worker, coalescing barrier when the pool
    runs short). The unbounded twin runs wave-at-a-time too (the
    budgeted path forces it, and sharded deep drains label depths at
    first-claim rather than minimal)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("fp",))

    def spawn(**kw):
        return (
            TwoPhaseSys(5)
            .checker()
            .spawn_sharded_tpu_bfs(
                mesh=mesh,
                frontier_per_device=8,
                table_capacity_per_device=1 << 14,
                **kw,
            )
            .join()
        )

    A = TwoPhaseSys(5).packed_action_count()
    rows = 1 << math.ceil(math.log2(4 * 8 * A / 0.5 + 1))
    metrics_registry().reset()
    budgeted = spawn(
        hbm_budget_mib=budget_for_table(rows),
        host_budget_mib=0.02,
        spill_dir=str(tmp_path),
        async_pipeline=async_on,
    )
    unbounded = spawn(max_drain_waves=1)
    _assert_identical(
        budgeted, unbounded, min_evictions=2, prefix="sharded_bfs"
    )
    assert budgeted.unique_state_count() == 8832
    snap = metrics_registry().snapshot()
    assert snap["sharded_bfs.storage.spills"] >= 1, "host budget never spilled"
