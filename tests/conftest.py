"""Test configuration: force a virtual 8-device CPU mesh before JAX loads.

Multi-chip hardware is not available in CI; sharding tests run on
``xla_force_host_platform_device_count=8`` per the project test strategy.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
