"""Test configuration: force a virtual 8-device CPU mesh before JAX loads.

Multi-chip hardware is not available in CI; sharding tests run on
``xla_force_host_platform_device_count=8`` per the project test strategy.

Note: this image boots python through an ``axon`` sitecustomize that
registers a tunneled TPU backend and forces ``jax_platforms=axon,cpu`` via
``jax.config`` (overriding the ``JAX_PLATFORMS`` env var), so the config
must be re-pinned to cpu *after* importing jax — env vars alone are not
enough. Tests must never dispatch through the single-client TPU tunnel.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from stateright_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_cache,
)

enable_persistent_cache()
