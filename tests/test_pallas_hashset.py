"""Pallas visited-set insert vs the XLA scatter-claim path: exact outcome
parity (fresh/found/pending flags and final table contents-as-set) on
randomized sorted batches, in interpret mode (CPU).

The kernel requires sorted keys (the checkers' wave dedup guarantees it);
these tests mirror that contract, including inactive sentinel lanes and
repeat-insert batches.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu.ops.hashset import MAX_PROBES, hashset_insert, hashset_new
from stateright_tpu.ops.pallas_hashset import (
    TILE_ROWS,
    pallas_hashset_insert,
)

CAP = TILE_ROWS * 2  # two tiles; exercises the cross-tile margin


def _sorted_batch(rng, n, active_frac=0.9, dup_frac=0.0, span=None):
    hi = rng.integers(0, span or (1 << 32), size=n, dtype=np.uint64).astype(
        np.uint32
    )
    lo = rng.integers(1, 1 << 32, size=n, dtype=np.uint64).astype(np.uint32)
    if dup_frac:
        k = max(1, int(n * dup_frac))
        hi[:k] = hi[n // 2 : n // 2 + k]
        lo[:k] = lo[n // 2 : n // 2 + k]
    active = rng.random(n) < active_frac
    hi = np.where(active, hi, 0xFFFFFFFF).astype(np.uint32)
    lo = np.where(active, lo, 0xFFFFFFFF).astype(np.uint32)
    order = np.lexsort((lo, hi))
    return (
        jnp.asarray(hi[order]),
        jnp.asarray(lo[order]),
        jnp.asarray(active[order]),
    )


def _table_keys(table):
    t = np.asarray(table)
    live = (t[:, 0] != 0) | (t[:, 1] != 0)
    return set(zip(t[live, 0].tolist(), t[live, 1].tolist()))


def _dedup_first(hi, lo, active):
    """Wave-unique mask: first active occurrence of each (hi, lo)."""
    hi, lo, active = (np.asarray(x) for x in (hi, lo, active))
    seen = set()
    out = np.zeros_like(active)
    for i in range(len(hi)):
        if active[i] and (int(hi[i]), int(lo[i])) not in seen:
            seen.add((int(hi[i]), int(lo[i])))
            out[i] = True
    return jnp.asarray(out)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_parity_with_xla_insert(seed):
    rng = np.random.default_rng(seed)
    hi, lo, active = _sorted_batch(rng, 512)
    uniq = _dedup_first(hi, lo, active)

    t_x, fresh_x, found_x, pend_x = hashset_insert(
        hashset_new(CAP), hi, lo, uniq
    )
    t_p, fresh_p, found_p, pend_p = pallas_hashset_insert(
        hashset_new(CAP), hi, lo, uniq, interpret=True
    )
    assert np.array_equal(np.asarray(fresh_x), np.asarray(fresh_p))
    assert np.array_equal(np.asarray(found_x), np.asarray(found_p))
    assert np.array_equal(np.asarray(pend_x), np.asarray(pend_p))
    assert _table_keys(t_x) == _table_keys(t_p)


def test_second_insert_reports_found():
    rng = np.random.default_rng(7)
    hi, lo, active = _sorted_batch(rng, 256, active_frac=1.0)
    uniq = _dedup_first(hi, lo, active)
    table, fresh1, _found1, _ = pallas_hashset_insert(
        hashset_new(CAP), hi, lo, uniq, interpret=True
    )
    table, fresh2, found2, pend2 = pallas_hashset_insert(
        table, hi, lo, uniq, interpret=True
    )
    assert not bool(np.asarray(fresh2).any())
    assert np.array_equal(np.asarray(found2), np.asarray(uniq))
    assert not bool(np.asarray(pend2).any())
    assert int(np.asarray(fresh1).sum()) == int(np.asarray(uniq).sum())


def test_in_batch_duplicates_report_found():
    """Superset of the wave-unique contract: the kernel resolves in-batch
    duplicates itself (second occurrence -> found)."""
    rng = np.random.default_rng(3)
    hi, lo, active = _sorted_batch(rng, 128, active_frac=1.0, dup_frac=0.25)
    table, fresh, found, pend = pallas_hashset_insert(
        hashset_new(CAP), hi, lo, jnp.asarray(active), interpret=True
    )
    hi_n, lo_n = np.asarray(hi), np.asarray(lo)
    n_unique = len(set(zip(hi_n.tolist(), lo_n.tolist())))
    assert int(np.asarray(fresh).sum()) == n_unique
    assert int(np.asarray(found).sum()) == len(hi_n) - n_unique
    assert not bool(np.asarray(pend).any())


def test_clustered_keys_cross_tile_margin():
    """Keys homing at the tile boundary probe into the apron of the next
    tile; claims there must be visible to the next tile's window."""
    # All keys home into the last row of tile 0: hi top bits == TILE_ROWS-1.
    shift = 32 - (CAP.bit_length() - 1)
    base_hi = np.uint32((TILE_ROWS - 1) << shift)
    n = 64
    hi = np.full(n, base_hi, np.uint32)
    lo = np.arange(1, n + 1, dtype=np.uint32)
    active = jnp.ones((n,), bool)
    table, fresh, _found, pend = pallas_hashset_insert(
        hashset_new(CAP), jnp.asarray(hi), jnp.asarray(lo), active,
        interpret=True,
    )
    assert bool(np.asarray(fresh).all())
    assert not bool(np.asarray(pend).any())
    # Rows spill past the tile-0 boundary into tile 1's region.
    t = np.asarray(table)
    assert (t[TILE_ROWS : TILE_ROWS + n - 1, 1] != 0).any()
    # A second pass over tile-1-homed keys must see those spilled rows.
    hi2 = np.full(n, np.uint32(TILE_ROWS << shift), np.uint32)
    lo2 = np.arange(1, n + 1, dtype=np.uint32)
    table, fresh2, _f2, pend2 = pallas_hashset_insert(
        table, jnp.asarray(hi2), jnp.asarray(lo2), active, interpret=True
    )
    assert bool(np.asarray(fresh2).all())
    assert not bool(np.asarray(pend2).any())


def test_probe_overflow_reports_pending():
    """More same-home keys than MAX_PROBES slots -> the excess report
    pending (the host grows the table), matching the XLA path."""
    n = MAX_PROBES + 16
    hi = np.zeros(n, np.uint32)  # all home at row 0
    lo = np.arange(1, n + 1, dtype=np.uint32)
    active = jnp.ones((n,), bool)
    table, fresh, _found, pend = pallas_hashset_insert(
        hashset_new(CAP), jnp.asarray(hi), jnp.asarray(lo), active,
        interpret=True,
    )
    assert int(np.asarray(fresh).sum()) == MAX_PROBES
    assert int(np.asarray(pend).sum()) == 16


def test_checker_hashset_impl_pallas_oracle():
    """The checker-level dispatch (`spawn_tpu_bfs(hashset_impl="pallas")`):
    a whole exhaustive check through the Pallas insert (interpret mode
    off-TPU) must reproduce the 2pc-3 oracle. Pins the _insert_sorted
    wiring, the TILE_ROWS capacity validation path, and the mixed
    pallas-wave/XLA-rehash table interplay."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=64, table_capacity=TILE_ROWS,
                       hashset_impl="pallas")
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 288
    checker.assert_properties()

    with pytest.raises(ValueError):
        TwoPhaseSys(3).checker().spawn_tpu_bfs(
            table_capacity=TILE_ROWS + 1, hashset_impl="pallas"
        )


class TestUnsortedInsert:
    """``hashset_insert_unsorted`` (round 4): the duplicate-tolerant
    scatter insert behind ``wave_dedup='scatter'``. Randomized dense
    tables force the documented danger cases: same-key twins racing
    different-key contenders for one slot, duplicate lanes, probe-cap
    overflow — exactly-one-fresh-per-distinct-key must hold through all
    of them."""

    def _keys(self, rng, n_distinct, n_lanes):
        # Full u32 range: the home slot is the TOP bits of hi (real
        # fingerprints are full-range murmur words), so a capped range
        # would squeeze every key into a prefix of the table and
        # overload it artificially.
        uniq = rng.integers(1, 1 << 32, (n_distinct, 2), np.uint64).astype(
            np.uint32
        )
        picks = rng.integers(0, n_distinct, n_lanes)
        return uniq[picks, 0], uniq[picks, 1]

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_exactly_one_fresh_per_distinct_active_key(self, seed):
        from stateright_tpu.ops.hashset import (
            hashset_insert_unsorted,
            hashset_new,
        )

        rng = np.random.default_rng(seed)
        # Tiny capacity => dense collision clusters; heavy duplication.
        cap, lanes = 256, 512
        hi, lo = self._keys(rng, 150, lanes)
        active = rng.random(lanes) < 0.8
        t, fresh, found, pend = jax.jit(hashset_insert_unsorted)(
            hashset_new(cap),
            jnp.asarray(hi),
            jnp.asarray(lo),
            jnp.asarray(active),
        )
        fresh, found, pend = map(np.asarray, (fresh, found, pend))
        distinct = {
            (int(a), int(b))
            for a, b, m in zip(hi, lo, active)
            if m
        }
        placed = {
            (int(a), int(b)) for a, b, f in zip(hi, lo, fresh) if f
        }
        # No key lost, no key double-claimed, nothing pending at this
        # load factor, every fresh lane carries a distinct key.
        assert int(fresh.sum()) == len(placed) == len(distinct)
        assert int(pend.sum()) == 0
        assert not (fresh & found).any()
        assert not (fresh & ~active).any() and not (found & ~active).any()
        # Re-insert: everything resolves as found, nothing fresh.
        _, fresh2, found2, pend2 = jax.jit(hashset_insert_unsorted)(
            t, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(active)
        )
        assert int(np.asarray(fresh2).sum()) == 0
        assert (np.asarray(found2) == active).all()
        assert int(np.asarray(pend2).sum()) == 0

    def test_matches_sorted_insert_table_contents(self):
        from stateright_tpu.ops.hashset import (
            hashset_insert,
            hashset_insert_unsorted,
            hashset_new,
        )

        rng = np.random.default_rng(9)
        # Load ~0.15, matching the checkers' operating range: at extreme
        # density the two-phase insert legitimately reports stragglers
        # beyond the quarter-width compact as pending (grow-and-retry
        # territory), which this table-content comparison is not about —
        # the overload test below covers that path.
        cap, lanes = 2048, 1024
        hi, lo = self._keys(rng, 300, lanes)
        active = np.ones(lanes, bool)
        t_u, fresh_u, _, pend_u = jax.jit(hashset_insert_unsorted)(
            hashset_new(cap), jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(active),
        )
        assert int(np.asarray(pend_u).sum()) == 0
        # Sorted path needs wave-unique active lanes.
        order = np.lexsort((lo, hi))
        shi, slo = hi[order], lo[order]
        uniq = np.concatenate(
            [[True], (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])]
        )
        t_s, fresh_s, _, _ = jax.jit(hashset_insert)(
            hashset_new(cap), jnp.asarray(shi), jnp.asarray(slo),
            jnp.asarray(uniq),
        )
        assert int(np.asarray(fresh_u).sum()) == int(np.asarray(fresh_s).sum())
        # Same key SET stored (slot layout may differ by probe order).
        def stored(t):
            t = np.asarray(t)
            live = (t[:, 0] != 0) | (t[:, 1] != 0)
            return {(int(a), int(b)) for a, b in t[live]}

        assert stored(t_u) == stored(t_s)

    def test_probe_cap_overflow_reports_pending_never_false_fresh(self):
        from stateright_tpu.ops.hashset import (
            MAX_PROBES,
            hashset_insert_unsorted,
            hashset_new,
        )

        rng = np.random.default_rng(4)
        # Overload: far more distinct keys than capacity.
        cap = 64
        n = cap + MAX_PROBES + 64
        uniq = rng.integers(1, 1 << 32, (n, 2), np.uint64).astype(np.uint32)
        t, fresh, found, pend = jax.jit(hashset_insert_unsorted)(
            hashset_new(cap),
            jnp.asarray(uniq[:, 0]),
            jnp.asarray(uniq[:, 1]),
            jnp.ones((n,), bool),
        )
        fresh, pend = np.asarray(fresh), np.asarray(pend)
        assert pend.any()  # the overload must surface as pending
        # Every fresh claim is genuinely stored.
        t = np.asarray(t)
        live = {(int(a), int(b)) for a, b in t[(t[:, 0] != 0) | (t[:, 1] != 0)]}
        claimed = {
            (int(a), int(b))
            for a, b, f in zip(uniq[:, 0], uniq[:, 1], fresh)
            if f
        }
        assert claimed <= live and len(claimed) == int(fresh.sum())
        assert not (fresh & pend).any()

    def test_lane_zero_straggler_not_clobbered_by_padding(self):
        # Review repro (r4): phase-2 padding slots must not alias real
        # lane 0 in the scatter-back. Lane 0's home chain is pre-occupied
        # for both bulk rounds, forcing it into the straggler phase at
        # compact slot 0; its fresh bit must survive the padding writes.
        from stateright_tpu.ops.hashset import (
            hashset_insert,
            hashset_insert_unsorted,
            hashset_new,
        )

        cap = 4096  # home = hi >> 20; n/cap = 0.25 load
        t = hashset_new(cap)
        blockers_hi = jnp.asarray([0x80000000, 0x80000001], jnp.uint32)
        blockers_lo = jnp.asarray([1, 2], jnp.uint32)
        t, bf, _, _ = jax.jit(hashset_insert)(
            t, blockers_hi, blockers_lo, jnp.ones((2,), bool)
        )
        assert bool(np.asarray(bf).all())

        n = 1024
        rng = np.random.default_rng(11)
        # Lane 0: same home (0x800) as the blockers, distinct key — probes
        # two occupied slots, lands in phase 2 at compact slot 0. Other
        # lanes: full-range homes, almost all resolving in the bulk
        # rounds, so most of the m compact slots stay PADDING — the
        # pre-fix bug needs padding slots to alias lane 0's index.
        hi = rng.integers(1, 1 << 32, n, np.uint64).astype(np.uint32)
        lo = rng.integers(1, 1 << 32, n, np.uint64).astype(np.uint32)
        hi[0], lo[0] = 0x80000002, 3
        t, fresh, found, pend = jax.jit(hashset_insert_unsorted)(
            t, jnp.asarray(hi), jnp.asarray(lo), jnp.ones((n,), bool)
        )
        fresh, found, pend = map(np.asarray, (fresh, found, pend))
        assert fresh[0] and not found[0] and not pend[0]
        # And the key really is in the table.
        t = np.asarray(t)
        assert ((t[:, 0] == 0x80000002) & (t[:, 1] == 3)).any()
