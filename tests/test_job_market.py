"""Direct unit tests for ``checker/job_market.py``'s ``JobBroker``.

Load-bearing for the host engines' worker threads and the service
admission path, but previously only exercised indirectly through the BFS
checker. Covers the three contract corners: quiescence close (the last
idle worker shuts the market down), ``split_and_push`` with zero-size
pieces (fewer jobs than idle workers must not publish empty batches),
and the worker-death ``close()`` drain (queued work dropped, blocked
workers released)."""

import threading
import time
from collections import deque

from stateright_tpu.checker.job_market import JobBroker


def test_single_thread_quiescence_closes_market():
    broker = JobBroker(thread_count=1)
    # The lone worker going idle IS global quiescence: pop returns the
    # empty "no more jobs" sentinel and the market closes.
    assert broker.pop() == deque()
    assert broker.is_closed()
    # Post-close pops stay empty (no deadlock), pushes are dropped.
    assert broker.pop() == deque()
    broker.push(deque([1]))
    assert broker.pop() == deque()
    assert broker.is_closed()


def test_two_workers_drain_to_quiescence():
    broker = JobBroker(thread_count=2)
    broker.push(deque([3, 1]))
    broker.push(deque([2]))
    seen = []
    seen_lock = threading.Lock()

    def worker():
        while True:
            batch = broker.pop()
            if not batch:
                return
            with seen_lock:
                seen.extend(batch)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "worker hung instead of closing"
    assert sorted(seen) == [1, 2, 3]
    assert broker.is_closed()


def _blocked_worker(broker, results):
    """A worker parked in pop() (registers as idle) that records what it
    eventually receives."""

    def run():
        results.append(broker.pop())

    t = threading.Thread(target=run)
    t.start()
    # Wait until the worker is provably idle inside pop() (open_count
    # decremented) rather than merely started.
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with broker._cond:
            if broker._open_count < broker._thread_count:
                return t
        time.sleep(0.005)
    raise AssertionError("worker never went idle")


def test_split_and_push_zero_size_pieces_share_nothing():
    broker = JobBroker(thread_count=2)
    results = []
    t = _blocked_worker(broker, results)
    # One idle thread, one local job: pieces = 2, size = 1 // 2 = 0 —
    # the zero-size piece must be skipped, never published as an empty
    # batch that would wake the idle worker with no work.
    jobs = deque(["only"])
    broker.split_and_push(jobs)
    assert list(jobs) == ["only"], "local job must stay local"
    with broker._cond:
        assert not broker._job_batches, "no empty batch may be published"
    broker.close()
    t.join(timeout=5)
    assert results == [deque()]


def test_split_and_push_shares_surplus_with_idle_worker():
    broker = JobBroker(thread_count=2)
    results = []
    t = _blocked_worker(broker, results)
    jobs = deque([1, 2, 3, 4])
    broker.split_and_push(jobs)
    # pieces = 2, size = 2: half stays local, half goes to the idle
    # worker (appendleft preserves the shared half's order).
    assert len(jobs) == 2
    t.join(timeout=5)
    assert not t.is_alive()
    assert len(results) == 1 and len(results[0]) == 2
    assert sorted(list(jobs) + list(results[0])) == [1, 2, 3, 4]


def test_split_and_push_after_close_clears_jobs():
    broker = JobBroker(thread_count=2)
    broker.close()
    jobs = deque([1, 2, 3])
    broker.split_and_push(jobs)
    # A dead market takes no work and tells the caller to drop its own:
    # the local surplus is cleared so the dying worker never grinds on.
    assert not jobs


def test_worker_death_close_releases_blocked_worker():
    broker = JobBroker(thread_count=2)
    results = []
    blocked = _blocked_worker(broker, results)
    # The other worker "dies" (its exception path calls close(), as the
    # host engines do in their worker finally blocks): the blocked
    # worker must drain out with the empty sentinel instead of hanging.
    broker.close()
    blocked.join(timeout=5)
    assert not blocked.is_alive(), "blocked worker not released by close()"
    assert results == [deque()]
    # The released worker's own exit path closes too; only then is every
    # worker accounted for and the market fully closed.
    broker.close()
    assert broker.is_closed()


def test_worker_death_close_drops_queued_work():
    broker = JobBroker(thread_count=2)
    broker.push(deque([1, 2]))
    broker.push(deque([3]))
    got = broker.pop()  # worker takes one batch in hand...
    assert got
    broker.close()  # ...then dies: the still-queued batch must drop
    with broker._cond:
        assert not broker._job_batches, "close() must drop queued work"
    # The surviving worker's next pop observes the closed market and
    # exits (then closes itself on the way out).
    assert broker.pop() == deque()
    broker.close()
    assert broker.is_closed()
