"""The chaos suite: deterministic fault injection (utils/faults.py)
through the self-healing service layer.

The acceptance bar (ISSUE 13): for every injected fault class — host
probe, spill ENOSPC, pipeline-worker death, device-wave raise,
checkpoint write, stall — the job recovers via checkpointed retry and
its verdict (counts, depths, discoveries, golden reporter) is
bit-identical to the fault-free run; a packed tenant's blast radius is
exactly itself; and a kill-and-recover(service_dir) resumes a zoo job
bit-identically from its last durable checkpoint.

Budget notes: every service test reuses the suite's 2pc spawn shape
(frontier 16 / table 4096, one shared AOT namespace) so the persistent
compile cache keeps incarnations cheap, and the fault-free baseline is
computed once per module.
"""

import io
import os
import re
import threading
import time

import pytest

from stateright_tpu import WriteReporter
from stateright_tpu.checker.pipeline import PipelinePoisonedError
from stateright_tpu.checker.tpu import min_admissible_hbm_budget_mib
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.service import (
    CheckService,
    JobHandle,
    QueueFullError,
    RetryPolicy,
)
from stateright_tpu.utils.faults import (
    DeviceWaveFault,
    FaultInjector,
    FaultSpec,
    SpillFault,
    classify_fault,
    inject,
    seeded_specs,
    tenant_fault_of,
)

SPAWN_2PC = {
    "frontier_capacity": 16,
    "table_capacity": 1 << 12,
    "max_drain_waves": 2,
    # Same SHAPES as tests/test_service.py (the persistent jax compile
    # cache keys on the HLO, so lowerings stay warm across the suite)
    # but a DISTINCT in-process AOT namespace: sharing "t-svc" would
    # pre-warm test_service's executables and break its timing-shaped
    # assumption that a cold 2pc-4 job outlives one 0.75s quantum.
    "aot_cache": "t-flt",
}
UNIQUE_2PC3 = 288


def _golden(text_or_checker):
    if isinstance(text_or_checker, str):
        text = text_or_checker
    else:
        out = io.StringIO()
        text_or_checker.report(WriteReporter(out))
        text = out.getvalue()
    return re.sub(r"sec=\d+", "sec=_", text)


def _service(**kw):
    kw.setdefault("quantum_s", 5.0)
    kw.setdefault("default_spawn", dict(SPAWN_2PC))
    return CheckService(**kw)


@pytest.fixture(scope="module")
def baseline():
    """Fault-free 2pc-3 verdicts: in-core and out-of-core (the same
    numbers — that is the PR 5 guarantee — but captured separately so
    golden comparisons stay apples-to-apples)."""
    svc = _service()
    try:
        r = svc.submit(
            model_name="2pc", model_args={"rm_count": 3}
        ).result(timeout=300)
    finally:
        svc.close()
    return r


# -- the injector itself -----------------------------------------------------


def test_injector_fires_at_exact_hit_indices():
    inj = FaultInjector(FaultSpec("device.wave", at=2))
    inj.fire("device.wave")
    inj.fire("device.wave")
    with pytest.raises(DeviceWaveFault):
        inj.fire("device.wave")
    inj.fire("device.wave")  # count=1: only hit index 2 faults
    assert inj.hits("device.wave") == 4
    assert inj.triggered() == 1


def test_injector_tenant_filter_counts_only_matching_hits():
    inj = FaultInjector(FaultSpec("storage.host_probe", at=1, tenant="b"))
    inj.fire("storage.host_probe", tenant="a")  # not counted for the spec
    inj.fire("storage.host_probe", tenant="b")  # b hit 0
    with pytest.raises(Exception):
        inj.fire("storage.host_probe", tenant="b")  # b hit 1 -> fault
    assert inj.triggered() == 1


def test_classify_fault_walks_cause_chains():
    assert classify_fault(SpillFault()) == "spill"
    assert classify_fault(OSError(28, "No space left on device")) == "spill"
    inner = DeviceWaveFault()
    outer = RuntimeError("wrapped")
    outer.__cause__ = inner
    assert classify_fault(outer) == "device_wave"
    poisoned = PipelinePoisonedError(ValueError("worker died"))
    assert classify_fault(poisoned) == "pipeline_worker"
    assert classify_fault(ValueError("x")) == "unknown"
    assert tenant_fault_of(outer) is None


def test_seeded_specs_are_reproducible():
    sites = ["device.wave", "storage.host_probe", "storage.spill"]
    a = seeded_specs(1234, sites)
    b = seeded_specs(1234, sites)
    assert [(s.site, s.at) for s in a] == [(s.site, s.at) for s in b]
    c = seeded_specs(99, sites)
    assert [(s.site, s.at) for s in a] != [(s.site, s.at) for s in c]
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSpec("no.such.site")
    with pytest.raises(ValueError, match="stall_s"):
        FaultSpec("wave.stall")


def test_retry_policy_filter_and_backoff():
    p = RetryPolicy(max_retries=2, backoff_s=0.5, backoff_factor=2.0,
                    max_backoff_s=10.0, retry_on={"device_wave"})
    assert p.allows("device_wave", 0) and p.allows("device_wave", 1)
    assert not p.allows("device_wave", 2)
    assert not p.allows("spill", 0)
    assert p.delay_s(0) == 0.5 and p.delay_s(1) == 1.0
    assert RetryPolicy.from_dict(p.to_dict()).to_dict() == p.to_dict()


# -- per-fault-class recovery: bit-identical verdicts ------------------------


def test_device_wave_fault_retries_bit_identical(baseline):
    svc = _service()
    try:
        with inject(FaultSpec("device.wave", at=1)) as inj:
            h = svc.submit(model_name="2pc", model_args={"rm_count": 3})
            r = h.result(timeout=300)
        assert inj.triggered() == 1
        st = h.status()
        assert st["retries"] == 1
        assert st["faults"][0]["class"] == "device_wave"
        assert r["unique"] == baseline["unique"]
        assert r["states"] == baseline["states"]
        assert r["max_depth"] == baseline["max_depth"]
        assert set(r["discoveries"]) == set(baseline["discoveries"])
        assert _golden(r["report"]) == _golden(baseline["report"])
    finally:
        svc.close()


def test_host_probe_and_spill_faults_retry_bit_identical(
    baseline, tmp_path
):
    """Out-of-core 2pc-3 under the minimum budget: a host-probe death
    and a spill ENOSPC each fault the slice, the retry recovers, and
    the verdict matches the fault-free run exactly."""
    budget = min_admissible_hbm_budget_mib(TwoPhaseSys(3), 16)
    cases = [
        ("storage.host_probe", "host_probe", {}),
        (
            "storage.spill",
            "spill",
            {
                "host_budget_mib": 0.0001,
                "spill_dir": str(tmp_path / "spill"),
            },
        ),
    ]
    for site, klass, extra_spawn in cases:
        svc = _service()
        try:
            with inject(FaultSpec(site, at=0)) as inj:
                h = svc.submit(
                    model_name="2pc", model_args={"rm_count": 3},
                    hbm_budget_mib=budget, spawn=extra_spawn or None,
                )
                r = h.result(timeout=300)
            assert inj.triggered() == 1, site
            st = h.status()
            assert st["retries"] == 1, (site, st["faults"])
            assert st["faults"][0]["class"] == klass
            assert r["unique"] == baseline["unique"], site
            assert _golden(r["report"]) == _golden(baseline["report"])
        finally:
            svc.close()


def test_checkpoint_write_fault_retries_bit_identical(baseline, tmp_path):
    svc = _service()
    try:
        with inject(FaultSpec("checkpoint.write", at=0)) as inj:
            h = svc.submit(
                model_name="2pc", model_args={"rm_count": 3},
                spawn={
                    "checkpoint_path": str(tmp_path / "c.ckpt"),
                    "checkpoint_every_chunks": 1,
                },
            )
            r = h.result(timeout=300)
        assert inj.triggered() == 1
        st = h.status()
        assert st["retries"] == 1
        assert st["faults"][0]["class"] == "checkpoint_write"
        assert r["unique"] == baseline["unique"]
        assert _golden(r["report"]) == _golden(baseline["report"])
    finally:
        svc.close()


def test_stall_watchdog_auto_preempts_and_recovers(baseline):
    """A wedged wave (injected 1.2s sleep) trips the service stall
    watchdog, whose default action hook auto-preempts: the job suspends
    at its next yield point, retries, and finishes exactly."""
    svc = _service(packing=False, stall_deadline_s=0.3, quantum_s=30.0)
    try:
        with inject(
            FaultSpec("wave.stall", at=2, stall_s=1.2)
        ) as inj:
            h = svc.submit(model_name="2pc", model_args={"rm_count": 3})
            r = h.result(timeout=300)
        assert inj.triggered() == 1
        st = h.status()
        assert st["stall_preempts"] == 1
        assert st["preempts"] >= 1
        assert r["unique"] == baseline["unique"]
        assert _golden(r["report"]) == _golden(baseline["report"])
    finally:
        svc.close()


def test_pipeline_worker_death_fault_retries_bit_identical(baseline):
    """Async-pipeline worker death at the SERVICE level: the poisoned
    pipeline surfaces as the worker error, classifies as
    pipeline_worker, and the retry recovers exactly."""
    budget = min_admissible_hbm_budget_mib(TwoPhaseSys(3), 16)
    svc = _service()
    try:
        with inject(FaultSpec("pipeline.worker", at=1)) as inj:
            h = svc.submit(
                model_name="2pc", model_args={"rm_count": 3},
                hbm_budget_mib=budget,
                spawn={"async_pipeline": True},
            )
            r = h.result(timeout=300)
        assert inj.triggered() == 1
        st = h.status()
        assert st["retries"] == 1
        assert st["faults"][0]["class"] == "pipeline_worker"
        assert r["unique"] == baseline["unique"]
        assert _golden(r["report"]) == _golden(baseline["report"])
    finally:
        svc.close()


def test_retry_resumes_from_snapshot_not_scratch():
    """The checkpointed-retry contract: a fault on a RESUMED slice
    hands the pre-slice payload back, so the retry re-explores only
    from the last good wave boundary. Driven directly through
    _run_slice with the scheduler parked, for determinism."""
    svc = _service(packing=False, quantum_s=30.0)
    # Park the scheduler thread (close-without-jobs), then drive slices
    # on this thread: deterministic, no racing picker.
    svc._closing.set()
    svc._wake()
    svc._scheduler.join(timeout=30)
    svc._closing.clear()
    try:
        h = svc.submit(model_name="2pc", model_args={"rm_count": 4})
        job = svc.job(h.job_id)
        # Slice 1: run a bit, preempt -> suspended payload.
        t = threading.Thread(target=svc._run_slice, args=(job,))
        t.start()
        deadline = time.monotonic() + 60
        while svc._active_checker is None and time.monotonic() < deadline:
            time.sleep(0.002)
        checker = svc._active_checker
        assert checker is not None, "slice never spawned"
        checker.request_preempt()
        t.join(timeout=180)
        assert job.state == "suspended", job.state
        resumed_payload = job.payload
        assert resumed_payload is not None
        mid_unique = resumed_payload["unique_count"]
        # Slice 2: resumes from the payload, faults on its first wave.
        with inject(FaultSpec("device.wave", at=0)):
            svc._run_slice(job)
        assert job.state == "faulted", job.state
        # The snapshot (the suspended payload) came back — the retry
        # will NOT start from scratch.
        assert job.payload is not None
        assert job.payload["unique_count"] == mid_unique
        # Slice 3: the retry completes exactly.
        job.not_before = None
        svc._run_slice(job)
        assert job.state == "done", (job.state, job.error)
        assert job.result["unique"] == 1568
        assert job.retries == 1
    finally:
        svc.close()


def test_quarantine_after_exhausted_retries():
    svc = _service()
    try:
        with inject(FaultSpec("device.wave", at=0, count=10 ** 6)):
            h = svc.submit(
                model_name="2pc", model_args={"rm_count": 3},
                retry_policy=RetryPolicy(max_retries=1, backoff_s=0.01),
            )
            with pytest.raises(RuntimeError, match="quarantined"):
                h.result(timeout=300)
        st = h.status()
        assert st["state"] == "quarantined"
        assert st["retries"] == 1
        assert len(st["faults"]) == 2
        # The flight dump carries the forensics: history + traceback.
        assert st["flight"]["fault_class"] == "device_wave"
        assert "DeviceWaveFault" in st["flight"]["traceback"]
        assert st["error_traceback"] is not None
    finally:
        svc.close()


def test_no_retry_policy_fails_first_fault_with_traceback():
    svc = _service(retry_policy=None)
    try:
        with inject(FaultSpec("device.wave", at=0)):
            h = svc.submit(model_name="2pc", model_args={"rm_count": 3})
            with pytest.raises(RuntimeError, match="failed"):
                h.result(timeout=300)
        st = h.status()
        assert st["state"] == "failed"
        assert st["retries"] == 0
        # Satellite: the formatted traceback (not just repr) survives
        # into the status/HTTP view and the flight dump.
        assert "DeviceWaveFault" in st["error_traceback"]
        assert "Traceback" in st["error_traceback"]
        assert st["flight"]["traceback"] == st["error_traceback"]
    finally:
        svc.close()


# -- pack-local blast radius -------------------------------------------------


def test_pack_fault_blast_radius_is_one_tenant(baseline):
    """4 packed tenants, one injected per-tenant verdict fault: the 3
    survivors complete with ZERO preemptions, the faulted tenant is
    lane-dropped with its rolled-back payload slice and its solo retry
    matches the solo baseline bit-identically."""
    svc = _service()
    try:
        with inject(
            FaultSpec("pack.tenant.verdict", tenant="blast-2", at=0)
        ) as inj:
            handles = {
                jid: svc.submit(
                    model_name="2pc", model_args={"rm_count": 3},
                    job_id=jid,
                )
                for jid in (
                    "blast-0", "blast-1", "blast-2", "blast-3"
                )
            }
            results = {
                jid: h.result(timeout=300)
                for jid, h in handles.items()
            }
        assert inj.triggered() == 1
        for jid, r in results.items():
            assert r["unique"] == baseline["unique"], jid
            assert _golden(r["report"]) == _golden(baseline["report"])
        stats = {jid: h.status() for jid, h in handles.items()}
        faulted = stats.pop("blast-2")
        assert faulted["retries"] == 1
        assert faulted["faults"][0]["class"] == "pack_tenant"
        for jid, st in stats.items():
            # Survivors never preempted, never faulted — the blast
            # radius was exactly the faulted tenant.
            assert st["preempts"] == 0, (jid, st)
            assert st["retries"] == 0 and not st["faults"], (jid, st)
            assert st["packed"] is True, jid
    finally:
        svc.close()


def test_two_tenants_faulting_same_wave_both_drop_no_livelock(baseline):
    """Regression (review finding): when one wave faults SEVERAL
    tenants, every flagged tenant must be rolled back and dropped —
    leaving one resident would exclude it from scheduling while still
    counting it live, spinning the pack loop forever. Both faulted
    tenants retry, the survivor finishes untouched."""
    svc = _service()
    try:
        with inject(
            FaultSpec("pack.tenant.verdict", tenant="multi-0", at=0),
            FaultSpec("pack.tenant.verdict", tenant="multi-1", at=0),
        ) as inj:
            handles = {
                jid: svc.submit(
                    model_name="2pc", model_args={"rm_count": 3},
                    job_id=jid,
                )
                for jid in ("multi-0", "multi-1", "multi-2")
            }
            results = {
                jid: h.result(timeout=120)
                for jid, h in handles.items()
            }
        assert inj.triggered() == 2
        for jid, r in results.items():
            assert r["unique"] == baseline["unique"], jid
            assert _golden(r["report"]) == _golden(baseline["report"])
        stats = {jid: h.status() for jid, h in handles.items()}
        assert stats["multi-0"]["retries"] == 1
        assert stats["multi-1"]["retries"] == 1
        assert stats["multi-2"]["retries"] == 0
        assert stats["multi-2"]["preempts"] == 0
    finally:
        svc.close()


def test_pack_engine_fault_retries_all_members_solo(baseline):
    """A non-attributable engine fault (device wave raise under the
    shared dispatch) suspends every member and retries them solo — no
    job is failed, every verdict stays exact."""
    svc = _service()
    try:
        with inject(FaultSpec("device.wave", at=1)) as inj:
            handles = [
                svc.submit(model_name="2pc", model_args={"rm_count": 3})
                for _ in range(2)
            ]
            results = [h.result(timeout=300) for h in handles]
        assert inj.triggered() == 1
        for r in results:
            assert r["unique"] == baseline["unique"]
            assert _golden(r["report"]) == _golden(baseline["report"])
        # At least one member rode the fault->solo-retry path.
        assert any(h.status()["retries"] >= 1 for h in handles)
        for h in handles:
            st = h.status()
            if st["retries"]:
                assert st["packable"] is False
                assert "solo" in st["packable_reason"]
    finally:
        svc.close()


# -- durable recovery --------------------------------------------------------


def test_durable_recovery_resumes_bit_identical(baseline, tmp_path):
    """Kill-and-recover: a suspended zoo job's durable checkpoint +
    journal rebuild the queue after a 'crash' (close + fresh service),
    the finished-job record is reconstructed, an unjournalable job is
    surfaced durable:false, and the resumed job's verdict is
    bit-identical."""
    d = str(tmp_path / "svc")
    svc = _service(service_dir=d)
    try:
        done = svc.submit(
            model_name="2pc", model_args={"rm_count": 3},
            job_id="rec-done",
        )
        r_done = done.result(timeout=300)
        assert svc.job("rec-done").durable is True
        # Non-journalable: a custom model instance.
        custom = svc.submit(model=TwoPhaseSys(3), job_id="rec-custom")
        assert custom.status()["durable"] is False
        custom.result(timeout=300)
        # A job interrupted mid-run: close() preempts and flushes its
        # durable checkpoint.
        mid = svc.submit(
            model_name="2pc", model_args={"rm_count": 4},
            job_id="rec-mid",
        )
        deadline = time.monotonic() + 60
        while (
            svc.job("rec-mid").state == "queued"
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        time.sleep(0.5)
    finally:
        out = svc.close()
    assert out["closed"] is True
    assert svc.job("rec-mid").state == "suspended"
    assert os.path.exists(os.path.join(d, "jobs", "rec-mid.ckpt"))

    svc2 = CheckService.recover(
        d, quantum_s=5.0, default_spawn=dict(SPAWN_2PC)
    )
    try:
        # Finished-job record reconstructed (bit-identity evidence
        # included).
        j_done = svc2.job("rec-done")
        assert j_done.state == "done"
        assert j_done.result["unique"] == r_done["unique"]
        assert _golden(j_done.result["report"]) == _golden(
            r_done["report"]
        )
        # The resumed job completes from its last durable checkpoint.
        r_mid = JobHandle(svc2.job("rec-mid"), svc2).result(timeout=300)
        assert r_mid["unique"] == 1568
    finally:
        svc2.close()
    # Bit-identity of the recovered run vs a fault-free one.
    svc3 = _service()
    try:
        rb = svc3.submit(
            model_name="2pc", model_args={"rm_count": 4}
        ).result(timeout=300)
    finally:
        svc3.close()
    assert r_mid["states"] == rb["states"]
    assert r_mid["max_depth"] == rb["max_depth"]
    assert _golden(r_mid["report"]) == _golden(rb["report"])


def test_recover_surfaces_lost_nondurable_job(tmp_path):
    """An UNFINISHED durable:false job must come back as an honest
    failed record, not vanish."""
    import json

    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "jobs"), exist_ok=True)
    with open(os.path.join(d, "journal.jsonl"), "w") as f:
        f.write(json.dumps({
            "ev": "submit", "t": 0.0, "job_id": "lost-1",
            "durable": False, "spec": None,
        }) + "\n")
    svc = CheckService.recover(d, default_spawn=dict(SPAWN_2PC))
    try:
        j = svc.job("lost-1")
        assert j is not None and j.state == "failed"
        assert "durable: false" in j.error
    finally:
        svc.close()


# -- graceful degradation ----------------------------------------------------


def test_recover_bypasses_admission_bound(tmp_path):
    """Regression (review finding): replaying more journaled jobs than
    max_queued_jobs must not abort recovery with QueueFullError — the
    jobs were already admitted before the crash."""
    import json

    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "jobs"), exist_ok=True)
    with open(os.path.join(d, "journal.jsonl"), "w") as f:
        for i in range(3):
            f.write(json.dumps({
                "ev": "submit", "t": 0.0, "job_id": f"rb-{i}",
                "durable": True,
                "spec": {"model_name": "2pc",
                         "model_args": {"rm_count": 3}},
            }) + "\n")
    svc = CheckService.recover(
        d, max_queued_jobs=1, default_spawn=dict(SPAWN_2PC),
        quantum_s=5.0,
    )
    try:
        for i in range(3):
            job = svc.job(f"rb-{i}")
            assert job is not None and job.state != "failed", (
                i, job and job.error
            )
        # The bound still applies to NEW submissions.
        with pytest.raises(QueueFullError):
            svc.submit(model_name="2pc", model_args={"rm_count": 3})
        for i in range(3):
            JobHandle(svc.job(f"rb-{i}"), svc).cancel()
    finally:
        svc.close()


def test_timeout_on_nonpreemptible_backend_keeps_finished_verdict():
    """Regression (review finding): a non-preemptible slice that blows
    its timeout but RUNS TO COMPLETION keeps its verdict — the deadline
    could not cut the slice, and discarding a finished result would
    make the outcome depend on preempt-attempt ordering."""
    svc = CheckService(
        quantum_s=30.0, packing=False, spawn_method="spawn_bfs",
        default_spawn={},
    )
    try:
        h = svc.submit(
            model_name="2pc", model_args={"rm_count": 3},
            timeout_s=0.001,
        )
        r = h.result(timeout=300)
        assert r["unique"] == UNIQUE_2PC3
        assert h.status()["state"] == "done"
        assert h.status()["preemptible"] is False
    finally:
        svc.close()


def test_bounded_admission_queue():
    svc = _service(max_queued_jobs=2, quantum_s=0.5)
    try:
        h1 = svc.submit(model_name="2pc", model_args={"rm_count": 4})
        h2 = svc.submit(model_name="2pc", model_args={"rm_count": 4})
        with pytest.raises(QueueFullError, match="queue full"):
            svc.submit(model_name="2pc", model_args={"rm_count": 4})
        h1.cancel()
        h2.cancel()
    finally:
        svc.close()


def test_timeout_fails_with_partial_progress_evidence():
    svc = _service(packing=False, quantum_s=30.0)
    try:
        h = svc.submit(
            model_name="2pc", model_args={"rm_count": 5}, timeout_s=1.0
        )
        with pytest.raises(RuntimeError, match="timeout"):
            h.result(timeout=300)
        st = h.status()
        assert st["state"] == "failed"
        flight = st["flight"]
        assert flight["reason"] == "timeout"
        # Partial-progress evidence: the digest shows how far it got.
        assert flight["partial_progress"] is not None
        assert flight["partial_progress"]["unique_state_count"] > 0
    finally:
        svc.close()


# -- pipeline poison hygiene (satellite) -------------------------------------


def test_pipeline_poison_typed_error_no_hang_no_held_lock():
    """Injected worker death: the checker surfaces a typed
    PipelinePoisonedError carrying the original exception, the
    close/drain path terminates (no hang), and the tiered store's
    RLock is released."""
    budget = min_admissible_hbm_budget_mib(TwoPhaseSys(3), 16)
    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16, table_capacity=1 << 12,
            hbm_budget_mib=budget, async_pipeline=True,
            aot_cache="t-flt",
        )
    )
    with inject(FaultSpec("pipeline.worker", at=1)) as inj:
        for t in checker.handles():
            t.join(timeout=180)
            assert not t.is_alive(), "worker hung after poisoning"
    assert inj.triggered() == 1
    err = checker.worker_error()
    assert err is not None
    # Typed poison with the original worker exception in the chain.
    chain = []
    e = err
    while e is not None:
        chain.append(e)
        e = e.__cause__ or e.__context__
    assert any(isinstance(x, PipelinePoisonedError) for x in chain), chain
    assert classify_fault(err) == "pipeline_worker"
    poisoned = next(
        x for x in chain if isinstance(x, PipelinePoisonedError)
    )
    assert poisoned.cause is not None
    # The tiered store's merge fence is NOT left held.
    assert checker._tier._fence.acquire(timeout=2.0)
    checker._tier._fence.release()
    # The pipeline worker thread exited (close() ran, did not hang).
    assert not checker._pipe._thread.is_alive()


def test_fault_metric_families_are_hygiene_clean():
    """fault.* / retry.* / service.* families (dynamic per-class and
    per-site names included) export as distinct, grammar-legal
    Prometheus series."""
    from stateright_tpu.telemetry import metrics_registry
    from stateright_tpu.telemetry.server import registry_hygiene_problems

    reg = metrics_registry()
    # Ensure the dynamic names exist even if no chaos test ran first.
    for cls in ("host_probe", "spill", "pipeline_worker", "device_wave",
                "checkpoint_write", "pack_tenant", "unknown"):
        reg.counter(f"fault.by_class.{cls}")
    for site in ("storage.host_probe", "storage.spill", "device.wave"):
        reg.counter(f"fault.injected.{site}")
    reg.counter("service.recovery.jobs_resumed")
    problems = [
        p
        for p in registry_hygiene_problems(reg)
        if "fault" in p or "retry" in p or "service" in p
    ]
    assert problems == []


def test_close_reports_stuck_scheduler():
    """close(timeout=) must detect a scheduler that failed to join and
    say so (return value + service.close.stuck metric) instead of
    pretending the close succeeded."""
    from stateright_tpu.telemetry import metrics_registry

    svc = CheckService()
    # Park the real scheduler, then substitute a wedged stand-in.
    svc._closing.set()
    svc._wake()
    svc._scheduler.join(timeout=30)
    release = threading.Event()
    svc._scheduler = threading.Thread(target=release.wait, daemon=True)
    svc._scheduler.start()
    before = metrics_registry().snapshot().get("service.close.stuck", 0)
    out = svc.close(timeout=0.1)
    assert out == {"closed": False, "stuck": True}
    after = metrics_registry().snapshot().get("service.close.stuck", 0)
    assert after == before + 1
    release.set()


# -- device-liveness edge store under adversity (ISSUE 14) -------------------


def _live_graph():
    """A lasso-bearing graph with enough edges to force mid-run edge
    evictions under a tiny device store."""
    from test_device_liveness import PackedDGraph

    return PackedDGraph(
        [2 * i for i in range(24)] + [2],  # long even chain closing a cycle
        [0, 46],
    )


_LIVE_SPAWN = {
    "frontier_capacity": 16,
    "table_capacity": 1 << 10,
    "liveness": "device",
    # Minimum legal capacity (F·(A+1) rows): every couple of waves
    # evicts, so the injected fault lands MID-eviction, mid-run.
    "edge_log_capacity": 64,
    "aot_cache": "t-flt-live",
}


@pytest.fixture(scope="module")
def live_baseline():
    svc = _service()
    try:
        r = svc.submit(_live_graph, spawn=dict(_LIVE_SPAWN)).result(
            timeout=300
        )
    finally:
        svc.close()
    assert r["liveness"]["mode"] == "device"
    assert "odd" in r["discoveries"]
    return r


def test_liveness_edge_evict_fault_retries_bit_identical(live_baseline):
    """A fault mid-edge-eviction (the liveness.edge_evict seam inside
    LivenessEdgeStore.absorb) faults the slice; the checkpointed retry
    recovers and the device-liveness verdict is bit-identical to the
    fault-free run — a dropped edge store must never decay into a
    silent 'absence'."""
    svc = _service()
    try:
        with inject(FaultSpec("liveness.edge_evict", at=1)) as inj:
            h = svc.submit(_live_graph, spawn=dict(_LIVE_SPAWN))
            r = h.result(timeout=300)
        assert inj.triggered() == 1
        st = h.status()
        assert st["retries"] == 1
        assert st["faults"][0]["class"] == "liveness_evict"
        assert st["liveness_mode"] == "device"
        assert r["unique"] == live_baseline["unique"]
        assert set(r["discoveries"]) == set(live_baseline["discoveries"])
        assert (
            r["liveness"]["outcomes"]["odd"]["verdict"] == "counterexample"
        )
        assert _golden(r["report"]) == _golden(live_baseline["report"])
    finally:
        svc.close()


def test_liveness_survives_stall_preempt_resume(live_baseline):
    """Preempt mid-exploration (stall-watchdog auto-preempt), resume:
    the edge log rides the v3 payload intact and the resumed run's
    device verdict matches the uninterrupted one exactly."""
    svc = _service(packing=False, stall_deadline_s=0.3, quantum_s=30.0)
    try:
        with inject(FaultSpec("wave.stall", at=2, stall_s=1.2)) as inj:
            h = svc.submit(_live_graph, spawn=dict(_LIVE_SPAWN))
            r = h.result(timeout=300)
        assert inj.triggered() == 1
        st = h.status()
        assert st["stall_preempts"] == 1
        assert st["preempts"] >= 1
        assert r["unique"] == live_baseline["unique"]
        assert set(r["discoveries"]) == set(live_baseline["discoveries"])
        # The edge relation accumulated across BOTH incarnations (the
        # resumed store starts from the payload, not from scratch).
        assert (
            r["liveness"]["edge_store"]["edges_logged"]
            >= live_baseline["liveness"]["edge_store"]["edges_logged"]
        )
        assert _golden(r["report"]) == _golden(live_baseline["report"])
    finally:
        svc.close()


def test_liveness_metric_family_is_hygiene_clean():
    from stateright_tpu.telemetry import metrics_registry
    from stateright_tpu.telemetry.server import registry_hygiene_problems

    reg = metrics_registry()
    reg.counter("fault.by_class.liveness_evict")
    reg.counter("fault.injected.liveness.edge_evict")
    reg.counter("liveness.inconclusive")
    reg.counter("liveness.skipped_crashed_run")
    problems = [
        p for p in registry_hygiene_problems(reg) if "liveness" in p
    ]
    assert problems == []
