"""Telemetry layer tests: instrument semantics, trace shape, golden
reporter strings, end-to-end smoke with a JSONL sink, and the always-on
overhead budget."""

import io
import json
import os
import subprocess
import sys
import time

import pytest

from fixtures import LinearEquation
from stateright_tpu import TelemetryReporter, WriteReporter, fingerprint
from stateright_tpu.telemetry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Tracer,
    chrome_trace,
    chrome_trace_from_jsonl,
    get_tracer,
    metrics_registry,
)

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- metrics registry ------------------------------------------------------


def test_counter_semantics():
    c = Counter("c")
    assert c.snapshot() == 0
    c.inc()
    c.inc(41)
    assert c.snapshot() == 42


def test_gauge_semantics():
    g = Gauge("g")
    assert g.snapshot() is None
    g.set(7)
    g.set(3.5)
    assert g.snapshot() == 3.5


def test_histogram_log2_buckets_and_stats():
    h = Histogram("h")
    for v in (1, 2, 3, 4, 1024):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["sum"] == 1034
    assert snap["min"] == 1 and snap["max"] == 1024
    assert snap["mean"] == pytest.approx(1034 / 5)
    buckets = snap["buckets_log2"]
    # 1 -> bucket 0 ((0,1]); 2 -> bucket 1; 3,4 -> bucket 2 ((2,4]);
    # 1024 = 2^10 -> bucket 10. Trailing empties elided.
    assert len(buckets) == 11
    assert buckets[0] == 1 and buckets[1] == 1 and buckets[2] == 2
    assert buckets[10] == 1


def test_histogram_nonpositive_lands_in_bucket_zero():
    h = Histogram("h")
    h.observe(0)
    h.observe(-3)
    assert h.snapshot()["buckets_log2"] == [2]


def test_registry_get_or_create_is_stable_and_kind_checked():
    reg = MetricsRegistry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")
    reg.gauge("g").set(1)
    reg.histogram("h").observe(2)
    snap = reg.snapshot()
    assert snap["x"] == 0
    assert snap["g"] == 1
    assert snap["h"]["count"] == 1
    assert list(snap) == sorted(snap)


def test_default_registry_is_process_local_singleton():
    assert metrics_registry() is metrics_registry()


# -- tracer ----------------------------------------------------------------


def test_span_records_complete_event_with_args():
    tracer = Tracer()
    with tracer.span("work", a=1) as sp:
        sp.set(b=2)
    (ev,) = tracer.events()
    assert ev["name"] == "work"
    assert ev["ph"] == "X"
    assert ev["args"] == {"a": 1, "b": 2}
    assert ev["dur"] >= 0
    assert isinstance(ev["ts"], float)


def test_instant_and_ring_capacity():
    tracer = Tracer(ring_capacity=3)
    for i in range(5):
        tracer.instant("tick", i=i)
    events = tracer.events()
    assert len(events) == 3
    assert [e["args"]["i"] for e in events] == [2, 3, 4]
    assert all(e["ph"] == "i" for e in events)


def test_disabled_tracer_emits_nothing():
    tracer = Tracer()
    tracer.enabled = False
    with tracer.span("work") as sp:
        sp.set(a=1)
    tracer.instant("tick")
    assert tracer.events() == []


def test_jsonl_sink_and_chrome_export(tmp_path):
    tracer = Tracer()
    path = tmp_path / "trace.jsonl"
    sink = tracer.add_sink(str(path))
    with tracer.span("outer", n=1):
        tracer.instant("inner")
    tracer.remove_sink(sink)
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 2
    parsed = [json.loads(line) for line in lines]
    # Span closes after the instant, so the instant lands first.
    assert [p["name"] for p in parsed] == ["inner", "outer"]

    trace = chrome_trace_from_jsonl(str(path))
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    assert len(trace["traceEvents"]) == 2
    span_ev = trace["traceEvents"][1]
    assert span_ev["ph"] == "X" and "dur" in span_ev and "ts" in span_ev
    assert span_ev["pid"] and span_ev["tid"]


def test_chrome_trace_from_jsonl_skips_partial_tail(tmp_path):
    path = tmp_path / "trace.jsonl"
    path.write_text('{"name": "ok", "ph": "i", "ts": 1}\n{"name": "tru')
    assert len(chrome_trace_from_jsonl(str(path))["traceEvents"]) == 1


def test_chrome_trace_wraps_default_ring():
    trace = chrome_trace([{"name": "e", "ph": "i", "ts": 0}])
    assert trace["traceEvents"][0]["name"] == "e"


# -- reporter golden strings ----------------------------------------------


def _golden_output(checker):
    out = io.StringIO()
    checker.report(WriteReporter(out))
    return out.getvalue()


def _expected_solvable_tail():
    fp = fingerprint
    expected_path = "/".join(
        str(fp(s)) for s in [(0, 0), (1, 0), (2, 0), (2, 1)]
    )
    return (
        'Discovered "solvable" example Path[3]:\n'
        "- 'IncreaseX'\n"
        "- 'IncreaseX'\n"
        "- 'IncreaseY'\n"
        f"Fingerprint path: {expected_path}\n"
    )


def test_write_reporter_strings_unchanged_with_telemetry_sink(tmp_path):
    """The golden compatibility strings must be byte-identical with a
    trace sink attached and metrics flowing."""
    sink = get_tracer().add_sink(str(tmp_path / "t.jsonl"))
    try:
        checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
        output = _golden_output(checker)
    finally:
        get_tracer().remove_sink(sink)
    assert output.startswith("Done. states=15, unique=12, depth=4, sec=")
    assert output.endswith(_expected_solvable_tail())
    # The sink really was live during the run.
    assert (tmp_path / "t.jsonl").read_text().strip()


def test_telemetry_reporter_wraps_without_altering_inner(tmp_path):
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    plain = io.StringIO()
    checker.report(WriteReporter(plain))

    wrapped = io.StringIO()
    checker.report(
        TelemetryReporter(wrapped, inner=WriteReporter(wrapped))
    )
    wrapped_out = wrapped.getvalue()
    telemetry_at = wrapped_out.index("Telemetry ")
    inner_part = (
        wrapped_out[:telemetry_at]
        + wrapped_out[wrapped_out.index("\n", telemetry_at) + 1 :]
    )
    # Inner WriteReporter output byte-identical modulo the sec= field
    # (wall clock differs between the two report() calls).
    import re

    strip_sec = lambda s: re.sub(r"sec=\d+", "sec=_", s)  # noqa: E731
    assert strip_sec(inner_part) == strip_sec(plain.getvalue())
    telemetry_line = wrapped_out[telemetry_at:].splitlines()[0]
    snap = json.loads(telemetry_line[len("Telemetry ") :])
    assert snap["bfs.blocks"] >= 1


def test_checker_metrics_accessor():
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    assert checker.metrics() is metrics_registry()
    assert checker.metrics().snapshot()["bfs.states_generated"] >= 1


# -- end-to-end smoke: CPU BFS with tracing on ----------------------------


def test_smoke_host_bfs_trace_parses(tmp_path):
    """Tiny CPU BFS with the JSONL sink attached: the file parses, the
    Chrome export loads, and scripts/trace_summary.py renders it."""
    path = tmp_path / "bfs.jsonl"
    sink = get_tracer().add_sink(str(path))
    try:
        LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    finally:
        get_tracer().remove_sink(sink)
    events = chrome_trace_from_jsonl(str(path))["traceEvents"]
    blocks = [e for e in events if e["name"] == "bfs.block"]
    assert blocks, "host BFS must emit at least one block span"
    assert blocks[-1]["args"]["generated"] >= 1

    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_DIR, "scripts", "trace_summary.py"),
            str(path),
            "--chrome-out",
            str(tmp_path / "bfs.chrome.json"),
        ],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert r.returncode == 0, r.stderr
    with open(tmp_path / "bfs.chrome.json") as f:
        assert json.load(f)["traceEvents"]


def test_smoke_tpu_bfs_wave_spans(tmp_path):
    """The device checker (CPU backend) must emit ≥1 span per BFS wave
    carrying frontier-size, dedup-hit-rate, and occupancy args — the
    acceptance shape for every future perf judgment."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    path = tmp_path / "tpu.jsonl"
    sink = get_tracer().add_sink(str(path))
    try:
        checker = (
            TwoPhaseSys(2)
            .checker()
            .spawn_tpu_bfs(
                frontier_capacity=1 << 6,
                table_capacity=1 << 10,
                max_drain_waves=1,  # wave-at-a-time: one span per wave
            )
            .join()
        )
    finally:
        get_tracer().remove_sink(sink)
    assert checker.unique_state_count() == 56

    events = chrome_trace_from_jsonl(str(path))["traceEvents"]
    waves = [e for e in events if e["name"] == "tpu_bfs.wave"]
    # 2pc-2 BFS has several levels; each must have produced a wave span.
    assert len(waves) >= 3
    for ev in waves:
        args = ev["args"]
        assert args["frontier"] >= 1
        assert 0.0 <= args["dedup_hit_rate"] <= 1.0
        assert 0.0 <= args["occupancy"] <= 1.0
        assert "new_unique" in args and "max_depth" in args
    # The summary table renders wave rows for these spans.
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_DIR, "scripts", "trace_summary.py"),
            str(path),
        ],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "tpu_bfs.wave" in r.stdout

    reg = metrics_registry().snapshot()
    assert reg["tpu_bfs.waves"] >= len(waves)
    assert reg["tpu_bfs.hashset_occupancy"] > 0


# -- always-on overhead budget --------------------------------------------


def test_no_sink_overhead_under_budget():
    """The no-sink fast path must add <5% to a small host BFS run so the
    layer can stay always-on.

    Measured as (per-block instrumentation cost × blocks the run
    actually emitted) against the run's wall time. Direct wall-clock A/B
    of sub-second runs on this shared box swings ±20% run-to-run —
    far above the 5% budget being asserted — while the per-event cost
    over 10k iterations is stable, so this form bounds the same quantity
    without the flake (measured headroom is ~100x, not marginal)."""
    tracer = get_tracer()
    assert tracer.enabled
    reg = metrics_registry()
    blocks_before = reg.counter("bfs.blocks").snapshot()

    t0 = time.perf_counter()
    LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    run_secs = time.perf_counter() - t0
    blocks = reg.counter("bfs.blocks").snapshot() - blocks_before
    assert blocks >= 1

    # One iteration = one block's full instrumentation: the span with its
    # late-bound args plus the counter/histogram bumps bfs._check_block
    # performs.
    c1, c2, c3 = (
        reg.counter("telemetry_bench.a"),
        reg.counter("telemetry_bench.b"),
        reg.counter("telemetry_bench.c"),
    )
    h = reg.histogram("telemetry_bench.h")
    n = 10_000
    t0 = time.perf_counter()
    for i in range(n):
        with tracer.span("telemetry_bench.block") as sp:
            c1.inc()
            c2.inc(1500)
            c3.inc(3000)
            h.observe(1500)
            sp.set(evaluated=1500, generated=3000, max_depth=i,
                   unique_total=i)
    per_block = (time.perf_counter() - t0) / n
    tracer.clear()  # drop the bench spam from the ring buffer

    overhead = per_block * blocks
    assert overhead < 0.05 * run_secs, (
        f"always-on telemetry overhead too high: {blocks} blocks x "
        f"{per_block * 1e6:.1f}us = {overhead * 1e3:.2f}ms on a "
        f"{run_secs * 1e3:.0f}ms run"
    )
