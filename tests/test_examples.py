"""Example-model tests asserting the reference's exact state-space oracles:
2pc 288/8,832/665, paxos 16,668, ABD 544, single-copy 93.

Reference tests: examples/2pc.rs:151-170, paxos.rs:298-349,
linearizable-register.rs:260-313, single-copy-register.rs:89-135,
increment.rs, increment_lock.rs.
"""

import pytest

from stateright_tpu.actor import DeliverAction, Id, Network
from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
from stateright_tpu.models.increment import Increment, IncrementLock
from stateright_tpu.models.linearizable_register import AbdModelCfg
from stateright_tpu.models.paxos import PaxosModelCfg
from stateright_tpu.models.single_copy_register import SingleCopyModelCfg
from stateright_tpu.models.timers import PingerModelCfg
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


class Test2pc:
    def test_small_bfs(self):
        checker = TwoPhaseSys(3).checker().spawn_bfs().join()
        assert checker.unique_state_count() == 288
        checker.assert_properties()

    def test_larger_dfs(self):
        checker = TwoPhaseSys(5).checker().spawn_dfs().join()
        assert checker.unique_state_count() == 8832
        checker.assert_properties()

    def test_larger_with_symmetry(self):
        checker = TwoPhaseSys(5).checker().symmetry().spawn_dfs().join()
        assert checker.unique_state_count() == 665
        checker.assert_properties()


class TestIncrement:
    def test_finds_lost_update_race(self):
        checker = Increment(2).checker().spawn_bfs().join()
        assert checker.discovery("fin") is not None

    def test_symmetry_reduction_reduces(self):
        # The reference doc walks the 13 -> 8 state reduction for 2 threads
        # (increment.rs:36-105). Force full traversal with a never-failing
        # property ("fin" is falsifiable, which would early-exit the checker).
        from stateright_tpu import Property

        class Full(Increment):
            def properties(self):
                return [Property.always("true", lambda _m, _s: True)]

        assert Full(2).checker().spawn_dfs().join().unique_state_count() == 13
        assert (
            Full(2).checker().symmetry().spawn_dfs().join().unique_state_count()
            == 8
        )

    def test_lock_holds_properties(self):
        checker = IncrementLock(2).checker().spawn_dfs().join()
        checker.assert_properties()

    def test_lock_4_threads(self):
        checker = IncrementLock(4).checker().threads(2).spawn_dfs().join()
        checker.assert_properties()


class TestPaxos:
    @pytest.mark.slow
    def test_oracle_count_and_discovery(self):
        checker = (
            PaxosModelCfg(
                client_count=2,
                server_count=3,
                network=Network.new_unordered_nonduplicating(),
            )
            .into_model()
            .checker()
            .spawn_bfs()
            .join()
        )
        checker.assert_properties()
        checker.assert_discovery(
            "value chosen",
            [
                DeliverAction(src=Id(4), dst=Id(1), msg=Put(4, "B")),
                DeliverAction(src=Id(1), dst=Id(0), msg=Internal(("Prepare", (1, Id(1))))),
                DeliverAction(src=Id(0), dst=Id(1), msg=Internal(("Prepared", (1, Id(1)), None))),
                DeliverAction(src=Id(1), dst=Id(2), msg=Internal(("Accept", (1, Id(1)), (4, Id(4), "B")))),
                DeliverAction(src=Id(2), dst=Id(1), msg=Internal(("Accepted", (1, Id(1))))),
                DeliverAction(src=Id(1), dst=Id(4), msg=PutOk(4)),
                DeliverAction(src=Id(1), dst=Id(2), msg=Internal(("Decided", (1, Id(1)), (4, Id(4), "B")))),
                DeliverAction(src=Id(4), dst=Id(2), msg=Get(8)),
            ],
        )
        assert checker.unique_state_count() == 16668


class TestAbd:
    def test_oracle_count(self):
        checker = (
            AbdModelCfg(
                client_count=2,
                server_count=2,
                network=Network.new_unordered_nonduplicating(),
            )
            .into_model()
            .checker()
            .spawn_bfs()
            .join()
        )
        checker.assert_properties()
        assert checker.unique_state_count() == 544


class TestSingleCopy:
    def test_one_server_is_linearizable(self):
        checker = (
            SingleCopyModelCfg(
                client_count=2,
                server_count=1,
                network=Network.new_unordered_nonduplicating(),
            )
            .into_model()
            .checker()
            .spawn_dfs()
            .join()
        )
        checker.assert_properties()
        checker.assert_discovery(
            "value chosen",
            [
                DeliverAction(src=Id(2), dst=Id(0), msg=Put(2, "B")),
                DeliverAction(src=Id(0), dst=Id(2), msg=PutOk(2)),
                DeliverAction(src=Id(2), dst=Id(0), msg=Get(4)),
            ],
        )
        assert checker.unique_state_count() == 93

    def test_two_servers_not_linearizable(self):
        checker = (
            SingleCopyModelCfg(
                client_count=2,
                server_count=2,
                network=Network.new_unordered_nonduplicating(),
            )
            .into_model()
            .checker()
            .spawn_bfs()
            .join()
        )
        checker.assert_discovery(
            "linearizable",
            [
                DeliverAction(src=Id(3), dst=Id(1), msg=Put(3, "B")),
                DeliverAction(src=Id(1), dst=Id(3), msg=PutOk(3)),
                DeliverAction(src=Id(3), dst=Id(0), msg=Get(6)),
                DeliverAction(src=Id(0), dst=Id(3), msg=GetOk(6, "\x00")),
            ],
        )


class TestTimers:
    def test_bounded_exploration(self):
        checker = (
            PingerModelCfg(
                server_count=3, network=Network.new_unordered_nonduplicating()
            )
            .into_model()
            .checker()
            .target_max_depth(5)
            .spawn_bfs()
            .join()
        )
        assert checker.unique_state_count() > 10
        assert checker.max_depth() == 5


class TestIncrementDevice:
    """Device-path parity for the counter models (the last examples that
    were host-only): full-traversal counts, symmetry orbit counts, and the
    lost-update discovery all agree with the host checkers."""

    @staticmethod
    def _full(cls, n):
        from stateright_tpu import Property
        import jax.numpy as jnp

        class Full(cls):
            def properties(self):
                return [Property.always("true", lambda _m, _s: True)]

            def packed_conditions(self):
                return [lambda st: jnp.bool_(True)]

        return Full(n)

    def test_increment_device_count_parity(self):
        host = (
            self._full(Increment, 3).checker().spawn_bfs().join()
        )
        dev = (
            self._full(Increment, 3)
            .checker()
            .spawn_tpu_bfs(frontier_capacity=64, table_capacity=1 << 10)
            .join()
        )
        assert dev.worker_error() is None
        assert host.unique_state_count() == dev.unique_state_count()

    def test_increment_device_symmetry_orbits(self):
        host = (
            self._full(Increment, 2)
            .checker()
            .symmetry()
            .spawn_dfs()
            .join()
        )
        dev = (
            self._full(Increment, 2)
            .checker()
            .symmetry()
            .spawn_tpu_bfs(frontier_capacity=32, table_capacity=1 << 9)
            .join()
        )
        assert dev.worker_error() is None
        assert host.unique_state_count() == dev.unique_state_count() == 8

    def test_increment_device_finds_race_with_path(self):
        dev = (
            Increment(2)
            .checker()
            .spawn_tpu_bfs(frontier_capacity=32, table_capacity=1 << 9)
            .join()
        )
        assert dev.worker_error() is None
        path = dev.assert_any_discovery("fin")
        assert len(path.into_actions()) >= 1

    def test_increment_lock_device_holds_and_counts(self):
        host = (
            self._full(IncrementLock, 2).checker().spawn_bfs().join()
        )
        dev = (
            IncrementLock(2)
            .checker()
            .spawn_tpu_bfs(frontier_capacity=32, table_capacity=1 << 10)
            .join()
        )
        assert dev.worker_error() is None
        dev.assert_properties()
        assert dev.unique_state_count() == host.unique_state_count()
