"""Explorer tests: route handlers invoked directly, plus one HTTP round trip.

Mirrors the reference's strategy of testing handlers without a browser
(``/root/reference/src/checker/explorer.rs:322-593``)."""

import json
import urllib.request

import pytest

from fixtures import BinaryClock
from stateright_tpu.checker.explorer import (
    Snapshot,
    start_server,
    states_view,
    status_view,
)
from stateright_tpu.core.fingerprint import fingerprint
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def _on_demand(model):
    return model.checker().spawn_on_demand()


class TestViews:
    def test_states_view_lists_init_states(self):
        checker = _on_demand(BinaryClock())
        view = states_view(checker, [])
        assert view["path"] == ""
        assert len(view["next_steps"]) == 2
        outcomes = {s["outcome"] for s in view["next_steps"]}
        assert outcomes == {"0", "1"}
        for s in view["next_steps"]:
            assert s["action"] is None
            assert s["properties"][0]["status"] == "ok"

    def test_states_view_follows_fingerprints(self):
        checker = _on_demand(BinaryClock())
        fp0 = fingerprint(0)
        view = states_view(checker, [fp0])
        assert view["state"] == "0"
        (step,) = view["next_steps"]
        assert step["action"] == "'GoHigh'"  # default format_action is repr
        assert step["outcome"] == "1"
        assert step["fingerprint"] == str(fingerprint(1))

    def test_states_view_rejects_unknown_fingerprint(self):
        checker = _on_demand(BinaryClock())
        with pytest.raises(KeyError):
            states_view(checker, [123456789])

    def test_status_view_reports_properties_and_counts(self):
        checker = _on_demand(TwoPhaseSys(3))
        checker.run_to_completion()
        checker.join()
        view = status_view(checker)
        assert view["done"]
        assert view["unique_state_count"] == 288
        by_name = {p["name"]: p for p in view["properties"]}
        assert by_name["consistent"]["discovery"] is None  # always holds
        witness = by_name["commit agreement"]["discovery"]
        assert witness is not None
        assert witness["fingerprints"].count("/") >= 1

    def test_browsing_nudges_the_checker(self):
        checker = _on_demand(BinaryClock())
        assert checker.unique_state_count() <= 2
        states_view(checker, [fingerprint(0)])  # enumerates + nudges
        # BinaryClock's space is tiny; the nudge must not error and the
        # counters must stay coherent.
        assert checker.state_count() >= checker.unique_state_count() > 0

    def test_snapshot_keeps_first_path_per_window(self):
        snap = Snapshot(reset_seconds=3600)
        from stateright_tpu.core.path import Path

        p1 = Path([(0, "GoHigh"), (1, None)])
        p2 = Path([(1, "GoLow"), (0, None)])
        snap.visit(None, p1)
        snap.visit(None, p2)
        assert snap.recent_path() is p1


class TestHttp:
    def test_http_round_trip(self):
        server, checker = start_server(
            TwoPhaseSys(3).checker(), ("localhost", 0)
        )
        try:
            host, port = server.server_address[:2]
            base = f"http://{host}:{port}"

            def get(path):
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    return r.status, json.loads(r.read())

            code, status = get("/.status")
            assert code == 200
            assert {p["name"] for p in status["properties"]} == {
                "abort agreement",
                "commit agreement",
                "consistent",
            }

            code, init = get("/.states")
            assert code == 200
            (init_step,) = init["next_steps"]

            code, after = get("/.states/" + init_step["fingerprint"])
            assert code == 200
            assert len(after["next_steps"]) > 0

            req = urllib.request.Request(
                base + "/.runtocompletion", method="POST"
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                assert json.loads(r.read())["ok"]
            checker.join()
            code, done = get("/.status")
            assert done["unique_state_count"] == 288

            with urllib.request.urlopen(base + "/", timeout=10) as r:
                assert r.status == 200
                assert b"stateright_tpu explorer" in r.read()
        finally:
            server.shutdown()
