"""Raft leader election: exact-count oracles + property assertions.

The reference ships no Raft, so these counts are this framework's own
regression oracles (first measured from the host BFS checker, then pinned —
the same technique the reference uses for its examples, e.g.
``/root/reference/examples/2pc.rs:151-170``).
"""

import pytest

from stateright_tpu.actor import Network
from stateright_tpu.core.model import Expectation
from stateright_tpu.models.raft import LEADER, RaftModelCfg


def test_lossless_duplicating_counts():
    c = (
        RaftModelCfg(
            server_count=3,
            max_term=1,
            lossy=False,
            network=Network.new_unordered_duplicating(),
        )
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    assert c.unique_state_count() == 53
    assert c.max_depth() == 6


def test_lossy_duplicating_counts():
    c = (
        RaftModelCfg(
            server_count=3,
            max_term=1,
            lossy=True,
            network=Network.new_unordered_duplicating(),
        )
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    assert c.unique_state_count() == 2717


def test_lossy_nonduplicating_counts():
    c = (
        RaftModelCfg(server_count=3, max_term=1, lossy=True)
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    assert c.unique_state_count() == 665


def test_ordered_lossless_counts():
    c = (
        RaftModelCfg(
            server_count=3, max_term=1, lossy=False, network=Network.new_ordered()
        )
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    assert c.unique_state_count() == 341


def test_election_safety_holds_and_liveness_fails():
    c = (
        RaftModelCfg(server_count=3, max_term=1, lossy=True)
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    paths = c.discoveries()
    # Safety: no counterexample for "election safety".
    assert "election safety" not in paths
    # A leader is reachable…
    leader_path = paths["leader elected"]
    assert any(s.role == LEADER for s in leader_path.last_state().actor_states)
    # …but not guaranteed: adversarial schedules (message loss / split votes)
    # exhaust the term boundary leaderless, so "stable leader" yields an
    # eventually-counterexample whose final state has no leader.
    stuck = paths["stable leader"].last_state()
    assert not any(s.role == LEADER for s in stuck.actor_states)


def test_symmetry_reduction_shrinks_space_preserving_discoveries():
    full = (
        RaftModelCfg(
            server_count=3,
            max_term=1,
            lossy=True,
            network=Network.new_unordered_duplicating(),
        )
        .into_model()
        .checker()
        .spawn_dfs()
        .join()
    )
    reduced = (
        RaftModelCfg(
            server_count=3,
            max_term=1,
            lossy=True,
            network=Network.new_unordered_duplicating(),
        )
        .into_model()
        .checker()
        .symmetry()
        .spawn_dfs()
        .join()
    )
    assert full.unique_state_count() == 2717
    assert reduced.unique_state_count() == 621
    assert set(reduced.discoveries()) == {"leader elected", "stable leader"}


def test_single_node_cluster_elects_itself():
    c = (
        RaftModelCfg(server_count=1, max_term=1, lossy=False)
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    path = c.discoveries()["leader elected"]
    (state,) = [
        s for s in path.last_state().actor_states if s.role == LEADER
    ]
    assert state.term == 1


def test_crash_faults_preserve_election_safety():
    c = (
        RaftModelCfg(server_count=3, max_term=1, lossy=False, max_crashes=1)
        .into_model()
        .checker()
        .spawn_bfs()
        .join()
    )
    assert "election safety" not in c.discoveries()
