"""Occupancy-adaptive wave dispatch: bucketed/compacted checker must be
bit-identical to the fixed-width path.

Equivalence strategy: the bucket ladder only changes how many padding
lanes the expand grid carries — the dispatched live-lane sequence is
identical (ring pops and chunk compaction are stable, FIFO order is
preserved) — so unique/total counts, depths, discovery fingerprints, and
the golden WriteReporter strings must all match the ``bucket_ladder=0``
(fixed-width) dispatch exactly, for both the materializing and the
fingerprint-only (``expand_fps``) pipelines.
"""

import io
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stateright_tpu import WriteReporter
from stateright_tpu.checker.tpu import (
    _MIN_BUCKET,
    bucket_for,
    bucket_ladder_widths,
)
from stateright_tpu.models.linearizable_register import AbdModelCfg
from stateright_tpu.models.raft import RaftModelCfg
from stateright_tpu.models.single_copy_register import SingleCopyModelCfg
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.ops.hashset import hashset_new
from stateright_tpu.telemetry import metrics_registry


# -- ladder unit semantics -------------------------------------------------


def test_ladder_widths_descending_pow2():
    assert bucket_ladder_widths(2048, 4) == [2048, 1024, 512, 256, 128]
    assert bucket_ladder_widths(64, 4) == [64, 32, 16, 8]
    assert bucket_ladder_widths(64, 0) == [64]
    # The floor is one tile: rungs never go below _MIN_BUCKET.
    assert bucket_ladder_widths(16, 6) == [16, 8]
    assert min(bucket_ladder_widths(4096, 10)) >= _MIN_BUCKET


def test_bucket_for_picks_smallest_holding_rung():
    widths = [2048, 1024, 512, 256, 128]
    assert bucket_for(widths, 1) == 128
    assert bucket_for(widths, 128) == 128
    assert bucket_for(widths, 129) == 256
    assert bucket_for(widths, 1024) == 1024
    assert bucket_for(widths, 2048) == 2048
    # Beyond the widest rung: the widest rung is the cap.
    assert bucket_for(widths, 100_000) == 2048


# -- equivalence suite -----------------------------------------------------


def _golden(checker):
    out = io.StringIO()
    checker.report(WriteReporter(out))
    # The wall-clock field is the only permitted difference.
    return re.sub(r"sec=\d+", "sec=_", out.getvalue())


def _run_pair(model_fn, **kw):
    """Runs the same model bucketed (full ladder, forced — the default
    only auto-engages at production frontier sizes) and fixed-width;
    returns both finished checkers."""
    bucketed = (
        model_fn().checker().spawn_tpu_bfs(bucket_ladder=4, **kw).join()
    )
    fixed = (
        model_fn().checker().spawn_tpu_bfs(bucket_ladder=0, **kw).join()
    )
    assert bucketed.worker_error() is None
    assert fixed.worker_error() is None
    return bucketed, fixed


def _assert_identical(bucketed, fixed):
    assert bucketed.unique_state_count() == fixed.unique_state_count()
    assert bucketed.state_count() == fixed.state_count()
    assert bucketed.max_depth() == fixed.max_depth()
    assert bucketed._discoveries_fp == fixed._discoveries_fp
    assert _golden(bucketed) == _golden(fixed)


def test_bucketed_identical_2pc():
    """Materializing pipeline (2pc has no fps hooks), deep drain. Also
    asserts the bucketed run leaves the per-rung dispatch counters plus
    the compaction/fill gauges in the registry (the bench leg JSON reads
    them)."""
    metrics_registry().reset()
    b, f = _run_pair(
        lambda: TwoPhaseSys(3),
        frontier_capacity=64,
        table_capacity=1 << 10,
        drain_log_factor=1,  # frequent drain exits exercise rung changes
    )
    assert b.unique_state_count() == 288
    _assert_identical(b, f)
    snap = metrics_registry().snapshot()
    dispatch = {
        int(k.rsplit(".", 1)[1]): v
        for k, v in snap.items()
        if k.startswith("tpu_bfs.bucket_dispatch.")
    }
    assert dispatch, "bucketed run must record per-rung dispatch counts"
    assert all(w in bucket_ladder_widths(64, 4) for w in dispatch)
    assert 0.0 < snap["tpu_bfs.compaction_ratio"] <= 1.0
    assert 0.0 < snap["tpu_bfs.frontier_fill"] <= 1.0
    assert snap["tpu_bfs.wave_bucket"] in bucket_ladder_widths(64, 4)


def test_bucketed_identical_2pc_wave_at_a_time():
    """The chunk path (max_drain_waves=1) with per-chunk compaction."""
    b, f = _run_pair(
        lambda: TwoPhaseSys(3),
        frontier_capacity=64,
        table_capacity=1 << 10,
        max_drain_waves=1,
    )
    assert b.unique_state_count() == 288
    _assert_identical(b, f)


@pytest.mark.slow
@pytest.mark.parametrize("expand_fps", [None, False])
def test_bucketed_identical_abd(expand_fps):
    """ABD register (fps-capable): both the fingerprint-only wave
    (expand_fps=None resolves to on) and the forced materializing wave."""
    b, f = _run_pair(
        lambda: AbdModelCfg(2, 2).into_model(),
        frontier_capacity=256,
        table_capacity=1 << 13,
        drain_log_factor=1,
        expand_fps=expand_fps,
    )
    assert b.unique_state_count() == 544
    _assert_identical(b, f)


@pytest.mark.slow
def test_bucketed_identical_property_violation():
    """A property-violating model: the falsifiable ``stable leader``
    liveness property must be discovered at the SAME counterexample
    fingerprint (the golden reporter compares the replayed paths)."""
    b, f = _run_pair(
        lambda: RaftModelCfg(
            server_count=3, max_term=1, lossy=True
        ).into_model(),
        frontier_capacity=128,
        table_capacity=1 << 13,
        drain_log_factor=1,  # frequent drain exits exercise rung changes
    )
    assert "stable leader" in b._discoveries_fp
    _assert_identical(b, f)


def test_bucketed_identical_single_copy_fps():
    """Fast-lane coverage of the fingerprint-only pipeline: the 93-state
    single-copy register (fps-capable) at a tiny frontier; the slow lane
    re-checks fps on/off at scale on the ABD register."""
    b, f = _run_pair(
        lambda: SingleCopyModelCfg(2, 1).into_model(),
        frontier_capacity=64,
        table_capacity=1 << 10,
        drain_log_factor=1,
    )
    assert b.unique_state_count() == 93
    assert b._use_fps  # the pipeline under test really is the fps wave
    _assert_identical(b, f)


# -- dispatch overhead budget (tier-1 micro-benchmark) ---------------------


def test_bucket_dispatch_overhead_under_budget():
    """Bucket selection + compaction must stay under 5% of the
    fixed-width fused wave on a FULL frontier, so the adaptive dispatch
    can be always-on (mirror of the PR 3 telemetry overhead budget
    test).

    Measured as the per-dispatch cost the dispatcher actually pays on a
    full frontier — the live-count pull + ladder pick (it skips
    compaction when the widest rung is selected, asserted below) — plus
    the compaction gather charged at the widest rung it CAN run at
    (worst case over all dispatches), against the fused wave's own
    median. Both sides are median-of-iters in the same process, so box
    noise cancels instead of gating the assert (the wave does A× more
    work per lane than the compaction's single gather)."""
    model = TwoPhaseSys(5)
    checker = model.checker().spawn_tpu_bfs(
        frontier_capacity=512, table_capacity=1 << 14
    ).join()
    assert checker.worker_error() is None
    F = checker._F_max

    # A synthetic FULL frontier (every lane live) of real packed states.
    init = model.packed_init_states()
    states = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(
            x[:1], (F,) + x.shape[1:]
        ).astype(x.dtype),
        init,
    )
    hi, lo = jax.vmap(checker._fp_fn)(states)
    chunk = {
        "states": states,
        "hi": hi,
        "lo": lo,
        "ebits": jnp.zeros((F,), jnp.uint32),
        "depth": jnp.ones((F,), jnp.int32),
        "mask": jnp.ones((F,), bool),
    }

    # Full frontier selects the widest rung — the dispatcher never
    # compacts there (width == F_in skips _compact_chunk).
    assert bucket_for(checker._buckets, F) == F

    # Fixed-width wave reference: a fresh non-donating jit of the same
    # wave function (donation would consume the timed table).
    wave_fn = jax.jit(checker._wave)
    table = hashset_new(1 << 14)
    depth_cap = jnp.int32((1 << 31) - 1)
    args = (
        table, chunk["states"], chunk["hi"], chunk["lo"], chunk["ebits"],
        chunk["depth"], chunk["mask"], depth_cap,
    )
    jax.block_until_ready(wave_fn(*args))  # compile

    widest_compact = checker._buckets[1]  # widest rung compaction runs at

    def dispatch():
        # What _call_wave does before every full-frontier wave...
        live = int(np.asarray(chunk["mask"].sum()))
        assert bucket_for(checker._buckets, live) == F
        # ...plus the worst-case compaction of any bucketed dispatch
        # (the widest rung that actually compacts).
        jax.block_until_ready(
            checker._compact_chunk(chunk, widest_compact)
        )

    dispatch()  # compile the compaction

    def median_of(fn, iters=15):
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            fn()
            samples.append(time.perf_counter() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    wave_s = median_of(lambda: jax.block_until_ready(wave_fn(*args)))
    dispatch_s = median_of(dispatch)
    assert dispatch_s < 0.05 * wave_s, (
        f"bucket dispatch overhead too high: {dispatch_s * 1e3:.2f}ms vs "
        f"{wave_s * 1e3:.2f}ms fixed-width wave"
    )


# -- checkpoint/resume under donation (regression) -------------------------


def test_deep_drain_checkpoint_roundtrip_with_donation(tmp_path):
    """The ring-export/checkpoint path must keep NON-donated copies: a
    checkpoint written mid-run (the pool ring exported between donated
    drain calls) must resume to the exact full space. Guards the
    donation audit — a donated export would either crash (deleted
    buffer) or corrupt the resumed frontier. (The wave-at-a-time
    checkpoint flavor is covered by tests/test_checkpoint.py, which now
    also runs under donation.)"""
    ckpt = tmp_path / "bucketed_deep.ckpt"
    first = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        frontier_capacity=64,
        table_capacity=1 << 10,
        checkpoint_path=str(ckpt),
        checkpoint_every_chunks=2,  # caps waves-per-drain at 2
        drain_log_factor=1,
    ).join()
    assert first.worker_error() is None
    assert first.unique_state_count() == 1568
    assert ckpt.exists()
    resumed = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=64, resume_from=str(ckpt))
        .join()
    )
    assert resumed.worker_error() is None
    # The checkpoint may already cover the whole space; the resumed run
    # must land on exactly the full count either way.
    assert resumed.unique_state_count() == 1568
    resumed.assert_properties()
