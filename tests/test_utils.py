"""Utility-type semantics: VectorClock partial order + DenseNatMap density.

Oracle behaviors from the reference's inline tests
(``/root/reference/src/util/vector_clock.rs``, ``src/util/densenatmap.rs``).
"""

import pytest

from stateright_tpu.actor import Id
from stateright_tpu.core.fingerprint import fingerprint, stable_hash
from stateright_tpu.utils import DenseNatMap, RewritePlan, VectorClock


class TestVectorClock:
    def test_incremented_grows(self):
        vc = VectorClock().incremented(2)
        assert vc.elems() == (0, 0, 1)
        assert vc.incremented(0).elems() == (1, 0, 1)

    def test_merge_max(self):
        a = VectorClock([1, 5, 0])
        b = VectorClock([2, 3])
        assert VectorClock.merge_max(a, b) == VectorClock([2, 5, 0])

    def test_equality_pads_implicit_zeros(self):
        assert VectorClock([1, 0]) == VectorClock([1])
        assert VectorClock([1, 0]) != VectorClock([1, 1])

    def test_hash_truncates_trailing_zeros(self):
        assert hash(VectorClock([1, 0])) == hash(VectorClock([1]))
        assert stable_hash(VectorClock([1, 0, 0])) == stable_hash(
            VectorClock([1])
        )
        assert fingerprint(VectorClock([2, 1, 0])) == fingerprint(
            VectorClock([2, 1])
        )

    def test_partial_order(self):
        assert VectorClock([1, 2]) < VectorClock([2, 2])
        assert VectorClock([1, 2]) <= VectorClock([1, 2])
        assert VectorClock([2, 2]) > VectorClock([1, 2])
        assert VectorClock([1, 2, 0]) >= VectorClock([1, 2])

    def test_concurrent_clocks_incomparable(self):
        a, b = VectorClock([1, 0]), VectorClock([0, 1])
        assert a.concurrent_with(b)
        assert not (a < b) and not (a > b)
        assert not (a <= b) and not (a >= b)

    def test_display(self):
        assert str(VectorClock([1, 2])) == "<1, 2, ...>"


class TestDenseNatMap:
    def test_insert_appends_and_overwrites(self):
        m = DenseNatMap()
        assert m.insert(Id(0), "a") is None
        assert m.insert(Id(1), "b") is None
        assert m.insert(Id(0), "c") == "a"
        assert list(m) == ["c", "b"]

    def test_out_of_order_insert_raises(self):
        m = DenseNatMap()
        with pytest.raises(IndexError):
            m.insert(Id(1), "x")

    def test_from_pairs_any_order(self):
        m = DenseNatMap.from_pairs([(Id(1), "b"), (Id(0), "a")])
        assert m.values() == ["a", "b"]
        assert m.items() == [(Id(0), "a"), (Id(1), "b")]

    def test_from_pairs_rejects_sparse(self):
        with pytest.raises(ValueError):
            DenseNatMap.from_pairs([(Id(0), "a"), (Id(2), "c")])
        with pytest.raises(ValueError):
            DenseNatMap.from_pairs([(Id(0), "a"), (Id(0), "b")])

    def test_rewrite_reindexes(self):
        m = DenseNatMap(["b", "a"])
        plan = RewritePlan.from_values_to_sort(m.values())
        assert plan.reindex(m.values()) == ["a", "b"]
        rewritten = rewrite_roundtrip(m, plan)
        assert rewritten.values() == ["a", "b"]

    def test_stable_hash_matches_tuple(self):
        m = DenseNatMap(["a", "b"])
        assert fingerprint(m) != 0
        assert m == DenseNatMap(["a", "b"])
        assert m != DenseNatMap(["b", "a"])


def rewrite_roundtrip(value, plan):
    from stateright_tpu.utils import rewrite_value

    return rewrite_value(value, plan)


class TestCompileCache:
    """The persistent compile cache must never serve artifacts compiled
    for a different target (BENCH_r03's SIGILL-risk warning) or live at a
    poisonable world-writable path."""

    def test_platform_lineups_never_share_a_key(self):
        from stateright_tpu.utils.compile_cache import _target_tag

        assert _target_tag("cpu") != _target_tag("axon,cpu")
        assert _target_tag("cpu") == _target_tag("cpu")  # stable

    def test_cache_dir_under_home_and_private(self):
        import os

        from stateright_tpu.utils.compile_cache import (
            cache_dir,
            enable_persistent_cache,
        )

        d = cache_dir()
        assert d.startswith(os.path.expanduser("~"))
        assert "/tmp" not in d
        enable_persistent_cache()  # conftest already enabled it; idempotent
        st = os.stat(d)
        assert st.st_uid == os.getuid()
        assert (st.st_mode & 0o777) == 0o700
