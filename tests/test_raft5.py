"""The 5-node Raft baseline config (BASELINE.md: leader-election liveness,
lossy network, symmetry reduction).

The full 5-node lossy space is a TPU-scale workload (>300k states at depth 7
and growing; it is benched, capped, in ``bench.py``). CI pins the exact
tractable configs: the full 5-node lossless space on single-device and
sharded checkers, plus symmetry-reduced orbit counts (orbit-proper device
semantics — see ``tests/test_device_symmetry.py``) at 4 nodes (lossy) and
5 nodes (lossless, the full 120-permutation group).
"""

import pytest
import numpy as np

import jax

from stateright_tpu.models.raft import RaftModelCfg

RAFT5_LOSSLESS = 7_977
RAFT5_LOSSLESS_ORBITS = 123
RAFT4_LOSSY = 24_545
RAFT4_LOSSY_ORBITS = 1_181


@pytest.mark.slow
def test_raft5_lossless_device_and_sharded_parity():
    dev = (
        RaftModelCfg(server_count=5, max_term=1, lossy=False)
        .into_model()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=1 << 10, table_capacity=1 << 14)
        .join()
    )
    assert dev.worker_error() is None
    assert dev.unique_state_count() == RAFT5_LOSSLESS

    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("fp",))
    sh = (
        RaftModelCfg(server_count=5, max_term=1, lossy=False)
        .into_model()
        .checker()
        .spawn_sharded_tpu_bfs(
            mesh=mesh, frontier_per_device=128, table_capacity_per_device=1 << 11
        )
        .join()
    )
    assert sh.worker_error() is None
    assert sh.unique_state_count() == RAFT5_LOSSLESS
    # Liveness counterexample (split votes exhaust the term boundary
    # leaderless) is discoverable at 5 nodes.
    assert "stable leader" in dev.discoveries()


@pytest.mark.slow
def test_raft5_lossless_symmetry_orbits():
    c = (
        RaftModelCfg(server_count=5, max_term=1, lossy=False)
        .into_model()
        .checker()
        .symmetry()
        .spawn_tpu_bfs(frontier_capacity=1 << 10, table_capacity=1 << 14)
        .join()
    )
    assert c.worker_error() is None
    assert c.unique_state_count() == RAFT5_LOSSLESS_ORBITS


@pytest.mark.slow
def test_raft4_lossy_symmetry_orbits():
    full = (
        RaftModelCfg(server_count=4, max_term=1, lossy=True)
        .into_model()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=1 << 11, table_capacity=1 << 16)
        .join()
    )
    assert full.worker_error() is None
    assert full.unique_state_count() == RAFT4_LOSSY

    reduced = (
        RaftModelCfg(server_count=4, max_term=1, lossy=True)
        .into_model()
        .checker()
        .symmetry()
        .spawn_tpu_bfs(frontier_capacity=1 << 10, table_capacity=1 << 14)
        .join()
    )
    assert reduced.worker_error() is None
    assert reduced.unique_state_count() == RAFT4_LOSSY_ORBITS
    assert set(reduced.discoveries()) == {"leader elected", "stable leader"}
