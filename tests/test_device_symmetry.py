"""Device symmetry reduction: orbit-proper minimum-fingerprint keys.

The reference's symmetry reduction sorts actor rows to pick a representative
(``src/checker/rewrite_plan.rs:81-106``) — NOT a canonical form, because id
rewriting perturbs the sorted rows. Its reduced counts are traversal-order
artifacts: on 2pc-5 the pinned 665 is single-threaded-DFS-specific (BFS
order yields 508, random orders 707-757 — measured), so no wave-BFS engine
can reproduce it. The device checkers instead key the visited set on the
MINIMUM fingerprint over every actor permutation: a true orbit invariant,
giving engine- and traversal-independent counts that are also strictly
stronger reductions (2pc-5: 314 orbits vs 665 heuristic classes; 3-server
lossy-duplicating Raft: 464 vs 621). The host ``orbit_representative``
provides the same semantics for host checkers, which these tests use for
cross-engine parity.
"""

import numpy as np
import pytest

import jax

from stateright_tpu.actor import Network
from stateright_tpu.models.raft import RaftModelCfg
from stateright_tpu.models.two_phase_commit import TwoPhaseSys

# Brute-forced orbit counts (min over all permutations of every reachable
# state, computed independently of any checker).
TWO_PC_5_ORBITS = 314
RAFT_DUP_LOSSY_ORBITS = 464


def _tpu_sym(model, **kw):
    kw.setdefault("frontier_capacity", 256)
    kw.setdefault("table_capacity", 1 << 14)
    checker = model.checker().symmetry().spawn_tpu_bfs(**kw).join()
    assert checker.worker_error() is None
    return checker


def _sharded_sym(model, **kw):
    from jax.sharding import Mesh

    kw.setdefault("frontier_per_device", 64)
    kw.setdefault("table_capacity_per_device", 1 << 10)
    mesh = Mesh(np.array(jax.devices()[:8]), ("fp",))
    checker = (
        model.checker()
        .symmetry()
        .spawn_sharded_tpu_bfs(mesh=mesh, **kw)
        .join()
    )
    assert checker.worker_error() is None
    return checker



def _bfs_states(model, cap=None):
    """All reachable host states (dedup by hash, boundary-pruned),
    optionally capped — the enumeration oracle several tests share."""
    from collections import deque

    states = list(model.init_states())
    seen = {hash(s) for s in states}
    q = deque(states)
    acts = []
    while q and (cap is None or len(states) < cap):
        s = q.popleft()
        acts.clear()
        model.actions(s, acts)
        for a in acts:
            ns = model.next_state(s, a)
            if (
                ns is not None
                and model.within_boundary(ns)
                and hash(ns) not in seen
            ):
                seen.add(hash(ns))
                states.append(ns)
                q.append(ns)
    return states


def _raft_dup():
    return RaftModelCfg(
        server_count=3,
        max_term=1,
        lossy=True,
        network=Network.new_unordered_duplicating(),
    ).into_model()


def test_2pc5_device_orbit_count():
    checker = _tpu_sym(TwoPhaseSys(5))
    assert checker.unique_state_count() == TWO_PC_5_ORBITS
    checker.assert_properties()
    assert set(checker.discoveries()) == {"abort agreement", "commit agreement"}


def test_2pc5_sharded_orbit_count_matches():
    checker = _sharded_sym(TwoPhaseSys(5))
    assert checker.unique_state_count() == TWO_PC_5_ORBITS
    checker.assert_properties()


@pytest.mark.slow
def test_raft_device_orbit_count_and_host_parity():
    dev = _tpu_sym(_raft_dup(), table_capacity=1 << 12)
    assert dev.unique_state_count() == RAFT_DUP_LOSSY_ORBITS
    # Host DFS with the orbit-proper representative agrees exactly — the
    # cross-engine guarantee the sort heuristic cannot give.
    host = (
        _raft_dup()
        .checker()
        .symmetry_fn(lambda s: s.orbit_representative())
        .spawn_dfs()
        .join()
    )
    assert host.unique_state_count() == RAFT_DUP_LOSSY_ORBITS
    assert set(dev.discoveries()) == {"leader elected", "stable leader"}
    # Discovery paths replay through concrete (original-fingerprint) states.
    for path in dev.discoveries().values():
        assert len(path) >= 1


def test_2pc4_host_orbit_parity():
    host = (
        TwoPhaseSys(4)
        .checker()
        .symmetry_fn(lambda s: s.orbit_representative())
        .spawn_dfs()
        .join()
    )
    dev = _tpu_sym(TwoPhaseSys(4))
    assert host.unique_state_count() == dev.unique_state_count()


def test_device_group_action_matches_host():
    # The packed group action (gather + codec id rewrites) must agree with
    # the host RewritePlan application on every reachable state x
    # permutation — this is what makes the minimum over permutations a true
    # orbit key on the device. Agreement is at the FINGERPRINT level: the
    # device leaves the envelope table unsorted and relies on the
    # order-insensitive multiset digest in the fingerprint view, so raw
    # array equality with the (sorted) host packing is not expected.
    from itertools import permutations

    from stateright_tpu.ops.fingerprint import fingerprint_state
    from stateright_tpu.utils.rewrite import RewritePlan

    model = RaftModelCfg(server_count=3, max_term=1, lossy=True).into_model()
    n2o, o2n = model.packed_symmetry()
    fp_view = jax.jit(
        lambda s: fingerprint_state(model.packed_fingerprint_view(s))
    )
    apply_all = jax.jit(
        jax.vmap(
            lambda s, a, b: model.packed_apply_permutation(s, a, b),
            in_axes=(None, 0, 0),
        ),
        static_argnums=(),
    )

    states = _bfs_states(model)
    assert len(states) == 665

    perms = list(permutations(range(3)))
    for s in states[::7]:  # every 7th state: 96 states x 6 perms
        packed = model.pack_state(s)
        dev = apply_all(packed, np.asarray(n2o), np.asarray(o2n))
        for k, p in enumerate(perms):
            # packed_apply_permutation row k uses new_to_old = perms[k];
            # the matching host plan maps old i -> position of i in p.
            mapping = [0] * 3
            for new, old in enumerate(p):
                mapping[old] = new
            host_permuted = model.pack_state(s._permuted(RewritePlan(mapping)))
            got = {kk: np.asarray(v[k]) for kk, v in dev.items()}
            want_hi, want_lo = fp_view(host_permuted)
            got_hi, got_lo = fp_view(got)
            assert (int(got_hi), int(got_lo)) == (
                int(want_hi),
                int(want_lo),
            ), (p, s)


@pytest.mark.slow
def test_symmetry_checkpoint_resume(tmp_path):
    ckpt = tmp_path / "2pc4-sym.ckpt"
    first = (
        TwoPhaseSys(4)
        .checker()
        .symmetry()
        .target_state_count(150)
        .spawn_tpu_bfs(
            frontier_capacity=64,
            checkpoint_path=str(ckpt),
            checkpoint_every_chunks=1,
        )
        .join()
    )
    assert first.worker_error() is None
    assert ckpt.exists()

    full = _tpu_sym(TwoPhaseSys(4), frontier_capacity=64)
    resumed = (
        TwoPhaseSys(4)
        .checker()
        .symmetry()
        .spawn_tpu_bfs(frontier_capacity=64, resume_from=str(ckpt))
        .join()
    )
    assert resumed.worker_error() is None
    assert resumed.unique_state_count() == full.unique_state_count()

    # A symmetry checkpoint cannot resume a non-symmetry run (the visited
    # keys live in different spaces).
    mismatched = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        frontier_capacity=64, resume_from=str(ckpt)
    )
    with pytest.raises(RuntimeError):
        mismatched.join()
    assert "symmetry" in str(mismatched.worker_error())


def test_refined_keys_match_orbit_min_partition_2pc7():
    """The WL-refined canonical keys must induce the SAME equivalence
    partition as the exact n!-loop orbit-minimum keys — on the 5040-perm
    group (n=7) where the n! loop is too slow to ever run per-wave. 256
    random packed states plus a randomly permuted copy of each: the
    permuted copies pin orbit invariance (same key as their original), the
    cross-pairs pin that refinement never merges distinct orbits."""
    import jax.numpy as jnp

    from stateright_tpu.checker.builder import default_representative
    from stateright_tpu.checker.tpu import _make_key_fn
    from stateright_tpu.core.batch import BatchableModel
    from stateright_tpu.ops.fingerprint import fingerprint_state

    model = TwoPhaseSys(7)

    def fp_fn(s):
        return fingerprint_state(model.packed_fingerprint_view(s))

    refined = _make_key_fn(model, fp_fn, default_representative)
    orig = TwoPhaseSys.packed_refine_colors
    try:
        TwoPhaseSys.packed_refine_colors = BatchableModel.packed_refine_colors
        orbit_min = _make_key_fn(model, fp_fn, default_representative)
    finally:
        TwoPhaseSys.packed_refine_colors = orig
    assert refined is not orbit_min

    rng = np.random.default_rng(7)
    B, n = 256, 7
    batch = {
        "rm": jnp.asarray(rng.integers(0, 4, (B, n)), jnp.uint32),
        "tm": jnp.asarray(rng.integers(0, 3, (B,)), jnp.uint32),
        "prepared": jnp.asarray(rng.integers(0, 1 << n, (B,)), jnp.uint32),
        "msgs": jnp.asarray(rng.integers(0, 1 << (n + 2), (B,)), jnp.uint32),
    }
    n2o, o2n = model.packed_symmetry()
    pick = rng.integers(0, n2o.shape[0], (B,))
    permuted = jax.vmap(model.packed_apply_permutation)(
        batch, jnp.asarray(n2o[pick]), jnp.asarray(o2n[pick])
    )
    both = {k: jnp.concatenate([v, permuted[k]]) for k, v in batch.items()}

    rhi, rlo = jax.jit(refined)(both)
    mhi, mlo = jax.jit(orbit_min)(both)
    rkey = (np.asarray(rhi).astype(np.uint64) << 32) | np.asarray(rlo)
    mkey = (np.asarray(mhi).astype(np.uint64) << 32) | np.asarray(mlo)
    # Orbit invariance: each permuted copy keys with its original.
    assert (rkey[B:] == rkey[:B]).all()
    # Same partition as the exact orbit-minimum keys.
    assert (
        (rkey[:, None] == rkey[None, :]) == (mkey[:, None] == mkey[None, :])
    ).all()


def test_generic_refine_colors_equivariance_raft():
    """The generic PackedActorModel WL hook must be equivariant —
    ``refine(sigma(s), sigma(colors)) == sigma(refine(s, colors))`` — or
    same-orbit states would canonicalize differently and orbit counts
    would over-report (the one failure mode verify-or-fallback CANNOT
    catch). Checked directly on reachable raft states (id-references +
    envelope flows + reverse-reference detection all in play) across
    permutations and refinement rounds."""
    import jax.numpy as jnp

    model = _raft_dup()
    n2o_all, o2n_all = model.packed_symmetry()
    n = 3

    states = _bfs_states(model, cap=400)

    refine = jax.jit(model.packed_refine_colors)
    apply_p = jax.jit(model.packed_apply_permutation)
    rng = np.random.default_rng(3)
    for s in states[::37]:
        packed = {k: jnp.asarray(v) for k, v in model.pack_state(s).items()}
        for k in rng.integers(0, len(n2o_all), 3):
            n2o = jnp.asarray(n2o_all[k])
            o2n = jnp.asarray(o2n_all[k])
            ps = apply_p(packed, n2o, o2n)
            colors = jnp.zeros((n,), jnp.uint32)
            colors_p = jnp.zeros((n,), jnp.uint32)
            for _ in range(2):
                colors = refine(packed, colors)
                colors_p = refine(ps, colors_p)
                assert (
                    np.asarray(colors_p) == np.asarray(colors)[np.asarray(n2o)]
                ).all(), (s, np.asarray(n2o))


def test_weak_refine_hook_falls_back_exactly():
    """A deliberately useless refine hook (constant colors — a single tie
    class everywhere) must cost only speed, never counts: the adjacent-
    transposition verification fails on every non-fully-symmetric state
    and those lanes take the n!-loop fallback key."""
    import jax.numpy as jnp

    class WeakRefine2pc(TwoPhaseSys):
        def packed_refine_colors(self, state, colors):
            return jnp.zeros_like(colors)

    checker = _tpu_sym(WeakRefine2pc(5))
    assert checker.unique_state_count() == TWO_PC_5_ORBITS
    checker.assert_properties()


@pytest.mark.slow
def test_2pc7_sharded_orbit_count_matches():
    """The 5,040-perm WL keys computed inside the shard_map wave must
    reproduce the single-device orbit count — two independent dedup/
    routing implementations agreeing on the canonical partition."""
    checker = _sharded_sym(
        TwoPhaseSys(7),
        frontier_per_device=1 << 10,
        table_capacity_per_device=1 << 17,
    )
    assert checker.unique_state_count() == 920
    checker.assert_properties()


@pytest.mark.slow
def test_2pc9_device_orbit_count():
    """Symmetry over the 362,880-permutation group (n=9, the raised
    MAX_SYMMETRY_ACTORS bound): 2,232 canonical orbits of 10,340,352
    states. The n! table exists only as the never-executed fallback
    constant; WL keys never fall back on 2pc (per-RM data is local), so
    the whole 10.3M-state space checks in ~23s on the CPU backend where
    the unreduced run took 347s (r2)."""
    checker = _tpu_sym(
        TwoPhaseSys(9),
        frontier_capacity=1 << 13,
        table_capacity=1 << 21,
        drain_log_factor=48,
    )
    assert checker.unique_state_count() == 2232
    checker.assert_properties()


@pytest.mark.slow
def test_2pc8_device_orbit_count():
    """Symmetry over the 40,320-permutation group (n=8): 1,461 canonical
    orbits of 1,745,408
    states — and FASTER than the unreduced 2pc-8 run, because the orbit
    space collapses ~1,200x while the WL keys cost only ~n fingerprint
    passes per candidate."""
    checker = _tpu_sym(
        TwoPhaseSys(8),
        frontier_capacity=1 << 13,
        table_capacity=1 << 21,
        drain_log_factor=48,
    )
    assert checker.unique_state_count() == 1461
    checker.assert_properties()


@pytest.mark.slow
def test_2pc7_device_orbit_count():
    """The n!-wall milestone: symmetry on the 5,040-permutation group
    (2pc-7, 296,448 states) — infeasible under the r2 per-wave n! loop —
    completes through the WL-refined keys. Orbit count pinned from the
    first verified run (cross-checked by the partition-equality property
    test above, which pins refined == orbit-min on this exact group)."""
    checker = _tpu_sym(
        TwoPhaseSys(7),
        frontier_capacity=1 << 13,
        table_capacity=1 << 20,
        drain_log_factor=48,
    )
    assert checker.unique_state_count() == 920
    checker.assert_properties()


def test_custom_symmetry_fn_rejected_on_device():
    # Device symmetry reduces by the FULL permutation group; honoring a
    # user's partial-symmetry representative is impossible WITHOUT a
    # packed canonical form, so it must refuse instead of silently
    # over-merging states.
    with pytest.raises(ValueError):
        TwoPhaseSys(3).checker().symmetry_fn(
            lambda s: s.representative()
        ).spawn_tpu_bfs()


def test_custom_packed_representative_on_device():
    """A user-defined partial symmetry (reference ``Representative``,
    ``src/checker/representative.rs:65-68``) drives device dedup when the
    model implements ``packed_representative``: partial symmetry over only
    the FIRST THREE RMs of a 4-RM 2pc. Host (symmetry_fn) and device
    (packed_representative) canonicalize with different sort keys but
    quotient by the same S_3 action, so the reduced counts must agree."""
    import jax.numpy as jnp

    from stateright_tpu.utils.rewrite import RewritePlan

    K = 3

    def rep3(state):
        order = sorted(
            range(K),
            key=lambda i: (
                state.rm_state[i],
                state.tm_prepared[i],
                ("Prepared", i) in state.msgs,
            ),
        )
        mapping = list(range(len(state.rm_state)))
        for new, old in enumerate(order):
            mapping[old] = new
        return state._permuted(RewritePlan(mapping))

    class Partial2pc(TwoPhaseSys):
        def packed_representative(self, state):
            n = self.rm_count
            idx = jnp.arange(n, dtype=jnp.uint32)
            prep = (state["prepared"] >> idx) & jnp.uint32(1)
            msg = (state["msgs"] >> idx) & jnp.uint32(1)
            key = state["rm"] * jnp.uint32(4) + prep * jnp.uint32(2) + msg
            order3 = jnp.argsort(key[:K]).astype(jnp.int32)
            n2o = jnp.concatenate(
                [order3, jnp.arange(K, n, dtype=jnp.int32)]
            )
            o2n = (
                jnp.zeros((n,), jnp.int32)
                .at[n2o]
                .set(jnp.arange(n, dtype=jnp.int32))
            )
            return self.packed_apply_permutation(state, n2o, o2n)

    host = Partial2pc(4).checker().symmetry_fn(rep3).spawn_dfs().join()
    dev = (
        Partial2pc(4)
        .checker()
        .symmetry_fn(rep3)
        .spawn_tpu_bfs(frontier_capacity=256, table_capacity=1 << 14)
        .join()
    )
    assert dev.worker_error() is None
    assert dev.unique_state_count() == host.unique_state_count()
    # A partial symmetry must still reduce vs the unreduced space.
    full = TwoPhaseSys(4).checker().spawn_bfs().join()
    assert dev.unique_state_count() < full.unique_state_count()


def test_symmetry_requires_packed_support():
    # Models whose packed form cannot permute actors (auxiliary history
    # carries distinguished client identities) refuse loudly.
    from stateright_tpu.models.paxos import PaxosModelCfg

    with pytest.raises(TypeError):
        PaxosModelCfg(2, 2).into_model().checker().symmetry().spawn_tpu_bfs()
