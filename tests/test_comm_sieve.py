"""Compression-and-sieve cross-shard routing (the PR-17 exchange path).

Three layers, each tested here:

- ``ops/comm_sieve`` primitives — the receipt cache is EXACT (full-key
  compare: a hit is a proof, a collision is a miss, never a false
  positive), the Bloom filter is advisory and its false positives are
  audited against the design bound rather than assumed;
- the sharded checker's sieve+compact A/B — identical counts, depths,
  and discoveries with the sieve on vs off (bit-identity is by
  construction: a killed lane is one the owner already holds), with
  strictly fewer shipped lanes, surviving checkpoint/resume and
  out-of-core eviction (which flushes the sieve);
- the ``storage/runs.py`` wire codec — delta-encoded sorted fingerprint
  runs round-trip exactly on adversarial distributions (max-gap, dense,
  empty, random), and torn/forged frames raise instead of decoding.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.ops import comm_sieve
from stateright_tpu.storage.runs import (
    decode_sorted_fps,
    encode_sorted_fps,
)
from stateright_tpu.telemetry.metrics import metrics_registry


# ---------------------------------------------------------------- primitives


def _split(keys):
    keys = np.asarray(keys, np.uint64)
    return (
        jnp.asarray((keys >> np.uint64(32)).astype(np.uint32)),
        jnp.asarray((keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
    )


def test_receipt_cache_exact_membership():
    cache = comm_sieve.cache_new(4)
    hi, lo = _split([0x1_0000_0007, 0x2_0000_0008, 0x3_0000_0009])
    active = jnp.ones(3, bool)
    assert not bool(comm_sieve.cache_probe(cache, hi, lo, active).any())
    cache = comm_sieve.cache_insert(
        cache, hi, lo, jnp.array([True, True, False])
    )
    assert comm_sieve.cache_probe(cache, hi, lo, active).tolist() == [
        True,
        True,
        False,
    ]
    # Inactive lanes never report membership, held keys or not.
    assert not bool(
        comm_sieve.cache_probe(cache, hi, lo, jnp.zeros(3, bool)).any()
    )


def test_receipt_cache_collision_overwrites_never_lies():
    """Direct-mapped: a collider evicts the older key. The evicted key
    must then MISS (a stale hit would claim residency for a key the
    owner may not hold — the one failure mode that breaks exactness);
    the survivor must hit."""
    slots_log2 = 2
    rng = np.random.default_rng(7)
    keys = rng.integers(1, 2**63, size=64, dtype=np.uint64)
    hi, lo = _split(keys)
    slots = np.asarray(
        comm_sieve._cache_slot(hi, lo, 1 << slots_log2)
    )
    # With 64 keys over 4 slots a collision pair always exists.
    a = b = None
    for i in range(len(keys)):
        for j in range(i + 1, len(keys)):
            if slots[i] == slots[j] and keys[i] != keys[j]:
                a, b = i, j
                break
        if a is not None:
            break
    assert a is not None
    cache = comm_sieve.cache_new(slots_log2)
    one = jnp.ones(1, bool)
    cache = comm_sieve.cache_insert(cache, hi[a : a + 1], lo[a : a + 1], one)
    assert bool(comm_sieve.cache_probe(cache, hi[a : a + 1], lo[a : a + 1], one)[0])
    cache = comm_sieve.cache_insert(cache, hi[b : b + 1], lo[b : b + 1], one)
    assert bool(comm_sieve.cache_probe(cache, hi[b : b + 1], lo[b : b + 1], one)[0])
    assert not bool(
        comm_sieve.cache_probe(cache, hi[a : a + 1], lo[a : a + 1], one)[0]
    )


def test_bloom_no_false_negatives_and_fp_within_design():
    """Every inserted key probes True (Blooms never false-negative); the
    false-positive rate over never-inserted keys stays under 2x the 1%
    design point at full capacity. Hashes are fixed, so this is a
    deterministic measurement, not a flaky sample."""
    n = 4096
    rng = np.random.default_rng(17)
    keys = np.unique(rng.integers(1, 2**63, size=3 * n, dtype=np.uint64))
    members, strangers = keys[:n], keys[n : 2 * n]
    bits = comm_sieve.bloom_bits_for(n)
    bloom = comm_sieve.bloom_new(bits)
    mhi, mlo = _split(members)
    bloom = comm_sieve.bloom_insert(
        bloom, mhi, mlo, jnp.ones(len(members), bool)
    )
    assert bool(comm_sieve.bloom_probe(bloom, mhi, mlo).all())
    shi, slo = _split(strangers)
    fps = int(np.sum(np.asarray(comm_sieve.bloom_probe(bloom, shi, slo))))
    assert fps / len(strangers) < 2 * comm_sieve.BLOOM_DESIGN_FP_RATE, (
        f"{fps}/{len(strangers)} false positives"
    )


# ---------------------------------------------------------------- wire codec


@pytest.mark.parametrize(
    "fps",
    [
        [],
        [0],
        [2**64 - 1],
        [0, 2**64 - 1],  # the maximal single delta
        [0, 2**63, 2**64 - 1],  # two huge gaps
        list(range(1000)),  # dense run: delta=1 throughout
        [5] * 7,  # duplicates: zero deltas must survive
        list(range(0, 2**20, 4096)),  # strided
    ],
)
def test_wire_codec_round_trip(fps):
    fps = np.asarray(fps, np.uint64)
    buf = encode_sorted_fps(fps)
    out = decode_sorted_fps(buf)
    assert out.dtype == np.uint64
    np.testing.assert_array_equal(out, fps)


@pytest.mark.parametrize("seed", range(6))
def test_wire_codec_round_trip_random(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 5000))
    # Mix uniform-over-u64 with clustered runs: both delta regimes.
    uniform = rng.integers(0, 2**64, size=n, dtype=np.uint64)
    base = rng.integers(0, 2**63, dtype=np.uint64)
    clustered = base + np.arange(n, dtype=np.uint64)
    fps = np.sort(np.concatenate([uniform, clustered]))
    np.testing.assert_array_equal(decode_sorted_fps(encode_sorted_fps(fps)), fps)


def test_wire_codec_dense_runs_compress():
    """The point of the codec: consecutive fingerprints (the shape bulk
    eviction produces after the sort) cost ~1 byte each on the wire,
    not 8."""
    fps = np.arange(10_000, dtype=np.uint64) + np.uint64(2**40)
    buf = encode_sorted_fps(fps)
    assert len(buf) < 2 * len(fps)  # vs 8 B/key raw


def test_wire_codec_rejects_torn_and_forged_frames():
    fps = np.arange(100, dtype=np.uint64) * np.uint64(977)
    buf = encode_sorted_fps(fps)
    with pytest.raises(ValueError, match="magic"):
        decode_sorted_fps(b"NOPE" + buf[4:])
    with pytest.raises(ValueError):
        decode_sorted_fps(buf[:7])  # shorter than the header
    with pytest.raises(ValueError, match="declares"):
        decode_sorted_fps(buf[:-3])  # torn payload: count mismatch
    # Forged count field: payload decodes fewer keys than declared.
    forged = buf[:4] + np.uint32(101).tobytes() + buf[8:]
    with pytest.raises(ValueError, match="declares"):
        decode_sorted_fps(forged)


# ------------------------------------------------------- sharded sieve A/B


def _spawn(model, sieve, n_dev=4, **kw):
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("fp",))
    kw.setdefault("frontier_per_device", 32)
    kw.setdefault("table_capacity_per_device", 1 << 11)
    checker = (
        model.checker()
        .spawn_sharded_tpu_bfs(mesh=mesh, sieve=sieve, **kw)
        .join()
    )
    assert checker.worker_error() is None
    return checker


# The ab_2pc4 family below is slow-marked: the fixture pays two full
# sharded 2pc-4 compiles (~14s on a small box), which the flat
# `-m 'not slow'` tier-1 sweep cannot afford. The primitive exactness
# and codec tests above stay fast; CI runs this file with
# `-m 'slow or not slow'` in the dedicated compression-and-sieve step
# (tier1.yml), so every A/B gate still runs on every push.
@pytest.fixture(scope="module")
def ab_2pc4():
    """One sieve-off / sieve-on pair of 2pc-4 runs, shared by every A/B
    assertion below (sharded compiles are the expensive part)."""
    metrics_registry().reset()
    off = _spawn(TwoPhaseSys(4), sieve=False)
    snap_off = metrics_registry().snapshot()
    metrics_registry().reset()
    on = _spawn(TwoPhaseSys(4), sieve=True)
    snap_on = metrics_registry().snapshot()
    return off, snap_off, on, snap_on


@pytest.mark.slow
def test_sieve_bit_identical_2pc4(ab_2pc4):
    off, _, on, _ = ab_2pc4
    assert off.unique_state_count() == on.unique_state_count() == 1568
    assert off.state_count() == on.state_count()
    assert off.max_depth() == on.max_depth()
    assert set(off.discoveries()) == set(on.discoveries())
    on.assert_properties()


@pytest.mark.slow
def test_sieve_ships_strictly_fewer_lanes(ab_2pc4):
    _, snap_off, _, snap_on = ab_2pc4
    lanes_off = snap_off["sharded_bfs.comms.lanes_shipped"]
    lanes_on = snap_on["sharded_bfs.comms.lanes_shipped"]
    assert 0 < lanes_on < lanes_off, (lanes_off, lanes_on)
    # The compacted rungs dispatched below full width at least once.
    rungs = {
        k for k in snap_on if k.startswith("sharded_bfs.comms.rung_dispatch.")
    }
    assert rungs, "no rung dispatch recorded with the sieve on"
    killed = snap_on["sharded_bfs.comms.sieve.killed"]
    probes = snap_on["sharded_bfs.comms.sieve.probes"]
    assert 0 < killed <= probes


@pytest.mark.slow
def test_bloom_observed_fp_rate_audited(ab_2pc4):
    """The advisory Bloom's OBSERVED false-positive rate (routed lanes
    double as exact re-checks: ``bloom_hit & shipped & fresh`` is a
    counted FP, not an estimate) stays under 2x the configured design
    bound. The floor term keeps a tiny-probe run from failing on one
    unlucky (but in-bound) collision."""
    _, _, _, snap_on = ab_2pc4
    probes = snap_on["sharded_bfs.comms.sieve.bloom_probe_total"]
    fps = snap_on["sharded_bfs.comms.sieve.bloom_fp_total"]
    assert probes > 0
    assert fps <= max(3, 2 * comm_sieve.BLOOM_DESIGN_FP_RATE * probes), (
        f"observed {fps}/{probes} vs design "
        f"{comm_sieve.BLOOM_DESIGN_FP_RATE}"
    )


@pytest.mark.slow
def test_sieve_state_digest_declares_engine(ab_2pc4):
    off, _, on, _ = ab_2pc4
    d_on, d_off = on.state_digest(), off.state_digest()
    assert d_on["wave_kernel"] == d_off["wave_kernel"] == "staged"
    assert d_on["sieve"] is True and d_off["sieve"] is False
    assert d_on["comm_sieve"]["cache_slots"] > 0
    assert d_on["comm_sieve"]["bloom_bits"] > 0
    assert "comm_sieve" not in d_off


def test_fused_wave_kernel_refused_on_sharded():
    """Honest refusal, not silent fallback: the fused megakernel cannot
    express the cross-shard all_to_all, and asking for it on the
    sharded checker must say exactly why."""
    with pytest.raises(ValueError, match="no sharded path"):
        TwoPhaseSys(3).checker().spawn_sharded_tpu_bfs(
            mesh=Mesh(np.array(jax.devices()[:4]), ("fp",)),
            frontier_per_device=32,
            wave_kernel="fused",
        )


@pytest.mark.slow
def test_sieve_checkpoint_resume_bit_identical(tmp_path, ab_2pc4):
    """A sieved run checkpointed mid-flight resumes cold-sieve (receipts
    are not checkpointed — a cold cache only costs kills, never
    correctness) and still finishes exact."""
    off, _, _, _ = ab_2pc4
    ckpt = tmp_path / "2pc4-sieve.ckpt"
    first = (
        TwoPhaseSys(4)
        .checker()
        .target_state_count(500)
        .spawn_sharded_tpu_bfs(
            mesh=Mesh(np.array(jax.devices()[:4]), ("fp",)),
            frontier_per_device=32,
            table_capacity_per_device=1 << 11,
            sieve=True,
            checkpoint_path=str(ckpt),
            checkpoint_every_chunks=1,
        )
        .join()
    )
    assert first.worker_error() is None
    assert ckpt.exists()
    assert first.unique_state_count() < 1568
    resumed = (
        TwoPhaseSys(4)
        .checker()
        .spawn_sharded_tpu_bfs(
            mesh=Mesh(np.array(jax.devices()[:4]), ("fp",)),
            frontier_per_device=32,
            table_capacity_per_device=1 << 11,
            sieve=True,
            resume_from=str(ckpt),
        )
        .join()
    )
    assert resumed.worker_error() is None
    assert resumed.unique_state_count() == 1568
    assert resumed.max_depth() == off.max_depth()
    resumed.assert_properties()


@pytest.mark.slow
def test_sieve_bit_identical_2pc5():
    metrics_registry().reset()
    off = _spawn(
        TwoPhaseSys(5), sieve=False, table_capacity_per_device=1 << 13
    )
    snap_off = metrics_registry().snapshot()
    metrics_registry().reset()
    on = _spawn(
        TwoPhaseSys(5), sieve=True, table_capacity_per_device=1 << 13
    )
    snap_on = metrics_registry().snapshot()
    assert off.unique_state_count() == on.unique_state_count() == 8832
    assert off.state_count() == on.state_count()
    assert off.max_depth() == on.max_depth()
    assert (
        snap_on["sharded_bfs.comms.lanes_shipped"]
        < snap_off["sharded_bfs.comms.lanes_shipped"]
    )


@pytest.mark.slow
@pytest.mark.parametrize("fps", [True, False])
def test_sieve_bit_identical_abd_expand_fps(fps):
    """ABD register: the sieved sharded run must agree with the
    single-device checker under BOTH expand-fps modes. ``expand_fps``
    is a single-device knob (the sharded wave always materializes), so
    the sieve has to be invisible to either reference — same unique
    count, depth, and discoveries."""
    from stateright_tpu.models.linearizable_register import AbdModelCfg

    single = (
        AbdModelCfg(2, 2)
        .into_model()
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=8,
            table_capacity=1 << 12,
            expand_fps=fps,
        )
        .join()
    )
    assert single.worker_error() is None
    metrics_registry().reset()
    sieved = _spawn(
        AbdModelCfg(2, 2).into_model(),
        sieve=True,
        frontier_per_device=16,
        table_capacity_per_device=1 << 12,
    )
    assert sieved.unique_state_count() == single.unique_state_count() == 544
    assert sieved.max_depth() == single.max_depth()
    assert set(sieved.discoveries()) == set(single.discoveries())


@pytest.mark.slow
def test_sieve_out_of_core_eviction_flushes(tmp_path):
    """2pc-5 under an hbm budget that forces evictions with the sieve
    on: every eviction invalidates the receipts (keys leave the device
    table), the sieve flushes, and the run stays exact against the
    oracle count."""
    A = TwoPhaseSys(5).packed_action_count()
    rows = 1 << math.ceil(math.log2(4 * 8 * A / 0.5 + 1))
    metrics_registry().reset()
    budgeted = _spawn(
        TwoPhaseSys(5),
        sieve=True,
        frontier_per_device=8,
        table_capacity_per_device=1 << 14,
        hbm_budget_mib=((rows + 128) * 8) / (1 << 20),
    )
    assert budgeted.unique_state_count() == 8832
    budgeted.assert_properties()
    snap = metrics_registry().snapshot()
    assert snap["sharded_bfs.storage.evictions"] >= 1
    assert snap["sharded_bfs.comms.sieve.killed"] > 0
