"""Tiny deterministic model fixtures — the acceptance harness for every backend.

Behavioral parity with the reference fixtures at
``/root/reference/src/test_util.rs`` (BinaryClock, DGraph, LinearEquation,
Panicker, fn-as-model).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from stateright_tpu import Model, Property


class BinaryClock(Model):
    """A machine that cycles between two states."""

    GO_LOW = "GoLow"
    GO_HIGH = "GoHigh"

    def init_states(self):
        return [0, 1]

    def actions(self, state, actions):
        actions.append(self.GO_HIGH if state == 0 else self.GO_LOW)

    def next_state(self, state, action):
        return 1 if action == self.GO_HIGH else 0

    def properties(self):
        return [Property.always("in [0, 1]", lambda _, state: 0 <= state <= 1)]


class DGraph(Model):
    """A directed graph, specified via paths from initial states."""

    def __init__(self, prop: Property):
        self.inits: Set[int] = set()
        self.edges: Dict[int, Set[int]] = {}
        self.prop = prop

    @staticmethod
    def with_property(prop: Property) -> "DGraph":
        return DGraph(prop)

    def with_path(self, path: List[int]) -> "DGraph":
        src = path[0]
        self.inits.add(src)
        for dst in path[1:]:
            self.edges.setdefault(src, set()).add(dst)
            src = dst
        return self

    def check(self):
        return self.checker().spawn_bfs().join()

    def init_states(self):
        return sorted(self.inits)

    def actions(self, state, actions):
        actions.extend(sorted(self.edges.get(state, ())))

    def next_state(self, state, action):
        return action

    def properties(self):
        return [self.prop]


class LinearEquation(Model):
    """Finds x, y in u8 such that a*x + b*y = c (mod 256)."""

    INCREASE_X = "IncreaseX"
    INCREASE_Y = "IncreaseY"

    def __init__(self, a: int, b: int, c: int):
        self.a, self.b, self.c = a, b, c

    def init_states(self):
        return [(0, 0)]

    def actions(self, state, actions):
        actions.append(self.INCREASE_X)
        actions.append(self.INCREASE_Y)

    def next_state(self, state, action):
        x, y = state
        if action == self.INCREASE_X:
            return ((x + 1) % 256, y)
        return (x, (y + 1) % 256)

    def properties(self):
        def solvable(model, solution):
            x, y = solution
            return (model.a * x + model.b * y) % 256 == model.c % 256

        return [Property.sometimes("solvable", solvable)]


class Panicker(Model):
    """A model that raises during checking (worker shutdown test)."""

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        actions.append(1)

    def next_state(self, last_state, action):
        if last_state == 5:
            raise RuntimeError("reached panic state")
        return last_state + action

    def properties(self):
        return [Property.always("true", lambda _m, _s: True)]
