"""Consistency-semantics tests — parity with the reference's hand-built
history accept/reject cases (``src/semantics/*.rs`` test modules)."""

import pytest

from stateright_tpu.semantics import (
    LEN,
    LenOk,
    LinearizabilityTester,
    POP,
    PUSH_OK,
    PopOk,
    Push,
    READ,
    ReadOk,
    Register,
    SequentialConsistencyTester,
    VecSpec,
    WORegister,
    WO_READ,
    WO_WRITE_FAIL,
    WO_WRITE_OK,
    WoReadOk,
    WoWrite,
    WRITE_OK,
    Write,
)


class TestRegisterSpec:
    def test_models_expected_semantics(self):
        r = Register("A")
        assert r.invoke(READ) == ReadOk("A")
        assert r.invoke(Write("B")) == WRITE_OK
        assert r.invoke(READ) == ReadOk("B")

    def test_accepts_valid_histories(self):
        assert Register("A").is_valid_history([])
        assert Register("A").is_valid_history(
            [
                (READ, ReadOk("A")),
                (Write("B"), WRITE_OK),
                (READ, ReadOk("B")),
                (Write("C"), WRITE_OK),
                (READ, ReadOk("C")),
            ]
        )

    def test_rejects_invalid_histories(self):
        assert not Register("A").is_valid_history(
            [(READ, ReadOk("B")), (Write("B"), WRITE_OK)]
        )
        assert not Register("A").is_valid_history(
            [(Write("B"), WRITE_OK), (READ, ReadOk("A"))]
        )


class TestWORegisterSpec:
    def test_write_once(self):
        r = WORegister(None)
        assert r.invoke(WoWrite("A")) == WO_WRITE_OK
        assert r.invoke(WoWrite("A")) == WO_WRITE_OK  # same value ok
        assert r.invoke(WoWrite("B")) == WO_WRITE_FAIL
        assert r.invoke(WO_READ) == WoReadOk(("Some", "A"))


class TestLinearizability:
    def test_rejects_invalid_history(self):
        t = LinearizabilityTester(Register("A"))
        t.on_invoke(99, Write("B"))
        with pytest.raises(ValueError, match="already has an operation in flight"):
            t.on_invoke(99, Write("C"))
        t2 = LinearizabilityTester(Register("A"))
        t2.on_invret(99, Write("B"), WRITE_OK)
        t2.on_invret(99, Write("C"), WRITE_OK)
        with pytest.raises(ValueError, match="no in-flight invocation"):
            t2.on_return(99, WRITE_OK)

    def test_identifies_linearizable_register_history(self):
        t = LinearizabilityTester(Register("A"))
        t.on_invoke(0, Write("B"))
        t.on_invret(1, READ, ReadOk("A"))
        assert t.serialized_history() == [(READ, ReadOk("A"))]

        t = LinearizabilityTester(Register("A"))
        t.on_invoke(0, READ)
        t.on_invoke(1, Write("B"))
        t.on_return(0, ReadOk("B"))
        assert t.serialized_history() == [
            (Write("B"), WRITE_OK),
            (READ, ReadOk("B")),
        ]

    def test_identifies_unlinearizable_register_history(self):
        t = LinearizabilityTester(Register("A"))
        t.on_invret(0, READ, ReadOk("B"))
        assert t.serialized_history() is None

        t = LinearizabilityTester(Register("A"))
        t.on_invret(0, READ, ReadOk("B"))
        t.on_invoke(1, Write("B"))
        assert t.serialized_history() is None  # SC but not linearizable

    def test_identifies_linearizable_vec_history(self):
        t = LinearizabilityTester(VecSpec())
        t.on_invoke(0, Push(10))
        assert t.serialized_history() == []

        t = LinearizabilityTester(VecSpec())
        t.on_invoke(0, Push(10))
        t.on_invret(1, POP, PopOk(None))
        assert t.serialized_history() == [(POP, PopOk(None))]

        t = LinearizabilityTester(VecSpec())
        t.on_invoke(0, Push(10))
        t.on_invret(1, POP, PopOk(("Some", 10)))
        assert t.serialized_history() == [
            (Push(10), PUSH_OK),
            (POP, PopOk(("Some", 10))),
        ]

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PUSH_OK)
        t.on_invoke(0, Push(20))
        t.on_invret(1, LEN, LenOk(1))
        t.on_invret(1, POP, PopOk(("Some", 20)))
        t.on_invret(1, POP, PopOk(("Some", 10)))
        assert t.serialized_history() == [
            (Push(10), PUSH_OK),
            (LEN, LenOk(1)),
            (Push(20), PUSH_OK),
            (POP, PopOk(("Some", 20))),
            (POP, PopOk(("Some", 10))),
        ]

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PUSH_OK)
        t.on_invoke(1, LEN)
        t.on_invoke(0, Push(20))
        t.on_return(1, LenOk(2))
        assert t.serialized_history() == [
            (Push(10), PUSH_OK),
            (Push(20), PUSH_OK),
            (LEN, LenOk(2)),
        ]

    def test_identifies_unlinearizable_vec_history(self):
        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PUSH_OK)
        t.on_invret(1, POP, PopOk(None))
        assert t.serialized_history() is None  # SC but not linearizable

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PUSH_OK)
        t.on_invoke(1, LEN)
        t.on_invoke(0, Push(20))
        t.on_return(1, LenOk(0))
        assert t.serialized_history() is None

        t = LinearizabilityTester(VecSpec())
        t.on_invret(0, Push(10), PUSH_OK)
        t.on_invoke(0, Push(20))
        t.on_invret(1, LEN, LenOk(2))
        t.on_invret(1, POP, PopOk(("Some", 10)))
        t.on_invret(1, POP, PopOk(("Some", 20)))
        assert t.serialized_history() is None


class TestSequentialConsistency:
    def test_accepts_stale_read_disallowed_by_linearizability(self):
        # Thread 1's read may be ordered before thread 0's completed write.
        t = SequentialConsistencyTester(Register("A"))
        t.on_invret(0, Write("B"), WRITE_OK)
        t.on_invret(1, READ, ReadOk("A"))
        assert t.serialized_history() == [
            (READ, ReadOk("A")),
            (Write("B"), WRITE_OK),
        ]
        lt = LinearizabilityTester(Register("A"))
        lt.on_invret(0, Write("B"), WRITE_OK)
        lt.on_invret(1, READ, ReadOk("A"))
        assert lt.serialized_history() is None

    def test_respects_program_order(self):
        t = SequentialConsistencyTester(Register("A"))
        t.on_invret(0, Write("B"), WRITE_OK)
        t.on_invret(0, READ, ReadOk("A"))  # own stale read: invalid under SC
        assert t.serialized_history() is None

    def test_is_consistent(self):
        t = SequentialConsistencyTester(Register("A"))
        assert t.is_consistent()
        t.on_invret(0, READ, ReadOk("A"))
        assert t.is_consistent()


class TestTesterValueSemantics:
    def test_clone_and_hash(self):
        from stateright_tpu import stable_hash

        t = LinearizabilityTester(Register("A"))
        t.on_invoke(0, Write("B"))
        c = t.clone()
        assert t == c
        assert stable_hash(t) == stable_hash(c)
        c.on_return(0, WRITE_OK)
        assert t != c
        assert stable_hash(t) != stable_hash(c)
