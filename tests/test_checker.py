"""Checker-level tests: eventually-property semantics (including documented
false negatives), report output, visitors, builder plumbing.

Mirrors ``src/checker.rs`` test modules.
"""

import io

from fixtures import BinaryClock, DGraph, LinearEquation
from stateright_tpu import (
    PathRecorder,
    Property,
    WriteReporter,
    fingerprint,
)


def eventually_odd():
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


class TestEventuallyPropertyChecker:
    def test_can_validate(self):
        (
            DGraph.with_property(eventually_odd())
            .with_path([1])  # satisfied at terminal init
            .with_path([2, 3])  # satisfied at nonterminal init
            .with_path([2, 6, 7])  # satisfied at terminal next
            .with_path([4, 9, 10])  # satisfied at nonterminal next
            .check()
            .assert_properties()
        )
        for path in ([1], [2, 3], [2, 6, 7], [4, 9, 10]):
            DGraph.with_property(eventually_odd()).with_path(
                list(path)
            ).check().assert_properties()

    def test_can_discover_counterexample(self):
        d = (
            DGraph.with_property(eventually_odd())
            .with_path([0, 1])
            .with_path([0, 2])
            .check()
            .discovery("odd")
        )
        assert d.into_states() == [0, 2]
        d = (
            DGraph.with_property(eventually_odd())
            .with_path([0, 1])
            .with_path([2, 4])
            .check()
            .discovery("odd")
        )
        assert d.into_states() == [2, 4]
        d = (
            DGraph.with_property(eventually_odd())
            .with_path([0, 1, 4, 6])
            .with_path([2, 4, 8])
            .check()
            .discovery("odd")
        )
        assert d.into_states() == [2, 4, 6]

    def test_fixme_can_miss_counterexample_when_revisiting_a_state(self):
        # Documented reference false-negative semantics (cycles / DAG joins are
        # not treated as terminal): reproduce, do not "fix".
        assert (
            DGraph.with_property(eventually_odd())
            .with_path([0, 2, 4, 2])  # cycle
            .check()
            .discovery("odd")
            is None
        )
        assert (
            DGraph.with_property(eventually_odd())
            .with_path([0, 2, 4])
            .with_path([1, 4, 6])  # revisiting 4
            .check()
            .discovery("odd")
            is None
        )


class TestReport:
    def test_report_includes_property_names_and_paths(self):
        out = io.StringIO()
        LinearEquation(2, 10, 14).checker().spawn_bfs().join().report(
            WriteReporter(out)
        )
        output = out.getvalue()
        assert "Done. states=15, unique=12, depth=4, sec=" in output
        fp = fingerprint
        expected_path = "/".join(
            str(fp(s)) for s in [(0, 0), (1, 0), (2, 0), (2, 1)]
        )
        assert output.endswith(
            'Discovered "solvable" example Path[3]:\n'
            "- 'IncreaseX'\n"
            "- 'IncreaseX'\n"
            "- 'IncreaseY'\n"
            f"Fingerprint path: {expected_path}\n"
        )

    def test_dfs_report(self):
        out = io.StringIO()
        LinearEquation(2, 10, 14).checker().spawn_dfs().join().report(
            WriteReporter(out)
        )
        output = out.getvalue()
        assert "Done. states=55, unique=55, depth=28, sec=" in output
        assert 'Discovered "solvable" example Path[27]:' in output


class TestVisitor:
    def test_path_recorder_records_all_paths(self):
        recorder = PathRecorder()
        BinaryClock().checker().visitor(recorder).spawn_bfs().join()
        # 2 init states, each visited once (the other init is its successor).
        actions = sorted(
            tuple(p.into_actions()) for p in recorder.paths
        )
        assert actions == [(), ()]

    def test_fn_visitor(self):
        seen = []
        LinearEquation(2, 10, 14).checker().visitor(
            lambda path: seen.append(path.last_state())
        ).spawn_bfs().join()
        assert (0, 0) in seen


class TestBuilder:
    def test_property_lookup(self):
        model = BinaryClock()
        assert model.property("in [0, 1]").name == "in [0, 1]"
        try:
            model.property("nope")
            assert False
        except KeyError:
            pass

    def test_is_done_after_join(self):
        checker = BinaryClock().checker().spawn_bfs().join()
        assert checker.is_done()
        assert checker.max_depth() >= 1
