"""scripts/bench_compare.py: loader shapes (BENCH wrapper, raw bench
line, bare leg line, torn tail), the regression gate's exit codes, and
the trajectory table."""

import json
import os
import subprocess
import sys

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO_DIR, "scripts", "bench_compare.py")

sys.path.insert(0, os.path.join(REPO_DIR, "scripts"))

from bench_compare import load_rates  # noqa: E402


def _bench_line(value, **leg_rates):
    line = {"metric": "2pc-7 exhaustive", "value": value,
            "unit": "unique states/sec", "host_rate": 1234.5}
    for leg, rate in leg_rates.items():
        line[f"{leg}_rate"] = rate
    return line


def _wrapper(path, line, parsed=True):
    text = json.dumps(line)
    record = {
        "n": 6, "cmd": "python bench.py", "rc": 0,
        "tail": text + "\n",
        "parsed": line if parsed else None,
    }
    path.write_text(json.dumps(record))
    return str(path)


def _run(*argv):
    return subprocess.run(
        [sys.executable, SCRIPT, *argv],
        capture_output=True, text=True, timeout=60,
    )


def test_load_rates_from_wrapper_parsed(tmp_path):
    path = _wrapper(
        tmp_path / "a.json",
        _bench_line(9000.0, paxos=5000.0, ilock=100.0),
    )
    rates, advisory, note = load_rates(path)
    assert rates == {"2pc": 9000.0, "paxos": 5000.0, "ilock": 100.0}
    assert note is None
    assert "host" not in rates  # host_rate is the baseline, not a leg


def test_load_rates_salvages_truncated_tail(tmp_path):
    """A killed bench tears the tail mid-line; every complete key it
    still carries must be salvaged (BENCH_r04/r05 really look like this:
    parsed=null, 2000-char tail starting mid-JSON)."""
    text = json.dumps(_bench_line(9000.0, paxos=5000.0, scr4=8863.0))
    record = {"n": 5, "rc": 0, "parsed": None,
              "tail": text[len(text) // 2:]}  # torn: keeps the late keys
    path = tmp_path / "torn.json"
    path.write_text(json.dumps(record))
    rates, _, note = load_rates(str(path))
    assert "scr4" in rates and rates["scr4"] == 8863.0
    assert note is not None  # salvage is flagged to stderr


def test_load_rates_bare_leg_line(tmp_path):
    path = tmp_path / "smoke.json"
    path.write_text(json.dumps({"rate": 4321.0, "unique": 8832,
                                "device": "cpu", "advisory": True}))
    rates, advisory, _ = load_rates(str(path), as_leg="smoke")
    assert rates == {"smoke": 4321.0}
    assert advisory == {"smoke"}


def test_gate_passes_within_threshold(tmp_path):
    old = _wrapper(tmp_path / "old.json", _bench_line(9000.0, paxos=5000.0))
    new = _wrapper(tmp_path / "new.json", _bench_line(8500.0, paxos=5100.0))
    r = _run(old, new, "--threshold", "0.10")  # 2pc -5.6%: inside
    assert r.returncode == 0, r.stderr
    assert "REGRESSION" not in r.stdout


def test_gate_exits_nonzero_on_breach(tmp_path):
    old = _wrapper(tmp_path / "old.json", _bench_line(9000.0, paxos=5000.0))
    new = _wrapper(tmp_path / "new.json", _bench_line(7000.0, paxos=5100.0))
    r = _run(old, new, "--threshold", "0.10")  # 2pc -22%: breach
    assert r.returncode == 1
    assert "REGRESSION" in r.stderr
    assert "2pc" in r.stderr


def test_advisory_legs_never_gate(tmp_path):
    old_line = _bench_line(9000.0, ilock=4786.0)
    old_line["ilock_advisory"] = True
    new_line = _bench_line(9000.0, ilock=2847.0)  # -40%, but advisory
    new_line["ilock_advisory"] = True
    old = _wrapper(tmp_path / "old.json", old_line)
    new = _wrapper(tmp_path / "new.json", new_line)
    r = _run(old, new, "--threshold", "0.10")
    assert r.returncode == 0, r.stderr + r.stdout
    assert "advisory" in r.stdout


def test_dropped_leg_gates_and_new_leg_does_not(tmp_path):
    """A leg that vanished from the new file is a gate breach (a crashed
    leg is worse than a slow one); a brand-new leg is not."""
    old = _wrapper(tmp_path / "old.json", _bench_line(9000.0, paxos=5000.0))
    new = _wrapper(tmp_path / "new.json", _bench_line(8900.0, scr4=8000.0))
    r = _run(old, new)
    assert r.returncode == 1
    assert "DROPPED (gate)" in r.stdout  # paxos missing from new
    assert "(new leg)" in r.stdout  # scr4 missing from old
    assert "paxos" in r.stderr
    r = _run(old, new, "--legs", "2pc,paxos,scr4")
    assert "2pc" in r.stdout
    r = _run(old, new, "--legs", "2pc")
    assert r.returncode == 0  # the shared leg alone is within threshold
    assert "paxos" not in r.stdout and "scr4" not in r.stdout


def test_no_shared_legs_is_table_only(tmp_path):
    """Zero overlap (e.g. a fresh single-leg file vs a full bench line)
    is not a comparable trajectory: table + warning, no gate."""
    old = _wrapper(tmp_path / "old.json", _bench_line(9000.0))
    new = tmp_path / "smoke.json"
    new.write_text(json.dumps({"rate": 4321.0, "unique": 8832}))
    r = _run(old, str(new), "--as-leg", "smoke")
    assert r.returncode == 0, r.stderr + r.stdout
    assert "no shared legs" in r.stderr


def test_legs_filter_typo_errors_instead_of_vacuous_pass(tmp_path):
    old = _wrapper(tmp_path / "old.json", _bench_line(9000.0))
    new = _wrapper(tmp_path / "new.json", _bench_line(100.0))  # -98.9%
    r = _run(old, new, "--legs", "2pc5")  # typo'd leg name
    assert r.returncode == 2
    assert "matches no leg" in r.stderr


def test_trajectory_table_over_three_files(tmp_path):
    paths = [
        _wrapper(tmp_path / f"r{i}.json", _bench_line(1000.0 * i))
        for i in (1, 2, 3)
    ]
    r = _run(*paths)
    assert r.returncode == 0
    assert "r1.json" in r.stdout and "r3.json" in r.stdout
    assert "3,000.0" in r.stdout


def test_real_trajectory_files_compare():
    """The committed BENCH_r04 vs r05 (both torn-tail shapes) must load
    and diff without a gate breach at a loose threshold — the CPU-cheap
    verify-recipe invocation."""
    r = _run(
        os.path.join(REPO_DIR, "BENCH_r04.json"),
        os.path.join(REPO_DIR, "BENCH_r05.json"),
        "--threshold", "0.9",
    )
    assert r.returncode == 0, r.stderr + r.stdout
    assert "leg" in r.stdout


def test_unreadable_input_exits_two(tmp_path):
    path = tmp_path / "empty.json"
    path.write_text("{}")
    r = _run(str(path), str(path))
    assert r.returncode == 2
    assert "no leg rates" in r.stderr
