"""Fleet observability plane (ISSUE 18): the per-shard skew-forensics
fold and its ``fleet.*`` metric family, the injected-straggler
attribution gate (a chaos-stalled shard must be *named* by both the live
registry and the offline ``gap_report --fleet`` reader), the <5%
off-path overhead budget, the SLO ledger's exact ttfv decomposition and
burn-rate math, the service's run-registry LRU, and the registry-hygiene
lint over the two new metric families."""

import math
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.parallel import default_mesh
from stateright_tpu.service.slo import SLOLedger, decompose_ttfv
from stateright_tpu.telemetry import get_tracer, metrics_registry
from stateright_tpu.telemetry.fleet import FLEET_COLS, SKEW_COLS, FleetFold
from stateright_tpu.telemetry.metrics import MetricsRegistry, run_registries
from stateright_tpu.telemetry.server import registry_hygiene_problems
from stateright_tpu.utils.faults import FaultSpec, inject

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_DIR, "scripts"))

from gap_report import collect_fleet, fleet_block  # noqa: E402
from trace_summary import load_events  # noqa: E402


# -- the pure fold -----------------------------------------------------------


def test_fold_totals_skew_and_straggler():
    fold = FleetFold(n_shards=4)
    for _ in range(8):
        fold.consume({
            "live_lanes": [10.0, 10.0, 10.0, 10.0],
            "insert_load": [4.0, 4.0, 4.0, 20.0],
        })
    s = fold.summary()
    assert s["shards"] == 4 and s["waves"] == 8
    assert s["per_shard"][3]["insert_load"] == 160.0
    # No host walls anywhere -> the cost vector is the insert load, and
    # shard 3 carries it every wave.
    top = s["stragglers"][0]
    assert top["shard"] == 3
    assert top["persistence"] == 1.0
    assert top["score"] > 1.0
    assert s["skew"]["insert_load"]["max_over_mean"] == pytest.approx(2.5)
    assert s["skew"]["live_lanes"]["max_over_mean"] == pytest.approx(1.0)
    # A host tier wall, once present, preempts insert load as the cost.
    out = fold.consume({
        "live_lanes": [10.0] * 4,
        "insert_load": [4.0, 4.0, 4.0, 20.0],
        "probe_ms": [0.0, 50.0, 0.0, 0.0],
    })
    assert out["cost_skew"]["max_over_mean"] == pytest.approx(4.0)
    assert fold.slowest[1] == 1


def test_fold_span_args_round_trip():
    # The monitor / gap_report path replays the wave-span args through a
    # second fold — the two folds must agree exactly.
    rows = {
        "live_lanes": np.array([5.0, 6.0, 7.0]),
        "insert_load": np.array([1.0, 2.0, 3.0]),
        "probe_ms": np.array([0.25, 0.5, 0.125]),
    }
    args = FleetFold.span_args(rows, shards=3, hosts=1)
    assert args["fleet_shards"] == 3
    assert args["fleet_live_lanes"] == [5.0, 6.0, 7.0]
    direct, replay = FleetFold(), FleetFold()
    direct.consume(rows, waves=2)
    replay.consume_span_args({**args, "waves": 2})
    assert replay.summary() == direct.summary()
    # Spans without fleet columns are ignored, not misfolded.
    replay.consume_span_args({"keys": 512})
    assert replay.summary() == direct.summary()


# -- the live family on a real sharded run -----------------------------------


def _sharded_2pc3(fleet):
    metrics_registry().reset()
    t0 = time.perf_counter()
    ck = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(
            frontier_per_device=64, table_capacity_per_device=256,
            fleet=fleet,
        )
        .join()
    )
    wall = time.perf_counter() - t0
    assert ck.worker_error() is None
    return ck, wall, metrics_registry().snapshot()


def test_fleet_family_and_overhead_budget():
    ck, wall, snap = _sharded_2pc3(fleet=True)
    assert ck.unique_state_count() == 288
    assert snap["sharded_bfs.fleet.waves"] >= ck.max_depth()
    assert 0 <= int(snap["sharded_bfs.fleet.straggler.shard"]) < 8
    loads = [
        snap.get(f"sharded_bfs.fleet.shard.{d}.insert_load", 0.0)
        for d in range(8)
    ]
    assert sum(loads) > 0
    # Acceptance (ISSUE 18): the fold's self-measured cost stays under
    # the 5% budget — measured, not asserted on faith.
    assert snap["sharded_bfs.fleet.overhead_seconds"] < 0.05 * wall
    # The family the run just registered is hygiene-clean end to end.
    assert registry_hygiene_problems(metrics_registry()) == []


def test_fleet_off_is_bit_identical_and_free():
    on, _, snap_on = _sharded_2pc3(fleet=True)
    off, _, snap_off = _sharded_2pc3(fleet=False)
    assert snap_on["sharded_bfs.fleet.waves"] > 0
    assert on.unique_state_count() == off.unique_state_count() == 288
    assert on.state_count() == off.state_count()
    assert on.max_depth() == off.max_depth()
    assert set(on.discoveries()) == set(off.discoveries())
    assert not [k for k in snap_off if ".fleet." in k]


# -- the injected-straggler attribution gate ---------------------------------


def test_injected_straggler_is_attributed(tmp_path):
    """The ISSUE 18 acceptance test: stall exactly one shard's host-tier
    probe through the PR 13 chaos seam and demand the fleet forensics
    name that shard — in the live ``fleet.straggler.*`` gauges AND in
    the offline ``gap_report --fleet`` view of the run's trace — while
    the verdict stays exact. 2pc-5 is the smallest mesh-shaped space
    whose visited set exceeds the 4-shard admission floor (the per-shard
    table must absorb one 4x-skewed wave), so it is the cheapest run
    where the budget genuinely binds and the probe seam fires."""
    model = TwoPhaseSys(5)
    n, frontier = 4, 8
    # The tiny-budget recipe (test_storage_equivalence): cap L0 below
    # the visited-set size so late waves probe the host tiers.
    rows = 1 << math.ceil(
        math.log2(n * frontier * model.packed_action_count() / 0.5 + 1)
    )
    budget_mib = ((rows + 128) * 8) / (1 << 20)
    trace = tmp_path / "fleet_trace.jsonl"
    sink = get_tracer().add_sink(str(trace))
    metrics_registry().reset()
    try:
        with inject(
            FaultSpec(
                "storage.host_probe", tenant="shard-2",
                at=0, count=10 ** 6, stall_s=0.02,
            )
        ) as inj:
            ck = (
                TwoPhaseSys(5)
                .checker()
                .spawn_sharded_tpu_bfs(
                    mesh=default_mesh(n),
                    frontier_per_device=frontier,
                    table_capacity_per_device=1 << 14,
                    hbm_budget_mib=budget_mib,
                )
                .join()
            )
        assert ck.worker_error() is None
    finally:
        get_tracer().remove_sink(sink)
    assert inj.triggered() >= 3, "stall seam never fired — budget not binding?"
    assert ck.unique_state_count() == 8832
    # Live side: the registry names shard 2.
    snap = metrics_registry().snapshot()
    assert int(snap["sharded_bfs.fleet.straggler.shard"]) == 2
    assert snap["sharded_bfs.fleet.straggler.score"] > 1.5
    # Trace side: the stdlib reader reconstructs the same verdict.
    blk = fleet_block(collect_fleet(load_events(str(trace)))["sharded_bfs"])
    assert blk["stragglers"][0]["shard"] == 2
    assert blk["skew"]["probe_ms"]["max_over_mean"] > 2.0
    # And the CLI renders it by name.
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_DIR, "scripts", "gap_report.py"),
            str(trace), "--fleet",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "straggler: shard 2" in out.stdout


# -- SLO decomposition + ledger ----------------------------------------------


def test_slo_decomposition_partitions_exactly():
    d = decompose_ttfv(10.0, 2.5, 3.0)
    assert d == {
        "ttfv_s": 10.0, "queue_s": 2.5, "compile_s": 3.0, "explore_s": 4.5,
    }
    # Clamped: a discovery landing mid-compile never reports overlapping
    # phases — the three always sum to ttfv exactly.
    d = decompose_ttfv(5.0, 4.0, 3.0)
    assert (d["queue_s"], d["compile_s"], d["explore_s"]) == (4.0, 1.0, 0.0)
    d = decompose_ttfv(2.0, -1.0, 0.5)
    assert d["queue_s"] == 0.0 and d["explore_s"] == 1.5
    assert decompose_ttfv(None, 1.0, 1.0) is None


class _Job:
    """The minimal surface ``SLOLedger.observe`` reads off a CheckJob."""

    def __init__(self, job_id, mode, *, packed=False, wall=2.0,
                 queued=0.2, ttfv=0.5, warmup=0.1):
        self.job_id = job_id
        self.mode = mode
        self.packed = packed
        self.warmup_s = warmup
        self._lat = {"wall_s": wall, "queued_s": queued, "ttfv_s": ttfv}

    def latency(self):
        return dict(self._lat)


def test_slo_ledger_percentiles_and_burn_rate():
    reg = MetricsRegistry()
    led = SLOLedger(
        targets={"ttfv_s": 1.0, "verdict_s": 10.0, "objective": 0.9},
        registry=reg,
    )
    for i in range(10):
        led.observe(_Job(
            f"j{i}", "exhaustive", ttfv=(5.0 if i >= 8 else 0.5),
        ))
    view = led.snapshot()["modes"]["exhaustive"]
    assert view["jobs"] == 10
    assert view["ttfv"]["p50_s"] == 0.5
    assert view["decomposition"]["queue_s"]["p50_s"] == 0.2
    assert view["last"]["decomposition"]["explore_s"] == pytest.approx(4.7)
    # 2/10 ttfv violations against a 10% error budget -> burn rate 2.0;
    # verdicts all under target -> burn 0.
    assert view["burn_rate"]["ttfv"] == pytest.approx(2.0)
    assert view["burn_rate"]["verdict"] == 0.0
    # The packed flag wins over the base mode (a packed exhaustive job
    # is a "packed" row — its latency profile is the multiplexer's).
    led.observe(_Job("p0", "exhaustive", packed=True))
    assert led.snapshot()["modes"]["packed"]["jobs"] == 1
    # The published gauges mirror the view.
    snap = reg.snapshot()
    assert snap["slo.exhaustive.ttfv_p50_s"] == 0.5
    assert snap["slo.exhaustive.ttfv_burn_rate"] == pytest.approx(2.0)


def test_slo_ledger_rejects_bad_targets():
    with pytest.raises(ValueError):
        SLOLedger(targets={"objective": 1.5}, registry=MetricsRegistry())
    with pytest.raises(ValueError):
        SLOLedger(targets={"nope_s": 1.0}, registry=MetricsRegistry())


# -- registry hygiene over the new families ----------------------------------


def test_fleet_and_slo_metric_families_hygiene():
    # The PR 8 lint extended to the two ISSUE 18 families: every name
    # the fleet fold or the SLO ledger can register must survive the
    # Prometheus sanitizer without collisions.
    reg = MetricsRegistry()
    reg.counter("sharded_bfs.fleet.waves")
    reg.counter("service.registry_evicted")
    reg.gauge("sharded_bfs.fleet.overhead_seconds")
    for g in ("shard", "score", "persistence"):
        reg.gauge(f"sharded_bfs.fleet.straggler.{g}")
    for d in range(8):
        for col in FLEET_COLS:
            reg.gauge(f"sharded_bfs.fleet.shard.{d}.{col}")
    for col in SKEW_COLS + ("cost",):
        reg.gauge(f"sharded_bfs.fleet.skew.{col}.max_over_mean")
        reg.gauge(f"sharded_bfs.fleet.skew.{col}.cv")
    # The SLO ledger registers its real names itself — observe one job
    # per mode with both targets so every gauge family materializes.
    led = SLOLedger(
        targets={"ttfv_s": 1.0, "verdict_s": 10.0, "objective": 0.9},
        registry=reg,
    )
    led.observe(_Job("e0", "exhaustive"))
    led.observe(_Job("s0", "swarm"))
    led.observe(_Job("p0", "swarm", packed=True))
    assert registry_hygiene_problems(reg) == []


# -- service run-registry LRU ------------------------------------------------


def test_service_registry_lru_evicts_and_counts():
    from stateright_tpu.service import CheckService

    spawn = {
        "frontier_capacity": 16,
        "table_capacity": 1 << 12,
        "max_drain_waves": 2,
        "aot_cache": "t-svc",
    }
    metrics_registry().reset()
    svc = CheckService(default_spawn=spawn, max_run_registries=1)
    run_ids = []
    try:
        for _ in range(3):
            h = svc.submit(model_name="2pc", model_args={"rm_count": 3})
            assert h.result(timeout=180)["unique"] == 288
            run_ids.append(svc.job(h.job_id).run_id)
        # Eviction runs on the scheduler loop after the terminal slice —
        # poll rather than race it.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            snap = metrics_registry().snapshot()
            if snap.get("service.registry_evicted", 0) >= 2:
                break
            time.sleep(0.05)
        assert snap.get("service.registry_evicted", 0) >= 2
        live = run_registries()
        assert sum(1 for r in run_ids if r in live) <= 1
        # Evicted jobs keep their records/results — only the live
        # instrument registry is forgotten.
        assert svc.job(run_ids and h.job_id) is not None
    finally:
        svc.close()
