"""Async pipelined wave engine: the host-side machinery in isolation.

The bit-identical equivalence of ``async_pipeline=True`` runs lives in
``tests/test_storage_equivalence.py`` (async legs); this module covers
the machinery underneath it:

- ``HostPipeline`` (checker/pipeline.py): FIFO order, the drain epoch
  barrier, the bounded pending-verdict throttle, and error poisoning.
- The tracer's emit path under two threads (the worker closes wave
  spans concurrently with the checker thread) with the monitor's
  tracer-sink tap and a flight-recorder-style ring read racing it.
- The attribution engine's ``overlapped`` phase class: thread-safe,
  never part of a wave window, mode-aware report fields.
"""

import json
import threading
import time

import pytest

from stateright_tpu.checker.pipeline import HostPipeline
from stateright_tpu.telemetry import metrics_registry
from stateright_tpu.telemetry.attribution import WaveAttribution
from stateright_tpu.telemetry.trace import JsonlSink, Tracer


# -- HostPipeline ----------------------------------------------------------


def test_pipeline_fifo_and_drain():
    pipe = HostPipeline(name="t-fifo")
    seen = []
    for i in range(100):
        pipe.submit(lambda i=i: seen.append(i))
    pipe.drain()
    assert seen == list(range(100)), "jobs must run in submission order"
    assert pipe.pending() == 0
    assert pipe.submitted == 100
    pipe.close()


def test_pipeline_drain_is_epoch_barrier():
    pipe = HostPipeline(name="t-barrier")
    gate = threading.Event()
    done = []
    pipe.submit(gate.wait)
    pipe.submit(lambda: done.append(1))
    assert pipe.pending() == 2
    gate.set()
    pipe.drain()
    assert done == [1]
    pipe.close()


def test_pipeline_throttle_bounds_backlog():
    pipe = HostPipeline(name="t-throttle", max_pending=2)
    gate = threading.Event()
    pipe.submit(gate.wait)
    pipe.submit(lambda: None)
    # Backlog == max_pending: throttle returns immediately.
    pipe.throttle()
    pipe.submit(lambda: None)
    t = threading.Thread(target=pipe.throttle)
    t.start()
    t.join(timeout=0.2)
    assert t.is_alive(), "throttle must block while backlog > max_pending"
    gate.set()
    t.join(timeout=5.0)
    assert not t.is_alive()
    pipe.drain()
    pipe.close()


def test_pipeline_error_poisons_and_surfaces():
    pipe = HostPipeline(name="t-poison")
    ran = []

    def boom():
        raise ValueError("verdict failed")

    pipe.submit(boom)
    try:
        # Either outcome is correct, and which one happens is a race:
        # enqueued-then-skipped (worker hadn't run boom yet) or refused
        # outright (already poisoned).
        pipe.submit(lambda: ran.append(1))
    except RuntimeError:
        pass
    with pytest.raises(RuntimeError) as ei:
        pipe.drain()
    assert isinstance(ei.value.__cause__, ValueError)
    assert ran == [], "jobs after a failure must not run"
    with pytest.raises(RuntimeError):
        pipe.submit(lambda: None)
    pipe.close()


def test_pipeline_close_idempotent():
    pipe = HostPipeline(name="t-close")
    pipe.submit(lambda: None)
    pipe.close()
    pipe.close()
    with pytest.raises(RuntimeError):
        pipe.submit(lambda: None)


# -- two-thread tracer smoke (satellite: ring append lock + monitor tap) ---


def test_tracer_two_thread_emit_with_monitor_tap(tmp_path):
    """Two threads emit wave spans into one tracer feeding a JSONL sink
    AND the monitor's tracer-sink tap, while a third reader does
    flight-recorder-style ring reads. No torn lines, no sink errors, no
    lost events at the sinks."""
    from stateright_tpu.telemetry.server import MonitorCore

    registry = metrics_registry("t-two-thread")
    registry.reset()
    tracer = Tracer()
    path = tmp_path / "events.jsonl"
    sink = tracer.add_sink(JsonlSink(str(path)))
    core = MonitorCore(registry=registry, tracer=tracer)
    # 2 × 150 spans: enough to interleave constantly, small enough to
    # respect the tier-1 wall budget (the sink flushes per write).
    N = 150
    stop = threading.Event()

    def emitter(tid):
        for i in range(N):
            with tracer.span(
                "tpu_bfs.wave", wave=i, thread=tid
            ) as sp:
                sp.set(new_unique=1, generated=2, frontier=8)

    def ring_reader():
        while not stop.is_set():
            events = tracer.events()
            assert isinstance(events, list)
            time.sleep(0.001)

    reader = threading.Thread(target=ring_reader)
    reader.start()
    threads = [
        threading.Thread(target=emitter, args=(t,)) for t in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    reader.join()
    tracer.remove_sink(core, close=False)
    tracer.remove_sink(sink)
    core.close()

    lines = path.read_text().strip().splitlines()
    assert len(lines) == 2 * N, "sink must see every span exactly once"
    for line in lines:
        json.loads(line)  # no torn/interleaved writes
    snap = registry.snapshot()
    assert snap.get("monitor.sink_errors", 0) == 0
    assert snap.get("monitor.wave_events", 0) == 2 * N


# -- attribution: overlapped phase class -----------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            return self.t

    def advance(self, dt):
        with self._lock:
            self.t += dt


def test_attribution_overlapped_ledger():
    clock = FakeClock()
    registry = metrics_registry("t-overlap")
    registry.reset()
    attr = WaveAttribution("tpu_bfs", clock=clock, registry=registry,
                           tracer=Tracer())
    attr.set_overlap_mode(True)
    # One wave window on the "checker thread": 1.0s wall, 0.6s device.
    with attr.wave():
        with attr.phase("device"):
            clock.advance(0.6)
        # Worker-thread host work DURING the window must not join the
        # window's phase set (it is shadowed time, not serial wall) —
        # and must not trip the non-reentrant phase guard.
        with attr.overlapped("host_probe"):
            clock.advance(0.25)
        with attr.overlapped("checkpoint"):
            clock.advance(0.15)
    report = attr.report()
    assert report["overlap_mode"] is True
    assert report["overlapped_s"]["host_probe"] == pytest.approx(0.25)
    assert report["overlapped_s"]["checkpoint"] == pytest.approx(0.15)
    assert report["overlapped_total_s"] == pytest.approx(0.40)
    # The wave's wall includes the time the fake clock advanced inside
    # the overlapped windows (single-threaded fake), but phases_s must
    # only carry the device phase — overlapped time lands in gap, and
    # the ledger never overruns (mode-aware invariant).
    assert set(report["phases_s"]) == {"device"}
    assert report["phases_s"]["device"] == pytest.approx(0.6)
    assert report["overrun_s"] == 0.0
    assert report["within_tolerance"] is True
    snap = registry.snapshot()
    assert snap["tpu_bfs.pipeline.overlapped_seconds"] == pytest.approx(0.4)
    assert snap["tpu_bfs.pipeline.overlapped.host_probe_seconds"] == (
        pytest.approx(0.25)
    )


def test_attribution_overlapped_thread_safe():
    """Overlapped windows record from many threads concurrently without
    losing time (the ledger is lock-guarded; wall clock here)."""
    registry = metrics_registry("t-overlap-mt")
    registry.reset()
    attr = WaveAttribution("tpu_bfs", registry=registry, tracer=Tracer())

    def work():
        for _ in range(50):
            with attr.overlapped("host_probe"):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    report = attr.report()
    assert report["overlapped_s"]["host_probe"] >= 0.0
    # 200 windows; each inc'd the counter exactly once.
    spans = [
        e for e in attr._tracer.events()
        if e["name"] == "tpu_bfs.pipeline.overlapped"
    ]
    assert len(spans) == 200


def test_async_pipeline_rejects_visitor():
    """Per-chunk visitors reconstruct paths through verdicts the
    pipeline defers — the combination must refuse loudly, not corrupt."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    class V:
        def visit(self, model, path):
            pass

    with pytest.raises(ValueError, match="async_pipeline"):
        (
            TwoPhaseSys(3)
            .checker()
            .visitor(V())
            .spawn_tpu_bfs(
                frontier_capacity=16,
                table_capacity=1 << 12,
                async_pipeline=True,
            )
        )
