"""Multi-controller execution: the sharded checker over a mesh spanning
two PROCESSES (the local stand-in for multi-host TPU pods — same
``jax.distributed`` path, DCN collectives replaced by Gloo over CPU),
entered through the ``bootstrap_mesh`` entry point.

SURVEY §2.8 / PARITY "known gaps": the reference has no distributed
checking at all; this validates ours end to end — cross-process
``all_to_all``/``psum`` inside the deep drain, allgathered host pulls,
and exact oracle counts on both controllers. The sieve leg additionally
gates the compression-and-sieve routing: identical counts/depths to the
full-width exchange (bit-identity) with strictly fewer shipped lanes.
"""

import os
import re
import socket
import subprocess
import sys
import time

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_pair(mode, timeout=390):
    """Launches the two-process mesh in ``mode``; returns the parsed
    ``MULTIHOST-OK`` fields (identical across pids, asserted) plus the
    wall time — the CI leg reports timing as advisory, not a gate."""
    port = _free_port()
    child = os.path.join(os.path.dirname(__file__), "multihost_child.py")
    # Children must NOT inherit this process's single-device pin or its
    # force-host-device-count; they set their own.
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    t0 = time.perf_counter()
    procs = [
        subprocess.Popen(
            [sys.executable, child, str(i), str(port), mode],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    wall = time.perf_counter() - t0
    fields = []
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
        m = re.search(
            rf"MULTIHOST-OK pid={i} count=(\d+) states=(\d+) "
            rf"depth=(\d+) lanes=(\d+)",
            out,
        )
        assert m, out[-3000:]
        fields.append(tuple(int(g) for g in m.groups()))
    assert fields[0] == fields[1], f"controllers disagree: {fields}"
    if mode == "plain":
        # Fleet observability across the process boundary (ISSUE 18):
        # pid 0 serves a live monitor during the run and asserts its
        # /fleet view carries all 8 per-shard rows (4 owned by pid 1)
        # with real load, printing this line only on success.
        assert re.search(r"FLEET-OK pid=0 shards=8", outs[0]), \
            outs[0][-3000:]
    return fields[0], wall


# Each pair-launch costs two cold jax processes (imports + distributed
# init + compiles), which dominates wall time on small CI boxes — so the
# full-width baseline runs ONCE and both tests read it from here.
_PLAIN = {}


def _plain_pair():
    if "fields" not in _PLAIN:
        _PLAIN["fields"], _PLAIN["wall"] = _run_pair("plain")
    return _PLAIN["fields"], _PLAIN["wall"]


# The full-run legs are `slow`: each pair costs ~30-60s of compile on a
# small box, which blows the flat `-m 'not slow'` tier-1 budget. CI
# still runs them every push — the tier1.yml multi-process smoke step
# invokes this file with `-m 'slow or not slow'`. The evict_exchange
# leg below stays fast, so the flat suite always crosses a real process
# boundary (bootstrap_mesh + gloo allgathers) at least once.


@pytest.mark.slow
def test_two_process_mesh_exact_count():
    (count, _, _, _), wall = _plain_pair()
    assert count == 288
    print(f"[advisory] plain 2-process wall: {wall:.1f}s")


@pytest.mark.slow
def test_two_process_mesh_sieve_bit_identical():
    """Sieve on vs off across a real 2-process mesh: same counts, same
    depth (bit-identity gate), strictly fewer shipped lanes. Timing is
    printed as an advisory, never asserted — CI machines vary."""
    plain, wall_off = _plain_pair()
    sieved, wall_on = _run_pair("sieve")
    assert sieved[:3] == plain[:3], (plain, sieved)
    assert sieved[3] < plain[3], (
        f"sieve shipped {sieved[3]} lanes, full-width {plain[3]}"
    )
    print(
        f"[advisory] sieve off {wall_off:.1f}s / on {wall_on:.1f}s; "
        f"lanes {plain[3]} -> {sieved[3]}"
    )


def test_two_process_evict_exchange():
    """The compressed eviction path across a real 2-process mesh: the
    child drives ``_allgather_evicted_keys`` over a synthetic sharded
    table with known per-shard keys and asserts both controllers decode
    the identical ground truth; the parsed line carries the decoded key
    total and the wire byte count (in the ``lanes`` slot), compared
    across pids by ``_run_pair`` and bounded here against the raw table
    size (8 shards x 256 rows x 8 B).

    A full out-of-core run (hbm budget tripping mid-run, ~10 small
    collectives/wave over ~140 waves) is deliberately NOT exercised
    across processes: it trips an upstream XLA:CPU gloo limitation —
    sends are matched to receives by connection slot order, not tags,
    so overlapped small collectives sporadically abort with
    EnforceNotMet size mismatches long before any eviction runs
    (host-side wave traces were verified bit-identical across the two
    controllers, sieve on and off). Out-of-core correctness is covered
    single-process by test_comm_sieve.py::
    test_sieve_out_of_core_eviction_flushes; this leg pins the one
    genuinely cross-process piece, the compressed exchange itself."""
    (keys, _, _, wire), wall = _run_pair("evict_exchange", timeout=180)
    assert keys == 671  # sum of 40 + 17*d over the 7 non-empty shards
    assert 0 < wire < 8 * 256 * 8
    print(
        f"[advisory] evict-exchange 2-process wall: {wall:.1f}s, "
        f"wire {wire} B vs raw {8 * 256 * 8} B"
    )
