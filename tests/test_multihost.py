"""Multi-controller execution: the sharded checker over a mesh spanning
two PROCESSES (the local stand-in for multi-host TPU pods — same
``jax.distributed`` path, DCN collectives replaced by Gloo over CPU).

SURVEY §2.8 / PARITY "known gaps": the reference has no distributed
checking at all; this validates ours end to end — cross-process
``all_to_all``/``psum`` inside the deep drain, allgathered host pulls,
and exact oracle counts on both controllers.
"""

import socket
import subprocess
import sys
import os




def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_mesh_exact_count():
    port = _free_port()
    child = os.path.join(os.path.dirname(__file__), "multihost_child.py")
    # Children must NOT inherit this process's single-device pin or its
    # force-host-device-count; they set their own.
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, child, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=390)
            outs.append(out.decode())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
        assert f"MULTIHOST-OK pid={i} count=288" in out, out[-3000:]
