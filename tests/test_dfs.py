"""DFS checker tests — behavioral parity with ``src/checker/dfs.rs`` tests."""

import pytest

from fixtures import LinearEquation, Panicker
from stateright_tpu import StateRecorder


def test_visits_states_in_dfs_order():
    recorder = StateRecorder()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_dfs().join()
    assert recorder.states == [(0, 0)] + [(0, y) for y in range(1, 28)]


@pytest.mark.slow
def test_can_complete_by_enumerating_all_states():
    checker = LinearEquation(2, 4, 7).checker().spawn_dfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 55

    # DFS found this example... (2*0 + 10*27) % 256 == 14
    assert checker.discovery("solvable").into_actions() == ["IncreaseY"] * 27
    checker.assert_discovery("solvable", ["IncreaseX", "IncreaseY", "IncreaseX"])


def test_handles_panics_gracefully():
    with pytest.raises(RuntimeError):
        Panicker().checker().threads(2).spawn_dfs().join()


def test_can_apply_symmetry_reduction():
    # Two interchangeable counters: state (a, b); representative sorts them.
    from stateright_tpu import Model, Property

    class TwoCounters(Model):
        def init_states(self):
            return [(0, 0)]

        def actions(self, state, actions):
            a, b = state
            if a < 3:
                actions.append("IncA")
            if b < 3:
                actions.append("IncB")

        def next_state(self, state, action):
            a, b = state
            return (a + 1, b) if action == "IncA" else (a, b + 1)

        def properties(self):
            return [Property.always("bounded", lambda _, s: max(s) <= 3)]

    full = TwoCounters().checker().spawn_dfs().join()
    reduced = (
        TwoCounters()
        .checker()
        .symmetry_fn(lambda s: tuple(sorted(s)))
        .spawn_dfs()
        .join()
    )
    assert full.unique_state_count() == 16
    assert reduced.unique_state_count() == 10  # multisets {a<=b} of 0..3
    full.assert_properties()
    reduced.assert_properties()
