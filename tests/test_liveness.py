"""Opt-in cycle-aware ``eventually`` checking (`.complete_liveness()`).

The DEFAULT semantics reproduce the reference's documented false negatives
on cycles and DAG joins bit-for-bit (tests/test_checker.py pins that). The
opt-in post-pass closes them: a lasso — a condition-false path closing a
cycle — is exactly an infinite counterexample in a finite space. The
reference has no equivalent (FIXMEs at ``src/checker/bfs.rs:285-305``).
"""

import jax.numpy as jnp

from fixtures import DGraph
from stateright_tpu import Property
from stateright_tpu.core.batch import BatchableModel
from stateright_tpu.core.model import Model


def eventually_odd():
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def test_lasso_found_on_cycle_host_bfs():
    # The reference's own FIXME case: 0 -> 2 -> 4 -> 2 never hits an odd
    # state; default semantics miss it (no terminal state), the lasso pass
    # finds it with a certificate that revisits a state.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4, 2])
        .checker()
        .complete_liveness()
        .spawn_bfs()
        .join()
    )
    path = checker.discoveries().get("odd")
    assert path is not None
    states = path.into_states()
    assert all(s % 2 == 0 for s in states)
    assert states[-1] in states[:-1]  # the lasso certificate


def test_complete_liveness_refuses_capped_runs():
    # The lasso search ignores exploration caps, so a capped run could
    # hang on cap-bounded models and report over-cap certificates.
    import pytest

    with pytest.raises(ValueError):
        (
            DGraph.with_property(eventually_odd())
            .with_path([0, 2])
            .checker()
            .complete_liveness()
            .target_max_depth(3)
            .spawn_bfs()
        )


def test_lasso_found_on_dag_join_cycle_host_dfs():
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4, 2])
        .checker()
        .complete_liveness()
        .spawn_dfs()
        .join()
    )
    assert "odd" in checker.discoveries()


def test_no_lasso_when_cycle_passes_through_satisfying_state():
    # 0 -> 1 -> 2 -> 0 loops, but through odd 1: every infinite path
    # satisfies the property, so the pass must find nothing.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1, 2, 0])
        .checker()
        .complete_liveness()
        .spawn_bfs()
        .join()
    )
    assert checker.discoveries() == {}
    checker.assert_properties()


def test_terminal_counterexample_still_preferred():
    # A terminal even path: the standard semantics find it; the pass must
    # not override the existing discovery.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2])
        .checker()
        .complete_liveness()
        .spawn_bfs()
        .join()
    )
    d = checker.discoveries()["odd"]
    assert d.into_states() == [0, 2]


class _Cycler(Model, BatchableModel):
    """0 -> 1 -> 2 -> 1: the cycle {1, 2} never reaches 3."""

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        actions.append("step")

    def next_state(self, state, action):
        return {0: 1, 1: 2, 2: 1}[state]

    def properties(self):
        return [Property.eventually("three", lambda _, s: s == 3)]

    # -- packed protocol ---------------------------------------------------

    def packed_action_count(self):
        return 1

    def packed_init_states(self):
        return {"s": jnp.zeros((1,), jnp.uint32)}

    def packed_step(self, state, action_id):
        s = state["s"]
        nxt = jnp.where(s == 0, jnp.uint32(1),
                        jnp.where(s == 1, jnp.uint32(2), jnp.uint32(1)))
        return {"s": nxt}, jnp.bool_(True)

    def packed_conditions(self):
        return [lambda st: st["s"] == 3]

    def pack_state(self, host_state):
        import numpy as np

        return {"s": np.uint32(host_state)}

    def unpack_state(self, packed):
        return int(packed["s"])


def test_lasso_pass_composes_with_device_checker():
    # The pass is checker-independent (host-side, self-contained); wired
    # into TpuBfsChecker it fires after the device exploration finishes.
    dev = (
        _Cycler()
        .checker()
        .complete_liveness()
        .spawn_tpu_bfs(frontier_capacity=16, table_capacity=1 << 9)
        .join()
    )
    assert dev.worker_error() is None
    path = dev.discoveries().get("three")
    assert path is not None
    states = path.into_states()
    assert states[-1] in states[:-1]
    assert 3 not in states

    # Without the flag, the device checker reproduces the reference's
    # false negative (no terminal state -> no discovery).
    plain = (
        _Cycler()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=16, table_capacity=1 << 9)
        .join()
    )
    assert plain.worker_error() is None
    assert plain.discoveries() == {}
