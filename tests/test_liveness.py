"""Opt-in cycle-aware ``eventually`` checking (`.complete_liveness()`).

The DEFAULT semantics reproduce the reference's documented false negatives
on cycles and DAG joins bit-for-bit (tests/test_checker.py pins that). The
opt-in post-pass closes them: a lasso — a condition-false path closing a
cycle — is exactly an infinite counterexample in a finite space. The
reference has no equivalent (FIXMEs at ``src/checker/bfs.rs:285-305``).
"""

import jax.numpy as jnp

from fixtures import DGraph
from stateright_tpu import Property
from stateright_tpu.core.batch import BatchableModel
from stateright_tpu.core.model import Model


def eventually_odd():
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def test_lasso_found_on_cycle_host_bfs():
    # The reference's own FIXME case: 0 -> 2 -> 4 -> 2 never hits an odd
    # state; default semantics miss it (no terminal state), the lasso pass
    # finds it with a certificate that revisits a state.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4, 2])
        .checker()
        .complete_liveness()
        .spawn_bfs()
        .join()
    )
    path = checker.discoveries().get("odd")
    assert path is not None
    states = path.into_states()
    assert all(s % 2 == 0 for s in states)
    assert states[-1] in states[:-1]  # the lasso certificate


def test_complete_liveness_refuses_capped_runs():
    # The lasso search ignores exploration caps, so a capped run could
    # hang on cap-bounded models and report over-cap certificates.
    import pytest

    with pytest.raises(ValueError):
        (
            DGraph.with_property(eventually_odd())
            .with_path([0, 2])
            .checker()
            .complete_liveness()
            .target_max_depth(3)
            .spawn_bfs()
        )


def test_lasso_found_on_dag_join_cycle_host_dfs():
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2, 4, 2])
        .checker()
        .complete_liveness()
        .spawn_dfs()
        .join()
    )
    assert "odd" in checker.discoveries()


def test_terminal_counterexample_masked_by_dag_join_found():
    # The advisor's unsoundness repro: 0 -> 1 -> 4 and 0 -> 2 -> 4. BFS
    # reaches terminal 4 first via odd 1 (ebit cleared), so the join
    # masks the genuine maximal counterexample 0 -> 2 -> 4; the default
    # semantics report "holds" (reference FIXME #1, bfs.rs:285-290). The
    # opted-in pass must find the all-even maximal path — it ends at a
    # terminal state, not a cycle.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1, 4])
        .with_path([0, 2, 4])
        .checker()
        .complete_liveness()
        .spawn_bfs()
        .join()
    )
    path = checker.discoveries().get("odd")
    assert path is not None
    states = path.into_states()
    assert all(s % 2 == 0 for s in states)
    assert states == [0, 2, 4]
    import pytest

    with pytest.raises(AssertionError):
        checker.assert_properties()

    # Sanity: without the flag the default checkers miss it (the parity
    # behavior the fix must not change).
    plain = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1, 4])
        .with_path([0, 2, 4])
        .checker()
        .spawn_bfs()
        .join()
    )
    assert plain.discoveries() == {}


def test_terminal_false_init_is_a_counterexample():
    # Degenerate maximal path: a condition-false initial state with no
    # successors at all.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([2])
        .checker()
        .complete_liveness()
        .spawn_bfs()
        .join()
    )
    path = checker.discoveries().get("odd")
    # The default checker already finds terminal inits; whichever pass
    # reports it, the discovery must exist and be the one-state path.
    assert path is not None
    assert path.into_states() == [2]


def test_no_lasso_when_cycle_passes_through_satisfying_state():
    # 0 -> 1 -> 2 -> 0 loops, but through odd 1: every infinite path
    # satisfies the property, so the pass must find nothing.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 1, 2, 0])
        .checker()
        .complete_liveness()
        .spawn_bfs()
        .join()
    )
    assert checker.discoveries() == {}
    checker.assert_properties()


def test_terminal_counterexample_still_preferred():
    # A terminal even path: the standard semantics find it; the pass must
    # not override the existing discovery.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([0, 2])
        .checker()
        .complete_liveness()
        .spawn_bfs()
        .join()
    )
    d = checker.discoveries()["odd"]
    assert d.into_states() == [0, 2]


class _Cycler(Model, BatchableModel):
    """0 -> 1 -> 2 -> 1: the cycle {1, 2} never reaches 3."""

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        actions.append("step")

    def next_state(self, state, action):
        return {0: 1, 1: 2, 2: 1}[state]

    def properties(self):
        return [Property.eventually("three", lambda _, s: s == 3)]

    # -- packed protocol ---------------------------------------------------

    def packed_action_count(self):
        return 1

    def packed_init_states(self):
        return {"s": jnp.zeros((1,), jnp.uint32)}

    def packed_step(self, state, action_id):
        s = state["s"]
        nxt = jnp.where(s == 0, jnp.uint32(1),
                        jnp.where(s == 1, jnp.uint32(2), jnp.uint32(1)))
        return {"s": nxt}, jnp.bool_(True)

    def packed_conditions(self):
        return [lambda st: st["s"] == 3]

    def pack_state(self, host_state):
        import numpy as np

        return {"s": np.uint32(host_state)}

    def unpack_state(self, packed):
        return int(packed["s"])


def test_lasso_pass_composes_with_device_checker():
    # The pass is checker-independent (host-side, self-contained); wired
    # into TpuBfsChecker it fires after the device exploration finishes.
    dev = (
        _Cycler()
        .checker()
        .complete_liveness()
        .spawn_tpu_bfs(frontier_capacity=16, table_capacity=1 << 9)
        .join()
    )
    assert dev.worker_error() is None
    path = dev.discoveries().get("three")
    assert path is not None
    states = path.into_states()
    assert states[-1] in states[:-1]
    assert 3 not in states

    # Without the flag, the device checker reproduces the reference's
    # false negative (no terminal state -> no discovery).
    plain = (
        _Cycler()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=16, table_capacity=1 << 9)
        .join()
    )
    assert plain.worker_error() is None
    assert plain.discoveries() == {}


def test_lasso_found_fast_at_raft_scale():
    # The check-live CLI config (raft-3, lossy): a counterexample EXISTS,
    # and DFS order must find a certificate without exhausting the false
    # region (sub-second in practice; the bound is slack for CI noise).
    import time

    from stateright_tpu.models.raft import RaftModelCfg
    from stateright_tpu.checker.liveness import find_eventually_lasso

    model = (
        RaftModelCfg(server_count=3, max_term=1, lossy=True)
        .into_model()
        .retain_properties("stable leader")
    )
    prop = model.properties()[0]
    t0 = time.time()
    path = find_eventually_lasso(model, prop)
    dt = time.time() - t0
    assert path is not None
    states = path.into_states()
    # Condition false along the whole path (the certificate's substance).
    assert not any(prop.condition(model, s) for s in states)
    # Either certificate shape is valid: a revisit (lasso) or a state with
    # no within-boundary successors (maximal path — raft-3 hits this one:
    # stuck candidates at max_term with a drained network are terminal).
    last = states[-1]
    if last not in states[:-1]:
        acts = []
        model.actions(last, acts)
        succs = [model.next_state(last, a) for a in acts]
        assert not any(
            ns is not None and model.within_boundary(ns) for ns in succs
        )
    assert dt < 30, f"lasso search took {dt:.1f}s on the raft-3 region"


def test_absence_certification_at_100k_states():
    # The worst case the docstring budgets for: NO counterexample, so the
    # pass must exhaust the whole condition-false region. A 100K chain
    # ending in an odd state certifies absence only after walking every
    # state once; the bound pins the region-exhaust rate at fast-lane
    # scale.
    import time

    from stateright_tpu.checker.liveness import find_eventually_lasso

    n = 100_000
    g = DGraph.with_property(eventually_odd())
    g.inits.add(0)
    for i in range(n - 1):
        g.edges[2 * i] = {2 * (i + 1)}
    g.edges[2 * (n - 1)] = {2 * n + 1}  # the single odd, terminal state
    t0 = time.time()
    assert find_eventually_lasso(g, g.prop) is None
    dt = time.time() - t0
    assert dt < 60, f"absence certification took {dt:.1f}s for {n} states"


class _Diamond(Model, BatchableModel):
    """0 -> {1, 2} -> 4 (terminal): the DAG-join repro on the DEVICE
    path. BFS reaches terminal 4 first via odd 1 (ebit cleared, both
    in-wave dedup pipelines deterministically keep the lower lane =
    parent 1), so the join masks the genuine maximal counterexample
    0 -> 2 -> 4 — the reference's FIXME #1 semantics, which the device
    checkers reproduce bit-for-bit (checker/tpu.py parity notes)."""

    _A0 = {0: 1, 1: 4, 2: 4}  # action 0; 4 is terminal
    _A1 = {0: 2}  # action 1

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        if state in self._A0:
            actions.append("a0")
        if state in self._A1:
            actions.append("a1")

    def next_state(self, state, action):
        table = self._A0 if action == "a0" else self._A1
        return table.get(state)

    def properties(self):
        return [Property.eventually("odd", lambda _, s: s % 2 == 1)]

    # -- packed protocol ---------------------------------------------------

    def packed_action_count(self):
        return 2

    def packed_init_states(self):
        return {"s": jnp.zeros((1,), jnp.uint32)}

    def packed_step(self, state, action_id):
        s = state["s"]
        nxt0 = jnp.where(
            s == 0, jnp.uint32(1), jnp.uint32(4)
        )  # 1 and 2 both step to 4
        valid0 = (s == 0) | (s == 1) | (s == 2)
        nxt = jnp.where(action_id == 0, nxt0, jnp.uint32(2))
        valid = jnp.where(action_id == 0, valid0, s == 0)
        return {"s": jnp.where(valid, nxt, s)}, valid

    def packed_conditions(self):
        return [lambda st: (st["s"] % 2) == 1]

    def pack_state(self, host_state):
        import numpy as np

        return {"s": np.uint32(host_state)}

    def unpack_state(self, packed):
        return int(packed["s"])


def test_terminal_merge_at_dag_join_pinned_on_device_checker():
    # Regression pin for the liveness FIXME inheritance (the module
    # docstring links here): the DEFAULT device checker must KEEP the
    # reference's false negative — terminal 4's unmet-ebit is masked by
    # the DAG join because the in-wave dedup winner (parent 1, the odd
    # state) carries a cleared bit — while the opt-in pass finds the
    # all-even maximal path. If the pin ever breaks, default semantics
    # silently diverged from the reference.
    plain = (
        _Diamond()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=8, table_capacity=1 << 9)
        .join()
    )
    assert plain.worker_error() is None
    assert plain.unique_state_count() == 4  # {0, 1, 2, 4}
    assert plain.discoveries() == {}  # the known-wrong merge, pinned

    fixed = (
        _Diamond()
        .checker()
        .complete_liveness()
        .spawn_tpu_bfs(frontier_capacity=8, table_capacity=1 << 9)
        .join()
    )
    assert fixed.worker_error() is None
    path = fixed.discoveries().get("odd")
    assert path is not None
    assert path.into_states() == [0, 2, 4]


def test_pinned_false_negatives_fixed_under_device_liveness():
    # ISSUE 14 acceptance: the two pinned false-negative shapes above
    # (terminal-merge at the DAG join, the cycle) now yield REAL
    # counterexamples under liveness="device" — no host post-pass —
    # while the default-mode pins in this file stay green untouched.
    fixed = (
        _Diamond()
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=8, table_capacity=1 << 9,
            liveness="device",
        )
        .join()
    )
    assert fixed.worker_error() is None
    path = fixed.discoveries().get("odd")
    assert path is not None
    assert path.into_states() == [0, 2, 4]  # the masked-terminal shape
    assert fixed.liveness_mode == "device"

    cyc = (
        _Cycler()
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16, table_capacity=1 << 9,
            liveness="device",
        )
        .join()
    )
    path = cyc.discoveries().get("three")
    assert path is not None
    states = path.into_states()
    assert states[-1] in states[:-1]  # the lasso shape
    assert 3 not in states
