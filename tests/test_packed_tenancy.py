"""Tenant-packed waves (PR 12): co-scheduled multi-tenant dispatch.

The contract under test: each packed tenant's results — counts, depths,
discovery fingerprints, golden reporter — are BIT-IDENTICAL to its solo
``spawn_tpu_bfs`` run. The argument (checker/packed_tenancy.py): XOR
salting preserves within-tenant dedup exactly, and the owner-ticket
scatter insert preserves per-tenant FIFO lane order, so a tenant's claim
sequence is candidate-order-equivalent to its solo run under the CPU
backend's default ``wave_dedup="scatter"``.

Fast lane: 2pc-3 packs (pair, mid-run join, lane-drop preempt → resume
into a later pack / a solo checker, async pipeline, out-of-core
per-tenant partitions), service-level packing (co-scheduled jobs with
zero preempts, mid-run join, honest packable/preemptible surfacing,
budget admission), and the ``pack.tenant.*`` registry hygiene gate.
Slow lane: ABD (fps-capable model, materializing solo twin).

Shapes reuse the suite's standard 2pc spawn (frontier 16 / table 4096)
so the persistent compile cache keeps these cheap; one shared AOT
namespace per engine configuration means incarnations never re-trace.
"""

import io
import re
import time

import pytest

from stateright_tpu import WriteReporter
from stateright_tpu.checker.packed_tenancy import TenantPackedEngine
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.service import CheckService
from stateright_tpu.telemetry import metrics_registry

ENGINE_KW = dict(
    frontier_capacity=16, table_capacity=1 << 12, max_tenants=4,
    aot_cache="t-pack",
)
UNIQUE_2PC3 = 288
UNIQUE_2PC4 = 1568


def _golden(checker_or_text):
    if isinstance(checker_or_text, str):
        text = checker_or_text
    else:
        out = io.StringIO()
        checker_or_text.report(WriteReporter(out))
        text = out.getvalue()
    return re.sub(r"sec=\d+", "sec=_", text)


@pytest.fixture(scope="module")
def solo_2pc3():
    """The solo reference run every packed tenant is compared against
    (scatter dedup — the CPU backend default — is what packing's
    order-equivalence argument targets)."""
    return (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=16, table_capacity=1 << 12)
        .join()
    )


def _drive(engine, max_steps=20_000):
    """Runs the engine to quiescence; returns {key: view} of finished
    tenants (slots released as they finish)."""
    views = {}
    steps = 0
    while engine.live_count():
        for key in engine.step():
            views[key] = engine.view(key)
            engine.release(key)
        steps += 1
        assert steps < max_steps, "packed engine did not converge"
    return views


def _assert_matches_solo(view, solo):
    assert view.unique_state_count() == solo.unique_state_count()
    assert view.state_count() == solo.state_count()
    assert view.max_depth() == solo.max_depth()
    assert set(view._discovery_names()) == set(solo._discovery_names())
    # Golden report includes the reconstructed discovery PATHS, so this
    # is discovery-fingerprint- and parent-pointer-exact.
    assert _golden(view) == _golden(solo)


# -- engine-level bit-identity ------------------------------------------------


def test_packed_pair_bit_identical_vs_solo(solo_2pc3):
    """Two tenants of one shared wave each reproduce the solo run
    exactly — counts, depths, discoveries, golden reporter."""
    engine = TenantPackedEngine(TwoPhaseSys(3), **ENGINE_KW)
    a = engine.admit("a", "pk-a")
    b = engine.admit("b", "pk-b")
    _drive(engine)
    engine.close()
    _assert_matches_solo(a, solo_2pc3)
    _assert_matches_solo(b, solo_2pc3)


def test_tenant_join_mid_run(solo_2pc3):
    """Admission claims a free lane slot in a LIVE pack: the late tenant
    starts from its own seed mid-flight and still matches solo."""
    engine = TenantPackedEngine(TwoPhaseSys(3), **ENGINE_KW)
    early = engine.admit("early", "pk-early")
    for _ in range(5):
        engine.step()
    late = engine.admit("late", "pk-late")
    _drive(engine)
    engine.close()
    _assert_matches_solo(early, solo_2pc3)
    _assert_matches_solo(late, solo_2pc3)


def test_lane_drop_preempt_resumes_into_later_pack(solo_2pc3):
    """Preempting a packed tenant drops its lanes — no device drain —
    and its checkpoint-v2 payload slice resumes into a LATER pack
    (alongside a fresh tenant) bit-identically."""
    engine = TenantPackedEngine(TwoPhaseSys(3), **ENGINE_KW)
    engine.admit("victim", "pk-v1")
    engine.admit("peer", "pk-p1")
    for _ in range(6):
        engine.step()
    payload = engine.drop("victim")
    assert payload is not None and payload["kind"] == "tpu_bfs"
    assert engine.view("victim") is None  # slot freed
    assert engine.free_slots() == 3
    peer_views = _drive(engine)
    engine.close()
    _assert_matches_solo(
        peer_views.get("peer") or engine.view("peer"), solo_2pc3
    )

    later = TenantPackedEngine(TwoPhaseSys(3), **ENGINE_KW)
    resumed = later.admit("victim", "pk-v2", resume_from=payload)
    fresh = later.admit("fresh", "pk-f2")
    _drive(later)
    later.close()
    _assert_matches_solo(resumed, solo_2pc3)
    _assert_matches_solo(fresh, solo_2pc3)


def test_lane_drop_payload_resumes_solo(solo_2pc3):
    """The payload slice is a STANDARD checkpoint-v2 payload: a dropped
    tenant resumes on a plain ``TpuBfsChecker`` bit-identically (the
    cross-path escape hatch — packed jobs are never locked in)."""
    engine = TenantPackedEngine(TwoPhaseSys(3), **ENGINE_KW)
    engine.admit("solo-bound", "pk-sb")
    for _ in range(4):
        engine.step()
    payload = engine.drop("solo-bound")
    engine.close()
    resumed = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16, table_capacity=1 << 12,
            resume_from=payload,
        )
        .join()
    )
    _assert_matches_solo(resumed, solo_2pc3)


def test_packed_async_pipeline(solo_2pc3):
    """``async_pipeline=True``: per-tenant probes, parent logs, and
    survivor re-entry ride the FIFO host worker overlapped with the
    next dispatch — results unchanged."""
    engine = TenantPackedEngine(
        TwoPhaseSys(3), async_pipeline=True, **ENGINE_KW
    )
    a = engine.admit("as-a", "pk-as-a")
    b = engine.admit("as-b", "pk-as-b")
    _drive(engine)
    engine.close()
    _assert_matches_solo(a, solo_2pc3)
    _assert_matches_solo(b, solo_2pc3)


def test_packed_out_of_core_partitions(solo_2pc3):
    """A budget-capped shared table evicts into PER-TENANT partitions
    (each tenant's since-eviction claims drain into its own run set);
    results stay exact and the stale-probe accounting lands in each
    tenant's own registry."""
    from stateright_tpu.checker.tpu import min_admissible_hbm_budget_mib

    budget = min_admissible_hbm_budget_mib(TwoPhaseSys(3), 16) * 2
    kw = dict(ENGINE_KW)
    kw["aot_cache"] = "t-pack-oc"
    engine = TenantPackedEngine(
        TwoPhaseSys(3), hbm_budget_mib=budget, **kw
    )
    a = engine.admit("oc-a", "pk-oc-a")
    b = engine.admit("oc-b", "pk-oc-b")
    _drive(engine)
    engine.close()
    _assert_matches_solo(a, solo_2pc3)
    _assert_matches_solo(b, solo_2pc3)
    snap = metrics_registry("pk-oc-a").snapshot()
    assert snap.get("pack.tenant.storage_stale", 0) > 0, (
        "the budget never bound (no per-tenant host probes happened)"
    )


def test_resume_admission_under_budget_pressure(solo_2pc3):
    """Review regression: a budget eviction fired by the ADMISSION's own
    bulk key claims must flush the joining tenant's restored keys into
    its partition (the tenant registers before restoring). Without
    that, a resumed payload bigger than the budget-capped table would
    silently lose its earlier-batch visited keys and re-count them."""
    from stateright_tpu.checker.tpu import min_admissible_hbm_budget_mib

    donor = TenantPackedEngine(TwoPhaseSys(3), **ENGINE_KW)
    donor.admit("big", "pk-big")
    steps = 0
    while donor.view("big").unique_state_count() < 250:
        donor.step()
        steps += 1
        assert steps < 20_000
    payload = donor.drop("big")
    donor.close()
    assert len(payload["children"]) >= 250

    kw = dict(ENGINE_KW)
    kw["aot_cache"] = "t-pack-oc"
    tight = TenantPackedEngine(
        TwoPhaseSys(3),
        hbm_budget_mib=min_admissible_hbm_budget_mib(TwoPhaseSys(3), 16),
        **kw,
    )
    # White-box: the tenant must be REGISTERED before its restore runs
    # (so an eviction fired by the admission's own claims flushes its
    # resident keys) — the load needed to force that eviction mid-loop
    # is not deterministic, so pin the ordering directly.
    orig_restore = tight._restore_tenant
    seen = {}

    def spy(t, pl):
        seen["registered"] = tight._by_key.get("big") is t
        return orig_restore(t, pl)

    tight._restore_tenant = spy
    resumed = tight.admit("big", "pk-big2", resume_from=payload)
    assert seen["registered"] is True
    _drive(tight)
    tight.close()
    _assert_matches_solo(resumed, solo_2pc3)

    # A FAILED admission must deregister cleanly (free slot, no ghost).
    bad = dict(payload)
    bad["fp_scheme"] = "not-a-scheme"
    eng = TenantPackedEngine(TwoPhaseSys(3), **ENGINE_KW)
    with pytest.raises(ValueError, match="fingerprint scheme"):
        eng.admit("ghost", "pk-ghost", resume_from=bad)
    assert eng.view("ghost") is None
    assert eng.free_slots() == 4
    eng.close()


@pytest.mark.slow
def test_packed_abd_bit_identical_vs_solo():
    """ABD (an fps-capable actor model): packed tenants match the solo
    materializing run exactly. (The solo fps pipeline is itself
    bit-identical to materializing — tests/test_expand_fps.py — so this
    pins the packed path to both.)"""
    from stateright_tpu.models.linearizable_register import AbdModelCfg

    solo = (
        AbdModelCfg(2, 2)
        .into_model()
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=8, table_capacity=1 << 12,
            expand_fps=False,
        )
        .join()
    )
    engine = TenantPackedEngine(
        AbdModelCfg(2, 2).into_model(),
        frontier_capacity=8, table_capacity=1 << 12, max_tenants=2,
        aot_cache="t-pack-abd",
    )
    a = engine.admit("abd-a", "pk-abd-a")
    b = engine.admit("abd-b", "pk-abd-b")
    _drive(engine, max_steps=200_000)
    engine.close()
    _assert_matches_solo(a, solo)
    _assert_matches_solo(b, solo)


# -- service-level packing ----------------------------------------------------

SPAWN_2PC = {
    "frontier_capacity": 16,
    "table_capacity": 1 << 12,
    "max_drain_waves": 2,
    "aot_cache": "t-pack-svc",
}


@pytest.fixture
def service():
    svc = CheckService(quantum_s=0.75, default_spawn=dict(SPAWN_2PC))
    yield svc
    svc.close()


def test_service_packs_same_shape_jobs(service):
    """The scheduler co-schedules same-configuration jobs into one
    pack: both complete exactly, in ONE slice each, with ZERO preempts
    — concurrency without the r10 drain/restore churn — and the packed/
    packable/preemptible facts are surfaced in status()."""
    h1 = service.submit(model_name="2pc", model_args={"rm_count": 3})
    h2 = service.submit(model_name="2pc", model_args={"rm_count": 3})
    r1 = h1.result(timeout=180)
    r2 = h2.result(timeout=180)
    assert r1["unique"] == r2["unique"] == UNIQUE_2PC3
    assert _golden(r1["report"]) == _golden(r2["report"])
    for h in (h1, h2):
        st = h.status()
        assert st["packed"] is True
        assert st["packable"] is True and st["packable_reason"] is None
        assert st["preemptible"] is True
        assert st["preempts"] == 0
        assert st["slices"] == 1
        assert st["latency"]["ttfv_s"] is not None
    # Per-tenant lane accounting landed in each job's own registry.
    snap = metrics_registry(h1.job_id).snapshot()
    assert snap.get("pack.tenant.states_unique", 0) + 1 >= UNIQUE_2PC3
    assert snap.get("pack.tenant.joins") == 1


def test_service_join_live_pack(service):
    """A same-shape job submitted while a pack is RUNNING joins it
    mid-flight (admission = claim a free lane) instead of waiting for
    the device."""
    h1 = service.submit(model_name="2pc", model_args={"rm_count": 4})
    deadline = time.monotonic() + 60
    while (
        service.job(h1.job_id).state == "queued"
        and time.monotonic() < deadline
    ):
        time.sleep(0.002)
    h2 = service.submit(model_name="2pc", model_args={"rm_count": 4})
    r1 = h1.result(timeout=300)
    r2 = h2.result(timeout=300)
    assert r1["unique"] == r2["unique"] == UNIQUE_2PC4
    assert _golden(r1["report"]) == _golden(r2["report"])
    s2 = h2.status()
    assert s2["packed"] is True
    # The joiner never waited for a full time-slice rotation: one slice,
    # no preempt of the running pack.
    assert s2["slices"] == 1 and s2["preempts"] == 0


def test_full_pack_yields_to_higher_priority_same_shape():
    """Review regression: a FULL pack must count a higher-priority
    same-shape arrival as a preemption contender (it cannot join — no
    free lane — and without this it would starve past every quantum).
    The suspended members' payload slices then resume into later packs,
    still exact."""
    svc = CheckService(
        quantum_s=0.2, default_spawn=dict(SPAWN_2PC),
        max_pack_tenants=2,
    )
    try:
        lows = [
            svc.submit(model_name="2pc", model_args={"rm_count": 4})
            for _ in range(2)
        ]
        deadline = time.monotonic() + 60
        while (
            any(svc.job(h.job_id).state == "queued" for h in lows)
            and time.monotonic() < deadline
        ):
            time.sleep(0.002)
        lows_running = all(
            svc.job(h.job_id).state == "running" for h in lows
        )
        high = svc.submit(
            model_name="2pc", model_args={"rm_count": 4}, priority=5
        )
        assert high.result(timeout=300)["unique"] == UNIQUE_2PC4
        for h in lows:
            assert h.result(timeout=300)["unique"] == UNIQUE_2PC4
        if lows_running:
            # The full pack actually yielded: its members were
            # lane-dropped (suspended) at least once.
            assert sum(
                svc.job(h.job_id).preempts for h in lows
            ) >= 1
    finally:
        svc.close()


def test_service_surfaces_non_packable_reasons(service):
    """Honesty satellite: every disqualifier is named in status() (and
    therefore over GET /jobs/<id>), not silently degraded."""
    cases = [
        (dict(spawn={"attribution": True}), "spawn overrides"),
        (dict(options={"symmetry": True}), "symmetry"),
        (dict(options={"target_state_count": 50}), "target_state_count"),
    ]
    for kwargs, needle in cases:
        h = service.submit(
            model_name="2pc", model_args={"rm_count": 3}, **kwargs
        )
        st = h.status()
        assert st["packable"] is False
        assert needle in st["packable_reason"], st["packable_reason"]
        h.cancel()
    # A SERVICE-WIDE default the packed engine cannot honor (e.g. a
    # pipeline override) disqualifies packing too — silently dropping
    # it would make packed and time-sliced runs diverge semantically.
    svc2 = CheckService(
        quantum_s=0.75,
        default_spawn=dict(SPAWN_2PC, expand_fps=False),
    )
    try:
        h = svc2.submit(model_name="2pc", model_args={"rm_count": 3})
        st = h.status()
        assert st["packable"] is False
        assert "default_spawn" in st["packable_reason"]
        h.cancel()
    finally:
        svc2.close()


def test_service_non_preemptible_backend_surfaced():
    """A host-engine service (no preempt payloads) reports
    ``preemptible: false`` from the live checker — the operator sees
    that this job class serializes the device."""
    svc = CheckService(
        quantum_s=0.2, spawn_method="spawn_bfs", packing=False,
    )
    # The device-spawn defaults don't apply to a host engine.
    svc.default_spawn = {}
    try:
        h = svc.submit(model_name="2pc", model_args={"rm_count": 3})
        assert h.result(timeout=180)["unique"] == UNIQUE_2PC3
        assert h.status()["preemptible"] is False
    finally:
        svc.close()


def test_budget_rejected_at_admission(service):
    """Satellite 2: an over-budget request fails AT SUBMIT with a clear
    error (not at OOM on the scheduler thread), and an admissible budget
    derives the job's table capacity instead of the fixed default."""
    with pytest.raises(ValueError, match="rejected at admission"):
        service.submit(
            model_name="2pc", model_args={"rm_count": 4},
            hbm_budget_mib=0.001,
        )
    from stateright_tpu.checker.tpu import min_admissible_hbm_budget_mib
    from stateright_tpu.storage import max_table_rows_for_budget

    budget = min_admissible_hbm_budget_mib(TwoPhaseSys(4), 16)
    h = service.submit(
        model_name="2pc", model_args={"rm_count": 4},
        hbm_budget_mib=budget,
    )
    job = service.job(h.job_id)
    assert job.derived_table_capacity == max_table_rows_for_budget(budget)
    assert job.packable is False  # budgeted jobs run solo tiered
    h.cancel()


def test_tenant_metric_family_hygiene():
    """The new ``pack.tenant.*`` family (and the engine's ``pack.*``
    wave family) survive the Prometheus sanitizer without collisions —
    the registry lint the tier-1 suite runs over every metric family."""
    from stateright_tpu.telemetry import (
        TenantInstruments,
        WaveInstruments,
        registry_hygiene_problems,
    )
    from stateright_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    TenantInstruments("pack", registry=reg)
    wi = WaveInstruments("pack", registry=reg)
    wi.bucket_dispatch(16)
    assert registry_hygiene_problems(reg) == []
