"""``packed_expand`` == vmap(``packed_step``) on every valid lane.

``PackedActorModel.packed_expand`` (round 4) rebuilds the deliver / drop /
timeout / crash candidate blocks with specialized per-class steppers so
the wave kernels stop paying every branch for every candidate; these tests
pin it lane-for-lane against the generic single-action path (the oracle)
on real reachable states across network semantics, auxiliary history, and
crash faults. Valid masks must agree everywhere; candidate states must
agree wherever valid (invalid lanes are masked to sentinels before any
downstream use — see ``checker/tpu.py::_wave``).
"""

from collections import deque

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stateright_tpu.actor import Network
from stateright_tpu.models.linearizable_register import AbdModelCfg
from stateright_tpu.models.paxos import PaxosModelCfg
from stateright_tpu.models.raft import RaftModelCfg
from stateright_tpu.models.single_copy_register import SingleCopyModelCfg


def _reachable_sample(model, cap=60, explore_cap=3000):
    """Up to ``cap`` reachable host states, evenly sampled from the first
    ``explore_cap`` in BFS order (full enumeration is minutes on the
    larger crash/dup spaces; a BFS prefix still spans every action class
    and both empty and loaded networks)."""
    states = list(model.init_states())
    seen = {hash(s) for s in states}
    q = deque(states)
    acts = []
    while q and len(states) < explore_cap:
        s = q.popleft()
        acts.clear()
        model.actions(s, acts)
        for a in acts:
            ns = model.next_state(s, a)
            if ns is not None and hash(ns) not in seen:
                seen.add(hash(ns))
                states.append(ns)
                q.append(ns)
    step = max(1, len(states) // cap)
    return states[::step][:cap]


CASES = {
    "raft-lossy-nondup": lambda: RaftModelCfg(
        server_count=3, max_term=1, lossy=True
    ),
    "raft-dup-lossless": lambda: RaftModelCfg(
        server_count=3,
        max_term=1,
        lossy=False,
        network=Network.new_unordered_duplicating(),
    ),
    "raft-crashes": lambda: RaftModelCfg(
        server_count=3, max_term=1, lossy=True, max_crashes=1
    ),
    "abd-ordered-history": lambda: AbdModelCfg(
        2, 2, network=Network.new_ordered()
    ),
    "single-copy-history": lambda: SingleCopyModelCfg(2, 1),
    "paxos-history": lambda: PaxosModelCfg(2, 2),
}


@pytest.mark.parametrize("case", CASES, ids=list(CASES))
def test_expand_matches_step_on_valid_lanes(case):
    model = CASES[case]().into_model()
    A = model.packed_action_count()
    aids = jnp.arange(A, dtype=jnp.int32)

    expand = jax.jit(model.packed_expand)
    step = jax.jit(
        lambda s: jax.vmap(lambda a: model.packed_step(s, a))(aids)
    )

    checked = 0
    for host_state in _reachable_sample(model):
        packed = jax.tree_util.tree_map(
            jnp.asarray, model.pack_state(host_state)
        )
        cand_e, valid_e = expand(packed)
        cand_s, valid_s = step(packed)
        ve = np.asarray(valid_e)
        vs = np.asarray(valid_s)
        assert (ve == vs).all(), (
            f"{case}: valid masks diverge on lanes "
            f"{np.nonzero(ve != vs)[0].tolist()}"
        )
        for (ke, xe), (_, xs) in zip(
            jax.tree_util.tree_flatten_with_path(cand_e)[0],
            jax.tree_util.tree_flatten_with_path(cand_s)[0],
        ):
            xe = np.asarray(xe)[ve]
            xs = np.asarray(xs)[vs]
            assert (xe == xs).all(), (
                f"{case}: leaf {jax.tree_util.keystr(ke)} diverges on a "
                "valid lane"
            )
        checked += int(ve.sum())
    assert checked > 0  # the sample exercised real transitions
