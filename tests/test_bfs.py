"""BFS checker tests — behavioral parity with ``src/checker/bfs.rs`` tests."""

import pytest

from fixtures import LinearEquation, Panicker
from stateright_tpu import StateRecorder


def test_visits_states_in_bfs_order():
    recorder = StateRecorder()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_bfs().join()
    assert recorder.states == [
        (0, 0),  # distance 0
        (1, 0), (0, 1),  # distance 1
        (2, 0), (1, 1), (0, 2),  # distance 2
        (3, 0), (2, 1),  # distance 3
    ]


def test_can_complete_by_enumerating_all_states():
    checker = LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 12

    # BFS found this example... (2*2 + 10*1) % 256 == 14
    assert checker.discovery("solvable").into_actions() == [
        "IncreaseX", "IncreaseX", "IncreaseY",
    ]
    # ...but there are other solutions, e.g. (2*0 + 10*27) % 256 == 14.
    checker.assert_discovery("solvable", ["IncreaseY"] * 27)


def test_handles_panics_gracefully():
    # A worker raising must shut down all threads; join surfaces the failure.
    with pytest.raises(RuntimeError):
        Panicker().checker().threads(2).spawn_bfs().join()


def test_multithreaded_counts_match():
    single = LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    multi = LinearEquation(2, 4, 7).checker().threads(4).spawn_bfs().join()
    assert multi.unique_state_count() == single.unique_state_count() == 65536


def test_target_state_count_stops_early():
    checker = (
        LinearEquation(2, 4, 7)
        .checker()
        .target_state_count(100)
        .spawn_bfs()
        .join()
    )
    # Overshoot is allowed, undershoot is not (while states remain).
    assert 100 <= checker.state_count() < 65536 * 2


def test_target_max_depth_bounds_exploration():
    checker = (
        LinearEquation(2, 4, 7)
        .checker()
        .target_max_depth(3)
        .spawn_bfs()
        .join()
    )
    assert checker.max_depth() == 3
    # depth 1 (init) + depth 2 + depth 3 enqueued; depth-3 states not expanded:
    # states at depth d are the (x, y) with x+y == d-1, i.e. d of them.
    assert checker.unique_state_count() == 1 + 2 + 3
