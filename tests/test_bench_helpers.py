"""bench.py's evidence readers: the probe-log summary and the sentinel
device-results collector that land in the round's bench JSON."""

import json

import bench  # repo root is on sys.path via tests/conftest.py


def test_probe_log_summary(tmp_path, monkeypatch):
    log = tmp_path / "PROBE_LOG.jsonl"
    log.write_text(
        '{"ts": "t1", "ok": false}\n'
        "not json\n"
        '{"ts": "t2", "ok": true}\n'
        '{"ts": "t3", "ok": false, "standdown": true}\n'
    )
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    s = bench._probe_log_summary()
    assert s == {
        "attempts": 2,
        "ok": 1,
        "standdowns": 1,
        "first": "t1",
        "last": "t3",
        "last_ok": "t2",
    }


def test_probe_log_summary_absent(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    assert bench._probe_log_summary() is None


def test_sentinel_device_results_collects_every_record_shape(
    tmp_path, monkeypatch
):
    runs = tmp_path / "DEVICE_RUNS.jsonl"
    records = [
        # cpu results and null results are excluded; later tpu wins.
        {"leg": "2pc", "result": {"device": "tpu", "rate": 1.0}},
        {"leg": "2pc", "result": {"device": "tpu", "rate": 9.0}},
        {"leg": "paxos3", "result": {"device": "cpu", "rate": 2.0}},
        {"leg": "raft5", "result": None},
        {"ab": "2pc-scatter", "result": {"device": "tpu", "rate": 3.0}},
        {"flip_test": True, "result": {"device": "tpu", "winner": "x"}},
        {"breakdown": "abd3o", "result": {"device": "tpu", "fused_wave_ms": 1}},
    ]
    runs.write_text("".join(json.dumps(r) + "\n" for r in records))
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    out = bench._sentinel_device_results()
    assert set(out) == {"2pc", "2pc-scatter", "flip_test", "breakdown_abd3o"}
    assert out["2pc"]["rate"] == 9.0  # retries: later entries win


def test_sentinel_device_results_none_without_tpu(tmp_path, monkeypatch):
    runs = tmp_path / "DEVICE_RUNS.jsonl"
    runs.write_text('{"leg": "2pc", "result": {"device": "cpu"}}\n')
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    assert bench._sentinel_device_results() is None


def test_evaluate_pipeline_choice_flags_slower_configured():
    """The measured-policy gate (PR 7 satellite): the configured pipeline
    is flagged only when it measures >10% slower than the other one —
    both directions, noise band tolerated, degenerate inputs never flag."""
    # abd3o-shaped regression: configured fps, materialize 2.5x faster.
    assert bench.evaluate_pipeline_choice("fps", 25.0, 10.0) is True
    # configured materialize, fps faster.
    assert bench.evaluate_pipeline_choice("materialize", 10.0, 25.0) is True
    # Correctly-configured pipelines never flag.
    assert bench.evaluate_pipeline_choice("fps", 10.0, 25.0) is False
    assert bench.evaluate_pipeline_choice("materialize", 25.0, 10.0) is False
    # Inside the 10% noise band: no flag either way.
    assert bench.evaluate_pipeline_choice("fps", 10.5, 10.0) is False
    # Degenerate inputs (unsupported model, failed calibration).
    assert bench.evaluate_pipeline_choice(None, 10.0, 5.0) is False
    assert bench.evaluate_pipeline_choice("fps", None, 5.0) is False
    assert bench.evaluate_pipeline_choice("fps", 10.0, 0.0) is False
