"""bench.py's evidence readers: the probe-log summary and the sentinel
device-results collector that land in the round's bench JSON."""

import json

import bench  # repo root is on sys.path via tests/conftest.py


def test_probe_log_summary(tmp_path, monkeypatch):
    log = tmp_path / "PROBE_LOG.jsonl"
    log.write_text(
        '{"ts": "t1", "ok": false}\n'
        "not json\n"
        '{"ts": "t2", "ok": true}\n'
        '{"ts": "t3", "ok": false, "standdown": true}\n'
    )
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    s = bench._probe_log_summary()
    assert s == {
        "attempts": 2,
        "ok": 1,
        "standdowns": 1,
        "first": "t1",
        "last": "t3",
        "last_ok": "t2",
    }


def test_probe_log_summary_absent(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    assert bench._probe_log_summary() is None


def test_sentinel_device_results_collects_every_record_shape(
    tmp_path, monkeypatch
):
    runs = tmp_path / "DEVICE_RUNS.jsonl"
    records = [
        # cpu results and null results are excluded; later tpu wins.
        {"leg": "2pc", "result": {"device": "tpu", "rate": 1.0}},
        {"leg": "2pc", "result": {"device": "tpu", "rate": 9.0}},
        {"leg": "paxos3", "result": {"device": "cpu", "rate": 2.0}},
        {"leg": "raft5", "result": None},
        {"ab": "2pc-scatter", "result": {"device": "tpu", "rate": 3.0}},
        {"flip_test": True, "result": {"device": "tpu", "winner": "x"}},
        {"breakdown": "abd3o", "result": {"device": "tpu", "fused_wave_ms": 1}},
    ]
    runs.write_text("".join(json.dumps(r) + "\n" for r in records))
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    out = bench._sentinel_device_results()
    assert set(out) == {"2pc", "2pc-scatter", "flip_test", "breakdown_abd3o"}
    assert out["2pc"]["rate"] == 9.0  # retries: later entries win


def test_sentinel_device_results_none_without_tpu(tmp_path, monkeypatch):
    runs = tmp_path / "DEVICE_RUNS.jsonl"
    runs.write_text('{"leg": "2pc", "result": {"device": "cpu"}}\n')
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    assert bench._sentinel_device_results() is None


def test_evaluate_pipeline_choice_flags_slower_configured():
    """The measured-policy gate (PR 7 satellite): the configured pipeline
    is flagged only when it measures >10% slower than the other one —
    both directions, noise band tolerated, degenerate inputs never flag."""
    # abd3o-shaped regression: configured fps, materialize 2.5x faster.
    assert bench.evaluate_pipeline_choice("fps", 25.0, 10.0) is True
    # configured materialize, fps faster.
    assert bench.evaluate_pipeline_choice("materialize", 10.0, 25.0) is True
    # Correctly-configured pipelines never flag.
    assert bench.evaluate_pipeline_choice("fps", 10.0, 25.0) is False
    assert bench.evaluate_pipeline_choice("materialize", 25.0, 10.0) is False
    # Inside the 10% noise band: no flag either way.
    assert bench.evaluate_pipeline_choice("fps", 10.5, 10.0) is False
    # Degenerate inputs (unsupported model, failed calibration).
    assert bench.evaluate_pipeline_choice(None, 10.0, 5.0) is False
    assert bench.evaluate_pipeline_choice("fps", None, 5.0) is False
    assert bench.evaluate_pipeline_choice("fps", 10.0, 0.0) is False


def test_pct_percentiles():
    """The service leg's stdlib percentile: linear interpolation,
    None-safe, empty-safe."""
    assert bench._pct([], 50) is None
    assert bench._pct([None, None], 99) is None
    assert bench._pct([3.0], 99) == 3.0
    assert bench._pct([1.0, 2.0, 3.0, 4.0], 50) == 2.5
    assert bench._pct([1.0, None, 3.0], 50) == 2.0
    assert bench._pct([1.0, 2.0, 3.0, 4.0], 100) == 4.0
    assert bench._pct([1.0, 2.0, 3.0, 4.0], 0) == 1.0


def test_service_report_round_trip(tmp_path):
    """scripts/service_report.py reads a bench --service record and
    emits the shared --json summary shape."""
    import io
    import sys

    sys.path.insert(
        0, str(__import__("pathlib").Path(bench.__file__).parent / "scripts")
    )
    import service_report

    record = {
        "metric": "service aggregate unique states/sec",
        "value": 1234.5,
        "device": "cpu",
        "model": "2pc-5",
        "jobs": 2,
        "quantum_s": 0.5,
        "batch_rate": 1300.0,
        "single_job_rate": 1250.0,
        "service_overhead_pct": 3.8,
        "aggregate_states_per_s": 1234.5,
        "concurrent_wall_s": 14.3,
        "p50_ttfv_s": 0.5,
        "p99_ttfv_s": 0.9,
        "preempts_total": 3,
        "jobs_zero_compile": 1,
        "per_job": [
            {"job_id": "job-1", "tenant": "t0", "unique": 8832,
             "ttfv_s": 0.4, "wall_s": 7.0, "queued_s": 0.01,
             "active_s": 6.0, "preempts": 2, "slices": 3,
             "rate": 1250.0, "compile_s": 2.0},
            {"job_id": "job-2", "tenant": "t1", "unique": 8832,
             "ttfv_s": 0.6, "wall_s": 9.0, "queued_s": 0.02,
             "active_s": 6.1, "preempts": 1, "slices": 2,
             "rate": 1240.0, "compile_s": 0.0},
        ],
    }
    path = tmp_path / "BENCH_r10.json"
    path.write_text("garbage line\n" + json.dumps(record) + "\n")
    loaded = service_report.load_record(str(path))
    assert loaded["per_job"][1]["compile_s"] == 0.0
    summary = service_report.summarize(loaded)
    assert summary["p99_ttfv_s"] == 0.9
    assert summary["jobs_zero_compile"] == 1
    out = io.StringIO()
    service_report.render(summary, out=out)
    text = out.getvalue()
    assert "p99  0.900s" in text
    assert "job-2" in text
    # Missing record is a clean nonzero exit, not a traceback.
    empty = tmp_path / "empty.json"
    empty.write_text("{}\n")
    assert service_report.main([str(empty), "--json"]) == 2
