"""In-memory checkpoint preempt/resume: a mid-run suspend followed by a
resume must be BIT-IDENTICAL to the uninterrupted run — state counts,
unique counts, depths, discovery fingerprints, and the golden
WriteReporter strings. The argument is the checkpoint-equivalence one
(tests/test_storage_equivalence.py): ``request_preempt`` drains the run
through the exact ``checkpoint_payload`` machinery ``save_checkpoint``
pickles, and ``resume_from=<payload>`` is the exact restore path — only
the pickle round trip is skipped.

Covers 2pc (materializing pipeline, deep-drain yield), ABD
(``expand_fps`` pipeline), a double preempt (suspend → resume → suspend
→ resume), and a suspend that lands mid-L0→L1 eviction (the payload must
carry the storage tiers)."""

import io
import math
import re
import time

import pytest

from stateright_tpu import WriteReporter
from stateright_tpu.checker.tpu import TpuBfsChecker
from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def _golden(checker):
    out = io.StringIO()
    checker.report(WriteReporter(out))
    return re.sub(r"sec=\d+", "sec=_", out.getvalue())


def _abd_model():
    from stateright_tpu.models.linearizable_register import AbdModelCfg

    return AbdModelCfg(2, 2).into_model()


def _preempt_at(checker, threshold: int, timeout_s: float = 120.0):
    """Requests preemption once the run has made real progress (so the
    suspend lands mid-space, not at the seed), then waits the worker
    out. Returns True when the run actually suspended (a fast run may
    finish first — callers skip the resume leg then)."""
    deadline = time.monotonic() + timeout_s
    while (
        checker.unique_state_count() < threshold
        and not checker.is_done()
        and time.monotonic() < deadline
    ):
        time.sleep(0.002)
    checker.request_preempt()
    for h in checker.handles():
        h.join()
    assert checker.worker_error() is None
    return checker.preempted


def _assert_bit_identical(resumed, reference):
    assert resumed.worker_error() is None
    assert reference.worker_error() is None
    assert resumed.unique_state_count() == reference.unique_state_count()
    assert resumed.state_count() == reference.state_count()
    assert resumed.max_depth() == reference.max_depth()
    assert resumed._discoveries_fp == reference._discoveries_fp
    assert _golden(resumed) == _golden(reference)


# Every 2pc-4 spawn in this module shares one AOT namespace (identical
# config by construction), so the preempted/resumed incarnations re-use
# the fixture run's executables instead of re-tracing per incarnation —
# exactly how the service keeps resumes cheap, and it keeps this module
# inside the tier-1 time budget.
SPAWN_2PC4 = {
    "frontier_capacity": 16,
    "table_capacity": 1 << 12,
    "aot_cache": "t-preempt-2pc4",
}


@pytest.fixture(scope="module")
def uninterrupted_2pc4():
    checker = (
        TwoPhaseSys(4).checker().spawn_tpu_bfs(**SPAWN_2PC4).join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 1568
    return checker


def test_preempt_resume_2pc_bit_identical(uninterrupted_2pc4):
    """Deep-drain yield point: suspend mid-space, resume, finish — all
    run invariants match the uninterrupted run exactly."""
    first = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        max_drain_waves=2, **SPAWN_2PC4
    )
    if not _preempt_at(first, threshold=200):
        pytest.skip("run finished before the preempt request landed")
    assert first.is_done()  # the handle is joinable/reportable
    assert first.unique_state_count() < 1568
    payload = first.preempt_payload()
    assert payload["version"] == 2

    resumed = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(resume_from=payload, **SPAWN_2PC4)
        .join()
    )
    _assert_bit_identical(resumed, uninterrupted_2pc4)
    resumed.assert_properties()


def test_double_preempt_resume_2pc(uninterrupted_2pc4):
    """Two suspend/resume cycles (the service's steady state) compose:
    still bit-identical."""
    stage = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        max_drain_waves=2, **SPAWN_2PC4
    )
    if not _preempt_at(stage, threshold=150):
        pytest.skip("run finished before the first preempt")
    stage2 = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        max_drain_waves=2, resume_from=stage.preempt_payload(),
        **SPAWN_2PC4
    )
    if not _preempt_at(stage2, threshold=600):
        pytest.skip("resumed run finished before the second preempt")
    final = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(resume_from=stage2.preempt_payload(), **SPAWN_2PC4)
        .join()
    )
    _assert_bit_identical(final, uninterrupted_2pc4)


def test_preempt_resume_abd_fps_pipeline():
    """The fingerprint-only expansion pipeline (ABD's default) suspends
    and resumes bit-identically too — the payload path must cover the
    (parent, action)-reference frontier representation."""
    spawn_abd = {
        "frontier_capacity": 32,
        "table_capacity": 1 << 12,
        "aot_cache": "t-preempt-abd",
    }
    reference = _abd_model().checker().spawn_tpu_bfs(**spawn_abd).join()
    assert reference.worker_error() is None
    assert reference.unique_state_count() == 544
    first = _abd_model().checker().spawn_tpu_bfs(
        max_drain_waves=2, **spawn_abd
    )
    assert first.pipeline == "fps"
    if not _preempt_at(first, threshold=100):
        pytest.skip("run finished before the preempt request landed")
    resumed = (
        _abd_model()
        .checker()
        .spawn_tpu_bfs(resume_from=first.preempt_payload(), **spawn_abd)
        .join()
    )
    _assert_bit_identical(resumed, reference)
    resumed.assert_properties()


# -- suspend landing mid-L0→L1 eviction -------------------------------------


def _tiny_budget(model, frontier: int, load=0.55) -> float:
    actions = model.packed_action_count()
    rows = 1 << math.ceil(math.log2(frontier * actions / load + 1))
    return ((rows + 128) * 8) / (1 << 20)


class _PreemptDuringEviction(TpuBfsChecker):
    """Issues the preempt request from INSIDE the first L0→L1 eviction,
    so the suspend request lands mid-eviction: the eviction must
    complete, the yield point honors the request at the next boundary,
    and the payload must carry the freshly-written storage tier."""

    def _evict_l0(self, table, defer=False):
        self.request_preempt()
        return super()._evict_l0(table, defer=defer)


def test_preempt_mid_eviction_resume(uninterrupted_2pc4):
    budget = _tiny_budget(TwoPhaseSys(4), 16)
    first = _PreemptDuringEviction(
        TwoPhaseSys(4).checker(),
        frontier_capacity=16,
        table_capacity=1 << 12,
        hbm_budget_mib=budget,
        max_drain_waves=2,
        aot_cache="t-preempt-2pc4-oob",
    )
    for h in first.handles():
        h.join()
    assert first.worker_error() is None
    assert first.preempted, "the post-eviction boundary must honor the request"
    payload = first.preempt_payload()
    assert payload.get("storage"), (
        "a suspend landing mid-eviction must carry the L1 runs"
    )
    assert first.unique_state_count() < 1568

    resumed = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=16,
            table_capacity=1 << 12,
            hbm_budget_mib=budget,
            resume_from=payload,
            aot_cache="t-preempt-2pc4-oob",
        )
        .join()
    )
    _assert_bit_identical(resumed, uninterrupted_2pc4)
    assert resumed.unique_state_count() == 1568
    resumed.assert_properties()


# -- sharded checker yield points -------------------------------------------


def _sharded(model_checker, **kw):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("fp",))
    kw.setdefault("frontier_per_device", 32)
    kw.setdefault("table_capacity_per_device", 512)
    return model_checker.spawn_sharded_tpu_bfs(mesh=mesh, **kw)


def test_preempt_resume_sharded():
    reference = _sharded(TwoPhaseSys(4).checker()).join()
    assert reference.worker_error() is None
    assert reference.unique_state_count() == 1568
    first = _sharded(
        TwoPhaseSys(4).checker(), max_drain_waves=2,
    )
    if not _preempt_at(first, threshold=200):
        pytest.skip("run finished before the preempt request landed")
    resumed = _sharded(
        TwoPhaseSys(4).checker(),
        resume_from=first.preempt_payload(),
    ).join()
    assert resumed.worker_error() is None
    assert resumed.unique_state_count() == reference.unique_state_count()
    assert resumed.state_count() == reference.state_count()
    assert resumed._discoveries_fp == reference._discoveries_fp
    resumed.assert_properties()


def test_solo_preempt_payload_admits_into_pack(uninterrupted_2pc4):
    """Cross-path resume (PR 12): a SOLO checker's preempt payload
    admits into a tenant-packed engine — the packed continuation is
    bit-identical to the uninterrupted solo run. (The reverse direction
    — a dropped tenant's payload slice resuming on a solo checker — is
    tests/test_packed_tenancy.py.)"""
    from stateright_tpu.checker.packed_tenancy import TenantPackedEngine

    checker = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        max_drain_waves=2, **SPAWN_2PC4
    )
    if not _preempt_at(checker, threshold=200):
        pytest.skip("run finished before the preempt landed")
    engine = TenantPackedEngine(
        TwoPhaseSys(4),
        frontier_capacity=16, table_capacity=1 << 12, max_tenants=4,
        aot_cache="t-pack-resume",
    )
    view = engine.admit(
        "resumed", "pk-xr", resume_from=checker.preempt_payload()
    )
    steps = 0
    while engine.live_count():
        engine.step()
        steps += 1
        assert steps < 20_000
    engine.close()
    assert view.unique_state_count() == (
        uninterrupted_2pc4.unique_state_count()
    )
    assert view.state_count() == uninterrupted_2pc4.state_count()
    assert view.max_depth() == uninterrupted_2pc4.max_depth()
    assert _golden(view) == _golden(uninterrupted_2pc4)
