"""Packed ordered networks + crash faults: device/host exact parity.

Round-2 capability closes (VERDICT items 4): ordered FIFO flows per the
reference's ``Network::Ordered`` (``src/actor/network.rs:46-68``, head-of-flow
delivery ``src/actor/model.rs:254-259``) and ``Crash`` actions
(``src/actor/model.rs:372-381``) on the device path, including the
hash-excludes-``crashed`` quirk (``src/actor/model_state.rs:86-97``) via
``packed_fingerprint_view``.
"""

import numpy as np
import pytest

from stateright_tpu.actor import Network
from stateright_tpu.models.linearizable_register import AbdModelCfg
from stateright_tpu.models.raft import RaftModelCfg


def _tpu(model, **kw):
    kw.setdefault("frontier_capacity", 256)
    kw.setdefault("table_capacity", 1 << 14)
    checker = model.checker().spawn_tpu_bfs(**kw).join()
    assert checker.worker_error() is None
    return checker


@pytest.mark.slow
def test_ordered_abd_round_trip_and_parity():
    # The `linearizable-register check N ordered` bench family
    # (reference bench.sh:31-34), scaled to the 2-client config.
    model = AbdModelCfg(2, 2, network=Network.new_ordered()).into_model()
    init = model.init_states()[0]
    assert model.unpack_state(model.pack_state(init)) == init
    host = model.checker().spawn_bfs().join()
    dev = _tpu(model)
    assert host.unique_state_count() == dev.unique_state_count() == 620
    assert sorted(host.discoveries()) == sorted(dev.discoveries()) == [
        "value chosen"
    ]
    dev.assert_properties()


@pytest.mark.slow
def test_raft_crash_faults_parity():
    model = RaftModelCfg(
        server_count=3, max_term=1, lossy=True, max_crashes=1
    ).into_model()
    init = model.init_states()[0]
    assert model.unpack_state(model.pack_state(init)) == init
    host = model.checker().spawn_bfs().join()
    dev = _tpu(model)
    assert host.unique_state_count() == dev.unique_state_count() == 2252
    assert sorted(dev.discoveries()) == ["leader elected", "stable leader"]


def test_crashed_flags_excluded_from_fingerprint():
    model = RaftModelCfg(
        server_count=3, max_term=1, max_crashes=1
    ).into_model()
    packed = model.pack_state(model.init_states()[0])
    view = model.packed_fingerprint_view(packed)
    assert "crashed" in packed and "crashed" not in view


@pytest.mark.slow
def test_raft_crash_sharded_parity():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("fp",))
    checker = (
        RaftModelCfg(server_count=3, max_term=1, lossy=True, max_crashes=1)
        .into_model()
        .checker()
        .spawn_sharded_tpu_bfs(
            mesh=mesh, frontier_per_device=64, table_capacity_per_device=1 << 10
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 2252


def test_nonempty_initial_network_packs_with_host_parity():
    """Pre-seeded initial networks (reference ``ActorModel::init_network``,
    ``src/actor/model.rs:96-100``) stage onto the device path: the packed
    init states carry the seeded envelopes, and counts match the host
    checker exactly. Seeds a RequestVote so server 1 can immediately grant
    a vote it would otherwise only see after a timeout."""
    from stateright_tpu.actor.network import Envelope

    seeded = Network.new_unordered_nonduplicating(
        [Envelope(src=0, dst=1, msg=("RequestVote", 1))]
    )
    cfg = RaftModelCfg(
        server_count=3, max_term=1, lossy=True, network=seeded
    )
    host = cfg.into_model().checker().spawn_bfs().join()
    dev = _tpu(cfg.into_model())
    assert dev.unique_state_count() == host.unique_state_count()
    assert set(dev.discoveries()) == set(host.discoveries())


@pytest.mark.slow
def test_nonempty_initial_ordered_network_packs_with_host_parity():
    """Same, over per-pair FIFO flows: the seeded queue order is the
    packed flows' positional canonical order."""
    from stateright_tpu.actor.network import Envelope

    seeded = Network.new_ordered(
        [
            Envelope(src=0, dst=1, msg=("RequestVote", 1)),
            Envelope(src=2, dst=1, msg=("RequestVote", 1)),
        ]
    )
    cfg = RaftModelCfg(
        server_count=3, max_term=1, lossy=False, network=seeded
    )
    host = cfg.into_model().checker().spawn_bfs().join()
    dev = _tpu(cfg.into_model())
    assert dev.unique_state_count() == host.unique_state_count()
    assert set(dev.discoveries()) == set(host.discoveries())


@pytest.mark.slow
def test_ordered_abd_3_clients_bench_family_parity():
    """The `linearizable-register check 3 ordered` bench-family config
    (BASELINE.md measurement configs): 3 clients / 2 servers over ordered
    FIFO flows, 46,516 states (host oracle measured once, pinned), with
    the linearizability history holding on the device path."""
    model = AbdModelCfg(
        3, 2, network=Network.new_ordered(), envelope_capacity=12
    ).into_model()
    checker = (
        model.checker()
        .spawn_tpu_bfs(frontier_capacity=1 << 11, table_capacity=1 << 17)
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 46_516
    checker.assert_properties()
