"""Conformance plane: wire codec, device/host parity, service traffic.

The load-bearing guarantee is **bit-identity**: every device verdict —
lin/SC consistency for histories, first-divergence index + offending
action for traces — must equal the host oracle on the same record
(``audit.host_is_consistent`` / ``replay.replay_host``). The randomized
parity sweeps here run hundreds of seeded histories per shape bucket,
covering the edges the packed codecs must model: in-flight tail ops,
double invokes, orphan returns, wrong returns.
"""

import json
import os
import random
import time
import threading

import pytest

from stateright_tpu.conformance import (
    ConformanceChecker,
    WireRefusal,
    audit_batch,
    bucket_records,
    decode_lines,
    encode_record,
    host_is_consistent,
    mutate_trace,
    random_history,
    random_walk_trace,
    replay_batch,
    replay_host,
)
from stateright_tpu.conformance.audit import pack_history
from stateright_tpu.service.jobs import JobHandle, RetryPolicy
from stateright_tpu.service.service import CheckService
from stateright_tpu.service.zoo import aot_namespace, default_zoo
from stateright_tpu.telemetry import registry_hygiene_problems
from stateright_tpu.telemetry.metrics import metrics_registry
from stateright_tpu.utils.faults import FaultSpec, inject

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEED_CORPUS = os.path.join(
    REPO_DIR, "examples", "conformance_corpus.jsonl"
)

# Every history shape bucket the parity sweep covers: (spec, semantics,
# client threads, ops per thread).
HISTORY_SHAPES = (
    ("register", "linearizability", 2, 2),
    ("register", "sequential", 2, 2),
    ("register", "linearizability", 3, 2),
    ("vec", "linearizability", 2, 2),
    ("vec", "sequential", 2, 2),
)


def _histories(seed, n, spec, semantics, threads, ops):
    """n seeded histories for one shape, cycling clean/random/invalid
    (random mode leaves tail ops in flight ~25% of the time)."""
    rng = random.Random(seed)
    modes = ("clean", "random", "invalid")
    return [
        random_history(
            rng, spec=spec, semantics=semantics, threads=threads,
            ops_per_thread=ops, mode=modes[i % 3],
            rec_id=f"{spec[:3]}-{semantics[:3]}-{i}",
        )
        for i in range(n)
    ]


# -- wire --------------------------------------------------------------------


def test_wire_roundtrip_all_shapes():
    records = []
    for i, (spec, semantics, c, o) in enumerate(HISTORY_SHAPES):
        records += _histories(100 + i, 30, spec, semantics, c, o)
    lines = [encode_record(r) for r in records]
    decoded, refusals = decode_lines(lines)
    assert not refusals, refusals[:2]
    assert len(decoded) == len(records)
    for orig, dec in zip(records, decoded):
        # Prefix compare: the decoder stops at a latching client bug
        # (double invoke / orphan return) — the host testers refuse
        # everything after the latch, so the tail is unreachable.
        assert dec["events"] == [
            tuple(e) for e in orig["events"][: len(dec["events"])]
        ]
        assert dec["semantics"] == orig["semantics"]
        assert dec["spec"] == orig["spec"]
        if orig["meta"].get("expect") != "invalid":
            assert len(dec["events"]) == len(orig["events"])


def test_wire_trace_roundtrip_exact():
    zoo = default_zoo()
    model = zoo["increment_lock"]()
    rng = random.Random(3)
    rec = random_walk_trace(
        model, rng, 10, model_name="increment_lock"
    )
    decoded, refusals = decode_lines([encode_record(rec)])
    assert not refusals
    assert decoded[0]["actions"] == rec["actions"]
    assert decoded[0]["init"] == rec["init"]
    assert decoded[0]["model"] == "increment_lock"


def test_wire_refusals_are_honest():
    bad = [
        "not json at all",
        json.dumps({"kind": "trace", "id": "x"}),  # no version
        json.dumps({"v": 99, "kind": "trace", "id": "x"}),
        json.dumps({"v": 1, "kind": "trace", "id": "x"}),  # no model
        json.dumps({"v": 1, "kind": "history", "id": "h",
                    "spec": "register", "semantics": "causal",
                    "events": []}),  # unknown semantics
        json.dumps({"v": 1, "kind": "history", "id": "h",
                    "spec": "register",
                    "semantics": "linearizability",
                    "events": [["banana", 0]]}),  # bad event type
    ]
    decoded, refusals = decode_lines(bad)
    assert decoded == []
    assert len(refusals) == len(bad)
    for i, r in enumerate(refusals):
        assert r["line"] == i + 1  # 1-based line numbers in refusals
        assert r["reason"]
    with pytest.raises(WireRefusal):
        decode_lines(bad, strict=True)


# -- device/host parity: histories -------------------------------------------


@pytest.mark.parametrize(
    "spec,semantics,threads,ops",
    HISTORY_SHAPES,
    ids=[f"{s}-{m[:3]}-C{c}O{o}" for s, m, c, o in HISTORY_SHAPES],
)
def test_history_parity_randomized(spec, semantics, threads, ops):
    """>=500 seeded histories per shape bucket: the vmapped device
    verdict equals the host tester's on every one, and the
    by-construction labels hold (clean => consistent, invalid =>
    inconsistent)."""
    records = _histories(42, 500, spec, semantics, threads, ops)
    lines = [encode_record(r) for r in records]
    decoded, refusals = decode_lines(lines)
    assert not refusals, refusals[:2]
    assert len(decoded) == len(records)
    mismatches = 0
    checked = []
    refused = []
    # The real ingestion pipeline: wire-decoded records, bucketed by
    # exact shape (an injected double invoke bumps a record's O, so a
    # mixed sweep spans several buckets), one dispatch per bucket.
    for recs in bucket_records(decoded).values():
        verdicts = audit_batch(recs)
        assert len(verdicts) == len(recs)
        for rec, v in zip(recs, verdicts):
            if v.get("refused") is not None:
                # A client-bug injection can bump a record past the
                # device compile-sanity bounds; the refusal must be
                # honest (named bound, only ever an invalid record —
                # clean/random records stay inside the sweep's shape).
                assert rec["meta"]["expect"] == "invalid", (
                    rec["id"], v,
                )
                assert "bound is" in v["refused"], v
                refused.append(rec["id"])
                continue
            checked.append(rec["id"])
            host = host_is_consistent(rec)
            if bool(v["consistent"]) != host:
                mismatches += 1
            expect = rec["meta"]["expect"]
            if expect == "consistent":
                assert v["consistent"], rec["id"]
            elif expect == "invalid":
                assert not v["consistent"], rec["id"]
                assert not v["valid_history"], rec["id"]
    assert len(checked) + len(refused) == len(records)
    # Refusals are the over-bound tail, never the bulk of the sweep.
    assert len(checked) >= (2 * len(records)) // 3
    assert mismatches == 0


def test_sequential_weaker_than_linearizability():
    """SC drops the real-time constraint: every linearizable history is
    SC-consistent, and some SC-consistent histories are NOT
    linearizable (stale reads of non-overlapping ops). Both facts must
    show up in a randomized sweep."""
    rows = _histories(9, 300, "register", "linearizability", 2, 2)
    decoded, refusals = decode_lines([encode_record(r) for r in rows])
    assert not refusals
    gap = 0
    for recs in bucket_records(decoded).values():
        lin_v = audit_batch(recs)
        sc_v = audit_batch(
            [dict(r, semantics="sequential") for r in recs]
        )
        for lv, sv in zip(lin_v, sc_v):
            if lv["consistent"]:
                assert sv["consistent"]  # lin => SC
            if sv["consistent"] and not lv["consistent"]:
                gap += 1
    assert gap > 0, "sweep never exercised the lin/SC gap"


# -- device/host parity: traces ----------------------------------------------


def _trace_bundle(model_name, seed=5, n=6, steps=10):
    zoo = default_zoo()
    model = zoo[model_name]()
    rng = random.Random(seed)
    clean = [
        random_walk_trace(
            model, rng, steps, rec_id=f"{model_name}-{i}",
            model_name=model_name,
        )
        for i in range(n)
    ]
    mutated = [m for m in (
        mutate_trace(model, rng, r) for r in clean
    ) if m is not None]
    return model, clean + mutated


@pytest.mark.parametrize("model_name", ["increment_lock", "2pc"])
def test_trace_parity_bit_identical(model_name):
    """Device replay verdicts equal the host oracle on all five fields
    (conforms, divergence index, offending action, steps, final
    fingerprint) for clean and known-divergent traces."""
    model, records = _trace_bundle(model_name)
    assert any(r["id"].endswith("-div") for r in records)
    T = max(len(r["actions"]) for r in records)
    ns = aot_namespace(model_name, {})
    verdicts = replay_batch(records, model, ns, T, lanes=16)
    for rec, v in zip(records, verdicts):
        host = replay_host(rec, model)
        assert v == host, (rec["id"], v, host)
        if rec["id"].endswith("-div"):
            assert not v["conforms"]
            assert v["divergence_index"] == (
                rec["meta"]["divergence_index"]
            )
            assert v["offending_action"] == (
                rec["meta"]["offending_action"]
            )
        else:
            assert v["conforms"] and v["divergence_index"] is None


def test_trace_padding_is_inert():
    """A short trace in a long lane bucket must score identically to
    the same trace in a tight bucket (padding never steps)."""
    model, records = _trace_bundle("increment_lock", n=3)
    ns = aot_namespace("increment_lock", {})
    T = max(len(r["actions"]) for r in records)
    tight = replay_batch(records, model, ns, T, lanes=len(records))
    padded = replay_batch(records, model, ns, T + 7, lanes=64)
    assert tight == padded


# -- checker + seed corpus ---------------------------------------------------


def _seed_records():
    with open(SEED_CORPUS, encoding="utf-8") as f:
        lines = [ln for ln in f if ln.strip()]
    records, refusals = decode_lines(lines)
    assert not refusals
    return lines, records


def test_seed_corpus_checker_parity_and_hygiene():
    """The checked-in corpus through ConformanceChecker with the host
    parity gate ON: labels hold, metrics registry passes the hygiene
    lint, report counts are consistent."""
    lines, records = _seed_records()
    ck = ConformanceChecker(
        records, default_zoo(), run_id="t-conf-seed", parity=True,
        batch_lanes=32,
    )
    deadline = time.monotonic() + 300
    while not ck.is_done() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert ck.is_done() and ck.worker_error() is None
    rep = ck.conformance_report()
    # The corpus deliberately carries one history past the register DP
    # compile-sanity bound — an honest refusal, not a stall.
    refuse_ids = {
        r["id"] for r in records
        if r["kind"] == "history" and pack_history(r)[1] is not None
    }
    assert rep["refusals"] == len(refuse_ids) > 0
    assert (
        rep["traces"] + rep["histories"] + rep["refusals"]
        == len(records)
    )
    n_div_labels = sum(
        1 for r in records if r["kind"] == "trace"
        and r["meta"].get("expect") == "divergent"
    )
    assert rep["divergences"] == n_div_labels
    for rec, v in zip(records, rep["records"]):
        if rec["kind"] != "trace":
            continue
        if rec["meta"].get("expect") == "divergent":
            assert v["divergence_index"] == (
                rec["meta"]["divergence_index"]
            ), (rec["id"], v)
        else:
            assert v["conforms"], (rec["id"], v)
    assert registry_hygiene_problems(
        metrics_registry("t-conf-seed")
    ) == []


def test_checker_refuses_unknown_model_not_crashes():
    rec = {
        "kind": "trace", "id": "t", "model": "no-such-model",
        "model_args": {}, "init": 0, "actions": [0], "meta": {},
    }
    ck = ConformanceChecker([rec], default_zoo(), parity=False)
    deadline = time.monotonic() + 60
    while not ck.is_done() and time.monotonic() < deadline:
        time.sleep(0.01)
    rep = ck.conformance_report()
    assert rep["refusals"] == 1
    assert "no-such-model" in rep["records"][0]["refused"]


# -- service traffic class ---------------------------------------------------


def _svc(**kw):
    kw.setdefault("warm_start", False)
    return CheckService(**kw)


def test_service_conformance_job_end_to_end(tmp_path):
    lines, records = _seed_records()
    svc = _svc(service_dir=str(tmp_path / "svc"))
    try:
        h = svc.submit(conformance=lines, spawn={"parity": True})
        res = h.result(timeout=300)
        conf = res["conformance"]
        assert len(conf["records"]) == len(records)
        # The corpus's one over-bound history surfaces as an honest
        # per-record refusal in the service verdict too.
        n_refuse = sum(
            1 for r in records
            if r["kind"] == "history"
            and pack_history(r)[1] is not None
        )
        assert conf["refusals"] == n_refuse > 0
        assert conf["divergences"] >= 1
        st = h.status()
        assert st["mode"] == "conformance"
        assert st["packable"] is False  # honest scheduling surface
        # Named-corpus store round-trip (the HTTP "corpus" field's
        # backing): names only, never paths.
        svc.corpus_store.save("seed", lines)
        assert svc.corpus_store.list() == ["seed"]
        with pytest.raises(ValueError, match="invalid corpus name"):
            svc.corpus_store.load("../../etc/passwd")
    finally:
        svc.close()


def test_service_conformance_rejects_model_surface():
    svc = _svc()
    try:
        with pytest.raises(ValueError, match="model"):
            svc.submit(
                conformance=["{}"], model_name="2pc",
                mode="conformance",
            )
        with pytest.raises(ValueError, match="spawn"):
            svc.submit(
                conformance=["{}"],
                spawn={"resume_from": "/tmp/evil"},
            )
        with pytest.raises(WireRefusal):
            svc.submit(conformance=['{"v": 1, "kind": "trace"}'])
    finally:
        svc.close()


def test_service_conformance_fault_retry_bit_identical(tmp_path):
    """A conformance.batch fault mid-audit: the retry recovers through
    the journal and the final verdicts are bit-identical to a
    fault-free run of the same upload."""
    lines, _ = _seed_records()
    svc = _svc(service_dir=str(tmp_path / "svc"))
    try:
        clean = svc.submit(conformance=lines).result(timeout=300)
        with inject(FaultSpec("conformance.batch", at=0)):
            h = svc.submit(
                conformance=lines,
                retry_policy=RetryPolicy(
                    max_retries=2, backoff_s=0.01
                ),
            )
            res = h.result(timeout=300)
        assert h.status()["retries"] >= 1
        assert h.status()["faults"], "fault never injected"
        assert res["conformance"]["records"] == (
            clean["conformance"]["records"]
        )
    finally:
        svc.close()


def test_service_conformance_journal_recovery(tmp_path):
    """A journaled-but-never-run conformance job replays from its
    durable spec (the canonical wire lines) on recover(), bit-identical
    to a fresh submission."""
    lines, _ = _seed_records()
    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "jobs"), exist_ok=True)
    spec = {
        "mode": "conformance", "records": lines,
        "spawn": {"parity": False}, "priority": 0,
        "deadline_s": None, "tenant": None, "timeout_s": None,
        "retry_policy": None,
    }
    with open(os.path.join(d, "journal.jsonl"), "w") as f:
        f.write(json.dumps({
            "ev": "submit", "t": 0.0, "job_id": "conf-rec",
            "durable": True, "spec": spec,
        }) + "\n")
    svc = CheckService.recover(d, warm_start=False)
    try:
        job = svc.job("conf-rec")
        assert job is not None and job.state != "failed", (
            job and job.error
        )
        r_rec = JobHandle(job, svc).result(timeout=300)
        r_fresh = svc.submit(conformance=lines).result(timeout=300)
        assert r_rec["conformance"]["records"] == (
            r_fresh["conformance"]["records"]
        )
    finally:
        svc.close()


def test_service_conformance_preempt_resume_bit_identical(tmp_path):
    """Driven-slice preemption mid-upload: the resumed incarnation's
    verdict table equals an uninterrupted run's exactly (the preempt
    payload carries the verdict cursor, not partial batches)."""
    lines, records = _seed_records()
    svc = _svc(service_dir=str(tmp_path / "svc"), quantum_s=30.0)
    try:
        # Baseline first, while the scheduler is still alive; parking
        # it below (join) is permanent for this service instance.
        baseline = svc.submit(conformance=lines).result(timeout=300)
        svc._closing.set()
        svc._wake()
        svc._scheduler.join(timeout=30)
        svc._closing.clear()
        h = svc.submit(conformance=lines, spawn={"batch_lanes": 4})
        job = svc.job(h.job_id)
        t = threading.Thread(target=svc._run_slice, args=(job,))
        t.start()
        deadline = time.monotonic() + 60
        while (
            svc._active_checker is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.001)
        ck = svc._active_checker
        assert ck is not None
        ck.request_preempt()
        t.join(timeout=180)
        if job.state == "suspended":  # preempt landed mid-upload
            svc._run_slice(job)
        assert job.state == "done", (job.state, job.error)
        assert job.result["conformance"]["records"] == (
            baseline["conformance"]["records"]
        )
    finally:
        svc.close()
