"""Checkpoint/resume for the TPU BFS checker.

New capability (SURVEY §5 flags the reference's lack: a killed check loses
all progress). Wave-granular: the parent-pointer map + pending frontier
chunks serialize; the device visited set is rebuilt from the parent map's
keys on resume.
"""

import pytest

from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def test_resume_completes_the_space(tmp_path):
    ckpt = tmp_path / "2pc.ckpt"
    first = (
        TwoPhaseSys(4)
        .checker()
        .target_state_count(500)  # stop early, leaving work pending
        .spawn_tpu_bfs(
            frontier_capacity=64,
            checkpoint_path=str(ckpt),
            checkpoint_every_chunks=1,
        )
        .join()
    )
    assert first.worker_error() is None
    assert ckpt.exists()
    assert first.unique_state_count() < 1568

    resumed = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=64, resume_from=str(ckpt))
        .join()
    )
    assert resumed.worker_error() is None
    assert resumed.unique_state_count() == 1568
    resumed.assert_properties()
    # Discovery paths replay through the restored parent map.
    for path in resumed.discoveries().values():
        assert len(path) >= 1


def test_resume_rejects_non_batchable_model(tmp_path):
    from stateright_tpu import FnModel

    def fn(prev, out):
        if prev is None:
            out.append(0)

    with pytest.raises(TypeError):
        FnModel(fn).checker().spawn_tpu_bfs(
            resume_from=str(tmp_path / "nope.ckpt")
        )


def test_resume_rejects_differently_configured_model(tmp_path):
    ckpt = tmp_path / "2pc.ckpt"
    TwoPhaseSys(3).checker().target_state_count(50).spawn_tpu_bfs(
        frontier_capacity=64,
        checkpoint_path=str(ckpt),
        checkpoint_every_chunks=1,
    ).join()
    assert ckpt.exists()

    # Same class, different parameters: mixing the 3-RM visited set into a
    # 4-RM search must be refused, not silently corrupted.
    resumed = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        frontier_capacity=64, resume_from=str(ckpt)
    )
    with pytest.raises(RuntimeError):
        resumed.join()
    err = resumed.worker_error()
    assert isinstance(err, ValueError)
    assert "differently-configured" in str(err)


def _sharded(model_checker, n_dev=8, **kw):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("fp",))
    kw.setdefault("frontier_per_device", 32)
    kw.setdefault("table_capacity_per_device", 512)
    return model_checker.spawn_sharded_tpu_bfs(mesh=mesh, **kw)


def test_sharded_resume_completes_the_space(tmp_path):
    ckpt = tmp_path / "2pc-sharded.ckpt"
    first = _sharded(
        TwoPhaseSys(4).checker().target_state_count(500),
        checkpoint_path=str(ckpt),
        checkpoint_every_chunks=1,
    ).join()
    assert first.worker_error() is None
    assert ckpt.exists()
    assert first.unique_state_count() < 1568

    resumed = _sharded(
        TwoPhaseSys(4).checker(), resume_from=str(ckpt)
    ).join()
    assert resumed.worker_error() is None
    assert resumed.unique_state_count() == 1568
    resumed.assert_properties()
    # Discovery paths replay through the restored parent map.
    for path in resumed.discoveries().values():
        assert len(path) >= 1


def test_sharded_resume_on_a_different_mesh_size(tmp_path):
    # Keys re-route by `hi % n` on restore, so a checkpoint written on an
    # 8-device mesh resumes on a 4-device one (elastic restart — the
    # reference has no notion of this at all).
    ckpt = tmp_path / "2pc-elastic.ckpt"
    _sharded(
        TwoPhaseSys(4).checker().target_state_count(500),
        n_dev=8,
        checkpoint_path=str(ckpt),
        checkpoint_every_chunks=1,
    ).join()
    assert ckpt.exists()
    resumed = _sharded(
        TwoPhaseSys(4).checker(), n_dev=4, resume_from=str(ckpt)
    ).join()
    assert resumed.worker_error() is None
    assert resumed.unique_state_count() == 1568
    resumed.assert_properties()


def test_sharded_resume_rejects_differently_configured_model(tmp_path):
    ckpt = tmp_path / "2pc-sharded3.ckpt"
    _sharded(
        TwoPhaseSys(3).checker().target_state_count(50),
        checkpoint_path=str(ckpt),
        checkpoint_every_chunks=1,
    ).join()
    assert ckpt.exists()

    resumed = _sharded(TwoPhaseSys(4).checker(), resume_from=str(ckpt))
    with pytest.raises(RuntimeError):
        resumed.join()
    err = resumed.worker_error()
    assert isinstance(err, ValueError)
    assert "differently-configured" in str(err)


def test_cross_checker_resume_is_rejected(tmp_path):
    # A TpuBfs checkpoint has a chunk queue, a sharded one a frontier pool;
    # resuming across kinds must fail loudly, not KeyError mid-restore.
    ckpt = tmp_path / "kind.ckpt"
    TwoPhaseSys(3).checker().target_state_count(50).spawn_tpu_bfs(
        frontier_capacity=64,
        checkpoint_path=str(ckpt),
        checkpoint_every_chunks=1,
    ).join()
    assert ckpt.exists()
    resumed = _sharded(TwoPhaseSys(3).checker(), resume_from=str(ckpt))
    with pytest.raises(RuntimeError):
        resumed.join()
    assert "kind" in str(resumed.worker_error())

    ckpt2 = tmp_path / "kind2.ckpt"
    _sharded(
        TwoPhaseSys(3).checker().target_state_count(50),
        checkpoint_path=str(ckpt2),
        checkpoint_every_chunks=1,
    ).join()
    assert ckpt2.exists()
    resumed2 = TwoPhaseSys(3).checker().spawn_tpu_bfs(
        frontier_capacity=64, resume_from=str(ckpt2)
    )
    with pytest.raises(RuntimeError):
        resumed2.join()
    assert "kind" in str(resumed2.worker_error())


def test_checkpoint_counts_are_coherent(tmp_path):
    ckpt = tmp_path / "2pc3.ckpt"
    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=32,
            checkpoint_path=str(ckpt),
            checkpoint_every_chunks=1,
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 288
    # Resuming a finished run is a no-op continuation that converges to the
    # same counts.
    resumed = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=32, resume_from=str(ckpt))
        .join()
    )
    assert resumed.worker_error() is None
    assert resumed.unique_state_count() == 288


def test_stale_sym_scheme_header_is_refused():
    # Unit level (ADVICE r03): an r2-era orbit-min checkpoint (stale or
    # absent sym_scheme tag) must not resume into the r3 WL key space,
    # and full-group vs custom-representative schemes must never mix.
    from stateright_tpu.checker.tpu import (
        CUSTOM_REP_SCHEME,
        SYM_KEY_SCHEME,
        checkpoint_header,
        validate_checkpoint_header,
    )

    model = TwoPhaseSys(3)

    def validate(payload, sym_scheme=SYM_KEY_SCHEME):
        validate_checkpoint_header(
            payload, "tpu_bfs", "hint", model, model.packed_action_count(),
            symmetry=True, sym_scheme=sym_scheme,
        )

    good = checkpoint_header(
        "tpu_bfs", model, model.packed_action_count(), symmetry=True
    )
    validate(good)  # sanity: the untampered header passes

    stale = dict(good, sym_scheme="orbitmin-v1")
    with pytest.raises(ValueError, match="symmetry-key scheme"):
        validate(stale)

    absent = dict(good)
    absent["sym_scheme"] = None
    with pytest.raises(ValueError, match="symmetry-key scheme"):
        validate(absent)

    # Full-group checkpoint into a custom-representative checker and the
    # reverse: refused both ways.
    with pytest.raises(ValueError, match="symmetry-key scheme"):
        validate(good, sym_scheme=CUSTOM_REP_SCHEME)
    custom = dict(good, sym_scheme=CUSTOM_REP_SCHEME)
    with pytest.raises(ValueError, match="symmetry-key scheme"):
        validate(custom, sym_scheme=SYM_KEY_SCHEME)


def test_tampered_sym_scheme_checkpoint_refused_on_resume(tmp_path):
    # Integration level: a REAL symmetry checkpoint whose sym_scheme tag
    # is rewritten to the r2 scheme must be refused by an actual resume.
    import pickle

    ckpt = tmp_path / "2pc3-sym.ckpt"
    first = (
        TwoPhaseSys(3)
        .checker()
        .symmetry()
        .target_state_count(40)
        .spawn_tpu_bfs(
            frontier_capacity=32,
            checkpoint_path=str(ckpt),
            checkpoint_every_chunks=1,
        )
        .join()
    )
    assert first.worker_error() is None
    assert ckpt.exists()

    payload = pickle.loads(ckpt.read_bytes())
    payload["sym_scheme"] = "orbitmin-v1"
    ckpt.write_bytes(pickle.dumps(payload))

    resumed = (
        TwoPhaseSys(3)
        .checker()
        .symmetry()
        .spawn_tpu_bfs(frontier_capacity=32, resume_from=str(ckpt))
    )
    with pytest.raises(RuntimeError):
        resumed.join()
    err = resumed.worker_error()
    assert isinstance(err, ValueError)
    assert "symmetry-key scheme" in str(err)
