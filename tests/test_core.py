"""Core-layer tests: fingerprinting, paths, visitors.

Mirrors reference coverage in ``src/lib.rs``, ``src/checker/path.rs`` tests.
"""

import dataclasses

import pytest

from fixtures import LinearEquation
from stateright_tpu import FnModel, Path, fingerprint, stable_hash


class TestFingerprint:
    def test_stable_across_calls(self):
        assert fingerprint((1, 2, "x")) == fingerprint((1, 2, "x"))

    def test_nonzero(self):
        for v in [0, 1, "", (), None, frozenset()]:
            assert fingerprint(v) != 0

    def test_distinguishes_values(self):
        assert fingerprint((1, 2)) != fingerprint((2, 1))
        assert fingerprint(1) != fingerprint("1")
        assert fingerprint((1, (2,))) != fingerprint(((1,), 2))
        assert fingerprint(0) != fingerprint(False)

    def test_unordered_containers_are_order_insensitive(self):
        assert stable_hash({1, 2, 3}) == stable_hash({3, 1, 2})
        assert stable_hash({"a": 1, "b": 2}) == stable_hash({"b": 2, "a": 1})
        assert stable_hash(frozenset([1, 2])) == stable_hash({2, 1})

    def test_list_and_tuple_equivalent(self):
        assert stable_hash([1, 2]) == stable_hash((1, 2))

    def test_dataclass(self):
        @dataclasses.dataclass
        class S:
            x: int
            y: tuple

        assert stable_hash(S(1, (2,))) == stable_hash(S(1, (2,)))
        assert stable_hash(S(1, (2,))) != stable_hash(S(2, (2,)))

    def test_golden_values(self):
        # Pin fingerprints so accidental encoding changes (which would break
        # path-by-fingerprint replay across versions) are caught.
        assert fingerprint((0, 0)) == 10608462791517047230
        assert fingerprint("init") == 15397491202650269466

    def test_rejects_unhashable(self):
        with pytest.raises(TypeError):
            stable_hash(object())


class TestPath:
    def test_from_fingerprints_replays_model(self):
        model = LinearEquation(2, 10, 14)
        fps = [
            fingerprint((0, 0)),
            fingerprint((0, 1)),
            fingerprint((1, 1)),
            fingerprint((2, 1)),
        ]
        path = Path.from_fingerprints(model, fps)
        assert path.last_state() == (2, 1)
        assert path.last_state() == Path.final_state(model, fps)

    def test_from_fingerprints_raises_on_bad_init(self):
        def fn(prev, out):
            if prev is None:
                out.append("UNEXPECTED")

        model = FnModel(fn)
        with pytest.raises(RuntimeError, match="No\ninit state"):
            Path.from_fingerprints(model, [fingerprint("expected")])

    def test_from_fingerprints_raises_on_bad_next(self):
        def fn(prev, out):
            if prev is None:
                out.append("expected")
            else:
                out.append("UNEXPECTED")

        model = FnModel(fn)
        with pytest.raises(RuntimeError, match="no subsequent"):
            Path.from_fingerprints(
                model, [fingerprint("expected"), fingerprint("expected")]
            )

    def test_from_actions(self):
        model = LinearEquation(2, 10, 14)
        path = Path.from_actions(model, (0, 0), ["IncreaseX", "IncreaseY"])
        assert path.last_state() == (1, 1)
        assert path.into_actions() == ["IncreaseX", "IncreaseY"]
        assert Path.from_actions(model, (9, 9), ["IncreaseX"]) is None

    def test_encode_and_display(self):
        model = LinearEquation(2, 10, 14)
        path = Path.from_actions(model, (0, 0), ["IncreaseX"])
        assert path.encode() == f"{fingerprint((0, 0))}/{fingerprint((1, 0))}"
        assert str(path) == "Path[1]:\n- 'IncreaseX'\n"

    def test_into_states_and_vec(self):
        model = LinearEquation(2, 10, 14)
        path = Path.from_actions(model, (0, 0), ["IncreaseY"])
        assert path.into_states() == [(0, 0), (0, 1)]
        assert path.into_vec() == [((0, 0), "IncreaseY"), ((0, 1), None)]
