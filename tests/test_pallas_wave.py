"""Fused Pallas wave megakernel (``wave_kernel="fused"``): staged-vs-fused
bit-identity across the zoo, composition with preempt/resume and the
capability surfaces, and honest refusals.

The fused wave (ops/pallas_wave.py) runs the whole wave body — packed
expand, fingerprinting, sort-dedup, the VMEM tile-sweep insert,
compaction, property evaluation, coverage reductions — in ONE Pallas
dispatch. Off-TPU it executes under the Pallas interpreter with exact
semantics, so this module exercises the real kernel logic on CPU: every
check here compares against ``wave_kernel="staged"`` with
``wave_dedup="sort"`` — the dedup discipline the fused sweep embeds —
and demands BIT-IDENTICAL results (counts, depths, discovery
fingerprints, golden reports including violation traces, coverage
ledgers).

Interpret-mode waves are slow, so the 2pc-3 pair is spawned ONCE as
module fixtures (with coverage recording on, so the same pair also
settles the coverage-ledger identity) and shared by every 2pc-shaped
assertion; only checks whose config genuinely differs (per-wave engine,
preempt/resume, capacity rounding) pay their own spawns."""

import io
import re
import time

import pytest

from stateright_tpu import WriteReporter
from stateright_tpu.models.sharded_kv import ShardedKv
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from test_tpu_bfs import Chain

# Shared shapes: 4096 rows = 2 tile-sweep tiles, so the fused grid's
# window chaining (apron patching across consecutive tiles) is
# exercised, not just the single-tile fast case.
SPAWN = {"frontier_capacity": 256, "table_capacity": 1 << 12}


def _golden(checker):
    out = io.StringIO()
    checker.report(WriteReporter(out))
    return re.sub(r"sec=\d+", "sec=_", out.getvalue())


def _spawn(model, **kw):
    checker = model.checker().spawn_tpu_bfs(**SPAWN, **kw).join()
    assert checker.worker_error() is None
    return checker


def _assert_bit_identical(fused, staged):
    assert fused.unique_state_count() == staged.unique_state_count()
    assert fused.state_count() == staged.state_count()
    assert fused.max_depth() == staged.max_depth()
    assert fused._discoveries_fp == staged._discoveries_fp
    assert _golden(fused) == _golden(staged)


@pytest.fixture(scope="module")
def staged_2pc():
    return _spawn(TwoPhaseSys(3), wave_dedup="sort", coverage=True)


@pytest.fixture(scope="module")
def fused_2pc():
    return _spawn(TwoPhaseSys(3), wave_kernel="fused", coverage=True)


# -- zoo bit-identity -------------------------------------------------------

ZOO = [
    # Shallow always-violation at depth 2: the golden compare pins the
    # first-violation trace, not just the verdict.
    ("sharded_kv unguarded", lambda: ShardedKv(2, 2, 1, guarded=False)),
    # The fixed protocol: same shapes, passing verdict.
    ("sharded_kv guarded", lambda: ShardedKv(2, 2, 1, guarded=True)),
    # Eventually counterexample (unreachable target -> terminal trace).
    ("chain eventually-violation", lambda: Chain(6, reach=9)),
    # Eventually discharged at the terminal.
    ("chain eventually-pass", lambda: Chain(6, reach=6)),
]


@pytest.mark.parametrize(
    "make", [m for _, m in ZOO], ids=[n for n, _ in ZOO]
)
def test_zoo_fused_bit_identical_to_staged(make):
    staged = _spawn(make(), wave_dedup="sort")
    fused = _spawn(make(), wave_kernel="fused")
    _assert_bit_identical(fused, staged)


def test_2pc_fused_bit_identical_to_staged(fused_2pc, staged_2pc):
    # Full passing sweep with always + sometimes + eventually properties
    # against the reference counts.
    _assert_bit_identical(fused_2pc, staged_2pc)
    assert fused_2pc.unique_state_count() == 288
    assert fused_2pc.state_count() == 1146
    assert fused_2pc.max_depth() == 11
    fused_2pc.assert_properties()


def test_fused_coverage_ledger_bit_identical(fused_2pc, staged_2pc):
    cov_s, cov_f = staged_2pc.coverage_report(), fused_2pc.coverage_report()
    assert cov_s is not None and cov_f is not None
    assert cov_f == cov_s


def test_fused_per_wave_path_matches_deep_drain(fused_2pc):
    # max_drain_waves=1 forces the per-wave host loop (the path bench
    # attribution prices); the fixture ran the deep device drain. Both
    # must agree (the coverage ledger rides the golden report).
    wave = _spawn(
        TwoPhaseSys(3), wave_kernel="fused", coverage=True,
        max_drain_waves=1,
    )
    _assert_bit_identical(wave, fused_2pc)


# -- preempt/resume composition ---------------------------------------------


def test_fused_preempt_resume_bit_identical():
    """A fused run suspended mid-space and resumed (still fused) must
    match the uninterrupted fused run exactly — the checkpoint payload
    carries no engine-specific state, so the megakernel composes with
    the service's suspend machinery rather than refusing it."""
    spawn = dict(wave_kernel="fused", aot_cache="t-fused-preempt")
    reference = _spawn(TwoPhaseSys(3), **spawn)
    assert reference.unique_state_count() == 288

    first = TwoPhaseSys(3).checker().spawn_tpu_bfs(
        max_drain_waves=2, **SPAWN, **spawn
    )
    deadline = time.monotonic() + 120.0
    while (
        first.unique_state_count() < 80
        and not first.is_done()
        and time.monotonic() < deadline
    ):
        time.sleep(0.002)
    first.request_preempt()
    for h in first.handles():
        h.join()
    assert first.worker_error() is None
    if not first.preempted:
        pytest.skip("run finished before the preempt request landed")
    assert first.unique_state_count() < 288

    resumed = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(resume_from=first.preempt_payload(), **SPAWN, **spawn)
        .join()
    )
    assert resumed.worker_error() is None
    _assert_bit_identical(resumed, reference)


# -- capacity ergonomics ----------------------------------------------------


def test_fused_rounds_table_capacity_with_note():
    # 3000 rows is not a tile-sweep shape; admission rounds up to the
    # next power of two >= TILE_ROWS and SAYS so (config_notes reach the
    # report via Reporter.report_config_notes). The staged XLA path
    # would refuse 3000 outright (power-of-two assert in the worker).
    checker = (
        Chain(6)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=64, table_capacity=3000,
            wave_kernel="fused",
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.config_notes
    assert any("rounded 3000 -> 4096" in n for n in checker.config_notes)
    assert "Note: table_capacity rounded 3000 -> 4096" in _golden(checker)
    assert checker.unique_state_count() == 7


def test_staged_valid_capacity_reports_no_note(staged_2pc):
    # The note fires only when admission actually adjusted something: a
    # staged run with an admissible capacity reports none.
    assert not staged_2pc.config_notes
    assert "Note:" not in _golden(staged_2pc)


# -- honest refusals + capability surfaces ----------------------------------


def test_fused_refuses_scatter_dedup():
    with pytest.raises(ValueError, match="scatter.*incompatible"):
        TwoPhaseSys(3).checker().spawn_tpu_bfs(
            **SPAWN, wave_kernel="fused", wave_dedup="scatter"
        )


def test_fused_refuses_symmetry():
    with pytest.raises(ValueError, match="symmetry"):
        TwoPhaseSys(3).checker().symmetry().spawn_tpu_bfs(
            **SPAWN, wave_kernel="fused"
        )


def test_fused_refuses_expand_fps():
    with pytest.raises(ValueError, match="expand_fps"):
        TwoPhaseSys(3).checker().spawn_tpu_bfs(
            **SPAWN, wave_kernel="fused", expand_fps=True
        )


def test_fused_refuses_device_liveness():
    with pytest.raises(ValueError, match="liveness='device'"):
        TwoPhaseSys(3).checker().spawn_tpu_bfs(
            **SPAWN, wave_kernel="fused", liveness="device"
        )


def test_invalid_wave_kernel_rejected():
    with pytest.raises(ValueError, match="wave_kernel"):
        TwoPhaseSys(3).checker().spawn_tpu_bfs(
            **SPAWN, wave_kernel="mega"
        )


def test_fused_declares_itself_unpackable(fused_2pc, staged_2pc):
    # The tenant-packed engine dispatches the staged wave only; a fused
    # job must say it runs solo (the PR 12 packable_reason convention)
    # instead of silently falling back.
    assert fused_2pc.packing_reason
    assert "fused" in fused_2pc.packing_reason
    assert staged_2pc.packing_reason is None


def test_service_classifies_fused_spawn_as_unpackable():
    # The service's admission classifier already rejects any spawn
    # override from packing; wave_kernel='fused' therefore time-slices
    # solo with an honest reason — never a silent downgrade to staged.
    from stateright_tpu.service.service import CheckService

    svc = CheckService.__new__(CheckService)
    svc.packing = True
    svc.spawn_method = "spawn_tpu_bfs"
    svc.default_spawn = {}
    packable, reason = svc._classify_packable(
        aot_namespace="2pc",
        options={},
        spawn={"wave_kernel": "fused"},
        hbm_budget_mib=None,
    )
    assert packable is False
    assert "wave_kernel" in reason


def test_fused_state_digest_records_engine(fused_2pc):
    assert fused_2pc.state_digest()["wave_kernel"] == "fused"
