"""Native fingerprint store: C++/Python implementations agree exactly."""

import numpy as np
import pytest

from stateright_tpu.native import (
    NativeFingerprintStore,
    PyFingerprintStore,
    make_fingerprint_store,
)


@pytest.fixture(params=["python", "native"])
def store(request):
    if request.param == "python":
        return PyFingerprintStore()
    try:
        return NativeFingerprintStore(64)
    except RuntimeError:
        pytest.skip("toolchain unavailable")


class TestFingerprintStore:
    def test_insert_first_writer_wins(self, store):
        c = np.array([10, 20, 10], np.uint64)
        p = np.array([0, 10, 99], np.uint64)
        assert store.insert_batch(c, p) == 2
        assert store.parent(10) is None  # first write (root) won
        assert store.parent(20) == 10

    def test_chain_walks_to_root(self, store):
        store.insert_batch(
            np.array([1, 2, 3], np.uint64), np.array([0, 1, 2], np.uint64)
        )
        assert store.chain(3) == [1, 2, 3]
        assert store.chain(1) == [1]
        with pytest.raises(KeyError):
            store.chain(42)

    def test_chain_with_dangling_parent_terminates(self, store):
        # Parent 1 was never inserted: the chain ends at it but includes it
        # (both implementations must agree).
        store.insert_batch(np.array([2], np.uint64), np.array([1], np.uint64))
        assert store.chain(2) == [1, 2]

    def test_membership_and_len(self, store):
        store.insert_batch(np.array([5], np.uint64), np.array([0], np.uint64))
        assert 5 in store and 6 not in store
        assert len(store) == 1

    def test_export_round_trips(self, store):
        c = np.array([7, 8, 9], np.uint64)
        p = np.array([0, 7, 7], np.uint64)
        store.insert_batch(c, p)
        ch, pa = store.export()
        pairs = dict(zip(ch.tolist(), pa.tolist()))
        assert pairs == {7: 0, 8: 7, 9: 7}


def test_native_store_builds_and_grows():
    try:
        s = NativeFingerprintStore(64)
    except RuntimeError:
        pytest.skip("toolchain unavailable")
    rng = np.random.default_rng(7)
    keys = rng.integers(1, 2**63, size=200_000, dtype=np.uint64)
    parents = np.zeros_like(keys)
    fresh = s.insert_batch(keys, parents)
    assert fresh == len(np.unique(keys))
    assert len(s) == fresh


def test_factory_prefers_native():
    store = make_fingerprint_store()
    # On this image the toolchain exists, so the native store must load.
    assert type(store).__name__ == "NativeFingerprintStore"


def test_device_checkers_use_store_for_paths():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=64)
        .join()
    )
    assert checker.worker_error() is None
    for path in checker.discoveries().values():
        assert len(path) >= 1
