"""Parity tests: sharded multi-device BFS == host checker, exact counts.

Runs on the virtual 8-device CPU mesh (see conftest). The oracle counts are
the reference's own (288 / 8,832 for 2pc — ``/root/reference/examples/2pc.rs:153-159``).
"""

import jax
import pytest

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.parallel import default_mesh


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    assert default_mesh().devices.size == 8


def test_sharded_2pc_3rms_matches_oracle():
    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(frontier_per_device=64, table_capacity_per_device=256)
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 288
    checker.assert_properties()


def test_sharded_2pc_5rms_matches_oracle():
    checker = (
        TwoPhaseSys(5)
        .checker()
        .spawn_sharded_tpu_bfs(frontier_per_device=256, table_capacity_per_device=512)
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 8832
    checker.assert_properties()


def test_sharded_matches_host_bfs_counts():
    host = TwoPhaseSys(4).checker().spawn_bfs().join()
    dev = TwoPhaseSys(4).checker().spawn_sharded_tpu_bfs(
        frontier_per_device=128, table_capacity_per_device=512
    ).join()
    assert dev.worker_error() is None
    assert dev.unique_state_count() == host.unique_state_count()


def test_sharded_discovery_paths_replay():
    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(frontier_per_device=64)
        .join()
    )
    assert checker.worker_error() is None
    paths = checker.discoveries()
    assert set(paths) == {"abort agreement", "commit agreement"}
    for path in paths.values():
        # Paths replay through the host model (nondeterminism discipline).
        assert len(path) >= 1


def test_sharded_target_max_depth():
    full = TwoPhaseSys(3).checker().spawn_bfs().join()
    capped = (
        TwoPhaseSys(3)
        .checker()
        .target_max_depth(3)
        .spawn_sharded_tpu_bfs(frontier_per_device=64)
        .join()
    )
    assert capped.worker_error() is None
    assert capped.max_depth() <= 3
    assert capped.unique_state_count() < full.unique_state_count()


@pytest.mark.slow
def test_sharded_eventually_counterexample_replays():
    # The Raft liveness oracle (tests/test_raft.py) on the sharded mesh:
    # "stable leader" is an eventually property whose counterexample is a
    # terminal leaderless schedule; the discovery fingerprint is picked on
    # one device and must replay through the host model from a sharded run.
    from stateright_tpu.models.raft import LEADER, RaftModelCfg

    checker = (
        RaftModelCfg(server_count=3, max_term=1, lossy=True)
        .into_model()
        .checker()
        .spawn_sharded_tpu_bfs(
            frontier_per_device=64, table_capacity_per_device=1 << 10
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 665
    paths = checker.discoveries()
    # Safety holds; reachability and the liveness counterexample are found.
    assert set(paths) == {"leader elected", "stable leader"}
    elected = paths["leader elected"].last_state()
    assert any(s.role == LEADER for s in elected.actor_states)
    stuck = paths["stable leader"].last_state()
    assert not any(s.role == LEADER for s in stuck.actor_states)


@pytest.mark.parametrize("n_dev", [1, 2, 4])
def test_sharded_submesh_sizes(n_dev):
    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(
            mesh=default_mesh(n_dev), frontier_per_device=64
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 288


@pytest.mark.slow
def test_sharded_deep_drain_tiny_rings_and_log():
    """Forces the deep drain through its host-exit machinery: a tiny log
    (many log-full exits), tiny rings (growth via export + re-push), and a
    small waves cap — the exact count must survive all of it."""
    checker = (
        TwoPhaseSys(5)
        .checker()
        .spawn_sharded_tpu_bfs(
            frontier_per_device=32,
            table_capacity_per_device=512,
            drain_log_factor=1,
            pool_factor=1,
            max_drain_waves=3,
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 8832
    checker.assert_properties()


def test_sharded_waves_mode_still_exact():
    """max_drain_waves=1 disables the deep drain; the wave-at-a-time path
    must produce the same oracle count."""
    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(
            frontier_per_device=64,
            table_capacity_per_device=256,
            max_drain_waves=1,
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 288


@pytest.mark.slow
def test_sharded_one_lane_frontier_grow_until_fits():
    """frontier_per_device=1 makes the round-robin receive quota
    (n*ceil(B/n)) comparable to the whole ring — the host push path must
    grow until the received rows provably fit instead of wrapping."""
    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(
            frontier_per_device=1,
            table_capacity_per_device=512,
            pool_factor=1,
            drain_log_factor=1,
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 288
