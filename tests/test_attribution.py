"""Wave-timeline attribution (stateright_tpu.telemetry.attribution):
fake-clock classifier units (phases sum to wall, compile/evict windows,
nesting rules), checker integration (bit-identical results + a coherent
ledger + the probe-length audit), the monitor's pipeline gauges, the
gap_report/trace_summary renderers, and the attribution-OFF overhead
budget."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry import metrics_registry
from stateright_tpu.telemetry.attribution import WaveAttribution
from stateright_tpu.telemetry.metrics import MetricsRegistry
from stateright_tpu.telemetry.trace import Tracer

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GAP_REPORT = os.path.join(REPO_DIR, "scripts", "gap_report.py")
TRACE_SUMMARY = os.path.join(REPO_DIR, "scripts", "trace_summary.py")


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _attr(**kwargs):
    clk = FakeClock()
    tracer = Tracer()
    attr = WaveAttribution(
        "t", clock=clk, tracer=tracer, registry=MetricsRegistry(), **kwargs
    )
    return attr, clk, tracer


# -- fake-clock classifier units -------------------------------------------


def test_phases_sum_to_wall_with_residual_gap():
    attr, clk, _ = _attr()
    with attr.wave():
        with attr.phase("device"):
            clk.advance(2.0)
        with attr.phase("host_probe"):
            clk.advance(1.0)
        clk.advance(0.5)  # unclassified host work -> gap
    rep = attr.report()
    assert rep["wall_s"] == pytest.approx(3.5)
    assert rep["phases_s"]["device"] == pytest.approx(2.0)
    assert rep["phases_s"]["host_probe"] == pytest.approx(1.0)
    assert rep["gap_s"] == pytest.approx(0.5)
    # The invariant: phases + gap == wall exactly (gap is the residual).
    assert sum(rep["phases_s"].values()) + rep["gap_s"] == pytest.approx(
        rep["wall_s"]
    )
    assert rep["within_tolerance"] and rep["overrun_s"] == 0.0
    assert rep["utilization"] == pytest.approx(2.0 / 3.5)


def test_compile_detection_and_evict_window_classified():
    attr, clk, _ = _attr()
    with attr.wave():
        with attr.phase("compile"):
            clk.advance(4.0)
        with attr.phase("device"):
            clk.advance(1.0)
        with attr.phase("evict"):
            clk.advance(2.0)
        with attr.phase("checkpoint"):
            clk.advance(0.5)
    rep = attr.report()
    assert rep["phases_s"]["compile"] == pytest.approx(4.0)
    assert rep["phases_s"]["evict"] == pytest.approx(2.0)
    # Overlap headroom: only the HOST phases (probe/evict/checkpoint)
    # can hide under device compute, capped by the device time there is
    # to hide them under — compile/table_grow are device-serial.
    oh = rep["overlap_headroom"]
    assert oh["host_overlappable_s"] == pytest.approx(2.5)
    assert oh["device_s"] == pytest.approx(1.0)
    assert oh["headroom_s"] == pytest.approx(1.0)
    assert oh["predicted_wall_s"] == pytest.approx(rep["wall_s"] - 1.0)


def test_nested_phase_records_nothing():
    attr, clk, _ = _attr()
    with attr.wave():
        with attr.phase("device"):
            with attr.phase("evict"):  # nested: ignored by design
                clk.advance(1.0)
            clk.advance(1.0)
    rep = attr.report()
    assert rep["phases_s"]["device"] == pytest.approx(2.0)
    assert "evict" not in rep["phases_s"]
    assert rep["gap_s"] == pytest.approx(0.0)


def test_phase_outside_wave_reported_separately():
    """Seed/restore-time phases (no wave window open) must NOT inflate
    the in-wave ledger — folding them into phases_s would break the
    phases-sum-to-wall invariant on every resumed run."""
    attr, clk, _ = _attr()
    with attr.phase("checkpoint"):  # e.g. a restore-time table rebuild
        clk.advance(3.0)
    with attr.wave():
        with attr.phase("device"):
            clk.advance(1.0)
    rep = attr.report()
    assert "checkpoint" not in rep["phases_s"]
    assert rep["outside_wave_s"]["checkpoint"] == pytest.approx(3.0)
    assert rep["wall_s"] == pytest.approx(1.0)
    assert sum(rep["phases_s"].values()) + rep["gap_s"] == pytest.approx(
        rep["wall_s"]
    )
    assert rep["within_tolerance"]


def test_wave_kind_drain_counts_drains_and_span_args():
    attr, clk, tracer = _attr()
    with attr.wave("drain"):
        with attr.phase("device"):
            clk.advance(1.5)
        clk.advance(0.5)
    rep = attr.report()
    assert rep["drains"] == 1 and rep["waves"] == 0
    (ev,) = [e for e in tracer.events() if e["name"] == "t.pipeline"]
    assert ev["args"]["kind"] == "drain"
    assert ev["args"]["wall_ms"] == pytest.approx(2000.0)
    assert ev["args"]["device_ms"] == pytest.approx(1500.0)
    assert ev["args"]["gap_ms"] == pytest.approx(500.0)


def test_observe_probe_lengths_feeds_histogram_and_ledger():
    attr, _, _ = _attr()
    attr.observe_probe_lengths([10, 5, 0, 1, 0, 0])
    rep = attr.report()
    assert rep["probe_length_counts"] == [10, 5, 0, 1]
    hist = attr._registry.histogram("t.hashset.probe_length").snapshot()
    assert hist["count"] == 16
    assert hist["max"] == 3


def test_probe_length_counts_match_resident_keys():
    import jax.numpy as jnp

    from stateright_tpu.ops.hashset import (
        hashset_insert_unsorted,
        hashset_new,
        hashset_probe_length_counts,
    )

    rng = np.random.default_rng(3)
    hi = jnp.asarray(rng.integers(1, 1 << 32, 500, dtype=np.uint32))
    lo = jnp.asarray(rng.integers(1, 1 << 32, 500, dtype=np.uint32))
    table, fresh, _found, pending = hashset_insert_unsorted(
        hashset_new(1 << 10), hi, lo, jnp.ones((500,), bool)
    )
    assert not bool(pending.any())
    counts = hashset_probe_length_counts(np.asarray(table))
    assert counts.sum() == int(fresh.sum())


# -- monitor surface --------------------------------------------------------


def test_monitor_pipeline_gauges_and_sse_event():
    from stateright_tpu.telemetry.server import MonitorCore

    reg = MetricsRegistry()
    tracer = Tracer()
    core = MonitorCore(registry=reg, tracer=tracer)
    try:
        q = core.broker.subscribe()
        core.write_event({
            "name": "tpu_bfs.pipeline", "ph": "X", "ts": 0.0, "dur": 4000.0,
            "pid": 1, "tid": 1,
            "args": {"kind": "wave", "wall_ms": 4.0, "device_ms": 3.0,
                     "host_probe_ms": 0.5, "gap_ms": 0.5},
        })
        assert reg.gauge("monitor.pipeline.utilization").snapshot() == (
            pytest.approx(0.75)
        )
        assert reg.gauge("monitor.pipeline.host_share").snapshot() == (
            pytest.approx(0.125)
        )
        kind, payload = q.get(timeout=2)
        assert kind == "pipeline"
        assert payload["phases_ms"]["device"] == pytest.approx(3.0)
        assert payload["utilization"] == pytest.approx(0.75)
    finally:
        core.close()


# -- checker integration ----------------------------------------------------


@pytest.fixture(scope="module")
def base_run():
    """Unattributed 2pc-4 on the wave path: the bit-identical oracle and
    the overhead budget's real-run denominator."""
    reg = metrics_registry()
    waves0 = reg.counter("tpu_bfs.waves").snapshot()
    t0 = time.perf_counter()
    checker = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 7,
            table_capacity=1 << 12,
            max_drain_waves=1,
        )
        .join()
    )
    secs = time.perf_counter() - t0
    waves = reg.counter("tpu_bfs.waves").snapshot() - waves0
    return checker, secs, waves


@pytest.fixture(scope="module")
def attributed_run():
    """Attribution-mode 2pc-4 on the default deep-drain path."""
    return (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 7,
            table_capacity=1 << 12,
            attribution=True,
        )
        .join()
    )


def test_attribution_results_bit_identical(base_run, attributed_run):
    base, _, _ = base_run
    assert attributed_run.unique_state_count() == base.unique_state_count()
    assert attributed_run.state_count() == base.state_count()
    assert attributed_run.max_depth() == base.max_depth()
    assert sorted(attributed_run.discoveries()) == sorted(
        base.discoveries()
    )


def test_attribution_ledger_sums_and_detects_compile(attributed_run):
    rep = attributed_run.attribution_report()
    assert rep is not None
    # The acceptance invariant: phases + gap == wall within tolerance
    # (gap is residual, so only an overrun can break it).
    assert rep["within_tolerance"], rep
    total = sum(rep["phases_s"].values()) + rep["gap_s"]
    assert total == pytest.approx(rep["wall_s"], rel=0.05)
    # Compile detection: the run's first drain/wave misses the AOT cache.
    assert rep["phases_s"].get("compile", 0) > 0
    assert rep["phases_s"].get("device", 0) > 0
    assert rep["waves"] + rep["drains"] >= 1
    # Overlap headroom is always non-null (zero host work => zero).
    oh = rep["overlap_headroom"]
    assert oh["predicted_wall_s"] is not None
    assert oh["predicted_wall_s"] <= rep["wall_s"]
    # Probe-length audit covers every resident key (no tier: L0 holds
    # the full visited set).
    assert sum(rep["probe_length_counts"]) == (
        attributed_run.unique_state_count()
    )


def test_attribution_report_none_when_disabled(base_run):
    base, _, _ = base_run
    assert base.attribution_report() is None


def test_sharded_attribution_ledger_and_identical_counts():
    checker = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(
            frontier_per_device=1 << 5,
            table_capacity_per_device=1 << 10,
            attribution=True,
        )
        .join()
    )
    assert checker.unique_state_count() == 288
    rep = checker.attribution_report()
    assert rep["within_tolerance"], rep
    assert rep["phases_s"].get("device", 0) > 0
    assert sum(rep["probe_length_counts"]) == 288


# -- attribution-off overhead budget ----------------------------------------


def test_attribution_off_overhead_under_budget(base_run):
    """With attribution disabled the checkers pay one shared-nullcontext
    enter/exit per hook site per wave. Same form as the telemetry/monitor
    budget tests: the measured per-wave disabled-path cost times a real
    run's wave count must stay under 5% of that run's wall (direct A/B
    of sub-second runs on this shared box swings more than the budget
    being asserted)."""
    from stateright_tpu.checker.tpu import _NULL_CTX

    _, run_secs, waves = base_run
    assert waves >= 1
    sites = 6  # wave window + device + probe + grow + checkpoint + evict
    n = 10_000
    t0 = time.perf_counter()
    for _ in range(n):
        for _ in range(sites):
            with _NULL_CTX:
                pass
    per_wave = (time.perf_counter() - t0) / n
    overhead = per_wave * waves
    assert overhead < 0.05 * run_secs, (
        f"attribution-off overhead too high: {waves} waves x "
        f"{per_wave * 1e6:.1f}us = {overhead * 1e3:.2f}ms on a "
        f"{run_secs * 1e3:.0f}ms run"
    )


# -- gap_report / trace_summary renderers -----------------------------------


def _pipeline_event(wall, device, probe, gap, name="tpu_bfs.pipeline"):
    return {
        "name": name, "ph": "X", "ts": 1.0, "dur": wall * 1e3,
        "args": {"kind": "wave", "wall_ms": wall, "device_ms": device,
                 "host_probe_ms": probe, "gap_ms": gap},
    }


def test_gap_report_ledger_and_nonnull_headroom(tmp_path):
    path = tmp_path / "attr.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps(_pipeline_event(10.0, 6.0, 3.0, 1.0)) + "\n")
        f.write(json.dumps(_pipeline_event(8.0, 5.0, 2.0, 1.0)) + "\n")
    r = subprocess.run(
        [sys.executable, GAP_REPORT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "phase ledger: tpu_bfs (2 waves" in r.stdout
    assert "overlap headroom: 5.0 ms" in r.stdout  # min(5 probe, 11 dev)
    assert "predicted wall under" in r.stdout

    r = subprocess.run(
        [sys.executable, GAP_REPORT, str(path), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    led = json.loads(r.stdout)["tpu_bfs"]
    assert led["overlap_headroom"]["headroom_ms"] == pytest.approx(5.0)
    assert led["overlap_headroom"]["predicted_wall_ms"] == pytest.approx(
        13.0
    )


def test_gap_report_exits_nonzero_without_attribution_spans(tmp_path):
    path = tmp_path / "plain.jsonl"
    path.write_text(
        json.dumps({"name": "tpu_bfs.wave", "ph": "X", "ts": 1.0,
                    "dur": 5.0, "args": {"new_unique": 3}}) + "\n"
    )
    r = subprocess.run(
        [sys.executable, GAP_REPORT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "attribution" in r.stderr


def test_trace_summary_attribution_table(tmp_path):
    path = tmp_path / "attr.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({
            "name": "tpu_bfs.wave", "ph": "X", "ts": 1.0, "dur": 5000.0,
            "args": {"frontier": 4, "generated": 8, "new_unique": 4,
                     "dedup_hit_rate": 0.5, "occupancy": 0.1,
                     "max_depth": 2},
        }) + "\n")
        f.write(json.dumps(_pipeline_event(10.0, 7.0, 2.0, 1.0)) + "\n")
    r = subprocess.run(
        [sys.executable, TRACE_SUMMARY, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "attribution (per-phase ms share of wave wall):" in r.stdout
    assert "tpu_bfs.pipeline" in r.stdout
    assert "device=7.0ms(70%)" in r.stdout
