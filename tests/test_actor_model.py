"""ActorModel tests — exact-count parity with the reference's test suite
(``/root/reference/src/actor/model.rs:660-1131``)."""

from actor_fixtures import Ping, PingPongCfg, Pong
from stateright_tpu import Expectation, StateRecorder
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    ActorModelState,
    DropAction,
    Envelope,
    Id,
    Network,
    Out,
    Timers,
)


def states_and_network(states, envelopes):
    return ActorModelState(
        actor_states=list(states),
        network=Network.new_unordered_duplicating(envelopes),
        timers_set=[Timers() for _ in states],
        crashed=[False] * len(states),
        history=(0, 0),
    )


def test_visits_expected_states():
    recorder = StateRecorder()
    checker = (
        PingPongCfg(maintains_history=False, max_nat=1)
        .into_model()
        .lossy_network(True)
        .checker()
        .visitor(recorder)
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 14
    state_space = recorder.states
    assert len(state_space) == 14

    e01 = lambda msg: Envelope(Id(0), Id(1), msg)
    e10 = lambda msg: Envelope(Id(1), Id(0), msg)
    expected = [
        # When the network loses no messages...
        states_and_network([0, 0], [e01(Ping(0))]),
        states_and_network([0, 1], [e01(Ping(0)), e10(Pong(0))]),
        states_and_network([1, 1], [e01(Ping(0)), e10(Pong(0)), e01(Ping(1))]),
        # When the network loses the message for state (0, 0)...
        states_and_network([0, 0], []),
        # When the network loses a message for state (0, 1)...
        states_and_network([0, 1], [e10(Pong(0))]),
        states_and_network([0, 1], [e01(Ping(0))]),
        states_and_network([0, 1], []),
        # When the network loses a message for state (1, 1)...
        states_and_network([1, 1], [e10(Pong(0)), e01(Ping(1))]),
        states_and_network([1, 1], [e01(Ping(0)), e01(Ping(1))]),
        states_and_network([1, 1], [e01(Ping(0)), e10(Pong(0))]),
        states_and_network([1, 1], [e01(Ping(1))]),
        states_and_network([1, 1], [e10(Pong(0))]),
        states_and_network([1, 1], [e01(Ping(0))]),
        states_and_network([1, 1], []),
    ]
    from stateright_tpu import fingerprint

    assert {fingerprint(s) for s in state_space} == {
        fingerprint(s) for s in expected
    }


def test_no_op_depends_on_network():
    IGNORED, INTERESTING = "Ignored", "Interesting"

    class Client(Actor):
        def __init__(self, server):
            self.server = server

        def on_start(self, id, o):
            o.send(self.server, IGNORED)
            o.send(self.server, INTERESTING)
            return "Awaiting an interesting message."

        def on_msg(self, id, state, src, msg, o):
            if msg == INTERESTING:
                return "Got an interesting message."
            return None

    class Server(Actor):
        def on_start(self, id, o):
            return "Awaiting an interesting message."

        def on_msg(self, id, state, src, msg, o):
            if msg == INTERESTING:
                return "Got an interesting message."
            return None

    def build(network):
        return (
            ActorModel()
            .actor(Client(server=Id(1)))
            .actor(Server())
            .lossy_network(False)
            .init_network(network)
            .property(Expectation.ALWAYS, "Check everything", lambda _m, _s: True)
        )

    # Unordered: ignored-message delivery is a pruned no-op.
    assert (
        build(Network.new_unordered_duplicating())
        .checker()
        .spawn_bfs()
        .join()
        .unique_state_count()
        == 2
    )
    assert (
        build(Network.new_unordered_nonduplicating())
        .checker()
        .spawn_bfs()
        .join()
        .unique_state_count()
        == 2
    )
    # Ordered: the no-op delivery still consumes the head of the FIFO flow.
    assert (
        build(Network.new_ordered())
        .checker()
        .spawn_bfs()
        .join()
        .unique_state_count()
        == 3
    )


def test_maintains_fixed_delta_despite_lossy_duplicating_network():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .lossy_network(True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4094
    checker.assert_no_discovery("delta within 1")


def test_may_never_reach_max_on_lossy_network():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .lossy_network(True)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 4094
    # Can lose the first message and get stuck, for example.
    checker.assert_discovery(
        "must reach max", [DropAction(Envelope(Id(0), Id(1), Ping(0)))]
    )


def test_eventually_reaches_max_on_perfect_delivery_network():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .init_network(Network.new_unordered_nonduplicating())
        .lossy_network(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_can_reach_max():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .lossy_network(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    assert checker.discovery("can reach max").last_state().actor_states == [4, 5]


def test_might_never_reach_beyond_max():
    checker = (
        PingPongCfg(maintains_history=False, max_nat=5)
        .into_model()
        .init_network(Network.new_unordered_nonduplicating())
        .lossy_network(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 11
    # A liveness property that fails to hold (due to the boundary).
    assert checker.discovery("must exceed max").last_state().actor_states == [5, 5]


def test_handles_undeliverable_messages():
    class NoopActor(Actor):
        def on_start(self, id, o):
            return ()

    checker = (
        ActorModel()
        .actor(NoopActor())
        .property(Expectation.ALWAYS, "unused", lambda _m, _s: True)
        .init_network(
            Network.new_unordered_duplicating(
                [Envelope(src=Id(0), dst=Id(99), msg="undeliverable")]
            )
        )
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.unique_state_count() == 1


def test_maintains_history():
    checker = (
        PingPongCfg(maintains_history=True, max_nat=1)
        .into_model()
        .lossy_network(False)
        .checker()
        .spawn_bfs()
        .join()
    )
    checker.assert_no_discovery("#in <= #out")
    checker.assert_no_discovery("#out <= #in + 1")


def test_crash_fingerprint_parity_quirk():
    # Parity quirk: `crashed` is deliberately excluded from state
    # hashing/equality (reference model_state.rs:86-97), so crashing an actor
    # with no set timers produces a state that dedups against its parent —
    # the crashed behavior is NOT explored separately and "must reach max"
    # stays unfalsified even with max_crashes(1).
    checker = (
        PingPongCfg(maintains_history=False, max_nat=1)
        .into_model()
        .lossy_network(False)
        .max_crashes(1)
        .checker()
        .spawn_bfs()
        .join()
    )
    assert checker.discovery("must reach max") is None
    # But the Crash actions were generated (state_count sees the duplicates).
    assert checker.state_count() > checker.unique_state_count()
