"""Child process for the cross-process warm-start tests.

Each invocation is one genuinely fresh process (cold jax, cold
in-memory AOT caches) serving a single 2pc-3 job against a shared
``service_dir``. The driver (``tests/test_warmstart.py``) runs it twice
with the same directory: the first child populates the disk AOT store
(``service_dir/aot/``), the second must serve its job compile-free off
it — the tentpole's "a fresh process serves its first job compile-free"
claim, exercised with a real process boundary rather than the
in-process ``clear_shared_aot_caches()`` emulation bench.py uses.

Usage: ``python warmstart_child.py <service_dir> [mode]``

Modes:
- ``aot`` (default) — a ``target_max_depth`` job (kept OUT of the seed
  plane by its target) on a ``packing=False`` service: isolates the
  disk-AOT executable plane from incremental re-checking.
- ``seed`` — a plain full-space job: first child saves a finished-run
  seed, second child's resubmission must reseed (zero explore waves).

The output is one ``WARMSTART-CHILD {json}`` line with the per-job
``aot_cache.*`` counters, the summed ``pipeline.compile_seconds``
phases, and the verdict — the driver gates on those.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

service_dir = sys.argv[1]
mode = sys.argv[2] if len(sys.argv) > 2 else "aot"

from stateright_tpu.service import CheckService  # noqa: E402
from stateright_tpu.telemetry import (  # noqa: E402
    metrics_registry,
    registry_hygiene_problems,
)

SPAWN = {
    "frontier_capacity": 16,
    "table_capacity": 1 << 12,
    "max_drain_waves": 2,
}

svc = CheckService(
    service_dir=service_dir,
    packing=False,
    quantum_s=60.0,
    default_spawn=dict(SPAWN),
)
# The depth target exceeds 2pc-3's true depth: the space is explored in
# full (verdicts are the real ones) while the target keeps the job out
# of the seed plane — the disk-AOT evidence stays uncontaminated.
options = {"target_max_depth": 64} if mode == "aot" else None
handle = svc.submit(
    model_name="2pc", model_args={"rm_count": 3}, options=options
)
result = handle.result(timeout=300.0)
status = handle.status()
snap = metrics_registry(handle.job_id).snapshot()
compile_phase_s = sum(
    v
    for k, v in snap.items()
    if k.endswith("pipeline.compile_seconds") and isinstance(v, (int, float))
)
waves = int(snap.get("tpu_bfs.waves", 0))
print(
    "WARMSTART-CHILD "
    + json.dumps(
        {
            "mode": mode,
            "unique": result["unique"],
            "properties_hold": result["properties_hold"],
            "aot": result.get("aot"),
            "warm_start": bool(status.get("warm_start")),
            "seeded_from": status.get("seeded_from"),
            "compile_phase_s": compile_phase_s,
            "waves": waves,
            # Metric-name lint over BOTH registries this process touched
            # (default carries warmstart.*/aot_cache.* service counters,
            # the job registry carries the per-tenant copies).
            "hygiene": (
                registry_hygiene_problems()
                + registry_hygiene_problems(metrics_registry(handle.job_id))
            ),
        }
    )
)
svc.close()
