"""Ordered-reliable-link and write-once-register adapter tests.

The ORL scenario mirrors the reference's test shape
(``/root/reference/src/actor/ordered_reliable_link.rs``): a sender pushes a
sequence over a lossy duplicating network; with the ORL wrapper the receiver
sees exactly-once in-order delivery on every schedule.
"""

from dataclasses import dataclass
from typing import Tuple

from stateright_tpu.actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
)
from stateright_tpu.actor.ordered_reliable_link import (
    ActorWrapper,
    NETWORK_TIMER,
    OrlState,
    ack_msg,
    deliver_msg,
)
from stateright_tpu.core.model import Expectation


@dataclass(frozen=True)
class SenderState:
    pass


class Sender(Actor):
    def __init__(self, dst: Id, values: Tuple[str, ...]):
        self.dst = dst
        self.values = values

    def on_start(self, id: Id, o: Out) -> SenderState:
        for v in self.values:
            o.send(self.dst, v)
        return SenderState()


@dataclass(frozen=True)
class ReceiverState:
    received: Tuple[str, ...]


class Receiver(Actor):
    def on_start(self, id: Id, o: Out) -> ReceiverState:
        return ReceiverState(received=())

    def on_msg(self, id: Id, state: ReceiverState, src: Id, msg, o: Out):
        return ReceiverState(received=state.received + (msg,))


def _orl_model():
    model = ActorModel(cfg=None, init_history=None)
    model.actor(ActorWrapper(Sender(Id(1), ("a", "b"))))
    model.actor(ActorWrapper(Receiver()))
    order = {"a": 0, "b": 1}

    def no_redelivery(_m, state):
        received = state.actor_states[1].wrapped_state.received
        return all(received.count(v) < 2 for v in ("a", "b"))

    def ordered(_m, state):
        # Non-decreasing, like the reference's "ordered" property: a later
        # message may overtake (and thereby permanently skip) a dropped
        # earlier one, but delivery never reorders.
        received = state.actor_states[1].wrapped_state.received
        indices = [order[v] for v in received]
        return indices == sorted(indices)

    def all_delivered(_m, state):
        return state.actor_states[1].wrapped_state.received == ("a", "b")

    return (
        model.init_network(Network.new_unordered_duplicating())
        .lossy_network(True)
        .within_boundary_fn(lambda _cfg, state: len(state.network) < 4)
        .property(Expectation.ALWAYS, "no redelivery", no_redelivery)
        .property(Expectation.ALWAYS, "ordered", ordered)
        .property(Expectation.SOMETIMES, "all delivered", all_delivered)
    )


class TestOrderedReliableLink:
    def test_exactly_once_in_order_under_loss_and_duplication(self):
        checker = _orl_model().checker().spawn_bfs().join()
        assert "no redelivery" not in checker.discoveries()
        assert "ordered" not in checker.discoveries()
        assert "all delivered" in checker.discoveries()
        assert checker.unique_state_count() > 0

    def test_on_start_wraps_sends_with_sequencers(self):
        o = Out()
        state = ActorWrapper(Sender(Id(1), ("a", "b"))).on_start(Id(0), o)
        assert state.next_send_seq == 3
        assert state.msgs_pending_ack == ((1, Id(1), "a"), (2, Id(1), "b"))
        kinds = [c.kind for c in o]
        assert kinds == ["SetTimer", "Send", "Send"]

    def test_duplicate_deliver_is_acked_but_dropped(self):
        wrapper = ActorWrapper(Receiver())
        o = Out()
        state = wrapper.on_start(Id(1), o)
        o = Out()
        state2 = wrapper.on_msg(Id(1), state, Id(0), deliver_msg(1, "a"), o)
        assert state2.wrapped_state.received == ("a",)
        o = Out()
        again = wrapper.on_msg(Id(1), state2, Id(0), deliver_msg(1, "a"), o)
        assert again is None  # dropped…
        assert [c.kind for c in o] == ["Send"]  # …but still acked

    def test_ack_clears_pending(self):
        wrapper = ActorWrapper(Sender(Id(1), ("a",)))
        state = wrapper.on_start(Id(0), Out())
        o = Out()
        next_state = wrapper.on_msg(Id(0), state, Id(1), ack_msg(1), o)
        assert next_state.msgs_pending_ack == ()
        # Second identical ack is a no-op.
        assert wrapper.on_msg(Id(0), next_state, Id(1), ack_msg(1), Out()) is None

    def test_network_timer_resends_pending(self):
        wrapper = ActorWrapper(Sender(Id(1), ("a", "b")))
        state = wrapper.on_start(Id(0), Out())
        o = Out()
        assert wrapper.on_timeout(Id(0), state, NETWORK_TIMER, o) is None
        sends = [c for c in o if c.kind == "Send"]
        assert [c.args for c in sends] == [
            (Id(1), deliver_msg(1, "a")),
            (Id(1), deliver_msg(2, "b")),
        ]


class TestWORegister:
    def test_client_round_trip_with_write_once_server(self):
        from stateright_tpu.actor.write_once_register import (
            Get,
            GetOk,
            Put,
            PutFail,
            PutOk,
            WORegisterClient,
            record_invocations,
            record_returns,
        )
        from stateright_tpu.semantics import LinearizabilityTester
        from stateright_tpu.semantics.write_once_register import WORegister

        @dataclass(frozen=True)
        class ServerState:
            value: object

        class WOServer(Actor):
            def on_start(self, id: Id, o: Out) -> ServerState:
                return ServerState(value=None)

            def on_msg(self, id: Id, state: ServerState, src: Id, msg, o: Out):
                if isinstance(msg, Put):
                    if state.value is None:
                        o.send(src, PutOk(msg.request_id))
                        return ServerState(value=msg.value)
                    if state.value == msg.value:
                        o.send(src, PutOk(msg.request_id))
                        return None
                    o.send(src, PutFail(msg.request_id))
                    return None
                if isinstance(msg, Get):
                    o.send(src, GetOk(msg.request_id, state.value))
                    return None
                return None

        model = ActorModel(
            cfg=None, init_history=LinearizabilityTester(WORegister())
        )
        model.actor(WOServer())
        model.actor(WORegisterClient(put_count=1, server_count=1))
        model.actor(WORegisterClient(put_count=1, server_count=1))
        checker = (
            model.init_network(Network.new_unordered_nonduplicating())
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _, state: state.history.serialized_history() is not None,
            )
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
            .checker()
            .spawn_bfs()
            .join()
        )
        checker.assert_properties()
        assert checker.unique_state_count() > 0
