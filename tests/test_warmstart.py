"""Warm-start serving (ISSUE 19): the persistent disk AOT store, warm
pools, and incremental re-checking via finished-run seeds.

The planes under test share one discipline — refuse, never mis-execute:
a stale or torn artifact is counted and treated as a miss (the run goes
cold), it is never deserialized-and-hoped. The cross-process half runs
``tests/warmstart_child.py`` in real subprocesses (cold jax, cold
in-memory caches) against a shared ``service_dir``; everything else
exercises the service API in-process.
"""

import io
import json
import os
import pickle
import re
import subprocess
import sys
import time

import jax.numpy as jnp
import pytest
from jax import lax

from stateright_tpu import WriteReporter
from stateright_tpu.core.batch import BatchableModel
from stateright_tpu.core.model import Model, Property
from stateright_tpu.service import CheckService
from stateright_tpu.storage.persist import (
    AotDiskStore,
    aot_fence,
)
from stateright_tpu.telemetry import (
    metrics_registry,
    registry_hygiene_problems,
)
from stateright_tpu.utils.faults import FaultSpec, inject

# The suite's shared cheap-2pc shapes (tests/test_service.py): one AOT
# namespace for the module, so in-memory cache hits keep repeats cheap.
SPAWN_WS = {
    "frontier_capacity": 16,
    "table_capacity": 1 << 12,
    "max_drain_waves": 2,
    "aot_cache": "t-ws",
}
UNIQUE_2PC3 = 288
UNIQUE_2PC4 = 1568


def _golden(checker_or_text):
    """Report text normalized for golden comparison: timing scrubbed and
    the warm-start config-note lines dropped (a seeded run must match
    its cold twin everywhere EXCEPT the note naming the seed)."""
    text = checker_or_text
    if not isinstance(text, str):
        out = io.StringIO()
        text.report(WriteReporter(out))
        text = out.getvalue()
    text = re.sub(r"sec=\d+", "sec=_", text)
    return "".join(
        line
        for line in text.splitlines(keepends=True)
        if "warm-start:" not in line
    )


def _service(tmp_path, **kw):
    kw.setdefault("quantum_s", 60.0)
    kw.setdefault("default_spawn", dict(SPAWN_WS))
    return CheckService(service_dir=str(tmp_path), **kw)


# ---------------------------------------------------------------------------
# Disk AOT store: fences and corruption (unit level)
# ---------------------------------------------------------------------------


def test_aot_store_refuses_stale_fence_and_corrupt_entries(tmp_path):
    """A serialized executable round-trips through the store; a forged
    jax-version/backend fence refuses as stale, a torn blob as corrupt —
    both land as misses (recompile), never as an executed artifact."""
    import jax

    store = AotDiskStore(str(tmp_path / "aot"))
    exe = jax.jit(lambda x: x * 2).lower(jnp.int32(3)).compile()
    assert store.save_entry("ns", ("sig",), "wave", (1, 2), exe)

    loaded, outcome = store.load_entry("ns", ("sig",), "wave", (1, 2))
    assert outcome == "hit"
    assert int(loaded(jnp.int32(21))) == 42
    assert store.load_entry("ns", ("sig",), "wave", (9, 9))[1] == "miss"

    path = store.entry_path("ns", ("sig",), "wave", (1, 2))
    with open(path, "rb") as f:
        entry = pickle.loads(f.read())
    assert entry["fence"] == aot_fence()

    # Forge the fence: same file, wrong jax version — refused stale.
    entry["fence"] = dict(entry["fence"], jax_version="0.0.0-forged")
    with open(path, "wb") as f:
        f.write(pickle.dumps(entry))
    assert store.load_entry("ns", ("sig",), "wave", (1, 2)) == (None, "stale")

    # Tear the artifact: an unpicklable half-blob — refused corrupt.
    with open(path, "wb") as f:
        f.write(b"\x80\x04torn")
    assert store.load_entry("ns", ("sig",), "wave", (1, 2)) == (None, "corrupt")

    # The binding counts each outcome into its registry.
    reg = metrics_registry("t-ws-fence-unit")
    binding = store.binding("ns", ("sig",), registry=reg)
    assert binding.load("wave", (1, 2)) is None  # corrupt
    assert binding.load("wave", (9, 9)) is None  # miss
    binding.save("wave", (3, 4), exe)
    snap = reg.snapshot()
    assert snap["aot_cache.refused_corrupt"] == 1
    assert snap["aot_cache.disk_miss"] == 1
    assert snap["aot_cache.saved"] == 1
    assert not [
        p
        for p in registry_hygiene_problems(reg)
        if "aot_cache" in p
    ]


# ---------------------------------------------------------------------------
# Cross-process disk AOT round-trip
# ---------------------------------------------------------------------------


def _run_child(service_dir, mode):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(os.path.dirname(__file__), "warmstart_child.py"),
            str(service_dir),
            mode,
        ],
        capture_output=True,
        text=True,
        timeout=540,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    for line in proc.stdout.splitlines():
        if line.startswith("WARMSTART-CHILD "):
            return json.loads(line[len("WARMSTART-CHILD "):])
    raise AssertionError(f"no child record in output: {proc.stdout[-500:]}")


def test_disk_aot_roundtrip_across_processes(tmp_path):
    """Two genuinely separate processes share one ``service_dir``: the
    first compiles and persists (disk misses + saves), the second serves
    the same job off the disk store — disk hits, zero disk misses, and
    zero recorded compile phases. The tentpole's cold-process claim with
    a real process boundary."""
    cold = _run_child(tmp_path, "aot")
    assert cold["properties_hold"] is True
    assert cold["aot"] is not None, "disk store never attached"
    assert cold["aot"]["aot_cache.disk_miss"] >= 1
    assert cold["aot"]["aot_cache.saved"] >= 1
    assert cold["aot"]["aot_cache.disk_hit"] == 0

    warm = _run_child(tmp_path, "aot")
    assert warm["unique"] == cold["unique"]
    assert warm["properties_hold"] is True
    assert warm["aot"]["aot_cache.disk_hit"] >= 1
    assert warm["aot"]["aot_cache.disk_miss"] == 0
    assert warm["aot"]["aot_cache.refused_stale"] == 0
    # The acceptance criterion: a disk-cache-hit job records NO compile
    # phases (the attribution detectors never saw a fresh compile).
    assert warm["compile_phase_s"] == 0


# ---------------------------------------------------------------------------
# Warm pool
# ---------------------------------------------------------------------------


def test_warm_pool_precompiles_to_ready(tmp_path):
    """``warm_pool=`` pre-compiles the registered shapes on a background
    thread at service start; per-shape readiness is surfaced in
    ``status()`` and the pool gauges, and the pool's own jobs stay out
    of the SLO ledger."""
    svc = _service(tmp_path, warm_pool=[("2pc", {"rm_count": 3})])
    try:
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            states = {e["state"] for e in svc.warm_pool_status.values()}
            if states and "pending" not in states:
                break
            time.sleep(0.2)
        assert len(svc.warm_pool_status) == 1
        (entry,) = svc.warm_pool_status.values()
        assert entry["state"] == "ready", entry
        st = svc.status()
        assert st["warm_start"]["enabled"] is True
        (pool_entry,) = st["warm_start"]["pool"].values()
        assert pool_entry["state"] == "ready"
        # Warm jobs are not served verdicts: the SLO ledger stays empty.
        assert all(
            v["jobs"] == 0 for v in svc.slo.snapshot()["modes"].values()
        )
        # The new metric families pass the registry lint.
        assert not [
            p
            for p in registry_hygiene_problems()
            if "warmstart" in p or "aot_cache" in p or "slo" in p
        ]
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# Incremental re-checking: seeds
# ---------------------------------------------------------------------------


def test_unchanged_model_reseed_completes_in_verify_only(tmp_path):
    """A finished full run leaves a seed; resubmitting the unchanged
    model on a fresh service restores it — zero explore waves, the exact
    counts, a bit-identical verdict, and a golden report that matches
    the cold run everywhere except the warm-start note naming the
    seed."""
    svc1 = _service(tmp_path)
    try:
        h1 = svc1.submit(model_name="2pc", model_args={"rm_count": 3})
        r1 = h1.result(timeout=300.0)
        st1 = h1.status()
    finally:
        svc1.close()
    assert not st1.get("warm_start")
    assert r1["unique"] == UNIQUE_2PC3
    seeds = os.listdir(tmp_path / "seeds")
    assert len(seeds) == 1 and seeds[0].endswith(".seed")
    # Disk-AOT persistence itself is gated by the cross-process test
    # above — not re-asserted here because executables that were served
    # from jax's persistent compilation cache (warm on a developer box,
    # enabled by conftest) don't round-trip through
    # serialize_executable and are honestly refused at save time.
    aot = metrics_registry(h1.job_id).snapshot()
    assert (
        aot.get("aot_cache.saved", 0) + aot.get("aot_cache.save_refused", 0)
        > 0
    ), "disk AOT store never attempted a save"

    svc2 = _service(tmp_path)
    try:
        h2 = svc2.submit(model_name="2pc", model_args={"rm_count": 3})
        r2 = h2.result(timeout=300.0)
        st2 = h2.status()
    finally:
        svc2.close()
    assert st2["warm_start"] is True
    assert st2["seeded_from"]["mode"] == "exact"
    assert st2["seeded_from"]["keys"] == UNIQUE_2PC3
    assert st2["seeded_from"]["invalidated_uniques"] == 0
    assert r2["warm_start"] is True
    # O(verify): the seeded run explored nothing.
    assert metrics_registry(h2.job_id).snapshot().get("tpu_bfs.waves", 0) == 0
    # Bit-identical verdict + golden report (modulo the honest note).
    assert (r2["unique"], r2["states"], r2["max_depth"]) == (
        r1["unique"], r1["states"], r1["max_depth"],
    )
    assert r2["properties_hold"] == r1["properties_hold"]
    assert _golden(r2["report"]) == _golden(r1["report"])
    # The honest capability surfacing: the seeded report names its seed.
    assert "warm-start: seeded from persisted run" in r2["report"]
    assert "warm-start" not in r1["report"]


class SwitchBits(Model, BatchableModel):
    """K set-bit actions dispatched through ``lax.switch`` on the raw
    action id, plus an optional provably-dead action (guard never true).
    ``edit_live`` rewrites one live guard into a semantically identical
    but structurally different form — the not-provably-safe edit class."""

    def __init__(self, nbits=3, dead=True, edit_live=False):
        self.nbits = int(nbits)
        self.dead = bool(dead)
        self.edit_live = bool(edit_live)

    def packed_action_count(self):
        return self.nbits + (1 if self.dead else 0)

    def packed_init_states(self):
        return {"bits": jnp.zeros((1, self.nbits), jnp.uint32)}

    def packed_step(self, state, action_id):
        branches = []
        for i in range(self.nbits):
            def set_bit(st, _i=i):
                b = st["bits"]
                if _i == 0 and self.edit_live:
                    valid = b[_i] < jnp.uint32(1)
                else:
                    valid = b[_i] == jnp.uint32(0)
                return {"bits": b.at[_i].set(jnp.uint32(1))}, valid

            branches.append(set_bit)
        if self.dead:
            def dead_action(st):
                return {"bits": st["bits"]}, st["bits"][0] > jnp.uint32(1)

            branches.append(dead_action)
        return lax.switch(action_id, branches, state)

    def properties(self):
        return [Property.always("ok", lambda m, s: True)]

    def packed_conditions(self):
        return [lambda st: jnp.bool_(True)]


def _run_switch(svc, **model_kw):
    h = svc.submit(
        model=SwitchBits(**model_kw), spawn={"coverage": True}
    )
    r = h.result(timeout=300.0)
    return r, h.status()


def test_dead_action_removal_seeds_live_edit_falls_back(tmp_path):
    """The one admitted edit class: removing an action whose coverage
    proves it never fired reseeds (per-action jaxpr digests license it);
    editing a LIVE action — even semantics-preservingly — is not
    provable and falls back to an honest full recheck, whose verdict
    still agrees."""
    svc = _service(tmp_path)
    try:
        r1, st1 = _run_switch(svc, nbits=3, dead=True)
        assert not st1.get("warm_start")
        assert r1["unique"] == 8
        cov = r1["coverage"]["actions"]["table"]
        assert cov["action_3"]["fired"] == 0, "the dead action fired?"

        # Dead-action removal: provably dead => seeded, exact counts.
        r2, st2 = _run_switch(svc, nbits=3, dead=False)
        assert st2["warm_start"] is True
        assert st2["seeded_from"]["mode"] == "dead_action_removal"
        assert st2["seeded_from"]["invalidated_uniques"] == 0
        assert (r2["unique"], r2["states"]) == (r1["unique"], r1["states"])
        assert r2["properties_hold"] is True

        # Live-action edit: conservative fallback, full recheck, same
        # verdict (the edit was semantics-preserving).
        r3, st3 = _run_switch(svc, nbits=3, dead=True, edit_live=True)
        assert not st3.get("warm_start")
        assert "not a pure removal" in st3["warm_start_reason"]
        assert (r3["unique"], r3["states"]) == (r1["unique"], r1["states"])
        assert r3["properties_hold"] is True
    finally:
        svc.close()


def test_corrupt_or_faulted_seed_falls_back_to_full_recheck(tmp_path):
    """A torn seed artifact, or a disk fault at the ``warmstart.
    seed_load`` injection seam, refuses the seed (counted) and the run
    completes cold with the correct verdict — seeds are an optimization,
    never a soundness dependency."""
    svc1 = _service(tmp_path)
    try:
        h1 = svc1.submit(model_name="2pc", model_args={"rm_count": 3})
        r1 = h1.result(timeout=300.0)
    finally:
        svc1.close()
    assert r1["unique"] == UNIQUE_2PC3
    (seed_name,) = os.listdir(tmp_path / "seeds")
    seed_path = tmp_path / "seeds" / seed_name

    def refused_run(svc):
        before = metrics_registry().snapshot().get("warmstart.seed_refused", 0)
        h = svc.submit(model_name="2pc", model_args={"rm_count": 3})
        r = h.result(timeout=300.0)
        st = h.status()
        after = metrics_registry().snapshot().get("warmstart.seed_refused", 0)
        assert not st.get("warm_start")
        assert st["warm_start_reason"]
        assert after == before + 1
        assert r["unique"] == r1["unique"]
        assert r["properties_hold"] == r1["properties_hold"]
        return st

    # Torn artifact: truncate the pickle mid-blob.
    blob = seed_path.read_bytes()
    seed_path.write_bytes(blob[: len(blob) // 2])
    svc2 = _service(tmp_path)
    try:
        st = refused_run(svc2)
        assert "seed artifact refused" in st["warm_start_reason"]
    finally:
        svc2.close()

    # Restore the artifact; fail the *read* instead via the fault seam.
    seed_path.write_bytes(blob)
    svc3 = _service(tmp_path)
    try:
        with inject(FaultSpec("warmstart.seed_load")):
            st = refused_run(svc3)
        assert "SeedLoadFault" in st["warm_start_reason"]
    finally:
        svc3.close()


@pytest.mark.slow
def test_preempted_run_still_seeds_bit_identical(tmp_path):
    """Preempt/resume composes with the seed plane: a job served across
    multiple slices (real contention, short quantum) still persists a
    valid seed at completion, and the reseeded resubmit is bit-identical
    with zero explore waves.

    Slow-marked (two contended 2pc-4 jobs at a 0.75s quantum take ~2
    minutes on a busy CPU box); the tier-1 workflow runs it explicitly
    in the warm-start step with ``-m 'slow or not slow'``."""
    svc1 = _service(tmp_path, quantum_s=0.75)
    try:
        h1 = svc1.submit(model_name="2pc", model_args={"rm_count": 4})
        h2 = svc1.submit(model_name="2pc", model_args={"rm_count": 4})
        r1 = h1.result(timeout=300.0)
        r2 = h2.result(timeout=300.0)
        assert r1["unique"] == UNIQUE_2PC4
        assert r2["unique"] == UNIQUE_2PC4
        assert h1.status()["preempts"] + h2.status()["preempts"] >= 1
    finally:
        svc1.close()

    svc2 = _service(tmp_path)
    try:
        h3 = svc2.submit(model_name="2pc", model_args={"rm_count": 4})
        r3 = h3.result(timeout=300.0)
        st3 = h3.status()
    finally:
        svc2.close()
    assert st3["warm_start"] is True
    assert st3["seeded_from"]["keys"] == UNIQUE_2PC4
    assert metrics_registry(h3.job_id).snapshot().get("tpu_bfs.waves", 0) == 0
    assert (r3["unique"], r3["states"], r3["max_depth"]) == (
        r1["unique"], r1["states"], r1["max_depth"],
    )
    assert _golden(r3["report"]) == _golden(r1["report"])
