"""TPU backend tests: device ops units + host/TPU exact-count parity.

Parity strategy per SURVEY §4: the host checkers are the oracle; the TPU
backend must reproduce their unique/total counts, depths, and discoveries
on the reference workloads (2pc: 288 / 8,832) and on semantics fixtures
(eventually bits, boundary, depth caps).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stateright_tpu.core.batch import BatchableModel
from stateright_tpu.core.model import Model, Property
from stateright_tpu.core.visitor import PathRecorder
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.ops.fingerprint import fingerprint_state, fp_to_int
from stateright_tpu.ops.hashset import hashset_contains, hashset_insert, hashset_new


class Chain(Model, BatchableModel):
    """0 -> 1 -> ... -> n (terminal); the liveness-semantics fixture.

    ``reach`` sets the eventually target; a target > n is unreachable and
    must produce a counterexample path ending at the terminal state.
    """

    def __init__(self, n, reach=None, bound=None):
        self.n = n
        self.reach = reach
        self.bound = bound

    # host side
    def init_states(self):
        return [0]

    def actions(self, state, actions):
        if state < self.n:
            actions.append("inc")

    def next_state(self, state, action):
        return state + 1

    def within_boundary(self, state):
        return self.bound is None or state <= self.bound

    def properties(self):
        props = []
        if self.reach is not None:
            props.append(
                Property.eventually("reach", lambda _m, s: s == self.reach)
            )
        props.append(Property.always("small", lambda _m, s: s <= self.n))
        return props

    # packed side
    def packed_action_count(self):
        return 1

    def packed_init_states(self):
        return jnp.zeros((1,), jnp.uint32)

    def packed_step(self, state, action_id):
        return state + 1, state < self.n

    def packed_within_boundary(self, state):
        if self.bound is None:
            return jnp.bool_(True)
        return state <= self.bound

    def packed_conditions(self):
        conds = []
        if self.reach is not None:
            conds.append(lambda s: s == self.reach)
        conds.append(lambda s: s <= self.n)
        return conds

    def pack_state(self, host_state):
        return np.uint32(host_state)

    def unpack_state(self, packed):
        return int(packed)


def assert_parity(model, **tpu_kwargs):
    tpu = model.checker().spawn_tpu_bfs(**tpu_kwargs).join()
    host = model.checker().spawn_bfs().join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert tpu.state_count() == host.state_count()
    assert tpu.max_depth() == host.max_depth()
    assert set(tpu.discoveries()) == set(host.discoveries())
    return tpu, host


# -- device op units -------------------------------------------------------


def test_fingerprint_deterministic_and_distinct():
    a = {"x": jnp.uint32(1), "y": jnp.arange(4, dtype=jnp.uint32)}
    b = {"x": jnp.uint32(2), "y": jnp.arange(4, dtype=jnp.uint32)}
    fa1 = fp_to_int(*fingerprint_state(a))
    fa2 = fp_to_int(*fingerprint_state(a))
    fb = fp_to_int(*fingerprint_state(b))
    assert fa1 == fa2
    assert fa1 != fb
    assert fa1 != 0


def test_fingerprint_no_collisions_small_space():
    # All 2^16 2-word states must hash distinctly (birthday bound @64-bit).
    import jax

    xs, ys = jnp.meshgrid(
        jnp.arange(256, dtype=jnp.uint32), jnp.arange(256, dtype=jnp.uint32)
    )
    states = jnp.stack([xs.ravel(), ys.ravel()], axis=-1)
    hi, lo = jax.vmap(fingerprint_state)(states)
    combined = np.asarray(hi).astype(np.uint64) << np.uint64(32) | np.asarray(
        lo
    ).astype(np.uint64)
    assert len(np.unique(combined)) == 65536


def test_hashset_insert_and_membership():
    table = hashset_new(256)
    hi = jnp.arange(1, 101, dtype=jnp.uint32)
    lo = hi * jnp.uint32(7)
    active = jnp.ones((100,), bool)
    table, fresh, found, overflow = hashset_insert(table, hi, lo, active)
    assert int(fresh.sum()) == 100
    assert int(found.sum()) == 0
    assert int(overflow.sum()) == 0
    # Re-insert: everything already present.
    table, fresh2, found2, overflow2 = hashset_insert(table, hi, lo, active)
    assert int(fresh2.sum()) == 0
    assert int(found2.sum()) == 100
    assert bool(hashset_contains(table, hi[:5], lo[:5]).all())
    absent = hashset_contains(table, hi + jnp.uint32(1000), lo)
    assert not bool(absent.any())


def test_hashset_duplicate_probe_collisions():
    # Many keys landing on the same probe chain still all insert.
    table = hashset_new(128)
    n = 64
    lo = jnp.full((n,), 5, jnp.uint32)  # identical probe base ingredient
    hi = jnp.arange(1, n + 1, dtype=jnp.uint32)
    table, fresh, _found, overflow = hashset_insert(
        table, hi, lo, jnp.ones((n,), bool)
    )
    assert int(fresh.sum()) == n
    assert int(overflow.sum()) == 0


# -- parity on the reference workload --------------------------------------


def test_2pc_3rm_parity():
    tpu, _host = assert_parity(
        TwoPhaseSys(3), frontier_capacity=256, table_capacity=1024
    )
    assert tpu.unique_state_count() == 288
    tpu.assert_properties()
    tpu.assert_discovery(
        "abort agreement",
        [("TmAbort",)] + [("RmRcvAbortMsg", i) for i in range(3)],
    )


@pytest.mark.slow
def test_2pc_5rm_parity():
    tpu, _host = assert_parity(
        TwoPhaseSys(5), frontier_capacity=1024, table_capacity=16384
    )
    assert tpu.unique_state_count() == 8832


def test_table_growth_mid_run():
    # Tiny initial table forces repeated grow+rehash during the check.
    tpu = (
        TwoPhaseSys(3)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=64, table_capacity=64)
        .join()
    )
    assert tpu.unique_state_count() == 288


# -- semantics fixtures ----------------------------------------------------


def test_eventually_satisfied_no_counterexample():
    model = Chain(5, reach=5)
    tpu, _ = assert_parity(model)
    assert tpu.discoveries() == {}


def test_eventually_counterexample_at_terminal():
    model = Chain(5, reach=7)  # unreachable
    tpu, host = assert_parity(model)
    path = tpu.assert_any_discovery("reach")
    assert path.into_states() == [0, 1, 2, 3, 4, 5]
    assert host.assert_any_discovery("reach").into_states() == path.into_states()


def test_target_max_depth_parity():
    model = Chain(10)
    tpu = model.checker().target_max_depth(3).spawn_tpu_bfs().join()
    host = model.checker().target_max_depth(3).spawn_bfs().join()
    assert tpu.unique_state_count() == host.unique_state_count() == 3
    assert tpu.max_depth() == host.max_depth() == 3


def test_within_boundary_parity():
    model = Chain(10, bound=4)
    tpu, host = assert_parity(model)
    assert tpu.unique_state_count() == 5  # 0..4


def test_visitor_paths_match_host():
    model = Chain(4)
    tpu_rec, host_rec = PathRecorder(), PathRecorder()
    model.checker().visitor(tpu_rec).spawn_tpu_bfs().join()
    model.checker().visitor(host_rec).spawn_bfs().join()
    assert tpu_rec.paths == host_rec.paths
    assert len(tpu_rec.paths) == 5


def test_unbatchable_model_rejected():
    from stateright_tpu.core.model import FnModel

    model = FnModel(lambda s, out: out.append(0) if s is None else None)
    with pytest.raises(TypeError, match="BatchableModel"):
        model.checker().spawn_tpu_bfs()


@pytest.mark.slow
def test_deep_drain_tiny_ring_and_log_exact():
    """Forces the deep drain's stress machinery — ring growth
    (export + re-push), log-full drain exits, and host-queue spill
    re-ingest — on a tiny ring/log; the exact oracle count must survive."""
    checker = (
        TwoPhaseSys(5)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=32,
            table_capacity=1 << 12,
            drain_log_factor=1,
            pool_factor=1,
            max_drain_waves=3,
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 8832
    checker.assert_properties()


def test_fingerprint_chunked_wide_words_path():
    """The n > 64 (chunk-parallel) fingerprint branch: deterministic,
    sensitive to every word position, and collision-free on random
    wide-state word vectors."""
    from stateright_tpu.ops.fingerprint import fingerprint_words

    rng = np.random.default_rng(11)
    words = jnp.asarray(
        rng.integers(0, 1 << 32, size=100, dtype=np.uint64).astype(np.uint32)
    )
    fp = jax.jit(fingerprint_words)
    base = tuple(int(x) for x in fp(words))
    assert base == tuple(int(x) for x in fp(words))  # deterministic
    for i in range(100):  # every position is live
        flipped = words.at[i].set(words[i] ^ jnp.uint32(1))
        assert tuple(int(x) for x in fp(flipped)) != base, i
    # Length sensitivity (zero-padding must not alias n with n+1).
    longer = jnp.concatenate([words, jnp.zeros((1,), jnp.uint32)])
    assert tuple(int(x) for x in fp(longer)) != base
    # Uniqueness over a batch of random wide vectors.
    batch = jnp.asarray(
        rng.integers(0, 1 << 32, size=(2000, 100), dtype=np.uint64).astype(
            np.uint32
        )
    )
    his, los = jax.jit(jax.vmap(fingerprint_words))(batch)
    pairs = set(zip(np.asarray(his).tolist(), np.asarray(los).tolist()))
    assert len(pairs) == 2000


@pytest.mark.slow
def test_deep_drain_2pc8_scale_exact():
    """Scale regression net: 2pc-8 (1,745,408 states — measured once from
    this checker and cross-validated by the sharded mesh) exercises table
    growth, log-full drain exits, and multi-GB-candidate waves end to end."""
    checker = (
        TwoPhaseSys(8)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 13,
            table_capacity=1 << 20,  # forces ~2 growth/rehash cycles
            drain_log_factor=48,
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 1_745_408
    checker.assert_properties()


class TestScatterDedup:
    """wave_dedup='scatter' (round 4): sort-free in-wave dedup via the
    duplicate-tolerant insert. Counts must match the sorted path exactly;
    the incompatible/unknown configurations must refuse."""

    def test_counts_match_sorted_path(self):
        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        c = (
            TwoPhaseSys(4)
            .checker()
            .spawn_tpu_bfs(
                frontier_capacity=64,
                table_capacity=1 << 12,
                wave_dedup="scatter",
            )
            .join()
        )
        assert c.worker_error() is None
        assert c.unique_state_count() == 1568
        c.assert_properties()

    def test_symmetry_orbit_counts_match(self):
        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        runs = {}
        for mode in ("sort", "scatter"):
            c = (
                TwoPhaseSys(4)
                .checker()
                .symmetry()
                .spawn_tpu_bfs(
                    frontier_capacity=64,
                    table_capacity=1 << 12,
                    wave_dedup=mode,
                )
                .join()
            )
            assert c.worker_error() is None
            runs[mode] = c.unique_state_count()
        assert runs["sort"] == runs["scatter"]

    def test_pallas_combination_refused(self):
        import pytest as _pytest

        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        with _pytest.raises(ValueError, match="incompatible"):
            TwoPhaseSys(3).checker().spawn_tpu_bfs(
                table_capacity=1 << 12,
                wave_dedup="scatter",
                hashset_impl="pallas",
            )

    def test_unknown_mode_refused(self):
        import pytest as _pytest

        from stateright_tpu.models.two_phase_commit import TwoPhaseSys

        with _pytest.raises(ValueError, match="wave_dedup"):
            TwoPhaseSys(3).checker().spawn_tpu_bfs(wave_dedup="radix")
