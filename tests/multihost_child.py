"""Child process for the multi-controller (multi-host) sharded tests.

Each of two processes owns 4 virtual CPU devices; the ``bootstrap_mesh``
entry point (``parallel/base_mesh.py``) initializes ``jax.distributed``
from the ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
``JAX_PROCESS_ID`` convention and returns the joint 8-device ``"fp"``
mesh spanning both. The sharded checker then runs SPMD-over-hosts: both
processes execute the same host loop, jit dispatches agree, and host
pulls allgather (``ShardedTpuBfsChecker._pull``).

Usage: ``python multihost_child.py <process_id> <coordinator_port> [mode]``

Modes:
- ``plain`` (default) — 2pc-3, full-width exchange.
- ``sieve``           — 2pc-3 with the compression-and-sieve routing on
                        (receipt-cache kills + rung-compacted exchange).
- ``evict_exchange``  — the multi-process delta-compressed eviction
                        allgather (``_allgather_evicted_keys``) driven
                        directly over a synthetic sharded table with
                        known per-shard keys; both controllers must
                        decode the identical ground truth.

The output line carries counts AND the shipped-lane tally so the driver
can gate bit-identity and the sieve's traffic reduction across modes.
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
mode = sys.argv[3] if len(sys.argv) > 3 else "plain"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)

import jax

jax.config.update("jax_platforms", "cpu")
# Cross-process collectives on the CPU backend (the DCN stand-in); without
# this the first multiprocess computation fails to compile.
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Bootstrap BEFORE any model/checker import: jax.distributed must
# initialize before the first computation touches the backend.
from stateright_tpu.parallel import bootstrap_mesh
from stateright_tpu.utils.compile_cache import enable_persistent_cache

# Config-only (safe pre-init); both children share the cache — jax's
# atomic writes make the concurrent misses race-free — so the sieve leg
# reuses the plain leg's base programs instead of recompiling them.
enable_persistent_cache()

mesh = bootstrap_mesh()

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry.metrics import metrics_registry

assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4
assert mesh.devices.size == 8

if mode == "evict_exchange":
    # Drive the compress stage of the tentpole directly: a synthetic
    # (n, rows, 2) table with known per-shard keys, sharded over the
    # real 2-process mesh, pushed through the production
    # _allgather_evicted_keys. Covers the two-step lens/bytes
    # allgather, the header-only empty-shard ownership case, and the
    # codec's value extremes — and both controllers must decode the
    # identical per-shard key lists. (The full out-of-core run is kept
    # single-process; see test_multihost.py for why.)
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from stateright_tpu.parallel.sharded import ShardedTpuBfsChecker
    from stateright_tpu.telemetry.instruments import CommsInstruments
    from stateright_tpu.telemetry.trace import get_tracer

    n, rows = 8, 256
    mult = np.uint64(0x9E3779B97F4A7C15)  # odd => bijection mod 2^64
    full = np.zeros((n, rows, 2), np.uint32)
    truth = []
    for d in range(n):
        if d == 5:
            # Empty shard: its owner still ships the 8-byte codec
            # header, which is what disambiguates ownership.
            truth.append(np.zeros(0, np.uint64))
            continue
        count = 40 + 17 * d
        keys = (np.arange(1, count + 1, dtype=np.uint64)
                + np.uint64(d * 1000)) * mult
        if d == 0:
            keys[0] = np.uint64(1)  # hi word all-zero, still live
            keys[1] = np.uint64(2**64 - 1)  # codec's max delta reach
        assert len(np.unique(keys)) == count
        slots = (np.arange(count) * 7) % rows  # 7 coprime to 256
        full[d, slots, 0] = (keys >> np.uint64(32)).astype(np.uint32)
        full[d, slots, 1] = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        truth.append(np.sort(keys))
    table = jax.make_array_from_callback(
        full.shape,
        NamedSharding(mesh, PartitionSpec("fp")),
        lambda idx: full[idx],
    )
    obj = object.__new__(ShardedTpuBfsChecker)
    obj._n = n
    obj._ci = CommsInstruments("sharded_bfs")
    obj._tracer = get_tracer()
    shard_keys = obj._allgather_evicted_keys(table)
    assert len(shard_keys) == n
    for d in range(n):
        got = np.asarray(shard_keys[d], np.uint64)
        assert np.array_equal(got, truth[d]), (d, got, truth[d])
    wire = int(
        metrics_registry()
        .snapshot()
        .get("sharded_bfs.comms.evict_wire_bytes", 0)
    )
    raw = full.size * full.itemsize
    assert 0 < wire < raw, (wire, raw)
    total = int(sum(len(k) for k in truth))
    print(
        f"MULTIHOST-OK pid={pid} count={total} states={total} "
        f"depth=0 lanes={wire}",
        flush=True,
    )
    sys.exit(0)

kw = dict(frontier_per_device=32, table_capacity_per_device=512)
if mode == "sieve":
    kw["sieve"] = True
model, expected = TwoPhaseSys(3), 288

# Fleet observability across a REAL process boundary (plain leg, pid 0
# only): a live monitor taps the default tracer before the run, and its
# /fleet view must carry one row per shard of the JOINT mesh — 8 rows,
# 4 of them owned by the OTHER controller (the per-shard columns ride
# the same allgather as the comms exchange, so both hosts see all 8).
monitor = None
if pid == 0 and mode == "plain":
    from stateright_tpu.telemetry.server import MonitorServer

    monitor = MonitorServer(port=0)

checker = model.checker().spawn_sharded_tpu_bfs(mesh=mesh, **kw).join()
err = checker.worker_error()
assert err is None, err
count = checker.unique_state_count()
assert count == expected, count
checker.assert_properties()
snap = metrics_registry().snapshot()
lanes = snap.get("sharded_bfs.comms.lanes_shipped", 0)
print(
    f"MULTIHOST-OK pid={pid} count={count} "
    f"states={checker.state_count()} depth={checker.max_depth()} "
    f"lanes={lanes}",
    flush=True,
)

if monitor is not None:
    import json
    from urllib.request import urlopen

    with urlopen(f"{monitor.url}/fleet", timeout=10) as r:
        fleet = json.load(r)
    per_shard = fleet.get("per_shard") or []
    assert len(per_shard) == 8, fleet
    assert fleet.get("hosts") == 2, fleet
    # Remote shards (4..7 live on pid 1) must carry real load, proving
    # the rows crossed the process boundary rather than zero-filling.
    assert all(row.get("insert_load", 0) > 0 for row in per_shard), per_shard
    assert len(fleet.get("stragglers") or []) >= 1, fleet
    monitor.close()
    print(f"FLEET-OK pid={pid} shards={len(per_shard)}", flush=True)
