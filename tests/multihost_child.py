"""Child process for the multi-controller (multi-host) sharded test.

Each of two processes owns 4 virtual CPU devices; ``jax.distributed``
joins them into one 8-device mesh spanning both. The sharded checker then
runs SPMD-over-hosts: both processes execute the same host loop, jit
dispatches agree, and host pulls allgather (``ShardedTpuBfsChecker._pull``).

Usage: ``python multihost_child.py <process_id> <coordinator_port>``.
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    f"localhost:{port}", num_processes=2, process_id=pid
)

import numpy as np
from jax.sharding import Mesh

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stateright_tpu.models.two_phase_commit import TwoPhaseSys

assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

mesh = Mesh(np.array(jax.devices()), ("fp",))
checker = (
    TwoPhaseSys(3)
    .checker()
    .spawn_sharded_tpu_bfs(
        mesh=mesh, frontier_per_device=32, table_capacity_per_device=512
    )
    .join()
)
err = checker.worker_error()
assert err is None, err
assert checker.unique_state_count() == 288, checker.unique_state_count()
checker.assert_properties()
print(f"MULTIHOST-OK pid={pid} count=288", flush=True)
