"""Device-path auxiliary history: linearizability workloads on TpuBfs.

The reference's flagship bench models carry a ``LinearizabilityTester`` as
``ActorModel`` history (``/root/reference/examples/paxos.rs:280-282``); these
tests pin the packed-history encoding (bijective with the host tester), the
device interleaving-table predicate (agrees with the host Wing&Gong search on
every reachable state, both satisfiable and not), and exact device/host
state-count parity on the reference oracle counts: paxos 16,668, ABD 544,
single-copy 93 (``BASELINE.md``).
"""

import numpy as np
import pytest

import jax

from stateright_tpu.models.linearizable_register import AbdModelCfg
from stateright_tpu.models.paxos import PaxosModelCfg
from stateright_tpu.models.single_copy_register import SingleCopyModelCfg


def _tpu(model, **kw):
    kw.setdefault("frontier_capacity", 256)
    kw.setdefault("table_capacity", 1 << 14)
    checker = model.checker().spawn_tpu_bfs(**kw).join()
    assert checker.worker_error() is None
    return checker


def _host_reachable(model):
    """All reachable host states by plain BFS."""
    from collections import deque

    states = list(model.init_states())
    seen = {hash(s) for s in states}
    q = deque(states)
    acts = []
    while q:
        s = q.popleft()
        acts.clear()
        model.actions(s, acts)
        for a in acts:
            ns = model.next_state(s, a)
            if ns is not None and hash(ns) not in seen:
                seen.add(hash(ns))
                states.append(ns)
                q.append(ns)
    return states


# -- encoding bijectivity -----------------------------------------------------


@pytest.mark.parametrize(
    "cfg",
    [PaxosModelCfg(2, 2), SingleCopyModelCfg(2, 2), AbdModelCfg(2, 2)],
    ids=["paxos", "single-copy", "abd"],
)
def test_pack_unpack_round_trip_all_reachable(cfg):
    model = cfg.into_model()
    for s in _host_reachable(model):
        rt = model.unpack_state(model.pack_state(s))
        assert rt == s, f"pack/unpack round trip diverged:\n{s!r}\n{rt!r}"


# -- predicate agreement with the host Wing&Gong search -----------------------


@pytest.mark.parametrize(
    "cfg,expect_violations",
    [(PaxosModelCfg(2, 2), False), (SingleCopyModelCfg(2, 2), True)],
    ids=["paxos-all-linearizable", "single-copy-with-violations"],
)
def test_device_predicate_matches_host_tester(cfg, expect_violations):
    model = cfg.into_model()
    states = _host_reachable(model)
    host = np.array(
        [s.history.serialized_history() is not None for s in states]
    )
    hists = np.stack(
        [np.asarray(model.pack_state(s)["hist"]) for s in states]
    )
    fn = jax.jit(jax.vmap(model.codec._lin.predicate()))
    dev = np.asarray(fn(hists))
    assert (dev == host).all(), (
        f"predicate disagrees on {int((dev != host).sum())}/{len(states)} states"
    )
    assert (~host).any() == expect_violations


@pytest.mark.parametrize(
    "cfg",
    [SingleCopyModelCfg(2, 2), AbdModelCfg(2, 2)],
    ids=["single-copy-2c", "abd-2c"],
)
def test_dp_predicate_matches_lane_grid(cfg):
    # The consumption-vector DP must agree with the superseded lane-grid
    # enumeration (an independent oracle) on every reachable history.
    model = cfg.into_model()
    states = _host_reachable(model)
    hists = np.stack(
        [np.asarray(model.pack_state(s)["hist"]) for s in states]
    )
    lin = model.codec._lin
    dp = np.asarray(jax.jit(jax.vmap(lin.predicate()))(hists))
    lanes = np.asarray(jax.jit(jax.vmap(lin.predicate_lanes()))(hists))
    assert (dp == lanes).all(), (
        f"DP vs lane grid disagree on {int((dp != lanes).sum())}"
        f"/{len(states)} states"
    )


@pytest.mark.slow
def test_dp_predicate_matches_lane_grid_three_clients():
    # C=3 crosses into multi-peer constraint vectors and 27-node DP
    # topology; single-copy with two servers has real violations.
    model = SingleCopyModelCfg(3, 2).into_model()
    states = _host_reachable(model)
    hists = np.stack(
        [np.asarray(model.pack_state(s)["hist"]) for s in states]
    )
    lin = model.codec._lin
    dp = np.asarray(jax.jit(jax.vmap(lin.predicate()))(hists))
    lanes = np.asarray(jax.jit(jax.vmap(lin.predicate_lanes()))(hists))
    host = np.array(
        [s.history.serialized_history() is not None for s in states]
    )
    assert (dp == lanes).all() and (dp == host).all()
    assert (~host).any()


# -- exact device/host count parity (reference oracle counts) -----------------


@pytest.mark.slow
def test_paxos_device_parity_16668():
    checker = _tpu(
        PaxosModelCfg(2, 3).into_model(),
        frontier_capacity=1024,
        table_capacity=1 << 16,
    )
    assert checker.unique_state_count() == 16_668
    checker.assert_properties()  # linearizable holds; value chosen found
    assert set(checker.discoveries()) == {"value chosen"}


@pytest.mark.slow
def test_abd_device_parity_544():
    checker = _tpu(AbdModelCfg(2, 2).into_model())
    assert checker.unique_state_count() == 544
    checker.assert_properties()
    assert set(checker.discoveries()) == {"value chosen"}


def test_single_copy_device_parity_93():
    checker = _tpu(SingleCopyModelCfg(2, 1).into_model())
    assert checker.unique_state_count() == 93
    checker.assert_properties()


@pytest.mark.slow
def test_single_copy_two_servers_not_linearizable_on_device():
    checker = _tpu(SingleCopyModelCfg(2, 2).into_model())
    disc = checker.discoveries()
    assert "linearizable" in disc  # the always-property counterexample
    # Path replay validates the fingerprint trail through the host model.
    assert len(disc["linearizable"].into_vec()) >= 2


@pytest.mark.slow
def test_paxos_sharded_parity():
    import jax as _jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(_jax.devices()[:8]), ("fp",))
    checker = (
        PaxosModelCfg(2, 2)
        .into_model()
        .checker()
        .spawn_sharded_tpu_bfs(
            mesh=mesh, frontier_per_device=64, table_capacity_per_device=1 << 10
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 111
    checker.assert_properties()
