"""Live-monitor tests: Prometheus exposition golden, progress/ETA
estimation, SSE smoke, stall watchdog (fake clock), flight-recorder
round trips (in-process exception and subprocess SIGTERM), Explorer
integration, golden reporter strings with the monitor attached, and the
monitor-on overhead budget. All CPU-only, tier-1 fast."""

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from fixtures import LinearEquation
from stateright_tpu import WriteReporter
from stateright_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    MonitorServer,
    ProgressEstimator,
    StallWatchdog,
    Tracer,
    get_tracer,
    metrics_registry,
    prometheus_text,
)
from stateright_tpu.telemetry.server import MonitorCore, sanitize_metric_name

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLIGHT_REPORT = os.path.join(REPO_DIR, "scripts", "flight_report.py")
TRACE_SUMMARY = os.path.join(REPO_DIR, "scripts", "trace_summary.py")


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _get_json(url, timeout=10):
    code, body = _get(url, timeout=timeout)
    return code, json.loads(body)


# -- Prometheus exposition --------------------------------------------------


def test_metrics_exposition_golden():
    """The /metrics text format is a compatibility surface: sanitized
    names, counters suffixed _total, unset gauges elided, log2
    histograms as cumulative le-buckets."""
    reg = MetricsRegistry()
    reg.counter("tpu_bfs.waves").inc(3)
    reg.counter("tpu_bfs.bucket_dispatch.1024").inc()
    reg.gauge("tpu_bfs.hashset_occupancy").set(0.41)
    reg.gauge("tpu_bfs.storage.host_bytes").set(4096)
    reg.gauge("never.set")  # no sample => elided
    h = reg.histogram("bfs.block_states")
    h.observe(1)
    h.observe(3)
    h.observe(4)
    assert prometheus_text(reg) == (
        "# TYPE stateright_bfs_block_states histogram\n"
        'stateright_bfs_block_states_bucket{le="1.0"} 1\n'
        'stateright_bfs_block_states_bucket{le="4.0"} 3\n'
        'stateright_bfs_block_states_bucket{le="+Inf"} 3\n'
        "stateright_bfs_block_states_sum 8\n"
        "stateright_bfs_block_states_count 3\n"
        "# TYPE stateright_tpu_bfs_bucket_dispatch_1024_total counter\n"
        "stateright_tpu_bfs_bucket_dispatch_1024_total 1\n"
        "# TYPE stateright_tpu_bfs_hashset_occupancy gauge\n"
        "stateright_tpu_bfs_hashset_occupancy 0.41\n"
        "# TYPE stateright_tpu_bfs_storage_host_bytes gauge\n"
        "stateright_tpu_bfs_storage_host_bytes 4096\n"
        "# TYPE stateright_tpu_bfs_waves_total counter\n"
        "stateright_tpu_bfs_waves_total 3\n"
    )


def test_metric_name_sanitization():
    assert sanitize_metric_name("a.b-c d") == "stateright_a_b_c_d"
    assert sanitize_metric_name("x", prefix="") == "x"
    assert sanitize_metric_name("9x", prefix="") == "_9x"


# -- progress / ETA estimator ----------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_estimator_eta_band_nonnull_after_three_waves():
    clock = FakeClock()
    est = ProgressEstimator(clock=clock)
    # Decaying frontier: growth < 1, ETA converges.
    for frontier in (1000, 500, 250, 125):
        est.observe(n_new=frontier, generated=frontier * 3,
                    frontier=frontier, depth=1)
        clock.t += 1.0
    snap = est.snapshot()
    assert snap["waves"] == 4
    assert snap["ewma_states_per_s"] > 0
    assert 0.4 < snap["frontier_growth"] < 0.6
    assert snap["eta_s_low"] is not None
    assert snap["eta_s_high"] is not None
    assert snap["eta_s_low"] <= snap["eta_s_high"]
    # Decaying at g=0.5 from 125: ~125 remaining beyond the frontier.
    assert snap["eta_s_high"] < 10.0
    assert snap["dedup_hit_rate"] == pytest.approx(2 / 3)


def test_estimator_growing_frontier_band_is_finite_and_ordered():
    clock = FakeClock()
    est = ProgressEstimator(clock=clock)
    for frontier in (10, 20, 40, 80):
        est.observe(n_new=frontier, generated=frontier, frontier=frontier)
        clock.t += 1.0
    low, high = est.eta_band()
    assert low is not None and high is not None and low <= high
    assert est.frontier_growth() > 1.5


def test_estimator_null_before_min_waves():
    est = ProgressEstimator(clock=FakeClock())
    est.observe(n_new=5, generated=10, frontier=5)
    assert est.eta_band() == (None, None)


# -- stall watchdog (fake clock, no threads) --------------------------------


def test_stall_watchdog_fires_once_and_rearms(capsys):
    clock = FakeClock()
    reg = MetricsRegistry()
    tracer = Tracer()
    stalls = []
    dog = StallWatchdog(
        deadline_s=10.0, registry=reg, tracer=tracer, clock=clock,
        on_stall=stalls.append,
    )
    assert not dog.poll()  # fresh: inside the deadline
    clock.t += 9.0
    assert not dog.poll()
    clock.t += 2.0  # 11s since pet: stall
    assert dog.poll()
    assert not dog.poll()  # fires once per stall
    assert stalls and stalls[0] > 10.0
    assert reg.counter("monitor.stalls").snapshot() == 1
    instants = [e for e in tracer.events() if e["name"] == "monitor.stall"]
    assert len(instants) == 1
    assert instants[0]["args"]["deadline_s"] == 10.0
    assert "monitor.stall" in capsys.readouterr().err
    # A wave re-arms; the next overrun fires again.
    dog.pet()
    clock.t += 11.0
    assert dog.poll()
    assert reg.counter("monitor.stalls").snapshot() == 2


def test_stall_watchdog_disarms_when_checker_done():
    """Waves stopping because the check FINISHED is not a stall: a
    monitor held open past completion must stay silent."""
    clock = FakeClock()
    reg = MetricsRegistry()
    done = [True]
    dog = StallWatchdog(
        deadline_s=10.0, registry=reg, tracer=Tracer(), clock=clock,
        done_fn=lambda: done[0],
    )
    clock.t += 11.0
    assert not dog.poll()
    assert reg.counter("monitor.stalls").snapshot() == 0
    # Still-running checker overrunning the deadline fires as usual.
    done[0] = False
    assert dog.poll()
    assert reg.counter("monitor.stalls").snapshot() == 1


def test_monitor_core_counts_explicit_zero_waves():
    """A drain span's ``waves=0`` (final wave rides the following wave
    span) must count zero — only a MISSING arg defaults to 1."""
    core = MonitorCore(registry=MetricsRegistry(), tracer=Tracer())
    span = {"ph": "X", "name": "tpu_bfs.drain", "dur": 1000.0,
            "args": {"new_unique": 5, "generated": 10, "frontier": 8,
                     "waves": 0}}
    core.write_event(dict(span, args=dict(span["args"])))
    assert core.estimator.waves == 0
    core.write_event(dict(span, args=dict(span["args"], waves=3)))
    assert core.estimator.waves == 3
    no_waves = dict(span["args"])
    del no_waves["waves"]
    core.write_event(dict(span, args=no_waves))
    assert core.estimator.waves == 4


def test_monitor_prefers_live_ring_count_over_capacity_frontier():
    """Deep-drain spans carry the dispatch CAPACITY as ``frontier``
    (constant F_max all run) and the live pending count as
    ``ring_count`` — the progress fit must read the live value, or the
    growth factor and ETA band are capacity-derived constants in the
    default (deep-drain) mode."""
    core = MonitorCore(registry=MetricsRegistry(), tracer=Tracer())
    for ring in (1000, 500, 250, 125):
        core.write_event({
            "ph": "X", "name": "tpu_bfs.drain", "dur": 1000.0,
            "args": {"new_unique": ring, "generated": ring * 3,
                     "frontier": 4096, "ring_count": ring, "waves": 1},
        })
    snap = core.estimator.snapshot()
    assert snap["frontier"] == 125  # live, not the 4096 capacity
    assert snap["frontier_growth"] < 0.6  # decaying, not flat ~1.0
    # Consume-wave spans carry the live value as `live_lanes` instead.
    core.write_event({
        "ph": "X", "name": "tpu_bfs.wave", "dur": 1000.0,
        "args": {"new_unique": 60, "generated": 180, "frontier": 4096,
                 "live_lanes": 60},
    })
    assert core.estimator.snapshot()["frontier"] == 60


# -- flight recorder --------------------------------------------------------


def test_flight_dump_on_exception_round_trip(tmp_path):
    """dump -> scripts/flight_report.py parses and renders."""
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    rec = FlightRecorder(
        run_id="testrun", out_dir=str(tmp_path), checker=checker
    )
    try:
        raise ValueError("boom at wave 7")
    except ValueError:
        path = rec.dump("exception", exc=sys.exc_info())
    assert path == str(tmp_path / "flight-testrun.json")
    with open(path) as f:
        record = json.load(f)
    assert record["flight_recorder"] == 1
    assert record["reason"] == "exception"
    assert record["exception"]["type"] == "ValueError"
    assert "boom at wave 7" in record["exception"]["traceback"]
    assert record["digest"]["backend"] == "BfsChecker"
    assert record["digest"]["unique_state_count"] == 12
    assert record["digest"]["discoveries"] == ["solvable"]
    assert isinstance(record["ring"], list)
    assert isinstance(record["metrics"], dict)

    r = subprocess.run(
        [sys.executable, FLIGHT_REPORT, path],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "ValueError: boom at wave 7" in r.stdout
    assert "BfsChecker" in r.stdout


def test_flight_excepthook_chains(tmp_path):
    rec = FlightRecorder(run_id="hook", out_dir=str(tmp_path))
    seen = []
    prev, sys.excepthook = sys.excepthook, lambda *a: seen.append(a)
    try:
        rec.install()
        try:
            raise RuntimeError("unhandled")
        except RuntimeError:
            sys.excepthook(*sys.exc_info())
    finally:
        rec.uninstall()
        sys.excepthook = prev
    assert seen, "previous excepthook must still run"
    with open(tmp_path / "flight-hook.json") as f:
        assert json.load(f)["exception"]["type"] == "RuntimeError"


_SIGTERM_CHILD = """
import sys
sys.path.insert(0, {repo!r})
from stateright_tpu import Model, Property
from stateright_tpu.telemetry import MonitorServer

class Endless(Model):
    # Unbounded counter chain: the BFS never finishes, so the parent's
    # SIGTERM always lands mid-run (deterministically "mid-wave").
    def init_states(self):
        return [0]

    def actions(self, state, actions):
        actions.append("inc")

    def next_state(self, state, action):
        return state + 1

    def properties(self):
        return [Property.always("ok", lambda m, s: True)]

mon = MonitorServer(
    port=0, run_id="sigterm", flight_recorder=True, flight_dir={out!r}
)
checker = Endless().checker().spawn_bfs()
mon.attach(checker)
print("READY", mon.port, flush=True)
checker.join()
"""


def test_sigterm_produces_parseable_flight_file(tmp_path):
    """Killing a monitored run mid-run dumps flight-<run_id>.json whose
    ring buffer holds the final wave/block spans, and flight_report.py
    renders it."""
    child = subprocess.Popen(
        [sys.executable, "-c",
         _SIGTERM_CHILD.format(repo=REPO_DIR, out=str(tmp_path))],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        line = child.stdout.readline()
        assert line.startswith("READY"), line
        time.sleep(1.0)  # let blocks flow so the ring has spans
        child.send_signal(signal.SIGTERM)
        rc = child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=30)
    # The recorder re-delivers the signal: exit reflects SIGTERM death.
    assert rc != 0
    path = tmp_path / "flight-sigterm.json"
    assert path.exists(), "SIGTERM must leave a flight dump"
    with open(path) as f:
        record = json.load(f)
    assert record["reason"] == "SIGTERM"
    assert record["digest"]["backend"] == "BfsChecker"
    assert record["digest"]["done"] is False
    spans = [e for e in record["ring"]
             if e.get("ph") == "X" and "unique_total" in (e.get("args") or {})]
    assert spans, "ring buffer must carry the final block spans"
    r = subprocess.run(
        [sys.executable, FLIGHT_REPORT, str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "SIGTERM" in r.stdout


# -- monitor server: /metrics, /status, /events -----------------------------


@pytest.fixture
def monitor():
    mon = MonitorServer(port=0)
    yield mon
    mon.close()


def test_status_and_metrics_concurrent_with_checking(monitor):
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs()
    monitor.attach(checker)
    checker.join()
    code, status = _get_json(monitor.url + "/status")
    assert code == 200
    assert status["checker"]["backend"] == "BfsChecker"
    assert status["checker"]["unique_state_count"] == 12
    progress = status["progress"]
    assert progress["unique_states"] >= 1
    assert "eta_s_low" in progress and "eta_s_high" in progress
    assert isinstance(status["metrics"], dict)
    code, body = _get(monitor.url + "/metrics")
    assert code == 200
    text = body.decode()
    assert "stateright_bfs_blocks_total" in text
    assert "# TYPE" in text
    code, index = _get_json(monitor.url + "/")
    assert code == 200
    assert {"/metrics", "/status", "/events"} <= set(index["endpoints"])


def test_sse_stream_delivers_wave_events(monitor):
    """Connect, receive >= 1 wave event, disconnect."""
    frames = []
    connected = threading.Event()

    def reader():
        req = urllib.request.urlopen(monitor.url + "/events", timeout=15)
        try:
            buf = b""
            connected.set()
            deadline = time.time() + 10
            while time.time() < deadline:
                # SSE is line-oriented; readline never blocks past the
                # next flushed event (a fixed-size read would).
                line = req.readline()
                if not line:
                    break
                buf += line
                at = buf.find(b"event: wave")
                if at != -1 and buf.find(b"\n\n", at) != -1:
                    # Full frame (event line + data line) received.
                    frames.append(buf)
                    break
        finally:
            req.close()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    assert connected.wait(timeout=10)
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs()
    monitor.attach(checker)
    checker.join()
    t.join(timeout=15)
    assert frames, "SSE client must receive at least one wave event"
    text = frames[0].decode()
    assert "event: hello" in text  # stream liveness marker
    data = next(
        line for line in text.splitlines()
        if line.startswith("data:") and '"new_unique"' in line
    )
    payload = json.loads(data[len("data:"):])
    assert payload["new_unique"] >= 0
    assert "ewma_states_per_s" in payload
    # Disconnected reader must be dropped from the broker. The handler
    # notices on its next write, so nudge one event through.
    deadline = time.time() + 10
    while monitor.core.broker.client_count() and time.time() < deadline:
        monitor.core.broker.publish("wave", {"nudge": 1})
        time.sleep(0.05)
    assert monitor.core.broker.client_count() == 0


def test_device_checker_eta_nonnull_after_three_waves(monitor):
    """The acceptance shape: a device-backend run with the monitor
    attached serves /status with non-null ETA fields after >= 3 waves."""
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    checker = (
        TwoPhaseSys(2)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 6, table_capacity=1 << 10,
            max_drain_waves=1,  # wave-at-a-time: one event per wave
        )
    )
    monitor.attach(checker)
    checker.join()
    assert checker.unique_state_count() == 56
    code, status = _get_json(monitor.url + "/status")
    assert code == 200
    progress = status["progress"]
    assert progress["waves"] >= 3
    assert progress["eta_s_low"] is not None
    assert progress["eta_s_high"] is not None
    assert progress["ewma_states_per_s"] > 0
    # The ETA band also publishes as gauges (Prometheus surface).
    snap = metrics_registry().snapshot()
    assert snap["monitor.eta_low_seconds"] is not None
    assert snap["monitor.states_per_second_ewma"] > 0
    digest = checker.state_digest()
    assert digest["table_capacity"] >= 1 << 10  # may have grown mid-run
    assert digest["frontier_capacity"] == 1 << 6


def test_golden_reporter_strings_unchanged_with_monitor_attached(monitor):
    """The WriteReporter compatibility strings must stay byte-identical
    while the monitor consumes every span the run emits."""
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs()
    monitor.attach(checker)
    checker.join()
    out = io.StringIO()
    checker.report(WriteReporter(out))
    assert out.getvalue().startswith(
        "Done. states=15, unique=12, depth=4, sec="
    )
    assert monitor.core.estimator.waves >= 1  # the monitor really saw it


def test_explorer_serves_monitor_endpoints():
    from stateright_tpu.checker.explorer import start_server
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    server, checker = start_server(
        TwoPhaseSys(3).checker(), ("localhost", 0)
    )
    try:
        host, port = server.server_address[:2]
        base = f"http://{host}:{port}"
        checker.run_to_completion()
        checker.join()
        code, status = _get_json(base + "/.status")
        assert code == 200
        # The on-demand checker's /.status carries the same progress
        # fields as the monitor /status.
        progress = status["progress"]
        assert progress is not None
        assert progress["unique_states"] >= 288
        assert {"eta_s_low", "eta_s_high", "ewma_states_per_s"} <= set(
            progress
        )
        code, body = _get(base + "/metrics")
        assert code == 200
        assert b"stateright_on_demand_blocks_total" in body
        code, mstatus = _get_json(base + "/status")
        assert code == 200
        assert mstatus["checker"]["backend"] == "OnDemandChecker"
    finally:
        server.shutdown()


# -- monitor-on overhead budget --------------------------------------------


def test_monitor_on_overhead_under_budget():
    """Monitor-on vs monitor-off must cost <5% on a checker run. Same
    form as PR 3's always-on budget test: the per-event sink cost
    (estimator + gauges + zero-client broker fanout) times the events a
    real run emits, measured against that run's wall time — direct A/B
    of sub-second runs on this shared box swings far more than the 5%
    being asserted, while per-event cost over 10k iterations is stable."""
    reg = metrics_registry()
    blocks_before = reg.counter("bfs.blocks").snapshot()
    t0 = time.perf_counter()
    LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    run_secs = time.perf_counter() - t0
    events = reg.counter("bfs.blocks").snapshot() - blocks_before
    assert events >= 1

    mon = MonitorServer(port=0)
    try:
        ev = {
            "name": "tpu_bfs.wave", "ph": "X", "ts": 0.0, "dur": 1000.0,
            "pid": 1, "tid": 1,
            "args": {
                "frontier": 512, "generated": 4096, "new_unique": 1024,
                "dedup_hit_rate": 0.75, "occupancy": 0.3, "max_depth": 9,
            },
        }
        n = 10_000
        t0 = time.perf_counter()
        for _ in range(n):
            mon.core.write_event(ev)
        per_event = (time.perf_counter() - t0) / n
    finally:
        mon.close()

    overhead = per_event * events
    assert overhead < 0.05 * run_secs, (
        f"monitor overhead too high: {events} events x "
        f"{per_event * 1e6:.1f}us = {overhead * 1e3:.2f}ms on a "
        f"{run_secs * 1e3:.0f}ms run"
    )


# -- trace_summary hardening + JsonlSink tail durability --------------------


def test_trace_summary_counts_torn_lines_and_tops(tmp_path):
    path = tmp_path / "torn.jsonl"
    events = [
        {"name": "tpu_bfs.wave", "ph": "X", "ts": 1.0, "dur": 5000.0,
         "args": {"frontier": 4, "generated": 8, "new_unique": 4,
                  "dedup_hit_rate": 0.5, "occupancy": 0.1,
                  "max_depth": 2}},
        {"name": "tpu_bfs.table_grow", "ph": "X", "ts": 2.0,
         "dur": 9000.0, "args": {"from_capacity": 8}},
    ]
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
        f.write('{"name": "torn", "ph": "X", "ts": 3')  # killed mid-write
    r = subprocess.run(
        [sys.executable, TRACE_SUMMARY, str(path), "--top", "2"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "skipped 1 unparseable line(s)" in r.stderr
    assert "tpu_bfs.wave" in r.stdout
    # --top lists the slowest spans of ANY kind, slowest first (its
    # header is the LAST "span" column header in the output).
    top = r.stdout[r.stdout.rindex("span"):]
    assert top.index("table_grow") < top.index("tpu_bfs.wave")


def test_jsonl_sink_close_flushes_and_is_idempotent(tmp_path):
    from stateright_tpu.telemetry import JsonlSink

    path = tmp_path / "tail.jsonl"
    f = open(path, "w", buffering=1 << 20)  # big buffer: no auto-flush
    sink = JsonlSink(f)
    # Bypass write_event's per-write flush to prove close() flushes.
    f.write('{"name": "tail-event"}\n')
    assert path.read_text() == ""  # still buffered
    sink.close()
    assert "tail-event" in path.read_text()
    sink.close()  # idempotent: atexit may replay it
    f.close()
