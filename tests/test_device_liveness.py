"""Device-native liveness (``liveness="device"``): sound ``eventually``
verdicts from the condition-false edge store + trim/reach kernels.

The contract under test (ISSUE 14 acceptance): device-liveness verdicts
match ``lasso_discoveries`` exactly — both certificate shapes (lasso and
masked terminal) — on every liveness model shape, on both device
checkers, composed with packing, async pipelining, out-of-core eviction,
and preempt/resume.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu import Property
from stateright_tpu.checker.liveness import lasso_discoveries
from stateright_tpu.core.batch import BatchableModel
from stateright_tpu.core.model import Model

from test_liveness import _Cycler, _Diamond, eventually_odd


class PackedDGraph(Model, BatchableModel):
    """The host fixtures' ``DGraph`` (eventually-odd property) as a
    packed model, so every graph shape in tests/test_liveness.py runs
    on the device checkers too. States are u32 node ids; actions index
    each node's sorted successor list."""

    def __init__(self, *paths):
        self.inits = set()
        self.edges = {}
        for path in paths:
            src = path[0]
            self.inits.add(src)
            for dst in path[1:]:
                self.edges.setdefault(src, set()).add(dst)
                src = dst
        nodes = set(self.inits) | set(self.edges)
        for ds in self.edges.values():
            nodes |= ds
        size = max(nodes) + 1
        self._A_max = max(
            (len(v) for v in self.edges.values()), default=1
        ) or 1
        self._succ = np.zeros((size, self._A_max), np.uint32)
        self._vld = np.zeros((size, self._A_max), bool)
        for s, ds in self.edges.items():
            for i, d in enumerate(sorted(ds)):
                self._succ[s, i] = d
                self._vld[s, i] = True

    # -- host protocol -----------------------------------------------------

    def init_states(self):
        return sorted(self.inits)

    def actions(self, state, actions):
        actions.extend(
            i for i in range(self._A_max) if self._vld[state, i]
        )

    def next_state(self, state, action):
        if not self._vld[state, action]:
            return None
        return int(self._succ[state, action])

    def properties(self):
        return [eventually_odd()]

    # -- packed protocol ----------------------------------------------------

    def packed_action_count(self):
        return self._A_max

    def packed_init_states(self):
        return {"s": jnp.asarray(sorted(self.inits), jnp.uint32)}

    def packed_step(self, state, action_id):
        s = state["s"]
        nxt = jnp.asarray(self._succ)[s, action_id]
        valid = jnp.asarray(self._vld)[s, action_id]
        return {"s": jnp.where(valid, nxt, s)}, valid

    def packed_conditions(self):
        return [lambda st: (st["s"] % 2) == 1]

    def pack_state(self, host_state):
        return {"s": np.uint32(host_state)}

    def unpack_state(self, packed):
        return int(packed["s"])


def _chain(n, tail_odd=True):
    """0 -> 2 -> ... -> 2(n-1) [-> odd terminal]: the absence-certification
    shape (no cycle; the only terminal is condition-true)."""
    path = [2 * i for i in range(n)]
    if tail_odd:
        path.append(2 * n + 1)
    return PackedDGraph(path)


# Every graph shape tests/test_liveness.py exercises, plus the absence
# chain. (name, model factory, expected-verdict hints.)
GRAPH_CASES = {
    "cycle": lambda: PackedDGraph([0, 2, 4, 2]),
    "dag_join_terminal": lambda: PackedDGraph([0, 1, 4], [0, 2, 4]),
    "terminal_init": lambda: PackedDGraph([2]),
    "cycle_through_odd": lambda: PackedDGraph([0, 1, 2, 0]),
    "terminal_preferred": lambda: PackedDGraph([0, 2]),
    "absence_chain": lambda: _chain(64),
}


def _spawn(model, kind, *, liveness=None, **kw):
    b = model.checker()
    if kind == "tpu":
        return b.spawn_tpu_bfs(
            frontier_capacity=16, table_capacity=1 << 9,
            liveness=liveness, **kw,
        ).join()
    assert kind == "sharded"
    return b.spawn_sharded_tpu_bfs(
        frontier_per_device=16, table_capacity_per_device=1 << 9,
        liveness=liveness, **kw,
    ).join()


def _assert_sound_eventually(model, prop, path):
    """A valid `eventually` counterexample: all states condition-false,
    ending in a revisit (lasso) or a terminal state (maximal path)."""
    states = path.into_states()
    assert not any(prop.condition(model, s) for s in states)
    last = states[-1]
    if last in states[:-1]:
        return  # lasso certificate
    acts = []
    model.actions(last, acts)
    succs = [model.next_state(last, a) for a in acts]
    assert not any(
        ns is not None and model.within_boundary(ns) for ns in succs
    )


def _expected_verdicts(model):
    """Ground truth: the default-semantics discoveries plus the exact
    host lasso pass on top — what device liveness must match."""
    plain = _spawn(model, "tpu")
    have = set(plain.discoveries())
    extra = lasso_discoveries(model, model.properties(), have)
    return have | set(extra)


@pytest.mark.parametrize("case", sorted(GRAPH_CASES))
@pytest.mark.parametrize("kind", ["tpu", "sharded"])
def test_verdicts_match_lasso_discoveries(case, kind):
    model = GRAPH_CASES[case]()
    expected = _expected_verdicts(GRAPH_CASES[case]())
    dev = _spawn(model, kind, liveness="device")
    assert dev.worker_error() is None
    found = dev.discoveries()
    assert set(found) == expected
    prop = model.properties()[0]
    for path in found.values():
        _assert_sound_eventually(model, prop, path)
    # The absence/counterexample evidence is recorded per property.
    rep = dev.liveness_report()
    assert rep["mode"] == "device"
    if "odd" not in expected:
        assert rep["outcomes"]["odd"]["verdict"] == "absent"


@pytest.mark.parametrize("kind", ["tpu", "sharded"])
def test_fixture_models_match(kind):
    for model_cls in (_Cycler, _Diamond):
        expected = _expected_verdicts(model_cls())
        dev = _spawn(model_cls(), kind, liveness="device")
        assert set(dev.discoveries()) == expected
        prop = dev.model().properties()[0]
        for path in dev.discoveries().values():
            _assert_sound_eventually(dev.model(), prop, path)


def test_async_pipeline_and_out_of_core_compose():
    # Async + tiered store + a tiny edge log (forced mid-run evictions):
    # the verdict and certificate must match the plain device run.
    model = PackedDGraph([0, 2, 4, 2], [0, 6], [6, 8, 10, 6])
    base = _spawn(PackedDGraph([0, 2, 4, 2], [0, 6], [6, 8, 10, 6]),
                  "tpu", liveness="device")
    composed = _spawn(
        model, "tpu", liveness="device", async_pipeline=True,
        edge_log_capacity=64,
    )
    assert composed.worker_error() is None
    assert set(composed.discoveries()) == set(base.discoveries())
    assert (
        composed.discoveries()["odd"].into_states()
        == base.discoveries()["odd"].into_states()
    )
    # The tiny log really evicted mid-run (not just the final flush).
    assert composed._live_store.stats()["evictions"] >= 1


def test_preempt_resume_preserves_edge_log():
    # Preempt mid-exploration; the edge store rides the v3 payload and
    # the resumed run's verdict is identical to an uninterrupted one.
    model_fn = lambda: _chain(48)  # noqa: E731
    baseline = _spawn(model_fn(), "tpu", liveness="device")
    assert baseline._live_outcomes["odd"]["verdict"] == "absent"

    ck = model_fn().checker().spawn_tpu_bfs(
        frontier_capacity=8, table_capacity=1 << 9, liveness="device",
        max_drain_waves=2,
    )
    ck.request_preempt()
    for h in ck.handles():
        h.join()
    if not ck.preempted:
        pytest.skip("run finished before the preempt could land")
    payload = ck.preempt_payload()
    assert payload["version"] == 3
    assert payload["liveness"]["edges_logged"] >= 0
    resumed = model_fn().checker().spawn_tpu_bfs(
        frontier_capacity=8, table_capacity=1 << 9, liveness="device",
        resume_from=payload,
    ).join()
    assert resumed.worker_error() is None
    assert resumed.unique_state_count() == baseline.unique_state_count()
    assert resumed._live_outcomes["odd"]["verdict"] == "absent"
    # The pre-preempt incarnation's edges survived into the verdict.
    assert (
        resumed._live_store.stats()["edges_logged"]
        >= baseline._live_store.stats()["edges_logged"]
    )


def test_packed_tenants_match_solo():
    from stateright_tpu.checker.packed_tenancy import TenantPackedEngine

    solo = _spawn(PackedDGraph([0, 2, 4, 2]), "tpu", liveness="device")
    eng = TenantPackedEngine(
        PackedDGraph([0, 2, 4, 2]), frontier_capacity=16,
        table_capacity=1 << 10, max_tenants=4, liveness="device",
    )
    views = {k: eng.admit(k) for k in ("a", "b", "c")}
    done = set()
    for _ in range(200):
        done |= set(eng.step())
        if done >= set(views):
            break
    eng.close()
    assert done >= set(views)
    for v in views.values():
        assert v.liveness_mode == "device"
        assert (
            {k: p.into_states() for k, p in v.discoveries().items()}
            == {
                k: p.into_states()
                for k, p in solo.discoveries().items()
            }
        )


def test_mode_mismatch_and_cap_refusals():
    model = PackedDGraph([0, 2, 4, 2])
    with pytest.raises(ValueError, match="uncapped"):
        model.checker().target_max_depth(3).spawn_tpu_bfs(
            liveness="device"
        )
    with pytest.raises(ValueError, match="expand_fps"):
        PackedDGraph([0, 2]).checker().spawn_tpu_bfs(
            liveness="device", expand_fps=True
        )
    with pytest.raises(ValueError, match="liveness"):
        model.checker().spawn_tpu_bfs(liveness="both")
    # Resume mode mismatches are refused in either direction.
    ck = model.checker().spawn_tpu_bfs(
        frontier_capacity=8, table_capacity=1 << 9, liveness="device",
        max_drain_waves=2,
    )
    ck.request_preempt()
    for h in ck.handles():
        h.join()
    if ck.preempted:
        # The restore runs on the worker thread; join() surfaces its
        # ValueError as the worker failure.
        with pytest.raises(RuntimeError) as ei:
            model.checker().spawn_tpu_bfs(
                resume_from=ck.preempt_payload()
            ).join()
        assert isinstance(ei.value.__cause__, ValueError)
        assert "liveness" in str(ei.value.__cause__)


def test_trim_kernel_shapes():
    from stateright_tpu.ops.edge_store import lasso_trim, reach_any

    # Chain: dies in O(1) rounds via pointer-doubling contraction, NOT
    # O(n) peels — the property that keeps absence certification fast.
    n = 4096
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    ev = np.ones((n - 1,), bool)
    nv = np.ones((n,), bool)
    alive, rounds = lasso_trim(src, dst, ev, nv)
    assert not alive.any()
    assert rounds <= 3

    # Pure cycle: everything survives in one round.
    csrc = np.arange(8, dtype=np.int32)
    cdst = np.roll(csrc, -1).astype(np.int32)
    alive, _r = lasso_trim(
        csrc, cdst, np.ones((8,), bool), np.ones((8,), bool)
    )
    assert alive.all()

    # Chain INTO a cycle: the whole tail survives (leads to a cycle).
    src2 = np.array([0, 1, 2, 3], np.int32)
    dst2 = np.array([1, 2, 3, 2], np.int32)
    alive, _r = lasso_trim(
        src2, dst2, np.ones((4,), bool), np.ones((4,), bool)
    )
    assert alive.all()

    # Reachability with early exit: roots {0} reach candidate {3}.
    hit, _reach = reach_any(
        src2, dst2, np.ones((4,), bool),
        np.array([True, False, False, False]),
        np.array([False, False, False, True]),
    )
    assert hit
    # ...but not an unreachable candidate.
    hit, reach = reach_any(
        np.array([1], np.int32), np.array([2], np.int32),
        np.ones((1,), bool),
        np.array([True, False, False]),
        np.array([False, False, True]),
    )
    assert not hit
    assert reach.tolist() == [True, False, False]


def test_edge_store_checkpoint_roundtrip(tmp_path):
    from stateright_tpu.storage import LivenessEdgeStore

    store = LivenessEdgeStore()
    store.absorb(
        phi=np.array([1, 1, 2], np.uint32),
        plo=np.array([0, 0, 0], np.uint32),
        chi=np.array([2, 2, 0], np.uint32),
        clo=np.array([0, 0, 0], np.uint32),
        emask=np.array([1, 1, 0], np.uint32),  # duplicate edge dedups
        tmask=np.array([0, 0, 1], np.uint32),
    )
    store.add_roots(np.array([1 << 32], np.uint64), np.array([1]))
    state = store.export_state()
    other = LivenessEdgeStore()
    other.load_state(state)
    src, dst, roots, terms = other.property_slice(0)
    assert len(src) == 1  # deduped
    assert roots.tolist() == [1 << 32]
    assert terms.tolist() == [2 << 32]
    # Corrupt the CRC: the restore must refuse.
    bad = dict(state, crc=state["crc"] ^ 1)
    with pytest.raises(ValueError, match="CRC"):
        LivenessEdgeStore().load_state(bad)


def test_host_pass_budget_inconclusive(capsys):
    # Satellite: the bounded host post-pass yields an honest
    # `inconclusive` (reporter line + metric) instead of an unbounded
    # stall inside discoveries().
    import io

    from stateright_tpu.checker.liveness import (
        INCONCLUSIVE,
        find_eventually_lasso,
    )
    from stateright_tpu.report import WriteReporter
    from test_liveness import eventually_odd
    from fixtures import DGraph

    g = DGraph.with_property(eventually_odd())
    g.inits.add(0)
    for i in range(500):
        g.edges[2 * i] = {2 * (i + 1)}
    g.edges[2 * 500] = {2 * 500 + 1}
    assert (
        find_eventually_lasso(g, g.prop, budget_states=10)
        is INCONCLUSIVE
    )
    # Unbounded: certifies absence on the same region.
    assert find_eventually_lasso(g, g.prop) is None

    # Chain ends at an ODD terminal: the default semantics find nothing
    # (no counterexample exists) and certifying absence needs the full
    # region — which the budget forbids.
    checker = (
        DGraph.with_property(eventually_odd())
        .with_path([2 * i for i in range(200)] + [401])
        .checker()
        .complete_liveness(budget_states=5)
        .spawn_bfs()
        .join()
    )
    assert checker.discoveries() == {}
    assert checker._lasso_inconclusive == ["odd"]
    assert checker.liveness_report()["inconclusive"] == ["odd"]
    assert (
        checker.metrics().snapshot().get("liveness.inconclusive") == 1
    )
    out = io.StringIO()
    checker.report(WriteReporter(out))
    assert 'Liveness "odd" inconclusive' in out.getvalue()


def test_crashed_run_skip_is_signaled():
    # Satellite: a crashed run's skipped pass must never read as
    # absence — counter + WriteReporter warning.
    import io

    from stateright_tpu.report import WriteReporter
    from stateright_tpu.utils.faults import FaultSpec, inject

    with inject(FaultSpec("device.wave", at=0)):
        ck = _Cycler().checker().complete_liveness().spawn_tpu_bfs(
            frontier_capacity=16, table_capacity=1 << 9
        )
        for h in ck.handles():
            h.join()
    assert ck.worker_error() is not None
    assert ck.discoveries() == {}
    assert (
        ck.metrics().snapshot().get("liveness.skipped_crashed_run") == 1
    )
    out = io.StringIO()
    with pytest.raises(RuntimeError):
        ck.report(WriteReporter(out))
    assert "Liveness pass skipped" in out.getvalue()
