"""Packed actor-system parity: device checkers == host checkers, exactly.

The packed ``ActorModel`` machinery (``stateright_tpu.actor.packed``) stages
deliver/drop/timeout transitions into fixed-width kernels; these tests pin
exact state-count agreement with the host model across network semantics —
the framework's core correctness contract (SURVEY §4 layer 3).
"""

import pytest

from stateright_tpu.actor import Network
from stateright_tpu.models.raft import LEADER, RaftModelCfg


def _tpu(cfg, **kw):
    checker = (
        cfg.into_model()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=256, table_capacity=1 << 14, **kw)
        .join()
    )
    assert checker.worker_error() is None
    return checker


def test_pack_unpack_round_trip():
    model = RaftModelCfg(server_count=3, max_term=1).into_model()
    init = model.init_states()[0]
    assert model.unpack_state(model.pack_state(init)) == init


def test_parity_lossless_duplicating():
    cfg = RaftModelCfg(
        server_count=3,
        max_term=1,
        lossy=False,
        network=Network.new_unordered_duplicating(),
    )
    assert _tpu(cfg).unique_state_count() == 53


def test_parity_lossy_duplicating():
    cfg = RaftModelCfg(
        server_count=3,
        max_term=1,
        lossy=True,
        network=Network.new_unordered_duplicating(),
    )
    assert _tpu(cfg).unique_state_count() == 2717


def test_parity_lossy_nonduplicating():
    cfg = RaftModelCfg(server_count=3, max_term=1, lossy=True)
    assert _tpu(cfg).unique_state_count() == 665


@pytest.mark.slow
def test_parity_on_sharded_mesh():
    checker = (
        RaftModelCfg(server_count=3, max_term=1, lossy=True)
        .into_model()
        .checker()
        .spawn_sharded_tpu_bfs(frontier_per_device=64)
        .join()
    )
    assert checker.worker_error() is None
    assert checker.unique_state_count() == 665


def test_device_discoveries_replay_and_are_meaningful():
    checker = _tpu(RaftModelCfg(server_count=3, max_term=1, lossy=True))
    paths = checker.discoveries()
    assert set(paths) == {"leader elected", "stable leader"}
    elected = paths["leader elected"].last_state()
    assert any(s.role == LEADER for s in elected.actor_states)
    stuck = paths["stable leader"].last_state()
    assert not any(s.role == LEADER for s in stuck.actor_states)


def test_tpu_simulation_runs_packed_actor_system():
    checker = (
        RaftModelCfg(server_count=3, max_term=1, lossy=False)
        .into_model()
        .checker()
        .target_state_count(20_000)
        .spawn_tpu_simulation(seed=3, lanes=128, steps_per_call=16)
        .join()
    )
    assert checker.worker_error() is None
    paths = checker.discoveries()
    if "leader elected" in paths:
        final = paths["leader elected"].last_state()
        assert any(s.role == LEADER for s in final.actor_states)


class TestPackedGuardrails:
    # Crash faults and ordered networks are now packed (round 2;
    # tests/test_packed_ordered_crash.py pins device/host parity). The
    # remaining refusals are history-less codecs asked to carry history
    # and non-empty initial networks.
    def test_history_without_codec_width_unsupported(self):
        model = RaftModelCfg(server_count=3, max_term=1).into_model()
        model.init_history = object()  # aux history the codec can't pack
        with pytest.raises(NotImplementedError):
            model.packed_action_count()

    def test_host_checking_still_works_for_unsupported_configs(self):
        # The same PackedActorModel object remains a plain ActorModel: host
        # checkers handle what the packed path refuses.
        cfg = RaftModelCfg(server_count=3, max_term=1, max_crashes=1)
        checker = cfg.into_model().checker().spawn_bfs().join()
        assert "election safety" not in checker.discoveries()

    def test_undersized_envelope_capacity_is_caught_by_counts(self):
        model = (
            RaftModelCfg(server_count=3, max_term=1, lossy=True)
            .into_model()
            .with_envelope_capacity(2)  # far below the reachable bound
        )
        checker = model.checker().spawn_tpu_bfs(frontier_capacity=128).join()
        assert checker.worker_error() is None
        # Overflowing transitions were pruned: counts fall short of the
        # host oracle, which is how parity tests surface a bad capacity.
        assert checker.unique_state_count() < 665


class TestFlowPairs:
    """``with_flow_pairs`` (round 4): ordered-network flow tables scale
    with the structurally reachable pair set instead of N^2."""

    def test_restricted_pairs_preserve_counts(self):
        # Host/device parity on ordered ABD IS the exactness proof: the
        # host model is unrestricted, so any wrongly excluded pair (or a
        # too-shallow flow) would diverge the device count.
        from stateright_tpu.models.linearizable_register import AbdModelCfg

        cfg = AbdModelCfg(2, 2, network=Network.new_ordered())
        model = cfg.into_model()
        assert model.flow_pairs is not None
        assert len(model.flow_pairs) == 10  # 12 directed minus 2 c<->c
        dev = _tpu(cfg)
        assert dev.unique_state_count() == 620  # full host enumeration
        dev.assert_properties()

    def test_pack_state_rejects_excluded_flow(self):
        import pytest as _pytest

        from stateright_tpu.actor import Id
        from stateright_tpu.actor.network import Envelope
        from stateright_tpu.models.linearizable_register import AbdModelCfg

        model = AbdModelCfg(2, 2, network=Network.new_ordered()).into_model()
        state = model.init_states()[0]
        # Forge a client->client message (pair excluded by construction).
        state.network.send(Envelope(src=Id(2), dst=Id(3), msg=object()))
        with _pytest.raises(ValueError, match="flow_pairs"):
            model.pack_state(state)

    def test_symmetry_with_flow_pairs_refused(self):
        import pytest as _pytest

        from stateright_tpu.models.linearizable_register import AbdModelCfg

        model = AbdModelCfg(2, 2, network=Network.new_ordered()).into_model()
        with _pytest.raises(NotImplementedError):
            model.packed_symmetry()

    def test_duplicate_pairs_rejected(self):
        import pytest as _pytest

        from stateright_tpu.models.linearizable_register import AbdModelCfg

        model = AbdModelCfg(2, 2).into_model()  # unordered: pairs unset
        assert model.flow_pairs is None
        with _pytest.raises(ValueError, match="duplicates"):
            model.with_flow_pairs([(0, 1), (0, 1)])

    def test_ordered_single_copy_host_device_parity(self):
        # Review finding (r4): ordered single-copy had no parity coverage
        # for its restricted pairs + provably-safe flow depth. The host
        # model is unrestricted, so agreement IS the exactness proof.
        from collections import deque

        from stateright_tpu.models.single_copy_register import (
            SingleCopyModelCfg,
        )

        cfg = SingleCopyModelCfg(2, 1, network=Network.new_ordered())
        host_model = cfg.into_model()
        seen = set()
        q = deque(host_model.init_states())
        for s in q:
            seen.add(s)
        n = 0
        acts = []
        while q:
            s = q.popleft()
            n += 1
            acts.clear()
            host_model.actions(s, acts)
            for a in acts:
                ns = host_model.next_state(s, a)
                if ns is not None and ns not in seen:
                    seen.add(ns)
                    q.append(ns)
        dev = _tpu(cfg)
        assert dev.unique_state_count() == n
        dev.assert_properties()

    def test_multi_server_ordered_abd_keeps_conservative_depth(self):
        # Review finding (r4): with 3+ servers the quorum can complete
        # ops while a laggard replica's server->server FIFO accumulates
        # (4c/3s reaches depth 5 within 22K states), so only the
        # 2-server quorum==all case gets the measured-exact depth 2.
        from stateright_tpu.models.linearizable_register import AbdModelCfg

        multi = AbdModelCfg(4, 3, network=Network.new_ordered()).into_model()
        assert multi.flow_capacity == 8
        two = AbdModelCfg(3, 2, network=Network.new_ordered()).into_model()
        assert two.flow_capacity == 2
