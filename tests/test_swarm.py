"""Swarm verification engine (checker/swarm.py): seeded determinism,
preempt/resume and packed-vs-solo bit-identity, the frontier-seeded
hybrid handoff, service mode="swarm" integration, and the sharded-KV
zoo model's host/device parity.

The determinism contract under test is the acceptance criterion: same
seed => bit-identical discoveries and walk counts across
``wave_steps`` chunking, across preempt/resume, and packed vs solo —
the stop decision lives INSIDE the fused scan, so wave boundaries can
never influence results.
"""

import io

import pytest

from stateright_tpu.checker.swarm import (
    SwarmPackedEngine,
    frontier_seeds_from_payload,
)
from stateright_tpu.models.sharded_kv import ShardedKv
from stateright_tpu.models.two_phase_commit import TwoPhaseSys

# One model instance + AOT namespace for the whole module: the wave-fn
# cache keys on model IDENTITY (checker/swarm.py), so same-shape tests
# reuse one compiled scan instead of paying ~2s of jit each (the
# tier-1 budget rule).
MODEL_2PC3 = TwoPhaseSys(3)
SWARM_KW = dict(lanes=64, sample_capacity=1 << 12, aot_cache="t-swarm")


def _fingerprint_result(ck):
    """Everything the determinism contract covers, as one comparable
    value: discovery fingerprint trails, walk/step counts, the coverage
    sample, and depth."""
    return (
        ck.state_count(),
        ck.unique_state_count(),
        ck.max_depth(),
        dict(ck._discoveries_fps),
        ck.coverage_estimate()["saturated"],
    )


def _solo(seed, wave_steps=32, target=20_000, **kw):
    ck = (
        MODEL_2PC3
        .checker()
        .target_state_count(target)
        .spawn_swarm(seed=seed, wave_steps=wave_steps, **SWARM_KW, **kw)
        .join()
    )
    assert ck.worker_error() is None
    return ck


def test_swarm_finds_sometimes_properties():
    ck = _solo(seed=7, target=50_000)
    paths = ck.discoveries()
    assert "abort agreement" in paths and "commit agreement" in paths
    for name, path in paths.items():
        final = path.last_state()
        if name == "abort agreement":
            assert all(s == "Aborted" for s in final.rm_state)
        if name == "commit agreement":
            assert all(s == "Committed" for s in final.rm_state)


def test_swarm_unique_sample_is_honest():
    # 2pc-3 has 288 reachable states; an unsaturated sample must never
    # exceed that, and the walk-step total is not the unique count.
    ck = _solo(seed=7, target=50_000)
    est = ck.coverage_estimate()
    assert not est["saturated"]
    assert 0 < ck.unique_state_count() <= 288
    assert ck.state_count() >= 50_000 > ck.unique_state_count()


def test_swarm_deterministic_across_wave_steps():
    a = _fingerprint_result(_solo(seed=11, wave_steps=16))
    b = _fingerprint_result(_solo(seed=11, wave_steps=128))
    assert a == b


def test_swarm_deterministic_across_preempt_resume():
    import time

    reference = _fingerprint_result(_solo(seed=11, wave_steps=16))
    ck = (
        MODEL_2PC3
        .checker()
        .target_state_count(20_000)
        .spawn_swarm(seed=11, wave_steps=16, **SWARM_KW)
    )
    time.sleep(0.05)
    ck.request_preempt()
    ck.join()
    if not ck.preempted:
        pytest.skip("run finished before the preempt landed")
    resumed = (
        MODEL_2PC3
        .checker()
        .target_state_count(20_000)
        .spawn_swarm(
            seed=11, wave_steps=16, resume_from=ck.preempt_payload(),
            **SWARM_KW,
        )
        .join()
    )
    assert resumed.worker_error() is None
    assert _fingerprint_result(resumed) == reference


def test_swarm_packed_vs_solo_bit_identical():
    model = MODEL_2PC3
    eng = SwarmPackedEngine(
        model, lanes=64, wave_steps=16, max_trace_len=512,
        sample_capacity=1 << 12, max_tenants=2,
    )
    v1 = eng.admit("j1", seed=11, target_state_count=20_000)
    v2 = eng.admit("j2", seed=12, target_state_count=20_000)
    done = set()
    for _ in range(500):
        done |= set(eng.step())
        if len(done) == 2:
            break
    assert done == {"j1", "j2"}
    for view, seed in ((v1, 11), (v2, 12)):
        solo = _solo(seed=seed, wave_steps=16)
        assert (
            view.state_count(),
            view.unique_state_count(),
            view.max_depth(),
            dict(view._fps),
        ) == (
            solo.state_count(),
            solo.unique_state_count(),
            solo.max_depth(),
            dict(solo._discoveries_fps),
        )
        # Packed discovery paths replay through the host model too.
        for path in view.discoveries().values():
            assert len(path) >= 1
    eng.release("j1")
    eng.release("j2")


def test_swarm_pack_drop_resumes_solo_bit_identical():
    model = MODEL_2PC3
    eng = SwarmPackedEngine(
        model, lanes=64, wave_steps=16, max_trace_len=512,
        sample_capacity=1 << 12, max_tenants=2,
    )
    eng.admit("j1", seed=11, target_state_count=20_000)
    eng.step()  # one wave in the pack
    payload = eng.drop("j1")
    assert payload is not None and payload["kind"] == "swarm"
    resumed = (
        MODEL_2PC3
        .checker()
        .target_state_count(20_000)
        .spawn_swarm(
            seed=11, wave_steps=16, resume_from=payload, **SWARM_KW
        )
        .join()
    )
    assert resumed.worker_error() is None
    assert _fingerprint_result(resumed) == _fingerprint_result(
        _solo(seed=11, wave_steps=16)
    )


def test_swarm_finds_violation_exhaustive_confirms():
    # The known-violation hunt: the unguarded sharded KV's torn-write
    # race. The swarm must find it, the exhaustive checker must agree
    # it exists, and the swarm's counterexample must replay to a
    # genuinely torn state.
    swarm = (
        ShardedKv(2, 2, 1, guarded=False)
        .checker()
        .target_state_count(100_000)
        .spawn_swarm(seed=5, wave_steps=32, **SWARM_KW)
        .join()
    )
    assert swarm.worker_error() is None
    path = swarm.discoveries().get("no torn writes")
    assert path is not None, "swarm missed the torn-write violation"
    assert any(path.last_state().torn)
    exhaustive = (
        ShardedKv(2, 2, 1, guarded=False).checker().spawn_bfs().join()
    )
    assert "no torn writes" in exhaustive.discoveries()


def test_swarm_hybrid_frontier_seeding():
    import time

    # A budget-exhausted exhaustive run hands its live frontier to the
    # swarm as restart seeds; seeded discoveries replay as fragments
    # from their seed state.
    bfs = MODEL_2PC3.checker().spawn_tpu_bfs(
        frontier_capacity=1 << 6, table_capacity=1 << 10,
        max_drain_waves=1,
    )
    bfs.request_preempt()
    time.sleep(0.02)
    bfs.join()
    if not bfs.preempted:
        pytest.skip("exhaustive run finished before the preempt landed")
    payload = bfs.preempt_payload()
    seeds = frontier_seeds_from_payload(MODEL_2PC3, payload)
    ck = (
        MODEL_2PC3
        .checker()
        .target_state_count(30_000)
        .spawn_swarm(seed=9, wave_steps=32, seeds=seeds, **SWARM_KW)
        .join()
    )
    assert ck.worker_error() is None
    # Spawning straight from the payload dict is the one-liner form.
    ck2 = (
        MODEL_2PC3
        .checker()
        .target_state_count(5_000)
        .spawn_swarm(seed=9, wave_steps=32, seeds=payload, **SWARM_KW)
        .join()
    )
    assert ck2.worker_error() is None
    for path in ck.discoveries().values():
        assert len(path) >= 1  # replays from its seed state


def test_swarm_trace_overflow_counted_and_reported():
    # Walks deeper than the trace buffer (no user depth cap) are
    # truncated: counted, and warned about at run end.
    ck = (
        MODEL_2PC3
        .checker()
        .target_state_count(5_000)
        .spawn_swarm(
            seed=3, wave_steps=32, max_trace_len=4, lanes=64,
            sample_capacity=1 << 12,
        )
        .join()
    )
    assert ck.worker_error() is None
    assert ck._trace_overflows > 0
    snap = ck.metrics().snapshot()
    assert snap.get("swarm.trace_overflow", 0) > 0
    out = io.StringIO()
    from stateright_tpu.report import WriteReporter

    ck.report(WriteReporter(out))
    assert "truncated at the trace buffer" in out.getvalue()


def test_swarm_no_overflow_under_semantic_depth_cap():
    # A user depth cap IS the buffer bound: capped walks are a semantic
    # choice, not truncation — no warning, no counter.
    ck = (
        MODEL_2PC3
        .checker()
        .target_max_depth(4)
        .target_state_count(3_000)
        .spawn_swarm(seed=3, wave_steps=16, **SWARM_KW)
        .join()
    )
    assert ck.worker_error() is None
    assert ck.max_depth() <= 4
    assert ck._trace_overflows == 0


def test_swarm_coverage_ledger_counts_walk_actions():
    ck = (
        MODEL_2PC3
        .checker()
        .target_state_count(20_000)
        .spawn_swarm(seed=7, wave_steps=32, coverage=True, **SWARM_KW)
        .join()
    )
    assert ck.worker_error() is None
    rep = ck.coverage_report()
    assert rep is not None
    table = rep["actions"]["table"]
    assert table["TmAbort"]["fired"] > 0
    assert table["RmPrepare_0"]["fired"] > 0
    # 2pc-3's actions are all live in the reachable space; a healthy
    # walk budget fires every one of them.
    assert rep["vacuity"]["dead_actions"] == []


def test_swarm_coverage_resume_does_not_double_count():
    # The restored carry's cov vector is cumulative; the previous
    # incarnation already consumed it into the run_id's registry, so a
    # resume must baseline its delta there — not re-inc the whole
    # prefix (regression: resumed coverage runs inflated action_fired).
    from stateright_tpu.telemetry import metrics_registry

    def fired_total(run_id):
        reg = metrics_registry(run_id)
        return sum(
            value
            for name, value in reg.snapshot().items()
            if name.startswith("swarm.coverage.action_fired.")
        )

    ck = (
        MODEL_2PC3
        .checker()
        .target_state_count(20_000)
        .spawn_swarm(
            seed=13, wave_steps=16, coverage=True, run_id="t-swarm-cov-a",
            **SWARM_KW,
        )
        .join()
    )
    assert ck.worker_error() is None
    reference = fired_total("t-swarm-cov-a")
    assert reference > 0

    import time

    first = (
        MODEL_2PC3
        .checker()
        .target_state_count(20_000)
        .spawn_swarm(
            seed=13, wave_steps=16, coverage=True, run_id="t-swarm-cov-b",
            **SWARM_KW,
        )
    )
    time.sleep(0.05)
    first.request_preempt()
    first.join()
    if not first.preempted:
        pytest.skip("run finished before the preempt landed")
    resumed = (
        MODEL_2PC3
        .checker()
        .target_state_count(20_000)
        .spawn_swarm(
            seed=13, wave_steps=16, coverage=True, run_id="t-swarm-cov-b",
            resume_from=first.preempt_payload(), **SWARM_KW,
        )
        .join()
    )
    assert resumed.worker_error() is None
    # The walk sequence is bit-identical (the determinism contract), so
    # the run-scoped registry totals must match exactly — any excess is
    # the pre-preempt prefix counted twice.
    assert fired_total("t-swarm-cov-b") == reference


def test_swarm_rejections():
    with pytest.raises(NotImplementedError):
        MODEL_2PC3.checker().symmetry().spawn_swarm(seed=1)
    from stateright_tpu import FnModel

    def fn(prev, out):
        if prev is None:
            out.append(0)

    with pytest.raises(TypeError):
        FnModel(fn).checker().spawn_swarm(seed=1)
    # Resuming a swarm payload into a different fleet shape is refused.
    ck = (
        MODEL_2PC3
        .checker()
        .target_state_count(2_000)
        .spawn_swarm(seed=1, wave_steps=8, **SWARM_KW)
    )
    ck.request_preempt()
    ck.join()
    if ck.preempted:
        with pytest.raises(ValueError):
            MODEL_2PC3.checker().spawn_swarm(
                seed=1, wave_steps=8, lanes=128,
                sample_capacity=1 << 12,
                resume_from=ck.preempt_payload(),
            )


# -- service integration ----------------------------------------------------


def test_service_swarm_jobs_pack_and_match_solo():
    from stateright_tpu.service.service import CheckService

    svc = CheckService(quantum_s=10.0)
    try:
        h1 = svc.submit(
            model_name="2pc", model_args={"rm_count": 3},
            options={"target_state_count": 10_000},
            mode="swarm", seed=21,
        )
        h2 = svc.submit(
            model_name="2pc", model_args={"rm_count": 3},
            options={"target_state_count": 10_000},
            mode="swarm", seed=22,
        )
        r1 = h1.result(timeout=180)
        r2 = h2.result(timeout=180)
        s1, s2 = h1.status(), h2.status()
        assert s1["mode"] == "swarm" and s1["seed"] == 21
        assert s1["packable"] and s2["packable"]
        assert s1["packed"] or s2["packed"]
        assert s1["preemptible"] is True
        # Packed verdicts == the solo run at the service's fleet shape.
        solo = (
            MODEL_2PC3
            .checker()
            .target_state_count(10_000)
            .spawn_swarm(
                seed=21,
                **{
                    k: v
                    for k, v in svc.default_swarm_spawn.items()
                },
            )
            .join()
        )
        assert r1["states"] == solo.state_count()
        assert r1["unique"] == solo.unique_state_count()
        # Discovery sets match the solo run too (which ones were found
        # is workload-dependent at this small target; identity is the
        # contract).
        assert sorted(r1["discoveries"]) == sorted(
            solo._discoveries_fps
        )
        assert r2["states"] > 0
    finally:
        svc.close()


def test_service_swarm_classification_and_rejections():
    from stateright_tpu.service.service import CheckService

    svc = CheckService()
    try:
        with pytest.raises(ValueError):
            svc.submit(model_name="2pc", mode="warm")  # typo'd mode
        with pytest.raises(ValueError):
            svc.submit(
                model_name="2pc", mode="swarm", hbm_budget_mib=64
            )
        with pytest.raises(ValueError):
            # Known-at-admission conflict: rejected at submit, not as
            # a retried mid-run NotImplementedError.
            svc.submit(
                model_name="2pc", mode="swarm",
                options={"symmetry": True},
            )
        with pytest.raises(ValueError):
            # No stop bound at all: 2pc's holding always-property is
            # never "discovered", so the walk would sample forever —
            # rejected at submit, not left occupying the device.
            svc.submit(model_name="2pc", mode="swarm")
        with pytest.raises(ValueError):
            # int32 walk-carry range enforced at admission, not as a
            # mid-run failure burning the packed path's retry budget.
            svc.submit(
                model_name="2pc", mode="swarm",
                options={"target_state_count": 2**31},
            )
        # timeout_s alone is an acceptable bound (the job would end
        # with partial-progress evidence instead of running unbounded);
        # cancel right away — admission is what's under test.
        svc.submit(
            model_name="2pc", mode="swarm", timeout_s=30.0,
        ).cancel()
        h = svc.submit(
            model_name="2pc", model_args={"rm_count": 3},
            options={"target_state_count": 2_000},
            spawn={"lanes": 32, "sample_capacity": 1 << 10},
            mode="swarm", seed=1,
        )
        st = h.status()
        # A fleet-shape override honestly disqualifies packing.
        assert st["packable"] is False
        assert "spawn overrides" in st["packable_reason"]
        assert h.result(timeout=120)["states"] > 0
    finally:
        svc.close()


def test_swarm_pack_same_wave_fault_does_not_lose_completion():
    # Tenant A finishes in the SAME wave whose harvest faults for B:
    # the raised TenantFaultError discards that step()'s done list, so
    # A's completion must stay reportable (and A must keep counting as
    # live) or the service strands a finished job in RUNNING forever.
    from stateright_tpu.utils.faults import (
        FaultSpec,
        TenantFaultError,
        inject,
    )

    eng = SwarmPackedEngine(
        MODEL_2PC3, lanes=64, wave_steps=64, max_trace_len=512,
        sample_capacity=1 << 12, max_tenants=2,
    )
    eng.admit("A", seed=11, target_state_count=100)  # stops in wave 1
    eng.admit("B", seed=12, target_state_count=1_000_000)
    with inject(FaultSpec("swarm.tenant.verdict", at=0, tenant="B")):
        with pytest.raises(TenantFaultError):
            eng.step()
    eng.drop("B")  # what the service's blast-radius handler does
    assert eng.live_count() >= 1
    assert "A" in eng.step()
    eng.release("A")
    assert eng.free_slots() == 2


def test_swarm_rejects_int32_overflowing_target():
    with pytest.raises(ValueError):
        (MODEL_2PC3.checker().target_state_count(2**31)
         .spawn_swarm(seed=1, **SWARM_KW))


def test_swarm_pack_tenant_fault_blast_radius():
    from stateright_tpu.service.service import CheckService
    from stateright_tpu.utils.faults import FaultSpec, inject

    # A per-tenant harvest fault drops ONLY that tenant (it retries
    # from its wave boundary); the surviving tenant's verdict is still
    # bit-identical to its solo run.
    with inject(
        FaultSpec("swarm.tenant.verdict", at=0, tenant="fault-job")
    ):
        svc = CheckService(quantum_s=10.0)
        try:
            h1 = svc.submit(
                model_name="2pc", model_args={"rm_count": 3},
                options={"target_state_count": 10_000},
                mode="swarm", seed=21, job_id="fault-job",
            )
            h2 = svc.submit(
                model_name="2pc", model_args={"rm_count": 3},
                options={"target_state_count": 10_000},
                mode="swarm", seed=22,
            )
            r1 = h1.result(timeout=180)
            r2 = h2.result(timeout=180)
            assert h1.status()["retries"] >= 1
            solo = (
                MODEL_2PC3
                .checker()
                .target_state_count(10_000)
                .spawn_swarm(
                    seed=21, **dict(svc.default_swarm_spawn)
                )
                .join()
            )
            # The faulted job recovered to the exact solo verdict.
            assert r1["states"] == solo.state_count()
            assert r1["unique"] == solo.unique_state_count()
            assert r2["states"] > 0
        finally:
            svc.close()


# -- honest capability surfacing --------------------------------------------


def test_simulation_backends_report_capabilities():
    from stateright_tpu.checker.simulation import SimulationChecker
    from stateright_tpu.checker.swarm import SwarmChecker
    from stateright_tpu.checker.tpu_simulation import TpuSimulationChecker

    assert SwarmChecker.supports_preempt is True
    assert SwarmChecker.supports_packing is True
    for cls in (SimulationChecker, TpuSimulationChecker):
        assert cls.supports_preempt is False
        assert cls.supports_packing is False
        assert cls.packing_reason


def test_swarm_wave_cache_keys_on_model_identity():
    # Same aot_cache namespace + identical packed SHAPES but different
    # transition logic (guarded vs unguarded ShardedKv) must never
    # share a compiled wave fn — the guarded model verified with the
    # unguarded kernel would report a violation against the fixed
    # protocol.
    unguarded = (
        ShardedKv(2, 2, 1, guarded=False)
        .checker()
        .target_state_count(50_000)
        .spawn_swarm(seed=5, wave_steps=32, aot_cache="t-collide",
                     lanes=64, sample_capacity=1 << 12)
        .join()
    )
    assert "no torn writes" in unguarded._discoveries_fps
    guarded = (
        ShardedKv(2, 2, 1, guarded=True)
        .checker()
        .target_state_count(3_000)
        .spawn_swarm(seed=5, wave_steps=32, aot_cache="t-collide",
                     lanes=64, sample_capacity=1 << 12)
        .join()
    )
    assert "no torn writes" not in guarded._discoveries_fps
    assert "no total tear" not in guarded._discoveries_fps


def test_swarm_metric_family_hygiene():
    # The swarm.* family (engine counters + per-tenant view counters +
    # the shared trace_overflow name) must export to distinct,
    # grammar-legal Prometheus names — the PR 8 lint, extended to the
    # new family.
    from stateright_tpu.telemetry.metrics import MetricsRegistry
    from stateright_tpu.telemetry.server import registry_hygiene_problems

    reg = MetricsRegistry()
    for name in (
        "swarm.wave_calls", "swarm.walk_steps", "swarm.walks_completed",
        "swarm.restarts", "swarm.restarts_deduped",
        "swarm.trace_overflow", "swarm.unique_sample",
    ):
        reg.counter(name)
    reg.gauge("swarm.sample_saturated")
    reg.gauge("swarm.sample_occupancy")
    reg.histogram("swarm.hit_depth")
    assert registry_hygiene_problems(reg) == []


# -- the sharded-KV zoo model ------------------------------------------------


def test_sharded_kv_host_device_parity_guarded():
    # Guarded: the always-property holds, so both engines explore the
    # full space — counts and discoveries must match exactly.
    host = ShardedKv(2, 2, 1, guarded=True).checker().spawn_bfs().join()
    dev = (
        ShardedKv(2, 2, 1, guarded=True)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=1 << 8, table_capacity=1 << 12)
        .join()
    )
    assert host.unique_state_count() == dev.unique_state_count()
    assert sorted(host.discoveries()) == sorted(dev.discoveries()) == [
        "fully migrated", "saturated writes",
    ]
    assert "no torn writes" not in host.discoveries()


def test_sharded_kv_vacuity_clean_coverage():
    ck = (
        ShardedKv(2, 2, 1, guarded=True)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 8, table_capacity=1 << 12,
            coverage=True,
        )
        .join()
    )
    rep = ck.coverage_report()
    vac = rep["vacuity"]
    assert vac["dead_actions"] == []
    assert vac["unexercised_always"] == []
    assert vac["undiscovered_sometimes"] == []


def test_sharded_kv_in_zoo():
    from stateright_tpu.service.zoo import default_zoo

    model = default_zoo()["sharded_kv"](shards=2, keys=2, max_version=1)
    assert model.packed_action_count() == 2 * (2 + 2)


def test_sharded_kv_retain_filters_consistently():
    m = ShardedKv(2, 2, 1, retain=("no total tear",))
    assert [p.name for p in m.properties()] == ["no total tear"]
    assert len(m.packed_conditions()) == 1
    assert len(m.packed_antecedents()) == 1
    with pytest.raises(ValueError):
        ShardedKv(2, 2, 1, retain=("no such property",)).properties()
    # The deep violation is reachable in the small config too, and the
    # retained model's run ends exactly at that discovery.
    ck = (
        m.checker()
        .target_state_count(200_000)
        .spawn_swarm(seed=5, wave_steps=32, **SWARM_KW)
        .join()
    )
    assert ck.worker_error() is None
    path = ck.discoveries().get("no total tear")
    assert path is not None and all(path.last_state().torn)
