"""Simulation + on-demand checker tests (parity with reference test intent)."""

from fixtures import BinaryClock, LinearEquation
from stateright_tpu import Property


def test_simulation_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_simulation(0).join()
    checker.assert_properties()
    checker.assert_discovery("solvable", ["IncreaseX", "IncreaseY", "IncreaseX"])


def test_simulation_detects_loop_and_checks_eventually():
    # BinaryClock cycles forever; eventually-prop "is high" fails on the
    # looping trace that never goes high... but every trace alternates, so it
    # is satisfied. Use a sometimes property to terminate instead.
    class Clock2(BinaryClock):
        def properties(self):
            return [Property.sometimes("high", lambda _, s: s == 1)]

    checker = Clock2().checker().spawn_simulation(42).join()
    checker.assert_any_discovery("high")


def test_simulation_respects_target_state_count():
    # No discoveries possible: terminates only via target_state_count.
    class Unsolvable(LinearEquation):
        def properties(self):
            return [Property.sometimes("never", lambda _m, _s: False)]

    checker = (
        Unsolvable(2, 4, 7)
        .checker()
        .target_state_count(500)
        .spawn_simulation(7)
        .join()
    )
    assert checker.state_count() >= 500


def test_on_demand_run_to_completion():
    checker = LinearEquation(2, 10, 14).checker().spawn_on_demand()
    assert not checker.is_done()
    checker.run_to_completion()
    checker.join()
    checker.assert_properties()
    assert checker.unique_state_count() == 12


def test_on_demand_check_fingerprint_expands_one_state():
    from stateright_tpu import fingerprint

    checker = LinearEquation(2, 4, 7).checker().spawn_on_demand()
    # Ask for the init state: workers expand just that state.
    checker.check_fingerprint(fingerprint((0, 0)))
    import time

    deadline = time.monotonic() + 5.0
    while checker.unique_state_count() < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    # (0,0) expanded into (1,0) and (0,1) but nothing deeper yet.
    assert checker.unique_state_count() == 3
    # Now expand one of the children.
    checker.check_fingerprint(fingerprint((1, 0)))
    deadline = time.monotonic() + 5.0
    while checker.unique_state_count() < 5 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert checker.unique_state_count() == 5  # + (2,0), (1,1)
