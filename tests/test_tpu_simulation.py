"""TPU simulation checker: vmapped random-walk lanes.

Mirrors the host simulation test strategy (discovery validity, not exact
counts — random walks are approximate by design); discovery paths must
replay through the host model like every device checker's.
"""

import pytest

from stateright_tpu.models.two_phase_commit import TwoPhaseSys


def test_tpu_simulation_finds_sometimes_properties():
    # 2pc's holding "consistent" always-property can never be discovered, so
    # (like the reference) simulation would sample forever without a target.
    checker = (
        TwoPhaseSys(3)
        .checker()
        .target_state_count(50_000)
        .spawn_tpu_simulation(seed=7, lanes=128, steps_per_call=32)
        .join()
    )
    assert checker.worker_error() is None
    paths = checker.discoveries()
    assert "abort agreement" in paths and "commit agreement" in paths


def test_tpu_simulation_respects_target_state_count():
    checker = (
        TwoPhaseSys(3)
        .checker()
        .target_state_count(5_000)
        .spawn_tpu_simulation(seed=3, lanes=64, steps_per_call=16)
        .join()
    )
    assert checker.worker_error() is None
    assert checker.state_count() >= 1
    assert checker.unique_state_count() == checker.state_count()


def test_tpu_simulation_discovery_paths_replay():
    checker = (
        TwoPhaseSys(3)
        .checker()
        .target_state_count(20_000)
        .spawn_tpu_simulation(seed=11, lanes=256, steps_per_call=32)
        .join()
    )
    assert checker.worker_error() is None
    for name, path in checker.discoveries().items():
        final = path.last_state()
        if name == "abort agreement":
            assert all(s == "Aborted" for s in final.rm_state)
        if name == "commit agreement":
            assert all(s == "Committed" for s in final.rm_state)


def test_tpu_simulation_max_depth_cap():
    checker = (
        TwoPhaseSys(3)
        .checker()
        .target_max_depth(4)
        .target_state_count(2_000)
        .spawn_tpu_simulation(seed=5, lanes=64, steps_per_call=16)
        .join()
    )
    assert checker.worker_error() is None
    assert checker.max_depth() <= 4


def test_tpu_simulation_trace_overflow_counted_and_reported():
    # Lanes overflowing the trace buffer with NO user depth cap were
    # silently aborted like a depth-cap; now they are counted
    # (swarm.trace_overflow) and the run-end reporter warns, so
    # truncation is never mistaken for absence of discoveries.
    import io

    from stateright_tpu.report import WriteReporter

    checker = (
        TwoPhaseSys(3)
        .checker()
        .target_state_count(5_000)
        .spawn_tpu_simulation(
            seed=3, lanes=64, steps_per_call=16, max_trace_len=4
        )
        .join()
    )
    assert checker.worker_error() is None
    assert checker._trace_overflows > 0
    assert checker.metrics().snapshot().get("swarm.trace_overflow", 0) > 0
    out = io.StringIO()
    checker.report(WriteReporter(out))
    assert "truncated at the trace buffer" in out.getvalue()


def test_tpu_simulation_depth_cap_is_not_overflow():
    # An explicit target_max_depth IS the buffer bound — a semantic
    # choice, not truncation: no counter, no warning.
    checker = (
        TwoPhaseSys(3)
        .checker()
        .target_max_depth(4)
        .target_state_count(2_000)
        .spawn_tpu_simulation(seed=5, lanes=64, steps_per_call=16)
        .join()
    )
    assert checker.worker_error() is None
    assert checker._trace_overflows == 0


def test_tpu_simulation_rejects_symmetry():
    with pytest.raises(NotImplementedError):
        TwoPhaseSys(3).checker().symmetry().spawn_tpu_simulation(seed=1)


def test_tpu_simulation_rejects_non_batchable():
    from stateright_tpu import FnModel

    def fn(prev, out):
        if prev is None:
            out.append(0)
        elif prev < 3:
            out.append(prev + 1)

    with pytest.raises(TypeError):
        FnModel(fn).checker().spawn_tpu_simulation(seed=1)
