"""Equivalence pins for the fingerprint-only expansion path.

The fps wave (``TpuBfsChecker`` with ``expand_fps``) dedups on candidate
fingerprints computed from per-transition deltas (``packed_expand_fps``)
and materializes only fresh lanes (``packed_take``) — candidate states
never exist as arrays. Correctness rests on three exact contracts, pinned
here lane-for-lane across the model families (deliver / drop / timeout /
crash classes, ordered / unordered / duplicating networks, histories):

1. ``packed_expand_fps`` fingerprints == ``packed_fingerprint`` of the
   ``packed_expand`` candidate, on every valid lane;
2. ``packed_expand_fps`` validity == ``packed_expand`` validity AND the
   candidate's ``packed_within_boundary``;
3. ``packed_take(state, a)`` == the ``packed_expand`` candidate ``a``.

Plus checker-level oracles: the fps wave and the materializing wave agree
with the reference's exact counts (``examples/paxos.rs:325``,
``examples/linearizable-register.rs:286``) and with each other.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stateright_tpu.actor import Network
from stateright_tpu.models.linearizable_register import AbdModelCfg
from stateright_tpu.models.paxos import PaxosModelCfg
from stateright_tpu.models.raft import RaftModelCfg
from stateright_tpu.models.single_copy_register import SingleCopyModelCfg


def _frontier_states(m, waves=3, cap=400):
    """A few real BFS levels of packed states via the materializing path."""
    init = m.packed_init_states()
    states = [
        {k: np.asarray(v[i]) for k, v in init.items()}
        for i in range(len(m.init_states()))
    ]
    seen = set()
    out = []
    exp = jax.jit(m.packed_expand)
    wb = jax.jit(m.packed_within_boundary)
    frontier = states
    for _ in range(waves):
        nxt = []
        for st in frontier:
            cand, valid = exp({k: jnp.asarray(v) for k, v in st.items()})
            valid = np.asarray(valid)
            for a in range(valid.shape[0]):
                if not valid[a]:
                    continue
                child = {k: np.asarray(v[a]) for k, v in cand.items()}
                if not bool(wb({k: jnp.asarray(v) for k, v in child.items()})):
                    continue
                key = tuple((k, v.tobytes()) for k, v in sorted(child.items()))
                if key in seen:
                    continue
                seen.add(key)
                nxt.append(child)
        out.extend(frontier)
        frontier = nxt[:cap]
        if not frontier:
            break
    out.extend(frontier)
    return out[:cap]


FAMILIES = {
    "abd_ordered": lambda: AbdModelCfg(
        2, 2, network=Network.new_ordered(), envelope_capacity=8,
        flow_capacity=2,
    ).into_model(),
    "abd_unordered": lambda: AbdModelCfg(2, 2).into_model(),
    "single_copy": lambda: SingleCopyModelCfg(2, 1).into_model(),
    "paxos": lambda: PaxosModelCfg(2, 3).into_model(),
    "raft_lossy_timers": lambda: RaftModelCfg(
        3, max_term=1, lossy=True
    ).into_model(),
    "raft_crashes": lambda: RaftModelCfg(
        3, max_term=1, lossy=True, max_crashes=1
    ).into_model(),
}


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_fps_lane_equivalence(family):
    m = FAMILIES[family]()
    A = m.packed_action_count()
    aids = jnp.arange(A, dtype=jnp.int32)

    @jax.jit
    def oracle(stj):
        """Materializing path's view of one state: candidate fps, combined
        validity, and per-action packed_take rebuilds."""
        cand, valid = m.packed_expand(stj)
        valid = valid & jax.vmap(m.packed_within_boundary)(cand)
        fhi, flo = jax.vmap(m.packed_fingerprint)(cand)
        tk = jax.vmap(lambda a: m.packed_take(stj, a))(aids)
        return cand, valid, fhi, flo, tk

    j_fps = jax.jit(m.packed_expand_fps)
    checked = 0
    for st in _frontier_states(m, waves=2, cap=40):
        stj = {k: jnp.asarray(v) for k, v in st.items()}
        cand, valid, fhi, flo, tk = oracle(stj)
        hi, lo, v2 = j_fps(stj)
        valid = np.asarray(valid)
        assert np.array_equal(valid, np.asarray(v2)), (family, "validity")
        assert np.array_equal(
            np.asarray(fhi)[valid], np.asarray(hi)[valid]
        ), (family, "fingerprint hi")
        assert np.array_equal(
            np.asarray(flo)[valid], np.asarray(lo)[valid]
        ), (family, "fingerprint lo")
        for k in cand:
            assert np.array_equal(
                np.asarray(tk[k])[valid], np.asarray(cand[k])[valid]
            ), (family, k, "packed_take")
        checked += int(valid.sum())
    assert checked > 0, f"{family}: no valid candidates exercised"


@pytest.mark.parametrize(
    "cfg, expected",
    [
        (lambda: AbdModelCfg(2, 2).into_model(), 544),
        (lambda: SingleCopyModelCfg(2, 1).into_model(), 93),
        (lambda: PaxosModelCfg(2, 3).into_model(), 16_668),
    ],
    ids=["abd544", "scr93", "paxos16668"],
)
def test_fps_wave_oracle_counts(cfg, expected):
    c = (
        cfg()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=256, table_capacity=1 << 16)
        .join()
    )
    assert c.worker_error() is None, c.worker_error()
    assert c._use_fps, "actor models must auto-select the fps wave"
    assert c.unique_state_count() == expected
    c.assert_properties()


def test_fps_off_matches(two=None):
    """expand_fps=False forces the materializing wave; counts agree."""
    m = AbdModelCfg(2, 2).into_model()
    c = (
        m.checker()
        .spawn_tpu_bfs(
            frontier_capacity=256, table_capacity=1 << 13, expand_fps=False
        )
        .join()
    )
    assert c.worker_error() is None, c.worker_error()
    assert not c._use_fps
    assert c.unique_state_count() == 544


def test_fps_knob_validation():
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    with pytest.raises(ValueError, match="packed_expand_fps"):
        TwoPhaseSys(3).checker().spawn_tpu_bfs(
            frontier_capacity=64, table_capacity=1 << 10, expand_fps=True
        )


def test_fps_symmetry_yields_to_materializing_wave():
    """Symmetry needs candidate states for orbit keys: auto-detect must
    fall back, and forcing fps under symmetry must refuse."""
    m = RaftModelCfg(3, max_term=1, lossy=True).into_model()
    b = m.checker().symmetry()
    c = b.spawn_tpu_bfs(frontier_capacity=128, table_capacity=1 << 13)
    assert not c._use_fps
    c.join()
    assert c.worker_error() is None, c.worker_error()
    with pytest.raises(ValueError, match="symmetry"):
        m.checker().symmetry().spawn_tpu_bfs(
            frontier_capacity=128, table_capacity=1 << 13, expand_fps=True
        )
