"""Out-of-core tiered visited store (stateright_tpu.storage): unit tests
for the run/Bloom/store primitives, knob validation on the checkers, and
the tier-1 eviction smoke (an L0→L1 eviction on CPU, steady-state under
a second)."""

import math
import pickle
import time

import numpy as np
import pytest

from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.storage import (
    RUN_BLOCK,
    BloomFilter,
    FingerprintRun,
    TieredVisitedStore,
    decode_varint_u64,
    encode_varint_u64,
)
from stateright_tpu.telemetry import metrics_registry


def budget_for_table(rows: int) -> float:
    """The smallest hbm_budget_mib that admits a ``rows``-row table (plus
    the probe apron the allocation carries)."""
    return ((rows + 128) * 8) / (1 << 20)


def min_table_rows(frontier: int, actions: int, load=0.55) -> int:
    """The checker's own floor: one worst-case wave under the load cap."""
    return 1 << math.ceil(math.log2(frontier * actions / load + 1))


# -- varint codec ----------------------------------------------------------


def test_varint_roundtrip_random():
    rng = np.random.default_rng(7)
    vals = rng.integers(0, 1 << 63, 50_000, dtype=np.uint64)
    assert np.array_equal(decode_varint_u64(encode_varint_u64(vals)), vals)


def test_varint_roundtrip_edges():
    vals = np.array(
        [0, 1, 127, 128, (1 << 35) - 1, 1 << 35, (1 << 64) - 1],
        dtype=np.uint64,
    )
    assert np.array_equal(decode_varint_u64(encode_varint_u64(vals)), vals)
    assert encode_varint_u64(np.zeros(0, np.uint64)) == b""
    assert len(decode_varint_u64(b"")) == 0


# -- bloom filter ----------------------------------------------------------


def test_bloom_no_false_negatives_and_low_fp_rate():
    rng = np.random.default_rng(11)
    keys = np.unique(rng.integers(1, 1 << 62, 40_000, dtype=np.uint64))
    bf = BloomFilter.build(keys)
    assert bf.contains(keys).all()  # never a false negative
    probes = rng.integers(1, 1 << 62, 100_000, dtype=np.uint64)
    probes = probes[~np.isin(probes, keys)]
    assert bf.contains(probes).mean() < 0.02  # sized for <1% FP


# -- fingerprint runs ------------------------------------------------------


def test_run_probe_exact_and_block_boundaries():
    rng = np.random.default_rng(3)
    # Straddle block boundaries exactly (RUN_BLOCK and RUN_BLOCK + 1).
    for n in (5, RUN_BLOCK, RUN_BLOCK + 1, 3 * RUN_BLOCK + 17):
        keys = np.unique(rng.integers(1, 1 << 62, n, dtype=np.uint64))
        run = FingerprintRun.build(keys)
        assert np.array_equal(run.decode_all(), keys)
        q = np.concatenate(
            [keys[::3], rng.integers(1, 1 << 62, 999, dtype=np.uint64)]
        )
        assert np.array_equal(run.probe(q), np.isin(q, keys))


def test_run_checkpoint_roundtrip_and_corruption_rejected():
    rng = np.random.default_rng(5)
    keys = np.unique(rng.integers(1, 1 << 62, 9_000, dtype=np.uint64))
    run = FingerprintRun.build(keys)
    state = pickle.loads(pickle.dumps(run.to_state()))
    back = FingerprintRun.from_state(state)
    assert np.array_equal(back.decode_all(), keys)

    corrupt = dict(state)
    corrupt["payload"] = state["payload"][:-1] + b"\x00"
    with pytest.raises(ValueError, match="CRC"):
        FingerprintRun.from_state(corrupt)
    torn = dict(state)
    torn["count"] = state["count"] + 1
    with pytest.raises(ValueError, match="does not match its payload"):
        FingerprintRun.from_state(torn)
    torn["count"] = state["count"] + RUN_BLOCK  # changes the block count
    with pytest.raises(ValueError, match="block structure"):
        FingerprintRun.from_state(torn)


def test_run_spill_probe_uniform(tmp_path):
    rng = np.random.default_rng(9)
    keys = np.unique(rng.integers(1, 1 << 62, 12_000, dtype=np.uint64))
    run = FingerprintRun.build(keys)
    spilled = run.spill(str(tmp_path / "r.fpr"))
    q = np.concatenate(
        [keys[::5], rng.integers(1, 1 << 62, 2_000, dtype=np.uint64)]
    )
    assert np.array_equal(spilled.probe(q), run.probe(q))
    assert spilled.disk_nbytes > 0 and spilled.payload is None


# -- tiered store ----------------------------------------------------------


def test_store_merges_at_threshold_and_dedups_cross_run_twins():
    store = TieredVisitedStore(merge_run_threshold=3, prefix="t_merge")
    rng = np.random.default_rng(13)
    batch = rng.integers(1, 1 << 62, 5_000, dtype=np.uint64)
    store.evict(batch)
    store.evict(batch[: 2_000])  # duplicates of run 1
    assert len(store.l1) == 2
    store.evict(rng.integers(1, 1 << 62, 1_000, dtype=np.uint64))
    # Threshold hit: one merged run, cross-run twins deduped.
    assert len(store.l1) == 1
    assert store.l1[0].count < 5_000 + 2_000 + 1_000
    assert store.probe(np.unique(batch)).all()


def test_bloom_fp_audit_counters_within_configured_bound():
    """Audit counters for the probabilistic machinery: the two-phase
    probe emits ``*.storage.host_probe.bloom_probe_total`` /
    ``bloom_fp_total``, the OBSERVED false-positive rate stays under 2x
    the configured design bound (<1%, ``bloom.DESIGN_FP_RATE``), and the
    probe never drops a negative (a fresh key reported visited would
    silently lose a state) nor misses a positive (a visited key reported
    fresh would corrupt counts)."""
    from stateright_tpu.storage.bloom import DESIGN_FP_RATE

    store = TieredVisitedStore(prefix="t_bloom_audit")
    rng = np.random.default_rng(21)
    present = np.unique(rng.integers(1, 1 << 62, 50_000, dtype=np.uint64))
    store.evict(present)

    absent = rng.integers(1, 1 << 62, 60_000, dtype=np.uint64)
    absent = absent[~np.isin(absent, present)]
    # Exactness both ways: the Bloom layer only prefilters — the binary
    # search corrects every false positive before the checker sees it.
    assert not store.probe(absent).any()
    assert store.probe(present).all()

    reg = metrics_registry()
    probes = reg.counter(
        "t_bloom_audit.storage.host_probe.bloom_probe_total"
    ).snapshot()
    fps = reg.counter(
        "t_bloom_audit.storage.host_probe.bloom_fp_total"
    ).snapshot()
    assert probes >= len(absent)
    # present-key probes produce no FPs, so rate-vs-absent is the honest
    # denominator; with 60k absent probes the 2x margin is >25 sigma.
    assert fps / len(absent) < 2 * DESIGN_FP_RATE, (fps, len(absent))
    assert store.instruments.bench_stats()["bloom_fp_rate"] is not None


def test_store_spills_past_host_budget_and_probes_union(tmp_path):
    store = TieredVisitedStore(
        host_budget_mib=0.02, spill_dir=str(tmp_path), prefix="t_spill"
    )
    rng = np.random.default_rng(17)
    batches = [
        rng.integers(1, 1 << 62, 6_000, dtype=np.uint64) for _ in range(4)
    ]
    for b in batches:
        store.evict(b)
    assert store.l2, "host budget never spilled"
    allk = np.unique(np.concatenate(batches))
    assert store.probe(allk).all()
    miss = rng.integers(1, 1 << 62, 3_000, dtype=np.uint64)
    miss = miss[~np.isin(miss, allk)]
    assert not store.probe(miss).any()
    # Checkpoint round trip across spilled runs.
    state = pickle.loads(pickle.dumps(store.export_state()))
    back = TieredVisitedStore(prefix="t_spill_back")
    back.load_state(state)
    assert back.probe(allk).all()
    assert not back.probe(miss).any()


def test_store_compacts_l2_at_threshold(tmp_path):
    """L2 spill files merge once the threshold accumulates: a long
    tight-budget run must not grow fds and per-probe Bloom checks
    linearly with its eviction count."""
    store = TieredVisitedStore(
        host_budget_mib=0.001, spill_dir=str(tmp_path),
        merge_run_threshold=3, prefix="t_l2c",
    )
    rng = np.random.default_rng(23)
    batches = [
        rng.integers(1, 1 << 62, 4_000, dtype=np.uint64) for _ in range(7)
    ]
    for b in batches:
        store.evict(b)  # budget ~1KiB: every run spills immediately
    assert len(store.l2) < 3, f"L2 never compacted: {len(store.l2)} runs"
    # Retired spill files are deleted, survivors still answer exactly.
    import os

    assert len(os.listdir(tmp_path)) == len(store.l2)
    allk = np.unique(np.concatenate(batches))
    assert store.probe(allk).all()


def test_store_requires_spill_dir_with_host_budget():
    with pytest.raises(ValueError, match="spill_dir"):
        TieredVisitedStore(host_budget_mib=1.0, prefix="t_bad")


# -- checker knob validation ----------------------------------------------


def test_checker_rejects_host_budget_without_hbm_budget(tmp_path):
    with pytest.raises(ValueError, match="hbm_budget_mib"):
        TwoPhaseSys(3).checker().spawn_tpu_bfs(
            frontier_capacity=16, table_capacity=1 << 10,
            host_budget_mib=1.0, spill_dir=str(tmp_path),
        )


def test_checker_rejects_budget_below_one_wave():
    # One worst-case wave must fit a freshly-evicted table, or the
    # grow-and-retry loop could never terminate.
    with pytest.raises(ValueError, match="worst-case wave"):
        TwoPhaseSys(3).checker().spawn_tpu_bfs(
            frontier_capacity=1 << 10, table_capacity=1 << 10,
            hbm_budget_mib=0.001,
        )


# -- tier-1 eviction smoke -------------------------------------------------


def test_l0_eviction_smoke_fast():
    """An L0→L1 eviction end to end on CPU, steady-state under a second:
    the smallest admissible budget on 2pc-3 evicts on the first pregrow,
    and the run is capped after a handful of waves so the test budgets
    the eviction + probe machinery, not a full-space traversal (the
    equivalence suite owns exact-count preservation)."""
    m = TwoPhaseSys(3)
    rows = min_table_rows(16, m.packed_action_count())
    # Best of two: the first run in a fresh process pays one-time
    # tracing/dispatch costs the per-checker warmup stamp cannot fully
    # attribute; the second run is the steady-state figure the satellite
    # budget (<1s) is about.
    steady = []
    for _ in range(2):
        metrics_registry().reset()
        t0 = time.perf_counter()
        checker = (
            TwoPhaseSys(3)
            .checker()
            .target_state_count(150)
            .spawn_tpu_bfs(
                frontier_capacity=16,
                table_capacity=1 << 12,
                hbm_budget_mib=budget_for_table(rows),
            )
            .join()
        )
        wall = time.perf_counter() - t0
        assert checker.worker_error() is None
        assert 0 < checker.unique_state_count() <= 288
        snap = metrics_registry().snapshot()
        assert snap["tpu_bfs.storage.evictions"] >= 1
        assert snap["tpu_bfs.storage.probe_keys"] > 0
        steady.append(wall - (checker.warmup_seconds or 0.0))
        if steady[-1] < 1.0:
            break
    assert min(steady) < 1.0, (
        f"eviction smoke steady state took {min(steady):.2f}s"
    )
