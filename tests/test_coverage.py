"""State-space cartography (stateright_tpu.telemetry.coverage): ledger
units (vacuity, near-miss depth, revisit accounting, sanitization), the
device reduction layout, checker integration (2pc/ABD coverage-on vs
coverage-off bit-identical equivalence on both device backends + the
always-on host engines), the seeded-vacuity fixture flagged by
scripts/coverage_report.py (and 2pc clean), the run-end
undiscovered-property reporter lines, the monitor's coverage gauges/SSE,
the metric-registry hygiene lint, and the coverage-off overhead budget."""

import io
import json
import os
import re
import subprocess
import sys
import time

import jax.numpy as jnp
import pytest

from stateright_tpu import Model, Property, WriteReporter
from stateright_tpu.core.batch import BatchableModel
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.telemetry import get_tracer, metrics_registry
from stateright_tpu.telemetry.coverage import (
    CoverageLedger,
    DeviceCoverage,
    coverage_action_labels,
    sanitize_component,
)
from stateright_tpu.telemetry.metrics import MetricsRegistry
from stateright_tpu.telemetry.trace import Tracer

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COVERAGE_REPORT = os.path.join(REPO_DIR, "scripts", "coverage_report.py")


class VacuousChain(Model, BatchableModel):
    """The seeded-vacuity fixture: a 0→1→…→8 chain whose second action
    is never enabled anywhere (dead), whose ``always`` invariant has an
    antecedent that never fires (vacuous pass), and whose ``sometimes``
    target is unreachable (undiscovered)."""

    N = 8

    # -- host surface ------------------------------------------------------

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        if state < self.N:
            actions.append("step")

    def next_state(self, state, action):
        return state + 1

    def properties(self):
        return [
            Property.always(
                "guarded invariant",
                lambda m, s: True,
                antecedent=lambda m, s: s > m.N,
            ),
            Property.sometimes("reach the unreachable", lambda m, s: s == 100),
        ]

    # -- packed surface ----------------------------------------------------

    def packed_action_count(self):
        return 2

    def packed_action_labels(self):
        return ["step", "never_fires"]

    def packed_init_states(self):
        return {"x": jnp.zeros((1, 1), jnp.uint32)}

    def packed_step(self, state, action_id):
        x = state["x"]
        valid = (action_id == 0) & (x[0] < jnp.uint32(self.N))
        return {"x": jnp.where(valid, x + 1, x)}, valid

    def packed_conditions(self):
        return [
            lambda s: jnp.bool_(True),
            lambda s: s["x"][0] == jnp.uint32(100),
        ]

    def packed_antecedents(self):
        return [lambda s: s["x"][0] > jnp.uint32(self.N), None]


# -- ledger units ------------------------------------------------------------


def _props():
    return VacuousChain().properties()


def test_sanitize_component():
    assert sanitize_component("abort agreement") == "abort_agreement"
    assert sanitize_component("a/b:c?") == "a_b_c_"
    assert sanitize_component("") == "_"


def test_ledger_block_recording_and_vacuity():
    reg = MetricsRegistry()
    led = CoverageLedger(
        "t", _props(), action_labels=["step", "never_fires"],
        registry=reg, tracer=Tracer(),
    )
    led.record_seed(1)
    led.record_block(
        evaluated=9, terminals=1,
        fired={"step": 8}, fresh={"step": 8},
        exercised={}, succ_counts={1: 8, 0: 1},
        depth_counts={2: 4, 3: 4}, max_depth=9,
    )
    rep = led.report()
    assert rep["evaluated"] == 9
    assert rep["generated"] == 8
    assert rep["unique"] == 9  # seed + 8 fresh
    assert rep["terminal_states"] == 1
    assert rep["revisits"] == 0
    vac = rep["vacuity"]
    assert vac["dead_actions"] == ["never_fires"]
    assert vac["unexercised_always"] == ["guarded invariant"]
    assert vac["undiscovered_sometimes"] == ["reach the unreachable"]
    assert rep["vacuous"]
    # Near-miss depth: deepest frontier evaluated while unwitnessed.
    assert (
        rep["properties"]["reach the unreachable"]["near_miss_depth"] == 9
    )
    # Registry families: dead action exported as an explicit zero.
    snap = reg.snapshot()
    assert snap["t.coverage.action_fired.never_fires"] == 0
    assert snap["t.coverage.action_fired.step"] == 8
    assert snap["t.coverage.states_evaluated"] == 9


def test_ledger_revisits_and_never_new():
    led = CoverageLedger(
        "t", [], action_labels=["a", "b"],
        registry=MetricsRegistry(), tracer=Tracer(),
    )
    led.record_block(
        evaluated=4, terminals=0,
        fired={"a": 6, "b": 4}, fresh={"a": 5},
        exercised={}, succ_counts={}, depth_counts={1: 5},
    )
    rep = led.report()
    assert rep["revisits"] == 5
    assert rep["revisit_rate"] == pytest.approx(0.5)
    assert rep["actions"]["never_new"] == ["b"]
    assert rep["vacuity"]["dead_actions"] == []


def test_ledger_finalize_emits_summary_and_discovered_set():
    tracer = Tracer()
    props = [Property.sometimes("w", lambda m, s: True)]
    led = CoverageLedger(
        "t", props, registry=MetricsRegistry(), tracer=tracer
    )
    led.finalize(discovered={"w"})
    events = [e for e in tracer.events() if e["name"] == "t.coverage.summary"]
    assert len(events) == 1
    rep = events[0]["args"]["report"]
    assert rep["properties"]["w"]["discovered"] is True
    assert rep["vacuity"]["undiscovered_sometimes"] == []
    # Re-finalize (host workers): emits again, last one wins for readers.
    led.finalize(discovered=set())
    events = [e for e in tracer.events() if e["name"] == "t.coverage.summary"]
    assert len(events) == 2
    assert events[-1]["args"]["report"]["vacuity"]["undiscovered_sometimes"] == [
        "w"
    ]


def test_device_layout_wave_reduce():
    layout = DeviceCoverage(action_count=2, property_count=2)
    eval_mask = jnp.array([True, True, False])
    cvalid = jnp.array([[True, False], [True, True], [False, False]])
    fresh = jnp.array([True, False, True, False, False, False])
    lane_action = jnp.arange(6, dtype=jnp.int32) % 2
    new_depth = jnp.array([2, 2, 3, 3, 4, 4], jnp.int32)
    exercised = [
        jnp.array([True, False, False]),
        jnp.array([True, True, False]),
    ]
    vec = [int(x) for x in layout.wave_reduce(
        eval_mask=eval_mask, cvalid=cvalid, fresh=fresh,
        lane_action=lane_action, new_depth=new_depth, exercised=exercised,
    )]
    assert vec[0] == 2  # evaluated
    assert vec[1] == 0  # terminals (both eval lanes have a successor)
    assert vec[layout.s_fired] == [2, 1]
    assert vec[layout.s_fresh] == [2, 0]
    assert vec[layout.s_props] == [1, 2]
    # succ: lane0 has 1 (bin 0), lane1 has 2 (bin 1)
    assert vec[layout.s_succ] == [1, 1]
    depth_bins = vec[layout.s_depth]
    assert depth_bins[2] == 1 and depth_bins[3] == 1
    assert sum(depth_bins) == 2


def test_count_distinct_pairs():
    hi = jnp.array([1, 1, 2, 2, 3], jnp.uint32)
    lo = jnp.array([7, 7, 8, 9, 1], jnp.uint32)
    valid = jnp.array([True, True, True, True, False])
    assert int(DeviceCoverage.count_distinct(hi, lo, valid)) == 3
    assert int(
        DeviceCoverage.count_distinct(hi, lo, jnp.zeros((5,), bool))
    ) == 0


def test_coverage_action_labels_defaults_and_override():
    m = VacuousChain()
    assert coverage_action_labels(m, 2) == ["step", "never_fires"]

    class Bare(BatchableModel):
        def packed_action_count(self):
            return 3

    assert coverage_action_labels(Bare(), 3) == [
        "action_0", "action_1", "action_2"
    ]


# -- checker integration: bit-identical equivalence ---------------------------


def _golden(checker):
    out = io.StringIO()
    checker.report(WriteReporter(out))
    return re.sub(r"sec=\d+", "sec=_", out.getvalue())


@pytest.fixture(scope="module")
def base_2pc():
    reg = metrics_registry()
    waves0 = reg.counter("tpu_bfs.waves").snapshot()
    t0 = time.perf_counter()
    checker = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=1 << 7, table_capacity=1 << 12)
        .join()
    )
    secs = time.perf_counter() - t0
    waves = reg.counter("tpu_bfs.waves").snapshot() - waves0
    return checker, secs, waves


def test_tpu_coverage_bit_identical_2pc_deep_drain(base_2pc):
    base, _, _ = base_2pc
    cov = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 7, table_capacity=1 << 12, coverage=True
        )
        .join()
    )
    assert cov.unique_state_count() == base.unique_state_count()
    assert cov.state_count() == base.state_count()
    assert cov.max_depth() == base.max_depth()
    assert sorted(cov.discoveries()) == sorted(base.discoveries())
    assert _golden(cov) == _golden(base)
    rep = cov.coverage_report()
    assert rep["unique"] == cov.unique_state_count()
    assert sum(rep["shape"]["depth_hist"]) == cov.unique_state_count()
    assert not rep["vacuous"], rep["vacuity"]
    # Real labels via packed_action_labels.
    assert "TmCommit" in rep["actions"]["table"]


def test_tpu_coverage_bit_identical_2pc_wave_mode(base_2pc):
    base, _, _ = base_2pc
    cov = (
        TwoPhaseSys(4)
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 7,
            table_capacity=1 << 12,
            max_drain_waves=1,
            coverage=True,
        )
        .join()
    )
    assert cov.unique_state_count() == base.unique_state_count()
    assert cov.state_count() == base.state_count()
    assert _golden(cov) == _golden(base)
    rep = cov.coverage_report()
    assert sum(rep["shape"]["depth_hist"]) == cov.unique_state_count()


def test_tpu_coverage_bit_identical_abd_fps():
    """ABD register: the fps wave (expand_fps auto-on) with coverage on
    must match the coverage-off run exactly."""
    from stateright_tpu.models.linearizable_register import AbdModelCfg

    base = (
        AbdModelCfg(2, 2)
        .into_model()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=256, table_capacity=1 << 13)
        .join()
    )
    cov = (
        AbdModelCfg(2, 2)
        .into_model()
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=256, table_capacity=1 << 13, coverage=True
        )
        .join()
    )
    assert cov._use_fps and base._use_fps
    assert base.unique_state_count() == 544
    assert cov.unique_state_count() == 544
    assert cov.state_count() == base.state_count()
    assert cov.max_depth() == base.max_depth()
    rep = cov.coverage_report()
    assert rep["unique"] == 544
    assert sum(rep["shape"]["depth_hist"]) == 544


def test_sharded_coverage_bit_identical_2pc():
    base = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(
            frontier_per_device=1 << 5, table_capacity_per_device=1 << 10
        )
        .join()
    )
    cov = (
        TwoPhaseSys(3)
        .checker()
        .spawn_sharded_tpu_bfs(
            frontier_per_device=1 << 5,
            table_capacity_per_device=1 << 10,
            coverage=True,
        )
        .join()
    )
    assert base.unique_state_count() == 288
    assert cov.unique_state_count() == 288
    assert cov.state_count() == base.state_count()
    rep = cov.coverage_report()
    assert rep["unique"] == 288
    assert sum(rep["shape"]["depth_hist"]) == 288
    assert not rep["vacuous"]


def test_host_bfs_always_on_near_miss():
    """Host engines record coverage unconditionally; the unsolvable
    equation (2x + 10y is even, 5 is odd) is a genuine vacuous pass."""
    from fixtures import LinearEquation

    c = LinearEquation(2, 10, 5).checker().spawn_bfs().join()
    rep = c.coverage_report()
    assert rep is not None
    p = rep["properties"]["solvable"]
    assert p["exercised"] == 0 and p["discovered"] is False
    assert p["near_miss_depth"] == 511
    assert rep["vacuity"]["undiscovered_sometimes"] == ["solvable"]
    assert rep["unique"] == c.unique_state_count()


def test_host_dfs_coverage_and_actions():
    from fixtures import LinearEquation

    c = LinearEquation(1, 1, 3).checker().spawn_dfs().join()
    rep = c.coverage_report()
    assert rep["properties"]["solvable"]["discovered"] is True
    assert not rep["vacuity"]["undiscovered_sometimes"]
    table = rep["actions"]["table"]
    assert "IncreaseX" in table and table["IncreaseX"]["fired"] > 0


def test_device_vacuity_fixture_flagged():
    c = (
        VacuousChain()
        .checker()
        .spawn_tpu_bfs(
            frontier_capacity=8, table_capacity=1 << 8, coverage=True
        )
        .join()
    )
    assert c.unique_state_count() == 9
    rep = c.coverage_report()
    vac = rep["vacuity"]
    assert vac["dead_actions"] == ["never_fires"]
    assert vac["unexercised_always"] == ["guarded invariant"]
    assert vac["undiscovered_sometimes"] == ["reach the unreachable"]
    assert rep["vacuous"]
    assert rep["terminal_states"] == 1  # state 8 has no successor
    # Depth histogram: one fresh state per depth 1..9.
    assert sum(rep["shape"]["depth_hist"]) == 9


# -- scripts/coverage_report.py ----------------------------------------------


def _trace_run(tmp_path, spawn):
    path = str(tmp_path / "trace.jsonl")
    sink = get_tracer().add_sink(path)
    try:
        spawn().join()
    finally:
        get_tracer().remove_sink(sink)
    return path


def _run_report(path, *extra):
    return subprocess.run(
        [sys.executable, COVERAGE_REPORT, path, *extra],
        capture_output=True, text=True, timeout=120,
    )


def test_coverage_report_flags_vacuity_fixture(tmp_path):
    path = _trace_run(
        tmp_path,
        lambda: VacuousChain().checker().spawn_tpu_bfs(
            frontier_capacity=8, table_capacity=1 << 8, coverage=True
        ),
    )
    r = _run_report(path)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "DEAD" in r.stdout
    assert "VACUOUS (antecedent never fired)" in r.stdout
    assert "NOT DISCOVERED" in r.stdout
    assert "vacuity findings present" in r.stderr
    # --no-gate renders without failing; --json is machine-readable.
    assert _run_report(path, "--no-gate").returncode == 0
    rj = _run_report(path, "--json")
    assert rj.returncode == 1
    rep = json.loads(rj.stdout)["tpu_bfs"]
    assert rep["vacuity"]["dead_actions"] == ["never_fires"]


def test_coverage_report_clean_on_2pc(tmp_path):
    path = _trace_run(
        tmp_path,
        lambda: TwoPhaseSys(3).checker().spawn_tpu_bfs(
            frontier_capacity=1 << 6, table_capacity=1 << 12, coverage=True
        ),
    )
    r = _run_report(path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no vacuity findings" in r.stdout
    assert "TmCommit" in r.stdout


def test_coverage_report_exit2_without_coverage_data(tmp_path):
    path = tmp_path / "plain.jsonl"
    path.write_text(
        json.dumps({"name": "tpu_bfs.wave", "ph": "X", "ts": 1.0,
                    "dur": 5.0, "args": {"new_unique": 3}}) + "\n"
    )
    r = _run_report(str(path))
    assert r.returncode == 2
    assert "coverage" in r.stderr


def test_trace_summary_coverage_table(tmp_path):
    path = _trace_run(
        tmp_path,
        lambda: TwoPhaseSys(3).checker().spawn_tpu_bfs(
            frontier_capacity=1 << 6, table_capacity=1 << 12, coverage=True
        ),
    )
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_DIR, "scripts", "trace_summary.py"),
         path],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "coverage (cumulative, per backend):" in r.stdout
    assert "tpu_bfs" in r.stdout


# -- run-end undiscovered-property reporter lines -----------------------------


def test_write_reporter_undiscovered_lines():
    from fixtures import LinearEquation

    out = io.StringIO()
    LinearEquation(2, 10, 5).checker().spawn_bfs().join().report(
        WriteReporter(out)
    )
    assert (
        'Property "solvable" not discovered (sometimes)\n' in out.getvalue()
    )


def test_write_reporter_no_undiscovered_lines_when_all_found():
    from fixtures import LinearEquation

    out = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_bfs().join().report(
        WriteReporter(out)
    )
    assert "not discovered" not in out.getvalue()


def test_device_reporter_undiscovered_line():
    out = io.StringIO()
    c = (
        VacuousChain()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=8, table_capacity=1 << 8)
        .join()
    )
    c.report(WriteReporter(out))
    assert (
        'Property "reach the unreachable" not discovered (sometimes)\n'
        in out.getvalue()
    )


# -- monitor surface ----------------------------------------------------------


def test_monitor_coverage_gauges_and_sse_event():
    from stateright_tpu.telemetry.server import MonitorCore

    reg = MetricsRegistry()
    tracer = Tracer()
    core = MonitorCore(registry=reg, tracer=tracer)
    try:
        q = core.broker.subscribe()
        core.write_event({
            "name": "tpu_bfs.coverage", "ph": "X", "ts": 0.0, "dur": 1.0,
            "pid": 1, "tid": 1,
            "args": {"evaluated": 100, "terminals": 3,
                     "actions_fired": 15, "actions_total": 17,
                     "dead_actions": 2, "revisit_rate": 0.75,
                     "sometimes_witnessed": 1, "sometimes_total": 2},
        })
        assert reg.gauge(
            "monitor.coverage.action_coverage"
        ).snapshot() == pytest.approx(15 / 17)
        assert reg.gauge("monitor.coverage.dead_actions").snapshot() == 2
        assert reg.gauge(
            "monitor.coverage.revisit_rate"
        ).snapshot() == pytest.approx(0.75)
        kind, payload = q.get(timeout=2)
        assert kind == "coverage"
        assert payload["actions_total"] == 17
        assert payload["sometimes_witnessed"] == 1
    finally:
        core.close()


# -- metric-registry hygiene lint ---------------------------------------------


def test_registry_hygiene_clean_across_families():
    """coverage/pipeline/storage families (awkward labels included) must
    export to distinct, grammar-legal Prometheus names."""
    from stateright_tpu.storage import StorageInstruments
    from stateright_tpu.telemetry.attribution import WaveAttribution
    from stateright_tpu.telemetry.server import registry_hygiene_problems

    reg = MetricsRegistry()
    tracer = Tracer()
    CoverageLedger(
        "tpu_bfs",
        [
            Property.always("space name!", lambda m, s: True),
            Property.sometimes("dots.and/slashes", lambda m, s: False),
        ],
        action_labels=["Tm Commit", "Rm:Prepare", "action_0"],
        registry=reg, tracer=tracer,
    )
    attr = WaveAttribution("tpu_bfs", tracer=tracer, registry=reg)
    with attr.wave():
        with attr.phase("device"):
            pass
    StorageInstruments("tpu_bfs", registry=reg)
    assert registry_hygiene_problems(reg) == []


def test_registry_hygiene_catches_collision():
    from stateright_tpu.telemetry.server import registry_hygiene_problems

    reg = MetricsRegistry()
    reg.counter("x.coverage.action_fired.a b")
    reg.counter("x.coverage.action_fired.a_b")
    problems = registry_hygiene_problems(reg)
    assert len(problems) == 1
    assert "both export as" in problems[0]


def test_global_registry_hygiene():
    """The process-global registry, after whatever runs this test file
    (and its siblings) produced, must lint clean — the tier-1 guard the
    satellite asks for."""
    from stateright_tpu.telemetry.server import registry_hygiene_problems

    assert registry_hygiene_problems(metrics_registry()) == []


# -- coverage-off overhead budget ---------------------------------------------


def test_coverage_off_overhead_under_budget(base_2pc):
    """With coverage off the device checkers pay a handful of
    ``self._cov is None`` attribute checks per wave — no extra traced
    ops, no extra transfers. Same form as the attribution/telemetry
    budget tests: the measured disabled-path cost times a real run's
    wave count must stay under 5% of that run's wall."""
    base, run_secs, waves = base_2pc
    assert base._cov is None
    assert waves >= 1
    sites = 4  # wave consume + span emit + drain consume + seed
    n = 100_000
    cov = None
    t0 = time.perf_counter()
    for _ in range(n):
        for _ in range(sites):
            if cov is not None:
                raise AssertionError
    per_wave = (time.perf_counter() - t0) / n
    overhead = per_wave * waves
    assert overhead < 0.05 * run_secs, (
        f"coverage-off overhead too high: {waves} waves x "
        f"{per_wave * 1e6:.2f}us = {overhead * 1e3:.2f}ms on a "
        f"{run_secs * 1e3:.0f}ms run"
    )


# -- report --json convention (gap/storage/coverage) --------------------------


def test_storage_report_json_single_object(tmp_path):
    path = tmp_path / "st.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({
            "name": "tpu_bfs.storage.evict", "ph": "X", "ts": 1.0,
            "dur": 2000.0, "args": {"fps": 128},
        }) + "\n")
        f.write(json.dumps({
            "name": "tpu_bfs.storage.probe", "ph": "X", "ts": 5.0,
            "dur": 500.0,
            "args": {"keys": 64, "hits_l1": 3, "bloom_rejects": 60},
        }) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_DIR, "scripts",
                                      "storage_report.py"),
         str(path), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    s = json.loads(r.stdout)
    assert s["evict"]["count"] == 1 and s["evict"]["fps"] == 128
    assert s["probe"]["keys"] == 64
