"""Round benchmark: TPU BFS throughput on two-phase commit.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Workload: exhaustive check of the 7-RM two-phase-commit model
(296,448 unique states — the scaled-up version of the reference's
``2pc check N`` bench config, ``/root/reference/bench.sh:27``) on the
``TpuBfsChecker`` device backend. Baseline: the host ``BfsChecker`` on the
same model, rate-sampled with a state-count cap so the bench stays fast;
the reference itself publishes no absolute numbers (BASELINE.md).

Diagnostics go to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

RM_COUNT = 7
EXPECTED_UNIQUE = 296_448
HOST_CAP = 30_000
DEVICE_PROBE_TIMEOUT_S = 60
DEVICE_PROBE_ATTEMPTS = 3


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _accelerator_usable() -> bool:
    """Probes device init in a subprocess: a wedged device tunnel hangs
    ``jax.devices()`` indefinitely, which must not hang the bench. The
    tunnel is flaky, so probe with short timeouts and a few retries rather
    than one long wait (a wedged tunnel costs ~3 min total, not 5+)."""
    code = "import jax; d = jax.devices(); print('probe-ok', d[0].platform)"
    for attempt in range(1, DEVICE_PROBE_ATTEMPTS + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=DEVICE_PROBE_TIMEOUT_S,
                capture_output=True,
            )
        except subprocess.TimeoutExpired:
            log(
                f"device probe {attempt}/{DEVICE_PROBE_ATTEMPTS} timed out "
                f"after {DEVICE_PROBE_TIMEOUT_S}s"
            )
            continue
        if b"probe-ok" in r.stdout:
            platform = r.stdout.split()[-1].decode()
            log(f"device probe ok: platform={platform}")
            return True
        log(
            f"device probe {attempt}/{DEVICE_PROBE_ATTEMPTS} failed: "
            f"{r.stderr[-500:]!r}"
        )
    return False


DEVICE_RUN_TIMEOUT_S = 900


def main():
    """Parent entry: tries the full bench on the accelerator in a subprocess
    (the flaky tunnel can wedge mid-run, not just at init), falling back to
    a CPU-pinned in-process run. The child prints the JSON line; the parent
    relays it."""
    if "--child" in sys.argv:
        return run_bench(pin_cpu=False)
    if _accelerator_usable():
        try:
            r = subprocess.run(
                [sys.executable, __file__, "--child"],
                timeout=DEVICE_RUN_TIMEOUT_S,
                capture_output=True,
            )
        except subprocess.TimeoutExpired:
            log(f"device bench run wedged after {DEVICE_RUN_TIMEOUT_S}s")
        else:
            sys.stderr.buffer.write(r.stderr[-4000:])
            line = r.stdout.decode().strip().splitlines()
            if r.returncode == 0 and line:
                print(line[-1])
                return
            log(f"device bench run failed (rc={r.returncode})")
    log("falling back to CPU backend")
    run_bench(pin_cpu=True)


def run_bench(pin_cpu: bool):
    import jax

    if pin_cpu:
        # sitecustomize forces jax_platforms=axon,cpu via jax.config, which
        # overrides the JAX_PLATFORMS env var — re-pin through the config.
        jax.config.update("jax_platforms", "cpu")

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    device = jax.devices()[0]
    log(f"bench device: {device.platform} ({device})")

    t0 = time.time()
    host = (
        TwoPhaseSys(RM_COUNT)
        .checker()
        .target_state_count(HOST_CAP)
        .spawn_bfs()
        .join()
    )
    host_dt = time.time() - t0
    host_rate = host.unique_state_count() / host_dt
    log(
        f"host BfsChecker: {host.unique_state_count()} unique "
        f"in {host_dt:.2f}s = {host_rate:,.0f}/s (capped)"
    )

    t0 = time.time()
    checker = (
        TwoPhaseSys(RM_COUNT)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=1 << 13, table_capacity=1 << 20)
        .join()
    )
    tpu_dt = time.time() - t0
    err = checker.worker_error()
    if err is not None:
        raise err
    unique = checker.unique_state_count()
    if unique != EXPECTED_UNIQUE:
        raise AssertionError(
            f"2pc-{RM_COUNT} count mismatch: {unique} != {EXPECTED_UNIQUE}"
        )
    checker.assert_properties()
    # Exclude one-time XLA compilation (the time until the first wave
    # returned) so the metric reports steady-state exploration throughput.
    warmup = checker.warmup_seconds or 0.0
    steady = max(tpu_dt - warmup, 1e-9)
    tpu_rate = unique / steady
    log(
        f"TpuBfs: {unique} unique in {tpu_dt:.2f}s wall "
        f"({warmup:.2f}s compile warmup) = {tpu_rate:,.0f}/s steady-state"
    )

    # Secondary: the reference's flagship linearizability workload (paxos,
    # 2 clients / 3 servers = 16,668 states, examples/paxos.rs:325) with the
    # LinearizabilityTester history checked ON DEVICE per wave.
    from stateright_tpu.models.paxos import PaxosModelCfg

    t0 = time.time()
    paxos = (
        PaxosModelCfg(2, 3)
        .into_model()
        .checker()
        .spawn_tpu_bfs(frontier_capacity=1 << 11, table_capacity=1 << 16)
        .join()
    )
    paxos_dt = time.time() - t0
    err = paxos.worker_error()
    if err is not None:
        raise err
    if paxos.unique_state_count() != 16_668:
        raise AssertionError(
            f"paxos-2c3s count mismatch: {paxos.unique_state_count()} != 16668"
        )
    paxos.assert_properties()
    paxos_warm = paxos.warmup_seconds or 0.0
    paxos_rate = 16_668 / max(paxos_dt - paxos_warm, 1e-9)
    log(
        f"TpuBfs paxos-2c3s: 16668 unique in {paxos_dt:.2f}s wall "
        f"({paxos_warm:.2f}s warmup) = {paxos_rate:,.0f}/s steady-state"
    )

    # Tertiary: the BASELINE.md 5-node Raft config (leader-election
    # liveness, lossy network) — a TPU-scale space (>300k states by depth
    # 7), explored up to a generated-state cap so the bench stays bounded.
    from stateright_tpu.models.raft import RaftModelCfg

    RAFT_CAP = 300_000
    t0 = time.time()
    raft = (
        RaftModelCfg(server_count=5, max_term=1, lossy=True)
        .into_model()
        .checker()
        .target_state_count(RAFT_CAP)
        .spawn_tpu_bfs(frontier_capacity=1 << 12, table_capacity=1 << 20)
        .join()
    )
    raft_dt = time.time() - t0
    err = raft.worker_error()
    if err is not None:
        raise err
    raft_warm = raft.warmup_seconds or 0.0
    raft_rate = raft.unique_state_count() / max(raft_dt - raft_warm, 1e-9)
    log(
        f"TpuBfs raft-5 lossy (capped {RAFT_CAP} generated): "
        f"{raft.unique_state_count()} unique in {raft_dt:.2f}s wall "
        f"({raft_warm:.2f}s warmup) = {raft_rate:,.0f}/s steady-state"
    )

    print(
        json.dumps(
            {
                "metric": f"2pc-{RM_COUNT} exhaustive unique states/sec (TpuBfs)",
                "value": round(tpu_rate, 1),
                "unit": "unique states/sec",
                "vs_baseline": round(tpu_rate / host_rate, 3),
                "baseline": "host BfsChecker (Python), same model, capped run",
                "unique_states": unique,
                "wall_s": round(tpu_dt, 2),
                "warmup_s": round(warmup, 2),
                "paxos_2c3s_rate": round(paxos_rate, 1),
                "paxos_2c3s_wall_s": round(paxos_dt, 2),
                "raft5_lossy_rate": round(raft_rate, 1),
                "raft5_lossy_unique": raft.unique_state_count(),
                "raft5_lossy_wall_s": round(raft_dt, 2),
                "device": device.platform,
            }
        )
    )


if __name__ == "__main__":
    main()
