"""Round benchmark: TPU BFS throughput on two-phase commit.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Workload: exhaustive check of the 7-RM two-phase-commit model
(296,448 unique states — the scaled-up version of the reference's
``2pc check N`` bench config, ``/root/reference/bench.sh:27``) on the
``TpuBfsChecker`` device backend. Baseline: the host ``BfsChecker`` on the
same model, rate-sampled with a state-count cap so the bench stays fast;
the reference itself publishes no absolute numbers (BASELINE.md).

Diagnostics go to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

RM_COUNT = 7
EXPECTED_UNIQUE = 296_448
HOST_CAP = 30_000
DEVICE_PROBE_TIMEOUT_S = 300


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _accelerator_usable() -> bool:
    """Probes device init in a subprocess: a wedged device tunnel hangs
    ``jax.devices()`` indefinitely, which must not hang the bench."""
    code = "import jax; d = jax.devices(); print('probe-ok', d[0].platform)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=DEVICE_PROBE_TIMEOUT_S,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        log(f"device probe timed out after {DEVICE_PROBE_TIMEOUT_S}s")
        return False
    ok = b"probe-ok" in r.stdout
    if not ok:
        log(f"device probe failed: {r.stderr[-500:]!r}")
    return ok


def main():
    import jax

    if not _accelerator_usable():
        log("falling back to CPU backend")
        jax.config.update("jax_platforms", "cpu")

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    device = jax.devices()[0]
    log(f"bench device: {device.platform} ({device})")

    t0 = time.time()
    host = (
        TwoPhaseSys(RM_COUNT)
        .checker()
        .target_state_count(HOST_CAP)
        .spawn_bfs()
        .join()
    )
    host_dt = time.time() - t0
    host_rate = host.unique_state_count() / host_dt
    log(
        f"host BfsChecker: {host.unique_state_count()} unique "
        f"in {host_dt:.2f}s = {host_rate:,.0f}/s (capped)"
    )

    t0 = time.time()
    checker = (
        TwoPhaseSys(RM_COUNT)
        .checker()
        .spawn_tpu_bfs(frontier_capacity=1 << 13, table_capacity=1 << 20)
        .join()
    )
    tpu_dt = time.time() - t0
    err = checker.worker_error()
    if err is not None:
        raise err
    unique = checker.unique_state_count()
    if unique != EXPECTED_UNIQUE:
        raise AssertionError(
            f"2pc-{RM_COUNT} count mismatch: {unique} != {EXPECTED_UNIQUE}"
        )
    checker.assert_properties()
    # Exclude one-time XLA compilation (the time until the first wave
    # returned) so the metric reports steady-state exploration throughput.
    warmup = checker.warmup_seconds or 0.0
    steady = max(tpu_dt - warmup, 1e-9)
    tpu_rate = unique / steady
    log(
        f"TpuBfs: {unique} unique in {tpu_dt:.2f}s wall "
        f"({warmup:.2f}s compile warmup) = {tpu_rate:,.0f}/s steady-state"
    )

    print(
        json.dumps(
            {
                "metric": f"2pc-{RM_COUNT} exhaustive unique states/sec (TpuBfs)",
                "value": round(tpu_rate, 1),
                "unit": "unique states/sec",
                "vs_baseline": round(tpu_rate / host_rate, 3),
                "baseline": "host BfsChecker (Python), same model, capped run",
                "unique_states": unique,
                "wall_s": round(tpu_dt, 2),
                "warmup_s": round(warmup, 2),
                "device": device.platform,
            }
        )
    )


if __name__ == "__main__":
    main()
