"""Round benchmark: TPU BFS throughput on the reference bench workloads.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Primary workload: exhaustive check of the 7-RM two-phase-commit model
(296,448 unique states — the scaled-up version of the reference's
``2pc check N`` bench config, ``/root/reference/bench.sh:27``) on the
``TpuBfsChecker`` device backend. Baseline: the host ``BfsChecker`` on the
same model, rate-sampled with a state-count cap so the bench stays fast;
the reference itself publishes no absolute numbers (BASELINE.md).

Secondary legs: paxos 2c/3s with the linearizability history checked on
device per wave (reference flagship, ``examples/paxos.rs:325``), the
BASELINE.md 5-node lossy Raft at a depth cap, and — on the accelerator
only — the north-star ``paxos check 3`` config (1.19M states).

Each leg runs in its OWN subprocess with its own timeout: the device
tunnel on this image is flaky and can wedge any single run; a wedged leg
must cost only its own timeout, not the whole bench. Legs that fail on
the accelerator are retried CPU-pinned so the primary line always
carries at least a fallback number — EXCEPT the ``ACCEL_ONLY_LEGS``,
which are skipped outright when no accelerator is reachable (their CPU
compute cost exceeds any sensible fallback budget). Diagnostics go to
stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

RM_COUNT = 7
EXPECTED_UNIQUE = 296_448
HOST_CAP = 30_000
DEVICE_PROBE_TIMEOUT_S = 60
DEVICE_PROBE_ATTEMPTS = 3
LEG_TIMEOUT_S = {"2pc": 720, "paxos": 600, "raft5": 600, "paxos3": 900}
# Accelerator-only legs: far too slow for the CPU fallback (paxos-3c3s
# takes ~15 min of pure compute there), so a tunnel failure skips them
# instead of burning the fallback budget.
ACCEL_ONLY_LEGS = {"paxos3"}


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _accelerator_usable() -> bool:
    """Probes device init in a subprocess: a wedged device tunnel hangs
    ``jax.devices()`` indefinitely, which must not hang the bench. The
    tunnel is flaky, so probe with short timeouts and a few retries rather
    than one long wait (a wedged tunnel costs ~3 min total, not 5+)."""
    code = "import jax; d = jax.devices(); print('probe-ok', d[0].platform)"
    for attempt in range(1, DEVICE_PROBE_ATTEMPTS + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=DEVICE_PROBE_TIMEOUT_S,
                capture_output=True,
            )
        except subprocess.TimeoutExpired:
            log(
                f"device probe {attempt}/{DEVICE_PROBE_ATTEMPTS} timed out "
                f"after {DEVICE_PROBE_TIMEOUT_S}s"
            )
            continue
        if b"probe-ok" in r.stdout:
            platform = r.stdout.split()[-1].decode()
            log(f"device probe ok: platform={platform}")
            return True
        log(
            f"device probe {attempt}/{DEVICE_PROBE_ATTEMPTS} failed: "
            f"{r.stderr[-500:]!r}"
        )
    return False


def _leg_specs():
    """One spec per leg: model factory, builder tweaks, spawn kwargs, and
    the pinned oracle count. The shared skeleton in ``_run_leg`` does the
    rest (optional host baseline, count assert, rate computation)."""
    from stateright_tpu.models.paxos import PaxosModelCfg
    from stateright_tpu.models.raft import RaftModelCfg
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    return {
        "2pc": dict(
            model=lambda: TwoPhaseSys(RM_COUNT),
            spawn=dict(
                frontier_capacity=1 << 13,
                table_capacity=1 << 20,
                drain_log_factor=48,
            ),
            expected=EXPECTED_UNIQUE,
            host_baseline=True,
        ),
        # Paxos BFS frontiers are narrow (hundreds of states); a small
        # fixed wave width wastes far fewer masked lanes (measured 3.4x
        # steady-state vs 2048 lanes on the CPU backend).
        "paxos": dict(
            model=lambda: PaxosModelCfg(2, 3).into_model(),
            spawn=dict(frontier_capacity=1 << 9, table_capacity=1 << 16),
            expected=16_668,
        ),
        # The north-star workload (BASELINE.md: `paxos check 3`): 3
        # clients / 3 servers with the linearizability history checked on
        # device per wave; the property HOLDS, so this is a full-space
        # traversal. Count pinned from a full TpuBfsChecker (device-path)
        # run executed on the CPU backend (862s).
        "paxos3": dict(
            model=lambda: PaxosModelCfg(3, 3, envelope_capacity=24).into_model(),
            spawn=dict(
                frontier_capacity=1 << 11,
                table_capacity=1 << 21,
                drain_log_factor=32,
            ),
            expected=1_194_428,
        ),
        # Depth cap (not a state-count target) keeps raft-5 deterministic
        # AND deep-drain-eligible; 29,522 is the pinned depth-7 oracle
        # (TpuBfsChecker on the CPU backend; the single-device deep drain
        # is strict-FIFO so cap semantics are exact). Frontier kept modest:
        # raft-5 packs ~1.3KB/state and expands 125 actions/lane. The
        # "stable leader" liveness property is intentionally falsifiable,
        # so properties are not asserted.
        "raft5": dict(
            model=lambda: RaftModelCfg(
                server_count=5, max_term=1, lossy=True
            ).into_model(),
            builder=lambda b: b.target_max_depth(7),
            spawn=dict(frontier_capacity=1 << 11, table_capacity=1 << 21),
            expected=29_522,
            check_properties=False,
        ),
    }


def _run_leg(leg: str, pin_cpu: bool):
    """Child entry: runs one leg, prints its result dict as a JSON line."""
    import jax

    if pin_cpu:
        # sitecustomize forces jax_platforms=axon,cpu via jax.config, which
        # overrides the JAX_PLATFORMS env var — re-pin through the config.
        jax.config.update("jax_platforms", "cpu")
    device = jax.devices()[0]
    log(f"[{leg}] device: {device.platform} ({device})")
    out = {"device": device.platform}

    specs = _leg_specs()
    if leg not in specs:
        raise ValueError(f"unknown leg {leg!r} (have: {sorted(specs)})")
    spec = specs[leg]
    if spec.get("host_baseline"):
        t0 = time.time()
        host = (
            spec["model"]()
            .checker()
            .target_state_count(HOST_CAP)
            .spawn_bfs()
            .join()
        )
        host_dt = time.time() - t0
        out["host_rate"] = host.unique_state_count() / host_dt
        log(
            f"[{leg}] host BfsChecker: {host.unique_state_count()} unique "
            f"in {host_dt:.2f}s = {out['host_rate']:,.0f}/s (capped)"
        )

    t0 = time.time()
    builder = spec["model"]().checker()
    builder = spec.get("builder", lambda b: b)(builder)
    checker = builder.spawn_tpu_bfs(**spec["spawn"]).join()
    dt = time.time() - t0
    err = checker.worker_error()
    if err is not None:
        raise err
    expected = spec["expected"]
    if checker.unique_state_count() != expected:
        raise AssertionError(
            f"{leg} count mismatch: "
            f"{checker.unique_state_count()} != {expected}"
        )
    if spec.get("check_properties", True):
        checker.assert_properties()
    out.update(
        unique=expected,
        wall_s=dt,
        warmup_s=checker.warmup_seconds or 0.0,
        rate=expected / max(dt - (checker.warmup_seconds or 0.0), 1e-9),
    )
    log(
        f"[{leg}] {out.get('unique')} unique in {out.get('wall_s'):.2f}s "
        f"wall ({out.get('warmup_s'):.2f}s warmup) = "
        f"{out.get('rate'):,.0f}/s steady-state"
    )
    print(json.dumps(out))


def _leg_subprocess(leg: str, pin_cpu: bool):
    """Runs one leg in a child; returns its result dict or None."""
    argv = [sys.executable, __file__, "--leg", leg]
    # CPU-pinned fallbacks get extra headroom: they exist so the bench
    # always emits a number, and a slow host must not be killed like a
    # wedged tunnel.
    timeout_s = LEG_TIMEOUT_S[leg] * (3 if pin_cpu else 1)
    if pin_cpu:
        argv.append("--cpu")
    try:
        # stderr inherits the parent's stream: diagnostics (and OOM
        # reports) surface live instead of dying with the child.
        r = subprocess.run(argv, timeout=timeout_s, stdout=subprocess.PIPE)
    except subprocess.TimeoutExpired:
        log(f"[{leg}] wedged after {timeout_s}s")
        return None
    lines = r.stdout.decode().strip().splitlines()
    if r.returncode == 0 and lines:
        try:
            return json.loads(lines[-1])
        except json.JSONDecodeError:
            pass
    log(f"[{leg}] failed (rc={r.returncode})")
    return None


def main():
    if "--leg" in sys.argv:
        return _run_leg(
            sys.argv[sys.argv.index("--leg") + 1], "--cpu" in sys.argv
        )

    on_accel = _accelerator_usable()
    results = {}
    for leg in ("2pc", "paxos", "raft5", "paxos3"):
        res = _leg_subprocess(leg, pin_cpu=False) if on_accel else None
        if res is None:
            if leg in ACCEL_ONLY_LEGS:
                log(f"[{leg}] accelerator-only leg skipped")
                continue
            log(f"[{leg}] falling back to CPU-pinned run")
            res = _leg_subprocess(leg, pin_cpu=True)
        if res is not None:
            results[leg] = res

    if "2pc" not in results:
        # Still emit the JSON line (the output contract) with an error
        # marker rather than nothing.
        print(
            json.dumps(
                {
                    "metric": f"2pc-{RM_COUNT} exhaustive unique "
                    "states/sec (TpuBfs)",
                    "value": 0,
                    "unit": "unique states/sec",
                    "vs_baseline": 0,
                    "error": "primary 2pc leg failed on every backend",
                }
            )
        )
        return
    primary = results["2pc"]
    line = {
        "metric": f"2pc-{RM_COUNT} exhaustive unique states/sec (TpuBfs)",
        "value": round(primary["rate"], 1),
        "unit": "unique states/sec",
        "vs_baseline": round(primary["rate"] / primary["host_rate"], 3),
        "baseline": "host BfsChecker (Python), same model, capped run",
        "unique_states": primary["unique"],
        "wall_s": round(primary["wall_s"], 2),
        "warmup_s": round(primary["warmup_s"], 2),
        "device": primary["device"],
    }
    for leg in ("paxos", "raft5", "paxos3"):
        if leg in results:
            line[f"{leg}_rate"] = round(results[leg]["rate"], 1)
            line[f"{leg}_unique"] = results[leg]["unique"]
            line[f"{leg}_wall_s"] = round(results[leg]["wall_s"], 2)
            line[f"{leg}_device"] = results[leg]["device"]
    print(json.dumps(line))


if __name__ == "__main__":
    main()
