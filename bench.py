"""Round benchmark: TPU BFS throughput on the reference bench workloads.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Primary workload: exhaustive check of the 7-RM two-phase-commit model
(296,448 unique states — the scaled-up version of the reference's
``2pc check N`` bench config, ``/root/reference/bench.sh:27``) on the
``TpuBfsChecker`` device backend. Baseline: the host ``BfsChecker`` on the
same model, rate-sampled with a state-count cap so the bench stays fast;
the reference itself publishes no absolute numbers (BASELINE.md).

Secondary legs cover every BASELINE.md measurement config: paxos 2c/3s
with the linearizability history checked on device per wave (reference
flagship, ``examples/paxos.rs:325``), ``increment_lock`` with 4 threads
(``examples/increment_lock.rs:97-106``), the 3-client ordered ABD
register (``bench.sh:31-34``), the BASELINE.md 5-node lossy Raft as a
time-to-counterexample run on its intentionally-falsifiable ``eventually
"stable leader"`` property, and — on the accelerator only — the
north-star ``paxos check 3`` config (1.19M states).

Each leg runs in its OWN subprocess with its own timeout: the device
tunnel on this image is flaky and can wedge any single run; a wedged leg
must cost only its own timeout, not the whole bench. The tunnel also
recovers on hour scales, so the device is re-probed before every leg and
once at bench end (re-running the primary 2pc leg on device if it came
back mid-bench). Legs that fail on the accelerator are retried
CPU-pinned so the primary line always carries at least a fallback
number — EXCEPT the ``ACCEL_ONLY_LEGS``, which are skipped outright when
no accelerator is reachable (their CPU compute cost exceeds any sensible
fallback budget). Diagnostics go to stderr; stdout carries only the JSON
line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_DIR = os.path.dirname(os.path.abspath(__file__))
# Single-tenant-chip coordination with scripts/tpu_sentinel.sh /
# device_bench_run.sh: the full bench advertises itself via the pid file
# (the sentinel stands down), and conversely never probes the device
# while the sentinel's device run holds its lock. Both live under a
# repo-owned 0700 runtime dir, not /tmp — predictable world-writable
# paths let any local user squat the lock and stand the bench down
# forever (same hazard class the compile-cache hardening closed).
RUNTIME_DIR = os.path.join(REPO_DIR, ".runtime")
os.makedirs(RUNTIME_DIR, mode=0o700, exist_ok=True)
BENCH_PID_FILE = os.path.join(RUNTIME_DIR, "stateright_bench_main.pid")
DEVICE_RUN_LOCK = os.path.join(RUNTIME_DIR, "device_bench_run.lock")

RM_COUNT = 7
EXPECTED_UNIQUE = 296_448
HOST_CAP = 30_000
DEVICE_PROBE_TIMEOUT_S = 60
DEVICE_PROBE_ATTEMPTS = 3
LEG_TIMEOUT_S = {
    "smoke": 120,
    "2pc": 720,
    "paxos": 600,
    "ilock": 300,
    "abd3o": 600,
    "raft5": 600,
    "paxos3": 900,
    "scr4": 900,
}
# Accelerator-only legs: far too slow for the CPU fallback, so a tunnel
# failure skips them instead of burning the fallback budget. EMPTY since
# round 4: the DP predicate + per-class expansion + scatter dedup
# brought the two former members inside their CPU fallback budgets —
# paxos3 (1.19M states) to ~350s (3,611/s, count exact) and scr4 to
# ~137s (4,535/s) — so every leg now lands in the bench JSON on every
# backend. The gate mechanism stays for future heavyweight legs.
ACCEL_ONLY_LEGS = set()


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _accelerator_usable(attempts: int = DEVICE_PROBE_ATTEMPTS) -> bool:
    """Probes device init in a subprocess: a wedged device tunnel hangs
    ``jax.devices()`` indefinitely, which must not hang the bench. The
    tunnel is flaky, so probe with short timeouts and a few retries rather
    than one long wait (a wedged tunnel costs ~3 min total, not 5+).
    Never probes while the sentinel's device run holds the chip — a
    second claimant wedges both; its results reach the bench JSON via
    ``sentinel_device_runs`` instead."""
    if os.path.isdir(DEVICE_RUN_LOCK):
        log("device run lock held (sentinel on the chip); not probing")
        return False
    code = "import jax; d = jax.devices(); print('probe-ok', d[0].platform)"
    for attempt in range(1, attempts + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=DEVICE_PROBE_TIMEOUT_S,
                capture_output=True,
            )
        except subprocess.TimeoutExpired:
            log(
                f"device probe {attempt}/{attempts} timed out "
                f"after {DEVICE_PROBE_TIMEOUT_S}s"
            )
            continue
        if b"probe-ok" in r.stdout:
            platform = r.stdout.split()[-1].decode()
            log(f"device probe ok: platform={platform}")
            return True
        log(
            f"device probe {attempt}/{attempts} failed: "
            f"{r.stderr[-500:]!r}"
        )
    return False


def _leg_specs():
    """One spec per leg: model factory, builder tweaks, spawn kwargs, and
    the pinned oracle count. The shared skeleton in ``_run_leg`` does the
    rest (optional host baseline, count assert, rate computation)."""
    from stateright_tpu.actor import Network
    from stateright_tpu.models.increment import IncrementLock
    from stateright_tpu.models.linearizable_register import AbdModelCfg
    from stateright_tpu.models.paxos import PaxosModelCfg
    from stateright_tpu.models.raft import RaftModelCfg
    from stateright_tpu.models.single_copy_register import SingleCopyModelCfg
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    return {
        # Device smoke leg (VERDICT r04 #1a): 2pc-5 — 8,832 states, warm in
        # seconds — exists to bank a completed `"device": "tpu"` datapoint
        # within the first minute of any tunnel window, BEFORE the
        # ~25-minute headline leg gets a chance to ride the window into a
        # wedge. Not part of the CPU bench rotation (its steady-state
        # window is too short to be a rate claim); advisory by design.
        "smoke": dict(
            model=lambda: TwoPhaseSys(5),
            spawn=dict(frontier_capacity=1 << 10, table_capacity=1 << 15),
            expected=8_832,
            advisory=True,
        ),
        "2pc": dict(
            model=lambda: TwoPhaseSys(RM_COUNT),
            spawn=dict(
                frontier_capacity=1 << 13,
                table_capacity=1 << 20,
                drain_log_factor=48,
            ),
            expected=EXPECTED_UNIQUE,
            host_baseline=True,
        ),
        # Paxos BFS frontiers are narrow (hundreds of states); a small
        # fixed wave width wastes far fewer masked lanes (measured 3.4x
        # steady-state vs 2048 lanes on the CPU backend).
        "paxos": dict(
            model=lambda: PaxosModelCfg(2, 3).into_model(),
            spawn=dict(frontier_capacity=1 << 9, table_capacity=1 << 16),
            expected=16_668,
        ),
        # The north-star workload (BASELINE.md: `paxos check 3`): 3
        # clients / 3 servers with the linearizability history checked on
        # device per wave; the property HOLDS, so this is a full-space
        # traversal. Count pinned from a full TpuBfsChecker (device-path)
        # run executed on the CPU backend (862s).
        "paxos3": dict(
            model=lambda: PaxosModelCfg(3, 3, envelope_capacity=24).into_model(),
            spawn=dict(
                frontier_capacity=1 << 11,
                table_capacity=1 << 21,
                drain_log_factor=32,
            ),
            expected=1_194_428,
        ),
        # BASELINE.md measurement config: `increment_lock` with 4 threads
        # (always-mutex; the "sum" ALWAYS property holds). Tiny space, so
        # the number is dominated by warmup — reported for config coverage,
        # with the steady-state rate computed net of warmup like the rest.
        # 257 states is warmup-dominated: the rate swings ±70% run-to-run
        # (4,786/s vs 2,847/s measured in round 4), so it is marked
        # advisory (VERDICT r04 #6) — the leg exists for BASELINE.md
        # config coverage, not as a throughput claim.
        "ilock": dict(
            model=lambda: IncrementLock(4),
            spawn=dict(frontier_capacity=1 << 6, table_capacity=1 << 10),
            expected=257,
            advisory=True,
        ),
        # BASELINE.md measurement config: `linearizable-register check 3
        # ordered` — 3 ABD clients / 2 servers over per-pair FIFO flows,
        # linearizability history checked on device per wave. Oracle pinned
        # by test_ordered_abd_3_clients_bench_family_parity
        # (tests/test_packed_ordered_crash.py).
        # flow_capacity=2 is measured-exact for the 2-server quorum (see
        # AbdModelCfg) and this leg's count assert pins it.
        "abd3o": dict(
            model=lambda: AbdModelCfg(
                3,
                2,
                network=Network.new_ordered(),
                envelope_capacity=12,
                flow_capacity=2,
            ).into_model(),
            spawn=dict(frontier_capacity=1 << 11, table_capacity=1 << 17),
            expected=46_516,
        ),
        # The reference bench-suite row `single-copy-register check 4`
        # (/root/reference/bench.sh:29): 4 register clients against one
        # non-replicated server, linearizability history (the 81-node
        # C=4 DP) checked on device per wave. Count pinned by this
        # framework's first completed run (round 4, 137s CPU; the r03
        # rehearsal exceeded an hour) — the reference publishes no count
        # for this config, so the oracle guards determinism and
        # regression, not cross-engine parity.
        "scr4": dict(
            model=lambda: SingleCopyModelCfg(
                4, 1, envelope_capacity=12
            ).into_model(),
            spawn=dict(
                frontier_capacity=1 << 12,
                table_capacity=1 << 22,
                drain_log_factor=32,
            ),
            expected=400_233,
        ),
        # BASELINE.md asks for time-to-counterexample: raft-5's
        # ``eventually "stable leader"`` is intentionally falsifiable, so
        # this leg runs the model with ONLY that property retained and
        # measures wall time until the checker records its discovery and
        # early-exits (the previous depth-7 slice measured compile + ramp,
        # not a BASELINE metric). Unique-at-exit is deterministic for the
        # strict-FIFO single-device drain but not asserted — the metric is
        # the discovery, not the count.
        "raft5": dict(
            model=lambda: RaftModelCfg(
                server_count=5, max_term=1, lossy=True
            )
            .into_model()
            .retain_properties("stable leader"),
            spawn=dict(frontier_capacity=1 << 11, table_capacity=1 << 21),
            expect_discovery="stable leader",
            check_properties=False,
        ),
    }


def _run_leg(leg: str, pin_cpu: bool):
    """Child entry: runs one leg, prints its result dict as a JSON line."""
    import jax

    if pin_cpu:
        # sitecustomize forces jax_platforms=axon,cpu via jax.config, which
        # overrides the JAX_PLATFORMS env var — re-pin through the config.
        jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: every leg is its own subprocess, so
    # without this each leg recompiles shapes the previous legs (or the
    # previous round) already built — through the device tunnel that is
    # 30-40s per jitted shape. Warmup accounting stays honest: cache hits
    # simply shrink warmup_seconds. MUST come after the platform pin: the
    # cache directory is keyed on the resolved platform line-up, so
    # enabling first would file this process's artifacts under the wrong
    # target (the r03 cross-target SIGILL-risk warning).
    from stateright_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    device = jax.devices()[0]
    log(f"[{leg}] device: {device.platform} ({device})")
    # Measurement-regime label (VERDICT r04 #7): in-bench legs share the
    # box with sibling legs' host baselines and caches and measure 4-15%
    # below solo runs; the number should say which regime produced it.
    out = {
        "device": device.platform,
        "run_mode": "in_bench" if "--in-bench" in sys.argv else "solo",
    }
    # Telemetry trace sink (--trace-out): every wave/drain span this leg's
    # checker emits streams to the JSONL file; the path rides the leg
    # result so the bench JSON says where the trace landed
    # (scripts/trace_summary.py renders it; chrome_trace_from_jsonl
    # exports Perfetto-loadable JSON).
    trace_path = _parse_trace_out()
    if trace_path is not None:
        from stateright_tpu.telemetry import get_tracer

        get_tracer().add_sink(trace_path)
        out["trace_path"] = trace_path

    specs = _leg_specs()
    if leg not in specs:
        raise ValueError(f"unknown leg {leg!r} (have: {sorted(specs)})")
    spec = specs[leg]
    spec["spawn"]["wave_dedup"] = _dedup_for(spec, device.platform)
    out["wave_dedup"] = spec["spawn"]["wave_dedup"]
    # Out-of-core mode (BENCH_r06 trajectory): ``--hbm-budget-mib N``
    # runs every leg with the tiered visited store so the spill/merge
    # overhead is quantifiable against the unbounded r05 numbers.
    budget = _parse_float_flag("--hbm-budget-mib")
    host_budget = _parse_float_flag("--host-budget-mib")
    if budget is not None:
        spec["spawn"]["hbm_budget_mib"] = budget
        if host_budget is not None:
            spec["spawn"]["host_budget_mib"] = host_budget
            spec["spawn"]["spill_dir"] = os.path.join(
                RUNTIME_DIR, f"spill_{leg}"
            )
        out["hbm_budget_mib"] = budget
    # Wave-timeline attribution (--attribution): fences each wave and
    # classifies wall into phases (telemetry/attribution.py). Opt-in:
    # the fences serialize dispatch, so the timed rate measures the
    # attributed regime — the per-leg record says so.
    if "--attribution" in sys.argv:
        spec["spawn"]["attribution"] = True
        out["attribution_enabled"] = True
    # Async pipelined wave engine (--async-pipeline): wave N's host-tier
    # probe/evict/checkpoint overlap wave N+1's device dispatch on a
    # host worker thread. Results bit-identical; the per-leg attribution
    # record gains the overlapped ledger. See bench.py --async-ab for
    # the dedicated on/off comparison leg.
    if "--async-pipeline" in sys.argv:
        spec["spawn"]["async_pipeline"] = True
        out["async_pipeline"] = True
    # State-space cartography (--coverage): the in-wave coverage
    # reductions (telemetry/coverage.py) ride the run; the per-leg
    # record carries the full report (actions/properties/shape/vacuity).
    # Results stay bit-identical — only the extra per-wave vector pull
    # changes pacing.
    if "--coverage" in sys.argv:
        spec["spawn"]["coverage"] = True
        out["coverage_enabled"] = True
    if spec.get("host_baseline") and "--no-host-baseline" not in sys.argv:
        t0 = time.time()
        host = (
            spec["model"]()
            .checker()
            .target_state_count(HOST_CAP)
            .spawn_bfs()
            .join()
        )
        host_dt = time.time() - t0
        out["host_rate"] = host.unique_state_count() / host_dt
        log(
            f"[{leg}] host BfsChecker: {host.unique_state_count()} unique "
            f"in {host_dt:.2f}s = {out['host_rate']:,.0f}/s (capped)"
        )

    t0 = time.time()
    builder = spec["model"]().checker()
    builder = spec.get("builder", lambda b: b)(builder)
    # Partial-progress sidecar (VERDICT r04 #1c): a tunnel wedge kills
    # this process via the caller's timeout; the sidecar preserves the
    # last observed unique-count/elapsed pair so device_bench_run.sh can
    # record a partial rate instead of `result: null`. Cleared up front —
    # a stale file from a previous killed run must never be salvaged as
    # THIS run's progress — and removed in the finally (join() re-raises
    # worker errors, and an errored run's sidecar is equally stale).
    progress_path = os.path.join(RUNTIME_DIR, f"leg_{leg}.progress.json")
    try:
        os.remove(progress_path)
    except OSError:
        pass
    # Live monitoring (--monitor-port): /metrics (Prometheus), /status
    # (JSON progress + ETA band), /events (SSE wave stream) served
    # concurrently with the check; the flight recorder rides along so a
    # SIGTERM'd (wedged-tunnel-timeout) leg leaves flight-<run_id>.json
    # forensics, and --stall-deadline-s arms the no-wave watchdog.
    # Created BEFORE spawn (the documented pattern): the worker thread
    # can finish waves of a short leg before a late-attached sink would
    # see them, skewing the wave/ETA counters.
    monitor = None
    monitor_port = _parse_float_flag("--monitor-port")
    stall_deadline_s = _parse_float_flag("--stall-deadline-s")
    if monitor_port is not None:
        from stateright_tpu.telemetry.server import MonitorServer

        monitor = MonitorServer(
            port=int(monitor_port),
            run_id=f"{leg}-{os.getpid()}",
            stall_deadline_s=stall_deadline_s,
            flight_recorder=True,
            flight_dir=RUNTIME_DIR,
        )
        out["monitor_port"] = monitor.port
        log(f"[{leg}] monitor serving at {monitor.url}")
    checker = None
    try:
        # Spawn inside the try: a spawn-time failure (bad knob, device
        # init) must still flight-dump and close the monitor below, not
        # leak its server thread / watchdog / tracer sink.
        checker = builder.spawn_tpu_bfs(**spec["spawn"])
        if monitor is not None:
            monitor.attach(checker)
        while not checker.is_done():
            time.sleep(2.0)
            try:
                # Atomic tmp+replace: timeout's SIGKILL mid-write must not
                # leave truncated JSON for the shell to splice verbatim
                # into DEVICE_RUNS.jsonl.
                tmp = progress_path + ".tmp"
                # Keys deliberately avoid "leg"/"device": the shell-side
                # completed-leg checks are line-based greps for
                # `"leg": X` + `"device": "tpu"`, and a salvaged partial
                # spliced onto one JSONL line must never match them.
                with open(tmp, "w") as f:
                    json.dump(
                        {
                            "partial_of": leg,
                            "on_device": device.platform,
                            "unique_so_far": checker.unique_state_count(),
                            "elapsed_s": round(time.time() - t0, 2),
                            "partial": True,
                        },
                        f,
                    )
                os.replace(tmp, progress_path)
            except OSError:
                pass
        checker.join()
        dt = time.time() - t0
    finally:
        if monitor is not None:
            # A worker error propagates AFTER this finally uninstalls the
            # excepthook — and a main-thread exception reaches the hook
            # only after monitor.close() has restored the original one —
            # so the crash dump must happen here or a crashed monitored
            # leg would leave no flight file at all.
            werr = checker.worker_error() if checker is not None else None
            exc = None
            if werr is not None:
                exc = ("worker_error", (type(werr), werr, werr.__traceback__))
            else:
                inflight = sys.exc_info()
                if inflight[0] is not None:
                    exc = ("exception", inflight)
            if exc is not None and monitor.flight is not None:
                try:
                    monitor.flight.dump(exc[0], exc=exc[1])
                except Exception as dump_err:  # noqa: BLE001
                    # A failed dump (disk full — plausibly what killed
                    # the run) must not supersede the real error.
                    log(f"[{leg}] flight dump failed: {dump_err!r}")
            monitor.close()
        try:
            os.remove(progress_path)
        except OSError:
            pass
    err = checker.worker_error()
    if err is not None:
        raise err
    expected = spec.get("expected")
    if expected is not None and checker.unique_state_count() != expected:
        raise AssertionError(
            f"{leg} count mismatch: "
            f"{checker.unique_state_count()} != {expected}"
        )
    if spec.get("check_properties", True):
        checker.assert_properties()
    warmup = checker.warmup_seconds or 0.0
    unique = checker.unique_state_count()
    out.update(
        unique=unique,
        wall_s=dt,
        warmup_s=warmup,
        rate=unique / max(dt - warmup, 1e-9),
    )
    if spec.get("advisory"):
        # Sub-second steady windows are not rate claims (VERDICT r04 #6).
        out["advisory"] = True
    # expand_fps as a measured policy: one calibration wave per pipeline
    # AFTER the timed run (its jits must not pollute the leg timing) but
    # BEFORE the telemetry snapshot (the mismatch counter rides it).
    # --no-calibrate skips it.
    if "--no-calibrate" not in sys.argv:
        out["pipeline_choice"] = _calibrate_pipeline(leg, spec, checker)
    # Leg-level observability: the wave/occupancy counters the run left in
    # the registry (scalar instruments only — histograms ride the trace).
    snap = checker.metrics().snapshot()
    out["telemetry"] = {
        k: v for k, v in snap.items() if not isinstance(v, dict)
    }
    # Occupancy-adaptive dispatch record (BENCH_r06+ trajectory): the
    # per-rung dispatch histogram, the run's last frontier fill /
    # compaction ratio, and whether buffer donation was active.
    out["bucket_dispatch"] = {
        k.rsplit(".", 1)[1]: v
        for k, v in snap.items()
        if ".bucket_dispatch." in k
    }
    out["frontier_fill"] = snap.get("tpu_bfs.frontier_fill")
    out["compaction_ratio"] = snap.get("tpu_bfs.compaction_ratio")
    out["donation"] = bool(getattr(checker, "donation_enabled", False))
    # Out-of-core record: spill/merge counters, peak per-tier occupancy,
    # and the effective compression ratio — zeros/absent on unbounded
    # runs, the r06-vs-r05 overhead evidence on budgeted ones.
    tier = getattr(checker, "_tier", None)
    if tier is not None:
        out["storage"] = tier.instruments.bench_stats()
    # Attribution record: the phase ledger + overlap headroom (the
    # go/no-go number for the async pipelined wave engine).
    attribution = checker.attribution_report()
    if attribution is not None:
        out["attribution"] = attribution
    # Coverage record: the state-space cartography + vacuity verdict
    # (scripts/coverage_report.py renders the same data from the trace).
    cov = checker.coverage_report()
    if cov is not None:
        out["coverage"] = cov
    want = spec.get("expect_discovery")
    if want is not None:
        path = checker.discoveries().get(want)
        if path is None:
            raise AssertionError(f"{leg}: no discovery for {want!r}")
        # Time-to-counterexample net of compile warmup: the BASELINE.md
        # metric for the falsifiable-liveness leg.
        out["ttc_s"] = max(dt - warmup, 0.0)
        out["counterexample_len"] = len(path.into_actions())
    log(
        f"[{leg}] {out.get('unique')} unique in {out.get('wall_s'):.2f}s "
        f"wall ({out.get('warmup_s'):.2f}s warmup) = "
        f"{out.get('rate'):,.0f}/s steady-state"
        + (f"; ttc={out['ttc_s']:.2f}s" if "ttc_s" in out else "")
    )
    print(json.dumps(out))


# Configured-vs-measured pipeline mismatch threshold: the configured
# pipeline must be >10% slower than the measured winner before the bench
# flags it — sub-10% deltas on this shared box are noise, while the
# regressions that motivated the policy (VERDICT r05: abd3o 2.5x, scr4
# 26%) clear it comfortably.
PIPELINE_MISMATCH_FACTOR = 1.10


def evaluate_pipeline_choice(
    configured, fps_ms, materialize_ms, factor=PIPELINE_MISMATCH_FACTOR
):
    """True when the CONFIGURED expansion pipeline measured more than
    ``factor``× slower than the other one — the silent-regression
    condition the calibration wave exists to surface. Pure so the gate
    is unit-testable without a jax run."""
    if configured not in ("fps", "materialize"):
        return False
    if not fps_ms or not materialize_ms:
        return False
    mine = fps_ms if configured == "fps" else materialize_ms
    other = materialize_ms if configured == "fps" else fps_ms
    return mine > factor * other


def _calibrate_pipeline(leg, spec, checker):
    """Times one calibration wave per expansion pipeline for this leg's
    model (breakdown.measure_pipeline_choice), records the configured
    pipeline next to both timings, and warns (stderr +
    ``bench.pipeline_mismatch`` counter — it rides the leg's telemetry
    snapshot) when the configured one is measurably slower. Never fatal:
    a failed calibration returns its error instead of killing the leg."""
    configured = getattr(checker, "pipeline", None)
    try:
        from stateright_tpu.checker.breakdown import measure_pipeline_choice

        res = measure_pipeline_choice(
            spec["model"](),
            frontier_capacity=min(
                spec["spawn"].get("frontier_capacity", 1 << 10), 1 << 10
            ),
            table_capacity=min(
                spec["spawn"].get("table_capacity", 1 << 16), 1 << 18
            ),
            wave_dedup=spec["spawn"].get("wave_dedup"),
        )
    except Exception as e:  # noqa: BLE001 - calibration is advisory
        return {"configured": configured, "error": repr(e)}
    res["configured"] = configured
    if res.get("supported"):
        mismatch = evaluate_pipeline_choice(
            configured, res.get("fps_ms"), res.get("materialize_ms")
        )
        res["mismatch"] = mismatch
        if mismatch:
            from stateright_tpu.telemetry import metrics_registry

            metrics_registry().counter("bench.pipeline_mismatch").inc()
            log(
                f"[{leg}] WARNING: configured pipeline {configured!r} "
                f"measured slower than {res['measured_faster']!r} "
                f"(fps {res['fps_ms']}ms vs materialize "
                f"{res['materialize_ms']}ms) — pass "
                f"expand_fps={configured != 'fps'} to spawn_tpu_bfs or "
                "update the leg spec"
            )
    return res


def _dedup_for(spec, platform: str) -> str:
    """Wave-dedup resolution shared by the timed legs and the breakdown
    attribution (which must describe the same pipeline): CLI ``--dedup``
    override > an explicit value in the leg spec > the library's shared
    backend default (``checker.tpu.default_wave_dedup`` — the one place
    the policy lives)."""
    override = _parse_dedup_flag()
    if override is not None:
        return override
    explicit = spec["spawn"].get("wave_dedup")
    if explicit is not None:
        return explicit
    from stateright_tpu.checker.tpu import default_wave_dedup

    return default_wave_dedup(platform)


def _run_breakdown(leg: str, pin_cpu: bool):
    """Child entry: per-wave stage cost attribution for one leg's model
    (VERDICT r03 #1b — the judgeability half of the TPU datapoint). Runs
    AFTER the timed legs so its stage-split jits never pollute leg
    timings; prints one JSON line."""
    import jax

    if pin_cpu:
        jax.config.update("jax_platforms", "cpu")
    # After the pin — the cache dir is keyed on the platform line-up.
    from stateright_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    from stateright_tpu.checker.breakdown import measure_wave_breakdown

    spec = _leg_specs()[leg]
    # Attribute the SAME dedup pipeline the timed legs run on this
    # backend — stage numbers for a pipeline the rate never executed
    # would mislead the next round.
    dedup = _dedup_for(spec, jax.devices()[0].platform)
    out = measure_wave_breakdown(
        spec["model"](),
        frontier_capacity=spec["spawn"].get("frontier_capacity", 1 << 11),
        table_capacity=spec["spawn"].get("table_capacity", 1 << 20),
        wave_dedup=dedup,
    )
    print(json.dumps(out))


def _probe_log_summary():
    """Summarizes the standing sentinel's probe log (scripts/
    tpu_sentinel.sh) so a CPU-fallback bench still carries proof of
    continuous tunnel attempts."""
    path = os.path.join(REPO_DIR, "PROBE_LOG.jsonl")
    if not os.path.exists(path):
        return None
    attempts = ok = standdowns = 0
    first = last = None
    last_ok = None
    with open(path) as f:
        for raw in f:
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if rec.get("standdown"):
                # Liveness heartbeat while a full bench held the chip —
                # not a tunnel attempt.
                standdowns += 1
            else:
                attempts += 1
                if rec.get("ok"):
                    ok += 1
                    last_ok = rec.get("ts")
            if first is None:
                first = rec.get("ts")
            last = rec.get("ts")
    return {
        "attempts": attempts,
        "ok": ok,
        "standdowns": standdowns,
        "first": first,
        "last": last,
        "last_ok": last_ok,
    }


def _parse_trace_out():
    """``--trace-out PATH`` (both forms): attach the telemetry JSONL sink.
    In the parent PATH is a base; each leg child gets ``PATH.<leg>.jsonl``
    so per-leg traces never interleave across subprocesses."""
    for i, arg in enumerate(sys.argv):
        if arg == "--trace-out":
            if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
                raise SystemExit("--trace-out requires a path")
            return sys.argv[i + 1]
        if arg.startswith("--trace-out="):
            return arg.split("=", 1)[1]
    return None


def _trace_out_args(leg: str):
    base = _parse_trace_out()
    if base is None:
        return ()
    return ("--trace-out", f"{base}.{leg}.jsonl")


def _parse_float_flag(flag: str):
    """``--flag N`` / ``--flag=N`` parsed as float (explicit error on a
    missing or non-numeric value), or None when absent."""
    for i, arg in enumerate(sys.argv):
        value = None
        if arg == flag:
            if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
                raise SystemExit(f"{flag} requires a numeric value")
            value = sys.argv[i + 1]
        elif arg.startswith(flag + "="):
            value = arg.split("=", 1)[1]
        if value is not None:
            try:
                return float(value)
            except ValueError:
                raise SystemExit(f"{flag} requires a numeric value")
    return None


def _budget_override_args():
    """Parent-level out-of-core and monitor flags must reach every leg
    child (the same silently-no-op hazard ``--dedup`` had). The monitor
    port is shared safely: legs run sequentially, one child at a time."""
    args = []
    for flag in (
        "--hbm-budget-mib",
        "--host-budget-mib",
        "--monitor-port",
        "--stall-deadline-s",
    ):
        value = _parse_float_flag(flag)
        if value is not None:
            args += [flag, str(value)]
    # Boolean flags forwarded verbatim (same silently-no-op hazard).
    for flag in (
        "--attribution", "--coverage", "--no-calibrate",
        "--async-pipeline",
    ):
        if flag in sys.argv:
            args.append(flag)
    return tuple(args)


def _parse_dedup_flag():
    """The one place ``--dedup`` is parsed (both forms, explicit error on
    a missing value — a trailing ``--dedup`` must not IndexError the
    whole bench and ``--dedup=X`` must not silently no-op)."""
    for i, arg in enumerate(sys.argv):
        if arg == "--dedup":
            if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
                raise SystemExit("--dedup requires a value (sort|scatter)")
            return sys.argv[i + 1]
        if arg.startswith("--dedup="):
            return arg.split("=", 1)[1]
    return None


def _dedup_override_args():
    """A parent-level ``--dedup X`` must reach every child (legs and
    breakdowns) or the override silently no-ops while appearing accepted
    (advisor finding, round 4)."""
    value = _parse_dedup_flag()
    return ("--dedup", value) if value is not None else ()


def _child_json(argv, timeout_s: float, label: str):
    """Runs one bench child; returns the JSON dict from its last stdout
    line, or None (wedge/crash/garbage). The shared leg-child protocol:
    stderr inherits the parent's stream so diagnostics (and OOM reports)
    surface live instead of dying with the child."""
    try:
        r = subprocess.run(argv, timeout=timeout_s, stdout=subprocess.PIPE)
    except subprocess.TimeoutExpired:
        log(f"[{label}] wedged after {timeout_s}s")
        return None
    lines = r.stdout.decode().strip().splitlines()
    if r.returncode == 0 and lines:
        try:
            return json.loads(lines[-1])
        except json.JSONDecodeError:
            pass
    log(f"[{label}] failed (rc={r.returncode})")
    return None


def _leg_subprocess(leg: str, pin_cpu: bool, extra=(), trace_name=None):
    """Runs one leg in a child; returns its result dict or None.
    ``trace_name`` overrides the trace filename component (the 2pc retry
    must not reopen — and truncate — the kept CPU result's trace)."""
    argv = [
        sys.executable, __file__, "--leg", leg, "--in-bench",
        *_dedup_override_args(), *_budget_override_args(),
        *_trace_out_args(trace_name or leg),
        *extra,
    ]
    # CPU-pinned fallbacks get extra headroom: they exist so the bench
    # always emits a number, and a slow host must not be killed like a
    # wedged tunnel.
    timeout_s = LEG_TIMEOUT_S[leg] * (3 if pin_cpu else 1)
    if pin_cpu:
        argv.append("--cpu")
    return _child_json(argv, timeout_s, leg)


def _sentinel_device_results():
    """tpu-labeled results the standing sentinel captured in
    DEVICE_RUNS.jsonl — attached to the bench JSON so a CPU-fallback run
    still carries any real device datapoints the sentinel landed."""
    path = os.path.join(REPO_DIR, "DEVICE_RUNS.jsonl")
    if not os.path.exists(path):
        return None
    out = {}
    with open(path) as f:
        for raw in f:
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue
            res = rec.get("result")
            if isinstance(res, dict) and res.get("device") == "tpu":
                key = rec.get("leg") or rec.get("ab") or (
                    "flip_test" if rec.get("flip_test") else None
                )
                if key is None and rec.get("breakdown"):
                    # Breakdown records key "breakdown_<leg>" so they
                    # never collide with the leg's own record.
                    key = f"breakdown_{rec['breakdown']}"
                if key:
                    out[str(key)] = res  # later entries win (retries)
    return out or None


def _validate_flag_combos():
    """Fail dependent-flag combos up front, before any work: in
    full-bench mode a bad combo would otherwise be forwarded to every
    leg child, each burning its timeout on rc=1 + a CPU-pinned fallback
    retry (same must-not-no-op rule as ``--dedup``: a flag the user
    asked for that silently never arms is worse than an error)."""
    for flag, needs in (
        ("--stall-deadline-s", "--monitor-port"),
        ("--host-budget-mib", "--hbm-budget-mib"),
    ):
        if (
            _parse_float_flag(flag) is not None
            and _parse_float_flag(needs) is None
        ):
            raise SystemExit(f"{flag} requires {needs}")


SERVICE_LEG_TIMEOUT_S = 1500


def _pct(values, p):
    """Linear-interpolated percentile (None-safe: None values dropped;
    empty -> None). Stdlib-only so the record never depends on numpy."""
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    if len(vals) == 1:
        return vals[0]
    pos = (p / 100.0) * (len(vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)


def _run_service_leg(pin_cpu: bool, packed: bool = False):
    """Child entry: the checking-as-a-service latency leg (BENCH_r10+;
    ``packed=True`` is the BENCH_r12+ tenant-packed variant).

    Three phases on the 2pc-N workload (its ``sometimes`` agreement
    properties make time-to-first-violation/witness a real latency
    signal while the ``always`` property keeps the run exhaustive):

    1. a batch ``spawn_tpu_bfs`` reference run (the throughput yardstick),
    2. one job through ``CheckService`` (service overhead must stay
       within 10% of the batch path),
    3. >= 4 concurrent jobs. Time-sliced mode (``--service``): a
       sub-second quantum; per-job submit->first-discovery latency
       (p50/p99), aggregate states/s, preemption counts, and the
       shared-AOT-cache evidence (jobs with zero compile phases in
       their attribution ledgers). Packed mode (``--service-packed``,
       default 8 jobs): the same fleet co-scheduled into shared waves —
       the ROADMAP gate is aggregate states/s within 15% of the
       single-job rate with ZERO preemptions, plus the lane-occupancy
       evidence (``pack.lanes_live / pack.lanes_dispatched``).
    """
    import jax

    if pin_cpu:
        jax.config.update("jax_platforms", "cpu")
    from stateright_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.service import CheckService

    device = jax.devices()[0]
    log(f"[service] device: {device.platform} ({device})")
    jobs_n = int(
        _parse_float_flag("--service-jobs") or (8 if packed else 4)
    )
    quantum = _parse_float_flag("--service-quantum") or 0.5
    rm = int(_parse_float_flag("--service-rm") or 5)
    spawn = dict(frontier_capacity=1 << 10, table_capacity=1 << 15)
    out = {
        "device": device.platform,
        "model": f"2pc-{rm}",
        "jobs": jobs_n,
        "quantum_s": quantum,
        "packed": packed,
    }

    # 1. Batch reference (the normal spawn path, identical capacities).
    t0 = time.time()
    batch = TwoPhaseSys(rm).checker().spawn_tpu_bfs(**spawn).join()
    wall = time.time() - t0
    warm = batch.warmup_seconds or 0.0
    expected = batch.unique_state_count()
    out["expected_unique"] = expected
    out["batch_rate"] = expected / max(wall - warm, 1e-9)
    log(f"[service] batch: {expected} unique, {out['batch_rate']:,.0f}/s")

    # No service_dir here: the concurrent phase measures time-sliced
    # throughput with purely in-memory AOT sharing (the r10-comparable
    # metric), and a persistent dir would let later jobs reseed from
    # the single-job phase's finished run. Disk-plane accounting —
    # per-job aot_cache.* counters — lives in the warm-start sub-leg
    # below; per_job rows here record "aot": null, which readers take
    # as "no disk store attached".
    svc = CheckService(
        quantum_s=quantum, default_spawn=spawn,
        packing=packed, max_pack_tenants=max(8, jobs_n),
    )
    try:
        # 2. Single job: no contention, so no preemption — the measured
        # delta vs batch is pure service overhead (scheduler polling).
        h = svc.submit(model_name="2pc", model_args={"rm_count": rm})
        res = h.result(timeout=SERVICE_LEG_TIMEOUT_S / 2)
        if res["unique"] != expected:
            raise AssertionError(
                f"service single-job count mismatch: "
                f"{res['unique']} != {expected}"
            )
        out["single_job_rate"] = res["rate"]
        out["service_overhead_pct"] = 100.0 * (
            1.0 - res["rate"] / out["batch_rate"]
        )
        log(
            f"[service] single job: {res['rate']:,.0f}/s "
            f"({out['service_overhead_pct']:+.1f}% vs batch)"
        )

        # 3. Concurrent load. Time-sliced mode: attribution per job so
        # the ledger proves the AOT-cache sharing (compile-free jobs)
        # and shows preempt overhead as checkpoint phases. Packed mode:
        # no spawn overrides (they would disqualify packing) — the
        # engine's lane counters carry the occupancy evidence instead,
        # isolated in a freshly-reset default registry.
        if packed:
            from stateright_tpu.telemetry import metrics_registry

            metrics_registry().reset()
        t0 = time.time()
        handles = [
            svc.submit(
                model_name="2pc",
                model_args={"rm_count": rm},
                spawn=None if packed else {"attribution": True},
                tenant=f"tenant-{i}",
            )
            for i in range(jobs_n)
        ]
        for h in handles:
            h.result(timeout=SERVICE_LEG_TIMEOUT_S)
        wall = time.time() - t0
        per_job, ttfvs, zero_compile, total_unique = [], [], 0, 0
        for h in handles:
            st = h.status()
            r = st["result"]
            if r["unique"] != expected:
                raise AssertionError(
                    f"{st['job_id']} count mismatch: "
                    f"{r['unique']} != {expected}"
                )
            total_unique += r["unique"]
            lat = st["latency"]
            ttfvs.append(lat["ttfv_s"])
            attr = r.get("attribution") or {}
            # compile_s_total spans every incarnation of a preempted job
            # (the per-run registry accumulates across resumes); the
            # final-ledger sum is the fallback for old records. Packed
            # jobs have no per-job ledger — their honest compile figure
            # is the engine compile time accrued while resident
            # (warmup_s), zero when the pack executables were warm.
            compile_s = r.get("compile_s_total")
            if compile_s is None and packed:
                compile_s = r.get("warmup_s", 0.0)
            if compile_s is None:
                compile_s = attr.get("phases_s", {}).get("compile", 0.0)
                compile_s += (attr.get("outside_wave_s") or {}).get(
                    "compile", 0.0
                )
            if compile_s == 0.0:
                zero_compile += 1
            per_job.append(
                {
                    "job_id": st["job_id"],
                    "tenant": st["tenant"],
                    "unique": r["unique"],
                    "ttfv_s": lat["ttfv_s"],
                    "wall_s": lat["wall_s"],
                    "active_s": lat["active_s"],
                    "queued_s": lat["queued_s"],
                    "preempts": st["preempts"],
                    "slices": st["slices"],
                    "packed": st.get("packed", False),
                    # Fault-tolerance evidence (PR 13): a healthy bench
                    # run shows zeros; a chaos leg shows the recovery.
                    # (A quarantined job would have raised at result()
                    # above, so this is False here by construction —
                    # recorded anyway so report readers key on a real
                    # field.)
                    "retries": st.get("retries", 0),
                    "faults": len(st.get("faults") or []),
                    "quarantined": st.get("state") == "quarantined",
                    # Liveness honesty (ISSUE 14): how `eventually`
                    # verdicts were produced, and downgrades.
                    "liveness_mode": st.get("liveness_mode"),
                    "liveness_reason": st.get("liveness_reason"),
                    # Verification mode (ISSUE 15): exhaustive | swarm.
                    "mode": st.get("mode", "exhaustive"),
                    "rate": r["rate"],
                    "compile_s": compile_s,
                    # Warm-start evidence (ISSUE 19): per-job disk-AOT
                    # counters (absent without a service_dir) and the
                    # seeded flag — report readers distinguish disk hits
                    # from in-memory hits by these.
                    "warm_start": bool(st.get("warm_start")),
                    "aot": r.get("aot"),
                }
            )
        out["aggregate_states_per_s"] = total_unique / wall
        out["service_rate"] = out["aggregate_states_per_s"]
        out["concurrent_wall_s"] = wall
        out["p50_ttfv_s"] = _pct(ttfvs, 50)
        out["p99_ttfv_s"] = _pct(ttfvs, 99)
        out["preempts_total"] = sum(j["preempts"] for j in per_job)
        out["retries_total"] = sum(j["retries"] for j in per_job)
        out["faults_total"] = sum(j["faults"] for j in per_job)
        out["jobs_zero_compile"] = zero_compile
        out["per_job"] = per_job
        # Steady-state aggregate (compile excluded — the same window
        # single_job_rate is measured over, so the two are comparable;
        # the wall-clock aggregate above stays the conservative
        # headline). Pack compiles are one shared wall for every
        # member, so the fleet's compile time is the per-job max.
        compile_wall = max(
            (j["compile_s"] for j in per_job), default=0.0
        )
        out["aggregate_steady_states_per_s"] = total_unique / max(
            wall - compile_wall, 1e-9
        )
        out["aggregate_vs_single_pct"] = 100.0 * (
            out["aggregate_steady_states_per_s"] / out["single_job_rate"]
            - 1.0
        )
        if packed:
            # Lane-occupancy evidence from the engine's counters (the
            # registry was reset just before the fleet was submitted,
            # so these cover exactly the packed phase).
            snap = metrics_registry().snapshot()
            live = snap.get("pack.lanes_live", 0)
            dispatched = snap.get("pack.lanes_dispatched", 0)
            out["pack"] = {
                "waves": snap.get("pack.waves", 0),
                "lanes_live": live,
                "lanes_dispatched": dispatched,
                "lane_fill": (live / dispatched) if dispatched else None,
                "packed_jobs": sum(
                    1 for j in per_job if j.get("packed")
                ),
            }
        def fmt_s(v):
            # ttfv percentiles are None when no job ever discovered a
            # property — the log line must not crash a leg whose
            # throughput/preemption data is complete.
            return "n/a" if v is None else f"{v:.2f}s"

        log(
            f"[service] {jobs_n} concurrent: "
            f"{out['aggregate_states_per_s']:,.0f}/s aggregate, "
            f"ttfv p50={fmt_s(out['p50_ttfv_s'])} "
            f"p99={fmt_s(out['p99_ttfv_s'])}, "
            f"{out['preempts_total']} preempts, "
            f"{zero_compile}/{jobs_n} jobs compile-free"
        )
        # The service's rolling SLO ledger (service/slo.py): per-mode
        # ttfv/verdict percentiles + queue/compile/explore decomposition
        # over everything this leg served — service_report.py renders it
        # as the SLO table.
        out["slo"] = svc.slo.snapshot()
    finally:
        svc.close()

    # 4. Warm-start sub-leg (ISSUE 19): the same shape served from a
    # persistent service_dir across an emulated process restart, with
    # the two planes measured SEPARATELY. The executable plane uses
    # ``target_max_depth`` jobs (a target beyond 2pc's true depth, so
    # the space is explored in full but the job stays out of the seed
    # plane): cold-cold compiles and writes the disk AOT store, a
    # resident resubmit gives the warm ttfv, and the post-restart run
    # must be served compile-free off disk — that cold-vs-warm pair is
    # the headline ``cold_over_warm_pct``. The seed plane uses plain
    # full runs: the first writes the finished-run seed, the
    # post-restart resubmit must reseed (O(verify), near-zero explore).
    # True cross-process isolation is covered by tests/test_warmstart.py
    # and the tier-1 smoke; the bench emulates the restart in-process
    # (clear_shared_aot_caches) so one child carries the whole record.
    # CPU-advisory like every latency number here.
    if not packed:
        import shutil
        import tempfile

        from stateright_tpu.checker.tpu import clear_shared_aot_caches

        wdir = tempfile.mkdtemp(prefix="bench-warmstart-")

        # The sub-leg must measure THIS repo's disk-AOT plane, so jax's
        # own persistent compilation cache is repinned to a fresh temp
        # dir for its duration: executables XLA loads from a warm box
        # cache don't round-trip through serialize_executable ("Symbols
        # not found" on this jax line), so a warm box cache would turn
        # every sub-leg save into an honest save_refused and the
        # cold-process run into a recompile — measuring the box, not
        # the store.
        def _repin_xla_cache(path):
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:
                pass
            try:
                jax.config.update("jax_compilation_cache_dir", path)
            except Exception:
                pass

        xla_prev = jax.config.jax_compilation_cache_dir
        xla_tmp = tempfile.mkdtemp(prefix="bench-warmstart-xla-")
        clear_shared_aot_caches()  # drop exes compiled under the old cache
        _repin_xla_cache(xla_tmp)

        def _one(svc_ws, target=False):
            h = svc_ws.submit(
                model_name="2pc", model_args={"rm_count": rm},
                options={"target_max_depth": 64} if target else None,
            )
            r = h.result(timeout=SERVICE_LEG_TIMEOUT_S / 2)
            st = h.status()
            lat = st["latency"]
            return {
                "ttfv_s": lat["ttfv_s"],
                "wall_s": lat["wall_s"],
                "warmup_s": r["warmup_s"],
                "warm_start": bool(st.get("warm_start")),
                "aot": r.get("aot"),
                "unique": r["unique"],
            }

        try:
            svc_ws = CheckService(
                quantum_s=quantum, default_spawn=spawn,
                packing=False, service_dir=wdir,
            )
            reps = 3  # medians: single-shot ttfv is noise-dominated
            try:
                first = _one(svc_ws, target=True)   # cold-cold: compiles
                warm_rows = [
                    _one(svc_ws, target=True) for _ in range(reps)
                ]  # resident warm resubmits
                _one(svc_ws)  # plain full run: writes the seed
            finally:
                svc_ws.close()
            cold_rows, pool_waits = [], []
            pool_aot = {}
            reseed_row = None
            for i in range(reps):
                clear_shared_aot_caches()  # emulate a process restart
                # The intended cold-process flow: the warm pool
                # pre-loads this shape's executables at service start
                # (from the disk AOT store when present — no compile),
                # so the first real job pays neither compile nor
                # deserialize. The wait-to-ready is recorded.
                t_pool = time.time()
                svc_ws = CheckService(
                    quantum_s=quantum, default_spawn=spawn,
                    packing=False, service_dir=wdir,
                    warm_pool=[("2pc", {"rm_count": rm})],
                )
                try:
                    deadline = time.time() + 120.0
                    while time.time() < deadline and any(
                        e["state"] == "pending"
                        for e in svc_ws.warm_pool_status.values()
                    ):
                        time.sleep(0.05)
                    pool_waits.append(time.time() - t_pool)
                    if i == 0:
                        # Disk-plane evidence lives in the POOL job's
                        # registry (it did the load); the measured job
                        # then finds everything warm in memory.
                        from stateright_tpu.telemetry import (
                            metrics_registry as _mreg,
                        )

                        pool_jid = next(
                            (e.get("job_id")
                             for e in svc_ws.warm_pool_status.values()),
                            None,
                        )
                        pool_aot = {
                            k: v
                            for k, v in (_mreg(pool_jid).snapshot()
                                         if pool_jid else {}).items()
                            if k.startswith("aot_cache.")
                        }
                    cold_rows.append(_one(svc_ws, target=True))
                    if i == reps - 1:
                        reseed_row = _one(svc_ws)  # seeded resubmission
                finally:
                    svc_ws.close()
            warm_row, cold_row = warm_rows[0], cold_rows[0]
            warm_ttfv = _pct(
                [r["ttfv_s"] or r["wall_s"] for r in warm_rows], 50
            )
            cold_ttfv = _pct(
                [r["ttfv_s"] or r["wall_s"] for r in cold_rows], 50
            )
            out["warmstart"] = {
                "process_emulated": True,
                "first_ttfv_s": first["ttfv_s"] or first["wall_s"],
                "first_warmup_s": first["warmup_s"],
                "warm_ttfv_s": warm_ttfv,
                "cold_ttfv_s": cold_ttfv,
                "cold_over_warm_pct": (
                    100.0 * (cold_ttfv / warm_ttfv - 1.0)
                    if warm_ttfv and cold_ttfv is not None
                    else None
                ),
                "cold_warmup_s": cold_row["warmup_s"],
                "cold_pool_wait_s": _pct(pool_waits, 50),
                "cold_pool_aot": pool_aot,
                "cold_aot": cold_row["aot"],
                # Seed plane: the post-restart plain resubmit.
                "seeded": reseed_row["warm_start"],
                "seeded_ttfv_s": (
                    reseed_row["ttfv_s"] or reseed_row["wall_s"]
                ),
                "cpu_advisory": device.platform == "cpu",
            }
            log(
                f"[service] warm-start: first={out['warmstart']['first_ttfv_s']:.2f}s "
                f"warm={warm_ttfv:.3f}s cold-process={cold_ttfv:.3f}s "
                f"({out['warmstart']['cold_over_warm_pct']:+.1f}%, pool "
                f"disk hits {pool_aot.get('aot_cache.disk_hit', 0)}, "
                f"pool wait {out['warmstart']['cold_pool_wait_s']:.2f}s, "
                f"cold warmup={cold_row['warmup_s']:.2f}s); reseed "
                f"{out['warmstart']['seeded_ttfv_s']:.3f}s "
                f"(seeded={reseed_row['warm_start']})"
            )
        finally:
            _repin_xla_cache(xla_prev)
            shutil.rmtree(wdir, ignore_errors=True)
            shutil.rmtree(xla_tmp, ignore_errors=True)
    print(json.dumps(out))


def _run_slo_leg(pin_cpu: bool):
    """Child entry: the end-to-end SLO attribution leg (BENCH_r18).

    Drives a job fleet through every verification mode and records the
    service's rolling SLO ledger (``service/slo.py``): per-mode p50/p99
    ttfv + verdict latency, the queue/compile/explore ttfv
    decomposition (clamped to partition ttfv exactly — the record
    asserts the partition holds within 5%), and burn rates against the
    leg's targets.

    Two service phases on 2pc-N (its ``sometimes`` properties make ttfv
    a real signal):

    1. unpacked service: ``jobs_n`` exhaustive then ``jobs_n`` swarm
       jobs — the ``exhaustive`` / ``swarm`` mode rows;
    2. tenant-packed service: a plain fleet co-scheduled into shared
       waves — the ``packed`` mode row (a packed slice's mode wins over
       its base mode in the ledger).
    """
    import jax

    if pin_cpu:
        jax.config.update("jax_platforms", "cpu")
    from stateright_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    from stateright_tpu.service import CheckService

    device = jax.devices()[0]
    log(f"[slo] device: {device.platform} ({device})")
    rm = int(_parse_float_flag("--service-rm") or 4)
    jobs_n = int(_parse_float_flag("--service-jobs") or 3)
    spawn = dict(frontier_capacity=1 << 10, table_capacity=1 << 15)
    # Deliberately loose targets: a healthy bench leg should show burn
    # rates near zero — the gauges' existence is what the record
    # demonstrates, not a tuned objective.
    targets = {"ttfv_s": 120.0, "verdict_s": 600.0, "objective": 0.9}
    out = {
        "device": device.platform,
        "model": f"2pc-{rm}",
        "jobs_per_mode": jobs_n,
        "slo_targets": targets,
    }

    # Phase 1: unpacked — exhaustive and swarm rows. packing defaults
    # ON, and a packed slice's mode wins in the ledger, so it must be
    # forced off here or every row would land under "packed".
    svc = CheckService(
        default_spawn=spawn, packing=False, slo_targets=targets
    )
    try:
        for mode in ("exhaustive", "swarm"):
            # Swarm jobs need a stop bound at admission (a holding
            # property is never "discovered"); exhaustive jobs stop at
            # fixpoint on their own.
            options = (
                {"target_state_count": 10_000} if mode == "swarm" else {}
            )
            handles = [
                svc.submit(
                    model_name="2pc",
                    model_args={"rm_count": rm},
                    options=options,
                    mode=mode,
                    seed=i,
                )
                for i in range(jobs_n)
            ]
            for h in handles:
                h.result(timeout=SERVICE_LEG_TIMEOUT_S)
            log(f"[slo] {jobs_n} {mode} jobs served")
        snap_unpacked = svc.slo.snapshot()
    finally:
        svc.close()

    # Phase 2: packed — plain fleet, co-scheduled (spawn overrides
    # would disqualify packing, so none are passed).
    svc = CheckService(
        default_spawn=spawn, packing=True,
        max_pack_tenants=max(8, jobs_n), slo_targets=targets,
    )
    try:
        handles = [
            svc.submit(
                model_name="2pc",
                model_args={"rm_count": rm},
                tenant=f"tenant-{i}",
            )
            for i in range(max(2, jobs_n))
        ]
        for h in handles:
            h.result(timeout=SERVICE_LEG_TIMEOUT_S)
        log(f"[slo] {max(2, jobs_n)} packed jobs served")
        snap_packed = svc.slo.snapshot()
    finally:
        svc.close()

    # One merged snapshot: each mode row comes from the service that
    # actually served that mode (the two ledgers are disjoint by
    # construction — phase 1 never packs, phase 2 only packs).
    slo = dict(snap_unpacked)
    slo["modes"] = {
        m: (
            snap_packed["modes"][m]
            if snap_packed["modes"][m]["jobs"] > 0
            else snap_unpacked["modes"][m]
        )
        for m in snap_unpacked["modes"]
    }
    out["slo"] = slo

    # Acceptance evidence: the decomposition partitions ttfv within 5%
    # per mode (exactly, by construction — recorded so the check is a
    # number in the record, not a claim in a docstring).
    partitions = {}
    for mode, view in slo["modes"].items():
        last = (view.get("last") or {}).get("decomposition")
        if last:
            gap = abs(
                last["queue_s"] + last["compile_s"] + last["explore_s"]
                - last["ttfv_s"]
            )
            partitions[mode] = gap <= 0.05 * max(last["ttfv_s"], 1e-9)
    out["decomposition_partitions"] = partitions

    def fmt_s(v):
        return "n/a" if v is None else f"{v:.2f}s"

    for mode, view in slo["modes"].items():
        if view["jobs"]:
            log(
                f"[slo] {mode}: {view['jobs']} jobs, ttfv "
                f"p50={fmt_s(view['ttfv']['p50_s'])} "
                f"p99={fmt_s(view['ttfv']['p99_s'])}, verdict "
                f"p50={fmt_s(view['verdict']['p50_s'])}"
            )
    print(json.dumps(out))


def _main_slo():
    """Parent entry for ``bench.py --slo``: runs the SLO leg in a child
    (wedge isolation) and writes ``BENCH_r18.json`` (override with
    ``--slo-out PATH``), printing the same record as the one JSON
    line. Render with ``scripts/slo_report.py`` or compare the
    trajectory with ``scripts/bench_compare.py --slo``."""
    on_accel = _accelerator_usable()
    passthrough = []
    for flag in ("--service-jobs", "--service-rm"):
        value = _parse_float_flag(flag)
        if value is not None:
            passthrough += [flag, str(value)]

    def run(pin_cpu):
        argv = [sys.executable, __file__, "--slo-leg", *passthrough]
        if pin_cpu:
            argv.append("--cpu")
        return _child_json(
            argv, SERVICE_LEG_TIMEOUT_S * (3 if pin_cpu else 1), "slo"
        )

    rec = run(pin_cpu=not on_accel)
    if rec is None and on_accel:
        log("[slo] falling back to CPU-pinned run")
        rec = run(pin_cpu=True)
    if rec is None:
        print(
            json.dumps(
                {
                    "metric": "service SLO ttfv p50 (per-mode ledger)",
                    "value": 0,
                    "unit": "seconds",
                    "error": "slo leg failed on every backend",
                }
            )
        )
        return
    packed_p50 = (
        rec["slo"]["modes"].get("packed", {}).get("ttfv", {}).get("p50_s")
    )
    record = {
        "metric": "service SLO ttfv p50 (packed mode, queue/compile/"
        "explore attributed)",
        "value": round(packed_p50, 3) if packed_p50 is not None else 0,
        "unit": "seconds",
        **rec,
    }
    out_path = None
    for i, arg in enumerate(sys.argv):
        if arg == "--slo-out" and i + 1 < len(sys.argv):
            out_path = sys.argv[i + 1]
        elif arg.startswith("--slo-out="):
            out_path = arg.split("=", 1)[1]
    if out_path is None:
        out_path = os.path.join(REPO_DIR, "BENCH_r18.json")
    with open(out_path, "w") as f:
        # One JSON line, like every BENCH_r* record (the line-oriented
        # readers — slo_report, bench_compare — scan for it).
        f.write(json.dumps(record) + "\n")
    log(f"[slo] record written to {out_path}")
    print(json.dumps(record))


CONFORMANCE_TIMEOUT_S = 1800


def _run_conformance_leg(pin_cpu: bool):
    """Child entry: the conformance-plane throughput legs (BENCH_r20).

    (a) **replay**: traces/sec through the vmapped trace replayer at
        batch sizes 1/64/1024 (one jitted ``vmap(lax.scan)`` dispatch
        per batch) — the batching win is the headline: a 1024-lane
        batch must amortize dispatch overhead that dominates at
        batch=1.
    (b) **audit**: histories/sec through the batched device
        linearizability tester at the same batch sizes.
    (c) **divergence-rate sweep**: replay throughput at 0/10/50%
        divergent lanes — the kernel is branchless (a diverged lane
        keeps riding the scan, masked), so throughput must be flat in
        the divergence rate; a slope would mean divergence handling
        re-introduced per-lane control flow.

    Warm convention: every shape dispatches twice, the first run pays
    the compile (recorded as *_cold_s), the second is the steady-state
    headline — the number a resident service's warm pool serves.
    """
    import jax

    if pin_cpu:
        jax.config.update("jax_platforms", "cpu")
    from stateright_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    import random as _random

    from stateright_tpu.conformance import (
        audit_batch,
        mutate_trace,
        random_history,
        random_walk_trace,
        replay_batch,
    )
    from stateright_tpu.service.zoo import aot_namespace, default_zoo

    device = jax.devices()[0]
    log(f"[conformance] device: {device.platform} ({device})")
    model_name = "increment_lock"
    model = default_zoo()[model_name]()
    ns = aot_namespace(model_name, {})
    rng = _random.Random(20)
    T = 16
    batches = (1, 64, 1024)

    # One pool of distinct seeded walks, replicated (fresh ids) up to
    # the largest batch: verdict work is per-lane, so replication keeps
    # generation cheap without making lanes degenerate.
    walk_pool = [
        random_walk_trace(
            model, rng, T, rec_id=f"w{i}", model_name=model_name
        )
        for i in range(32)
    ]
    divergent_pool = []
    for rec in walk_pool:
        mut = mutate_trace(model, rng, rec)
        if mut is not None:
            divergent_pool.append(mut)
    assert divergent_pool, "no mutation sites in the walk pool"

    def trace_batch(n, divergent_fraction=0.0):
        out = []
        n_div = int(round(n * divergent_fraction))
        for i in range(n):
            src = (
                divergent_pool[i % len(divergent_pool)]
                if i < n_div else walk_pool[i % len(walk_pool)]
            )
            out.append(dict(src, id=f"{src['id']}-{i}"))
        return out

    def time_replay(recs, lanes):
        def once():
            t0 = time.perf_counter()
            verdicts = replay_batch(recs, model, ns, T, lanes=lanes)
            return verdicts, time.perf_counter() - t0

        _v, cold = once()
        verdicts, warm = once()
        return verdicts, warm, cold

    out = {
        "device": device.platform,
        "model": model_name,
        "trace_steps": T,
        "replay": {},
        "audit": {},
        "divergence_sweep": {},
    }

    # (a) replay throughput vs batch size.
    for n in batches:
        recs = trace_batch(n)
        verdicts, warm, cold = time_replay(recs, lanes=n)
        assert all(v["conforms"] for v in verdicts)
        rate = n / max(warm, 1e-9)
        out["replay"][str(n)] = {
            "traces_per_s": rate, "warm_s": warm, "cold_s": cold,
        }
        log(
            f"[conformance] replay batch={n}: {rate:,.0f} traces/s "
            f"(warm {warm * 1e3:.1f}ms, cold {cold:.2f}s)"
        )
    b1 = out["replay"]["1"]["traces_per_s"]
    bmax = out["replay"][str(batches[-1])]["traces_per_s"]
    out["replay_batch_amortization"] = bmax / max(b1, 1e-9)

    # (b) audit throughput vs batch size (one shape bucket: the
    # register C=2/O=2 linearizability grid).
    hist_pool = [
        random_history(
            rng, spec="register", semantics="linearizability",
            threads=2, ops_per_thread=2,
            mode=("clean", "random")[i % 2], rec_id=f"h{i}",
        )
        for i in range(64)
    ]
    # Replication must preserve the bucket: drop the occasional
    # off-shape history (a tail op left in flight can reduce O).
    from stateright_tpu.conformance import bucket_key

    key0 = bucket_key(hist_pool[0])
    hist_pool = [h for h in hist_pool if bucket_key(h) == key0]
    for n in batches:
        recs = [
            dict(hist_pool[i % len(hist_pool)], id=f"h{i}-{n}")
            for i in range(n)
        ]

        def once():
            t0 = time.perf_counter()
            verdicts = audit_batch(recs)
            return verdicts, time.perf_counter() - t0

        _v, cold = once()
        verdicts, warm = once()
        assert all("refused" not in v for v in verdicts)
        rate = n / max(warm, 1e-9)
        out["audit"][str(n)] = {
            "histories_per_s": rate, "warm_s": warm, "cold_s": cold,
        }
        log(
            f"[conformance] audit batch={n}: {rate:,.0f} histories/s "
            f"(warm {warm * 1e3:.1f}ms, cold {cold:.2f}s)"
        )

    # (c) divergence-rate sweep at the largest batch: branchless lanes
    # => flat throughput.
    n = batches[-1]
    for frac in (0.0, 0.1, 0.5):
        recs = trace_batch(n, divergent_fraction=frac)
        verdicts, warm, _cold = time_replay(recs, lanes=n)
        n_div = sum(1 for v in verdicts if not v["conforms"])
        assert n_div == int(round(n * frac)), (n_div, frac)
        rate = n / max(warm, 1e-9)
        out["divergence_sweep"][f"{int(frac * 100)}pct"] = {
            "traces_per_s": rate, "divergent_lanes": n_div,
        }
        log(
            f"[conformance] divergence {int(frac * 100)}%: "
            f"{rate:,.0f} traces/s"
        )
    rates = [
        v["traces_per_s"] for v in out["divergence_sweep"].values()
    ]
    out["divergence_flatness"] = min(rates) / max(max(rates), 1e-9)
    print(json.dumps(out))


def _main_conformance():
    """Parent entry for ``bench.py --conformance``: runs the
    conformance throughput legs in a child (wedge isolation) and writes
    ``BENCH_r20.json`` (override with ``--conformance-out PATH``),
    printing the same record as the one JSON line. Render the
    trajectory with ``scripts/bench_compare.py --conformance``."""
    on_accel = _accelerator_usable()

    def run(pin_cpu):
        argv = [sys.executable, __file__, "--conformance-leg"]
        if pin_cpu:
            argv.append("--cpu")
        return _child_json(
            argv, CONFORMANCE_TIMEOUT_S * (3 if pin_cpu else 1),
            "conformance",
        )

    rec = run(pin_cpu=not on_accel)
    if rec is None and on_accel:
        log("[conformance] falling back to CPU-pinned run")
        rec = run(pin_cpu=True)
    if rec is None:
        print(
            json.dumps(
                {
                    "metric": "conformance replay throughput "
                    "(1024-lane batch)",
                    "value": 0,
                    "unit": "traces/sec",
                    "error": "conformance leg failed on every backend",
                }
            )
        )
        return
    headline = rec["replay"]["1024"]["traces_per_s"]
    record = {
        "metric": "conformance replay throughput (1024-lane batch, "
        "vmapped trace replayer)",
        "value": round(headline, 1),
        "unit": "traces/sec",
        "conformance": rec,
    }
    if rec.get("divergence_flatness", 1.0) < 0.5:
        log(
            "[conformance] WARNING: throughput is not flat in the "
            f"divergence rate (min/max {rec['divergence_flatness']:.2f})"
        )
    out_path = None
    for i, arg in enumerate(sys.argv):
        if arg == "--conformance-out" and i + 1 < len(sys.argv):
            out_path = sys.argv[i + 1]
        elif arg.startswith("--conformance-out="):
            out_path = arg.split("=", 1)[1]
    if out_path is None:
        out_path = os.path.join(REPO_DIR, "BENCH_r20.json")
    with open(out_path, "w") as f:
        # One JSON line, like every BENCH_r* record (the line-oriented
        # readers scan for the "conformance" key).
        f.write(json.dumps(record) + "\n")
    log(f"[conformance] record written to {out_path}")
    print(json.dumps(record))


ASYNC_AB_TIMEOUT_S = 1800


def _run_async_ab_leg(pin_cpu: bool):
    """Child entry: the async-pipeline A/B (BENCH_r11+, ROADMAP item 3's
    acceptance gate). One out-of-core 2pc-N run twice with the SAME
    spawn config — async_pipeline off, then on — both with attribution
    ledgers. Asserts bit-identical results (counts, depths,
    discoveries, golden reporter) and records per-leg rate, realized
    pipeline utilization, the async-off ledger's PREDICTED utilization
    under perfect overlap (the PR-7 headroom estimate), and the
    async-on worker's achieved overlap — the instrument closing its
    own loop. Config mirrors tests/test_storage_equivalence.py's
    acceptance run (frontier 16 forces multiple L0 evictions)."""
    import io
    import re

    import jax

    if pin_cpu:
        jax.config.update("jax_platforms", "cpu")
    from stateright_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    from stateright_tpu import WriteReporter
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.telemetry import metrics_registry

    device = jax.devices()[0]
    log(f"[async_ab] device: {device.platform} ({device})")
    rm = int(_parse_float_flag("--ab-rm") or 5)
    model = TwoPhaseSys(rm)
    frontier = 16
    budget = _parse_float_flag("--hbm-budget-mib")
    if budget is None:
        # The smallest admissible budget for this frontier: maximum
        # eviction pressure (THE shared definition — it tracks the
        # checker's load factor and table layout by construction).
        from stateright_tpu.checker.tpu import (
            min_admissible_hbm_budget_mib,
        )

        budget = min_admissible_hbm_budget_mib(model, frontier)
    spawn = dict(
        frontier_capacity=frontier,
        table_capacity=1 << 14,
        hbm_budget_mib=budget,
        attribution=True,
    )
    out = {
        "device": device.platform,
        "model": f"2pc-{rm}",
        "hbm_budget_mib": budget,
        # CPU boxes make the rate half of this leg noise; the
        # utilization delta is the claim (see tier1.yml note). Keyed
        # "async_advisory" so bench_compare's trajectory gate reads it
        # as the advisory flag of the "async" headline leg.
        "async_advisory": device.platform == "cpu",
    }

    def golden(checker):
        sink = io.StringIO()
        checker.report(WriteReporter(sink))
        return re.sub(r"sec=\d+", "sec=_", sink.getvalue())

    legs = {}
    goldens = {}
    for name, async_on in (("async_off", False), ("async_on", True)):
        metrics_registry().reset()
        t0 = time.time()
        checker = (
            TwoPhaseSys(rm)
            .checker()
            .spawn_tpu_bfs(**spawn, async_pipeline=async_on)
            .join()
        )
        wall = time.time() - t0
        warm = checker.warmup_seconds or 0.0
        rep = checker.attribution_report()
        snap = checker.metrics().snapshot()
        leg = {
            "unique": checker.unique_state_count(),
            "states": checker.state_count(),
            "max_depth": checker.max_depth(),
            "wall_s": wall,
            "warmup_s": warm,
            "rate": checker.unique_state_count() / max(wall - warm, 1e-9),
            "utilization": rep.get("utilization"),
            "monitor_utilization_gauge": snap.get(
                "tpu_bfs.pipeline.utilization"
            ),
            "evictions": snap.get("tpu_bfs.storage.evictions"),
            "attribution": rep,
        }
        if async_on:
            leg["overlapped_total_s"] = rep.get("overlapped_total_s")
        if not leg["evictions"]:
            # The leg's whole claim is out-of-core overlap; a budget
            # that never bound (e.g. the load-factor arithmetic above
            # drifting from checker/tpu._MAX_LOAD) would silently
            # compare two in-core runs and report a ~0 delta as if the
            # acceptance gate ran.
            raise AssertionError(
                f"async A/B {name} leg recorded no L0 evictions — the "
                f"hbm budget ({budget} MiB) never bound; the leg is "
                "not measuring the out-of-core pipeline"
            )
        legs[name] = leg
        goldens[name] = golden(checker)
        log(
            f"[async_ab] {name}: {leg['unique']} unique, "
            f"{leg['rate']:,.0f}/s, utilization="
            f"{(leg['utilization'] or 0.0):.3f}"
        )
    identical = (
        legs["async_off"]["unique"] == legs["async_on"]["unique"]
        and legs["async_off"]["states"] == legs["async_on"]["states"]
        and legs["async_off"]["max_depth"] == legs["async_on"]["max_depth"]
        and goldens["async_off"] == goldens["async_on"]
    )
    out["bit_identical"] = identical
    if not identical:
        raise AssertionError(
            "async-on leg diverged from async-off: "
            f"{ {k: (v['unique'], v['states'], v['max_depth']) for k, v in legs.items()} }"
        )
    off_att = legs["async_off"]["attribution"]
    oh = off_att.get("overlap_headroom") or {}
    device_s = (off_att.get("phases_s") or {}).get("device")
    predicted_wall = oh.get("predicted_wall_s")
    out["predicted_utilization"] = (
        device_s / predicted_wall
        if device_s is not None and predicted_wall
        else None
    )
    out["utilization_delta"] = (
        (legs["async_on"]["utilization"] or 0.0)
        - (legs["async_off"]["utilization"] or 0.0)
    )
    out["async_off"] = legs["async_off"]
    out["async_on"] = legs["async_on"]
    print(json.dumps(out))


MEGAKERNEL_TIMEOUT_S = 1800


def _run_megakernel_leg(pin_cpu: bool):
    """Child entry: the fused-wave megakernel A/B (BENCH_r16). Two zoo
    models — 2pc-N (full passing sweep) and the shallow sharded_kv
    torn-write violation — each run twice with the SAME spawn config:
    ``wave_kernel="staged"`` (with ``wave_dedup="sort"``, the discipline
    the fused sweep implements) then ``wave_kernel="fused"``, both with
    attribution ledgers and ``max_drain_waves=1`` so every wave goes
    through the per-wave engine and the ledger prices each dispatch.
    Asserts bit-identical results (counts, depths, golden reporter —
    including the sharded_kv violation trace) and records per-leg
    ``utilization``, ``gap_share``, and ``phase_windows`` (the staged
    chain's ``device`` windows vs the fused path's single
    ``wave_kernel`` dispatch per wave). On CPU the fused kernel runs
    under the Pallas interpreter — utilization is advisory there (the
    interpreter pays a python-loop tax XLA compute doesn't), while the
    gap_share drop (fewer host/dispatch seams per wave) holds on every
    backend."""
    import io
    import re

    import jax

    if pin_cpu:
        jax.config.update("jax_platforms", "cpu")
    from stateright_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    from stateright_tpu import WriteReporter
    from stateright_tpu.models.sharded_kv import ShardedKv
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.telemetry import metrics_registry

    device = jax.devices()[0]
    log(f"[megakernel] device: {device.platform} ({device})")
    rm = int(_parse_float_flag("--mega-rm") or 5)
    spawn = dict(
        frontier_capacity=1024,
        table_capacity=1 << 14,
        attribution=True,
        max_drain_waves=1,
    )
    primary = f"2pc-{rm}"
    models = (
        (primary, lambda: TwoPhaseSys(rm)),
        (
            "sharded_kv(2x2 shallow torn-write)",
            lambda: ShardedKv(2, 2, 1, guarded=False),
        ),
    )
    out = {
        "device": device.platform,
        # CPU boxes run the fused kernel under the Pallas interpreter:
        # wall/utilization are that interpreter's cost, not the
        # megakernel's — the gap_share drop is the portable claim.
        "advisory": device.platform == "cpu",
        "models": {},
    }

    def golden(checker):
        sink = io.StringIO()
        checker.report(WriteReporter(sink))
        return re.sub(r"sec=\d+", "sec=_", sink.getvalue())

    for mname, make in models:
        legs = {}
        goldens = {}
        for leg, kw in (
            ("staged", dict(wave_dedup="sort")),
            ("fused", dict(wave_kernel="fused")),
        ):
            metrics_registry().reset()
            t0 = time.time()
            checker = (
                make().checker().spawn_tpu_bfs(**spawn, **kw).join()
            )
            wall = time.time() - t0
            warm = checker.warmup_seconds or 0.0
            rep = checker.attribution_report()
            legs[leg] = {
                "unique": checker.unique_state_count(),
                "states": checker.state_count(),
                "max_depth": checker.max_depth(),
                "wall_s": wall,
                "warmup_s": warm,
                "rate": checker.unique_state_count()
                / max(wall - warm, 1e-9),
                "utilization": rep.get("utilization"),
                "gap_share": rep.get("gap_share"),
                "phase_windows": rep.get("phase_windows"),
                "attribution": rep,
            }
            goldens[leg] = golden(checker)
            log(
                f"[megakernel] {mname} {leg}: "
                f"{legs[leg]['unique']} unique, "
                f"utilization={(legs[leg]['utilization'] or 0.0):.3f}, "
                f"gap_share={(legs[leg]['gap_share'] or 0.0):.3f}"
            )
        identical = (
            legs["staged"]["unique"] == legs["fused"]["unique"]
            and legs["staged"]["states"] == legs["fused"]["states"]
            and legs["staged"]["max_depth"] == legs["fused"]["max_depth"]
            and goldens["staged"] == goldens["fused"]
        )
        if not identical:
            raise AssertionError(
                f"fused leg diverged from staged on {mname}: "
                f"{ {k: (v['unique'], v['states'], v['max_depth']) for k, v in legs.items()} }"
            )
        rec = {
            "bit_identical": True,
            "staged": legs["staged"],
            "fused": legs["fused"],
            "utilization_delta": (
                (legs["fused"]["utilization"] or 0.0)
                - (legs["staged"]["utilization"] or 0.0)
            ),
            "gap_share_delta": (
                (legs["fused"]["gap_share"] or 0.0)
                - (legs["staged"]["gap_share"] or 0.0)
            ),
        }
        if rec["gap_share_delta"] >= 0:
            log(
                f"[megakernel] WARNING: {mname} fused gap_share did not "
                f"drop ({rec['gap_share_delta']:+.3f})"
            )
        if rec["utilization_delta"] <= 0 and not out["advisory"]:
            log(
                f"[megakernel] WARNING: {mname} fused utilization did "
                f"not rise ({rec['utilization_delta']:+.3f})"
            )
        out["models"][mname] = rec
    prim = out["models"][primary]
    out["bit_identical"] = all(
        r["bit_identical"] for r in out["models"].values()
    )
    out["gap_share_delta"] = prim["gap_share_delta"]
    out["utilization_delta"] = prim["utilization_delta"]
    print(json.dumps(out))


LIVENESS_TIMEOUT_S = 1200


class _LevelDag:
    """The absence-certification workload (BENCH_r14): a wide, shallow
    DAG — every maximal path ends at a terminal ``level == L`` state
    where the ``eventually "done"`` condition finally holds. No cycles,
    no condition-false terminal ⇒ NO counterexample, so certifying
    absence costs the FULL condition-false region on the host post-pass
    (one Python ``actions``+``next_state`` re-expansion per false
    state) but only the trim fixpoint on the device path (~L peel
    rounds — the ≥5× headline). Width/level tuned to ~74K states.

    Host states are ACTOR-SHAPED on purpose — a (level, field-tuple,
    message-frozenset) record, not a bare int — so the host pass pays
    the per-state construction + hashing cost real models pay (the
    ``checker/liveness.py`` docstring's thousands-to-tens-of-thousands
    states/s bracket); a bare-int encoding would flatter the host pass
    ~30× below any workload anyone actually checks. The packed side is
    the same u32 codec either way (``pack_state`` strips the
    deterministic garnish), so the two paths explore the identical
    region."""

    W = 1 << 13
    WB = 13  # bit-width of the value field
    L = 20

    def _mk(self, level, value):
        bits = tuple((value >> i) & 1 for i in range(self.WB))
        msgs = frozenset((i, b) for i, b in enumerate(bits) if b)
        return (level, bits, msgs)

    def _value(self, state):
        return sum(b << i for i, b in enumerate(state[1]))

    def init_states(self):
        return [self._mk(0, 0)]

    def within_boundary(self, state):
        return True

    def actions(self, state, actions):
        if state[0] < self.L:
            actions.extend((0, 1))

    def next_state(self, state, action):
        level = state[0]
        value = (2 * self._value(state) + action + level) % self.W
        return self._mk(level + 1, value)

    def properties(self):
        from stateright_tpu import Property

        return [
            Property.eventually("done", lambda _m, s: s[0] == self.L)
        ]

    # -- packed protocol ---------------------------------------------------

    def packed_action_count(self):
        return 2

    def packed_init_states(self):
        import jax.numpy as jnp

        return {"s": jnp.zeros((1,), jnp.uint32)}

    def packed_step(self, state, action_id):
        import jax.numpy as jnp

        s = state["s"]
        W = jnp.uint32(self.W)
        level, value = s // W, s % W
        valid = level < jnp.uint32(self.L)
        nxt = (level + 1) * W + (
            2 * value + action_id.astype(jnp.uint32) + level
        ) % W
        return {"s": jnp.where(valid, nxt, s)}, valid

    def packed_conditions(self):
        import jax.numpy as jnp

        return [lambda st: (st["s"] // jnp.uint32(self.W)) == self.L]

    def pack_state(self, host_state):
        import numpy as np

        return {
            "s": np.uint32(host_state[0] * self.W + self._value(host_state))
        }

    def unpack_state(self, packed):
        s = int(packed["s"])
        return self._mk(s // self.W, s % self.W)


def _run_liveness_leg(pin_cpu: bool):
    """Child entry: the device-liveness legs (BENCH_r14).

    (a) raft-3 check-live config (lossy, stable-leader): the
        ``liveness="device"`` run must produce a REAL counterexample —
        the soundness headline — with the analysis cost recorded.
    (b) absence certification at equal state count: the _LevelDag
        region, certified absent by the device trim/reach pass vs the
        host post-pass exhausting the same condition-false region —
        the ≥5× wall-clock claim (advisory outside the acceptance
        gate, like every timing on a shared box)."""
    import jax

    if pin_cpu:
        # See _run_leg: sitecustomize overrides the env var, so re-pin
        # through the config.
        jax.config.update("jax_platforms", "cpu")

    from stateright_tpu.checker.liveness import find_eventually_lasso
    from stateright_tpu.core.batch import BatchableModel
    from stateright_tpu.core.model import Model
    from stateright_tpu.models.raft import RaftModelCfg

    device = jax.devices()[0]
    log(f"[liveness] device: {device.platform} ({device})")

    # (a) raft-3 check-live, device path.
    raft = (
        RaftModelCfg(server_count=3, max_term=1, lossy=True)
        .into_model()
        .retain_properties("stable leader")
    )
    t0 = time.perf_counter()
    ck = (
        raft.checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 10, table_capacity=1 << 14,
            liveness="device",
        )
        .join()
    )
    raft_wall = time.perf_counter() - t0
    found = ck.discoveries()
    assert "stable leader" in found, "device path missed the raft-3 lasso"
    path = found["stable leader"]
    prop = raft.properties()[0]
    assert not any(prop.condition(raft, s) for s in path.into_states())
    raft_rec = {
        "unique": ck.unique_state_count(),
        "wall_s": raft_wall,
        "warmup_s": ck.warmup_seconds,
        "certificate_len": len(path),
        "liveness": ck.liveness_report(),
    }
    log(
        f"[liveness] raft-3 check-live: counterexample len "
        f"{len(path)} over {ck.unique_state_count()} states in "
        f"{raft_wall:.1f}s"
    )

    # (b) absence certification, equal state count both ways.
    class _Dag(_LevelDag, Model, BatchableModel):
        pass

    dag = _Dag()
    t0 = time.perf_counter()
    dev = (
        dag.checker()
        .spawn_tpu_bfs(
            frontier_capacity=1 << 12, table_capacity=1 << 17,
            liveness="device",
        )
        .join()
    )
    dev_wall = time.perf_counter() - t0
    outcome = dev._live_outcomes["done"]
    assert outcome["verdict"] == "absent", outcome
    analysis_cold_s = outcome["seconds"]
    # Steady-state analysis (the bench-wide warmup convention): the
    # first pass pays the trim/reach kernel compiles — one-time per
    # padded shape class — so the headline is the re-run, with the
    # cold number recorded alongside.
    from stateright_tpu.checker.device_liveness import analyze_liveness

    t0 = time.perf_counter()
    _paths, warm_outcomes = analyze_liveness(
        dag, dag.properties(), dev._ebit, dev._live_store,
        dev._host_fp, set(),
    )
    analysis_s = time.perf_counter() - t0
    assert warm_outcomes["done"]["verdict"] == "absent"

    host_model = _Dag()
    t0 = time.perf_counter()
    host_verdict = find_eventually_lasso(
        host_model, host_model.properties()[0]
    )
    host_pass_s = time.perf_counter() - t0
    assert host_verdict is None
    speedup = host_pass_s / max(analysis_s, 1e-9)
    log(
        f"[liveness] absence @ {dev.unique_state_count()} states: "
        f"device analysis {analysis_s:.2f}s vs host post-pass "
        f"{host_pass_s:.2f}s ({speedup:.1f}x)"
    )

    record = {
        "metric": "device-liveness absence certification vs host "
        "post-pass (equal state count)",
        "value": round(speedup, 1),
        "unit": "x host post-pass",
        "device": device.platform,
        "advisory": device.platform == "cpu",
        "raft3_check_live": raft_rec,
        "absence": {
            "states": dev.unique_state_count(),
            "device_analysis_s": analysis_s,
            "device_analysis_cold_s": analysis_cold_s,
            "device_wall_s": dev_wall,
            "device_warmup_s": dev.warmup_seconds,
            "host_pass_s": host_pass_s,
            "speedup": speedup,
            "trim_rounds": outcome.get("trim_rounds"),
            "edges": outcome.get("edges"),
            "liveness": dev.liveness_report(),
        },
    }
    print(json.dumps(record))


SWARM_TIMEOUT_S = 1200


def _run_swarm_leg(pin_cpu: bool):
    """Child entry: the swarm-verification legs (BENCH_r15).

    (a) raft-3 check-live time-to-first-violation: the exhaustive
        path must enumerate + run the liveness analysis before it can
        produce the `stable leader` counterexample; the swarm's
        randomized walks hit a leaderless cycle in a fraction of that
        wall — the headline ttfv speedup.
    (b) 2pc-3 witness hunt: swarm vs exhaustive wall to both
        `sometimes` examples (the easy-workload sanity leg; 2pc-3 on
        purpose — see the inline note on conjunctive witnesses).
    (c) sharded_kv at S=4/K=8/V=3 (~10^14 states — beyond the tiered
        store): walk-steps/s, the unique-coverage sample, and the
        `no torn writes` violation exhaustive checking cannot reach.
    """
    import jax

    if pin_cpu:
        jax.config.update("jax_platforms", "cpu")

    from stateright_tpu.models.raft import RaftModelCfg
    from stateright_tpu.models.sharded_kv import ShardedKv
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    device = jax.devices()[0]
    log(f"[swarm] device: {device.platform} ({device})")

    def swarm_run(model, seed, **kw):
        t0 = time.perf_counter()
        ck = model.checker().spawn_swarm(seed=seed, **kw).join()
        wall = time.perf_counter() - t0
        assert ck.worker_error() is None
        return ck, wall

    # The bench-wide warmup convention: every path runs twice under a
    # shared AOT cache — the first run pays the compiles (recorded as
    # *_cold_s), the second is the steady-state headline. A resident
    # service amortizes compiles across jobs (checker/swarm.py's wave
    # cache / checker/tpu.py's shared_aot_cache), so the warm number is
    # the one production traffic sees.
    # (a) raft-3 check-live: swarm vs exhaustive ttfv.
    def raft():
        return (
            RaftModelCfg(server_count=3, max_term=1, lossy=True)
            .into_model()
            .retain_properties("stable leader")
        )

    def exhaustive_raft():
        t0 = time.perf_counter()
        ck = (
            raft()
            .checker()
            .spawn_tpu_bfs(
                frontier_capacity=1 << 10, table_capacity=1 << 14,
                liveness="device", aot_cache="bench:raft3-live",
            )
            .join()
        )
        assert "stable leader" in ck.discoveries()
        return ck, time.perf_counter() - t0

    ex, exhaustive_cold = exhaustive_raft()
    _ex_warm, exhaustive_ttfv = exhaustive_raft()

    # One model INSTANCE per swarm leg, reused across the cold and warm
    # runs: the swarm wave cache pins models by identity, so a fresh
    # model per run would make the "warm" number pay the compile again
    # (the exhaustive side's shared_aot_cache is signature-keyed and
    # doesn't care).
    raft_model = raft()

    def swarm_raft():
        return swarm_run(
            raft_model, seed=7, lanes=512, wave_steps=64,
            max_trace_len=128, sample_capacity=1 << 15,
            sample_stride=8, aot_cache="bench:raft3-swarm",
        )

    sw, swarm_cold = swarm_raft()
    assert "stable leader" in sw.discoveries(), "swarm missed the lasso"
    _sw_warm, swarm_ttfv = swarm_raft()
    speedup = exhaustive_ttfv / max(swarm_ttfv, 1e-9)
    log(
        f"[swarm] raft-3 check-live ttfv (warm): swarm "
        f"{swarm_ttfv:.2f}s vs exhaustive {exhaustive_ttfv:.2f}s "
        f"({speedup:.1f}x; cold {swarm_cold:.1f}s vs "
        f"{exhaustive_cold:.1f}s)"
    )
    raft_rec = {
        "swarm_ttfv_s": swarm_ttfv,
        "swarm_ttfv_cold_s": swarm_cold,
        "exhaustive_ttfv_s": exhaustive_ttfv,
        "exhaustive_ttfv_cold_s": exhaustive_cold,
        "speedup": speedup,
        "swarm_walk_steps": sw.state_count(),
        "swarm_sample": sw.coverage_estimate(),
        "exhaustive_unique": ex.unique_state_count(),
    }

    # (b) 2pc-3 witness hunt (warm both ways, same convention). 2pc-3
    # on purpose: the all-N-commit witness needs ~3N coordinated steps
    # with abort actions competing at every one, so its per-walk hit
    # probability falls exponentially in N — at N>=4 uniform walks need
    # minutes where BFS needs seconds. That asymmetry is recorded here
    # honestly (the README table: rare coordinated witnesses and
    # certified absence are exhaustive territory; deep violations are
    # the swarm's — leg (c)).
    def exhaustive_2pc():
        t0 = time.perf_counter()
        ck = (
            TwoPhaseSys(3)
            .checker()
            .spawn_tpu_bfs(
                frontier_capacity=1 << 9, table_capacity=1 << 13,
                aot_cache="bench:2pc3",
            )
            .join()
        )
        return ck, time.perf_counter() - t0

    ex2, ex2_cold = exhaustive_2pc()
    _ex2w, ex2_wall = exhaustive_2pc()

    two_pc_model = TwoPhaseSys(3)

    def swarm_2pc():
        # 2pc's holding `consistent` always-property is never
        # "discovered", so (reference simulation semantics) the run
        # only ends at the walk budget — witness ttfv is measured by
        # polling the discovery names and preempting once both landed.
        t0 = time.perf_counter()
        ck = (
            two_pc_model
            .checker()
            .target_state_count(50_000_000)
            .spawn_swarm(
                seed=11, lanes=512, wave_steps=64,
                max_trace_len=64, sample_capacity=1 << 15,
                sample_stride=4, aot_cache="bench:2pc3-swarm",
            )
        )
        ttfv = None
        while not ck.is_done():
            if {"abort agreement", "commit agreement"} <= set(
                ck._discovery_names()
            ):
                ttfv = time.perf_counter() - t0
                ck.request_preempt()
                break
            time.sleep(0.02)
        ck.join()
        assert ck.worker_error() is None
        assert ttfv is not None, "swarm missed the 2pc-3 witnesses"
        return ck, ttfv

    sw2, sw2_cold = swarm_2pc()
    _sw2w, sw2_wall = swarm_2pc()
    two_pc_rec = {
        "model": "2pc-3",
        "swarm_wall_s": sw2_wall,
        "swarm_wall_cold_s": sw2_cold,
        "exhaustive_wall_s": ex2_wall,
        "exhaustive_wall_cold_s": ex2_cold,
        "swarm_walk_steps": sw2.state_count(),
        "swarm_sample": sw2.coverage_estimate(),
        "exhaustive_unique": ex2.unique_state_count(),
        "note": "conjunctive sometimes-witnesses get exponentially "
        "rare under uniform walks as N grows (2pc-5 takes minutes "
        "where BFS takes seconds) — rare coordinated witnesses are "
        "exhaustive territory; the swarm's is deep violations",
    }
    log(
        f"[swarm] 2pc-3 witnesses (warm): swarm {sw2_wall:.2f}s vs "
        f"exhaustive {ex2_wall:.2f}s"
    )

    # (c) the too-big-to-enumerate leg (~10^14 upper bound) and the
    # HEADLINE ttfv A/B: the deep `no total tear` violation sits >= 16
    # actions from init — the breadth-first frontier explodes long
    # before that depth, so the exhaustive run gets a generous wall
    # budget and is honestly preempted when it blows it; the swarm
    # reaches the depth in one walk.
    def deep_kv():
        return ShardedKv(4, 8, 3, retain=("no total tear",))

    EXHAUSTIVE_BUDGET_S = 60.0
    t0 = time.perf_counter()
    ex3 = deep_kv().checker().spawn_tpu_bfs(
        frontier_capacity=1 << 10, table_capacity=1 << 18,
    )
    ex3_found = None
    while not ex3.is_done():
        if "no total tear" in ex3._discovery_names():
            ex3_found = time.perf_counter() - t0
            break
        if time.perf_counter() - t0 > EXHAUSTIVE_BUDGET_S:
            ex3.request_preempt()
            break
        time.sleep(0.05)
    ex3.join()
    if ex3_found is None and "no total tear" in ex3._discovery_names():
        ex3_found = time.perf_counter() - t0
    ex3_states = ex3.unique_state_count()
    ex3_depth = ex3.max_depth()

    deep_model = deep_kv()

    def swarm_deep():
        return swarm_run(
            deep_model, seed=3, lanes=1024, wave_steps=128,
            max_trace_len=128, sample_capacity=1 << 17,
            sample_stride=8, aot_cache="bench:kv-deep",
        )

    sw3, sw3_cold = swarm_deep()
    assert "no total tear" in sw3._discoveries_fps, (
        "swarm missed the deep torn-write violation"
    )
    _sw3w, sw3_wall = swarm_deep()
    steps_per_s = sw3.state_count() / max(
        sw3_cold - (sw3.warmup_seconds or 0.0), 1e-9
    )
    # The honest headline: exhaustive ttfv when it found it, else the
    # budget it burned without finding it (a LOWER bound on its ttfv).
    ex3_ttfv_bound = (
        ex3_found if ex3_found is not None else EXHAUSTIVE_BUDGET_S
    )
    deep_speedup = ex3_ttfv_bound / max(sw3_wall, 1e-9)
    kv_rec = {
        "model": "sharded_kv(shards=4, keys=8, max_version=3)",
        "state_space_upper_bound": "~1e14",
        "violation": "no total tear (every key torn; depth >= 16)",
        "swarm_ttfv_s": sw3_wall,
        "swarm_ttfv_cold_s": sw3_cold,
        "exhaustive_found": ex3_found is not None,
        "exhaustive_ttfv_s": ex3_found,
        "exhaustive_budget_s": EXHAUSTIVE_BUDGET_S,
        "exhaustive_states_explored": ex3_states,
        "exhaustive_max_depth": ex3_depth,
        "speedup_lower_bound": deep_speedup,
        "ttfv_s": sw3_wall,
        "walk_steps": sw3.state_count(),
        "walk_steps_per_s": steps_per_s,
        "warmup_s": sw3.warmup_seconds,
        "sample": sw3.coverage_estimate(),
        "violation_len": len(sw3._discoveries_fps["no total tear"]),
    }
    log(
        f"[swarm] sharded_kv 4x8 deep violation: swarm ttfv "
        f"{sw3_wall:.2f}s vs exhaustive "
        + (
            f"{ex3_found:.2f}s"
            if ex3_found is not None
            else f"NOT FOUND in {EXHAUSTIVE_BUDGET_S:.0f}s "
            f"({ex3_states:,} states to depth {ex3_depth})"
        )
        + f" (>= {deep_speedup:.0f}x); {steps_per_s:,.0f} walk-steps/s"
    )

    record = {
        "metric": "swarm time-to-first-violation vs exhaustive "
        "(sharded_kv deep torn-write, exhaustive wall-budgeted)",
        "value": round(deep_speedup, 1),
        "unit": "x exhaustive ttfv (lower bound)",
        "device": device.platform,
        "advisory": device.platform == "cpu",
        "swarm": {
            "raft3_check_live": raft_rec,
            "two_phase": two_pc_rec,
            "sharded_kv": kv_rec,
        },
    }
    print(json.dumps(record))


def _main_swarm():
    """Parent entry for ``bench.py --swarm``: runs the swarm legs in a
    child (wedge isolation) and prints the one BENCH-record JSON line
    (BENCH_r15.json; render with ``scripts/bench_compare.py
    --swarm``)."""
    on_accel = _accelerator_usable()

    def run(pin_cpu):
        argv = [sys.executable, __file__, "--swarm-leg"]
        if pin_cpu:
            argv.append("--cpu")
        return _child_json(
            argv, SWARM_TIMEOUT_S * (3 if pin_cpu else 1), "swarm"
        )

    rec = run(pin_cpu=not on_accel)
    if rec is None and on_accel:
        log("[swarm] falling back to CPU-pinned run")
        rec = run(pin_cpu=True)
    if rec is None:
        print(
            json.dumps(
                {
                    "metric": "swarm time-to-first-violation vs "
                    "exhaustive (sharded_kv deep torn-write, "
                    "exhaustive wall-budgeted)",
                    "value": 0,
                    "unit": "x exhaustive ttfv (lower bound)",
                    "error": "swarm leg failed on every backend",
                }
            )
        )
        return
    if rec.get("value", 0) < 1:
        log(
            f"[swarm] WARNING: swarm ttfv {rec.get('value')}x did not "
            "beat exhaustive"
        )
    print(json.dumps(rec))


def _main_liveness():
    """Parent entry for ``bench.py --liveness``: runs the liveness legs
    in a child (wedge isolation) and prints the one BENCH-record JSON
    line (BENCH_r14.json)."""
    on_accel = _accelerator_usable()

    def run(pin_cpu):
        argv = [sys.executable, __file__, "--liveness-leg"]
        if pin_cpu:
            argv.append("--cpu")
        return _child_json(
            argv, LIVENESS_TIMEOUT_S * (3 if pin_cpu else 1), "liveness"
        )

    rec = run(pin_cpu=not on_accel)
    if rec is None and on_accel:
        log("[liveness] falling back to CPU-pinned run")
        rec = run(pin_cpu=True)
    if rec is None:
        print(
            json.dumps(
                {
                    "metric": "device-liveness absence certification "
                    "vs host post-pass (equal state count)",
                    "value": 0,
                    "unit": "x host post-pass",
                    "error": "liveness leg failed on every backend",
                }
            )
        )
        return
    if rec.get("value", 0) < 5:
        log(
            f"[liveness] WARNING: absence-certification speedup "
            f"{rec.get('value')}x below the 5x bar"
        )
    print(json.dumps(rec))


def _main_async_ab():
    """Parent entry for ``bench.py --async-ab``: runs the A/B leg in a
    child (wedge isolation) and prints the one BENCH-record JSON line
    (render it with ``scripts/bench_compare.py --ab-async``)."""
    on_accel = _accelerator_usable()
    passthrough = []
    for flag in ("--ab-rm", "--hbm-budget-mib"):
        value = _parse_float_flag(flag)
        if value is not None:
            passthrough += [flag, str(value)]

    def run(pin_cpu):
        argv = [sys.executable, __file__, "--async-ab-leg", *passthrough]
        if pin_cpu:
            argv.append("--cpu")
        return _child_json(
            argv, ASYNC_AB_TIMEOUT_S * (3 if pin_cpu else 1), "async_ab"
        )

    rec = run(pin_cpu=not on_accel)
    if rec is None and on_accel:
        log("[async_ab] falling back to CPU-pinned run")
        rec = run(pin_cpu=True)
    if rec is None:
        print(
            json.dumps(
                {
                    "metric": "async pipeline A/B "
                    "(out-of-core 2pc, async on vs off)",
                    "value": 0,
                    "unit": "unique states/sec",
                    "error": "async A/B leg failed on every backend",
                }
            )
        )
        return
    line = {
        "metric": "async pipeline A/B "
        f"(out-of-core {rec['model']}, async on vs off)",
        "value": round(rec["async_on"]["rate"], 1),
        "unit": "unique states/sec",
        **rec,
    }
    print(json.dumps(line))


def _main_megakernel():
    """Parent entry for ``bench.py --megakernel``: runs the fused-wave
    A/B leg in a child (wedge isolation) and prints the one BENCH-record
    JSON line (BENCH_r16.json; render with ``scripts/bench_compare.py
    --megakernel``)."""
    on_accel = _accelerator_usable()
    passthrough = []
    value = _parse_float_flag("--mega-rm")
    if value is not None:
        passthrough += ["--mega-rm", str(value)]

    def run(pin_cpu):
        argv = [sys.executable, __file__, "--megakernel-leg", *passthrough]
        if pin_cpu:
            argv.append("--cpu")
        return _child_json(
            argv, MEGAKERNEL_TIMEOUT_S * (3 if pin_cpu else 1), "megakernel"
        )

    rec = run(pin_cpu=not on_accel)
    if rec is None and on_accel:
        log("[megakernel] falling back to CPU-pinned run")
        rec = run(pin_cpu=True)
    if rec is None:
        print(
            json.dumps(
                {
                    "metric": "fused wave megakernel A/B "
                    "(2pc + sharded_kv shallow, staged vs fused)",
                    "value": 0,
                    "unit": "gap_share delta (fused - staged)",
                    "error": "megakernel leg failed on every backend",
                }
            )
        )
        return
    line = {
        "metric": "fused wave megakernel A/B "
        "(2pc + sharded_kv shallow, staged vs fused)",
        "value": round(rec["gap_share_delta"], 4),
        "unit": "gap_share delta (fused - staged)",
        **rec,
    }
    print(json.dumps(line))


MULTICHIP_TIMEOUT_S = 700
MULTICHIP_SHARD_COUNTS = (1, 2, 4, 8)


def _parse_int_flag(flag):
    v = _parse_float_flag(flag)
    return None if v is None else int(v)


def _run_multichip_leg(pin_cpu: bool):
    """Child entry for ``--multichip-leg``: one sharded 2pc-5 run at
    ``--shards N`` with the sieve on or off (``--sieve 0|1``), printing
    counts + steady-state rate + the comms ledger as a JSON line. The
    parent A/Bs these for the MULTICHIP scaling record."""
    shards = _parse_int_flag("--shards") or 8
    sieve = bool(_parse_int_flag("--sieve"))
    if pin_cpu:
        # Virtual shard pool BEFORE backend init: the CPU multichip legs
        # model a pod slice with 8 single-core devices.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if pin_cpu:
        jax.config.update("jax_platforms", "cpu")
    from stateright_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    import numpy as np
    from jax.sharding import Mesh

    from stateright_tpu.models.two_phase_commit import TwoPhaseSys
    from stateright_tpu.telemetry import metrics_registry

    devices = jax.devices()
    if shards > len(devices):
        print(json.dumps({"skipped": f"{shards} shards > {len(devices)}"}))
        return
    mesh = Mesh(np.array(devices[:shards]), ("fp",))
    log(
        f"[multichip] {shards} shard(s) on {devices[0].platform}, "
        f"sieve={'on' if sieve else 'off'}"
    )
    t0 = time.time()
    checker = (
        TwoPhaseSys(5)
        .checker()
        .spawn_sharded_tpu_bfs(
            mesh=mesh,
            frontier_per_device=max(8, 512 // shards),
            table_capacity_per_device=1 << 14,
            sieve=sieve,
        )
        .join()
    )
    wall = time.time() - t0
    err = checker.worker_error()
    if err is not None:
        raise RuntimeError(f"multichip leg failed: {err}")
    warmup = checker.warmup_seconds or 0.0
    unique = checker.unique_state_count()
    snap = metrics_registry().snapshot()
    waves = int(snap.get("sharded_bfs.waves", 0)) or 1
    lanes = int(snap.get("sharded_bfs.comms.lanes_shipped", 0))
    comms = {
        "lanes_shipped": lanes,
        "bytes_shipped": int(snap.get("sharded_bfs.comms.bytes_shipped", 0)),
        "lanes_per_wave": round(lanes / waves, 1),
        "sieve_kill_rate": snap.get("sharded_bfs.comms.sieve.kill_rate", 0.0),
        "bloom_probe_total": int(
            snap.get("sharded_bfs.comms.sieve.bloom_probe_total", 0)
        ),
        "bloom_fp_total": int(
            snap.get("sharded_bfs.comms.sieve.bloom_fp_total", 0)
        ),
        "rung_dispatch": {
            k.rsplit(".", 1)[1]: int(v)
            for k, v in snap.items()
            if k.startswith("sharded_bfs.comms.rung_dispatch.")
        },
    }
    # Fleet skew forensics (MULTICHIP_r07+): the per-shard imbalance
    # evidence — cumulative per-shard gauges, run-total skew, and the
    # EWMA straggler call — plus the fold's own measured overhead (the
    # <5% budget is a recorded number, not an assertion on faith).
    fleet = {
        "waves": int(snap.get("sharded_bfs.fleet.waves", 0)),
        "overhead_s": round(
            snap.get("sharded_bfs.fleet.overhead_seconds", 0.0), 4
        ),
        "straggler_shard": int(
            snap.get("sharded_bfs.fleet.straggler.shard", -1)
        ),
        "straggler_score": round(
            snap.get("sharded_bfs.fleet.straggler.score", 0.0), 3
        ),
        "straggler_persistence": round(
            snap.get("sharded_bfs.fleet.straggler.persistence", 0.0), 3
        ),
        "skew": {
            k.split(".fleet.skew.", 1)[1]: round(v, 3)
            for k, v in snap.items()
            if k.startswith("sharded_bfs.fleet.skew.")
        },
        "insert_load_per_shard": [
            snap.get(f"sharded_bfs.fleet.shard.{d}.insert_load", 0.0)
            for d in range(shards)
        ],
    }
    print(
        json.dumps(
            {
                "shards": shards,
                "sieve": sieve,
                "device": devices[0].platform,
                "unique": unique,
                "states": checker.state_count(),
                "depth": checker.max_depth(),
                "waves": waves,
                "wall_s": round(wall, 2),
                "warmup_s": round(warmup, 2),
                "rate": round(unique / max(wall - warmup, 1e-9), 1),
                "comms": comms,
                "fleet": fleet,
            }
        )
    )


def _main_multichip():
    """Parent entry for ``bench.py --multichip``: the MULTICHIP_r07
    scaling record — states/s vs shard count with a sieve on/off A/B at
    every width, bit-identity gated (identical counts/depths or the
    record says so, with fleet skew forensics per leg from r07 on).
    Writes ``MULTICHIP_r07.json`` (override with
    ``--multichip-out PATH``) with the legacy dryrun keys
    (``n_devices``/``rc``/``ok``/``skipped``/``tail``) plus the curve,
    and prints the same record as the one JSON line."""
    on_accel = _accelerator_usable()

    def run(shards, sieve, pin_cpu):
        argv = [
            sys.executable, __file__, "--multichip-leg",
            "--shards", str(shards), "--sieve", str(int(sieve)),
        ]
        if pin_cpu:
            argv.append("--cpu")
        return _child_json(
            argv,
            MULTICHIP_TIMEOUT_S * (3 if pin_cpu else 1),
            f"multichip-{shards}{'s' if sieve else ''}",
        )

    curve = []
    errors = []
    for shards in MULTICHIP_SHARD_COUNTS:
        pair = {}
        for sieve in (False, True):
            rec = run(shards, sieve, pin_cpu=not on_accel)
            if (rec is None or rec.get("skipped")) and on_accel:
                # "Accelerator usable" may mean a 1-device CPU backend
                # (the probe only proves init works): a leg that skipped
                # for want of devices retries with the virtual 8-device
                # CPU pool, same as an outright crash would.
                log(f"[multichip-{shards}] falling back to CPU-pinned run")
                rec = run(shards, sieve, pin_cpu=True)
            if rec is None or rec.get("skipped"):
                errors.append(
                    f"{shards}-shard sieve={'on' if sieve else 'off'} leg "
                    f"missing"
                )
                continue
            pair["on" if sieve else "off"] = rec
        if not pair:
            continue
        point = {"n_shards": shards}
        for key, rec in pair.items():
            point[f"sieve_{key}"] = rec
        if "on" in pair and "off" in pair:
            identical = all(
                pair["on"][k] == pair["off"][k]
                for k in ("unique", "states", "depth")
            )
            point["bit_identical"] = identical
            if not identical:
                errors.append(f"{shards}-shard sieve A/B results diverge")
            off_lanes = pair["off"]["comms"]["lanes_per_wave"]
            on_lanes = pair["on"]["comms"]["lanes_per_wave"]
            if off_lanes:
                point["lane_reduction_x"] = round(
                    off_lanes / max(on_lanes, 1e-9), 2
                )
        curve.append(point)

    ok = bool(curve) and not errors
    record = {
        # Legacy dryrun-multichip keys first: the series readers
        # (bench_compare --multichip) key on these across r01..r06.
        "n_devices": max(
            (p["n_shards"] for p in curve), default=0
        ),
        "rc": 0 if ok else 1,
        "ok": ok,
        "skipped": False,
        "tail": "; ".join(errors),
        "metric": "sharded states/s vs shard count "
        "(2pc-5, sieve on/off A/B, bit-identity gated)",
        "unit": "unique states/sec",
        "value": (
            curve[-1].get("sieve_on", {}).get("rate", 0) if curve else 0
        ),
        "curve": curve,
    }
    out_path = None
    for i, arg in enumerate(sys.argv):
        if arg == "--multichip-out" and i + 1 < len(sys.argv):
            out_path = sys.argv[i + 1]
        elif arg.startswith("--multichip-out="):
            out_path = arg.split("=", 1)[1]
    if out_path is None:
        out_path = os.path.join(REPO_DIR, "MULTICHIP_r07.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
    log(f"[multichip] record written to {out_path}")
    print(json.dumps(record))


def _main_service(packed: bool = False):
    """Parent entry for ``bench.py --service`` / ``--service-packed``:
    runs the service leg in a child (wedge isolation, like every other
    leg) and prints the one BENCH-record JSON line."""
    on_accel = _accelerator_usable()
    leg_flag = "--service-packed-leg" if packed else "--service-leg"
    label = "service-packed" if packed else "service"
    passthrough = []
    for flag in ("--service-jobs", "--service-quantum", "--service-rm"):
        value = _parse_float_flag(flag)
        if value is not None:
            passthrough += [flag, str(value)]

    def run(pin_cpu):
        argv = [sys.executable, __file__, leg_flag, *passthrough]
        if pin_cpu:
            argv.append("--cpu")
        return _child_json(
            argv, SERVICE_LEG_TIMEOUT_S * (3 if pin_cpu else 1), label
        )

    rec = run(pin_cpu=not on_accel)
    if rec is None and on_accel:
        log(f"[{label}] falling back to CPU-pinned run")
        rec = run(pin_cpu=True)
    kind = "CheckService packed" if packed else "CheckService"
    if rec is None:
        print(
            json.dumps(
                {
                    "metric": "service aggregate unique states/sec "
                    f"({kind}, concurrent 2pc)",
                    "value": 0,
                    "unit": "unique states/sec",
                    "error": "service leg failed on every backend",
                }
            )
        )
        return
    line = {
        "metric": "service aggregate unique states/sec "
        f"({kind}, {rec['jobs']} concurrent {rec['model']})",
        "value": round(rec["aggregate_states_per_s"], 1),
        "unit": "unique states/sec",
        **rec,
    }
    # ``--service-out PATH`` persists the record as a BENCH_r* file
    # (one JSON line, like --slo-out) so the warm-start sub-leg's
    # cold-vs-warm figures land in the trajectory.
    out_path = None
    for i, arg in enumerate(sys.argv):
        if arg == "--service-out" and i + 1 < len(sys.argv):
            out_path = sys.argv[i + 1]
        elif arg.startswith("--service-out="):
            out_path = arg.split("=", 1)[1]
    if out_path is not None:
        with open(out_path, "w") as f:
            f.write(json.dumps(line) + "\n")
        log(f"[{label}] record written to {out_path}")
    print(json.dumps(line))


def main():
    _validate_flag_combos()
    if "--service-packed-leg" in sys.argv:
        return _run_service_leg("--cpu" in sys.argv, packed=True)
    if "--service-leg" in sys.argv:
        return _run_service_leg("--cpu" in sys.argv)
    if "--service-packed" in sys.argv:
        return _main_service(packed=True)
    if "--slo-leg" in sys.argv:
        return _run_slo_leg("--cpu" in sys.argv)
    if "--slo" in sys.argv:
        return _main_slo()
    if "--conformance-leg" in sys.argv:
        return _run_conformance_leg("--cpu" in sys.argv)
    if "--conformance" in sys.argv:
        return _main_conformance()
    if "--service" in sys.argv:
        return _main_service()
    if "--async-ab-leg" in sys.argv:
        return _run_async_ab_leg("--cpu" in sys.argv)
    if "--async-ab" in sys.argv:
        return _main_async_ab()
    if "--megakernel-leg" in sys.argv:
        return _run_megakernel_leg("--cpu" in sys.argv)
    if "--megakernel" in sys.argv:
        return _main_megakernel()
    if "--multichip-leg" in sys.argv:
        return _run_multichip_leg("--cpu" in sys.argv)
    if "--multichip" in sys.argv:
        return _main_multichip()
    if "--liveness-leg" in sys.argv:
        return _run_liveness_leg("--cpu" in sys.argv)
    if "--liveness" in sys.argv:
        return _main_liveness()
    if "--swarm-leg" in sys.argv:
        return _run_swarm_leg("--cpu" in sys.argv)
    if "--swarm" in sys.argv:
        return _main_swarm()
    if "--breakdown" in sys.argv:
        return _run_breakdown(
            sys.argv[sys.argv.index("--breakdown") + 1], "--cpu" in sys.argv
        )
    if "--leg" in sys.argv:
        return _run_leg(
            sys.argv[sys.argv.index("--leg") + 1], "--cpu" in sys.argv
        )

    # Advertise the full-bench run to the sentinel (the chip is
    # single-tenant: a sentinel-fired device run mid-bench would wedge
    # both claimants). Removed in the finally below — a stale pid file
    # plus pid reuse would stand the sentinel down forever.
    try:
        with open(BENCH_PID_FILE, "w") as f:
            f.write(str(os.getpid()))
    except OSError:
        pass
    try:
        _main_benched()
    finally:
        try:
            os.remove(BENCH_PID_FILE)
        except OSError:
            pass


def _main_benched():
    on_accel = _accelerator_usable()
    results = {}
    for i, leg in enumerate(
        ("2pc", "paxos", "ilock", "abd3o", "raft5", "paxos3", "scr4")
    ):
        if not on_accel and i > 0:
            # The tunnel recovers on hour scales; a single cheap re-probe
            # per leg means a mid-bench recovery isn't wasted. (Skipped on
            # the first leg — the initial probe just failed.)
            on_accel = _accelerator_usable(attempts=1)
        res = _leg_subprocess(leg, pin_cpu=False) if on_accel else None
        if res is None:
            if on_accel:
                # A failed device leg usually means the tunnel wedged
                # mid-flight; stop pointing legs at it until a probe says
                # otherwise.
                on_accel = False
            if leg in ACCEL_ONLY_LEGS:
                log(f"[{leg}] accelerator-only leg skipped")
                continue
            log(f"[{leg}] falling back to CPU-pinned run")
            res = _leg_subprocess(leg, pin_cpu=True)
        if res is not None:
            results[leg] = res

    # End-of-bench device retry: if the primary leg fell back to CPU but
    # the tunnel has since recovered, one more attempt buys the round a
    # real device number on the headline metric.
    if (
        results.get("2pc", {}).get("device") == "cpu"
        and _accelerator_usable(attempts=1)
    ):
        log("[2pc] tunnel recovered post-bench; retrying primary leg on device")
        res = _leg_subprocess(
            "2pc", pin_cpu=False, extra=["--no-host-baseline"],
            trace_name="2pc_retry",
        )
        if res is not None and res.get("device") != "cpu":
            # The retry skipped the host baseline; carry the original over.
            res["host_rate"] = results["2pc"].get("host_rate")
            results["2pc"] = res

    if "2pc" not in results:
        # Still emit the JSON line (the output contract) with an error
        # marker rather than nothing.
        print(
            json.dumps(
                {
                    "metric": f"2pc-{RM_COUNT} exhaustive unique "
                    "states/sec (TpuBfs)",
                    "value": 0,
                    "unit": "unique states/sec",
                    "vs_baseline": 0,
                    "error": "primary 2pc leg failed on every backend",
                }
            )
        )
        return
    primary = results["2pc"]
    line = {
        "metric": f"2pc-{RM_COUNT} exhaustive unique states/sec (TpuBfs)",
        "value": round(primary["rate"], 1),
        "unit": "unique states/sec",
        "vs_baseline": round(primary["rate"] / primary["host_rate"], 3),
        # The denominator is this repo's own pure-Python host BfsChecker —
        # NOT the reference's Rust engine. The reference publishes no
        # absolute numbers (BASELINE.md) and this image has no Rust
        # toolchain to measure one, so the only defensible reference-engine
        # figure is the one implied by the driver's own north-star
        # arithmetic: >=50M states/s at >=20x the 32-thread Rust
        # BfsChecker implies ~2.5M states/s for the Rust engine on paxos.
        "baseline": "host BfsChecker (pure Python), same model, capped run"
        " — NOT the reference Rust engine",
        "ref_engine_estimate": {
            "states_per_sec": 2_500_000,
            "basis": "implied by BASELINE.md north-star (50M/s at 20x the"
            " 32-thread Rust BfsChecker); not measured — no Rust"
            " toolchain on this image, reference publishes no figures."
            " vs_baseline does NOT claim a win over the Rust engine.",
        },
        "unique_states": primary["unique"],
        "wall_s": round(primary["wall_s"], 2),
        "warmup_s": round(primary["warmup_s"], 2),
        "device": primary["device"],
    }
    line["run_mode"] = primary.get("run_mode", "in_bench")
    # Occupancy-adaptive dispatch trajectory (BENCH_r06+): the primary
    # leg's bucket histogram + frontier fill + donation status ride the
    # headline line, per-leg ones ride the loop below.
    if primary.get("bucket_dispatch"):
        line["bucket_dispatch"] = primary["bucket_dispatch"]
    if primary.get("frontier_fill") is not None:
        line["frontier_fill"] = round(primary["frontier_fill"], 4)
    line["donation"] = primary.get("donation", False)
    if primary.get("storage"):
        line["storage"] = primary["storage"]
    if primary.get("hbm_budget_mib") is not None:
        line["hbm_budget_mib"] = primary["hbm_budget_mib"]
    if primary.get("attribution"):
        line["attribution"] = primary["attribution"]
    if primary.get("coverage"):
        line["coverage"] = primary["coverage"]
    if primary.get("pipeline_choice"):
        line["pipeline_choice"] = primary["pipeline_choice"]
    for leg in ("paxos", "ilock", "abd3o", "raft5", "paxos3", "scr4"):
        if leg in results:
            line[f"{leg}_rate"] = round(results[leg]["rate"], 1)
            line[f"{leg}_unique"] = results[leg]["unique"]
            line[f"{leg}_wall_s"] = round(results[leg]["wall_s"], 2)
            line[f"{leg}_device"] = results[leg]["device"]
            if results[leg].get("bucket_dispatch"):
                line[f"{leg}_bucket_dispatch"] = results[leg][
                    "bucket_dispatch"
                ]
            if results[leg].get("frontier_fill") is not None:
                line[f"{leg}_frontier_fill"] = round(
                    results[leg]["frontier_fill"], 4
                )
            if results[leg].get("advisory"):
                line[f"{leg}_advisory"] = True
            if "ttc_s" in results[leg]:
                line[f"{leg}_ttc_s"] = round(results[leg]["ttc_s"], 2)
            if results[leg].get("storage"):
                line[f"{leg}_storage"] = results[leg]["storage"]
            if results[leg].get("attribution"):
                line[f"{leg}_attribution"] = results[leg]["attribution"]
            if results[leg].get("coverage"):
                line[f"{leg}_coverage"] = results[leg]["coverage"]
            if results[leg].get("pipeline_choice"):
                line[f"{leg}_pipeline_choice"] = results[leg][
                    "pipeline_choice"
                ]

    # Judgeability (VERDICT r03 #1b): per-wave stage attribution + roofline
    # for the headline leg and the predicate-heavy ABD leg, run after the
    # timed legs. Each is its own subprocess so a wedged breakdown costs
    # its own timeout only.
    for leg in ("2pc", "abd3o", "paxos3"):
        argv = [
            sys.executable, __file__, "--breakdown", leg,
            *_dedup_override_args(),
        ]
        if not on_accel:
            argv.append("--cpu")
        try:
            r = subprocess.run(argv, timeout=600, stdout=subprocess.PIPE)
            if r.returncode == 0 and r.stdout.strip():
                line[f"breakdown_{leg}"] = json.loads(
                    r.stdout.decode().strip().splitlines()[-1]
                )
        except (subprocess.TimeoutExpired, json.JSONDecodeError):
            log(f"[breakdown {leg}] failed or timed out")

    probes = _probe_log_summary()
    if probes is not None:
        line["tunnel_probe_log"] = probes
    sentinel = _sentinel_device_results()
    if sentinel is not None:
        line["sentinel_device_runs"] = sentinel
    print(json.dumps(line))


if __name__ == "__main__":
    main()
