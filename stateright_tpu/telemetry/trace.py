"""Trace events: spans + instants, ring buffer, JSONL sink, Chrome export.

Events are recorded in Chrome trace-event form directly (``name``, ``ph``,
``ts``/``dur`` in microseconds, ``pid``/``tid``, ``args``) so the JSONL
sink is a plain line-per-event stream and the Perfetto export is just an
envelope around the same dicts. Timestamps come from
``time.perf_counter_ns`` — monotonic, so span durations are exact even
across wall-clock adjustments.

The in-memory ring buffer is always on (bounded, last-N events) and the
no-sink path is the fast path: one small dict + a deque append per event.
Per-state recording is a design error — backends emit one span per
wave/block/drain, keeping overhead well under the always-on budget
(asserted by ``tests/test_telemetry.py``'s overhead micro-benchmark).

``device_annotation``/``device_step_annotation`` bridge host spans into
``jax.profiler`` annotations so they line up with XLA device traces in
TensorBoard/Perfetto; they degrade to no-ops when jax (or its profiler)
is unavailable, keeping this module importable everywhere.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Dict, IO, Iterable, List, Optional

RING_CAPACITY = 4096


def _now_us() -> float:
    return time.perf_counter_ns() / 1000.0


def _flush_close(file, owns, lock):
    with lock:
        try:
            file.flush()
        except ValueError:
            pass  # already closed (idempotent close / atexit replay)
        if owns:
            file.close()


class JsonlSink:
    """Appends each event as one JSON line; thread-safe, flushed per
    write so a killed run still leaves a parseable prefix. ``close()``
    always flushes (even for caller-owned files) and every sink carries
    a ``weakref.finalize`` — it fires at interpreter exit so a short run
    that never detaches its sink still lands its tail events on disk,
    but unlike ``atexit.register(self.close)`` it does not pin the sink
    (and its fd) for the whole process lifetime: a long-lived service
    that churns through sinks gets each one flushed and released at GC."""

    def __init__(self, path_or_file):
        if hasattr(path_or_file, "write"):
            self._file: IO[str] = path_or_file
            self._owns = False
            self.path = getattr(path_or_file, "name", None)
        else:
            self._file = open(path_or_file, "w")
            self._owns = True
            self.path = os.fspath(path_or_file)
        self._lock = threading.Lock()
        self._finalizer = weakref.finalize(
            self, _flush_close, self._file, self._owns, self._lock
        )

    def write_event(self, event: Dict) -> None:
        line = json.dumps(event, separators=(",", ":"))
        try:
            with self._lock:
                self._file.write(line + "\n")
                self._file.flush()
        except ValueError:
            # remove_sink() can close this file while another checker's
            # worker thread is mid-_emit with a stale reference; telemetry
            # must never turn that race into a worker_error on an
            # otherwise healthy run. The event survives in the ring.
            pass

    def close(self) -> None:
        self._finalizer()  # at most once; later calls are no-ops


class _Span:
    """Context manager for one complete ("X") event. ``args`` is mutable
    until exit — callers fill in quantities only known at span end (a
    wave's new-unique count, dedup rate, occupancy)."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def set(self, **kwargs) -> "_Span":
        self.args.update(kwargs)
        return self

    def __enter__(self) -> "_Span":
        self._t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        t1 = _now_us()
        self._tracer._emit(
            {
                "name": self.name,
                "ph": "X",
                "ts": self._t0,
                "dur": t1 - self._t0,
                "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": self.args,
            }
        )


class _NullSpan:
    """The disabled-tracer span: still yields an object with the span
    surface so call sites stay unconditional."""

    __slots__ = ("args",)

    def __init__(self):
        self.args: Dict = {}

    def set(self, **kwargs) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    def __init__(self, ring_capacity: int = RING_CAPACITY):
        self._ring: deque = deque(maxlen=ring_capacity)
        self._sinks: List[JsonlSink] = []
        self.enabled = True
        # Emit lock: the async wave engine's host worker closes wave
        # spans concurrently with the checker thread's drain/compile
        # spans (and the monitor's tracer-sink tap consumes both), so
        # the ring append + sink fan-out must be one atomic step —
        # unlocked, a tap could observe event B before event A from the
        # thread that emitted A first, and interleaved sink writes
        # would tear. deque.append alone is GIL-atomic; the
        # append-then-fan-out sequence is not.
        self._emit_lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **args) -> "_Span":
        """``with tracer.span("tpu_bfs.wave", frontier=F) as sp: ...`` —
        the span records begin/duration on exit; fill late-bound args via
        ``sp.set(...)`` or ``sp.args[...]``."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        """A point event (scope: thread)."""
        if not self.enabled:
            return
        self._emit(
            {
                "name": name,
                "ph": "i",
                "ts": _now_us(),
                "s": "t",
                "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": args,
            }
        )

    def _emit(self, event: Dict) -> None:
        # Never held while a signal handler might re-enter: the flight
        # recorder's events() read deliberately stays lock-free (retry
        # loop below) so a SIGTERM dump cannot deadlock against a
        # checker thread parked mid-emit.
        with self._emit_lock:
            self._ring.append(event)
            for sink in self._sinks:
                sink.write_event(event)

    # -- sinks and inspection ----------------------------------------------

    def add_sink(self, sink) -> "JsonlSink":
        """Attaches a sink (anything with ``write_event``); a str/path
        argument is wrapped in a ``JsonlSink``. Returns the sink."""
        if not hasattr(sink, "write_event"):
            sink = JsonlSink(sink)
        self._sinks.append(sink)
        return sink

    def remove_sink(self, sink, close: bool = True) -> None:
        if sink in self._sinks:
            self._sinks.remove(sink)
        if close and hasattr(sink, "close"):
            sink.close()

    def events(self) -> List[Dict]:
        """The ring buffer's current contents, oldest first. A worker
        thread appending mid-copy raises RuntimeError from deque
        iteration (the flight recorder's SIGTERM dump races live wave
        emission); retry — the ring is bounded, so each attempt is
        fast — then fall back to a per-index best-effort copy rather
        than losing the final-wave forensics entirely."""
        for _ in range(8):
            try:
                return list(self._ring)
            except RuntimeError:
                continue
        out: List[Dict] = []
        for i in range(len(self._ring)):
            try:
                out.append(self._ring[i])
            except IndexError:
                break
        return out

    def clear(self) -> None:
        self._ring.clear()


class RunScopedTracer:
    """A view of a tracer that stamps ``run_id`` into every span's and
    instant's args. Checkers spawned with ``run_id=`` emit through one of
    these, so a multi-run process's interleaved wave spans stay
    attributable — ``MonitorCore(run_filter=...)`` selects one run's
    stream, and trace readers can group by ``args.run_id``. Everything
    else (sinks, ring buffer, enablement) delegates to the wrapped
    tracer: the events still land in THE process-local stream."""

    def __init__(self, run_id: str, tracer: Optional[Tracer] = None):
        self.run_id = run_id
        self._tracer = tracer if tracer is not None else get_tracer()

    def span(self, name: str, **args):
        args.setdefault("run_id", self.run_id)
        return self._tracer.span(name, **args)

    def instant(self, name: str, **args) -> None:
        args.setdefault("run_id", self.run_id)
        self._tracer.instant(name, **args)

    def __getattr__(self, name):
        return getattr(self._tracer, name)


_default_tracer = Tracer()


def get_tracer(run_id: Optional[str] = None):
    """THE process-local tracer every backend records into; with a
    ``run_id``, a :class:`RunScopedTracer` view of it."""
    if run_id is None:
        return _default_tracer
    return RunScopedTracer(run_id, _default_tracer)


def span(name: str, **args) -> "_Span":
    return _default_tracer.span(name, **args)


def instant(name: str, **args) -> None:
    _default_tracer.instant(name, **args)


# -- Chrome trace-event export (Perfetto / chrome://tracing) ---------------


def chrome_trace(events: Optional[Iterable[Dict]] = None) -> Dict:
    """Wraps events (default: the default tracer's ring buffer) in the
    Chrome trace-event JSON envelope. The object form (not the bare
    array) is what Perfetto's JSON importer documents."""
    if events is None:
        events = _default_tracer.events()
    return {
        "traceEvents": list(events),
        "displayTimeUnit": "ms",
    }


def chrome_trace_from_jsonl(path) -> Dict:
    """Re-envelopes a JSONL sink file (one event per line) as Chrome
    trace JSON. Unparseable trailing lines (a killed run's partial
    write) are skipped, never fatal."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return chrome_trace(events)


def write_chrome_trace(path, events: Optional[Iterable[Dict]] = None) -> None:
    with open(path, "w") as f:
        json.dump(chrome_trace(events), f)


# -- jax.profiler bridge ---------------------------------------------------


def device_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` so the host span shows up in
    XLA device traces; a no-op context when jax is unavailable."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 - profiler optional by design
        return contextlib.nullcontext()


def device_step_annotation(name: str, step: int):
    """A ``jax.profiler.StepTraceAnnotation`` (step-aligned variant used
    by the per-wave/per-drain loops); no-op without jax."""
    try:
        import jax

        return jax.profiler.StepTraceAnnotation(name, step_num=step)
    except Exception:  # noqa: BLE001
        return contextlib.nullcontext()
