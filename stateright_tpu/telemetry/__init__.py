"""Unified observability layer for every checker backend.

Three parts, all process-local and always importable:

- ``metrics``: a registry of named counters, gauges, and log-scale
  histograms with cheap ``inc``/``set``/``observe`` calls and a
  ``snapshot() -> dict`` for reporters and benches.
- ``trace``: span and instant events with monotonic timestamps, an
  always-on in-memory ring buffer, an opt-in JSONL sink, and a Chrome
  trace-event exporter (loadable in Perfetto / ``chrome://tracing``),
  plus an optional ``jax.profiler`` bridge so host spans line up with
  XLA device traces.
- ``attribution``: the opt-in wave-timeline attribution engine
  (``WaveAttribution``) — fenced per-wave wall-clock classified into
  device/host phases, with the overlap-headroom ledger
  ``scripts/gap_report.py`` renders.

The quantities GPU model-checking studies show must be observed *during*
runs — frontier width per wave, dedup hit-rate, hash-set load factor —
flow through here from every backend (host BFS/DFS, on-demand,
simulation, the TPU wave/drain loops, and the sharded mesh checker).
"""

from .attribution import WaveAttribution
from .coverage import CoverageLedger, DeviceCoverage
from .fleet import FleetFold, FleetInstruments, skew_stats
from .instruments import (
    BlockInstruments,
    CommsInstruments,
    TenantInstruments,
    WaveInstruments,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    discard_run_registry,
    metrics_registry,
    run_registries,
)
from .trace import (
    JsonlSink,
    RunScopedTracer,
    Tracer,
    chrome_trace,
    chrome_trace_from_jsonl,
    device_annotation,
    device_step_annotation,
    get_tracer,
    instant,
    span,
    write_chrome_trace,
)

# The monitor server drags in http.server (and its email dependency) —
# cost only monitored runs should pay, so its symbols resolve lazily
# (PEP 562), matching the function-local imports in Checker.serve_monitor
# and bench.py's --monitor-port path.
_SERVER_SYMBOLS = frozenset({
    "FlightRecorder",
    "MonitorCore",
    "MonitorServer",
    "ProgressEstimator",
    "StallWatchdog",
    "prometheus_text",
    "prometheus_text_all_runs",
    "registry_hygiene_problems",
})


def __getattr__(name):
    if name in _SERVER_SYMBOLS:
        from . import server

        return getattr(server, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


__all__ = [
    "BlockInstruments",
    "CommsInstruments",
    "Counter",
    "CoverageLedger",
    "DeviceCoverage",
    "FleetFold",
    "FleetInstruments",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "MonitorCore",
    "MonitorServer",
    "ProgressEstimator",
    "RunScopedTracer",
    "StallWatchdog",
    "TenantInstruments",
    "Tracer",
    "WaveAttribution",
    "WaveInstruments",
    "chrome_trace",
    "chrome_trace_from_jsonl",
    "device_annotation",
    "device_step_annotation",
    "discard_run_registry",
    "get_tracer",
    "instant",
    "metrics_registry",
    "prometheus_text",
    "prometheus_text_all_runs",
    "registry_hygiene_problems",
    "run_registries",
    "skew_stats",
    "span",
    "write_chrome_trace",
]
