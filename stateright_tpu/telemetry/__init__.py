"""Unified observability layer for every checker backend.

Two halves, both process-local and always importable:

- ``metrics``: a registry of named counters, gauges, and log-scale
  histograms with cheap ``inc``/``set``/``observe`` calls and a
  ``snapshot() -> dict`` for reporters and benches.
- ``trace``: span and instant events with monotonic timestamps, an
  always-on in-memory ring buffer, an opt-in JSONL sink, and a Chrome
  trace-event exporter (loadable in Perfetto / ``chrome://tracing``),
  plus an optional ``jax.profiler`` bridge so host spans line up with
  XLA device traces.

The quantities GPU model-checking studies show must be observed *during*
runs — frontier width per wave, dedup hit-rate, hash-set load factor —
flow through here from every backend (host BFS/DFS, on-demand,
simulation, the TPU wave/drain loops, and the sharded mesh checker).
"""

from .instruments import BlockInstruments, WaveInstruments
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    metrics_registry,
)
from .trace import (
    JsonlSink,
    Tracer,
    chrome_trace,
    chrome_trace_from_jsonl,
    device_annotation,
    device_step_annotation,
    get_tracer,
    instant,
    span,
    write_chrome_trace,
)

__all__ = [
    "BlockInstruments",
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "Tracer",
    "WaveInstruments",
    "chrome_trace",
    "chrome_trace_from_jsonl",
    "device_annotation",
    "device_step_annotation",
    "get_tracer",
    "instant",
    "metrics_registry",
    "span",
    "write_chrome_trace",
]
