"""Fleet observability: per-shard skew forensics for the sharded mesh.

The sharded checker's controller already sees per-shard data every wave —
``out_specs=P("fp")`` stacks one row per device, and the comms vector is
pulled as an ``(n, k)`` array before it is summed. This module is the
fold that stops throwing the per-shard axis away:

- :class:`FleetFold` — the pure aggregator. Fed one dict of per-shard
  columns per host-visible wave/drain (device counters, per-shard comms
  columns, host-side tier timings), it keeps per-shard running totals,
  per-wave skew (max/mean and coefficient of variation), and a
  persistent-straggler detector (EWMA of each shard's per-wave cost
  share, plus a slowest-wave tally) naming the top-k slowest shards.
- :class:`FleetInstruments` — the fold wired to a ``fleet.*`` metric
  family (per-shard gauges, skew gauges, straggler gauges) and to the
  wave span: ``record_wave`` returns JSON-able ``fleet_*`` span args so
  trace readers (``scripts/gap_report.py --fleet``) and the monitor's
  ``/fleet`` view reconstruct the same fold from the trace alone.

Everything here is host-side numpy over ``n_shards``-length vectors —
the device kernels only stack a few extra int32 scalars per shard — and
the bundle tracks its own fold cost (``fleet.overhead_seconds``) so the
<5% overhead budget is measured, not asserted on faith. Results are
never read back into the search: bit-identity is untouched by
construction.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .metrics import MetricsRegistry, metrics_registry

# Per-shard columns, in the order the fold reports them. The device
# vector (parallel/sharded.py `_wave_core`) carries the first five; the
# comms columns come from the per-shard exchange vector the controller
# already pulls; the host columns are per-shard tier timings.
FLEET_DEVICE_COLS = (
    "live_lanes",    # eval_mask lanes this shard expanded
    "generated",     # candidates generated on this shard
    "fresh",         # claim-winning lanes this shard generated
    "insert_load",   # unique keys RECEIVED at this shard (owner side)
    "overflow",      # probe-cap overflow at this shard's table
)
FLEET_COMMS_COLS = (
    "routed",        # candidate lanes entering this shard's router
    "sieve_hits",    # lanes the receipt cache killed pre-exchange
)
FLEET_HOST_COLS = (
    "probe_ms",      # host tier-probe wall attributed to this shard
    "evict_ms",      # host tier-evict wall attributed to this shard
    "evict_bytes",   # bytes this shard's table drained to its tier
)
FLEET_COLS = FLEET_DEVICE_COLS + FLEET_COMMS_COLS + FLEET_HOST_COLS

# Columns whose skew is worth a gauge (counters with a meaningful
# per-wave mesh mean). `overflow`/`evict_bytes` are episodic, not loads.
SKEW_COLS = ("live_lanes", "fresh", "insert_load", "probe_ms")


def skew_stats(values: Sequence[float]) -> Optional[Dict[str, float]]:
    """max/mean and coefficient of variation of one per-shard vector;
    None when the vector is empty or all-zero (no load, no skew)."""
    v = np.asarray(values, np.float64)
    if v.size == 0:
        return None
    mean = float(v.mean())
    if mean <= 0.0:
        return None
    return {
        "max_over_mean": float(v.max()) / mean,
        "cv": float(v.std()) / mean,
    }


class FleetFold:
    """The pure per-shard aggregator (no registry, no tracer — reusable
    from the monitor's span sink and from trace post-processing).

    ``consume`` takes one wave's ``{col: per-shard vector}`` dict;
    missing columns read as zero. The straggler detector ranks shards by
    an EWMA of their per-wave *cost share*, where a wave's cost vector
    is the host tier wall when any shard paid one (time dominates) and
    the owner-side insert load otherwise (the hash-partition imbalance
    proxy) — falling back to live lanes for waves with neither."""

    def __init__(self, n_shards: Optional[int] = None, hosts: int = 1,
                 top_k: int = 2, ewma_alpha: float = 0.25):
        self.n = n_shards
        self.hosts = max(1, int(hosts))
        self.top_k = max(1, int(top_k))
        self.alpha = float(ewma_alpha)
        self.waves = 0
        self.cost_waves = 0  # waves that carried a nonzero cost vector
        self.totals: Dict[str, np.ndarray] = {}
        self.ewma_share: Optional[np.ndarray] = None
        self.slowest: Optional[np.ndarray] = None
        self.last_skew: Dict[str, Dict[str, float]] = {}

    def _ensure(self, n: int) -> None:
        if self.n is None:
            self.n = n
        if self.ewma_share is None:
            self.totals = {
                c: np.zeros(self.n, np.float64) for c in FLEET_COLS
            }
            self.ewma_share = np.full(self.n, 1.0 / self.n)
            self.slowest = np.zeros(self.n, np.int64)

    def _cost(self, rows: Dict[str, np.ndarray]) -> np.ndarray:
        host = rows["probe_ms"] + rows["evict_ms"]
        if host.sum() > 0.0:
            return host
        if rows["insert_load"].sum() > 0.0:
            return rows["insert_load"]
        return rows["live_lanes"]

    def consume(self, rows: Dict[str, Sequence[float]],
                waves: int = 1) -> Dict[str, object]:
        """Folds one wave (or drain-aggregate: ``waves`` > 1) of
        per-shard columns; returns the wave's skew/straggler block (the
        live view the instruments publish)."""
        n = max(len(v) for v in rows.values())
        self._ensure(n)
        full = {
            c: np.asarray(
                rows.get(c, np.zeros(self.n)), np.float64
            )
            for c in FLEET_COLS
        }
        self.waves += max(1, int(waves))
        for c, v in full.items():
            self.totals[c] += v
        self.last_skew = {
            c: s
            for c in SKEW_COLS
            if (s := skew_stats(full[c])) is not None
        }
        cost = self._cost(full)
        total = float(cost.sum())
        out: Dict[str, object] = {"skew": self.last_skew}
        if total > 0.0:
            share = cost / total
            self.ewma_share = (
                (1.0 - self.alpha) * self.ewma_share + self.alpha * share
            )
            self.slowest[int(cost.argmax())] += 1
            self.cost_waves += 1
            out["cost_skew"] = skew_stats(cost)
        return out

    def stragglers(self) -> List[Dict[str, float]]:
        """The top-k slowest shards by EWMA cost share, slowest first.
        ``score`` is the share normalized by the balanced share ``1/n``
        (1.0 == perfectly balanced); ``persistence`` the fraction of
        cost-bearing waves this shard was the single slowest."""
        if self.ewma_share is None or not self.cost_waves:
            return []
        order = np.argsort(self.ewma_share)[::-1][: self.top_k]
        return [
            {
                "shard": int(d),
                "host": int(d) // max(1, self.n // self.hosts),
                "score": float(self.ewma_share[d] * self.n),
                "share": float(self.ewma_share[d]),
                "persistence": float(self.slowest[d]) / self.cost_waves,
                "slowest_waves": int(self.slowest[d]),
            }
            for d in order
        ]

    def summary(self) -> Dict[str, object]:
        """The ``/fleet`` JSON: per-shard totals + skew + stragglers."""
        if self.n is None or self.ewma_share is None:
            return {"shards": 0, "waves": 0, "per_shard": []}
        per_host = max(1, self.n // self.hosts)
        per_shard = [
            {
                "shard": d,
                "host": d // per_host,
                **{c: float(self.totals[c][d]) for c in FLEET_COLS},
                "cost_share_ewma": float(self.ewma_share[d]),
            }
            for d in range(self.n)
        ]
        skew_totals = {
            c: s
            for c in SKEW_COLS
            if (s := skew_stats(self.totals[c])) is not None
        }
        return {
            "shards": self.n,
            "hosts": self.hosts,
            "waves": self.waves,
            "per_shard": per_shard,
            "skew": skew_totals,
            "skew_last_wave": self.last_skew,
            "stragglers": self.stragglers(),
        }

    # -- span-args round trip (gap_report / MonitorCore) --------------------

    @staticmethod
    def span_args(rows: Dict[str, np.ndarray], shards: int,
                  hosts: int) -> Dict[str, object]:
        """One wave's fold input as JSON-able wave-span args (lists ride
        span args like scalars do)."""
        out: Dict[str, object] = {
            "fleet_shards": int(shards), "fleet_hosts": int(hosts),
        }
        for c in FLEET_COLS:
            v = rows.get(c)
            if v is None:
                continue
            out[f"fleet_{c}"] = [round(float(x), 3) for x in v]
        return out

    def consume_span_args(self, args: Dict[str, object]) -> None:
        """Feeds one wave span's ``fleet_*`` args back through the fold
        (the monitor's sink path — same math as the in-checker fold)."""
        shards = args.get("fleet_shards")
        if not shards:
            return
        self.hosts = max(self.hosts, int(args.get("fleet_hosts") or 1))
        rows = {
            c: args[f"fleet_{c}"]
            for c in FLEET_COLS
            if isinstance(args.get(f"fleet_{c}"), (list, tuple))
        }
        if rows:
            # Drain spans aggregate many device waves into one emission;
            # the span's own `waves` arg keeps the fold's wave count
            # honest (missing -> one host-visible wave).
            try:
                waves = max(1, int(args.get("waves") or 1))
            except (TypeError, ValueError):
                waves = 1
            self.consume(rows, waves=waves)


class FleetInstruments:
    """The fold + the ``fleet.*`` metric family for one sharded run.

    Per-shard gauges (``fleet.shard.<d>.<col>``, cumulative), skew
    gauges (``fleet.skew.<col>.max_over_mean`` / ``.cv`` — last wave's,
    plus the cost-vector pair under ``fleet.skew.cost.*``), straggler
    gauges (``fleet.straggler.shard`` / ``.score`` / ``.persistence``),
    a ``fleet.waves`` counter, and ``fleet.overhead_seconds`` — the
    fold's own measured host cost, the number the opt-out budget test
    holds against total wall."""

    def __init__(self, prefix: str, n_shards: int,
                 registry: MetricsRegistry = None, hosts: int = 1,
                 top_k: int = 2):
        reg = registry if registry is not None else metrics_registry()
        self._registry = reg
        self._prefix = prefix
        self.fold = FleetFold(n_shards, hosts=hosts, top_k=top_k)
        self.waves = reg.counter(f"{prefix}.fleet.waves")
        self.overhead = reg.gauge(f"{prefix}.fleet.overhead_seconds")
        self.overhead_s = 0.0
        self._g_straggler = reg.gauge(f"{prefix}.fleet.straggler.shard")
        self._g_score = reg.gauge(f"{prefix}.fleet.straggler.score")
        self._g_persist = reg.gauge(f"{prefix}.fleet.straggler.persistence")
        # Lazy per-shard / per-column gauges: only columns a run
        # actually records exist in the registry.
        self._shard_gauges: Dict[tuple, object] = {}
        self._skew_gauges: Dict[tuple, object] = {}

    def _shard_gauge(self, d: int, col: str):
        g = self._shard_gauges.get((d, col))
        if g is None:
            g = self._registry.gauge(
                f"{self._prefix}.fleet.shard.{d}.{col}"
            )
            self._shard_gauges[(d, col)] = g
        return g

    def _skew_gauge(self, col: str, stat: str):
        g = self._skew_gauges.get((col, stat))
        if g is None:
            g = self._registry.gauge(
                f"{self._prefix}.fleet.skew.{col}.{stat}"
            )
            self._skew_gauges[(col, stat)] = g
        return g

    def record_wave(self, rows: Dict[str, np.ndarray],
                    waves: int = 1) -> Dict[str, object]:
        """One host-visible wave's (or drain-aggregate's) per-shard
        columns: fold + gauges; returns the ``fleet_*`` span args."""
        t0 = time.perf_counter()
        fold = self.fold
        wave_view = fold.consume(rows, waves=waves)
        self.waves.inc(max(1, int(waves)))
        for d in range(fold.n):
            for c in FLEET_COLS:
                self._shard_gauge(d, c).set(float(fold.totals[c][d]))
        for c, s in wave_view["skew"].items():
            self._skew_gauge(c, "max_over_mean").set(s["max_over_mean"])
            self._skew_gauge(c, "cv").set(s["cv"])
        cost_skew = wave_view.get("cost_skew")
        if cost_skew is not None:
            self._skew_gauge("cost", "max_over_mean").set(
                cost_skew["max_over_mean"]
            )
            self._skew_gauge("cost", "cv").set(cost_skew["cv"])
        top = fold.stragglers()
        if top:
            self._g_straggler.set(top[0]["shard"])
            self._g_score.set(top[0]["score"])
            self._g_persist.set(top[0]["persistence"])
        args = fold.span_args(rows, fold.n, fold.hosts)
        self.overhead_s += time.perf_counter() - t0
        self.overhead.set(self.overhead_s)
        return args

    def summary(self) -> Dict[str, object]:
        out = self.fold.summary()
        out["overhead_s"] = self.overhead_s
        return out


def fleet_prometheus_lines(fold: FleetFold,
                           prefix: str = "stateright") -> List[str]:
    """Per-shard series with ``shard``/``host`` labels for the
    Prometheus exposition (the ``/fleet`` scrape view): one
    ``<prefix>_fleet_<col>{shard=,host=}`` gauge line per shard per
    recorded column, plus the straggler pair."""
    if fold.n is None or fold.ewma_share is None:
        return []
    per_host = max(1, fold.n // fold.hosts)
    lines: List[str] = []
    for c in FLEET_COLS:
        name = f"{prefix}_fleet_{c}"
        lines.append(f"# TYPE {name} gauge")
        for d in range(fold.n):
            lines.append(
                f'{name}{{shard="{d}",host="{d // per_host}"}} '
                f"{float(fold.totals[c][d])!r}"
            )
    name = f"{prefix}_fleet_cost_share_ewma"
    lines.append(f"# TYPE {name} gauge")
    for d in range(fold.n):
        lines.append(
            f'{name}{{shard="{d}",host="{d // per_host}"}} '
            f"{float(fold.ewma_share[d])!r}"
        )
    return lines
