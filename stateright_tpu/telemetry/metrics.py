"""Process-local metrics registry: counters, gauges, log-scale histograms.

Instruments must stay cheap enough to sit on checker hot paths (one call
per wave/block, never per state): ``inc``/``set``/``observe`` take a
per-instrument lock — ``value += x`` is LOAD/ADD/STORE bytecodes, so the
GIL alone would let concurrent host-checker workers lose updates — and
the microseconds that costs disappear at block/wave granularity (the
overhead budget is asserted by tests/test_telemetry.py). The registry
lock guards only instrument *creation* and ``snapshot``'s dict copy.

Naming convention: dotted paths, ``<backend>.<quantity>`` — e.g.
``tpu_bfs.waves``, ``bfs.states_generated``, ``hashset.occupancy``.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Union

Number = Union[int, float]


class Counter:
    """Monotonically increasing count (events, states, waves)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value: Number = 0
        self._lock = threading.Lock()

    def inc(self, amount: Number = 1) -> None:
        with self._lock:
            self.value += amount

    def snapshot(self) -> Number:
        return self.value


class Gauge:
    """Last-written value (occupancy, capacity, frontier width). A plain
    STORE_ATTR is already atomic under the GIL, so no lock."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[Number] = None

    def set(self, value: Number) -> None:
        self.value = value

    def snapshot(self) -> Optional[Number]:
        return self.value


class Histogram:
    """Log-scale (base-2) histogram over positive observations.

    Bucket ``i`` counts observations in ``(2**(i-1), 2**i]`` (bucket 0
    holds ``(0, 1]``; zero/negative observations land in bucket 0 too).
    Log buckets fit the heavy-tailed quantities checkers produce — wave
    widths span 1 to millions — with 64 buckets covering the u64 range.
    Tracks count/sum/min/max exactly alongside the buckets.
    """

    __slots__ = ("name", "buckets", "count", "sum", "min", "max", "_lock")

    N_BUCKETS = 64

    def __init__(self, name: str):
        self.name = name
        self.buckets: List[int] = [0] * self.N_BUCKETS
        self.count = 0
        self.sum: Number = 0
        self.min: Optional[Number] = None
        self.max: Optional[Number] = None
        self._lock = threading.Lock()

    def observe(self, value: Number) -> None:
        self.observe_many(value, 1)

    def observe_many(self, value: Number, n: int) -> None:
        """``n`` identical observations in one locked update — the bulk
        path audit consumers need (e.g. a probe-length distribution
        arriving as per-length counts; per-key ``observe`` calls would
        cost millions of lock round trips)."""
        if n <= 0:
            return
        if value > 1:
            i = min(math.ceil(math.log2(value)), self.N_BUCKETS - 1)
        else:
            i = 0
        with self._lock:
            self.buckets[i] += n
            self.count += n
            self.sum += value * n
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value

    def snapshot(self) -> Dict[str, object]:
        # Trailing empty buckets are elided: most histograms use a narrow
        # band of the 64-bucket range and snapshots feed JSON sinks.
        with self._lock:
            buckets = list(self.buckets)
            count, total = self.count, self.sum
            vmin, vmax = self.min, self.max
        hi = 0
        for i, b in enumerate(buckets):
            if b:
                hi = i + 1
        return {
            "count": count,
            "sum": total,
            "min": vmin,
            "max": vmax,
            "mean": (total / count) if count else None,
            "buckets_log2": buckets[:hi],
        }


class MetricsRegistry:
    """Named instruments, created on first use and stable thereafter.

    ``counter``/``gauge``/``histogram`` are get-or-create: callers hold
    the returned instrument and hit it directly on hot paths instead of
    re-resolving the name. Requesting an existing name as a different
    instrument kind raises — silent kind aliasing would corrupt both
    users' data.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(inst).__name__}, requested {cls.__name__}"
            )
        return inst

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def snapshot(self) -> Dict[str, object]:
        """Point-in-time ``{name: value}`` view of every instrument
        (histograms render as their stats dict), sorted by name for
        stable output."""
        with self._lock:
            items = sorted(self._instruments.items())
        return {name: inst.snapshot() for name, inst in items}

    def instruments(self) -> List:
        """Sorted ``(name, instrument)`` pairs — the typed view exporters
        need (``snapshot`` erases the counter/gauge distinction, which a
        Prometheus exposition cannot afford to lose)."""
        with self._lock:
            return sorted(self._instruments.items())

    def reset(self) -> None:
        """Drops every instrument (tests and run isolation)."""
        with self._lock:
            self._instruments.clear()


_default_registry = MetricsRegistry()

# Per-run registries, keyed by run_id (the multi-tenant namespacing fix:
# two checkers in one process previously collided on every instrument —
# `tpu_bfs.waves` counted both runs' waves and the gauges flapped between
# them). A checker spawned with ``run_id=`` records into its own registry;
# the default (run_id=None) stays THE process-local registry, so
# single-run processes and every existing caller are unchanged.
_run_lock = threading.Lock()
_run_registries: Dict[str, MetricsRegistry] = {}


def metrics_registry(run_id: Optional[str] = None) -> MetricsRegistry:
    """The process-local registry every backend records into, or — given
    a ``run_id`` — that run's own registry (created on first use). Run
    registries isolate concurrent checkers' instruments; drop them with
    ``discard_run_registry`` when the run's numbers are no longer
    needed (a long-lived service would otherwise accrete one registry
    per finished job)."""
    if run_id is None:
        return _default_registry
    reg = _run_registries.get(run_id)
    if reg is None:
        with _run_lock:
            reg = _run_registries.get(run_id)
            if reg is None:
                reg = MetricsRegistry()
                _run_registries[run_id] = reg
    return reg


def run_registries() -> Dict[str, MetricsRegistry]:
    """Snapshot of the per-run registries (``{run_id: registry}``) — the
    monitor's aggregate view iterates this to export every live run."""
    with _run_lock:
        return dict(_run_registries)


def discard_run_registry(run_id: str) -> None:
    """Forgets one run's registry (no-op when absent)."""
    with _run_lock:
        _run_registries.pop(run_id, None)
