"""Wave-timeline attribution: where real-run wall-clock goes between waves.

The telemetry layer says *that* a run is slow (spans, counters, the live
monitor) and ``checker/breakdown.py`` prices the jitted stages offline —
this module attributes the wall-clock of a REAL run to the gaps between
device work. In attribution mode (opt-in: ``spawn_tpu_bfs(...,
attribution=True)`` / ``spawn_sharded_tpu_bfs(..., attribution=True)``)
each host-visible wave is fenced (``jax.block_until_ready`` at phase
boundaries) and its wall time is classified into named phases:

- ``device``      — dispatch + device compute of the wave/drain executable
- ``host_probe``  — the host tier's Bloom+run probe at the wave exit
- ``evict``       — L0→L1 evictions (incl. the merges/spills they trigger)
- ``table_grow``  — device-table rehash growth
- ``checkpoint``  — checkpoint export + pickle
- ``compile``     — rung/table-shape compiles, detected as AOT-cache
  misses at the dispatch site (the one place a compile can happen)
- ``gap``         — the residual: host bookkeeping, transfers the fences
  don't cover, dispatch idle

The invariant is that phases sum to the measured wave wall: ``gap`` is
defined as the residual, so the only way the ledger can drift is phases
OVERRUNNING the wall (clock skew, double counting) — tracked as
``overrun_s`` and asserted under ``tolerance`` (default 5%). Phases never
nest: an inner ``phase()`` opened while another is open records nothing,
so call sites can wrap helpers without auditing their callees.

**Overlapped execution** (the async pipelined wave engine,
``async_pipeline=True``): host-tier work runs on a worker thread UNDER
device compute, so its time is a new phase class — ``overlapped`` —
recorded through the thread-safe ``overlapped(name)`` window instead of
``phase(name)``. Overlapped time is deliberately NOT part of any wave
window's phase set (it is wall-clock the run never paid serially), so
the sum-to-wall invariant stays exact per wave and the 5% tolerance
check is mode-aware by construction: in overlap mode the in-window
phases are device + the few remaining serial host sections, the gap is
the residual as before, and the shadowed host time reports separately
as ``overlapped_s`` (per phase). Each overlapped window also emits a
``<prefix>.pipeline.overlapped`` trace span so ``scripts/gap_report.py``
can render the ACHIEVED overlap next to the predicted headroom.

``overlapped_s`` is worker-side host time — an UPPER bound on the
wall-clock actually saved: the fraction executed while the checker
thread was itself blocked in an epoch-barrier drain (checkpoint
boundaries, queue-empty waits) ran concurrently with an idle device,
not under compute. The realized saving is what ``utilization`` /
wall-clock deltas measure directly; compare async-off vs async-on legs
(``bench.py --async-ab``) for the ground truth.

Results surface everywhere the existing plumbing reaches: per-phase
``<prefix>.pipeline.*`` registry counters/gauges, one
``<prefix>.pipeline`` trace span per wave (args carry ``wall_ms``,
``gap_ms``, and ``<phase>_ms`` — ``scripts/trace_summary.py`` renders the
attribution table, ``scripts/gap_report.py`` the ledger + overlap
headroom), ``monitor.pipeline.*`` in ``/status`` via the monitor sink,
and per-leg ``attribution`` records in ``bench.py --attribution``.

**Overlap headroom** is the go/no-go number for the async pipelined wave
engine (ROADMAP item 2): the wall-clock a perfect overlap of the host
phases (probe/evict/checkpoint) under device compute would save —
``min(host_overlappable_s, device_s)`` — and the predicted wall under it.

When ``jax.profiler`` is available and a ``profile_dir`` is set, a
programmatic capture over the first ``profile_waves`` attributed waves is
parsed (the Chrome-trace export XLA writes) to split device-busy from
device-idle *inside* the ``device`` phase — the fence can only see the
outside of the dispatch.

The clock is injectable (tests drive a fake clock through the classifier
deterministically); ``time.perf_counter`` is the default.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import threading
import time
from typing import Dict, List, Optional

from .metrics import MetricsRegistry, metrics_registry
from .trace import Tracer, get_tracer

__all__ = [
    "DEVICE_PHASES",
    "HOST_OVERLAPPABLE_PHASES",
    "PHASES",
    "WaveAttribution",
    "parse_profile_device_busy",
]

# The canonical phase names (call sites may add others; the ledger carries
# whatever was recorded). Order is the reporting order. Mirrored by
# scripts/trace_summary.py's PHASE_ORDER/HOST_OVERLAPPABLE — the trace
# readers must stay importable without this package (no-jax boxes).
PHASES = (
    "device",
    "wave_kernel",
    "host_probe",
    "evict",
    "table_grow",
    "checkpoint",
    "compile",
)
# Host phases an async pipelined engine could overlap under device
# compute (ROADMAP item 2) — the numerator of the headroom estimate.
# table_grow/compile are device-serial (the next wave needs their
# output), so they are NOT overlappable.
HOST_OVERLAPPABLE_PHASES = ("host_probe", "evict", "checkpoint")
# Phases that ARE device compute: "device" is the staged wave chain,
# "wave_kernel" the fused Pallas megakernel's single dispatch
# (wave_kernel="fused" — ops/pallas_wave.py). Utilization and the
# overlap-headroom denominator sum the class, so the two wave engines
# report comparable ledgers.
DEVICE_PHASES = ("device", "wave_kernel")
DEFAULT_TOLERANCE = 0.05


class _Phase:
    """One timed phase window inside (or between) waves. Non-reentrant by
    design: if another phase is already open this one records nothing
    (phases partition the wave wall; nesting would double-count)."""

    __slots__ = ("_attr", "name", "_t0", "_active")

    def __init__(self, attr: "WaveAttribution", name: str):
        self._attr = attr
        self.name = name
        self._t0 = 0.0
        self._active = False

    def __enter__(self) -> "_Phase":
        attr = self._attr
        if attr._open_phase is None:
            attr._open_phase = self
            self._active = True
            self._t0 = attr._clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._active:
            attr = self._attr
            attr._open_phase = None
            attr._add_phase(self.name, attr._clock() - self._t0)


class _OverlappedPhase:
    """One host-tier window running on the async pipeline's worker
    thread, shadowed under device compute. Thread-safe (its ledger is
    lock-guarded and it never touches the wave window's ``_open_phase``
    state) and reentrant across threads by construction: every window
    records, because overlapped windows measure real concurrent work
    rather than partitioning one thread's wall. Emits a
    ``<prefix>.pipeline.overlapped`` span so trace readers see the
    achieved overlap without the registry."""

    __slots__ = ("_attr", "name", "_t0", "_span")

    def __init__(self, attr: "WaveAttribution", name: str):
        self._attr = attr
        self.name = name

    def __enter__(self) -> "_OverlappedPhase":
        attr = self._attr
        self._span = attr._tracer.span(
            f"{attr.prefix}.pipeline.overlapped", phase=self.name
        )
        self._span.__enter__()
        self._t0 = attr._clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        attr = self._attr
        dt = max(0.0, attr._clock() - self._t0)
        attr._add_overlapped(self.name, dt)
        self._span.set(**{f"{self.name}_ms": dt * 1e3})
        self._span.__exit__(exc_type, exc, tb)


class _Wave:
    """One wave (or drain) window: measures wall, collects the phases
    recorded inside it, computes the residual gap on exit, and emits the
    ``<prefix>.pipeline`` trace span. Exit is idempotent so the worker's
    error path can ``abort()`` a window a crashed loop left open without
    double counting one that closed normally."""

    __slots__ = ("_attr", "kind", "phases", "_t0", "_span", "_done")

    def __init__(self, attr: "WaveAttribution", kind: str):
        self._attr = attr
        self.kind = kind
        self.phases: Dict[str, float] = {}
        self._done = False

    def __enter__(self) -> "_Wave":
        attr = self._attr
        attr._current = self
        attr._maybe_profile_start()
        self._span = attr._tracer.span(
            f"{attr.prefix}.pipeline", kind=self.kind
        )
        self._span.__enter__()
        self._t0 = attr._clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._done:
            return
        self._done = True
        attr = self._attr
        wall = attr._clock() - self._t0
        attr._current = None
        residual = wall - sum(self.phases.values())
        gap = max(0.0, residual)
        overrun = max(0.0, -residual)
        attr._wall_s += wall
        attr._gap_s += gap
        attr._overrun_s += overrun
        if self.kind == "drain":
            attr._drains += 1
        else:
            attr._waves += 1
        attr._c_waves.inc()
        attr._c_wall.inc(wall)
        attr._c_gap.inc(gap)
        if attr._wall_s > 0:
            device = sum(
                attr._totals.get(p, 0.0) for p in DEVICE_PHASES
            )
            attr._g_util.set(device / attr._wall_s)
            attr._g_gap.set(attr._gap_s / attr._wall_s)
        self._span.set(
            wall_ms=wall * 1e3,
            gap_ms=gap * 1e3,
            **{f"{k}_ms": v * 1e3 for k, v in self.phases.items()},
        )
        self._span.__exit__(exc_type, exc, tb)
        attr._maybe_profile_stop()


class WaveAttribution:
    """The per-run attribution engine one checker owns in attribution
    mode. ``wave()`` wraps each host-visible wave/drain window; ``phase()``
    wraps the classified sections inside it; ``fence()`` pins async device
    work into the surrounding phase. ``report()`` returns the ledger."""

    def __init__(
        self,
        prefix: str,
        clock=None,
        tracer: Tracer = None,
        registry: MetricsRegistry = None,
        tolerance: float = DEFAULT_TOLERANCE,
        profile_dir: Optional[str] = None,
        profile_waves: int = 8,
    ):
        self.prefix = prefix
        self._clock = clock if clock is not None else time.perf_counter
        self._tracer = tracer if tracer is not None else get_tracer()
        reg = registry if registry is not None else metrics_registry()
        self._registry = reg
        self.tolerance = tolerance
        self._totals: Dict[str, float] = {}
        # Window counts per phase: the fused wave's dispatch-overhead
        # story needs *how many* kernel dispatches a wave paid, not just
        # their seconds (one "wave_kernel" window per fused dispatch vs
        # the staged chain's per-stage XLA executables).
        self._windows: Dict[str, int] = {}
        # Phase time accrued OUTSIDE any wave window (seed/restore-time
        # checkpoint reads, the restore path's table grows): reported
        # separately so the in-wave phases + gap still sum to the wave
        # wall — folding it into _totals would silently break the
        # ledger invariant on every resumed run.
        self._outside: Dict[str, float] = {}
        self._phase_counters: Dict[str, object] = {}
        # Overlapped ledger (async pipelined engine): host-tier time the
        # worker thread spent shadowed under device compute, per phase.
        # Lock-guarded — the worker and checker threads both reach it.
        self._overlapped: Dict[str, float] = {}
        self._ov_lock = threading.Lock()
        self._ov_counters: Dict[str, object] = {}
        self._overlap_mode = False
        self._wall_s = 0.0
        self._gap_s = 0.0
        self._overrun_s = 0.0
        self._waves = 0
        self._drains = 0
        self._current: Optional[_Wave] = None
        self._open_phase: Optional[_Phase] = None
        p = f"{prefix}.pipeline"
        self._c_waves = reg.counter(f"{p}.waves")
        self._c_wall = reg.counter(f"{p}.wall_seconds")
        self._c_gap = reg.counter(f"{p}.gap_seconds")
        self._g_util = reg.gauge(f"{p}.utilization")
        self._g_gap = reg.gauge(f"{p}.gap_share")
        # Audit surface for the probabilistic machinery: the device
        # hash set's probe-chain displacement distribution (observed at
        # run end from the final table).
        self._probe_hist = reg.histogram(f"{prefix}.hashset.probe_length")
        self._probe_counts: Optional[List[int]] = None
        # jax.profiler window (best effort, never fatal).
        self._profile_dir = profile_dir
        self._profile_waves = max(1, profile_waves)
        self._profile_state = "pending" if profile_dir else "off"
        self._profile_t0_waves = 0
        self.device_split: Optional[Dict[str, float]] = None

    # -- recording ---------------------------------------------------------

    def wave(self, kind: str = "wave") -> _Wave:
        return _Wave(self, kind)

    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    def overlapped(self, name: str) -> _OverlappedPhase:
        """A host-tier window running on the async pipeline's worker
        thread — recorded into the separate ``overlapped`` ledger, never
        into any wave window (see the module docstring's mode-aware
        invariant note)."""
        return _OverlappedPhase(self, name)

    def set_overlap_mode(self, on: bool = True) -> None:
        """Marks the ledger as describing a pipelined run (reported as
        ``overlap_mode``): readers must not expect the host phases
        inside the wave windows — they ride ``overlapped_s``."""
        self._overlap_mode = bool(on)

    def fence(self, tree) -> None:
        """Blocks until every device array in ``tree`` is ready, so the
        surrounding phase window measures real work instead of async
        dispatch latency. Tolerates non-jax leaves and missing jax."""
        try:
            import jax

            jax.block_until_ready(tree)
        except Exception:  # noqa: BLE001 - fencing is best effort
            pass

    def _add_phase(self, name: str, dt: float) -> None:
        if dt < 0:
            dt = 0.0
        cur = self._current
        if cur is not None:
            cur.phases[name] = cur.phases.get(name, 0.0) + dt
            self._totals[name] = self._totals.get(name, 0.0) + dt
            self._windows[name] = self._windows.get(name, 0) + 1
        else:
            self._outside[name] = self._outside.get(name, 0.0) + dt
        c = self._phase_counters.get(name)
        if c is None:
            c = self._registry.counter(
                f"{self.prefix}.pipeline.{name}_seconds"
            )
            self._phase_counters[name] = c
        c.inc(dt)

    def _add_overlapped(self, name: str, dt: float) -> None:
        with self._ov_lock:
            self._overlapped[name] = self._overlapped.get(name, 0.0) + dt
            c = self._ov_counters.get(name)
            if c is None:
                c = self._registry.counter(
                    f"{self.prefix}.pipeline.overlapped.{name}_seconds"
                )
                self._ov_counters[name] = c
            total = self._ov_counters.get("__total__")
            if total is None:
                total = self._registry.counter(
                    f"{self.prefix}.pipeline.overlapped_seconds"
                )
                self._ov_counters["__total__"] = total
        # Counters carry their own locks; inc outside ours.
        c.inc(dt)
        total.inc(dt)

    def abort(self) -> None:
        """Finalizes any window a crashing loop left open (called from
        the checker worker's error path): the open phase is flushed and
        the wave closes normally, so the dying wave's ``.pipeline`` span
        still reaches the trace sinks (flight-recorder forensics) and no
        dangling ``_current``/``_open_phase`` state survives into a
        later ledger read. Also stops a still-running profiler window.
        No-op when nothing is open."""
        phase = self._open_phase
        if phase is not None:
            phase.__exit__(None, None, None)
        cur = self._current
        if cur is not None:
            cur.__exit__(None, None, None)
        self._profile_finalize()

    def observe_probe_lengths(self, counts) -> None:
        """Feeds the device hash set's displacement counts (index =
        probe-chain length, value = resident keys at that length) into
        the ``<prefix>.hashset.probe_length`` log2 histogram and keeps
        the exact counts for the ledger."""
        counts = [int(c) for c in counts]
        while counts and counts[-1] == 0:
            counts.pop()
        self._probe_counts = counts
        for d, c in enumerate(counts):
            if c:
                self._probe_hist.observe_many(d, c)

    # -- jax.profiler window (device-busy split) ---------------------------

    def _maybe_profile_start(self) -> None:
        if self._profile_state != "pending":
            return
        try:
            import jax

            jax.profiler.start_trace(self._profile_dir)
            self._profile_state = "running"
            self._profile_t0_waves = self._waves + self._drains
        except Exception:  # noqa: BLE001 - profiler optional by design
            self._profile_state = "failed"

    def _maybe_profile_stop(self) -> None:
        if self._profile_state != "running":
            return
        done = (self._waves + self._drains) - self._profile_t0_waves
        if done < self._profile_waves:
            return
        self._profile_finalize()

    def _profile_finalize(self) -> None:
        """Stops a still-running profiler window and parses the capture.
        Called from the window-count stop, from ``report()`` (a run that
        finishes in fewer than ``profile_waves`` windows must not leave
        the process profiler running — a later ``start_trace`` would
        raise — nor its capture unwritten), and from ``abort()``."""
        if self._profile_state != "running":
            return
        try:
            import jax

            jax.profiler.stop_trace()
            self._profile_state = "done"
            self.device_split = parse_profile_device_busy(self._profile_dir)
        except Exception:  # noqa: BLE001
            self._profile_state = "failed"

    # -- the ledger ---------------------------------------------------------

    def report(self) -> Dict[str, object]:
        """The phase ledger: totals, shares, the sum-to-wall invariant,
        and the overlap-headroom estimate (ROADMAP item 2's go/no-go)."""
        self._profile_finalize()
        wall = self._wall_s
        phases = {k: v for k, v in sorted(self._totals.items())}
        device = sum(phases.get(p, 0.0) for p in DEVICE_PHASES)
        host = sum(phases.get(p, 0.0) for p in HOST_OVERLAPPABLE_PHASES)
        headroom = min(host, device)
        with self._ov_lock:
            overlapped = dict(sorted(self._overlapped.items()))
        out: Dict[str, object] = {
            "prefix": self.prefix,
            "waves": self._waves,
            "drains": self._drains,
            "wall_s": wall,
            "phases_s": phases,
            "gap_s": self._gap_s,
            "overrun_s": self._overrun_s,
            "tolerance": self.tolerance,
            "within_tolerance": (
                self._overrun_s <= self.tolerance * wall if wall else True
            ),
            "phase_share": (
                {k: v / wall for k, v in phases.items()} if wall else {}
            ),
            "phase_windows": {
                k: v for k, v in sorted(self._windows.items())
            },
            "gap_share": (self._gap_s / wall) if wall else None,
            "utilization": (device / wall) if wall else None,
            "overlap_headroom": {
                "host_overlappable_s": host,
                "device_s": device,
                "headroom_s": headroom,
                "headroom_pct": (headroom / wall) if wall else 0.0,
                "predicted_wall_s": wall - headroom,
            },
            "device_split": self.device_split,
            # Overlapped execution (async pipelined engine): host time
            # shadowed under device compute — NOT in phases_s, so the
            # sum-to-wall invariant above stays exact in both modes.
            "overlap_mode": self._overlap_mode,
        }
        if overlapped or self._overlap_mode:
            out["overlapped_s"] = overlapped
            out["overlapped_total_s"] = sum(overlapped.values())
        if self._outside:
            # Phase time outside any wave window (seed/restore): real,
            # but not part of any wave's wall — reported separately so
            # the invariant above stays exact on resumed runs.
            out["outside_wave_s"] = {
                k: v for k, v in sorted(self._outside.items())
            }
        if self._probe_counts is not None:
            out["probe_length_counts"] = list(self._probe_counts)
        return out


def parse_profile_device_busy(logdir) -> Optional[Dict[str, float]]:
    """Best-effort device-busy/idle split from a ``jax.profiler`` capture:
    finds the newest Chrome-trace export under ``logdir`` and sums the
    complete-event durations on device-named process tracks against the
    tracks' observed span. Returns ``{"busy_s", "idle_s", "span_s",
    "source"}`` or None when no device track exists (CPU-only runs) or
    the capture is unreadable. Overlapping device events are summed, not
    unioned — an approximation, documented as such."""
    try:
        paths = sorted(
            glob.glob(
                os.path.join(logdir, "**", "*.trace.json.gz"),
                recursive=True,
            ),
            key=os.path.getmtime,
        )
        if not paths:
            return None
        with gzip.open(paths[-1], "rt") as f:
            trace = json.load(f)
        events = trace.get("traceEvents", [])
        device_pids = set()
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                pname = (ev.get("args") or {}).get("name", "")
                if "/device:" in pname or pname.startswith("TPU"):
                    device_pids.add(ev.get("pid"))
        if not device_pids:
            return None
        busy_us = 0.0
        t_lo, t_hi = None, None
        for ev in events:
            if ev.get("ph") != "X" or ev.get("pid") not in device_pids:
                continue
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
            busy_us += dur
            t_lo = ts if t_lo is None else min(t_lo, ts)
            t_hi = ts + dur if t_hi is None else max(t_hi, ts + dur)
        if t_lo is None:
            return None
        span_us = t_hi - t_lo
        return {
            "busy_s": busy_us / 1e6,
            "idle_s": max(0.0, span_us - busy_us) / 1e6,
            "span_s": span_us / 1e6,
            "source": "jax.profiler",
        }
    except Exception:  # noqa: BLE001 - profiling is advisory, never fatal
        return None
