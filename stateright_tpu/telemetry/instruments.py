"""Shared per-backend instrument bundles.

Every device checker (single-device and sharded) records the same
quantities per host-visible wave/drain, and every host engine the same
quantities per block; these bundles are the ONE place that shape lives so
the backends cannot drift (the per-wave span args here are the shape
``scripts/trace_summary.py`` and the acceptance trace consume).
"""

from __future__ import annotations

from .metrics import MetricsRegistry, metrics_registry


class WaveInstruments:
    """Counters/gauges/histogram for a device checker's wave loop, named
    ``<prefix>.waves`` etc., plus the canonical per-wave recording."""

    def __init__(self, prefix: str, registry: MetricsRegistry = None):
        reg = registry if registry is not None else metrics_registry()
        self._prefix = prefix
        self._registry = reg
        self.waves = reg.counter(f"{prefix}.waves")
        self.drains = reg.counter(f"{prefix}.drains")
        self.generated = reg.counter(f"{prefix}.states_generated")
        self.unique = reg.counter(f"{prefix}.states_unique")
        self.table_grows = reg.counter(f"{prefix}.table_grows")
        self.occupancy = reg.gauge(f"{prefix}.hashset_occupancy")
        self.capacity = reg.gauge(f"{prefix}.hashset_capacity")
        self.depth = reg.gauge(f"{prefix}.max_depth")
        self.warmup = reg.gauge(f"{prefix}.warmup_seconds")
        self.wave_new = reg.histogram(f"{prefix}.wave_new_unique")
        # Occupancy-adaptive dispatch: the bucket width the last wave ran
        # at, the live-lane fraction of that bucket (compaction ratio),
        # and the live fraction of the configured F_max (frontier fill).
        self.bucket = reg.gauge(f"{prefix}.wave_bucket")
        self.compaction = reg.gauge(f"{prefix}.compaction_ratio")
        self.frontier_fill = reg.gauge(f"{prefix}.frontier_fill")
        # Per-bucket dispatch counters, created lazily per width so the
        # registry only carries the ladder rungs a run actually used.
        self._bucket_counters = {}

    def bucket_dispatch(self, width: int, n: int = 1) -> None:
        """Counts ``n`` wave dispatches at ``width`` lanes (one counter
        per ladder rung: ``<prefix>.bucket_dispatch.<width>``)."""
        c = self._bucket_counters.get(width)
        if c is None:
            c = self._registry.counter(
                f"{self._prefix}.bucket_dispatch.{width}"
            )
            self._bucket_counters[width] = c
        c.inc(n)

    def record(
        self,
        span,
        *,
        frontier: int,
        generated: int,
        n_new: int,
        occupancy: float,
        capacity: int,
        max_depth: int,
        count_wave: bool = True,
        observe: bool = True,
        phase: str = None,
        bucket: int = None,
        compaction_ratio: float = None,
        **extra,
    ) -> None:
        """One wave's (or drain-aggregate's) telemetry: registry updates
        plus — when the caller holds a span open over it — the per-wave
        args. Drain aggregates pass ``count_wave=False``/``observe=False``
        and account their wave tally separately (the final unconsumed
        wave is consumed, and counted, host-side). ``bucket`` /
        ``compaction_ratio`` ride the span when the backend dispatched
        through the occupancy-adaptive bucket ladder."""
        if count_wave:
            self.waves.inc()
        self.generated.inc(generated)
        self.unique.inc(n_new)
        if observe:
            self.wave_new.observe(n_new)
        self.occupancy.set(occupancy)
        self.capacity.set(capacity)
        self.depth.set(max_depth)
        if span is not None:
            if phase is not None:
                extra["phase"] = phase
            if bucket is not None:
                extra["bucket"] = bucket
            if compaction_ratio is not None:
                extra["compaction_ratio"] = compaction_ratio
            span.set(
                frontier=frontier,
                generated=generated,
                new_unique=n_new,
                dedup_hit_rate=(
                    (generated - n_new) / generated if generated else 0.0
                ),
                occupancy=occupancy,
                capacity=capacity,
                max_depth=max_depth,
                **extra,
            )


class CommsInstruments:
    """Cross-shard exchange accounting for the sharded checker, named
    ``<prefix>.comms.*``. One bundle per sharded run; fed from the wave
    kernel's per-wave comms vector (sieve kills, Bloom audit, compacted
    rung) so the ledger reflects what the collectives actually shipped,
    not what the host thinks they should have."""

    def __init__(self, prefix: str, registry: MetricsRegistry = None):
        reg = registry if registry is not None else metrics_registry()
        p = f"{prefix}.comms"
        self._prefix = p
        self._registry = reg
        # Lanes that entered the router vs lanes the receipt cache proved
        # already-visited and dropped before the all_to_all.
        self.sieve_probes = reg.counter(f"{p}.sieve.probes")
        self.sieve_killed = reg.counter(f"{p}.sieve.killed")
        # Bloom audit: routed lanes double as exact membership re-checks
        # (the owner's insert verdict), so FPs are counted, not estimated.
        self.bloom_probes = reg.counter(f"{p}.sieve.bloom_probe_total")
        self.bloom_fps = reg.counter(f"{p}.sieve.bloom_fp_total")
        # What the collectives shipped: key lanes (8B out + 1B flag back
        # each) across all destinations, post-compaction.
        self.lanes_shipped = reg.counter(f"{p}.lanes_shipped")
        self.bytes_shipped = reg.counter(f"{p}.bytes_shipped")
        # Delta-compressed bytes the multi-host eviction exchange put on
        # the wire (storage/runs.py codec) — vs raw 8 B/slot allgather.
        self.evict_wire_bytes = reg.counter(f"{p}.evict_wire_bytes")
        self.kill_rate = reg.gauge(f"{p}.sieve.kill_rate")
        self.fp_rate = reg.gauge(f"{p}.sieve.bloom_fp_rate")
        # Per-rung dispatch counters, lazy like bucket_dispatch.
        self._rung_counters = {}

    # Wire cost per shipped lane: 8 key bytes out + 1 fresh-flag byte back.
    LANE_BYTES = 9

    def rung_dispatch(self, width: int, n: int = 1) -> None:
        """Counts ``n`` exchanges at rung ``width`` lanes per destination
        (``<prefix>.comms.rung_dispatch.<width>``)."""
        c = self._rung_counters.get(width)
        if c is None:
            c = self._registry.counter(
                f"{self._prefix}.rung_dispatch.{width}"
            )
            self._rung_counters[width] = c
        c.inc(n)

    def record(
        self,
        *,
        probes: int,
        killed: int,
        bloom_probes: int,
        bloom_hits: int,
        bloom_fps: int,
        lanes: int,
    ) -> dict:
        """One wave's (or drain-aggregate's) exchange totals. Returns the
        span-args dict so the caller can ride it on the wave span (the
        attribution ledger and ``gap_report`` read it from there)."""
        self.sieve_probes.inc(probes)
        self.sieve_killed.inc(killed)
        self.bloom_probes.inc(bloom_probes)
        self.bloom_fps.inc(bloom_fps)
        self.lanes_shipped.inc(lanes)
        self.bytes_shipped.inc(lanes * self.LANE_BYTES)
        if probes:
            self.kill_rate.set(killed / probes)
        if bloom_probes:
            self.fp_rate.set(bloom_fps / bloom_probes)
        return {
            "comms_probes": probes,
            "comms_killed": killed,
            "comms_bloom_probes": bloom_probes,
            "comms_bloom_hits": bloom_hits,
            "comms_bloom_fps": bloom_fps,
            "comms_lanes": lanes,
            "comms_bytes": lanes * self.LANE_BYTES,
        }


class BlockInstruments:
    """Counters/histogram for a host engine's per-block loop
    (``bfs.block`` / ``dfs.block`` / ``on_demand.block``)."""

    def __init__(self, prefix: str, registry: MetricsRegistry = None):
        reg = registry if registry is not None else metrics_registry()
        self.blocks = reg.counter(f"{prefix}.blocks")
        self.evaluated = reg.counter(f"{prefix}.states_evaluated")
        self.generated = reg.counter(f"{prefix}.states_generated")
        self.block_width = reg.histogram(f"{prefix}.block_states")

    def record(
        self, span, *, evaluated: int, generated: int, max_depth: int,
        unique_total: int, pending: int = None,
    ) -> None:
        """Closes out one block: registry updates + the block span's
        late-bound args (the span is entered by the caller around the
        block body and exited here). ``pending`` is the worker's live
        outstanding-work count — the monitor's frontier fit reads it
        (``evaluated`` is a block-width constant, useless for ETA)."""
        self.blocks.inc()
        self.evaluated.inc(evaluated)
        self.generated.inc(generated)
        self.block_width.observe(evaluated)
        extra = {} if pending is None else {"pending": pending}
        span.set(
            evaluated=evaluated,
            generated=generated,
            max_depth=max_depth,
            unique_total=unique_total,
            **extra,
        ).__exit__(None, None, None)


class TenantInstruments:
    """Per-tenant counters/gauges for the tenant-packed wave engine
    (``checker/packed_tenancy.py``), named ``<prefix>.tenant.*`` and
    recorded into the TENANT'S run-scoped registry — so a packed job's
    ``GET /jobs/<id>/metrics`` view carries its own lane accounting even
    though the physical waves are shared. One bundle per admitted tenant;
    the engine-wide (shared-wave) quantities ride a ``WaveInstruments``
    bundle under the engine's own registry."""

    def __init__(self, prefix: str, registry: MetricsRegistry = None):
        reg = registry if registry is not None else metrics_registry()
        p = f"{prefix}.tenant"
        self.joins = reg.counter(f"{p}.joins")
        self.waves = reg.counter(f"{p}.waves")
        self.lanes = reg.counter(f"{p}.lanes_dispatched")
        self.generated = reg.counter(f"{p}.states_generated")
        self.unique = reg.counter(f"{p}.states_unique")
        self.stale = reg.counter(f"{p}.storage_stale")
        self.lane_drops = reg.counter(f"{p}.preempt_lane_drops")
        self.lane_share = reg.gauge(f"{p}.lane_share")
        self.pending = reg.gauge(f"{p}.pending_lanes")
        self.depth = reg.gauge(f"{p}.max_depth")

    def record_wave(self, *, lanes: int, width: int, generated: int,
                    n_new: int, pending: int, max_depth: int) -> None:
        """One packed wave's slice of this tenant's accounting (only
        called for waves the tenant had lanes in)."""
        self.waves.inc()
        self.lanes.inc(lanes)
        self.generated.inc(generated)
        self.unique.inc(n_new)
        self.lane_share.set(lanes / width if width else 0.0)
        self.pending.set(pending)
        self.depth.set(max_depth)
