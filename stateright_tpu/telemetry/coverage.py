"""State-space cartography: coverage, vacuity, and shape profiling.

A green check run answers "did any property fail?" and says nothing about
*what was explored*. This module makes every run answer the TLC-style
coverage questions too:

- **Action coverage** — how often each action fired (produced a valid
  candidate) and how often it discovered a fresh state. An action that
  never fires is *dead* in the reachable space; one that fires but never
  yields a fresh state only ever rediscovers known states.
- **Property exercise** — for ``always`` properties with a declared
  ``antecedent`` (``Property.always(name, cond, antecedent=...)`` /
  ``BatchableModel.packed_antecedents``), the number of states where the
  antecedent held: zero means the invariant passed *vacuously*. For
  ``sometimes``, the witness count plus the **near-miss depth** (deepest
  frontier explored while still unwitnessed); for ``eventually``, the
  met-bit population (evaluated states whose condition had already held
  on their path).
- **Shape statistics** — new-unique-per-depth histogram, successors-per-
  state log2 histogram, terminal-state count, revisit rate (dedup
  in-degree), and — under symmetry — the orbit compression ratio
  (in-wave distinct plain fingerprints over distinct orbit keys).

The device checkers fold these as vmapped reductions INTO the existing
wave/drain jits (``DeviceCoverage.wave_reduce``) and drain one extra
int32 vector per host exit — GPUexplore-style: the statistics ride the
exploration kernel instead of a host-side re-walk, results stay
bit-identical, and with ``coverage=False`` (the default) no extra ops
are traced at all. The host engines feed per-block aggregates and are
always-on (their per-state Python loop dwarfs two dict bumps).

Surfaces: ``<prefix>.coverage.*`` registry metrics, one cumulative
``<prefix>.coverage`` trace span per host-visible wave (trace_summary's
coverage table, the monitor's ``monitor.coverage.*`` gauges + SSE
``coverage`` events + the Explorer's per-action bar panel), a
``<prefix>.coverage.summary`` instant at run end carrying the full
report (``scripts/coverage_report.py`` renders it and exits nonzero on
vacuity findings), and per-leg ``coverage`` records via
``bench.py --coverage``.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional

from .metrics import MetricsRegistry, metrics_registry
from .trace import Tracer, get_tracer

__all__ = [
    "DEPTH_BINS",
    "CoverageLedger",
    "DeviceCoverage",
    "coverage_action_labels",
    "sanitize_component",
]

# New-unique-per-depth histogram width (linear bins; deeper states
# saturate into the last bin and the report says so).
DEPTH_BINS = 64

_COMPONENT_RE = re.compile(r"[^A-Za-z0-9_]")


def sanitize_component(name: str) -> str:
    """A metric-name-safe component for user-provided labels (property
    names, action labels): every non-``[A-Za-z0-9_]`` rune becomes ``_``
    so the Prometheus exposition's own sanitizer is a no-op on coverage
    families. Collisions (two labels sanitizing identically) are caught
    by the registry-hygiene lint, not silently merged here."""
    out = _COMPONENT_RE.sub("_", name.strip()) or "_"
    return out


def _log2_bin(value: int) -> int:
    """The ``metrics.Histogram`` bucket index of ``value``: 0 for
    ``value <= 1``, else ``ceil(log2(value))``."""
    if value <= 1:
        return 0
    return (value - 1).bit_length()


def coverage_action_labels(model, action_count: int) -> List[str]:
    """The per-action label axis for a packed model: the model's
    ``packed_action_labels()`` when it provides one (padded/truncated to
    ``action_count`` defensively), else ``action_<id>``."""
    labels = None
    try:
        labels = list(model.packed_action_labels())
    except Exception:  # noqa: BLE001 - optional hook, never fatal
        labels = None
    if not labels:
        labels = []
    labels = [str(x) for x in labels[:action_count]]
    labels += [f"action_{i}" for i in range(len(labels), action_count)]
    return labels


class DeviceCoverage:
    """Static layout + the traceable per-wave reduction the device
    checkers fold into their wave jits.

    The reduction's output is ONE int32 vector per wave (a single extra
    host transfer per existing host exit; the deep drains accumulate it
    in their carry). Layout::

        [0] evaluated   [1] terminal   [2] uniq_fp   [3] uniq_key
        [4 : 4+A]                action fired counts
        [4+A : 4+2A]             action fresh counts
        [4+2A : 4+2A+P]          property exercise counts
        [... : +succ_bins]       successors-per-state log2 bins
        [... : +DEPTH_BINS]      fresh-unique-per-depth linear bins

    Everything except the action-fresh and depth slices is *eval-based*
    (recorded once per logical wave: a table-growth retry re-expands the
    same frontier); action-fresh/depth are *fresh-based* and accumulate
    across retries (only previously-pending lanes come back fresh).
    """

    def __init__(self, action_count: int, property_count: int,
                 symmetry: bool = False):
        self.A = int(action_count)
        self.P = int(property_count)
        self.symmetry = bool(symmetry)
        self.succ_bins = _log2_bin(self.A) + 1
        self.depth_bins = DEPTH_BINS
        self.size = 4 + 2 * self.A + self.P + self.succ_bins + self.depth_bins

    # -- slices (shared by the reduction and the host-side consume) --------

    @property
    def s_fired(self):
        return slice(4, 4 + self.A)

    @property
    def s_fresh(self):
        return slice(4 + self.A, 4 + 2 * self.A)

    @property
    def s_props(self):
        return slice(4 + 2 * self.A, 4 + 2 * self.A + self.P)

    @property
    def s_succ(self):
        base = 4 + 2 * self.A + self.P
        return slice(base, base + self.succ_bins)

    @property
    def s_depth(self):
        base = 4 + 2 * self.A + self.P + self.succ_bins
        return slice(base, base + self.depth_bins)

    # -- traceable pieces ---------------------------------------------------

    @staticmethod
    def count_distinct(hi, lo, valid):
        """In-wave distinct (hi, lo) pairs among ``valid`` lanes
        (traceable; one sort). The all-ones sentinel pair never collides
        with real keys — fingerprints/orbit keys nudge away from it."""
        import jax
        import jax.numpy as jnp

        sent = jnp.uint32(0xFFFFFFFF)
        shi = jnp.where(valid, hi, sent)
        slo = jnp.where(valid, lo, sent)
        shi, slo = jax.lax.sort((shi, slo), num_keys=2)
        first = jnp.concatenate(
            [jnp.ones((1,), bool), (shi[1:] != shi[:-1]) | (slo[1:] != slo[:-1])]
        )
        real = ~((shi == sent) & (slo == sent))
        return (first & real).sum(dtype=jnp.int32)

    def wave_reduce(self, *, eval_mask, cvalid, fresh, lane_action,
                    new_depth, exercised, uniq_fp=None, uniq_key=None):
        """The per-wave coverage vector (int32, ``self.size`` wide).

        ``eval_mask`` (F,) — frontier lanes evaluated this wave;
        ``cvalid`` (F, A) — valid candidates (already AND'd with
        ``eval_mask``); ``fresh`` (B,) — visited-set claim winners, in
        the same lane order as ``lane_action``/``new_depth`` (B,) —
        per-lane action id and child depth; ``exercised`` — list of
        (F,) bool vectors aligned with properties (may be empty);
        ``uniq_fp``/``uniq_key`` — optional scalar in-wave distinct
        counts (symmetry's orbit-compression numerator/denominator).
        """
        import jax.numpy as jnp

        i32 = jnp.int32
        zero = jnp.zeros((), i32)
        evaluated = eval_mask.sum(dtype=i32)
        terminal = (eval_mask & ~cvalid.any(axis=1)).sum(dtype=i32)
        act_fired = cvalid.sum(axis=0, dtype=i32)
        act_fresh = jnp.zeros((self.A,), i32).at[lane_action].add(
            fresh.astype(i32)
        )
        if self.P:
            prop_ex = jnp.stack([e.sum(dtype=i32) for e in exercised])
        else:
            prop_ex = jnp.zeros((0,), i32)
        succ = cvalid.sum(axis=1, dtype=i32)
        # Per-lane bin vector, NOT the scalar zero: with a single
        # successor bin (action_count == 1) the loop below never runs,
        # and a scalar index into succ_hist cannot take the (F,)-shaped
        # eval_mask update (latent until the first A=1 coverage run).
        sbin = jnp.zeros_like(succ)
        for j in range(self.succ_bins - 1):
            sbin = sbin + (succ > (1 << j)).astype(i32)
        succ_hist = jnp.zeros((self.succ_bins,), i32).at[sbin].add(
            eval_mask.astype(i32)
        )
        dbin = jnp.clip(new_depth, 0, self.depth_bins - 1)
        depth_hist = jnp.zeros((self.depth_bins,), i32).at[dbin].add(
            fresh.astype(i32)
        )
        head = jnp.stack([
            evaluated,
            terminal,
            (uniq_fp if uniq_fp is not None else zero).astype(i32),
            (uniq_key if uniq_key is not None else zero).astype(i32),
        ])
        return jnp.concatenate(
            [head, act_fired, act_fresh, prop_ex, succ_hist, depth_hist]
        )


class BlockCoverage:
    """Per-block accumulator for the host engines' ``_check_block``
    loops: plain dict bumps in the hot loop, one ``record_block`` flush
    per ≤BLOCK_SIZE block (the same once-per-block shape as their
    telemetry spans). Actions are keyed by the action object itself
    (``repr`` fallback for unhashables) and converted to labels only at
    flush — distinct actions per block are few."""

    __slots__ = (
        "ledger", "model", "evaluated", "terminals",
        "fired", "fresh", "exercised", "succ", "depth",
    )

    def __init__(self, ledger: "CoverageLedger", model):
        self.ledger = ledger
        self.model = model
        self.evaluated = 0
        self.terminals = 0
        self.fired: Dict[object, int] = {}
        self.fresh: Dict[object, int] = {}
        self.exercised: Dict[int, int] = {}
        self.succ: Dict[int, int] = {}
        self.depth: Dict[int, int] = {}

    def action(self, action, fresh: bool) -> None:
        """One valid transition via ``action`` (``fresh``: it claimed a
        brand-new state)."""
        try:
            self.fired[action] = self.fired.get(action, 0) + 1
        except TypeError:
            action = repr(action)
            self.fired[action] = self.fired.get(action, 0) + 1
        if fresh:
            self.fresh[action] = self.fresh.get(action, 0) + 1

    def exercise(self, index: int) -> None:
        self.exercised[index] = self.exercised.get(index, 0) + 1

    def _label(self, action) -> str:
        if isinstance(action, str):
            return action
        if isinstance(action, tuple) and all(
            isinstance(x, (str, int, bool)) for x in action
        ):
            # The common host-action shape ("RmPrepare", 2) reads as
            # RmPrepare_2 — matching the packed_action_labels idiom —
            # instead of repr's quote-and-paren noise.
            return "_".join(str(x) for x in action)
        try:
            return self.model.format_action(action)
        except Exception:  # noqa: BLE001 - labels are advisory
            return repr(action)

    def flush(self, max_depth: Optional[int] = None) -> None:
        if not self.evaluated and not self.fired:
            return
        self.ledger.record_block(
            evaluated=self.evaluated,
            terminals=self.terminals,
            fired={self._label(k): v for k, v in self.fired.items()},
            fresh={self._label(k): v for k, v in self.fresh.items()},
            exercised=self.exercised,
            succ_counts=self.succ,
            depth_counts=self.depth,
            max_depth=max_depth,
        )
        # One cumulative `.coverage` span per block (same cadence as the
        # engines' block spans): the live monitor's coverage gauges and
        # the Explorer panel read these.
        self.ledger.emit_wave_span()


class CoverageLedger:
    """The per-run coverage accumulator one checker owns.

    Device checkers feed it ``consume_device`` vectors (see
    ``DeviceCoverage``) at their existing host exits; host engines feed
    ``record_block`` aggregates once per ≤1500-state block. Both paths
    update the ``<prefix>.coverage.*`` registry instruments, and
    ``emit_wave_span``/``finalize`` surface the cumulative state into
    the trace stream for the monitor, trace_summary, and
    ``scripts/coverage_report.py``.
    """

    def __init__(
        self,
        prefix: str,
        properties,
        action_labels: Optional[List[str]] = None,
        symmetry: bool = False,
        registry: MetricsRegistry = None,
        tracer: Tracer = None,
    ):
        self.prefix = prefix
        self._p = f"{prefix}.coverage"
        reg = registry if registry is not None else metrics_registry()
        self._registry = reg
        self._tracer = tracer if tracer is not None else get_tracer()
        self._lock = threading.Lock()
        # Property metadata (expectation as its string value so the
        # report is JSON-clean without importing Expectation here).
        self._props = [
            {
                "name": p.name,
                "expectation": getattr(
                    p.expectation, "value", str(p.expectation)
                ),
                "has_antecedent": getattr(p, "antecedent", None) is not None,
            }
            for p in properties
        ]
        self.action_labels = (
            list(action_labels) if action_labels is not None else None
        )
        # -- accumulated state -------------------------------------------
        self._fired: Dict[str, int] = {}
        self._fresh: Dict[str, int] = {}
        if self.action_labels is not None:
            for label in self.action_labels:
                self._fired[label] = 0
                self._fresh[label] = 0
        self._exercised = [0] * len(self._props)
        self._near_miss = [None] * len(self._props)
        self._evaluated = 0
        self._terminals = 0
        self._generated = 0
        self._unique = 0
        self._seed_unique = 0
        self._depth_hist = [0] * DEPTH_BINS
        self._succ_bins: Dict[int, int] = {}
        self._uniq_fp = 0
        self._uniq_key = 0
        self._revisits_reported = 0
        self._symmetry = bool(symmetry)
        self._discovered: Optional[set] = None
        self._finalized = False
        # -- registry instruments ----------------------------------------
        self._c_eval = reg.counter(f"{self._p}.states_evaluated")
        self._c_term = reg.counter(f"{self._p}.terminal_states")
        self._c_revisit = reg.counter(f"{self._p}.revisits")
        self._g_revisit = reg.gauge(f"{self._p}.revisit_rate")
        self._g_action_cov = reg.gauge(f"{self._p}.action_coverage")
        self._g_orbit = (
            reg.gauge(f"{self._p}.orbit_compression") if symmetry else None
        )
        self._h_depth = reg.histogram(f"{self._p}.depth")
        self._h_succ = reg.histogram(f"{self._p}.successors")
        self._c_action_fired: Dict[str, object] = {}
        self._c_action_fresh: Dict[str, object] = {}
        if self.action_labels is not None:
            # Eager creation: dead actions must show as explicit zeros in
            # /metrics, not as absent families.
            for label in self.action_labels:
                self._action_counter(label, fired=True)
                self._action_counter(label, fired=False)
        self._c_prop_ex = [
            reg.counter(
                f"{self._p}.property_exercised.{sanitize_component(m['name'])}"
            )
            for m in self._props
        ]

    def _action_counter(self, label: str, fired: bool):
        cache = self._c_action_fired if fired else self._c_action_fresh
        c = cache.get(label)
        if c is None:
            kind = "action_fired" if fired else "action_fresh"
            c = self._registry.counter(
                f"{self._p}.{kind}.{sanitize_component(label)}"
            )
            cache[label] = c
        return c

    # -- recording ----------------------------------------------------------

    def record_seed(self, n_unique: int, depth: int = 1) -> None:
        """Initial states (they never flow through a wave/block): depth
        histogram + unique total."""
        n = int(n_unique)
        if n <= 0:
            return
        with self._lock:
            self._seed_unique += n
            self._unique += n
            self._depth_hist[min(max(depth, 0), DEPTH_BINS - 1)] += n
        self._h_depth.observe_many(depth, n)

    def consume_device(self, vec, layout: DeviceCoverage, *,
                       first_attempt: bool = True,
                       max_depth: Optional[int] = None) -> None:
        """One wave's (or drain-aggregate's) device coverage vector.
        ``first_attempt=False`` marks a table-growth retry of the same
        logical wave: only the fresh-based slices (action fresh, depth
        bins) accumulate — the eval-based ones were already recorded."""
        import numpy as np

        v = np.asarray(vec, dtype=np.int64)
        labels = self.action_labels or []
        fresh_by_action = v[layout.s_fresh]
        depth_bins = v[layout.s_depth]
        fired_by_action = v[layout.s_fired] if first_attempt else None
        succ_bins = v[layout.s_succ] if first_attempt else None
        with self._lock:
            for i, label in enumerate(labels):
                self._fresh[label] = self._fresh.get(label, 0) + int(
                    fresh_by_action[i]
                )
            for d in np.flatnonzero(depth_bins):
                self._depth_hist[int(d)] += int(depth_bins[d])
            self._unique += int(fresh_by_action.sum())
            if first_attempt:
                self._evaluated += int(v[0])
                self._terminals += int(v[1])
                self._uniq_fp += int(v[2])
                self._uniq_key += int(v[3])
                self._generated += int(fired_by_action.sum())
                for i, label in enumerate(labels):
                    self._fired[label] = self._fired.get(label, 0) + int(
                        fired_by_action[i]
                    )
                prop_ex = v[layout.s_props]
                for i in range(len(self._props)):
                    self._exercised[i] += int(prop_ex[i])
                for b in np.flatnonzero(succ_bins):
                    self._succ_bins[int(b)] = self._succ_bins.get(
                        int(b), 0
                    ) + int(succ_bins[b])
            if max_depth is not None:
                self._update_near_miss(max_depth)
            revisits, rev_delta = self._revisits_locked()
        # Registry updates outside the ledger lock (instruments lock
        # themselves; ordering races only skew gauges transiently).
        for i, label in enumerate(labels):
            if int(fresh_by_action[i]):
                self._action_counter(label, fired=False).inc(
                    int(fresh_by_action[i])
                )
        for d in np.flatnonzero(depth_bins):
            self._h_depth.observe_many(int(d), int(depth_bins[d]))
        if first_attempt:
            self._c_eval.inc(int(v[0]))
            self._c_term.inc(int(v[1]))
            for i, label in enumerate(labels):
                if int(fired_by_action[i]):
                    self._action_counter(label, fired=True).inc(
                        int(fired_by_action[i])
                    )
            for i, c in enumerate(self._c_prop_ex):
                n = int(v[layout.s_props][i])
                if n:
                    c.inc(n)
            for b in np.flatnonzero(succ_bins):
                self._h_succ.observe_many(
                    1 if int(b) == 0 else (1 << int(b)), int(succ_bins[b])
                )
        self._refresh_gauges(revisits, rev_delta)

    def record_block(self, *, evaluated: int, terminals: int,
                     fired: Dict[str, int], fresh: Dict[str, int],
                     exercised: Dict[int, int],
                     succ_counts: Dict[int, int],
                     depth_counts: Dict[int, int],
                     max_depth: Optional[int] = None) -> None:
        """One host-engine block's aggregates (labels are already
        strings; ``exercised`` maps property index -> count;
        ``depth_counts`` maps fresh-state depth -> count)."""
        generated = sum(fired.values())
        block_fresh = sum(fresh.values())
        with self._lock:
            self._evaluated += int(evaluated)
            self._terminals += int(terminals)
            self._generated += int(generated)
            self._unique += int(block_fresh)
            for label, n in fired.items():
                self._fired[label] = self._fired.get(label, 0) + int(n)
            for label, n in fresh.items():
                self._fresh[label] = self._fresh.get(label, 0) + int(n)
            for i, n in exercised.items():
                if 0 <= i < len(self._exercised):
                    self._exercised[i] += int(n)
            for s, n in succ_counts.items():
                b = _log2_bin(int(s))
                self._succ_bins[b] = self._succ_bins.get(b, 0) + int(n)
            for d, n in depth_counts.items():
                self._depth_hist[min(max(int(d), 0), DEPTH_BINS - 1)] += int(n)
            if max_depth is not None:
                self._update_near_miss(max_depth)
            revisits, rev_delta = self._revisits_locked()
        self._c_eval.inc(int(evaluated))
        self._c_term.inc(int(terminals))
        for label, n in fired.items():
            if n:
                self._action_counter(label, fired=True).inc(int(n))
        for label, n in fresh.items():
            if n:
                self._action_counter(label, fired=False).inc(int(n))
        for i, n in exercised.items():
            if n and 0 <= i < len(self._c_prop_ex):
                self._c_prop_ex[i].inc(int(n))
        for s, n in succ_counts.items():
            if n:
                self._h_succ.observe_many(int(s), int(n))
        for d, n in depth_counts.items():
            if n:
                self._h_depth.observe_many(int(d), int(n))
        self._refresh_gauges(revisits, rev_delta)

    def _update_near_miss(self, max_depth: int) -> None:
        """Deepest frontier evaluated while a ``sometimes`` property was
        still unwitnessed (caller holds the lock)."""
        for i, meta in enumerate(self._props):
            if meta["expectation"] != "sometimes":
                continue
            if self._exercised[i] == 0:
                prev = self._near_miss[i]
                self._near_miss[i] = (
                    max_depth if prev is None else max(prev, max_depth)
                )

    def _revisits_locked(self):
        """Cumulative revisit count + the not-yet-reported delta for the
        ``.revisits`` counter (caller holds the ledger lock, so the
        delta handoff is race-free across worker threads)."""
        revisits = max(
            0, int(self._generated - (self._unique - self._seed_unique))
        )
        delta = max(0, revisits - self._revisits_reported)
        self._revisits_reported = max(self._revisits_reported, revisits)
        return revisits, delta

    def _refresh_gauges(self, revisits: int, rev_delta: int = 0) -> None:
        if rev_delta:
            self._c_revisit.inc(rev_delta)
        if self._generated:
            self._g_revisit.set(revisits / self._generated)
        if self.action_labels:
            fired = sum(1 for x in self._fired.values() if x > 0)
            self._g_action_cov.set(fired / len(self.action_labels))
        if self._g_orbit is not None and self._uniq_key:
            self._g_orbit.set(self._uniq_fp / self._uniq_key)

    # -- surfacing -----------------------------------------------------------

    def emit_wave_span(self) -> None:
        """One cumulative ``<prefix>.coverage`` span per host-visible
        wave: the compact shape the monitor's ``monitor.coverage.*``
        gauges, the Explorer panel refresh, and trace_summary's coverage
        table consume."""
        with self._lock:
            args = self._span_args()
        with self._tracer.span(f"{self._p}", **args):
            pass

    def _span_args(self) -> Dict[str, object]:
        total = len(self.action_labels) if self.action_labels else None
        fired = sum(1 for x in self._fired.values() if x > 0)
        sometimes = [
            (i, m) for i, m in enumerate(self._props)
            if m["expectation"] == "sometimes"
        ]
        args = {
            "evaluated": self._evaluated,
            "terminals": self._terminals,
            "actions_fired": fired,
            "revisit_rate": (
                max(
                    0.0,
                    1.0 - (self._unique - self._seed_unique)
                    / self._generated,
                )
                if self._generated
                else 0.0
            ),
            "sometimes_witnessed": sum(
                1 for i, _ in sometimes if self._exercised[i] > 0
            ),
            "sometimes_total": len(sometimes),
            "props_total": len(self._props),
        }
        if total is not None:
            args["actions_total"] = total
            args["dead_actions"] = total - fired
        if self._symmetry and self._uniq_key:
            args["orbit_compression"] = self._uniq_fp / self._uniq_key
        return args

    def finalize(self, discovered=None) -> None:
        """Run-end: records the discovery outcome and emits a
        ``<prefix>.coverage.summary`` instant carrying the full report.
        Safe to call more than once (the host engines call it from every
        worker's shutdown path; readers take the LAST summary per
        prefix, so the final call's complete totals win)."""
        with self._lock:
            if discovered is not None:
                self._discovered = set(discovered)
            self._finalized = True
        report = self.report()
        self._tracer.instant(f"{self._p}.summary", report=report)

    def vacuity(self) -> Dict[str, List[str]]:
        """The CI-failing findings: dead actions (never enabled anywhere
        reachable), ``always`` properties whose declared antecedent never
        fired, and undiscovered ``sometimes`` properties. Informational
        cousins (fired-but-never-fresh actions, never-met ``eventually``
        conditions) ride the report, not this dict."""
        with self._lock:
            dead = (
                [a for a in self.action_labels if self._fired.get(a, 0) == 0]
                if self.action_labels is not None
                else []
            )
            unexercised = [
                m["name"]
                for i, m in enumerate(self._props)
                if m["expectation"] == "always"
                and m["has_antecedent"]
                and self._exercised[i] == 0
            ]
            undiscovered = [
                m["name"]
                for i, m in enumerate(self._props)
                if m["expectation"] == "sometimes"
                and (
                    m["name"] not in self._discovered
                    if self._discovered is not None
                    else self._exercised[i] == 0
                )
            ]
        return {
            "dead_actions": dead,
            "unexercised_always": unexercised,
            "undiscovered_sometimes": undiscovered,
        }

    def report(self) -> Dict[str, object]:
        """The full cartography (JSON-clean)."""
        vac = self.vacuity()
        with self._lock:
            wave_unique = self._unique - self._seed_unique
            revisits = max(0, self._generated - wave_unique)
            hi = 0
            for i, n in enumerate(self._depth_hist):
                if n:
                    hi = i + 1
            succ_hist = [
                self._succ_bins.get(b, 0)
                for b in range(max(self._succ_bins, default=-1) + 1)
            ]
            actions = {
                "total": (
                    len(self.action_labels)
                    if self.action_labels is not None
                    else None
                ),
                "fired": sum(1 for x in self._fired.values() if x > 0),
                "never_new": sorted(
                    a
                    for a, n in self._fired.items()
                    if n > 0 and self._fresh.get(a, 0) == 0
                ),
                "table": {
                    a: {
                        "fired": self._fired.get(a, 0),
                        "fresh": self._fresh.get(a, 0),
                    }
                    for a in (
                        self.action_labels
                        if self.action_labels is not None
                        else sorted(self._fired)
                    )
                },
            }
            props = {}
            for i, m in enumerate(self._props):
                entry = {
                    "expectation": m["expectation"],
                    "exercised": self._exercised[i],
                    "has_antecedent": m["has_antecedent"],
                }
                if self._discovered is not None:
                    entry["discovered"] = m["name"] in self._discovered
                if m["expectation"] == "sometimes":
                    entry["near_miss_depth"] = self._near_miss[i]
                props[m["name"]] = entry
            out = {
                "prefix": self.prefix,
                "evaluated": self._evaluated,
                "generated": self._generated,
                "unique": self._unique,
                "terminal_states": self._terminals,
                "revisits": revisits,
                "revisit_rate": (
                    revisits / self._generated if self._generated else 0.0
                ),
                "mean_in_degree": (
                    self._generated / wave_unique if wave_unique else None
                ),
                "actions": actions,
                "properties": props,
                "shape": {
                    "depth_hist": self._depth_hist[:hi],
                    "depth_saturated": bool(
                        self._depth_hist[DEPTH_BINS - 1]
                    ),
                    "succ_hist_log2": succ_hist,
                },
                "vacuity": vac,
                "vacuous": bool(any(vac.values())),
            }
            if self._symmetry:
                out["symmetry"] = {
                    "wave_distinct_fps": self._uniq_fp,
                    "wave_distinct_orbits": self._uniq_key,
                    "orbit_compression": (
                        self._uniq_fp / self._uniq_key
                        if self._uniq_key
                        else None
                    ),
                }
        return out
