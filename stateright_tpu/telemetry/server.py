"""Live run monitoring: streaming metrics, progress/ETA, stall watchdog,
crash flight recorder.

The monitor taps the ONE place every backend already reports through: the
telemetry tracer. ``MonitorCore`` subscribes to the default tracer as a
sink, so each wave/drain/block span a checker emits (GPUexplore-style
device exploration is opaque *between* waves — the wave boundary is
exactly where a live monitor can tap in) feeds three consumers without
touching any checker hot path:

- a ``ProgressEstimator`` (EWMA states/s, log-linear frontier growth-rate
  fit, an ETA band published as gauges),
- an ``EventBroker`` fanning wave-complete and storage-tier events to
  Server-Sent-Events clients (the Explorer dashboard), and
- a ``StallWatchdog`` that fires when no wave completes within a
  deadline (warning instant + metrics dump + optional ``jax.profiler``
  capture).

``MonitorServer`` wraps the core in an HTTP server:

- ``GET /metrics`` — Prometheus text exposition (sanitized names,
  counters suffixed ``_total``, log2 histograms as cumulative ``le``
  buckets, tier/storage gauges included);
- ``GET /status``  — JSON snapshot merging ``Checker.metrics()`` with
  the progress estimate (non-null ETA fields after >= 3 waves);
- ``GET /events``  — SSE stream of ``wave`` and ``storage`` events.

``FlightRecorder`` is the forensic half: on uncaught exception or
SIGTERM/SIGINT it atomically dumps the tracer ring buffer, a metrics
snapshot, and the checker's state digest to ``flight-<run_id>.json``
(rendered by ``scripts/flight_report.py``).

Everything here is stdlib-only and never blocks a checker: SSE client
queues are bounded and drop on overflow, and ``write_event`` is fully
exception-guarded (a monitor bug must never become a worker_error).
"""

from __future__ import annotations

import itertools
import json
import math
import os
import queue
import re
import signal
import sys
import threading
import time
import traceback
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from .metrics import Counter, Gauge, Histogram, MetricsRegistry, metrics_registry
from .trace import Tracer, get_tracer

# -- Prometheus text exposition --------------------------------------------

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str, prefix: str = "stateright") -> str:
    """Dotted registry names to the Prometheus grammar: illegal chars
    become ``_``, a namespace prefix keeps them collision-free, and a
    leading digit (impossible after the prefix, kept for prefix="")
    gets an underscore."""
    out = _NAME_SANITIZE.sub("_", name)
    if prefix:
        out = f"{prefix}_{out}"
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _label_str(labels: Optional[Dict[str, str]], extra: str = "") -> str:
    """Renders a label set (plus an optional pre-rendered ``k="v"`` pair
    like a histogram's ``le``) as the ``{...}`` suffix, or ``""``."""
    parts = []
    if labels:
        for k, v in sorted(labels.items()):
            v = str(v).replace("\\", "\\\\").replace('"', '\\"')
            parts.append(f'{k}="{v}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry: MetricsRegistry = None,
                    prefix: str = "stateright",
                    labels: Optional[Dict[str, str]] = None,
                    _seen_types: Optional[set] = None) -> str:
    """The full registry in Prometheus text exposition format (0.0.4).

    Counters gain the conventional ``_total`` suffix; gauges keep their
    registry name (unit suffixes like ``_seconds``/``_bytes`` are already
    part of the naming convention where they apply — e.g.
    ``tpu_bfs.warmup_seconds``, ``*.storage.host_bytes``); log2
    histograms render as cumulative ``le``-bucketed histograms with
    ``_sum``/``_count``. Unset gauges are elided rather than exported as
    fake zeros. ``labels`` attaches a constant label set to every series
    — the multi-run aggregate view exports each run's registry under a
    ``run_id`` label so same-named series never merge."""
    reg = registry if registry is not None else metrics_registry()
    lab = _label_str(labels)
    # Spec: at most one TYPE line per metric family. The multi-run
    # aggregate threads one `_seen_types` set through every registry so
    # same-named series from different runs share a single TYPE line.
    seen = _seen_types if _seen_types is not None else set()

    def type_line(lines, pname, kind):
        if pname not in seen:
            seen.add(pname)
            lines.append(f"# TYPE {pname} {kind}")

    lines: List[str] = []
    for name, inst in reg.instruments():
        if isinstance(inst, Counter):
            pname = sanitize_metric_name(name, prefix) + "_total"
            type_line(lines, pname, "counter")
            lines.append(f"{pname}{lab} {_fmt_value(inst.snapshot())}")
        elif isinstance(inst, Gauge):
            value = inst.snapshot()
            if value is None:
                continue
            pname = sanitize_metric_name(name, prefix)
            type_line(lines, pname, "gauge")
            lines.append(f"{pname}{lab} {_fmt_value(value)}")
        elif isinstance(inst, Histogram):
            snap = inst.snapshot()
            pname = sanitize_metric_name(name, prefix)
            type_line(lines, pname, "histogram")
            cum = 0
            for i, count in enumerate(snap["buckets_log2"]):
                cum += count
                if count:
                    le = _label_str(labels, f'le="{float(1 << i)}"')
                    lines.append(f"{pname}_bucket{le} {cum}")
            inf = _label_str(labels, 'le="+Inf"')
            lines.append(f'{pname}_bucket{inf} {snap["count"]}')
            lines.append(f"{pname}_sum{lab} {_fmt_value(snap['sum'])}")
            lines.append(f"{pname}_count{lab} {snap['count']}")
    return "\n".join(lines) + "\n"


def prometheus_text_all_runs(prefix: str = "stateright") -> str:
    """The aggregate exposition for a multi-run process: the default
    registry unlabeled, then every per-run registry
    (``telemetry.metrics.run_registries``) with a ``run_id`` label —
    same-named series from different runs stay distinct."""
    from .metrics import run_registries

    seen_types: set = set()
    parts = [prometheus_text(prefix=prefix, _seen_types=seen_types)]
    for run_id, reg in sorted(run_registries().items()):
        parts.append(
            prometheus_text(
                reg, prefix=prefix, labels={"run_id": run_id},
                _seen_types=seen_types,
            )
        )
    return "".join(parts)


def _fmt_value(v) -> str:
    if isinstance(v, float):
        return repr(v)
    return str(v)


def registry_hygiene_problems(registry: MetricsRegistry = None,
                              prefix: str = "stateright") -> List[str]:
    """The metric-registry lint (run as a tier-1 test): every registered
    name must survive the Prometheus sanitizer without colliding with a
    different registered name — two dotted names mapping to one
    exposition family would silently merge unrelated series. Counters
    are checked at their exported ``_total`` spelling, so a counter
    ``x.y`` and a gauge ``x.y_total`` collide too. Returns
    human-readable problem strings (empty == clean)."""
    reg = registry if registry is not None else metrics_registry()
    seen: Dict[str, str] = {}
    problems: List[str] = []
    for name, inst in reg.instruments():
        exported = sanitize_metric_name(name, prefix)
        if isinstance(inst, Counter):
            exported += "_total"
        if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", exported):
            problems.append(
                f"{name!r} sanitizes to non-Prometheus name {exported!r}"
            )
            continue
        other = seen.get(exported)
        if other is not None and other != name:
            problems.append(
                f"{name!r} and {other!r} both export as {exported!r}"
            )
        seen[exported] = name
    return problems


# -- progress / ETA estimation ---------------------------------------------


class ProgressEstimator:
    """Per-wave progress model: EWMA unique-states/s, a log-linear fit of
    the frontier growth rate, and an ETA band.

    The total state count is unknowable mid-run, so the ETA is a *band*
    built from what BFS frontiers actually do — ramp, plateau, decay:

    - ``eta_s_low``  assumes only the current frontier remains
      (draining it at the EWMA rate);
    - ``eta_s_high`` extrapolates the fitted per-wave growth factor
      ``g`` geometrically — a decaying frontier converges to
      ``frontier * g/(1-g)`` extra states, a growing one is clamped to a
      ``HORIZON``-wave extrapolation (the honest "at least this long").

    Both are None until ``MIN_WAVES`` observations, non-null thereafter.
    A ``clock`` injection point keeps the math unit-testable."""

    MIN_WAVES = 3
    HORIZON_WAVES = 64
    FIT_WINDOW = 32

    def __init__(self, clock=time.monotonic, halflife_s: float = 10.0):
        self._clock = clock
        self._halflife_s = halflife_s
        # RLock: eta_band()/snapshot() hold it across their whole read
        # (a /status poll must not see wave N's count with wave N-1's
        # EWMA) and re-enter via frontier_growth().
        self._lock = threading.RLock()
        self._t0: Optional[float] = None
        self._last_t: Optional[float] = None
        self.waves = 0
        self.ewma_states_per_s: Optional[float] = None
        self.unique_total = 0
        self.generated_total = 0
        self.max_depth = 0
        self.dedup_hit_rate = 0.0
        self.last_frontier: Optional[float] = None
        # (cumulative wave index, log2 frontier) points for the fit.
        self._fit_points: deque = deque(maxlen=self.FIT_WINDOW)

    def observe(self, *, n_new: int, generated: int, frontier=None,
                depth=None, waves: int = 1, dedup_hit_rate=None,
                t: Optional[float] = None) -> None:
        """One wave's (or drain-aggregate's: ``waves`` > 1) completion."""
        now = self._clock() if t is None else t
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            # waves=0 is legal (a drain whose only wave is re-emitted as
            # its own span): count nothing rather than inventing a wave.
            self.waves += max(0, int(waves))
            self.unique_total += int(n_new)
            self.generated_total += int(generated)
            if depth is not None:
                self.max_depth = max(self.max_depth, int(depth))
            if dedup_hit_rate is not None:
                self.dedup_hit_rate = float(dedup_hit_rate)
            elif generated:
                self.dedup_hit_rate = (generated - n_new) / generated
            if self._last_t is not None:
                dt = max(now - self._last_t, 1e-9)
                inst = n_new / dt
                alpha = 1.0 - 0.5 ** (dt / self._halflife_s)
                if self.ewma_states_per_s is None:
                    self.ewma_states_per_s = inst
                else:
                    self.ewma_states_per_s += alpha * (
                        inst - self.ewma_states_per_s
                    )
            self._last_t = now
            if frontier:
                self.last_frontier = float(frontier)
                self._fit_points.append(
                    (float(self.waves), math.log2(float(frontier)))
                )

    def frontier_growth(self) -> Optional[float]:
        """Fitted per-wave frontier growth factor (least squares over the
        recent ``(wave, log2 frontier)`` window); None under 2 points.
        > 1 means the BFS is still ramping, < 1 decaying toward done."""
        with self._lock:
            pts = list(self._fit_points)
        if len(pts) < 2:
            return None
        n = len(pts)
        mx = sum(x for x, _ in pts) / n
        my = sum(y for _, y in pts) / n
        var = sum((x - mx) ** 2 for x, _ in pts)
        if var == 0:
            return 1.0
        slope = sum((x - mx) * (y - my) for x, y in pts) / var
        return 2.0 ** slope

    def eta_band(self) -> Tuple[Optional[float], Optional[float]]:
        with self._lock:
            if (
                self.waves < self.MIN_WAVES
                or not self.last_frontier
                or not self.ewma_states_per_s
            ):
                return None, None
            rate = max(self.ewma_states_per_s, 1e-9)
            f = self.last_frontier
            g = self.frontier_growth() or 1.0
            low = f / rate
            if g < 1.0:
                remaining = f * g / (1.0 - g)
            else:
                # Still ramping: clamp the geometric extrapolation so the
                # band stays finite (it reads "at least", not "exactly").
                remaining = f * min(g, 4.0) * self.HORIZON_WAVES
            high = (f + remaining) / rate
            return low, max(low, high)

    def snapshot(self) -> Dict[str, object]:
        now = self._clock()
        with self._lock:
            eta_low, eta_high = self.eta_band()
            return {
                "waves": self.waves,
                "ewma_states_per_s": self.ewma_states_per_s,
                "frontier_growth": self.frontier_growth(),
                "frontier": self.last_frontier,
                "eta_s_low": eta_low,
                "eta_s_high": eta_high,
                "max_depth": self.max_depth,
                "dedup_hit_rate": self.dedup_hit_rate,
                "unique_states": self.unique_total,
                "states_generated": self.generated_total,
                "elapsed_s": (
                    now - self._t0 if self._t0 is not None else None
                ),
            }


def _default_run_id() -> str:
    """Shared by MonitorCore and a standalone FlightRecorder so their
    flight-<run_id>.json names stay glob-compatible."""
    return time.strftime("%Y%m%d-%H%M%S") + ("-%d" % os.getpid())


# -- SSE fan-out ------------------------------------------------------------

_SSE_CLOSE = (None, None)


class EventBroker:
    """Bounded fan-out from the tracer thread to SSE clients. Queues drop
    on overflow — a slow dashboard must never backpressure a checker."""

    QUEUE_DEPTH = 256

    def __init__(self, on_drop=None):
        self._lock = threading.Lock()
        self._queues: List["queue.Queue"] = []
        self.dropped = 0
        self._on_drop = on_drop

    def subscribe(self) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue(maxsize=self.QUEUE_DEPTH)
        with self._lock:
            self._queues.append(q)
        return q

    def unsubscribe(self, q) -> None:
        with self._lock:
            if q in self._queues:
                self._queues.remove(q)

    def client_count(self) -> int:
        with self._lock:
            return len(self._queues)

    def publish(self, kind: str, payload: Dict) -> None:
        with self._lock:
            queues = list(self._queues)
        for q in queues:
            try:
                q.put_nowait((kind, payload))
            except queue.Full:
                with self._lock:  # publishers race from span-exit threads
                    self.dropped += 1
                if self._on_drop is not None:
                    self._on_drop()

    def close(self) -> None:
        """Wakes every client loop with the close sentinel."""
        with self._lock:
            queues = list(self._queues)
        for q in queues:
            try:
                q.put_nowait(_SSE_CLOSE)
            except queue.Full:
                pass


# -- stall watchdog ----------------------------------------------------------


class StallWatchdog:
    """Fires when no wave completes within ``deadline_s``: a warning
    instant in the trace, a metrics dump to stderr, and (optional) a
    ``jax.profiler`` capture into ``capture_dir`` so the wedge itself
    gets profiled. Fires once per stall — the next wave re-arms it.

    ``clock`` is injectable and ``poll()`` is callable directly, so the
    deadline logic unit-tests with a fake clock and no threads."""

    def __init__(self, deadline_s: float, registry: MetricsRegistry = None,
                 tracer: Tracer = None, clock=time.monotonic,
                 on_stall=None, capture_dir: Optional[str] = None,
                 capture_s: float = 3.0, done_fn=None):
        self.deadline_s = float(deadline_s)
        self._done_fn = done_fn
        self._registry = registry if registry is not None else metrics_registry()
        self._tracer = tracer if tracer is not None else get_tracer()
        self._clock = clock
        self._on_stall = on_stall
        self._capture_dir = capture_dir
        self._capture_s = capture_s
        self._last_pet = clock()
        # Generation counters instead of a boolean latch: pet() racing
        # poll() on a bare `_stalled` flag could latch True just after a
        # wave landed, permanently suppressing the NEXT genuine stall.
        # With generations, "fired once per stall" is simply "don't fire
        # twice for the same pet generation" — race-proof by construction.
        self._pet_gen = 1
        self._fired_gen = 0
        self._stalls = self._registry.counter("monitor.stalls")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def pet(self, t: Optional[float] = None) -> None:
        self._last_pet = self._clock() if t is None else t
        self._pet_gen += 1

    def poll(self, now: Optional[float] = None) -> bool:
        """One deadline check; True when THIS call fired a stall."""
        gen = self._pet_gen
        now = self._clock() if now is None else now
        idle = now - self._last_pet
        if idle <= self.deadline_s or gen == self._fired_gen:
            return False
        if self._done_fn is not None and self._done_fn():
            # Waves stopped because the check FINISHED, not wedged — a
            # monitor held open past completion must not cry stall (and
            # must not burn a pointless profiler capture) every deadline.
            return False
        self._fired_gen = gen
        self._stalls.inc()
        self._tracer.instant(
            "monitor.stall", idle_s=idle, deadline_s=self.deadline_s
        )
        try:
            snap = self._registry.snapshot()
            sys.stderr.write(
                "monitor.stall: no wave for %.1fs (deadline %.1fs); "
                "metrics %s\n"
                % (idle, self.deadline_s,
                   json.dumps(snap, sort_keys=True, default=str))
            )
            sys.stderr.flush()
        except Exception:  # noqa: BLE001 - diagnostics must not raise
            pass
        if self._on_stall is not None:
            try:
                self._on_stall(idle)
            except Exception:  # noqa: BLE001
                pass
        if self._capture_dir is not None:
            self._profiler_capture()
        return True

    def _profiler_capture(self) -> None:
        """Best effort: profile the stalled process for ``capture_s`` so
        the trace shows WHERE it is wedged (device tunnel, host probe,
        compile). No-op when jax is unavailable."""
        try:
            import jax

            jax.profiler.start_trace(self._capture_dir)
            try:
                time.sleep(self._capture_s)
            finally:
                jax.profiler.stop_trace()
            self._tracer.instant(
                "monitor.stall_capture", dir=self._capture_dir
            )
        except Exception:  # noqa: BLE001 - profiler optional by design
            pass

    def start(self) -> "StallWatchdog":
        if self._thread is None:
            interval = max(min(self.deadline_s / 4.0, 1.0), 0.05)

            def loop():
                while not self._stop.wait(interval):
                    self.poll()

            self._thread = threading.Thread(
                target=loop, name="monitor-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# -- flight recorder ---------------------------------------------------------


class FlightRecorder:
    """Crash forensics: on uncaught exception or SIGTERM/SIGINT, dump the
    tracer ring buffer, a metrics snapshot, and the checker state digest
    to ``flight-<run_id>.json`` (atomic tmp+replace — a second signal
    mid-write must not leave torn JSON). ``scripts/flight_report.py``
    renders the file.

    ``install()`` chains — never replaces — the previous ``sys.excepthook``
    and signal handlers, and signal handlers are only installed from the
    main thread (the interpreter rejects them elsewhere)."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, run_id: Optional[str] = None, out_dir: str = ".",
                 checker=None, registry: MetricsRegistry = None,
                 tracer: Tracer = None):
        self.run_id = run_id or _default_run_id()
        self.out_dir = out_dir
        self.checker = checker
        self._registry = registry if registry is not None else metrics_registry()
        self._tracer = tracer if tracer is not None else get_tracer()
        self._prev_excepthook = None
        self._prev_signal: Dict[int, object] = {}
        self._installed = False
        self._tmp_seq = itertools.count()
        self.last_dump_path: Optional[str] = None

    @property
    def path(self) -> str:
        return os.path.join(self.out_dir, f"flight-{self.run_id}.json")

    def install(self) -> "FlightRecorder":
        if self._installed:
            return self
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._on_exception
        if threading.current_thread() is threading.main_thread():
            for sig in self.SIGNALS:
                try:
                    self._prev_signal[sig] = signal.signal(
                        sig, self._on_signal
                    )
                except (ValueError, OSError):
                    pass
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        if sys.excepthook is self._on_exception:
            sys.excepthook = self._prev_excepthook
        for sig, prev in self._prev_signal.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError, TypeError):
                pass
        self._prev_signal.clear()
        self._installed = False

    def _on_exception(self, exc_type, exc, tb) -> None:
        try:
            self.dump("exception", exc=(exc_type, exc, tb))
        except Exception:  # noqa: BLE001 - the hook must not mask the crash
            pass
        if self._prev_excepthook is not None:
            self._prev_excepthook(exc_type, exc, tb)

    def _on_signal(self, signum, frame) -> None:
        try:
            self.dump(signal.Signals(signum).name)
        except Exception:  # noqa: BLE001
            pass
        prev = self._prev_signal.get(signum)
        if callable(prev):
            prev(signum, frame)
            return
        # Re-deliver with the original disposition (default: terminate),
        # so `kill -TERM` still kills and the exit code stays honest.
        signal.signal(
            signum, prev if prev is not None else signal.SIG_DFL
        )
        signal.raise_signal(signum)

    @staticmethod
    def _bounded(fn, timeout_s: float = 2.0, default=None):
        """Runs ``fn`` on a side thread with a deadline. dump() executes
        inside signal handlers, where taking the (non-reentrant)
        registry/instrument locks directly could deadlock against the
        very frame the signal interrupted; a side thread blocks harmlessly
        instead and the dump proceeds without that section."""
        box: Dict[str, object] = {}

        def run():
            try:
                box["value"] = fn()
            except Exception as e:  # noqa: BLE001 - mid-crash best effort
                box["error"] = repr(e)

        t = threading.Thread(
            target=run, name="flight-dump-section", daemon=True
        )
        t.start()
        t.join(timeout_s)
        if "error" in box:
            return {"error": box["error"]}
        return box.get("value", default)

    def dump(self, reason: str, exc=None) -> str:
        """Writes the flight file; returns its path. Every section is
        individually guarded — a half-broken checker mid-crash must still
        yield the ring buffer and metrics."""
        record: Dict[str, object] = {
            "flight_recorder": 1,
            "run_id": self.run_id,
            "reason": reason,
            "pid": os.getpid(),
            "wall_time": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }
        if exc is not None:
            exc_type, exc_value, tb = exc
            record["exception"] = {
                "type": getattr(exc_type, "__name__", str(exc_type)),
                "message": str(exc_value),
                "traceback": "".join(
                    traceback.format_exception(exc_type, exc_value, tb)
                ),
            }
        else:
            record["exception"] = None
        record["metrics"] = self._bounded(
            self._registry.snapshot, default={}
        )
        record["digest"] = (
            self._bounded(self.checker.state_digest)
            if self.checker is not None
            else None
        )
        try:
            # events() retries the deque copy under concurrent appends
            # (worker threads keep emitting while a SIGTERM dump runs).
            record["ring"] = self._tracer.events()
        except Exception:  # noqa: BLE001
            record["ring"] = []
        path = self.path
        # Unique tmp per call: dump is not serialized (a SIGTERM handler
        # can interrupt an in-progress finally-block dump in the SAME
        # thread, so a lock would deadlock). Distinct tmp inodes mean the
        # interleaved dumps each complete whole; last replace wins and
        # the final file is never torn.
        tmp = f"{path}.tmp{next(self._tmp_seq)}"
        with open(tmp, "w") as f:
            json.dump(record, f, default=str)
        os.replace(tmp, path)
        self.last_dump_path = path
        return path


# -- the monitor core (tracer sink + status assembly) ------------------------


class MonitorCore:
    """The HTTP-free monitor: a tracer sink recognizing wave-level spans
    (``new_unique`` in args — the shape every device backend emits),
    host block spans (``unique_total``), and storage-tier spans
    (``.storage.`` in the name), feeding the estimator, the SSE broker,
    and the watchdog. Attach it with ``tracer.add_sink(core)``;
    ``MonitorServer`` does that for you."""

    def __init__(self, checker=None, registry: MetricsRegistry = None,
                 tracer: Tracer = None, run_id: Optional[str] = None,
                 stall_deadline_s: Optional[float] = None,
                 stall_capture_dir: Optional[str] = None,
                 clock=time.monotonic, run_filter: Optional[str] = None):
        self.checker = checker
        self.registry = registry if registry is not None else metrics_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        self.run_id = run_id or _default_run_id()
        # Per-run selection: with a ``run_filter``, only events stamped
        # with that ``run_id`` arg (checkers spawned with ``run_id=``
        # emit through a RunScopedTracer) feed this core — a multi-job
        # process can run one monitor per job without the jobs' waves
        # polluting each other's estimators. None = aggregate (default):
        # every wave from every run feeds the one estimator.
        self.run_filter = run_filter
        self.estimator = ProgressEstimator(clock=clock)
        # Slow-dashboard drops must be visible to operators, not just an
        # instance attribute: count them in the registry so /metrics and
        # /status carry them.
        self._c_sse_dropped = self.registry.counter("monitor.sse_dropped")
        self.broker = EventBroker(on_drop=self._c_sse_dropped.inc)
        self.closing = threading.Event()
        self._t0 = clock()
        self._clock = clock
        # Per-span-name high-water of ``unique_total`` so host block
        # spans (which carry totals, not deltas) yield new-unique deltas.
        # Locked: host engines exit block spans from N worker threads,
        # and an unsynchronized read-modify-write here would double-count
        # deltas against a stale high-water.
        self._block_unique: Dict[str, int] = {}
        self._block_lock = threading.Lock()
        self._g_rate = self.registry.gauge("monitor.states_per_second_ewma")
        self._g_growth = self.registry.gauge("monitor.frontier_growth")
        self._g_eta_low = self.registry.gauge("monitor.eta_low_seconds")
        self._g_eta_high = self.registry.gauge("monitor.eta_high_seconds")
        self._g_clients = self.registry.gauge("monitor.sse_clients")
        self._c_events = self.registry.counter("monitor.wave_events")
        self._c_errors = self.registry.counter("monitor.sink_errors")
        # Pipeline attribution (telemetry/attribution.py): cumulative
        # wall/phase sums over the run's `.pipeline` spans, surfaced as
        # monitor.pipeline.* shares in /status and /metrics. Cumulative —
        # a single wave's shares would flap with every checkpoint.
        self._c_pipeline = self.registry.counter("monitor.pipeline.events")
        self._g_pipe_util = self.registry.gauge(
            "monitor.pipeline.utilization"
        )
        self._g_pipe_host = self.registry.gauge(
            "monitor.pipeline.host_share"
        )
        self._g_pipe_gap = self.registry.gauge("monitor.pipeline.gap_share")
        # Coverage cartography (telemetry/coverage.py): the cumulative
        # `.coverage` spans refresh these + stream over SSE so the
        # Explorer's coverage panel and scrapers see action coverage and
        # vacuity risk live.
        self._c_coverage = self.registry.counter("monitor.coverage.events")
        self._g_cov_actions = self.registry.gauge(
            "monitor.coverage.action_coverage"
        )
        self._g_cov_dead = self.registry.gauge(
            "monitor.coverage.dead_actions"
        )
        self._g_cov_term = self.registry.gauge(
            "monitor.coverage.terminal_states"
        )
        self._g_cov_revisit = self.registry.gauge(
            "monitor.coverage.revisit_rate"
        )
        self._g_cov_sometimes = self.registry.gauge(
            "monitor.coverage.sometimes_witnessed"
        )
        self._pipe_wall_ms = 0.0
        self._pipe_device_ms = 0.0
        self._pipe_host_ms = 0.0
        self._pipe_gap_ms = 0.0
        # Fleet skew aggregation (telemetry/fleet.py): wave spans from a
        # sharded checker carry per-shard ``fleet_*`` columns; the fold
        # rebuilds the same skew/straggler view the in-checker
        # instruments publish — which makes this core the ONE scrape
        # target for a multi-process mesh (every controller emits
        # identical rows, so any process's monitor serves the fleet).
        from .fleet import FleetFold

        self.fleet = FleetFold()
        self._c_fleet = self.registry.counter("monitor.fleet.events")
        self.watchdog: Optional[StallWatchdog] = None
        if stall_deadline_s is not None:
            self.watchdog = StallWatchdog(
                stall_deadline_s, registry=self.registry,
                tracer=self.tracer, clock=clock,
                capture_dir=stall_capture_dir,
                done_fn=self._checker_done,
            ).start()
        self.tracer.add_sink(self)

    # -- sink surface (called from checker threads; must never raise) ------

    def write_event(self, event: Dict) -> None:
        try:
            self._consume(event)
        except Exception:  # noqa: BLE001 - monitor bugs stay monitor bugs
            self._c_errors.inc()

    def _consume(self, event: Dict) -> None:
        if event.get("ph") != "X":
            return
        name = event.get("name", "")
        args = event.get("args") or {}
        if (
            self.run_filter is not None
            and args.get("run_id") != self.run_filter
        ):
            return
        if "fleet_shards" in args:
            self.fleet.consume_span_args(args)
            self._c_fleet.inc()
            self.broker.publish("fleet", {
                "name": name,
                "skew": self.fleet.last_skew,
                "stragglers": self.fleet.stragglers(),
            })
        if "new_unique" in args:
            # Span `frontier` is the DISPATCH width (drains: F_max / G,
            # waves: the padded chunk width) — constant-ish all run. The
            # live quantities ride `ring_count` (drain pending total) and
            # `live_lanes` (pre-padding wave lanes); feed the estimator
            # those or the growth fit and ETA band would be flat
            # capacity-derived constants in the default deep-drain mode.
            live = next(
                (args[k] for k in ("ring_count", "live_lanes")
                 if args.get(k) is not None),
                args.get("frontier"),
            )
            self._on_wave(name, event, args,
                          n_new=int(args.get("new_unique") or 0),
                          generated=int(args.get("generated") or 0),
                          frontier=live,
                          # `waves=0` is meaningful (a drain whose final
                          # wave is counted by the following wave span) —
                          # only a MISSING arg defaults to 1.
                          waves=(int(args["waves"])
                                 if args.get("waves") is not None else 1))
        elif "unique_total" in args:
            # Host block span: totals, not deltas. Monotone per prefix.
            total = int(args.get("unique_total") or 0)
            with self._block_lock:
                prev = self._block_unique.get(name, 0)
                self._block_unique[name] = max(prev, total)
            self._on_wave(name, event, args,
                          n_new=max(0, total - prev),
                          generated=int(args.get("generated") or 0),
                          # `pending` is the worker's live outstanding
                          # count; `evaluated` is a block-width constant
                          # that would fake a seconds-scale ETA on an
                          # hours-long host run. Absent -> no fit, ETA
                          # stays honestly null.
                          frontier=args.get("pending"),
                          waves=1)
        elif name.endswith(".pipeline") and "wall_ms" in args:
            self._on_pipeline(name, args)
        elif name.endswith(".coverage") and "actions_fired" in args:
            self._on_coverage(name, args)
        elif ".storage." in name:
            self.broker.publish("storage", {
                "name": name,
                "ms": (event.get("dur") or 0.0) / 1000.0,
                "args": args,
            })

    def _on_wave(self, name, event, args, *, n_new, generated, frontier,
                 waves) -> None:
        self._c_events.inc()
        self.estimator.observe(
            n_new=n_new, generated=generated, frontier=frontier,
            depth=args.get("max_depth"), waves=waves,
            dedup_hit_rate=args.get("dedup_hit_rate"),
        )
        if self.watchdog is not None:
            self.watchdog.pet()
        est = self.estimator
        if est.ewma_states_per_s is not None:
            self._g_rate.set(est.ewma_states_per_s)
        growth = est.frontier_growth()
        if growth is not None:
            self._g_growth.set(growth)
        eta_low, eta_high = est.eta_band()
        if eta_low is not None:
            self._g_eta_low.set(eta_low)
            self._g_eta_high.set(eta_high)
        self._g_clients.set(self.broker.client_count())
        self.broker.publish("wave", {
            "name": name,
            "ms": (event.get("dur") or 0.0) / 1000.0,
            "frontier": frontier,
            "new_unique": n_new,
            "generated": generated,
            "waves": waves,
            "max_depth": args.get("max_depth"),
            "dedup_hit_rate": args.get("dedup_hit_rate"),
            "occupancy": args.get("occupancy"),
            "ewma_states_per_s": est.ewma_states_per_s,
            "eta_s_low": eta_low,
            "eta_s_high": eta_high,
        })

    def _on_pipeline(self, name, args) -> None:
        """One attribution span (args carry ``wall_ms``/``gap_ms`` and
        ``<phase>_ms``): accumulate, refresh the monitor.pipeline.*
        share gauges, and stream the per-wave breakdown over SSE."""
        from .attribution import HOST_OVERLAPPABLE_PHASES

        self._c_pipeline.inc()
        wall = float(args.get("wall_ms") or 0.0)
        device = float(args.get("device_ms") or 0.0)
        host = sum(
            float(args.get(f"{p}_ms") or 0.0)
            for p in HOST_OVERLAPPABLE_PHASES
        )
        gap = float(args.get("gap_ms") or 0.0)
        self._pipe_wall_ms += wall
        self._pipe_device_ms += device
        self._pipe_host_ms += host
        self._pipe_gap_ms += gap
        if self._pipe_wall_ms > 0:
            self._g_pipe_util.set(self._pipe_device_ms / self._pipe_wall_ms)
            self._g_pipe_host.set(self._pipe_host_ms / self._pipe_wall_ms)
            self._g_pipe_gap.set(self._pipe_gap_ms / self._pipe_wall_ms)
        self.broker.publish("pipeline", {
            "name": name,
            "wall_ms": wall,
            "phases_ms": {
                k[: -len("_ms")]: v
                for k, v in args.items()
                if k.endswith("_ms") and k != "wall_ms"
            },
            "utilization": (
                self._pipe_device_ms / self._pipe_wall_ms
                if self._pipe_wall_ms
                else None
            ),
        })

    def _on_coverage(self, name, args) -> None:
        """One cumulative coverage span (telemetry/coverage.py): refresh
        the monitor.coverage.* gauges and stream the payload over SSE —
        the Explorer panel re-pulls the per-action counters from /status
        on this signal."""
        self._c_coverage.inc()
        fired = args.get("actions_fired")
        total = args.get("actions_total")
        if fired is not None and total:
            self._g_cov_actions.set(fired / total)
        if args.get("dead_actions") is not None:
            self._g_cov_dead.set(args["dead_actions"])
        if args.get("terminals") is not None:
            self._g_cov_term.set(args["terminals"])
        if args.get("revisit_rate") is not None:
            self._g_cov_revisit.set(args["revisit_rate"])
        if args.get("sometimes_witnessed") is not None:
            self._g_cov_sometimes.set(args["sometimes_witnessed"])
        self.broker.publish("coverage", {
            "name": name,
            **{
                k: args.get(k)
                for k in (
                    "evaluated", "terminals", "actions_fired",
                    "actions_total", "dead_actions", "revisit_rate",
                    "sometimes_witnessed", "sometimes_total",
                    "props_total", "orbit_compression",
                )
                if k in args
            },
        })

    def attach(self, checker) -> "MonitorCore":
        """Late-binds the checker handle (monitors are usually created
        BEFORE ``spawn_*`` so the very first waves are observed; the
        handle only exists after)."""
        self.checker = checker
        return self

    def _checker_done(self) -> bool:
        checker = self.checker
        try:
            return checker is not None and bool(checker.is_done())
        except Exception:  # noqa: BLE001 - watchdog gate is best effort
            return False

    # -- views --------------------------------------------------------------

    def status(self) -> Dict[str, object]:
        """The ``/status`` JSON: progress estimate + checker counts +
        the full metrics snapshot (tier/storage gauges included)."""
        out: Dict[str, object] = {
            "run_id": self.run_id,
            "uptime_s": self._clock() - self._t0,
            "progress": self.estimator.snapshot(),
        }
        checker = self.checker
        if checker is not None:
            try:
                out["checker"] = {
                    "backend": type(checker).__name__,
                    "done": checker.is_done(),
                    "state_count": checker.state_count(),
                    "unique_state_count": checker.unique_state_count(),
                    "max_depth": checker.max_depth(),
                }
            except Exception as e:  # noqa: BLE001 - mid-run races tolerated
                out["checker"] = {"error": repr(e)}
        out["metrics"] = self.registry.snapshot()
        return out

    def fleet_view(self) -> Dict[str, object]:
        """The ``/fleet`` JSON: merged per-shard totals, per-wave skew,
        and the persistent-straggler ranking (empty-shaped when no
        sharded run has emitted fleet columns yet)."""
        out = self.fleet.summary()
        out["run_id"] = self.run_id
        return out

    def prometheus(self) -> str:
        text = prometheus_text(self.registry)
        # Per-shard fleet series with shard/host labels — the exposition
        # a mesh-wide scrape joins on, next to the unlabeled families.
        from .fleet import fleet_prometheus_lines

        lines = fleet_prometheus_lines(self.fleet)
        if lines:
            text = text + "\n".join(lines) + "\n"
        return text

    def close(self) -> None:
        self.closing.set()
        self.broker.close()
        if self.watchdog is not None:
            self.watchdog.stop()
        self.tracer.remove_sink(self, close=False)


# -- shared HTTP routing (used by MonitorServer AND the Explorer) ------------


def _send(handler: BaseHTTPRequestHandler, body: bytes,
          content_type: str, code: int = 200) -> None:
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def handle_monitor_get(handler: BaseHTTPRequestHandler, core: MonitorCore,
                       path: str) -> bool:
    """Routes ``/metrics``, ``/status``, ``/events``, ``/fleet`` on any
    BaseHTTPRequestHandler; returns False when the path is not ours so
    the caller's own routing continues (the Explorer mounts these next
    to ``/.status``/``/.states``)."""
    if core is None:
        return False
    if path == "/metrics":
        _send(
            handler, core.prometheus().encode(),
            "text/plain; version=0.0.4; charset=utf-8",
        )
        return True
    if path == "/status":
        _send(
            handler,
            json.dumps(core.status(), default=str).encode(),
            "application/json",
        )
        return True
    if path == "/fleet":
        _send(
            handler,
            json.dumps(core.fleet_view(), default=str).encode(),
            "application/json",
        )
        return True
    if path == "/events":
        _serve_sse(handler, core)
        return True
    return False


def _serve_sse(handler: BaseHTTPRequestHandler, core: MonitorCore,
               heartbeat_s: float = 15.0) -> None:
    q = core.broker.subscribe()
    try:
        # A stalled-but-connected client (full kernel send buffer) must
        # not block this handler thread forever — it would keep its queue
        # subscribed (every publish churns sse_dropped) and survive
        # close(). A write timeout converts the stall into a caught
        # socket error and releases the subscription.
        handler.connection.settimeout(2 * heartbeat_s)
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.end_headers()
        # An immediate hello lets clients confirm the stream is live
        # before the first wave lands.
        handler.wfile.write(

            b"event: hello\ndata: "
            + json.dumps({"run_id": core.run_id}).encode()
            + b"\n\n"
        )
        handler.wfile.flush()
        while not core.closing.is_set():
            try:
                kind, payload = q.get(timeout=heartbeat_s)
            except queue.Empty:
                handler.wfile.write(b": keepalive\n\n")
                handler.wfile.flush()
                continue
            if kind is None:  # close sentinel
                break
            handler.wfile.write(
                f"event: {kind}\n".encode()
                + b"data: "
                + json.dumps(payload, default=str).encode()
                + b"\n\n"
            )
            handler.wfile.flush()
    except OSError:  # disconnects and write timeouts both end the stream
        pass
    finally:
        core.broker.unsubscribe(q)


class _MonitorHandler(BaseHTTPRequestHandler):
    core: MonitorCore = None

    def log_message(self, *args):  # quiet by default
        pass

    def do_GET(self):
        try:
            if handle_monitor_get(self, self.core, self.path):
                return
            if self.path in ("/", ""):
                body = json.dumps({
                    "run_id": self.core.run_id,
                    "endpoints": [
                        "/metrics", "/status", "/events", "/fleet",
                    ],
                }).encode()
                _send(self, body, "application/json")
                return
            _send(self, b"", "application/json", code=404)
        except ConnectionError:
            # Routine client disconnect mid-response (scraper timeout,
            # curl ^C) must not traceback-spam a long monitored run.
            pass


class MonitorServer:
    """The in-process live monitor: ``MonitorCore`` + an HTTP server on
    its own daemon thread. Attach to any checker::

        monitor = checker.serve_monitor(port=8790)   # or port=0: ephemeral
        ... run ...
        monitor.close()

    ``flight_recorder=True`` additionally installs a ``FlightRecorder``
    (dumping ``flight-<run_id>.json`` on crash/SIGTERM) and
    ``stall_deadline_s=N`` arms the watchdog."""

    def __init__(self, checker=None, port: int = 0, host: str = "127.0.0.1",
                 registry: MetricsRegistry = None, tracer: Tracer = None,
                 run_id: Optional[str] = None,
                 stall_deadline_s: Optional[float] = None,
                 stall_capture_dir: Optional[str] = None,
                 flight_recorder: bool = False, flight_dir: str = ".",
                 run_filter: Optional[str] = None):
        self.core = MonitorCore(
            checker=checker, registry=registry, tracer=tracer,
            run_id=run_id, stall_deadline_s=stall_deadline_s,
            stall_capture_dir=stall_capture_dir, run_filter=run_filter,
        )
        self.flight: Optional[FlightRecorder] = None
        try:
            if flight_recorder:
                self.flight = FlightRecorder(
                    run_id=self.core.run_id, out_dir=flight_dir,
                    checker=checker, registry=self.core.registry,
                    tracer=self.core.tracer,
                ).install()
            handler = type(
                "Handler", (_MonitorHandler,), {"core": self.core}
            )
            self._server = ThreadingHTTPServer((host, port), handler)
        except BaseException:
            # A failed bind (port in use) must not leak the tracer sink,
            # the watchdog thread, or installed signal/except hooks.
            self.core.close()
            if self.flight is not None:
                self.flight.uninstall()
            raise
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="monitor-http",
            daemon=True,
        )
        self._thread.start()
        self.core.tracer.instant(
            "monitor.started", port=self.port, run_id=self.core.run_id
        )

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def attach(self, checker) -> "MonitorServer":
        """Late-binds the checker handle (create the monitor before
        ``spawn_*`` so the first waves are observed, attach after)."""
        self.core.attach(checker)
        if self.flight is not None:
            self.flight.checker = checker
        return self

    def close(self) -> None:
        self.core.close()
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        if self.flight is not None:
            self.flight.uninstall()
