"""Out-of-core tiered visited-fingerprint store.

The device checkers' visited set historically grew by doubling + rehash
(`ops/hashset.py`) until HBM ran out, hard-capping the largest checkable
state space at device memory. This package removes that ceiling with a
three-tier layout behind a batched probe/evict API:

- **L0** — the existing device hash table, now governed by a hard
  ``hbm_budget_mib`` knob on the checkers: when growth would exceed the
  budget, the full table drains to the host and resets, keeping only the
  working set (hot recent generations) on device.
- **L1** — evicted fingerprints as host-resident, delta-compressed sorted
  runs (64-bit fps sorted ascending, varint deltas, block-indexed for
  binary search) fronted by a per-run Bloom filter sized for <1% false
  positives. Runs merge LSM-style when their count passes a threshold.
- **L2** — merged runs spill to disk files when host bytes pass
  ``host_budget_mib``, with the same run/filter format so probes are
  uniform (the payload is just read block-wise from the file).

Wave dedup becomes a two-phase probe: the device table filters first,
then surviving L0-fresh candidates batch-probe L1/L2 on the host during
the wave's host exit. Results are bit-identical to the single-tier path:
the union of the tiers is exactly the visited set, so a key reports fresh
iff it was never seen (``tests/test_storage_equivalence.py``).

See README "Memory hierarchy" for the knobs and when eviction pays.
"""

from .bloom import BloomFilter
from .corpus import CorpusStore, validate_corpus_name
from .edge_log import LivenessEdgeStore, LivenessInstruments
from .persist import (
    AotDiskBinding,
    AotDiskStore,
    SeedStore,
    aot_fence,
    adapt_seed_checkpoint,
    build_seed_artifact,
    model_structure_signature,
    seed_compatibility,
)
from .runs import (
    RUN_BLOCK,
    FingerprintRun,
    decode_sorted_fps,
    decode_varint_u64,
    encode_sorted_fps,
    encode_varint_u64,
)
from .tiered import (
    StorageInstruments,
    TenantPartitions,
    TieredVisitedStore,
    max_table_rows_for_budget,
    validate_budget_knobs,
)

__all__ = [
    "AotDiskBinding",
    "AotDiskStore",
    "BloomFilter",
    "CorpusStore",
    "FingerprintRun",
    "SeedStore",
    "adapt_seed_checkpoint",
    "aot_fence",
    "build_seed_artifact",
    "model_structure_signature",
    "seed_compatibility",
    "LivenessEdgeStore",
    "LivenessInstruments",
    "RUN_BLOCK",
    "StorageInstruments",
    "TenantPartitions",
    "TieredVisitedStore",
    "decode_sorted_fps",
    "decode_varint_u64",
    "encode_sorted_fps",
    "encode_varint_u64",
    "max_table_rows_for_budget",
    "validate_budget_knobs",
    "validate_corpus_name",
]
